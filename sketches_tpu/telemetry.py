"""Self-sketching runtime telemetry: the library instruments itself with
its own sketches.

DDSketch exists for production latency monitoring (PAPER.md; the
high-cardinality-aggregation use case behind Moments sketch,
arXiv:1803.01969, and UDDSketch, arXiv:2004.08604), so this repo's own
runtime dogfoods it: every timed section feeds a **host-tier DDSketch
with a LogarithmicMapping** (``HISTOGRAM_REL_ACC`` alpha), which means
the p50/p99 a snapshot reports carry the paper's relative-error
guarantee rather than a bucket boundary's.  Three surfaces:

* **Metric registry** -- process-wide counters, gauges, and
  sketch-backed latency histograms, keyed by a **declared inventory**
  (:data:`METRICS`).  Library code may only use names declared here
  (enforced statically by the sketchlint ``telemetry-names`` rule and at
  runtime by :func:`counter_inc`/:func:`observe`); user code extends the
  inventory with :func:`declare`.
* **Trace spans** -- :func:`span`/:func:`finish_span` record
  Chrome-trace/perfetto ``X`` events (the device-track conventions
  ``bench.py``'s ``device_query_pcts`` parses) with thread-safe nesting
  (per-thread track, bounded ring, drops counted -- never unbounded
  growth), and feed the span's histogram on exit.
* **Exporters** -- :func:`snapshot` (JSON-safe dict, with the
  ``resilience.health()`` ledger bridged in so demotion counters and
  metrics always agree), :func:`prometheus_text` (text exposition;
  histograms as summaries), :func:`chrome_trace` (load it in
  ``chrome://tracing`` / perfetto).

Arming: OFF by default.  ``SKETCHES_TPU_TELEMETRY=1`` (declared in
``analysis/registry.py``) arms at process start; :func:`enable` /
:func:`disable` arm programmatically.  Cost discipline mirrors
``faults``: every instrumented seam guards on ``telemetry._ACTIVE``, so
the disarmed layer costs one attribute read + bool test per *dispatch*
-- no clock read, no allocation (tested in ``tests/test_telemetry.py``).
Wall-clock reads live ONLY in this module (:func:`clock` /
:func:`wall_time`): the sketchlint ``determinism`` rule carves out
``telemetry.py`` and keeps flagging clocks everywhere else.

Fleet semantics (r11): snapshots are **mergeable**.  Every histogram
summary embeds its sketch's sparse bin state, so
:func:`merge_snapshots` folds N per-process snapshots into one
fleet-wide snapshot -- counters by sum, gauges by their declared
``merge`` policy, histograms by DDSketch bin addition -- and the merged
p50/p99 carry the SAME ``HISTOGRAM_REL_ACC`` relative-error guarantee
as a single process (the paper's mergeability property, applied to the
library's own telemetry).  A declared :data:`SLOS` inventory (target +
window + burn-rate threshold per metric) is evaluated by
:func:`check_slo` against any snapshot, merged or not.

Tracing semantics (r13): spans and histogram observations taken while
the flight recorder (``sketches_tpu.tracing``) is armed link to the
current :class:`~sketches_tpu.tracing.TraceContext` -- chrome events
carry the ids (rendered as causal flow arrows), latency-histogram bins
retain bounded ``(trace_id, wall_time, value)`` **exemplar
reservoirs** (deterministic splitmix64 bottom-k; survive
:func:`merge_snapshots` by concat + re-reservoir, drops counted),
:func:`prometheus_text` annotates quantile lines OpenMetrics-style,
and :func:`exemplars_for` answers "which traces sit behind this
histogram's p99 bin".

CLI: ``python -m sketches_tpu.telemetry --check-bench OLD NEW`` is the
bench regression gate -- it compares two ``bench.py`` summary documents
(e.g. the checked-in ``BENCH_local_r*.json``) metric by metric against
per-metric thresholds and exits non-zero on regression.
``--merge A.json B.json ... [--out M.json]`` folds snapshot files;
``--check-slo SNAPSHOT.json`` evaluates the SLO inventory (exit 1 on
any burning SLO, 2 when nothing was evaluable); ``--bench-snapshot
BENCH.json OUT.json`` derives a snapshot from a bench summary's
measured latencies (the checked-in SLO-gate fixture).

Failure modes: recording against an undeclared metric name (or the
wrong kind) raises ``SketchValueError`` -- stringly-typed drift is
refused, not collected; a full trace ring drops the newest events and
counts them (``snapshot()['spans']['dropped']`` and the declared
``spans.dropped`` counter); merging snapshots with different histogram
relative accuracies (or pre-r11 snapshots without embedded bin state)
raises ``SketchValueError`` -- a silent accuracy downgrade is refused;
``--check-bench`` exits 1 on any regressed metric and 2 when the
documents share no comparable metric at all (wrong files beat a silent
pass), and ``--check-slo`` mirrors that contract.
"""

from __future__ import annotations

import dataclasses
import json
import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from sketches_tpu.analysis import registry

__all__ = [
    "TELEMETRY_ENV",
    "HISTOGRAM_REL_ACC",
    "Metric",
    "METRICS",
    "declare",
    "enable",
    "disable",
    "enabled",
    "reset",
    "clock",
    "wall_time",
    "counter_inc",
    "gauge_set",
    "observe",
    "finish_span",
    "span",
    "event",
    "snapshot",
    "merge_snapshots",
    "prometheus_text",
    "chrome_trace",
    "exemplars_for",
    "CHROME_PID_SPANS",
    "CHROME_PID_DEVICE",
    "EXEMPLARS_PER_BIN",
    "EXEMPLAR_BINS",
    "check_bench",
    "capture_class",
    "capture_mismatch",
    "find_comparable_pair",
    "SLO",
    "SLOS",
    "check_slo",
    "snapshot_from_bench",
    "main",
]

#: Declared in ``analysis/registry.py`` (the kill-switch inventory);
#: this alias keeps the import-path convention of the other levers.
TELEMETRY_ENV = registry.TELEMETRY.name

#: Relative accuracy of every self-sketch histogram: quantiles a
#: snapshot reports are within 1% of the recorded durations' exact
#: quantiles (the DDSketch contract, applied to ourselves).
HISTOGRAM_REL_ACC = 0.01

#: Declared, collision-free Chrome-trace process-track scheme: host
#: telemetry spans render on pid 1 (one tid per thread), the profiling
#: layer's device-clocked dispatches on pid 2 (one tid per engine
#: tier).  Both pids carry ``process_name``/``thread_name`` metadata
#: events so Perfetto labels tracks instead of showing bare ids; any
#: future track must claim a fresh pid here.
CHROME_PID_SPANS = 1
CHROME_PID_DEVICE = 2

#: Per-bin exemplar-reservoir bound: each latency-histogram bin retains
#: at most this many ``(trace_id, wall_time, value)`` exemplars
#: (deterministic splitmix64 bottom-k selection keyed on the trace id --
#: no RNG, replays exactly; the ``accuracy.py`` reservoir discipline).
EXEMPLARS_PER_BIN = 4

#: Bound on distinct bins carrying exemplars per histogram series
#: (overflow dropped + counted -- the ring discipline).
EXEMPLAR_BINS = 256


@dataclasses.dataclass(frozen=True)
class Metric:
    """One declared metric: its name, kind, owning module, and doc.

    ``kind`` is ``"counter"`` (monotone float), ``"gauge"`` (last write
    wins), or ``"histogram"`` (DDSketch-backed distribution of seconds;
    spans feed these).  Recording against a name whose declared kind
    does not match the API used raises ``SketchValueError``.

    ``merge`` is the gauge fold policy :func:`merge_snapshots` applies
    across processes (``"max"``, ``"min"``, or ``"sum"``); counters
    always fold by sum and histograms by sketch merge, so the field
    only matters for gauges.
    """

    name: str
    kind: str
    owner: str
    doc: str
    merge: str = "max"


# The library's metric inventory.  The sketchlint ``telemetry-names``
# rule parses these ``Metric(...)`` declarations and requires every
# telemetry call in the package to use one of them (no stringly-typed
# drift); the README "Observability" table documents the same set.
_DECLARED = (
    Metric("batched.ingest_batches", "counter", "sketches_tpu.batched",
           "Batches ingested through BatchedDDSketch.add."),
    Metric("distributed.ingest_batches", "counter", "sketches_tpu.parallel",
           "Batches ingested through DistributedDDSketch.add."),
    Metric("ingest.variant.stock", "counter", "sketches_tpu.kernels",
           "Pallas ingest batches served by the stock int8 construction."),
    Metric("ingest.variant.packed", "counter", "sketches_tpu.kernels",
           "Pallas ingest batches served by the packed sub-byte lo"
           " construction (DESIGN.md 2-r17)."),
    Metric("ingest.variant.hifold", "counter", "sketches_tpu.kernels",
           "Pallas ingest batches served by the folded pos/neg hi"
           " construction (2-r17; dead-listed default-off rung)."),
    Metric("ingest.variant.cmpfree", "counter", "sketches_tpu.kernels",
           "Pallas ingest batches served by the compare-free construction"
           " (2-r17; dead-listed default-off rung)."),
    Metric("scalar.values", "counter", "sketches_tpu.ddsketch",
           "Values flushed through the JaxDDSketch scalar/bulk paths."),
    Metric("wire.blobs_encoded", "counter", "sketches_tpu.pb.wire",
           "Wire blobs produced by state_to_bytes."),
    Metric("wire.blobs_decoded", "counter", "sketches_tpu.pb.wire",
           "Wire blobs admitted to bytes_to_state (quarantined included)."),
    Metric("wire.blobs_quarantined", "counter", "sketches_tpu.pb.wire",
           "Blobs isolated by a quarantine-mode bulk decode."),
    Metric("wire.native.decode_calls", "counter", "sketches_tpu.pb.wire",
           "Bulk decode batches scanned by the native C++ structural"
           " codec (dense scans and envelope splits both count)."),
    Metric("wire.native.careful_fallbacks", "counter",
           "sketches_tpu.pb.wire",
           "Blobs the native scanner handed back to the per-blob Python"
           " careful path (foreign, damaged, or pre-marked blobs)."),
    Metric("wire.native.template_miss", "counter", "sketches_tpu.pb.wire",
           "Careful handoffs whose canonical mapping prefix matched but"
           " whose structure deviated from the template shape."),
    Metric("native.load_attempts", "counter", "sketches_tpu.native",
           "Native-engine build/load attempts (retries included)."),
    Metric("resilience.downgrade", "counter", "sketches_tpu.resilience",
           "Downgrade events recorded in the resilience health ledger."),
    Metric("integrity.checks", "counter", "sketches_tpu.integrity",
           "Armed integrity verifications run at the guarded seams."),
    Metric("integrity.violations", "counter", "sketches_tpu.integrity",
           "Invariant/fingerprint violations the integrity layer caught."),
    Metric("integrity.repairs", "counter", "sketches_tpu.integrity",
           "Fields rewritten by integrity.repair() passes."),
    Metric("integrity.check_s", "histogram", "sketches_tpu.integrity",
           "Armed integrity verification wall time (label: seam)."),
    Metric("checkpoint.bytes", "gauge", "sketches_tpu.checkpoint",
           "Size of the most recently written checkpoint, in bytes."),
    Metric("ingest_s", "histogram", "sketches_tpu.batched",
           "Facade ingest dispatch wall time (labels: component, engine)."),
    Metric("query_s", "histogram", "sketches_tpu.batched",
           "Query dispatch wall time, labeled by the resolved engine tier"
           " (labels: component, tier)."),
    Metric("merge_s", "histogram", "sketches_tpu.batched",
           "Facade merge dispatch wall time (label: component)."),
    Metric("scalar.ingest_s", "histogram", "sketches_tpu.ddsketch",
           "JaxDDSketch flush/add_many wall time (label: path)."),
    Metric("distributed.fold_s", "histogram", "sketches_tpu.parallel",
           "psum fold of the distributed partials (cache misses only)."),
    Metric("wire.encode_s", "histogram", "sketches_tpu.pb.wire",
           "Bulk wire encode wall time per batch."),
    Metric("wire.decode_s", "histogram", "sketches_tpu.pb.wire",
           "Bulk wire decode wall time per batch."),
    Metric("native.load_s", "histogram", "sketches_tpu.native",
           "Native-engine build+load wall time (successful loads)."),
    Metric("checkpoint.save_s", "histogram", "sketches_tpu.checkpoint",
           "Checkpoint serialize+fsync+rename wall time."),
    Metric("checkpoint.restore_s", "histogram", "sketches_tpu.checkpoint",
           "Checkpoint load+validate wall time."),
    Metric("spans.dropped", "counter", "sketches_tpu.telemetry",
           "Trace events dropped because the 65k span ring was full."),
    Metric("tracing.traces", "counter", "sketches_tpu.tracing",
           "Root trace contexts minted (one per served/instrumented"
           " request while the recorder is armed)."),
    Metric("tracing.events", "counter", "sketches_tpu.tracing",
           "Structured events recorded into the flight-recorder ring"
           " (spans, decisions, faults, downgrades)."),
    Metric("tracing.dropped", "counter", "sketches_tpu.tracing",
           "Flight-recorder events overwritten because the bounded ring"
           " wrapped (the oldest event is replaced, never the newest)."),
    Metric("tracing.dumps", "counter", "sketches_tpu.tracing",
           "Forensic bundles dumped (auto-triggered by SLO burns, serve"
           " errors, cache poison, chaos classifications, or explicit)."),
    Metric("profiling.device_s", "histogram", "sketches_tpu.profiling",
           "Device-clocked (block_until_ready) dispatch time, attributed"
           " per phase and engine tier (labels: phase, tier)."),
    Metric("accuracy.audits", "counter", "sketches_tpu.accuracy",
           "Shadow-audit passes run against watched sketches."),
    Metric("accuracy.violations", "counter", "sketches_tpu.accuracy",
           "Audit passes where a realized quantile broke the alpha"
           " contract against the reservoir sample."),
    Metric("accuracy.rel_err", "gauge", "sketches_tpu.accuracy",
           "Worst realized relative quantile error seen by the most"
           " recent audit pass (label: stream)."),
    Metric("accuracy.collapsed_mass_frac", "gauge", "sketches_tpu.accuracy",
           "Fraction of a watched stream's mass clamped into the window"
           " edge bins at the most recent audit (label: stream)."),
    Metric("accuracy.collapse_recommended", "counter",
           "sketches_tpu.accuracy",
           "Drift audits that saw a non-adaptive stream's edge-clamped"
           " mass fraction cross its spec's collapse threshold -- the"
           " signal that the stream wants the uniform_collapse backend"
           " (label: stream)."),
    Metric("backend.collapses", "counter", "sketches_tpu.backends",
           "Uniform-collapse events: streams whose bins pair-merged one"
           " level (gamma -> gamma**2; alpha degraded predictably"
           " instead of tail mass clamping)."),
    Metric("backend.effective_alpha", "gauge", "sketches_tpu.backends",
           "Realized relative-accuracy bound of a collapsed stream"
           " after its most recent collapse (label: stream)."),
    Metric("backend.moment_solves", "counter", "sketches_tpu.backends",
           "Per-stream maximum-entropy quantile solves run by the"
           " moment backend."),
    Metric("backend.moment_fallbacks", "counter", "sketches_tpu.backends",
           "Moment-backend solves that fell back down the moment ladder"
           " (fewer moments, or the uniform-density floor) because the"
           " maxent Newton solve failed to converge."),
    Metric("elastic.reshards", "counter", "sketches_tpu.parallel",
           "Elastic reshard operations completed (grow, shrink, and"
           " kill-and-regrow alike; label: kind)."),
    Metric("elastic.reshard_s", "histogram", "sketches_tpu.parallel",
           "Elastic reshard wall time: fold the survivors, rebuild the"
           " mesh, verify the boundary."),
    Metric("elastic.dropped_mass", "counter", "sketches_tpu.parallel",
           "Total mass itemized as lost to dead shards/hosts across"
           " elastic reshards (exact per-stream accounting rides the"
           " ReshardReport)."),
    Metric("elastic.mesh_devices", "gauge", "sketches_tpu.parallel",
           "Device count of the most recently built elastic mesh."),
    Metric("elastic.host_losses", "counter", "sketches_tpu.parallel",
           "Whole-host (ICI-group) losses folded around during elastic"
           " reshards."),
    Metric("elastic.dcn_partitions", "counter", "sketches_tpu.parallel",
           "DCN partitions detected at the cross-host fold (unreachable"
           " process-local partials folded around, accounted)."),
    Metric("elastic.dcn_fold_s", "histogram", "sketches_tpu.parallel",
           "Cross-host (DCN) fold of process-local merged partials."),
    Metric("serve.requests", "counter", "sketches_tpu.serve",
           "Quantile requests submitted to the serving tier (admitted,"
           " cached, and shed alike)."),
    Metric("serve.shed", "counter", "sketches_tpu.serve",
           "Requests refused at admission (label: reason --"
           " queue_depth/tenant_quota/injected)."),
    Metric("serve.deadline_misses", "counter", "sketches_tpu.serve",
           "Requests answered (or refused) after their deadline budget"
           " was already spent."),
    Metric("serve.hedges", "counter", "sketches_tpu.serve",
           "Hedged dispatches issued for straggling/failed primary"
           " query dispatches (label: tier)."),
    Metric("serve.cache.hits", "counter", "sketches_tpu.serve",
           "Queries answered from the fingerprint-keyed result cache."),
    Metric("serve.cache.misses", "counter", "sketches_tpu.serve",
           "Cache-armed queries that had to dispatch to the device."),
    Metric("serve.cache.poisoned", "counter", "sketches_tpu.serve",
           "Cached entries that failed re-verification against the live"
           " fingerprint/checksum and were quarantined."),
    Metric("serve.breaker.trips", "counter", "sketches_tpu.serve",
           "Circuit-breaker openings per engine tier (label: tier)."),
    Metric("serve.queue_depth", "gauge", "sketches_tpu.serve",
           "Admission-queue depth at the most recent submit/flush."),
    Metric("serve.request_s", "histogram", "sketches_tpu.serve",
           "Per-request serving latency, submit to answer (label:"
           " source -- cache/dispatch)."),
    Metric("serve.batch_s", "histogram", "sketches_tpu.serve",
           "Fused flush dispatch wall time per tenant group (label:"
           " tier)."),
    Metric("window.rotations", "counter", "sketches_tpu.windows",
           "Windowed-ring bucket rotations: live time-slice buckets"
           " frozen into the ring as the clock crossed a slice"
           " boundary."),
    Metric("window.retired_mass", "counter", "sketches_tpu.windows",
           "Exact mass dropped off the last ladder rung by windowed"
           " bucket retirement (the ledger's retired side)."),
    Metric("window.ladder_collapses", "counter", "sketches_tpu.windows",
           "Collapse-on-retire applications: buckets brought to a"
           " coarser rung's declared collapse level as they aged down"
           " the ladder."),
    Metric("window.covered_buckets", "gauge", "sketches_tpu.windows",
           "Buckets covered by the most recent window query (the fused"
           " stacked-merge dispatch's arity)."),
    Metric("window.agg_reuse", "counter", "sketches_tpu.windows",
           "Window plans whose sealed-rung component was served"
           " entirely from a maintained two-stacks aggregate (zero new"
           " backend merges -- the maintained layer's hit rate)."),
    Metric("window.agg_rebuilds", "counter", "sketches_tpu.windows",
           "Two-stacks aggregate rebuilds: the derived stacks were"
           " dropped (restore, ring merge, torn sync) and repopulated"
           " from the ring on the next plan."),
    Metric("window.query_merges", "counter", "sketches_tpu.windows",
           "Backend merges spent ANSWERING window queries on the"
           " maintained path (component chain + suffix/back-tail"
           " combine) -- O(1) per query, vs O(covered buckets) with"
           " SKETCHES_TPU_WINDOW_AGG=0."),
    Metric("fabric.replica_syncs", "counter", "sketches_tpu.fabric",
           "Replica refreshes shipped over the wire seam: a replica's"
           " state replaced by a fold of the primary's, fingerprint"
           " ledgered at the sync point."),
    Metric("fabric.failovers", "counter", "sketches_tpu.fabric",
           "Tenant re-homings after a host loss: a surviving replica"
           " promoted to primary with the dropped mass itemized in the"
           " fabric's ledger."),
    Metric("fabric.hedge_cross_host", "counter", "sketches_tpu.fabric",
           "Cross-host hedge dispatches: a primary read that failed or"
           " straggled was re-issued against a fingerprint-verified"
           " replica on another host."),
    Metric("fabric.staleness_s", "histogram", "sketches_tpu.fabric",
           "Replica staleness observed at serve time (serving-clock"
           " seconds since the replica's ledgered sync), recorded per"
           " replica-served read (label: tenant)."),
)

#: Every declared metric by name (static inventory + runtime
#: :func:`declare` extensions).
METRICS: Dict[str, Metric] = {m.name: m for m in _DECLARED}

_VALID_KINDS = ("counter", "gauge", "histogram")
_VALID_MERGES = ("max", "min", "sum")

_lock = threading.Lock()

#: Fast-path guard: instrumented seams check this module flag before
#: doing any telemetry work, so the disarmed layer costs one bool test.
_ACTIVE = registry.enabled(registry.TELEMETRY)

# Trace timebase: ts fields are microseconds since this process epoch.
# The two module-level clock reads below (and the clock()/wall_time()
# bodies) are the ONLY wall-clock reads in the package -- the
# determinism rule's telemetry.py carve-out covers exactly this file.
_epoch_pc = time.perf_counter()
_epoch_wall = time.time()

_MAX_EVENTS = 65536

# Keyed by (name, ((label, value), ...)) -- labels canonically sorted.
_Key = Tuple[str, Tuple[Tuple[str, str], ...]]
_counters: Dict[_Key, float] = {}
_gauges: Dict[_Key, float] = {}
_hists: Dict[_Key, "_Hist"] = {}
_events: List[dict] = []
_events_dropped = 0
_tids: Dict[int, int] = {}


def _raise_value_error(msg: str) -> None:
    # Lazy import: resilience imports telemetry at module load (for the
    # ledger clock), so the taxonomy root is reached at call time only.
    from sketches_tpu.resilience import SketchValueError

    raise SketchValueError(msg)


_tracing_cached = None


def _tracing():
    """The tracing module, imported lazily (tracing imports telemetry at
    load, so the reverse edge must be deferred to call time).  Armed
    code paths only -- the disarmed fast path never reaches this."""
    global _tracing_cached
    if _tracing_cached is None:
        from sketches_tpu import tracing as _t

        _tracing_cached = _t
    return _tracing_cached


def declare(
    name: str, kind: str, doc: str, owner: str = "user", merge: str = "max"
) -> Metric:
    """Register a user-space metric (examples, applications, tests).

    Library code must use the static inventory instead (the sketchlint
    ``telemetry-names`` rule refuses in-package ``declare`` calls).
    ``merge`` is the cross-process gauge fold policy (gauges only; see
    :class:`Metric`).  Raises ``SketchValueError`` on an invalid kind or
    merge policy; re-declaring an existing name with a different kind
    raises, an identical re-declaration is a no-op.
    """
    if kind not in _VALID_KINDS:
        _raise_value_error(
            f"Unknown metric kind {kind!r}; expected one of {_VALID_KINDS}"
        )
    if merge not in _VALID_MERGES:
        _raise_value_error(
            f"Unknown gauge merge policy {merge!r}; expected one of"
            f" {_VALID_MERGES}"
        )
    with _lock:
        prev = METRICS.get(name)
        if prev is not None:
            if prev.kind != kind:
                _raise_value_error(
                    f"metric {name!r} already declared with kind"
                    f" {prev.kind!r}"
                )
            return prev
        m = Metric(name, kind, owner, doc, merge)
        METRICS[name] = m
        return m


def _metric(name: str, kind: str) -> Metric:
    m = METRICS.get(name)
    if m is None:
        _raise_value_error(
            f"undeclared telemetry metric {name!r}; library metrics belong"
            " in telemetry.METRICS, user metrics go through"
            " telemetry.declare()"
        )
    if m.kind != kind:
        _raise_value_error(
            f"telemetry metric {name!r} is a {m.kind}, not a {kind}"
        )
    return m


def _key(name: str, labels: Dict[str, Any]) -> _Key:
    return (
        name,
        tuple(sorted((k, str(v)) for k, v in labels.items())),
    )


# ---------------------------------------------------------------------------
# Arming
# ---------------------------------------------------------------------------


def enable(on: bool = True) -> None:
    """Arm (or, with ``on=False``, disarm) the telemetry layer.

    Never raises; the pre-existing metric state is kept (use
    :func:`reset` to clear it).  The flight recorder
    (``sketches_tpu.tracing``) follows this arming state -- it is
    always-armed-when-telemetry-is-armed unless its own kill switch
    disables it.
    """
    global _ACTIVE
    _ACTIVE = bool(on)
    _tracing()._sync(_ACTIVE)


def disable() -> None:
    """Disarm the telemetry layer (instrumented seams go back to one
    bool test per dispatch; recorded state is kept, never lost)."""
    enable(False)


def enabled() -> bool:
    """Whether the layer is armed (env switch or :func:`enable`);
    False -- the default -- means no seam records anything."""
    return _ACTIVE


def reset() -> None:
    """Clear every counter/gauge/histogram/trace event (test isolation
    hook; runtime-declared metrics stay declared).  Never raises."""
    global _events_dropped
    with _lock:
        _counters.clear()
        _gauges.clear()
        _hists.clear()
        _events.clear()
        _tids.clear()
        _events_dropped = 0


# ---------------------------------------------------------------------------
# Clocks (the package's only wall-clock reads -- see module docstring)
# ---------------------------------------------------------------------------


def clock() -> float:
    """Monotonic seconds (``time.perf_counter``): span/duration timebase.

    The one sanctioned monotonic read in the package -- instrumented
    seams call this instead of touching ``time`` (which the determinism
    lint would rightly flag as a replay hazard).  Never raises.
    """
    return time.perf_counter()


def wall_time() -> float:
    """Wall-clock seconds since the epoch (``time.time``).

    Operator-facing timestamps only (the resilience ledger's event
    times); nothing may branch on it.  Never raises.
    """
    return time.time()


# ---------------------------------------------------------------------------
# Recording
# ---------------------------------------------------------------------------


def _sketch_state(sk) -> dict:
    """A host DDSketch's sparse bin state as a JSON-safe dict
    (``{"zero_count", "pos": {key: mass}, "neg": {key: mass}}``)."""

    def bins(store) -> Dict[str, float]:
        return {
            str(k): float(store.bins[k - store.offset]) for k in store.keys()
        }

    return {
        "zero_count": float(sk.zero_count),
        "pos": bins(sk.store),
        "neg": bins(sk.negative_store),
    }


def _sketch_from_state(state: dict, rel_acc: float):
    """Rebuild a host DDSketch from :func:`_sketch_state` output (bin
    mass and zero count only; scalar min/max/sum are the caller's)."""
    from sketches_tpu.ddsketch import BaseDDSketch
    from sketches_tpu.mapping import LogarithmicMapping
    from sketches_tpu.store import DenseStore

    sk = BaseDDSketch(
        LogarithmicMapping(rel_acc), DenseStore(), DenseStore(),
        zero_count=float(state.get("zero_count", 0.0)),
    )
    for key, cnt in state.get("pos", {}).items():
        sk.store.add(int(key), float(cnt))
    for key, cnt in state.get("neg", {}).items():
        sk.negative_store.add(int(key), float(cnt))
    sk._count = sk.zero_count + sk.store.count + sk.negative_store.count
    return sk


def _exemplar_priority(trace_hex: str) -> int:
    """Deterministic reservoir priority of an exemplar: splitmix64 of
    its trace id.  A pure function of the id, so merge operands agree
    on selection without storing priorities (re-reservoir = bottom-k of
    the union).  An unparseable id sorts last (kept only if room)."""
    from sketches_tpu import tracing as _t

    try:
        return _t.splitmix64(int(trace_hex, 16))
    except (TypeError, ValueError):
        return (1 << 64) - 1


class _Hist:
    """One histogram: a host-tier DDSketch plus exact min/max, plus a
    small per-bin exemplar reservoir linking histogram mass to traces.

    The sketch import is lazy (first armed observation), so importing
    telemetry never pays for the sketch stack; count/sum come from the
    sketch's own (exact, f64) bookkeeping.  Exemplars are recorded only
    for trace-bearing positive observations (the latency case): each
    mapping bin keeps at most :data:`EXEMPLARS_PER_BIN` entries,
    selected by the deterministic splitmix64 bottom-k priority of their
    trace ids; at most :data:`EXEMPLAR_BINS` bins carry exemplars
    (overflow dropped + counted).  Failure modes follow the sketch's:
    quantiles of an empty histogram read as None/NaN.
    """

    __slots__ = ("sketch", "min", "max", "exemplars", "exemplars_seen",
                 "exemplars_dropped")

    def __init__(self):
        from sketches_tpu.ddsketch import DDSketch

        self.sketch = DDSketch(HISTOGRAM_REL_ACC)
        self.min = math.inf
        self.max = -math.inf
        self.exemplars: Dict[int, List[Tuple[int, str, float, float]]] = {}
        self.exemplars_seen = 0
        self.exemplars_dropped = 0

    def add(self, value: float, exemplar: Optional[Tuple[str, float]] = None
            ) -> None:
        self.sketch.add(value)
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        if exemplar is not None and value > 0:
            self._add_exemplar(value, exemplar)

    def _add_exemplar(self, value: float, ex: Tuple[str, float]) -> None:
        trace_hex, wall = ex
        self.exemplars_seen += 1
        key = self.sketch.mapping.key(value)
        lst = self.exemplars.get(key)
        if lst is None:
            if len(self.exemplars) >= EXEMPLAR_BINS:
                self.exemplars_dropped += 1
                return
            lst = self.exemplars[key] = []
        lst.append((_exemplar_priority(trace_hex), trace_hex, wall, value))
        if len(lst) > EXEMPLARS_PER_BIN:
            lst.sort()
            lst.pop()
            self.exemplars_dropped += 1

    def summary(self) -> dict:
        sk = self.sketch
        out = {
            "count": sk.count,
            "sum": sk.sum,
            "min": None if math.isinf(self.min) else self.min,
            "max": None if math.isinf(self.max) else self.max,
            "relative_accuracy": HISTOGRAM_REL_ACC,
        }
        for q, label in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99"),
                         (0.999, "p999")):
            out[label] = sk.get_quantile_value(q)
        # The sketch's sparse bin state rides along (JSON-safe: string
        # keys), so snapshots are MERGEABLE: merge_snapshots folds these
        # bins by key addition -- exactly DDSketch.merge -- and the
        # fleet-wide quantiles keep the alpha contract.
        out["state"] = _sketch_state(sk)
        if self.exemplars_seen:
            out["exemplars"] = {
                str(k): [
                    {"trace_id": t, "wall_time": w, "value": v}
                    for (_p, t, w, v) in sorted(lst)
                ]
                for k, lst in sorted(self.exemplars.items())
            }
            out["exemplars_seen"] = self.exemplars_seen
            out["exemplars_dropped"] = self.exemplars_dropped
        return out


def counter_inc(name: str, n: float = 1.0, **labels) -> None:
    """Add ``n`` to counter ``name`` (no-op while disarmed).

    Raises ``SketchValueError`` for an undeclared name or a non-counter
    metric.
    """
    if not _ACTIVE:
        return
    _metric(name, "counter")
    k = _key(name, labels)
    with _lock:
        _counters[k] = _counters.get(k, 0.0) + n


def gauge_set(name: str, value: float, **labels) -> None:
    """Set gauge ``name`` (last write wins; no-op while disarmed).

    Raises ``SketchValueError`` for an undeclared name or a non-gauge
    metric.
    """
    if not _ACTIVE:
        return
    _metric(name, "gauge")
    with _lock:
        _gauges[_key(name, labels)] = float(value)


def _trace_of(trace):
    """The effective trace context of an armed observation: the
    explicit ``trace=`` argument, else the tracing layer's current
    context (None when tracing is disarmed or nothing is bound)."""
    t = _tracing()
    if not t._ACTIVE:
        return None
    return trace if trace is not None else t.current()


def observe(name: str, seconds: float, trace=None, **labels) -> None:
    """Feed one duration into histogram ``name`` (no-op while disarmed).

    Raises ``SketchValueError`` for an undeclared name or a
    non-histogram metric; the value lands in a DDSketch, so snapshot
    quantiles are within ``HISTOGRAM_REL_ACC`` of exact.  ``trace``
    (a ``tracing.TraceContext``; defaults to the current bound context
    when the flight recorder is armed) attaches a ``(trace_id,
    wall_time, value)`` exemplar to the value's histogram bin.
    """
    if not _ACTIVE:
        return
    _metric(name, "histogram")
    ctx = _trace_of(trace)
    ex = (ctx.trace_hex, wall_time()) if ctx is not None else None
    k = _key(name, labels)
    with _lock:
        h = _hists.get(k)
        if h is None:
            h = _hists[k] = _Hist()
        h.add(float(seconds), ex)


def _tid() -> int:
    ident = threading.get_ident()
    t = _tids.get(ident)
    if t is None:
        t = _tids[ident] = len(_tids) + 1
    return t


def _append_event(ev: dict) -> None:
    # Caller holds ``_lock``: the drop counter mutates ``_counters``
    # directly (``counter_inc`` would deadlock re-acquiring the lock).
    global _events_dropped
    if len(_events) < _MAX_EVENTS:
        _events.append(ev)
    else:
        _events_dropped += 1
        k = ("spans.dropped", ())
        _counters[k] = _counters.get(k, 0.0) + 1.0


def finish_span(name: str, t0: float, trace=None, **labels) -> float:
    """Close a span opened at ``t0 = telemetry.clock()`` -> duration.

    Feeds histogram ``name`` and appends one Chrome-trace ``X`` event
    (per-thread track, bounded ring).  The explicit-``t0`` form is the
    hot-seam idiom: the seam pays ONE bool test while disarmed
    (``t0 = telemetry.clock() if telemetry._ACTIVE else None``) instead
    of a context-manager allocation.  ``trace`` (optional, defaults to
    the tracing layer's current context when armed; old callers are
    unchanged) links the span into its request's trace: the chrome
    event carries the ids (rendered as causal flow arrows by
    :func:`chrome_trace`), the histogram bin gains an exemplar, and the
    flight recorder mirrors the span.  Raises ``SketchValueError`` for
    an undeclared name; while disarmed it records nothing and returns
    0.0.
    """
    if not _ACTIVE:
        return 0.0
    _metric(name, "histogram")
    now = clock()
    dur = max(now - t0, 0.0)
    ctx = _trace_of(trace)
    ex = (ctx.trace_hex, wall_time()) if ctx is not None else None
    args = {k2: str(v) for k2, v in labels.items()}
    if ctx is not None:
        args.update(
            trace_id=ctx.trace_hex, span_id=ctx.span_hex,
            parent_id=ctx.parent_hex or "",
        )
    k = _key(name, labels)
    with _lock:
        h = _hists.get(k)
        if h is None:
            h = _hists[k] = _Hist()
        h.add(dur, ex)
        _append_event(
            {
                "name": name,
                "cat": "sketches_tpu",
                "ph": "X",
                "ts": (t0 - _epoch_pc) * 1e6,
                "dur": dur * 1e6,
                "pid": CHROME_PID_SPANS,
                "tid": _tid(),
                "args": args,
            }
        )
    t = _tracing()
    if t._ACTIVE:
        # Mirror the span into the flight recorder (outside _lock:
        # record_event takes the recorder's own lock and the declared
        # tracing.events counter re-enters this module's API).
        t.record_event("span", ctx=ctx, name=name, dur_s=dur, **labels)
    return dur


class _NoopSpan:
    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("name", "labels", "t0")

    def __init__(self, name: str, labels: Dict[str, Any]):
        self.name = name
        self.labels = labels

    def __enter__(self) -> "_Span":
        self.t0 = clock()
        return self

    def __exit__(self, *exc) -> bool:
        finish_span(self.name, self.t0, **self.labels)
        return False


def span(name: str, **labels):
    """Context manager timing a section into histogram ``name``.

    Nest freely across threads: each thread renders as its own trace
    track, and nesting shows as stacked ``X`` events.  Disarmed, it
    returns a shared no-op and records nothing; the name check (raises
    ``SketchValueError`` when undeclared) runs at exit via
    :func:`finish_span`, after the timed section.
    """
    if not _ACTIVE:
        return _NOOP_SPAN
    return _Span(name, labels)


def event(name: str, **labels) -> None:
    """Record an instant: counter ``name`` += 1 plus one trace ``i`` event.

    The bridge idiom for discrete occurrences (resilience downgrades).
    Raises ``SketchValueError`` for an undeclared/non-counter name;
    no-op while disarmed.
    """
    if not _ACTIVE:
        return
    _metric(name, "counter")
    k = _key(name, labels)
    with _lock:
        _counters[k] = _counters.get(k, 0.0) + 1.0
        _append_event(
            {
                "name": name,
                "cat": "sketches_tpu",
                "ph": "i",
                "s": "t",
                "ts": (clock() - _epoch_pc) * 1e6,
                "pid": CHROME_PID_SPANS,
                "tid": _tid(),
                "args": {k2: str(v) for k2, v in labels.items()},
            }
        )


# ---------------------------------------------------------------------------
# Exporters
# ---------------------------------------------------------------------------


def _render_key(k: _Key) -> str:
    name, labels = k
    if not labels:
        return name
    inner = ",".join(f'{lk}="{lv}"' for lk, lv in labels)
    return f"{name}{{{inner}}}"


def snapshot() -> dict:
    """JSON-safe snapshot of every metric plus the resilience ledger.

    ``resilience.health()`` rides along verbatim under ``"resilience"``,
    so demotion counters and the ledger can never disagree in one
    artifact; an empty snapshot (no counters, no histograms) is the
    disarmed/idle steady state, not an error.  When the profiling or
    accuracy-audit layers are armed their sections ride along too
    (``"profiling"``: the measured-vs-roofline attribution table,
    ``"accuracy"``: the drift-audit summary).  Every histogram summary
    embeds its sparse bin state, so snapshots written to disk stay
    foldable by :func:`merge_snapshots` / ``--merge``.
    """
    with _lock:
        counters = {_render_key(k): v for k, v in _counters.items()}
        gauges = {_render_key(k): v for k, v in _gauges.items()}
        hists = {_render_key(k): h.summary() for k, h in _hists.items()}
        spans = {"n_events": len(_events), "dropped": _events_dropped}
    from sketches_tpu import resilience

    out = {
        "enabled": _ACTIVE,
        "histogram_relative_accuracy": HISTOGRAM_REL_ACC,
        "counters": counters,
        "gauges": gauges,
        "histograms": hists,
        "spans": spans,
        "resilience": resilience.health(),
    }
    from sketches_tpu import profiling as _profiling

    if _profiling._ACTIVE:
        out["profiling"] = _profiling.attribution()
    from sketches_tpu import accuracy as _accuracy

    if _accuracy._ACTIVE:
        out["accuracy"] = _accuracy.summary()
    t = _tracing()
    if t._ACTIVE:
        out["tracing"] = t.stats()
    return out


def _prom_name(name: str) -> str:
    base = name.replace(".", "_").replace("-", "_")
    if base.endswith("_s"):
        base = base[:-2] + "_seconds"
    return "sketches_tpu_" + base


def _prom_labels(labels: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _quantile_key(state: dict, q: float) -> Optional[int]:
    """The mapping key of the bin containing quantile ``q`` of an
    embedded histogram state (negative/zero mass ranks below the
    positive bins -- the positive-valued latency case this layer
    records).  None for an empty state."""
    pos = {int(k): float(v) for k, v in (state.get("pos") or {}).items()}
    neg_total = sum(float(v) for v in (state.get("neg") or {}).values())
    zero = float(state.get("zero_count", 0.0))
    total = zero + neg_total + sum(pos.values())
    if total <= 0 or not pos:
        return None
    rank = q * total
    cum = zero + neg_total
    for k in sorted(pos):
        cum += pos[k]
        if cum >= rank:
            return k
    return max(pos)


def _exemplar_near(summary: dict, key: Optional[int]) -> Optional[dict]:
    """The exemplar entry nearest bin ``key`` (exact bin preferred,
    else smallest key distance) -> entry dict + its bin, or None when
    the summary carries no exemplars."""
    ex = summary.get("exemplars")
    if not isinstance(ex, dict) or not ex or key is None:
        return None
    best = min((int(k) for k in ex), key=lambda kk: abs(kk - key))
    entries = ex[str(best)]
    if not entries:
        return None
    return {"bin": best, **entries[0]}


def prometheus_text() -> str:
    """Prometheus text exposition of the current metrics.

    Counters export with a ``_total`` suffix, histograms as summaries
    (``quantile`` label series + ``_sum``/``_count``), all under the
    ``sketches_tpu_`` prefix.  Quantile lines whose bin carries a trace
    exemplar append an OpenMetrics-style exemplar annotation
    (``# {trace_id="..."} value timestamp``) linking the bucket to the
    trace that landed there.  An empty exposition is the disarmed/idle
    steady state; parse failures are the consumer's to report.
    """
    with _lock:
        counters = dict(_counters)
        gauges = dict(_gauges)
        hists = {k: h.summary() for k, h in _hists.items()}
    lines: List[str] = []
    seen_header = set()

    def header(name: str, prom: str, mtype: str) -> None:
        if prom in seen_header:
            return
        seen_header.add(prom)
        m = METRICS.get(name)
        if m is not None:
            lines.append(f"# HELP {prom} {m.doc}")
        lines.append(f"# TYPE {prom} {mtype}")

    for (name, labels), v in sorted(counters.items()):
        prom = _prom_name(name) + "_total"
        header(name, prom, "counter")
        lines.append(f"{prom}{_prom_labels(labels)} {v:g}")
    for (name, labels), v in sorted(gauges.items()):
        prom = _prom_name(name)
        header(name, prom, "gauge")
        lines.append(f"{prom}{_prom_labels(labels)} {v:g}")
    for (name, labels), s in sorted(hists.items()):
        prom = _prom_name(name)
        header(name, prom, "summary")
        state = s.get("state") or {}
        for q, label in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99"),
                         (0.999, "p999")):
            val = s[label]
            if val is None:
                continue
            qlabel = 'quantile="%g"' % q
            line = f"{prom}{_prom_labels(labels, qlabel)} {val:g}"
            ex = _exemplar_near(s, _quantile_key(state, q))
            if ex is not None:
                line += (
                    f' # {{trace_id="{ex["trace_id"]}"}}'
                    f" {ex['value']:g} {ex['wall_time']:.3f}"
                )
            lines.append(line)
        lines.append(f"{prom}_sum{_prom_labels(labels)} {s['sum']:g}")
        lines.append(f"{prom}_count{_prom_labels(labels)} {s['count']:g}")
    return "\n".join(lines) + ("\n" if lines else "")


def exemplars_for(snap: dict, metric: str, q: float = 0.99) -> dict:
    """Which traces sit behind quantile ``q`` of histogram ``metric`` in
    a snapshot -> ``{"metric", "q", "bin_key", "bin_value",
    "exemplar_bin", "exemplars": [{trace_id, wall_time, value}, ...]}``.

    Folds the metric's label series first (so merged and single-process
    snapshots answer alike), locates the bin containing ``q`` from the
    embedded sketch state, and returns that bin's exemplar reservoir
    (nearest exemplar-bearing bin when the exact bin kept none --
    reservoirs only hold traced observations).  An empty ``exemplars``
    list means no traced observation reached the neighborhood, not an
    error.  Raises ``SketchValueError`` when the snapshot carries no
    such histogram or no embedded bin state.
    """
    rel_acc = float(
        snap.get("histogram_relative_accuracy", HISTOGRAM_REL_ACC)
    )
    series = [
        sm for k, sm in (snap.get("histograms") or {}).items()
        if _series_name(k) == metric
    ]
    if not series:
        _raise_value_error(
            f"snapshot carries no histogram named {metric!r}"
        )
    merged = (
        series[0] if len(series) == 1
        else _merge_hist_summaries(series, rel_acc)
    )
    state = merged.get("state")
    if not isinstance(state, dict):
        _raise_value_error(
            f"histogram {metric!r} carries no embedded bin state (pre-r11"
            " snapshot); exemplars cannot be located"
        )
    key = _quantile_key(state, q)
    from sketches_tpu.mapping import LogarithmicMapping

    mapping = LogarithmicMapping(rel_acc)
    ex = merged.get("exemplars") or {}
    exemplar_bin = None
    entries: List[dict] = []
    if ex and key is not None:
        exemplar_bin = min(
            (int(k) for k in ex), key=lambda kk: abs(kk - key)
        )
        entries = list(ex[str(exemplar_bin)])
    return {
        "metric": metric,
        "q": q,
        "bin_key": key,
        "bin_value": mapping.value(key) if key is not None else None,
        "exemplar_bin": exemplar_bin,
        "exemplars": entries,
    }


def _flow_events(events: List[dict]) -> List[dict]:
    """Causal flow arrows linking trace-linked spans: for every span
    whose recorded ``parent_id`` is another recorded span, emit a
    Chrome flow start (``s``) at the parent and a binding-at-enclosing
    end (``f``/``bp=e``) at the child, id'd by the child span.  Spans
    without trace ids (or with parents outside the ring) emit nothing
    -- absent linkage degrades to plain spans, never an error."""
    by_span: Dict[str, dict] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        span = (e.get("args") or {}).get("span_id")
        if span:
            by_span[span] = e
    flows: List[dict] = []
    for e in by_span.values():
        args = e.get("args") or {}
        parent = by_span.get(args.get("parent_id") or "")
        if parent is None:
            continue
        common = {
            "name": "trace", "cat": "sketches_tpu.flow",
            "id": args["span_id"],
        }
        flows.append(
            {
                **common, "ph": "s", "pid": parent["pid"],
                "tid": parent["tid"], "ts": parent["ts"],
            }
        )
        flows.append(
            {
                **common, "ph": "f", "bp": "e", "pid": e["pid"],
                "tid": e["tid"], "ts": max(e["ts"], parent["ts"]),
            }
        )
    return flows


def chrome_trace() -> dict:
    """Chrome-trace/perfetto event JSON of the recorded spans.

    Same ``traceEvents`` conventions ``bench.py`` parses from the TPU
    runtime (``process_name``/``thread_name`` metadata + ``X`` duration
    events), so one viewer serves both.  The pid scheme is declared and
    collision-free (:data:`CHROME_PID_SPANS` for host span threads,
    :data:`CHROME_PID_DEVICE` for the profiling layer's device track),
    with ``thread_name`` metadata on every track; spans carrying trace
    ids are additionally linked by causal flow events (``s``/``f``), so
    Perfetto draws the request's path across threads.  An empty event
    list is the disarmed/idle steady state.
    """
    with _lock:
        events = list(_events)
        tids = dict(_tids)
    meta: List[dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": CHROME_PID_SPANS,
            "args": {"name": "sketches_tpu telemetry"},
        }
    ]
    for ident, t in sorted(tids.items(), key=lambda kv: kv[1]):
        meta.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": CHROME_PID_SPANS,
                "tid": t,
                "args": {"name": f"thread-{ident}"},
            }
        )
    all_events = meta + events + _flow_events(events)
    from sketches_tpu import profiling as _profiling

    if _profiling._ACTIVE:
        all_events = all_events + _profiling.chrome_events()
    return {"displayTimeUnit": "ms", "traceEvents": all_events}


# ---------------------------------------------------------------------------
# Snapshot merge algebra (the paper's mergeability, applied to ourselves)
# ---------------------------------------------------------------------------


def _series_name(rendered: str) -> str:
    """The metric base name of a rendered series key (labels stripped)."""
    return rendered.split("{", 1)[0]


def _gauge_policy(rendered: str) -> str:
    m = METRICS.get(_series_name(rendered))
    return m.merge if m is not None and m.kind == "gauge" else "max"


def _merge_exemplars(summaries: List[dict]) -> Optional[Tuple[dict, int, int]]:
    """Fold the operands' exemplar reservoirs -> ``(bins, seen,
    dropped)`` or None when no operand carries exemplars.

    Concat + re-reservoir: per bin, the union of entries (deduplicated
    on the full triple) is re-selected bottom-k by the splitmix64
    priority of the trace ids -- the same deterministic discipline the
    live reservoir applies, so the fold is associative and commutative
    (bounded top-k of a multiset under a fixed total order).  The bin
    set is ring-bounded at :data:`EXEMPLAR_BINS`, keeping the LARGEST
    keys (the tail bins exemplars exist for); everything trimmed is
    counted: ``dropped == seen - kept`` by construction.
    """
    by_bin: Dict[int, Dict[Tuple[str, float, float], dict]] = {}
    seen = 0
    any_ex = False
    for sm in summaries:
        seen += int(sm.get("exemplars_seen", 0) or 0)
        ex = sm.get("exemplars")
        if not isinstance(ex, dict):
            continue
        any_ex = True
        for bk, lst in ex.items():
            bucket = by_bin.setdefault(int(bk), {})
            for e in lst:
                entry = {
                    "trace_id": str(e["trace_id"]),
                    "wall_time": float(e["wall_time"]),
                    "value": float(e["value"]),
                }
                bucket[
                    (entry["trace_id"], entry["wall_time"], entry["value"])
                ] = entry
    if not any_ex and seen == 0:
        return None
    kept = 0
    out: Dict[str, List[dict]] = {}
    for bk in sorted(sorted(by_bin, reverse=True)[:EXEMPLAR_BINS]):
        cand = sorted(
            by_bin[bk].values(),
            key=lambda e: (
                _exemplar_priority(e["trace_id"]), e["wall_time"], e["value"]
            ),
        )[:EXEMPLARS_PER_BIN]
        kept += len(cand)
        out[str(bk)] = cand
    return out, seen, max(seen - kept, 0)


def _merge_hist_summaries(summaries: List[dict], rel_acc: float) -> dict:
    """Fold N histogram summaries into one by DDSketch bin addition.

    Same-key bin mass adds (exactly ``DDSketch.merge`` on equal-gamma
    sketches), so the merged quantiles carry the single-process alpha
    contract; count/sum/min/max fold exactly; exemplar reservoirs
    concat + re-reservoir deterministically (:func:`_merge_exemplars`
    -- the fold stays associative/commutative, drops counted).  Raises
    ``SketchValueError`` when a summary has no embedded bin state (a
    pre-r11 snapshot cannot be merged, only read).
    """
    pos: Dict[str, float] = {}
    neg: Dict[str, float] = {}
    zero = 0.0
    total_sum = 0.0
    mn, mx = math.inf, -math.inf
    for sm in summaries:
        st = sm.get("state")
        if not isinstance(st, dict):
            _raise_value_error(
                "snapshot histogram carries no embedded bin state (pre-r11"
                " format); re-export the snapshot with this version to merge"
            )
        for out_bins, in_bins in ((pos, st.get("pos", {})),
                                  (neg, st.get("neg", {}))):
            for k, v in in_bins.items():
                out_bins[k] = out_bins.get(k, 0.0) + float(v)
        zero += float(st.get("zero_count", 0.0))
        total_sum += float(sm.get("sum", 0.0))
        if sm.get("min") is not None:
            mn = min(mn, float(sm["min"]))
        if sm.get("max") is not None:
            mx = max(mx, float(sm["max"]))
    state = {"zero_count": zero, "pos": pos, "neg": neg}
    sk = _sketch_from_state(state, rel_acc)
    out = {
        "count": sk.count,
        "sum": total_sum,
        "min": None if math.isinf(mn) else mn,
        "max": None if math.isinf(mx) else mx,
        "relative_accuracy": rel_acc,
    }
    for q, label in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99"),
                     (0.999, "p999")):
        out[label] = sk.get_quantile_value(q)
    out["state"] = state
    merged_ex = _merge_exemplars(summaries)
    if merged_ex is not None:
        out["exemplars"], out["exemplars_seen"], out["exemplars_dropped"] = (
            merged_ex
        )
    return out


def _merge_health(healths: List[dict]) -> dict:
    """Fold resilience ledgers: counters sum, downgrade events
    concatenate (ring-bounded, overflow counted), conflicting tier
    entries join as ``"a|b"`` rather than silently picking one."""
    tiers: Dict[str, str] = {}
    counters: Dict[str, float] = {}
    downgrades: List[dict] = []
    dropped = 0.0
    for h in healths:
        if not isinstance(h, dict):
            continue
        for k, v in (h.get("tiers") or {}).items():
            if k in tiers and v not in tiers[k].split("|"):
                tiers[k] = tiers[k] + "|" + str(v)
            elif k not in tiers:
                tiers[k] = str(v)
        for k, v in (h.get("counters") or {}).items():
            counters[k] = counters.get(k, 0.0) + float(v)
        downgrades.extend(h.get("downgrades") or [])
        dropped += float(h.get("downgrades_dropped", 0))
    if len(downgrades) > _MAX_EVENTS:
        dropped += len(downgrades) - _MAX_EVENTS
        downgrades = downgrades[:_MAX_EVENTS]
    return {
        "tiers": tiers,
        "counters": counters,
        "downgrades": downgrades,
        "downgrades_dropped": dropped,
    }


def _merge_profiling(profs: List[dict]) -> dict:
    """Fold profiling attribution sections: measured calls/time sum,
    min/max fold; the roofline/peaks tables (static per build) come from
    the first operand carrying them.  Fleet-wide device-time
    *percentiles* live in the ``profiling.device_s`` histogram, which
    merges with full sketch fidelity."""
    measured: Dict[str, dict] = {}
    dropped = 0.0
    for p in profs:
        for k, row in (p.get("measured") or {}).items():
            agg = measured.get(k)
            if agg is None:
                agg = measured[k] = {
                    "phase": row.get("phase"),
                    "tier": row.get("tier"),
                    "calls": 0.0,
                    "total_s": 0.0,
                    "min_s": math.inf,
                    "max_s": -math.inf,
                }
            agg["calls"] += float(row.get("calls", 0))
            agg["total_s"] += float(row.get("total_s", 0.0))
            if row.get("min_s") is not None:
                agg["min_s"] = min(agg["min_s"], float(row["min_s"]))
            if row.get("max_s") is not None:
                agg["max_s"] = max(agg["max_s"], float(row["max_s"]))
        dropped += float(p.get("events_dropped", 0))
    for agg in measured.values():
        agg["mean_s"] = (
            agg["total_s"] / agg["calls"] if agg["calls"] else None
        )
        if math.isinf(agg["min_s"]):
            agg["min_s"] = None
        if math.isinf(agg["max_s"]):
            agg["max_s"] = None
    first = next((p for p in profs if p.get("roofline")), {})
    return {
        "measured": measured,
        "roofline": first.get("roofline", {}),
        "attribution": first.get("attribution", []),
        "peaks": first.get("peaks", {}),
        "events_dropped": dropped,
    }


def merge_snapshots(*snaps: dict) -> dict:
    """Fold N :func:`snapshot` documents into one fleet-wide snapshot.

    Counters fold by sum, gauges by their declared ``merge`` policy
    (``max`` for names this process has not declared), histograms by
    DDSketch bin addition -- so the merged p50/p99 carry the same
    ``HISTOGRAM_REL_ACC`` relative-error guarantee as any single
    process's, which is the paper's mergeability property applied to
    the library's own telemetry.  The fold is associative and
    commutative (bin addition is), so shard trees of any shape agree.

    Raises ``SketchValueError`` for zero operands, mismatched histogram
    relative accuracies, or histogram summaries without embedded bin
    state (pre-r11 snapshots).  ``merged_from`` counts the leaf
    snapshots folded in (merged operands contribute their own count).
    """
    if not snaps:
        _raise_value_error("merge_snapshots needs at least one snapshot")
    ras = {
        float(s.get("histogram_relative_accuracy", HISTOGRAM_REL_ACC))
        for s in snaps
    }
    if len(ras) != 1:
        _raise_value_error(
            "cannot merge snapshots with different histogram relative"
            f" accuracies {sorted(ras)}: the merged quantiles would carry"
            " no single alpha contract"
        )
    rel_acc = ras.pop()

    counters: Dict[str, float] = {}
    for s in snaps:
        for k, v in (s.get("counters") or {}).items():
            counters[k] = counters.get(k, 0.0) + float(v)

    gauges: Dict[str, float] = {}
    for s in snaps:
        for k, v in (s.get("gauges") or {}).items():
            v = float(v)
            if k not in gauges:
                gauges[k] = v
                continue
            policy = _gauge_policy(k)
            if policy == "sum":
                gauges[k] += v
            elif policy == "min":
                gauges[k] = min(gauges[k], v)
            else:
                gauges[k] = max(gauges[k], v)

    by_series: Dict[str, List[dict]] = {}
    for s in snaps:
        for k, sm in (s.get("histograms") or {}).items():
            by_series.setdefault(k, []).append(sm)
    hists = {
        k: _merge_hist_summaries(sms, rel_acc)
        for k, sms in by_series.items()
    }

    spans = {
        "n_events": sum(
            int((s.get("spans") or {}).get("n_events", 0)) for s in snaps
        ),
        "dropped": sum(
            int((s.get("spans") or {}).get("dropped", 0)) for s in snaps
        ),
    }

    out = {
        "enabled": any(bool(s.get("enabled")) for s in snaps),
        "histogram_relative_accuracy": rel_acc,
        "counters": counters,
        "gauges": gauges,
        "histograms": hists,
        "spans": spans,
        "resilience": _merge_health(
            [s.get("resilience") for s in snaps if s.get("resilience")]
        ),
        "merged_from": sum(int(s.get("merged_from", 1)) for s in snaps),
    }
    profs = [s["profiling"] for s in snaps if isinstance(s.get("profiling"), dict)]
    if profs:
        out["profiling"] = _merge_profiling(profs)
    trcs = [s["tracing"] for s in snaps if isinstance(s.get("tracing"), dict)]
    if trcs:
        out["tracing"] = {
            "events": sum(int(t.get("events", 0)) for t in trcs),
            "recorded": sum(int(t.get("recorded", 0)) for t in trcs),
            "dropped": sum(int(t.get("dropped", 0)) for t in trcs),
            "capacity": max(int(t.get("capacity", 0)) for t in trcs),
            "bundles": sum(int(t.get("bundles", 0)) for t in trcs),
            "bundles_dropped": sum(
                int(t.get("bundles_dropped", 0)) for t in trcs
            ),
        }
    accs = [s["accuracy"] for s in snaps if isinstance(s.get("accuracy"), dict)]
    if accs:
        out["accuracy"] = {
            "watched": sum(int(a.get("watched", 0)) for a in accs),
            "audits": sum(int(a.get("audits", 0)) for a in accs),
            "violations": sum(int(a.get("violations", 0)) for a in accs),
            "reports": sum(int(a.get("reports", 0)) for a in accs),
            "reports_dropped": sum(
                int(a.get("reports_dropped", 0)) for a in accs
            ),
        }
    return out


# ---------------------------------------------------------------------------
# SLO gate
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declared service-level objective over the metric inventory.

    ``kind="latency"``: ``metric`` names a histogram; the bad fraction
    is the recorded mass above ``target_s`` (computed from the embedded
    sketch bins, so it carries the alpha contract; falls back to a
    p99-vs-target check on stateless summaries).  ``kind="ratio"``:
    ``metric``/``total`` name counters; the bad fraction is their
    ratio.  ``budget`` is the allowed bad fraction over ``window``;
    the **burn rate** is ``bad_fraction / budget`` and the SLO is
    burning when it exceeds ``burn_threshold``.  A metric absent from
    the snapshot (or with zero mass) is *skipped*, never a pass.
    """

    name: str
    kind: str  # "latency" | "ratio"
    metric: str
    total: str = ""
    target_s: float = 0.0
    budget: float = 0.01
    burn_threshold: float = 1.0
    window: str = "1h"
    doc: str = ""


#: The declared SLO inventory ``--check-slo`` evaluates: the acceptance
#: instrument for the serving tier (ROADMAP #1).  Budgets are sized for
#: clean production-shaped workloads (the fleet_dashboard example, the
#: CI observability job), with headroom for host-timed jitter but not
#: for drift: a latency regression, a quarantine storm, or an alpha-
#: contract break burns through them.
SLOS: Tuple[SLO, ...] = (
    SLO(
        "query-latency", "latency", "query_s", target_s=0.25, budget=0.05,
        window="1h",
        doc="<=5% of (warm) query dispatches above 250 ms.",
    ),
    SLO(
        "ingest-latency", "latency", "ingest_s", target_s=1.0, budget=0.05,
        window="1h",
        doc="<=5% of facade ingest dispatches above 1 s.",
    ),
    SLO(
        "wire-decode-latency", "latency", "wire.decode_s", target_s=5.0,
        budget=0.25, window="1h",
        doc="<=25% of bulk wire decodes above 5 s (a bulk decode covers"
        " up to 100k+ blobs; the ROADMAP letter targets 1 s at 100k).",
    ),
    SLO(
        "wire-quarantine", "ratio", "wire.blobs_quarantined",
        total="wire.blobs_decoded", budget=0.001, window="1h",
        doc="<=0.1% of decoded blobs quarantined: more means corrupt"
        " producers or wire drift, not isolated bit rot.",
    ),
    SLO(
        "accuracy-contract", "ratio", "accuracy.violations",
        total="accuracy.audits", budget=0.01, window="1h",
        doc="<=1% of shadow audits may breach the alpha contract"
        " (UDDSketch's silent-collapse failure mode, gated).",
    ),
    SLO(
        "serve-shed", "ratio", "serve.shed", total="serve.requests",
        budget=0.05, window="1h",
        doc="<=5% of serving requests shed at admission: shedding is the"
        " declared overload valve, but sustained shedding means the"
        " fleet is undersized, not protected.",
    ),
    SLO(
        "serve-deadline", "ratio", "serve.deadline_misses",
        total="serve.requests", budget=0.05, window="1h",
        doc="<=5% of serving requests may miss their deadline budget"
        " even after degrading to the cheapest engine tier.",
    ),
)


def check_slo(
    snap: dict, slos: Optional[Tuple[SLO, ...]] = None
) -> Tuple[List[str], int, int]:
    """Evaluate :data:`SLOS` against a snapshot -> (report lines,
    n_burning, n_evaluated).

    Works on single-process and merged snapshots alike.  SLOs whose
    metrics are absent (or have zero total mass) are skipped -- callers
    must treat ``n_evaluated == 0`` as a failure in its own right (the
    ``check_bench`` convention: wrong files beat a silent pass).
    """
    if slos is None:
        slos = SLOS
    rel_acc = float(snap.get("histogram_relative_accuracy",
                             HISTOGRAM_REL_ACC))
    hists = snap.get("histograms") or {}
    counters = snap.get("counters") or {}
    lines: List[str] = []
    burning = evaluated = 0
    for slo in slos:
        if slo.kind == "ratio":
            bad = sum(
                float(v) for k, v in counters.items()
                if _series_name(k) == slo.metric
            )
            total = sum(
                float(v) for k, v in counters.items()
                if _series_name(k) == slo.total
            )
            if total <= 0:
                lines.append(f"  skipped  {slo.name}: no {slo.total} mass")
                continue
            frac = bad / total
            detail = f"bad {bad:g}/{total:g}"
        else:
            series = [
                sm for k, sm in hists.items()
                if _series_name(k) == slo.metric
            ]
            total = sum(float(sm.get("count", 0.0)) for sm in series)
            if total <= 0:
                lines.append(
                    f"  skipped  {slo.name}: no {slo.metric} observations"
                )
                continue
            states = [
                sm["state"] for sm in series
                if isinstance(sm.get("state"), dict)
            ]
            if len(states) == len(series):
                from sketches_tpu.mapping import LogarithmicMapping

                mapping = LogarithmicMapping(rel_acc)
                bad = 0.0
                for st in states:
                    for key, cnt in st.get("pos", {}).items():
                        if mapping.value(int(key)) > slo.target_s:
                            bad += float(cnt)
                frac = bad / total
                detail = f"bad {bad:g}/{total:g} above {slo.target_s:g}s"
            else:
                # Stateless (pre-r11) summary: p99 vs target is the best
                # available proxy -- burning iff p99 blows the target.
                p99 = max(
                    (float(sm["p99"]) for sm in series
                     if sm.get("p99") is not None),
                    default=0.0,
                )
                frac = slo.budget * (p99 / slo.target_s) if slo.target_s else 0.0
                detail = f"p99 {p99:g}s vs target {slo.target_s:g}s (no state)"
        if slo.budget > 0:
            burn = frac / slo.budget
        else:
            burn = math.inf if frac > 0 else 0.0
        evaluated += 1
        bad_slo = burn > slo.burn_threshold
        if bad_slo:
            burning += 1
        verdict = "BURNING" if bad_slo else "ok"
        lines.append(
            f"{verdict:>9}  {slo.name}: burn x{burn:.2f} ({detail},"
            f" budget {slo.budget:.2%}/{slo.window},"
            f" threshold x{slo.burn_threshold:g})"
        )
    return lines, burning, evaluated


# ---------------------------------------------------------------------------
# Bench-derived snapshots (the checked-in SLO-gate fixture)
# ---------------------------------------------------------------------------

#: Bench summary latency fields -> (histogram metric, labels): the
#: measured numbers a ``--bench-snapshot`` replays into sketch-backed
#: histograms, producing a real mergeable snapshot from a checked-in
#: BENCH document (so the SLO gate has a stable, reviewable fixture).
_BENCH_OBSERVE: Tuple[Tuple[str, str, Tuple[Tuple[str, str], ...]], ...] = (
    ("configs.c0_host_python.query_s", "query_s",
     (("component", "bench"), ("tier", "host"))),
    ("configs.c1_10k_streams.query_p50_s", "query_s",
     (("component", "bench"), ("tier", "c1"))),
    ("configs.c1_10k_streams.query_p99_s", "query_s",
     (("component", "bench"), ("tier", "c1"))),
    ("configs.c2_c4_1m_streams_cubic_collapsing.query_p50_s", "query_s",
     (("component", "bench"), ("tier", "c2"))),
    ("configs.c2s_shard_query_131k.worst_mixed_sign.query_sustained_s",
     "query_s", (("component", "bench"), ("tier", "shard131k"))),
    ("configs.c2s_shard_query_131k.wide_window.query_sustained_s",
     "query_s", (("component", "bench"), ("tier", "shard131k"))),
    ("configs.c2s_shard_query_131k.mid_occupancy.query_sustained_s",
     "query_s", (("component", "bench"), ("tier", "shard131k"))),
    ("configs.c2s_shard_query_131k.tight_telemetry.query_sustained_s",
     "query_s", (("component", "bench"), ("tier", "shard131k"))),
    ("configs.c2s_shard_query_131k.merge_per_shard_s", "merge_s",
     (("component", "bench"),)),
    ("configs.c3_distributed.cpu_mesh_8dev.psum_merge.merge_s",
     "distributed.fold_s", ()),
    ("configs.serde_bulk.to_bytes_s", "wire.encode_s", ()),
    ("configs.serde_bulk.from_bytes_s", "wire.decode_s", ()),
)


def snapshot_from_bench(bench_doc: dict) -> dict:
    """Derive a mergeable snapshot from a ``bench.py`` summary document.

    Each known latency field (:data:`_BENCH_OBSERVE`) is observed into
    the matching sketch-backed histogram, so the result is a REAL
    snapshot -- mergeable, SLO-checkable -- whose distributions are the
    bench's measured numbers.  Raises ``SketchValueError`` when the
    document carries none of the known fields (wrong file).
    """
    hists: Dict[_Key, _Hist] = {}
    observed = 0
    for path, metric, labels in _BENCH_OBSERVE:
        v = _lookup(bench_doc, path)
        if v is None:
            continue
        k = _key(metric, dict(labels))
        h = hists.get(k)
        if h is None:
            h = hists[k] = _Hist()
        h.add(float(v))
        observed += 1
    if not observed:
        _raise_value_error(
            "bench document carries no known latency field; expected a"
            " bench.py summary (e.g. BENCH_local_r05.json)"
        )
    return {
        "enabled": False,
        "histogram_relative_accuracy": HISTOGRAM_REL_ACC,
        "counters": {},
        "gauges": {},
        "histograms": {_render_key(k): h.summary() for k, h in hists.items()},
        "spans": {"n_events": 0, "dropped": 0},
        "resilience": {
            "tiers": {}, "counters": {}, "downgrades": [],
            "downgrades_dropped": 0,
        },
        "derived_from": "bench",
    }


# ---------------------------------------------------------------------------
# Bench regression gate
# ---------------------------------------------------------------------------

#: (dot.path into the bench summary document, direction, tolerance).
#: ``higher`` metrics regress when new < old * (1 - tol); ``lower``
#: (latency) metrics regress when new > old * (1 + tol).  Tolerances are
#: per-metric noise budgets: device-sustained rates are tight, host-timed
#: loops (Python/serde) breathe more run to run.
BENCH_GATE: Tuple[Tuple[str, str, float], ...] = (
    ("value", "higher", 0.15),
    ("configs.c0_host_python.add_per_s", "higher", 0.30),
    ("configs.c0_host_native.add_per_s", "higher", 0.30),
    ("configs.c0_jax_scalar.add_per_s", "higher", 0.30),
    ("configs.c0_jax_scalar.add_many_per_s", "higher", 0.30),
    ("configs.c1_10k_streams.ingest_fused_per_s", "higher", 0.15),
    ("configs.c1_10k_streams.ingest_dispatch_per_s", "higher", 0.15),
    ("configs.c1_10k_streams.query_p50_s", "lower", 0.30),
    ("configs.c2_c4_1m_streams_cubic_collapsing.ingest_fused_per_s",
     "higher", 0.15),
    ("configs.c2_c4_1m_streams_cubic_collapsing"
     ".ingest_fused_per_s_floorsub_batch512", "higher", 0.15),
    ("configs.c2_c4_1m_streams_cubic_collapsing"
     ".ingest_fused_per_s_floorsub_batch256", "higher", 0.15),
    # Per-construction-rung floor-subtracted ingest (r17 variants; only
    # present in driver captures that ran bench_ingest_variants on TPU).
    ("configs.ingest_variants.variants.stock.fused_floorsub_per_s",
     "higher", 0.20),
    ("configs.ingest_variants.variants.packed.fused_floorsub_per_s",
     "higher", 0.20),
    ("configs.c2s_shard_query_131k.worst_mixed_sign.query_sustained_s",
     "lower", 0.30),
    ("configs.c2s_shard_query_131k.tight_telemetry.query_sustained_s",
     "lower", 0.30),
    ("configs.c2s_shard_query_131k.worst_mixed_sign.device_query.p50_s",
     "lower", 0.25),
    ("configs.c2s_shard_query_131k.tight_telemetry.device_query.p50_s",
     "lower", 0.25),
    ("configs.c2s_shard_query_131k.merge_per_shard_s", "lower", 0.30),
    ("configs.serde_bulk.to_bytes_s", "lower", 0.40),
    ("configs.serde_bulk.from_bytes_s", "lower", 0.40),
    # Windowed query latency (r19 two-stacks maintained aggregates):
    # host-timed fused dispatches, so they breathe like the serde rows.
    ("configs.windowed.window_query_p50_s", "lower", 0.40),
    ("configs.windowed.window_query_vs_single_floorsub", "lower", 0.40),
    # Serve fabric (r21): host-timed fabric reads + failover blackout --
    # small host-clock numbers, so they get the serde-class tolerance.
    ("configs.serve_fabric.qps_vs_hosts.h4.warm_cache_qps",
     "higher", 0.40),
    ("configs.serve_fabric.qps_vs_hosts.h4.uncached_query_p50_s",
     "lower", 0.40),
    ("configs.serve_fabric.failover.blackout_s", "lower", 0.60),
)


def _lookup(doc: Any, path: str) -> Optional[float]:
    cur = doc
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur if isinstance(cur, (int, float)) else None


def capture_class(doc: dict) -> Dict[str, Optional[str]]:
    """The comparability fingerprint of a bench document.

    Two captures are comparable only when they ran on the same device
    class AND (when both declare it) the same default ingest
    construction rung -- an r06-style CPU-container capture compared
    against a TPU driver capture regresses every device metric for a
    reason that has nothing to do with the code (ISSUE 12 satellite 6:
    the two were previously indistinguishable except by eyeballing the
    ``device`` field).
    """
    device = doc.get("device")
    dev_class: Optional[str] = None
    if isinstance(device, str) and device:
        dev_class = "tpu" if "tpu" in device.lower() else "cpu"
    variant = doc.get("ingest_variant")
    return {
        "device_class": dev_class,
        "ingest_variant": variant if isinstance(variant, str) else None,
    }


def capture_mismatch(old_doc: dict, new_doc: dict) -> Optional[str]:
    """A named refusal reason when two bench documents are not
    comparable, else None.  Fields absent from either side (older
    captures predate the stamps) never refuse."""
    old_c, new_c = capture_class(old_doc), capture_class(new_doc)
    for key in ("device_class", "ingest_variant"):
        a, b = old_c[key], new_c[key]
        if a is not None and b is not None and a != b:
            return (
                f"cross-{key.replace('_', '-')} comparison:"
                f" old={a!r} new={b!r} -- device-sustained metrics are"
                " not comparable across capture classes"
            )
    return None


def check_bench(
    old_doc: dict, new_doc: dict, tolerance: Optional[float] = None
) -> Tuple[List[str], int, int]:
    """Compare two bench summary documents -> (report lines, n_regressed,
    n_compared).

    Walks :data:`BENCH_GATE`; metrics absent from either document are
    skipped (configs legitimately come and go), so callers must treat
    ``n_compared == 0`` as a failure in its own right -- two
    wrong-shaped files would otherwise "pass" vacuously.  Documents of
    different capture classes (:func:`capture_mismatch`) are REFUSED
    with a named reason line and ``compared == 0`` -- never silently
    compared, never silently passed.
    """
    lines: List[str] = []
    regressed = compared = 0
    reason = capture_mismatch(old_doc, new_doc)
    if reason is not None:
        return [f"  REFUSED  {reason}"], 0, 0
    for path, direction, tol in BENCH_GATE:
        if tolerance is not None:
            tol = tolerance
        old = _lookup(old_doc, path)
        new = _lookup(new_doc, path)
        if old is None or new is None or old == 0:
            continue
        compared += 1
        ratio = new / old
        if direction == "higher":
            bad = ratio < 1.0 - tol
            arrow = "throughput"
        else:
            bad = ratio > 1.0 + tol
            arrow = "latency"
        verdict = "REGRESSED" if bad else "ok"
        if bad:
            regressed += 1
        lines.append(
            f"{verdict:>9}  {path}: {old:g} -> {new:g}"
            f" (x{ratio:.3f}, {arrow}, tol {tol:.0%})"
        )
    return lines, regressed, compared


def _load_json(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def _round_of(path: str) -> int:
    """The rNN round number encoded in a bench capture filename (-1 when
    absent; lexicographic order then breaks ties)."""
    import os
    import re

    m = re.search(r"r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else -1


def find_comparable_pair(
    paths: List[str],
) -> Tuple[Optional[str], Optional[str], str]:
    """The newest checked-in bench capture plus the newest OLDER capture
    of the same class -> ``(old_path, new_path, reason)``.

    This replaces the CI gate's pinned r04->r05 pair (ISSUE 12 satellite
    1): the trajectory keeps growing, so the gate walks backward from
    the newest capture to the first predecessor :func:`capture_mismatch`
    accepts.  ``old_path`` is None when no predecessor is comparable
    (first capture of a new device class / construction rung) -- the
    caller reports ``reason`` and treats the gate as vacuous-by-name,
    not silently green.
    """
    ranked = sorted(paths, key=lambda p: (_round_of(p), p))
    if not ranked:
        return None, None, "no bench captures found"
    new_path = ranked[-1]
    try:
        new_doc = _load_json(new_path)
    except (OSError, ValueError) as e:
        return None, new_path, f"unreadable newest capture {new_path}: {e}"
    reasons = []
    for cand in reversed(ranked[:-1]):
        try:
            cand_doc = _load_json(cand)
        except (OSError, ValueError) as e:
            reasons.append(f"{cand}: unreadable ({e})")
            continue
        mismatch = capture_mismatch(cand_doc, new_doc)
        if mismatch is None:
            return cand, new_path, f"comparing {cand} -> {new_path}"
        reasons.append(f"{cand}: {mismatch}")
    detail = "; ".join(reasons) if reasons else "no older capture exists"
    return None, new_path, (
        f"no capture comparable with {new_path}: {detail}"
    )


def _slo_forensics(
    snap_doc: dict, snap_path: str, burning: int, evaluated: int
) -> None:
    """The ``--check-slo`` burn auto-trigger: dump a forensic bundle
    next to the offending snapshot (``<snapshot>.forensics.json``),
    with the p99 exemplar trace of a burning-candidate latency metric
    as the triggering trace.  Best-effort: a failed dump prints and
    moves on -- the gate's exit code is the contract, forensics are a
    bonus."""
    try:
        trigger = None
        for slo in SLOS:
            if slo.kind != "latency":
                continue
            try:
                found = exemplars_for(snap_doc, slo.metric, 0.99)
            except Exception:  # noqa: BLE001 - metric absent from snapshot
                continue
            if found["exemplars"]:
                trigger = found["exemplars"][0]["trace_id"]
                break
        t = _tracing()
        out_path = snap_path + ".forensics.json"
        t.dump_forensics(
            "slo-burn",
            trace=trigger,
            detail={
                "snapshot": snap_path, "burning": burning,
                "evaluated": evaluated,
            },
            snapshot=snap_doc,
            path=out_path,
        )
        print(f"check-slo: forensic bundle -> {out_path}")
    except Exception as e:  # noqa: BLE001 - forensics must not mask the gate
        print(f"check-slo: forensic dump failed: {e!r}")


def _dump_json(doc: dict, path: Optional[str]) -> None:
    text = json.dumps(doc, indent=1, sort_keys=True) + "\n"
    if path:
        with open(path, "w", encoding="utf-8") as f:
            f.write(text)
    else:
        print(text, end="")


def main(argv: Optional[List[str]] = None) -> int:
    """CLI entry point: bench regression gate, snapshot merge, SLO gate,
    bench-derived snapshots, and process snapshot dumps.

    Exit codes: 0 clean, 1 on any regressed metric / burning SLO, 2 when
    nothing was comparable or evaluable (wrong files must not pass
    silently).
    """
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m sketches_tpu.telemetry",
        description="telemetry utilities: bench regression gate, snapshot"
        " merge (fleet aggregation), SLO gate, snapshot dumps",
    )
    parser.add_argument(
        "--check-bench",
        nargs=2,
        metavar=("OLD", "NEW"),
        help="compare two bench.py summary JSONs (e.g. BENCH_local_r04.json"
        " BENCH_local_r05.json); non-zero exit on regression",
    )
    parser.add_argument(
        "--check-bench-latest",
        nargs="*",
        metavar="PATH",
        default=None,
        help="gate the newest checked-in bench capture against its newest"
        " COMPARABLE predecessor (same device class + ingest variant;"
        " defaults to BENCH_local_r*.json in the working directory) --"
        " replaces the pinned-pair invocation as the trajectory grows",
    )
    parser.add_argument(
        "--tolerance",
        type=float,
        default=None,
        help="override every per-metric tolerance with one fraction",
    )
    parser.add_argument(
        "--merge",
        nargs="+",
        metavar="SNAP",
        default=None,
        help="fold N snapshot JSONs (per-shard / per-job artifacts) into"
        " one fleet-wide snapshot; counters sum, gauges fold by declared"
        " policy, histograms merge as DDSketches (alpha preserved)",
    )
    parser.add_argument(
        "--check-slo",
        metavar="SNAPSHOT",
        default=None,
        help="evaluate the declared SLO inventory (telemetry.SLOS) against"
        " a snapshot JSON; exit 1 on any burning SLO, 2 if nothing was"
        " evaluable",
    )
    parser.add_argument(
        "--bench-snapshot",
        nargs=2,
        metavar=("BENCH", "OUT"),
        default=None,
        help="derive a mergeable snapshot from a bench.py summary's"
        " measured latencies and write it to OUT",
    )
    parser.add_argument(
        "--out",
        metavar="PATH",
        default=None,
        help="where --merge writes the merged snapshot (stdout otherwise)",
    )
    parser.add_argument(
        "--snapshot",
        metavar="PATH",
        default=None,
        help="write the current process's JSON snapshot to PATH",
    )
    parser.add_argument(
        "--prometheus",
        metavar="PATH",
        default=None,
        help="write the current process's Prometheus exposition to PATH",
    )
    args = parser.parse_args(argv)
    acted = False

    if args.snapshot:
        acted = True
        _dump_json(snapshot(), args.snapshot)
    if args.prometheus:
        acted = True
        with open(args.prometheus, "w", encoding="utf-8") as f:
            f.write(prometheus_text())

    if args.bench_snapshot:
        acted = True
        bench_path, out_path = args.bench_snapshot
        _dump_json(snapshot_from_bench(_load_json(bench_path)), out_path)
        print(f"bench-snapshot: {bench_path} -> {out_path}")

    if args.merge:
        acted = True
        merged = merge_snapshots(*[_load_json(p) for p in args.merge])
        _dump_json(merged, args.out)
        print(
            f"merge: folded {merged['merged_from']} snapshot(s)"
            + (f" -> {args.out}" if args.out else "")
        )

    if args.check_slo:
        snap_doc = _load_json(args.check_slo)
        lines, burning, evaluated = check_slo(snap_doc)
        for line in lines:
            print(line)
        if evaluated == 0:
            print(
                "check-slo: no SLO was evaluable against this snapshot"
                " (wrong file?)"
            )
            return 2
        if burning:
            print(f"check-slo: {burning}/{evaluated} SLO(s) BURNING")
            _slo_forensics(snap_doc, args.check_slo, burning, evaluated)
            return 1
        print(f"check-slo: {evaluated} SLO(s) within budget")
        return 0

    if args.check_bench_latest is not None:
        import glob as _glob

        paths = list(args.check_bench_latest) or sorted(
            _glob.glob("BENCH_local_r*.json")
        )
        old_path, new_path, reason = find_comparable_pair(paths)
        if old_path is None:
            # Named vacuous pass: the first capture of a new device
            # class / construction rung has nothing comparable behind
            # it; say exactly why instead of exit-2 ambiguity.
            print(f"check-bench-latest: gate vacuous -- {reason}")
            return 0
        print(f"check-bench-latest: {reason}")
        args.check_bench = [old_path, new_path]

    if not args.check_bench:
        if acted:
            return 0
        parser.print_usage()
        return 2

    old_path, new_path = args.check_bench
    old_doc = _load_json(old_path)
    new_doc = _load_json(new_path)
    lines, regressed, compared = check_bench(
        old_doc, new_doc, tolerance=args.tolerance
    )
    for line in lines:
        print(line)
    if compared == 0:
        if any("REFUSED" in line for line in lines):
            # The named cross-capture refusal (satellite 6): the reason
            # is already printed; the exit stays non-zero so a CI pair
            # pinned across capture classes fails loudly, not vacuously.
            print(
                "check-bench: REFUSED cross-capture comparison (see the"
                " named reason above); pick captures of one class or use"
                " --check-bench-latest"
            )
            return 2
        print(
            "check-bench: no comparable metric between the two documents"
            " (wrong files?)"
        )
        return 2
    if regressed:
        print(f"check-bench: {regressed}/{compared} metric(s) REGRESSED")
        return 1
    print(f"check-bench: {compared} metric(s) within tolerance")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
