"""Host-side (oracle) stores: key -> bin-count storage with dynamic growth.

Parity target: reference ``ddsketch/store.py`` (Store, DenseStore,
CollapsingLowestDenseStore, CollapsingHighestDenseStore -- SURVEY.md section 2
rows 5a-5d).  These are the *host* backend: plain Python lists, dynamic
resizing, used (a) as the drop-in compatible single-sketch backend and (b) as
the ground-truth oracle that the batched TPU path is parity-tested against.

The TPU-native counterpart lives in ``sketches_tpu/batched.py``: a static
``[n_streams, n_bins]`` device array with clamp-to-edge (always-collapsing)
semantics -- dynamic growth is a host-side concept that XLA's static shapes
deliberately replace (SURVEY.md section 7).
"""

from __future__ import annotations

import abc
import math
from typing import Iterator, Optional

__all__ = [
    "Store",
    "DenseStore",
    "CollapsingLowestDenseStore",
    "CollapsingHighestDenseStore",
]

CHUNK_SIZE = 128


class Store(abc.ABC):
    """Bin-count storage contract: integer keys -> float weights.

    Reference seam: ``ddsketch/store.py . Store``.

    Failure modes: ``merge`` raises ``TypeError`` for an incompatible
    store type; ``key_at_rank`` on an empty store is undefined --
    callers guard on ``is_empty`` (the sketches return ``None``/NaN for
    empty-sketch quantiles instead of calling in).
    """

    count: float

    @abc.abstractmethod
    def add(self, key: int, weight: float = 1.0) -> None:
        """Accumulate ``weight`` into bucket ``key``."""

    @abc.abstractmethod
    def key_at_rank(self, rank: float, lower: bool = True) -> int:
        """Key of the bucket containing the value of cumulative rank ``rank``.

        ``lower=True``: smallest key whose cumulative count exceeds ``rank``;
        ``lower=False``: smallest key whose cumulative count reaches
        ``rank + 1``.
        """

    @abc.abstractmethod
    def merge(self, store: "Store") -> None:
        """Fold another store's mass into this one (same-key addition)."""

    @abc.abstractmethod
    def copy(self) -> "Store":
        """Deep copy."""

    @property
    @abc.abstractmethod
    def is_empty(self) -> bool:
        ...


class DenseStore(Store):
    """Contiguous bins over ``[offset, offset + len(bins))``; grows on demand.

    Reference seam: ``ddsketch/store.py . DenseStore``.  Growth happens in
    ``CHUNK_SIZE`` steps; ``key_at_rank`` is a linear cumulative walk.

    Failure modes: ``merge`` of a non-dense store raises ``TypeError``;
    growth is unbounded by design (the collapsing subclasses bound it by
    folding overflow mass into the edge bins instead of failing), and
    ``key_at_rank`` on an empty store is undefined (guard on
    ``is_empty``).
    """

    def __init__(self, chunk_size: int = CHUNK_SIZE):
        self.chunk_size = chunk_size
        self.bins: list[float] = []
        self.count = 0.0
        self.min_key = math.inf
        self.max_key = -math.inf
        self.offset = 0

    def __repr__(self) -> str:
        return (
            f"{type(self).__name__}(count={self.count}, offset={self.offset},"
            f" bins={{{', '.join(f'{i + self.offset}: {b}' for i, b in enumerate(self.bins) if b > 0)}}})"
        )

    @property
    def is_empty(self) -> bool:
        return self.count == 0

    def keys(self) -> Iterator[int]:
        for i, b in enumerate(self.bins):
            if b > 0:
                yield i + self.offset

    def add(self, key: int, weight: float = 1.0) -> None:
        idx = self._get_index(key)
        self.bins[idx] += weight
        self.count += weight

    def _get_index(self, key: int) -> int:
        if key < self.min_key:
            self._extend_range(key)
        elif key > self.max_key:
            self._extend_range(key)
        return key - self.offset

    def _get_new_length(self, new_min_key: int, new_max_key: int) -> int:
        desired = new_max_key - new_min_key + 1
        return self.chunk_size * int(math.ceil(desired / self.chunk_size))

    def _extend_range(self, key: int, second_key: Optional[int] = None) -> None:
        second_key = key if second_key is None else second_key
        new_min_key = min(key, second_key, self.min_key)
        new_max_key = max(key, second_key, self.max_key)

        if self.is_empty and not self.bins:
            self.bins = [0.0] * self._get_new_length(new_min_key, new_max_key)
            self.offset = new_min_key
            self._adjust(new_min_key, new_max_key)
        elif new_min_key >= self.offset and new_max_key < self.offset + len(self.bins):
            self.min_key = min(self.min_key, new_min_key)
            self.max_key = max(self.max_key, new_max_key)
        else:
            new_length = self._get_new_length(new_min_key, new_max_key)
            if new_length > len(self.bins):
                self.bins.extend([0.0] * (new_length - len(self.bins)))
            self._adjust(new_min_key, new_max_key)

    def _adjust(self, new_min_key: int, new_max_key: int) -> None:
        """Recenter the physical array on the new key range (no collapsing)."""
        self._center_bins(new_min_key, new_max_key)
        self.min_key = min(self.min_key, new_min_key)
        self.max_key = max(self.max_key, new_max_key)

    def _shift_bins(self, shift: int) -> None:
        """Physically move bin contents by ``shift`` slots (offset -= shift)."""
        if shift > 0:
            self.bins = [0.0] * shift + self.bins[: len(self.bins) - shift]
        else:
            self.bins = self.bins[-shift:] + [0.0] * (-shift)
        self.offset -= shift

    def _center_bins(self, new_min_key: int, new_max_key: int) -> None:
        middle_key = new_min_key + (new_max_key - new_min_key + 1) // 2
        self._shift_bins(self.offset + len(self.bins) // 2 - middle_key)

    def key_at_rank(self, rank: float, lower: bool = True) -> int:
        running = 0.0
        for i, b in enumerate(self.bins):
            running += b
            if (lower and running > rank) or (not lower and running >= rank + 1):
                return i + self.offset
        return int(self.max_key)

    def merge(self, store: Store) -> None:
        if not isinstance(store, DenseStore):
            raise TypeError(f"Cannot merge {type(self).__name__} with {type(store).__name__}")
        if store.is_empty:
            return
        # The fast path (adopt the operand's bins wholesale) is only sound
        # when both stores share collapse semantics; otherwise an unbounded
        # store could inherit collapsed state, or a bounded one could exceed
        # its bin_limit.  Mixed types re-bin through add_raw, which clamps.
        if self.is_empty and type(store) is type(self) and (
            getattr(self, "bin_limit", None) == getattr(store, "bin_limit", None)
        ):
            self._copy_from(store)
            return
        self._extend_range(int(store.min_key), int(store.max_key))
        for i, b in enumerate(store.bins):
            if b > 0:
                self.add_raw(i + store.offset, b)

    def add_raw(self, key: int, weight: float) -> None:
        """Merge helper: same as add() (subclasses clamp here too)."""
        self.add(key, weight)

    def _copy_from(self, store: "DenseStore") -> None:
        self.bins = list(store.bins)
        self.offset = store.offset
        self.min_key = store.min_key
        self.max_key = store.max_key
        self.count = store.count

    def copy(self) -> "DenseStore":
        new = type(self).__new__(type(self))
        new.__dict__.update(self.__dict__)
        new.bins = list(self.bins)
        return new


class CollapsingLowestDenseStore(DenseStore):
    """DenseStore bounded by ``bin_limit``: keys below the representable floor
    collapse into the lowest bin (mass conserved, resolution lost at the low
    end).  Reference seam: ``ddsketch/store.py . CollapsingLowestDenseStore``.
    """

    def __init__(self, bin_limit: int, chunk_size: int = CHUNK_SIZE):
        super().__init__(chunk_size)
        self.bin_limit = bin_limit
        self.is_collapsed = False

    def _get_new_length(self, new_min_key: int, new_max_key: int) -> int:
        return min(super()._get_new_length(new_min_key, new_max_key), self.bin_limit)

    def _get_index(self, key: int) -> int:
        if key < self.min_key:
            if self.is_collapsed:
                return 0
            self._extend_range(key)
            if self.is_collapsed:
                return 0
        elif key > self.max_key:
            self._extend_range(key)
        return key - self.offset

    def _adjust(self, new_min_key: int, new_max_key: int) -> None:
        if new_max_key - new_min_key + 1 > len(self.bins):
            # Range exceeds capacity: pin to the top, collapse the bottom.
            new_min_key = new_max_key - len(self.bins) + 1
            if new_min_key >= self.max_key:
                # Everything currently stored collapses into the new floor bin.
                self.offset = new_min_key
                self.min_key = new_min_key
                self.bins = [0.0] * len(self.bins)
                self.bins[0] = self.count
            else:
                shift = self.offset - new_min_key
                if shift < 0:
                    collapsed = sum(self.bins[: -shift])
                    self.bins[: -shift] = [0.0] * (-shift)
                    self._shift_bins(shift)
                    self.bins[0] += collapsed
                else:
                    self._shift_bins(shift)
                self.min_key = new_min_key
            self.max_key = new_max_key
            self.is_collapsed = True
        else:
            self._center_bins(new_min_key, new_max_key)
            self.min_key = min(self.min_key, new_min_key)
            self.max_key = max(self.max_key, new_max_key)

    def _copy_from(self, store: DenseStore) -> None:
        super()._copy_from(store)
        if isinstance(store, CollapsingLowestDenseStore):
            self.is_collapsed = store.is_collapsed


class CollapsingHighestDenseStore(DenseStore):
    """Mirror image of CollapsingLowestDenseStore: overflow keys collapse into
    the highest bin.  Reference seam:
    ``ddsketch/store.py . CollapsingHighestDenseStore``.
    """

    def __init__(self, bin_limit: int, chunk_size: int = CHUNK_SIZE):
        super().__init__(chunk_size)
        self.bin_limit = bin_limit
        self.is_collapsed = False

    def _get_new_length(self, new_min_key: int, new_max_key: int) -> int:
        return min(super()._get_new_length(new_min_key, new_max_key), self.bin_limit)

    def _get_index(self, key: int) -> int:
        if key > self.max_key:
            if self.is_collapsed:
                return len(self.bins) - 1
            self._extend_range(key)
            if self.is_collapsed:
                return len(self.bins) - 1
        elif key < self.min_key:
            self._extend_range(key)
        return key - self.offset

    def _adjust(self, new_min_key: int, new_max_key: int) -> None:
        if new_max_key - new_min_key + 1 > len(self.bins):
            # Range exceeds capacity: pin to the bottom, collapse the top.
            new_max_key = new_min_key + len(self.bins) - 1
            if new_max_key <= self.min_key:
                self.offset = new_min_key
                self.min_key = new_min_key
                self.max_key = new_max_key
                self.bins = [0.0] * len(self.bins)
                self.bins[-1] = self.count
            else:
                shift = self.offset - new_min_key
                if shift > 0:
                    collapsed = sum(self.bins[len(self.bins) - shift :])
                    self.bins[len(self.bins) - shift :] = [0.0] * shift
                    self._shift_bins(shift)
                    self.bins[-1] += collapsed
                else:
                    self._shift_bins(shift)
                self.max_key = new_max_key
            self.min_key = new_min_key
            self.is_collapsed = True
        else:
            self._center_bins(new_min_key, new_max_key)
            self.min_key = min(self.min_key, new_min_key)
            self.max_key = max(self.max_key, new_max_key)

    def _copy_from(self, store: DenseStore) -> None:
        super()._copy_from(store)
        if isinstance(store, CollapsingHighestDenseStore):
            self.is_collapsed = store.is_collapsed
