"""Resilience layer: error taxonomy, degradation ladder, loss accounting.

The serving story (ROADMAP north star: heavy traffic, partial
infrastructure loss) needs every failure path to be a *degradation* path,
not a crash path.  DDSketch's full mergeability (PAPER.md) is what makes
that possible -- any subset of blobs/shards/partials is itself an exact
sketch of the mass it holds -- so the recovery primitives here are all
"keep the survivors, account for the rest":

* **Error taxonomy** (:class:`SketchError` and friends): one structured
  hierarchy replacing the ad-hoc ``ValueError``/``RuntimeError`` raises
  across the modules.  Every class keeps its legacy base (``ValueError``
  or ``RuntimeError``) so existing callers' ``except`` clauses -- and the
  pre-r7 test suite -- keep working unchanged.
* **Health registry** (:func:`record_downgrade` / :func:`health`): the
  process-wide ledger of every degradation any component took (engine
  ladder steps, native-tier loss, quarantined blobs, dead shards).  A
  downgrade is never silent: callers that survive a failure MUST record
  it here, and :func:`health` is the one snapshot an operator polls.
* **Reports** (:class:`QuarantineReport`, :class:`ShardLossReport`): the
  structured accounting objects the quarantine decode
  (``pb.wire.bytes_to_state(errors="quarantine")``) and the lost-shard
  fold (``parallel.DistributedDDSketch.merge_partial``) hand back.

Ladder semantics (docs/DESIGN.md section 8): the query engines degrade
``overlap -> tiles -> windowed -> wxla -> xla`` (each step drops to the
next-slower-but-simpler tier and is recorded); ingest degrades
``pallas -> xla``; the host tier degrades ``native -> python``.  Every
tier computes the same answer -- degradation costs latency, never
correctness.

This module sits near the bottom of the package: it imports only
:mod:`sketches_tpu.telemetry` (itself stdlib + the env registry), which
owns the process's wall clock (ledger timestamps) and mirrors every
downgrade event into the metrics layer when telemetry is armed.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from sketches_tpu import telemetry

__all__ = [
    "SketchError",
    "SketchValueError",
    "SpecError",
    "UnequalSketchParametersError",
    "WireDecodeError",
    "BlobTooLarge",
    "CheckpointCorrupt",
    "IntegrityError",
    "EngineUnavailable",
    "ShardLossError",
    "InjectedFault",
    "ServeOverload",
    "DeadlineExceeded",
    "ReplicaStale",
    "FabricUnavailable",
    "QuarantineRecord",
    "QuarantineReport",
    "ShardLossReport",
    "ReshardReport",
    "DowngradeEvent",
    "record_downgrade",
    "bump",
    "health",
    "reset",
    "QUERY_LADDER",
    "demote_query_tier",
]


# ---------------------------------------------------------------------------
# Error taxonomy
# ---------------------------------------------------------------------------


class SketchError(Exception):
    """Base of every structured sketches_tpu error.

    ``except SketchError`` catches everything this library raises on its
    own behalf (fault-injected failures included); backend exceptions
    (XLA compile errors, protobuf DecodeError) pass through untouched on
    the paths that do not explicitly ladder over them.
    """


class SketchValueError(SketchError, ValueError):
    """A caller handed the library an unusable value (bad weight, ragged
    batch width, refused wire bytes).  Subclasses ``ValueError`` so
    pre-taxonomy ``except ValueError`` call sites keep working."""


class SpecError(SketchValueError):
    """Invalid static configuration: sketch/spec constructor arguments,
    mesh axes, engine names."""


class UnequalSketchParametersError(SketchValueError):
    """Raised when merging sketches whose mappings (gamma/offset) differ.

    Lives here (taxonomy root) since r7; ``sketches_tpu.ddsketch``
    re-exports it, so the historical import path keeps working.
    """


class WireDecodeError(SketchValueError):
    """A wire blob failed the decode contract (structure, limits)."""


class BlobTooLarge(WireDecodeError):
    """Raised (or quarantined as ``over_limit``) when a wire blob exceeds
    the caller's ``max_blob_bytes`` admission cap."""


class CheckpointCorrupt(SketchError):
    """A checkpoint failed restore validation: truncated file, bad
    archive, checksum mismatch, or missing fields.  Deliberately NOT a
    ``ValueError``: corruption is an integrity failure, not a bad
    argument, and must not be swallowed by value-error handlers."""


class IntegrityError(SketchError):
    """Sketch state failed a self-verification: total-mass conservation,
    bin non-negativity, derived-counter agreement, or a cross-boundary
    fingerprint mismatch (``sketches_tpu.integrity``).  Like
    :class:`CheckpointCorrupt`, deliberately NOT a ``ValueError``:
    corruption is an integrity failure, not a bad argument, and must not
    be swallowed by value-error handlers.  Carries the
    :class:`~sketches_tpu.integrity.IntegrityReport` as ``.report`` when
    raised by :func:`sketches_tpu.integrity.verify`."""

    def __init__(self, message: str, report=None):
        super().__init__(message)
        self.report = report


class EngineUnavailable(SketchError, RuntimeError):
    """An execution engine cannot be used (native library failed to
    build/load after retries, Pallas tier lost mid-stream).  Subclasses
    ``RuntimeError`` for pre-taxonomy call sites."""


class ShardLossError(SketchError):
    """Raised on unrecoverable shard loss: no live shard remains to fold
    (partial loss degrades instead -- see ``ShardLossReport``)."""


class InjectedFault(SketchError):
    """The deterministic failure raised by an armed ``faults`` site."""


class ServeOverload(SketchError):
    """A serving request was shed at admission (``sketches_tpu.serve``):
    the global queue was at depth, the tenant was over quota, or an
    armed ``serve.queue_overflow`` fault forced the overflow path.
    Shedding is deliberate degradation, never silent: every shed bumps
    the ``serve.shed`` health counter and the declared telemetry
    metrics.  ``reason`` is the stable shed class
    (``queue_depth`` / ``tenant_quota`` / ``injected``)."""

    def __init__(self, message: str, reason: str = "", tenant: str = ""):
        super().__init__(message)
        self.reason = reason
        self.tenant = tenant


class DeadlineExceeded(SketchError):
    """A serving request's deadline budget was already spent before any
    dispatch could answer it (``sketches_tpu.serve``).  Raised at
    admission/flush time -- a request near (but not past) its deadline
    degrades to the cheapest engine tier instead of raising."""


class ReplicaStale(SketchError):
    """A serve-fabric read replica refused to answer
    (``sketches_tpu.fabric``): its content fingerprint no longer
    matches the primary's ledgered state (stale-WRONG -- the
    booby-trap), or its sync lag exceeds the tenant's declared
    staleness bound (stale-beyond-contract).  Refusal is loud and the
    read re-homes; a mismatched replica never serves.  ``reason`` is
    the stable refusal class (``fingerprint`` / ``staleness``)."""

    def __init__(self, message: str, reason: str = "", tenant: str = ""):
        super().__init__(message)
        self.reason = reason
        self.tenant = tenant


class FabricUnavailable(SketchError):
    """No serveable copy of a fabric tenant remains
    (``sketches_tpu.fabric``): the primary host is dead or partitioned
    and every replica either refused (:class:`ReplicaStale`) or lives
    on a dead/partitioned host.  Raised instead of serving a wrong or
    out-of-contract answer -- unavailability is declared, never
    improvised around."""


# ---------------------------------------------------------------------------
# Health registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DowngradeEvent:
    """One recorded degradation: ``component`` moved ``from_tier`` ->
    ``to_tier`` because ``reason``."""

    component: str
    from_tier: str
    to_tier: str
    reason: str
    time: float


_lock = threading.Lock()
_events: List[DowngradeEvent] = []
_tiers: Dict[str, str] = {}
_counters: Dict[str, float] = {}
_events_dropped = 0

#: Ledger ring bound (mirrors telemetry's 65k span ring): a long-lived
#: armed process cannot grow the downgrade ledger without bound.  Events
#: past the cap are dropped (newest first, like the span ring) and
#: counted in ``health()["downgrades_dropped"]``; the per-component
#: ``tiers`` map and the counters keep aggregating regardless.
_MAX_EVENTS = 65536


def record_downgrade(
    component: str, from_tier: str, to_tier: str, reason: str = ""
) -> DowngradeEvent:
    """Record one degradation step into the process-wide health ledger.

    Never fails the caller: a downgrade is already a failure being
    survived.  Ledger timestamps are operator-facing observability, not
    replay state (nothing branches on them); the wall clock lives in
    ``telemetry.wall_time`` -- the package's one clock boundary -- and
    armed telemetry mirrors the event as a ``resilience.downgrade``
    counter + trace instant so ledger and metrics snapshot agree.
    """
    ev = DowngradeEvent(
        component, from_tier, to_tier, str(reason)[:500],
        telemetry.wall_time(),
    )
    global _events_dropped
    with _lock:
        if len(_events) < _MAX_EVENTS:
            _events.append(ev)
        else:
            _events_dropped += 1
        _tiers[component] = to_tier
        _counters["downgrades"] = _counters.get("downgrades", 0) + 1
    if telemetry._ACTIVE:
        telemetry.event(
            "resilience.downgrade",
            component=component, from_tier=from_tier, to_tier=to_tier,
        )
    # The flight recorder's ladder-downgrade feed (one bool test when
    # disarmed; lazy import -- tracing imports this module's taxonomy).
    from sketches_tpu import tracing

    if tracing._ACTIVE:
        tracing.record_event(
            "resilience.downgrade", component=component,
            from_tier=from_tier, to_tier=to_tier, reason=str(reason)[:200],
        )
    return ev


def bump(name: str, n: float = 1) -> None:
    """Increment a named health counter (quarantined blobs, fired faults,
    dead shards, ...)."""
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def health() -> dict:
    """Snapshot of the resilience ledger.

    Returns ``{"tiers": {component: current tier}, "counters": {...},
    "downgrades": [event dicts, oldest first],
    "downgrades_dropped": n}``.  Empty maps mean no component has
    degraded -- the healthy steady state.  ``downgrades_dropped`` counts
    events past the fixed ring bound (oldest 65536 kept); the tiers map
    and counters aggregate every event regardless.  The snapshot is a
    deep copy; mutating it does not touch the ledger.
    """
    with _lock:
        return {
            "tiers": dict(_tiers),
            "counters": dict(_counters),
            "downgrades": [dataclasses.asdict(e) for e in _events],
            "downgrades_dropped": _events_dropped,
        }


def reset() -> None:
    """Clear the ledger (test isolation hook)."""
    global _events_dropped
    with _lock:
        _events.clear()
        _tiers.clear()
        _counters.clear()
        _events_dropped = 0


# ---------------------------------------------------------------------------
# Engine ladder bookkeeping
# ---------------------------------------------------------------------------

#: The query-engine degradation order, fastest first.  ``xla`` (the full
#: portable quantile) is the floor and may not fail over further.
QUERY_LADDER = ("overlap", "tiles", "windowed", "wxla", "xla")


def demote_query_tier(disabled: set, tier: str) -> Optional[str]:
    """Disable ``tier`` in a facade's ladder state -> the next tier label.

    Returns ``None`` when ``tier`` is the floor (nothing left to fall
    to -- the caller must re-raise).  A ``windowed`` failure disables the
    whole Pallas query family: overlap/tiles build on the same lowering
    machinery, so a windowed-tier lowering failure condemns them too.
    """
    if tier == "overlap":
        disabled.add("overlap")
        return "tiles"
    if tier == "tiles":
        disabled.add("tiles")
        return "windowed"
    if tier == "windowed":
        disabled.update(("overlap", "tiles", "windowed"))
        return "wxla"
    if tier == "wxla":
        disabled.add("wxla")
        return "xla"
    return None


# ---------------------------------------------------------------------------
# Quarantine accounting (bulk decode)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class QuarantineRecord:
    """One quarantined blob: its batch index, a stable reason ``kind``
    (``unparseable`` / ``mapping_mismatch`` / ``over_limit`` /
    ``invalid`` / ``error``), the exception class name, and its message."""

    index: int
    kind: str
    error: str
    message: str


@dataclasses.dataclass
class QuarantineReport:
    """Accounting for one quarantine-mode bulk decode.

    ``records`` lists every quarantined blob (index + structured
    reason), in batch order.  Quarantined streams decode as EMPTY rows
    (zero mass) in the returned state; every other stream decodes
    bit-identically to a clean decode of the same blob.
    """

    total: int
    records: List[QuarantineRecord] = dataclasses.field(default_factory=list)

    def add(self, index: int, kind: str, exc: BaseException) -> None:
        self.records.append(
            QuarantineRecord(index, kind, type(exc).__name__, str(exc)[:500])
        )

    @property
    def indices(self) -> List[int]:
        return [r.index for r in self.records]

    @property
    def counters(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for r in self.records:
            out[r.kind] = out.get(r.kind, 0) + 1
        return out

    @property
    def n_quarantined(self) -> int:
        return len(self.records)

    @property
    def n_ok(self) -> int:
        return self.total - len(self.records)

    def __bool__(self) -> bool:  # truthy iff anything was quarantined
        return bool(self.records)


# ---------------------------------------------------------------------------
# Shard-loss accounting (distributed fold)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ShardLossReport:
    """Accounting for a liveness-masked partial fold.

    The folded state is an EXACT sketch of the surviving shards' mass
    (mergeability: each partial is itself a sketch); this report says
    what was left behind.  ``dropped_count`` is per-stream mass lost
    with the dead shards -- derivable only while the dead partials are
    still readable (simulation / post-mortem); a fold taken after a real
    loss carries ``dropped_count=None`` and only the shard identities.
    """

    live: np.ndarray  # [K] bool
    surviving_count: np.ndarray  # [N]
    dropped_count: Optional[np.ndarray]  # [N], None if unknowable

    @property
    def dead_shards(self) -> List[int]:
        return [int(i) for i in np.nonzero(~self.live)[0]]

    @property
    def n_dead(self) -> int:
        return int((~self.live).sum())

    @property
    def dropped_fraction(self) -> Optional[np.ndarray]:
        """Per-stream fraction of total mass lost with the dead shards."""
        if self.dropped_count is None:
            return None
        total = self.surviving_count + self.dropped_count
        return self.dropped_count / np.maximum(total, 1.0)

    @property
    def total_dropped_fraction(self) -> Optional[float]:
        if self.dropped_count is None:
            return None
        total = float(self.surviving_count.sum() + self.dropped_count.sum())
        return float(self.dropped_count.sum()) / max(total, 1.0)


@dataclasses.dataclass
class ReshardReport:
    """Accounting for one elastic reshard (kill-and-regrow boundary).

    The regrown fleet holds EXACTLY the surviving mass: per-stream
    ``surviving_count`` must reappear bit-identically in the new fleet's
    fold (``exact``), and the mass lost with dead shards/hosts is
    itemized per stream in ``dropped_count`` -- nothing is lost
    silently.  ``fingerprint_pre``/``fingerprint_post`` carry the
    integrity layer's merge-additive content fingerprints across the
    boundary when it is armed (``None`` disarmed -- an absent proof,
    not a passed one); ``fingerprints_match`` is then the cross-boundary
    verdict.  A reshard that raises (torn, all-dead) produces NO report
    -- the original fleet is untouched.
    """

    live: np.ndarray  # [K] bool, over the OLD mesh's value shards
    from_devices: int
    to_devices: int
    surviving_count: np.ndarray  # [N]
    dropped_count: np.ndarray  # [N] mass lost with the dead shards
    exact: bool  # new fold count == surviving_count, bit-identical
    lost_hosts: Tuple[int, ...] = ()
    fingerprint_pre: Optional[np.ndarray] = None  # [N], armed only
    fingerprint_post: Optional[np.ndarray] = None  # [N], armed only

    @property
    def dead_shards(self) -> List[int]:
        return [int(i) for i in np.nonzero(~self.live)[0]]

    @property
    def n_dead(self) -> int:
        return int((~self.live).sum())

    @property
    def total_dropped(self) -> float:
        return float(self.dropped_count.sum())

    @property
    def total_dropped_fraction(self) -> float:
        total = float(self.surviving_count.sum() + self.dropped_count.sum())
        return self.total_dropped / max(total, 1.0)

    @property
    def fingerprints_match(self) -> Optional[bool]:
        """Cross-boundary fingerprint verdict (None when integrity was
        disarmed and no fingerprints were computed)."""
        if self.fingerprint_pre is None or self.fingerprint_post is None:
            return None
        return bool(
            np.allclose(
                self.fingerprint_post, self.fingerprint_pre,
                rtol=1e-5, atol=1e-3,
            )
        )
