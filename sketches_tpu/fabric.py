"""Sharded serve fabric: replicated, failover-safe multi-process serving.

One :class:`~sketches_tpu.serve.SketchServer` is one process -- one
process death takes every tenant down.  The fabric scales the serving
tier to a fleet of virtual hosts and survives host loss and partitions
with ZERO wrong answers, leaning on the property that makes DDSketch
replication sound: full mergeability.  A read replica is just a fold of
the primary's state shipped over the existing wire seam, so a replica
read carries the same alpha contract as a primary read -- the only new
failure mode is *staleness*, and staleness is declared, bounded, and
fingerprint-verified rather than silent.

Placement
    ``tenant -> hosts`` by rendezvous (highest-random-weight) hashing:
    every host is scored ``crc32(tenant "@host" i)`` and the tenant's
    copies live on the top-``replication`` scorers (first = primary).
    Deterministic (any process computes the same placement from the
    tenant name alone), and minimal-movement: killing a host re-homes
    only that host's tenants, onto the next-ranked survivors.

Replica sync protocol
    ``sync()`` serializes the primary's state through
    ``backends.wirefmt`` (the same seam checkpoints and cross-host
    shipping use), decodes it into the replica host's facade, and
    LEDGERS the sync point: the replica's content fingerprint
    (:func:`sketches_tpu.integrity.fingerprint` -- topology-free,
    merge-additive), its per-stream synced mass, the primary's write
    version, and the serving-clock sync time.  A decode failure or a
    fingerprint that disagrees with the primary aborts the sync and
    keeps the previous (still-consistent) replica.

Staleness contract
    Each tenant declares ``staleness_s``.  A replica serves ONLY when
    (a) its live fingerprint matches its ledgered sync fingerprint
    (anything else is stale-WRONG: the replica refuses loudly with
    :class:`~sketches_tpu.resilience.ReplicaStale` and the read
    re-homes -- a mismatched replica never serves), and (b) its sync
    lag is within the declared bound.  Partitioned primaries degrade
    reads to declared-staleness replica reads instead of errors;
    writes to a partitioned primary refuse loudly
    (:class:`~sketches_tpu.resilience.FabricUnavailable`) rather than
    fork the stream.

Failover accounting invariant
    When a host dies, each of its primary tenants re-homes onto the
    best surviving replica, and the mass ledger closes EXACTLY::

        dropped_count == expected_count - promoted_replica_synced_count

    per stream (unit weights make counts integer-valued; the equality
    is ``==``, never approximate).  The dropped mass is itemized in the
    tenant's ledger and the promoted replica's fingerprint is verified
    against its sync ledger before promotion -- a stale-wrong replica
    is skipped (and recorded), never promoted.  Every failover,
    handoff, and heal decision lands in the flight recorder with its
    triggering snapshot (:func:`sketches_tpu.tracing.dump_forensics`).

Cache discipline
    The fabric keeps a small result cache keyed on ``(tenant, content
    fingerprint digest, qs)`` with a payload checksum, exactly like the
    serving tier's.  Fingerprints are topology-free, so cache entries
    survive clean replica handoffs and failovers whose content is
    unchanged -- no recompute storm on rebalance.

Kill switch: ``SKETCHES_TPU_FABRIC=0`` refuses fabric construction
loudly (``SpecError``); plain single-process serving is unaffected.
Fault sites: ``fabric.replica_stale`` (silent replica corruption, the
fingerprint lane's adversary), ``mesh.partition_heal`` (a heal torn
between reconciliation and the un-partition commit; the host must stay
partitioned, never half-healed), plus ``reshard.torn`` at the handoff
seam and ``wire.blob`` on sync payloads.
"""

from __future__ import annotations

import binascii
import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sketches_tpu import faults, integrity, resilience, telemetry, tracing
from sketches_tpu.analysis import registry
from sketches_tpu.resilience import (
    FabricUnavailable,
    ReplicaStale,
    SketchValueError,
    SpecError,
)
from sketches_tpu.serve import ServeConfig, SketchServer

__all__ = [
    "FabricConfig",
    "FabricResult",
    "FailoverReport",
    "HandoffReport",
    "ServeFabric",
    "ReplicaStale",
    "FabricUnavailable",
    "placement",
]


def _rendezvous_score(tenant: str, host: int) -> int:
    return binascii.crc32(f"{tenant}@host{host}".encode()) & 0xFFFFFFFF


def placement(tenant: str, n_hosts: int, replication: int) -> Tuple[int, ...]:
    """Deterministic tenant placement -> hosts ranked by rendezvous
    score (first = primary, rest = replicas).

    Highest-random-weight hashing over the host ids: any process with
    the tenant name and the host count computes the same ranking, and
    removing a host re-ranks ONLY that host's tenants (minimal
    movement -- the property that makes failover re-homing cheap).
    ``replication`` caps the returned prefix at ``n_hosts`` copies.
    Raises ``SketchValueError`` for a non-positive fleet or factor.
    """
    if n_hosts <= 0:
        raise SketchValueError("a fabric needs at least one host")
    if replication <= 0:
        raise SketchValueError("replication must be positive")
    ranked = sorted(
        range(n_hosts), key=lambda h: (-_rendezvous_score(tenant, h), h)
    )
    return tuple(ranked[: min(replication, n_hosts)])


@dataclasses.dataclass(frozen=True)
class FabricConfig:
    """Fleet shape + serving knobs.

    ``replication`` counts TOTAL copies (primary included), clipped to
    the fleet size.  ``staleness_s`` is the default per-tenant bound a
    replica read may lag the primary's ledgered state;
    ``add_tenant(..., staleness_s=)`` overrides per tenant.
    ``serve_config`` seeds every virtual host's ``SketchServer``.
    ``cache_capacity`` sizes the fabric-level fingerprint-keyed result
    cache (0 disables it).  Non-positive host/replication counts and
    negative bounds raise ``SketchValueError`` at construction --
    a fleet shape that cannot serve is refused, never clamped.
    """

    n_hosts: int = 2
    replication: int = 2
    staleness_s: float = 30.0
    cache_capacity: int = 128
    serve_config: Optional[ServeConfig] = None

    def __post_init__(self):
        if self.n_hosts <= 0:
            raise SketchValueError("a fabric needs at least one host")
        if self.replication <= 0:
            raise SketchValueError("replication must be positive")
        if self.staleness_s < 0:
            raise SketchValueError("staleness_s must be non-negative")
        if self.cache_capacity < 0:
            raise SketchValueError("cache_capacity must be non-negative")


@dataclasses.dataclass
class FabricResult:
    """One answered fabric read: per-stream values for the requested
    quantiles, which ``host`` answered and in what ``role``
    (``primary`` / ``replica`` / ``cache``), the observed replica
    ``staleness_s`` (0 for primary answers), and the robustness
    accounting -- ``degraded`` (a partition forced a declared-staleness
    replica read), ``hedged`` (the answer came from a cross-host hedge
    after the primary dispatch failed)."""

    values: np.ndarray
    tier: str
    role: str
    host: int
    staleness_s: float = 0.0
    degraded: bool = False
    hedged: bool = False

    @property
    def cached(self) -> bool:
        return self.role == "cache"


@dataclasses.dataclass(frozen=True)
class FailoverReport:
    """One tenant re-homed after a host loss: the exact per-stream mass
    the dead primary held beyond the promoted replica's ledgered sync
    (``dropped_count``; ``exact`` is the ledger-closure check), plus
    any replicas that were SKIPPED because their fingerprint mismatched
    their sync ledger (the booby-trap firing during failover)."""

    tenant: str
    from_host: int
    to_host: int
    dropped_count: np.ndarray
    exact: bool
    fingerprint_hex: str
    refused_replicas: Tuple[int, ...] = ()

    @property
    def dropped_total(self) -> float:
        return float(self.dropped_count.sum())


@dataclasses.dataclass(frozen=True)
class HandoffReport:
    """One replica moved between hosts over the wire seam: the content
    fingerprint is topology-free, so ``fingerprint_hex`` is identical
    before and after a clean handoff and every fabric cache entry keyed
    on it survives (``cache_preserved``)."""

    tenant: str
    from_host: int
    to_host: int
    fingerprint_hex: str
    cache_preserved: bool


class _ReplicaLedger:
    """The sync-point record for one (tenant, host) replica: what the
    replica MUST still fingerprint to (digest), the exact per-stream
    mass it held at sync, and when/at which write version it synced."""

    __slots__ = ("digest", "synced_count", "synced_version", "synced_at")

    def __init__(self, digest: bytes, synced_count: np.ndarray,
                 synced_version: int, synced_at: float):
        self.digest = digest
        self.synced_count = synced_count
        self.synced_version = synced_version
        self.synced_at = synced_at


class _Host:
    """One virtual serving process: its own SketchServer, liveness, and
    the replica ledgers for the copies it holds."""

    __slots__ = ("server", "alive", "partitioned", "replicas")

    def __init__(self, server: SketchServer):
        self.server = server
        self.alive = True
        self.partitioned = False
        self.replicas: Dict[str, _ReplicaLedger] = {}


class _TenantMeta:
    """Fabric-side tenant bookkeeping: placement, the exact mass
    ledger, and the memoized primary fingerprint."""

    __slots__ = (
        "name", "spec", "n_streams", "staleness_s", "hosts", "version",
        "expected_count", "dropped_count", "fp_memo",
    )

    def __init__(self, name: str, spec, n_streams: int, staleness_s: float,
                 hosts: Tuple[int, ...]):
        self.name = name
        self.spec = spec
        self.n_streams = n_streams
        self.staleness_s = staleness_s
        self.hosts = list(hosts)
        self.version = 0
        self.expected_count = np.zeros(n_streams, np.float64)
        self.dropped_count = np.zeros(n_streams, np.float64)
        self.fp_memo: Optional[Tuple[int, np.ndarray, bytes]] = None


def _payload_checksum(digest: bytes, values: np.ndarray) -> int:
    payload = digest + np.ascontiguousarray(values).tobytes()
    return binascii.crc32(payload) & 0xFFFFFFFF


class _CacheEntry:
    __slots__ = ("values", "checksum")

    def __init__(self, digest: bytes, values: np.ndarray):
        self.values = values
        self.checksum = _payload_checksum(digest, values)


class ServeFabric:
    """The sharded serving fleet (module docstring for the placement /
    sync / staleness / failover contracts).

    Writes go through :meth:`ingest` (routed to the tenant's primary
    host); reads through :meth:`quantile` (primary first, cross-host
    hedge onto a fingerprint-verified replica when the primary dispatch
    fails, declared-staleness replica reads when the primary is
    partitioned).  Operational verbs: :meth:`sync` (replica refresh),
    :meth:`kill_host` (failover drill), :meth:`partition_host` /
    :meth:`heal_partition`, :meth:`handoff_replica` (rebalancing).
    Unknown tenants raise ``SpecError``; a fabric with
    ``SKETCHES_TPU_FABRIC=0`` refuses construction loudly.  Thread-safe
    under one lock (the fleet is virtual; dispatches serialize).
    """

    def __init__(
        self,
        config: Optional[FabricConfig] = None,
        *,
        clock: Optional[Callable[[], float]] = None,
    ):
        if not registry.enabled(registry.FABRIC):
            raise SpecError(
                "the sharded serve fabric is disabled"
                " (SKETCHES_TPU_FABRIC=0): refusing to construct a"
                " ServeFabric -- unset the kill switch or serve from a"
                " single-process SketchServer"
            )
        self.config = config or FabricConfig()
        self._clock = clock if clock is not None else telemetry.clock
        self._hosts = [
            _Host(SketchServer(self.config.serve_config, clock=self._clock))
            for _ in range(self.config.n_hosts)
        ]
        self._tenants: Dict[str, _TenantMeta] = {}
        self._cache: Dict[Tuple[str, bytes, Tuple[float, ...]], _CacheEntry] = {}
        self._cache_order: List[Tuple[str, bytes, Tuple[float, ...]]] = []
        self._lock = threading.RLock()
        self._stats: Dict[str, float] = {
            "requests": 0, "primary_reads": 0, "replica_reads": 0,
            "degraded_reads": 0, "cache_hits": 0, "hedges": 0,
            "replica_syncs": 0, "sync_aborts": 0, "failovers": 0,
            "handoffs": 0, "stale_refusals": 0, "heals": 0,
        }

    # -- placement --------------------------------------------------------

    @property
    def n_hosts(self) -> int:
        with self._lock:
            return len(self._hosts)

    def placement(self, name: str) -> Tuple[int, ...]:
        """The tenant's CURRENT copy set (primary first).  Reflects
        failovers and handoffs, unlike the pure :func:`placement`
        function it started from."""
        with self._lock:
            return tuple(self._meta(name).hosts)

    def live_hosts(self) -> Tuple[int, ...]:
        """Hosts that are alive AND reachable (not partitioned)."""
        with self._lock:
            return tuple(
                i for i, h in enumerate(self._hosts)
                if h.alive and not h.partitioned
            )

    def _meta(self, name: str) -> _TenantMeta:
        m = self._tenants.get(name)
        if m is None:
            raise SpecError(f"unknown fabric tenant {name!r}")
        return m

    # -- tenancy ----------------------------------------------------------

    def add_tenant(
        self, name: str, n_streams: int, *,
        staleness_s: Optional[float] = None, **kwargs,
    ):
        """Place tenant ``name`` on its rendezvous hosts and provision
        its primary + replicas -> the primary facade.

        ``kwargs`` pass through to the primary host's
        ``SketchServer.add_tenant`` (``spec=``, ``relative_accuracy=``,
        ...); windowed and mesh-sharded tenants are refused for now
        (replication ships dense folds over the wire seam).  Placement
        skips dead/partitioned hosts at registration.  Re-registering
        raises ``SpecError``.
        """
        if kwargs.get("window") is not None or kwargs.get("mesh") is not None:
            raise SpecError(
                "fabric tenants replicate dense folds: windowed and"
                " mesh-sharded tenants are not replicable yet --"
                " register them on a single SketchServer"
            )
        with self._lock:
            if name in self._tenants:
                raise SpecError(f"fabric tenant {name!r} already registered")
            ranked = placement(name, self.n_hosts, self.config.replication)
            usable = [
                h for h in ranked
                if self._hosts[h].alive and not self._hosts[h].partitioned
            ]
            if not usable:
                raise FabricUnavailable(
                    f"no live host to place tenant {name!r} on"
                )
            primary = usable[0]
            facade = self._hosts[primary].server.add_tenant(
                name, n_streams, **kwargs
            )
            bound = (
                self.config.staleness_s
                if staleness_s is None else float(staleness_s)
            )
            if bound < 0:
                raise SketchValueError("staleness_s must be non-negative")
            meta = _TenantMeta(name, facade.spec, n_streams, bound, usable)
            self._tenants[name] = meta
            for h in usable[1:]:
                self._provision_replica(meta, h)
            if tracing._ACTIVE:
                tracing.record_event(
                    "fabric.place", tenant=name, primary=primary,
                    replicas=str(tuple(usable[1:])),
                )
            return facade

    def _register_or_reuse(self, host: int, meta: _TenantMeta):
        """The replica facade on ``host`` (registering it on that
        host's server the first time; hosts that held this tenant in a
        past epoch reuse the registration -- tenant state is then
        REPLACED through the sync path, never merged)."""
        server = self._hosts[host].server
        try:
            return server.tenant(meta.name)
        except SpecError:
            return server.add_tenant(meta.name, meta.n_streams, spec=meta.spec)

    def _provision_replica(self, meta: _TenantMeta, host: int) -> None:
        self._register_or_reuse(host, meta)
        self._sync_replica(meta, host)

    # -- fingerprints -----------------------------------------------------

    def _primary_fingerprint(self, meta: _TenantMeta) -> Tuple[np.ndarray, bytes]:
        """The primary's ledgered content fingerprint (memoized per
        write version -- the state every replica must converge to)."""
        memo = meta.fp_memo
        if memo is not None and memo[0] == meta.version:
            return memo[1], memo[2]
        facade = self._hosts[meta.hosts[0]].server.tenant(meta.name)
        fp = integrity.fingerprint(meta.spec, facade.state)
        digest = np.ascontiguousarray(fp).tobytes()
        meta.fp_memo = (meta.version, fp, digest)
        return fp, digest

    @staticmethod
    def _live_digest(meta: _TenantMeta, facade) -> bytes:
        fp = integrity.fingerprint(meta.spec, facade.state)
        return np.ascontiguousarray(fp).tobytes()

    # -- write path -------------------------------------------------------

    def ingest(self, name: str, values, weights=None) -> None:
        """Ingest a batch into the tenant's PRIMARY (write path).

        Updates the exact mass ledger from the finite values in the
        batch.  A partitioned primary refuses the write loudly
        (``FabricUnavailable``) -- the stream must not fork; a dead
        primary means a failover is pending and also refuses.
        """
        with self._lock:
            meta = self._meta(name)
            primary = self._hosts[meta.hosts[0]]
            if not primary.alive:
                raise FabricUnavailable(
                    f"tenant {name!r}: primary host {meta.hosts[0]} is"
                    " dead and not yet re-homed; run kill_host/failover"
                )
            if primary.partitioned:
                raise FabricUnavailable(
                    f"tenant {name!r}: primary host {meta.hosts[0]} is"
                    " partitioned; writes refuse rather than fork the"
                    " stream (reads degrade to declared-staleness"
                    " replicas)"
                )
            primary.server.ingest(name, values, weights)
            vals = np.asarray(values, np.float64)
            finite = np.isfinite(vals)
            if weights is None:
                added = finite.sum(axis=-1).astype(np.float64)
            else:
                w = np.broadcast_to(
                    np.asarray(weights, np.float64), vals.shape
                )
                added = np.where(finite, w, 0.0).sum(axis=-1)
            meta.expected_count = meta.expected_count + np.broadcast_to(
                added, meta.expected_count.shape
            )
            meta.version += 1
            meta.fp_memo = None

    # -- replica sync -----------------------------------------------------

    def sync(self, name: Optional[str] = None) -> int:
        """Refresh replicas from their primaries over the wire seam ->
        the number of replicas synced (one tenant, or every tenant with
        ``name=None``).  Dead/partitioned endpoints are skipped; an
        aborted sync (corrupt payload, fingerprint disagreement) keeps
        the previous consistent replica and is counted, never silent."""
        with self._lock:
            names = [name] if name is not None else list(self._tenants)
            n = 0
            for nm in names:
                meta = self._meta(nm)
                primary = self._hosts[meta.hosts[0]]
                if not primary.alive or primary.partitioned:
                    continue
                for h in meta.hosts[1:]:
                    host = self._hosts[h]
                    if host.alive and not host.partitioned:
                        if self._sync_replica(meta, h):
                            n += 1
            return n

    def _sync_replica(self, meta: _TenantMeta, host_id: int) -> bool:
        """Ship the primary's fold to one replica and ledger the sync
        point.  Returns False (replica untouched) on an aborted sync."""
        from sketches_tpu.backends.wirefmt import (
            payload_from_bytes,
            payload_to_bytes,
        )

        primary_facade = self._hosts[meta.hosts[0]].server.tenant(meta.name)
        blobs = payload_to_bytes(meta.spec, primary_facade.state)
        if faults._ACTIVE:
            blobs = [
                faults.inject(faults.WIRE_BLOB, b, index=i)
                for i, b in enumerate(blobs)
            ]
        try:
            state = payload_from_bytes(meta.spec, blobs)
        except resilience.WireDecodeError:
            self._stats["sync_aborts"] += 1
            resilience.bump("fabric.sync_aborts")
            if tracing._ACTIVE:
                tracing.record_event(
                    "fabric.sync_abort", tenant=meta.name, host=host_id,
                    reason="wire_decode",
                )
            return False
        # The wire decode NORMALIZES the window (canonical key_offset),
        # so the primary's fingerprint and the replica's agree within
        # float-summation rounding, not bitwise; the LEDGERED digest is
        # the replica's own canonical fingerprint -- decode is a fixed
        # point, so every later gate (serve-time verify, handoff,
        # promotion) compares it bit-exactly.
        fp_want, _ = self._primary_fingerprint(meta)
        fp_got = integrity.fingerprint(meta.spec, state)
        if not ServeFabric._fp_close(fp_got, fp_want):
            # The wire round-trip did not reproduce the primary's
            # content: never ledger a sync point the replica cannot
            # fingerprint back to.
            self._stats["sync_aborts"] += 1
            resilience.bump("fabric.sync_aborts")
            if tracing._ACTIVE:
                tracing.record_event(
                    "fabric.sync_abort", tenant=meta.name, host=host_id,
                    reason="fingerprint",
                )
            return False
        got_digest = np.ascontiguousarray(fp_got).tobytes()
        host = self._hosts[host_id]
        facade = self._register_or_reuse(host_id, meta)
        facade.state = state
        host.server.invalidate(meta.name)
        host.replicas[meta.name] = _ReplicaLedger(
            got_digest, meta.expected_count.copy(), meta.version,
            float(self._clock()),
        )
        self._stats["replica_syncs"] += 1
        if telemetry._ACTIVE:
            telemetry.counter_inc("fabric.replica_syncs")
        if tracing._ACTIVE:
            tracing.record_event(
                "fabric.replica_sync", tenant=meta.name, host=host_id,
                version=meta.version, digest=got_digest.hex()[:16],
            )
        return True

    @staticmethod
    def _digest_of(spec, state) -> bytes:
        fp = integrity.fingerprint(spec, state)
        return np.ascontiguousarray(fp).tobytes()

    @staticmethod
    def _fp_close(got: np.ndarray, want: np.ndarray) -> bool:
        """Cross-representation fingerprint agreement: the integrity
        layer's tolerance (the window-normalizing wire decode reorders
        the float summation; content equality survives, bit equality
        does not)."""
        tol = integrity._FP_ATOL + integrity._FP_RTOL * np.abs(want)
        return bool(np.all(np.abs(got - want) <= tol))

    # -- fabric cache -----------------------------------------------------

    def _cache_get(
        self, name: str, digest: bytes, qs: Tuple[float, ...]
    ) -> Optional[np.ndarray]:
        if self.config.cache_capacity <= 0:
            return None
        entry = self._cache.get((name, digest, qs))
        if entry is None:
            return None
        if entry.checksum != _payload_checksum(digest, entry.values):
            # Bit-rotted entry: quarantine, recompute downstream.
            self._cache.pop((name, digest, qs), None)
            return None
        self._stats["cache_hits"] += 1
        return entry.values

    def _cache_put(
        self, name: str, digest: bytes, qs: Tuple[float, ...],
        values: np.ndarray,
    ) -> None:
        if self.config.cache_capacity <= 0:
            return
        key = (name, digest, qs)
        if key not in self._cache:
            self._cache_order.append(key)
            while len(self._cache_order) > self.config.cache_capacity:
                evicted = self._cache_order.pop(0)
                self._cache.pop(evicted, None)
        self._cache[key] = _CacheEntry(digest, values)

    # -- read path --------------------------------------------------------

    def quantile(
        self, name: str, quantiles: Sequence[float],
        deadline_s: Optional[float] = None,
    ) -> FabricResult:
        """The fabric read: primary first, cross-host hedge onto a
        fingerprint-verified replica when the primary dispatch fails,
        declared-staleness replica reads when the primary is
        partitioned or dead -> a :class:`FabricResult`.

        Admission refusals (``ServeOverload`` / ``DeadlineExceeded``)
        propagate -- shedding is a declared answer, not a failover
        trigger.  A replica whose fingerprint mismatches its sync
        ledger NEVER serves (:class:`ReplicaStale`, re-homed); when no
        copy can serve, :class:`FabricUnavailable` (or the last
        ``ReplicaStale`` when refusals were the only obstacle).
        """
        qs = tuple(sorted(float(q) for q in quantiles))
        if not qs:
            raise SketchValueError("a request needs at least one quantile")
        with self._lock:
            meta = self._meta(name)
            self._stats["requests"] += 1
            primary_id = meta.hosts[0]
            primary = self._hosts[primary_id]
            if primary.alive and not primary.partitioned:
                _, digest = self._primary_fingerprint(meta)
                cached = self._cache_get(name, digest, qs)
                if cached is not None:
                    return FabricResult(
                        values=cached, tier="cache", role="cache",
                        host=primary_id,
                    )
                try:
                    res = primary.server.query(name, qs, deadline_s)
                except (resilience.ServeOverload,
                        resilience.DeadlineExceeded):
                    raise
                except Exception as e:
                    # Cross-host hedge: the primary's whole engine
                    # ladder (serve's own hedge included) failed --
                    # re-issue against a verified replica.
                    self._stats["hedges"] += 1
                    if telemetry._ACTIVE:
                        telemetry.counter_inc("fabric.hedge_cross_host")
                    if tracing._ACTIVE:
                        tracing.record_event(
                            "fabric.hedge", tenant=name,
                            primary=primary_id, error=repr(e),
                        )
                    out = self._read_replicas(meta, qs, degraded=False)
                    return dataclasses.replace(out, hedged=True)
                self._stats["primary_reads"] += 1
                self._cache_put(name, digest, qs, res.values)
                return FabricResult(
                    values=res.values, tier=res.tier, role="primary",
                    host=primary_id, hedged=res.hedged,
                )
            # Primary unreachable: a dead primary should have been
            # re-homed by kill_host; re-home lazily if it was not.  A
            # partitioned primary degrades to declared-staleness
            # replica reads.
            if not primary.alive:
                self._failover_locked(meta)
                return self.quantile(name, qs, deadline_s)
            return self._read_replicas(meta, qs, degraded=True)

    def _read_replicas(
        self, meta: _TenantMeta, qs: Tuple[float, ...], *, degraded: bool
    ) -> FabricResult:
        """Serve from the first replica that passes the fingerprint and
        staleness gates, re-homing past refusals."""
        last_refusal: Optional[ReplicaStale] = None
        for host_id in meta.hosts[1:]:
            host = self._hosts[host_id]
            if not host.alive or host.partitioned:
                continue
            ledger = host.replicas.get(meta.name)
            if ledger is None:
                continue
            facade = host.server.tenant(meta.name)
            if faults._ACTIVE:
                flips = faults.replica_stale_flips(
                    meta.n_streams, meta.spec.n_bins
                )
                if flips:
                    # The adversary silently corrupts the stored
                    # replica -- no version bump, no announcement; only
                    # the fingerprint gate below can catch it.
                    facade.state = faults.apply_state_bitflips(
                        facade.state, flips
                    )
            live = self._live_digest(meta, facade)
            if live != ledger.digest:
                self._stats["stale_refusals"] += 1
                resilience.bump("fabric.replica_stale_refusals")
                if tracing._ACTIVE:
                    tracing.record_event(
                        "fabric.replica_refused", tenant=meta.name,
                        host=host_id, reason="fingerprint",
                    )
                last_refusal = ReplicaStale(
                    f"replica of {meta.name!r} on host {host_id} does"
                    " not fingerprint to its ledgered sync state:"
                    " refusing to serve (re-homing the read)",
                    reason="fingerprint", tenant=meta.name,
                )
                continue
            staleness = max(0.0, float(self._clock()) - ledger.synced_at)
            if staleness > meta.staleness_s:
                self._stats["stale_refusals"] += 1
                resilience.bump("fabric.replica_stale_refusals")
                if tracing._ACTIVE:
                    tracing.record_event(
                        "fabric.replica_refused", tenant=meta.name,
                        host=host_id, reason="staleness",
                        staleness_s=staleness,
                    )
                last_refusal = ReplicaStale(
                    f"replica of {meta.name!r} on host {host_id} is"
                    f" {staleness:.3f}s stale, beyond the declared"
                    f" {meta.staleness_s:.3f}s bound: refusing to serve",
                    reason="staleness", tenant=meta.name,
                )
                continue
            cached = self._cache_get(meta.name, ledger.digest, qs)
            if cached is not None:
                values = cached
                tier = "cache"
            else:
                res = host.server.query(meta.name, qs)
                values = res.values
                tier = res.tier
                self._cache_put(meta.name, ledger.digest, qs, values)
            self._stats["replica_reads"] += 1
            if degraded:
                self._stats["degraded_reads"] += 1
            _trc = tracing.new_trace() if tracing._ACTIVE else None
            if telemetry._ACTIVE:
                telemetry.observe(
                    "fabric.staleness_s", staleness, trace=_trc,
                    tenant=meta.name,
                )
            if tracing._ACTIVE:
                tracing.record_event(
                    "fabric.replica_read", ctx=_trc, tenant=meta.name,
                    host=host_id, staleness_s=staleness,
                    degraded=degraded,
                )
            return FabricResult(
                values=values, tier=tier, role="replica", host=host_id,
                staleness_s=staleness, degraded=degraded,
            )
        if last_refusal is not None:
            raise last_refusal
        raise FabricUnavailable(
            f"tenant {meta.name!r}: no live copy can serve (primary"
            " unreachable, no fingerprint-verified replica in bound)"
        )

    # -- failover ---------------------------------------------------------

    def kill_host(self, host_id: int) -> List[FailoverReport]:
        """Kill a virtual host -> the failover reports for every tenant
        it was primary for.

        Each such tenant re-homes onto its best surviving
        fingerprint-verified replica with the dropped mass itemized
        exactly in its ledger; the host's REPLICA copies are simply
        dropped (their primaries re-provision on the next sync).  Every
        decision lands in the flight recorder with its triggering
        snapshot.
        """
        with self._lock:
            if not (0 <= host_id < self.n_hosts):
                raise SketchValueError(f"no host {host_id}")
            host = self._hosts[host_id]
            if not host.alive:
                return []
            host.alive = False
            host.partitioned = False
            host.replicas.clear()
            reports = []
            for meta in self._tenants.values():
                if host_id in meta.hosts[1:]:
                    meta.hosts.remove(host_id)
                    self._restore_replication(meta)
            for meta in list(self._tenants.values()):
                if meta.hosts and meta.hosts[0] == host_id:
                    reports.append(self._failover_locked(meta))
            return reports

    def _failover_locked(self, meta: _TenantMeta) -> FailoverReport:
        """Promote the best verified replica of a dead-primary tenant;
        close the mass ledger exactly."""
        dead = meta.hosts[0]
        refused: List[int] = []
        chosen: Optional[int] = None
        for host_id in meta.hosts[1:]:
            host = self._hosts[host_id]
            if not host.alive or host.partitioned:
                continue
            ledger = host.replicas.get(meta.name)
            if ledger is None:
                continue
            facade = host.server.tenant(meta.name)
            if self._live_digest(meta, facade) != ledger.digest:
                # Stale-WRONG replica: never promoted, loudly recorded.
                refused.append(host_id)
                self._stats["stale_refusals"] += 1
                resilience.bump("fabric.replica_stale_refusals")
                continue
            chosen = host_id
            break
        if chosen is None:
            raise FabricUnavailable(
                f"tenant {meta.name!r}: primary host {dead} died and no"
                " fingerprint-verified replica survives"
                + (f" (refused: {refused})" if refused else "")
            )
        ledger = self._hosts[chosen].replicas.pop(meta.name)
        dropped = meta.expected_count - ledger.synced_count
        exact = bool(np.all(dropped >= 0))
        meta.dropped_count = meta.dropped_count + dropped
        meta.expected_count = ledger.synced_count.copy()
        meta.hosts.remove(chosen)
        if dead in meta.hosts:
            meta.hosts.remove(dead)
        meta.hosts.insert(0, chosen)
        meta.version += 1
        meta.fp_memo = None
        self._stats["failovers"] += 1
        _trc = tracing.new_trace() if tracing._ACTIVE else None
        if telemetry._ACTIVE:
            telemetry.counter_inc("fabric.failovers")
        if tracing._ACTIVE:
            tracing.record_event(
                "fabric.failover", ctx=_trc, tenant=meta.name,
                from_host=dead, to_host=chosen,
                dropped=float(dropped.sum()),
                refused=str(tuple(refused)),
            )
            tracing.dump_forensics(
                "fabric.failover", trace=_trc,
                detail={
                    "tenant": meta.name, "from_host": dead,
                    "to_host": chosen,
                    "dropped_total": float(dropped.sum()),
                    "fingerprint": ledger.digest.hex()[:16],
                    "refused_replicas": list(refused),
                },
            )
        self._restore_replication(meta)
        return FailoverReport(
            tenant=meta.name, from_host=dead, to_host=chosen,
            dropped_count=dropped, exact=exact,
            fingerprint_hex=ledger.digest.hex()[:16],
            refused_replicas=tuple(refused),
        )

    def _restore_replication(self, meta: _TenantMeta) -> None:
        """Re-provision replicas on the next-ranked live hosts until
        the tenant is back at its replication factor (or the fleet runs
        out of usable hosts)."""
        want = min(self.config.replication, self.n_hosts)
        ranked = placement(meta.name, self.n_hosts, self.n_hosts)
        for h in ranked:
            if len(meta.hosts) >= want:
                break
            host = self._hosts[h]
            if h in meta.hosts or not host.alive or host.partitioned:
                continue
            meta.hosts.append(h)
            primary = self._hosts[meta.hosts[0]]
            if primary.alive and not primary.partitioned:
                self._provision_replica(meta, h)

    def revive_host(self, host_id: int) -> int:
        """A replacement process rejoins the fleet under a dead host's
        id -> the number of tenants that regained a copy.

        The revived host starts with NO serving role: any facades left
        from its previous life are ledger-less (the fabric never serves
        a replica without a sync ledger), and every under-replicated
        tenant re-provisions onto it through the normal sync path --
        the replacement holds only fingerprint-verified state.
        """
        with self._lock:
            if not (0 <= host_id < self.n_hosts):
                raise SketchValueError(f"no host {host_id}")
            host = self._hosts[host_id]
            if host.alive:
                return 0
            host.alive = True
            host.partitioned = False
            host.replicas.clear()
            n = 0
            for meta in self._tenants.values():
                before = len(meta.hosts)
                self._restore_replication(meta)
                if len(meta.hosts) > before:
                    n += 1
            if tracing._ACTIVE:
                tracing.record_event(
                    "fabric.revive", host=host_id, reprovisioned=n,
                )
            return n

    # -- partitions -------------------------------------------------------

    def partition_host(self, host_id: int) -> None:
        """Mark a host unreachable: its primaries degrade reads to
        declared-staleness replicas (writes refuse), its replicas stop
        serving and syncing.  State is untouched -- a partition is a
        connectivity fact, not a loss."""
        with self._lock:
            if not (0 <= host_id < self.n_hosts):
                raise SketchValueError(f"no host {host_id}")
            host = self._hosts[host_id]
            if not host.alive:
                raise SpecError(f"host {host_id} is dead, not partitioned")
            host.partitioned = True
            if tracing._ACTIVE:
                tracing.record_event("fabric.partition", host=host_id)

    def heal_partition(self, host_id: int) -> int:
        """Heal a partition: reconcile the host's replicas from their
        primaries, then commit the un-partition -> replicas refreshed.

        ATOMIC against the ``mesh.partition_heal`` fault: the
        reconciliation plan is computed first, the injection seam fires
        before any commit, and a torn heal leaves the host partitioned
        (degraded but consistent), never half-healed.
        """
        with self._lock:
            if not (0 <= host_id < self.n_hosts):
                raise SketchValueError(f"no host {host_id}")
            host = self._hosts[host_id]
            if not host.alive:
                raise SpecError(f"host {host_id} is dead; heal cannot revive")
            if not host.partitioned:
                return 0
            # Reconciliation plan: which replicas on this host need a
            # refresh from a reachable primary.
            plan = [
                meta for meta in self._tenants.values()
                if host_id in meta.hosts[1:]
                and self._hosts[meta.hosts[0]].alive
                and not self._hosts[meta.hosts[0]].partitioned
            ]
            if faults._ACTIVE:
                faults.inject(faults.MESH_PARTITION_HEAL)
            host.partitioned = False
            n = 0
            for meta in plan:
                if self._sync_replica(meta, host_id):
                    n += 1
            self._stats["heals"] += 1
            _trc = tracing.new_trace() if tracing._ACTIVE else None
            if tracing._ACTIVE:
                tracing.record_event(
                    "fabric.heal", ctx=_trc, host=host_id, resynced=n,
                )
                tracing.dump_forensics(
                    "fabric.heal", trace=_trc,
                    detail={"host": host_id, "resynced": n},
                )
            return n

    # -- rebalancing ------------------------------------------------------

    def handoff_replica(
        self, name: str, from_host: int, to_host: int
    ) -> HandoffReport:
        """Move a replica between hosts over the wire seam -> the
        :class:`HandoffReport`.

        The content fingerprint is topology-free, so a clean handoff
        preserves the sync ledger AND every fabric cache entry keyed on
        the fingerprint -- no recompute storm.  ATOMIC against
        ``reshard.torn`` at the handoff seam: a torn handoff raises and
        leaves the source replica intact and serving.  A payload that
        does not fingerprint back to the ledger aborts loudly
        (``ReplicaStale``) -- a corrupt copy is never installed.
        """
        from sketches_tpu.backends.wirefmt import (
            payload_from_bytes,
            payload_to_bytes,
        )

        with self._lock:
            meta = self._meta(name)
            if from_host not in meta.hosts[1:]:
                raise SpecError(
                    f"host {from_host} holds no replica of {name!r}"
                )
            if to_host in meta.hosts:
                raise SpecError(
                    f"host {to_host} already holds a copy of {name!r}"
                )
            target = self._hosts[to_host]
            if not target.alive or target.partitioned:
                raise FabricUnavailable(
                    f"host {to_host} is not usable as a handoff target"
                )
            source = self._hosts[from_host]
            ledger = source.replicas.get(name)
            if ledger is None:
                raise SpecError(
                    f"host {from_host} has no sync ledger for {name!r}"
                )
            facade = source.server.tenant(name)
            blobs = payload_to_bytes(meta.spec, facade.state)
            if faults._ACTIVE:
                # The handoff is a mini-reshard: the replica moves
                # hosts.  Torn here = raise with the source intact.
                faults.inject(faults.RESHARD_TORN)
            state = payload_from_bytes(meta.spec, blobs)
            if ServeFabric._digest_of(meta.spec, state) != ledger.digest:
                raise ReplicaStale(
                    f"handoff of {name!r} {from_host}->{to_host}: the"
                    " shipped payload does not fingerprint to the sync"
                    " ledger; aborting (source replica intact)",
                    reason="fingerprint", tenant=name,
                )
            new_facade = self._register_or_reuse(to_host, meta)
            new_facade.state = state
            target.server.invalidate(name)
            target.replicas[name] = _ReplicaLedger(
                ledger.digest, ledger.synced_count.copy(),
                ledger.synced_version, ledger.synced_at,
            )
            source.replicas.pop(name, None)
            meta.hosts[meta.hosts.index(from_host)] = to_host
            self._stats["handoffs"] += 1
            _trc = tracing.new_trace() if tracing._ACTIVE else None
            if tracing._ACTIVE:
                tracing.record_event(
                    "fabric.handoff", ctx=_trc, tenant=name,
                    from_host=from_host, to_host=to_host,
                    digest=ledger.digest.hex()[:16],
                )
                tracing.dump_forensics(
                    "fabric.handoff", trace=_trc,
                    detail={
                        "tenant": name, "from_host": from_host,
                        "to_host": to_host,
                        "fingerprint": ledger.digest.hex()[:16],
                    },
                )
            return HandoffReport(
                tenant=name, from_host=from_host, to_host=to_host,
                fingerprint_hex=ledger.digest.hex()[:16],
                cache_preserved=True,
            )

    def reshard_tenant(self, name: str, *args, **kwargs):
        """Pass-through to the primary host's
        ``SketchServer.reshard_tenant`` (mesh-sharded primaries only;
        fabric tenants are dense today, so this raises ``SpecError``
        until distributed tenants replicate)."""
        with self._lock:
            meta = self._meta(name)
            server = self._hosts[meta.hosts[0]].server
        return server.reshard_tenant(name, *args, **kwargs)

    # -- introspection ----------------------------------------------------

    def ledger(self, name: str) -> Dict[str, Any]:
        """The tenant's exact mass ledger: per-stream expected (live)
        and dropped (itemized at failovers) counts, plus the primary's
        current content fingerprint digest."""
        with self._lock:
            meta = self._meta(name)
            out = {
                "expected_count": meta.expected_count.copy(),
                "dropped_count": meta.dropped_count.copy(),
                "expected_total": float(meta.expected_count.sum()),
                "dropped_total": float(meta.dropped_count.sum()),
                "staleness_s": meta.staleness_s,
                "hosts": tuple(meta.hosts),
            }
            primary = self._hosts[meta.hosts[0]]
            if primary.alive and not primary.partitioned:
                _, digest = self._primary_fingerprint(meta)
                out["fingerprint"] = digest.hex()[:16]
            return out

    def stats(self) -> Dict[str, float]:
        """Always-on fabric counters (a copy) plus fleet liveness."""
        with self._lock:
            out = dict(self._stats)
            out["hosts"] = self.n_hosts
            out["live_hosts"] = len(
                [h for h in self._hosts if h.alive and not h.partitioned]
            )
            out["tenants"] = len(self._tenants)
            out["cache_entries"] = len(self._cache)
            return out

    def host_server(self, host_id: int) -> SketchServer:
        """The virtual host's underlying server (drills and tests)."""
        with self._lock:
            if not (0 <= host_id < self.n_hosts):
                raise SketchValueError(f"no host {host_id}")
            return self._hosts[host_id].server
