"""Checkpoint / resume for device-tier sketch batches.

The reference's only durable format is the protobuf round-trip (SURVEY.md
section 5, checkpoint row); that stays the cross-language edge
(``sketches_tpu.pb``).  Bulk checkpoints of a ``[n_streams, n_bins]`` batch
go through this module instead: one ``device_get`` into a compressed npz of
the raw state arrays plus the spec, and ``device_put`` back on restore --
sketch state is one dense pytree, so checkpoint/resume is exactly an array
save/load, no orchestration needed.

Durability contract (r7):

* **Atomic writes.**  ``save_state`` serializes to memory, writes a
  same-directory temp file, fsyncs, and ``os.replace``s it into place --
  a crash mid-write leaves the previous checkpoint intact, never a torn
  file at ``path``.
* **Validated restores.**  The npz carries a content checksum (sha256
  over the spec json + every state array's name/dtype/shape/bytes).
  ``restore_state`` turns ANY restore failure -- truncated or
  corrupted archive, checksum mismatch, missing fields -- into a
  :class:`~sketches_tpu.resilience.CheckpointCorrupt` with the path and
  cause, never a bare numpy/zipfile stack trace.  Pre-r7 checkpoints
  (no checksum member) still restore; they just skip the content check.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import os
from typing import Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from sketches_tpu import faults, integrity, telemetry
from sketches_tpu.batched import BatchedDDSketch, SketchSpec, SketchState
from sketches_tpu.resilience import CheckpointCorrupt

__all__ = ["save", "restore", "restore_distributed", "save_state", "restore_state"]

_FIELDS = [f.name for f in dataclasses.fields(SketchState)]

#: Moment-backend state fields (``backends.moment.MomentState``);
#: imported lazily at save/restore so the checkpoint module stays
#: light for dense-only users.
_MOMENT_FIELDS = [
    "count", "zero_count", "neg_count", "sum", "min", "max", "powers",
    "log_powers",
]


def _state_arrays(spec: SketchSpec, state) -> dict:
    """Flatten any backend state to the npz array dict (the save-side
    twin of :func:`_arrays_to_backend_state`); raises ``SpecError``
    when the state type disagrees with ``spec.backend``."""
    from sketches_tpu.resilience import SpecError

    if spec.backend == "uniform_collapse":
        if not hasattr(state, "base"):
            raise SpecError(
                "uniform_collapse checkpoint needs an AdaptiveState;"
                f" got {type(state).__name__}"
            )
        arrays = {
            name: np.asarray(jax.device_get(getattr(state.base, name)))
            for name in _FIELDS
        }
        arrays["level"] = np.asarray(jax.device_get(state.level))
        return arrays
    if spec.backend == "moment":
        if not hasattr(state, "powers"):
            raise SpecError(
                "moment checkpoint needs a MomentState;"
                f" got {type(state).__name__}"
            )
        return {
            name: np.asarray(jax.device_get(getattr(state, name)))
            for name in _MOMENT_FIELDS
        }
    return {
        name: np.asarray(jax.device_get(getattr(state, name)))
        for name in _FIELDS
    }


def _arrays_to_backend_state(spec: SketchSpec, arrays: dict):
    """npz arrays -> the spec's backend state type (restore-side twin
    of :func:`_state_arrays`); a missing backend-specific member raises
    through the caller's ``CheckpointCorrupt`` wrapper."""
    if spec.backend == "uniform_collapse":
        from sketches_tpu.backends.uniform import AdaptiveState

        level = arrays.pop("level")
        return AdaptiveState(
            base=SketchState(**arrays), level=jnp.asarray(level, jnp.int32)
        )
    if spec.backend == "moment":
        from sketches_tpu.backends.moment import MomentState

        return MomentState(**arrays)
    return SketchState(**arrays)


def _digest(spec_json: str, arrays: dict) -> str:
    """Content checksum over the spec + every array's identity and bytes."""
    h = hashlib.sha256()
    h.update(spec_json.encode())
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def save_state(path: str, spec: SketchSpec, state: SketchState) -> None:
    """Write spec + state to ``path`` (npz; compressed, checksummed,
    atomically renamed into place)."""
    _t0 = telemetry.clock() if telemetry._ACTIVE else None
    if integrity._ACTIVE:
        # Guarded seam: refuse to persist an already-corrupted state
        # (raise/quarantine per the armed mode).
        integrity.verify_state(spec, state, seam="checkpoint.save")
    arrays = _state_arrays(spec, state)
    spec_json = json.dumps(
        {
            "relative_accuracy": spec.relative_accuracy,
            "mapping_name": spec.mapping_name,
            "n_bins": spec.n_bins,
            "key_offset": spec.key_offset,
            "dtype": jnp.dtype(spec.dtype).name,
            "bin_dtype": jnp.dtype(spec.bin_dtype).name,
            "backend": spec.backend,
            "collapse_threshold": spec.collapse_threshold,
            "max_collapses": spec.max_collapses,
            "n_moments": spec.n_moments,
        }
    )
    # Serialize to memory first: the bytes hit disk in one write, so the
    # only torn-write window left is the filesystem's own, which the
    # tmp+rename below closes.  (Write through a file object: np.savez on
    # a bare path silently appends '.npz', which would break the
    # save()/restore() round-trip for any other suffix.)
    extra = {}
    if integrity._ACTIVE:
        # Per-stream content fingerprint rides along so an armed restore
        # can verify the state across the save->restore boundary even on
        # pre-checksum readers (sha256 covers bytes; this covers
        # content).  ``integrity.fingerprint`` dispatches per backend
        # state type (dense / adaptive / moment).
        extra["__fingerprint__"] = integrity.fingerprint(spec, state)
    buf = io.BytesIO()
    np.savez_compressed(
        buf,
        __spec__=np.frombuffer(spec_json.encode(), np.uint8),
        __checksum__=np.frombuffer(_digest(spec_json, arrays).encode(), np.uint8),
        **extra,
        **arrays,
    )
    data = buf.getvalue()
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        if faults._ACTIVE:
            # "truncate" simulates a torn write reaching the final path;
            # "raise" simulates a crash before the rename (the previous
            # checkpoint must survive either way).
            data = faults.inject(faults.CHECKPOINT_WRITE, payload=data)
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        if _t0 is not None:
            telemetry.finish_span("checkpoint.save_s", _t0)
            telemetry.gauge_set("checkpoint.bytes", float(len(data)))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def restore_state(path: str) -> Tuple[SketchSpec, SketchState]:
    """Load (spec, state) previously written by ``save_state``.

    Raises :class:`CheckpointCorrupt` on any integrity failure (torn
    file, bad archive, checksum mismatch, missing members); a missing
    file stays ``FileNotFoundError``.
    """
    _t0 = telemetry.clock() if telemetry._ACTIVE else None
    try:
        spec, state, stored_fp = _restore_state_inner(path)
    except (FileNotFoundError, CheckpointCorrupt):
        raise
    except Exception as e:
        raise CheckpointCorrupt(
            f"checkpoint {path!r} failed to restore"
            f" ({type(e).__name__}: {e})"
        ) from e
    if integrity._ACTIVE:
        # Guarded seam: invariant-check the restored state, and when the
        # archive carries a content fingerprint (armed save), verify it
        # across the save->restore boundary (IntegrityError/quarantine
        # per the armed mode; distinct from CheckpointCorrupt, which
        # covers the archive's own validation above).
        integrity.verify_restore(
            spec, state, stored_fp, seam="checkpoint.restore"
        )
    if _t0 is not None:
        telemetry.finish_span("checkpoint.restore_s", _t0)
    return spec, state


def _restore_state_inner(path: str):
    with np.load(path) as data:
        stored_fp = (
            np.asarray(data["__fingerprint__"])
            if "__fingerprint__" in data.files
            else None
        )
        meta_json = bytes(data["__spec__"]).decode()
        meta = json.loads(meta_json)
        spec = SketchSpec(
            relative_accuracy=meta["relative_accuracy"],
            mapping_name=meta["mapping_name"],
            n_bins=meta["n_bins"],
            key_offset=meta["key_offset"],
            dtype=jnp.dtype(meta["dtype"]),
            # Pre-r3 checkpoints carry no bin_dtype: bins followed dtype.
            bin_dtype=jnp.dtype(meta.get("bin_dtype", meta["dtype"])),
            # Pre-r15 checkpoints carry no backend: every state was dense.
            backend=meta.get("backend", "dense"),
            collapse_threshold=meta.get("collapse_threshold", 0.01),
            max_collapses=meta.get("max_collapses", 10),
            n_moments=meta.get("n_moments", 12),
        )
        if spec.backend == "moment":
            fields = list(_MOMENT_FIELDS)
        elif spec.backend == "uniform_collapse":
            fields = _FIELDS + ["level"]
        else:
            fields = list(_FIELDS)
        if "__checksum__" in data.files:
            stored = bytes(data["__checksum__"]).decode()
            arrays_np = {
                name: np.asarray(data[name])
                for name in fields
                if name in data.files
            }
            got = _digest(meta_json, arrays_np)
            if got != stored:
                raise CheckpointCorrupt(
                    f"checkpoint {path!r} checksum mismatch"
                    f" (stored {stored[:12]}..., recomputed {got[:12]}...):"
                    " content corrupted after write"
                )
        arrays = {
            name: jnp.asarray(data[name]) for name in fields if name in data
        }
        if spec.backend != "dense":
            missing = [n for n in fields if n not in arrays]
            if missing:
                raise CheckpointCorrupt(
                    f"checkpoint {path!r} ({spec.backend} backend) is"
                    f" missing state members {missing}"
                )
            state = _arrays_to_backend_state(spec, arrays)
            return spec, state, stored_fp
        # Pre-adaptive-window checkpoints (round <= 2) carry no per-stream
        # offsets: every stream was on the spec default.
        if "key_offset" not in arrays:
            arrays["key_offset"] = jnp.full(
                arrays["count"].shape, spec.key_offset, dtype=jnp.int32
            )
        # Pre-occupied-bounds / pre-tile-summary checkpoints: derive the
        # missing arrays from the bins (host-side, one pass; exact).
        bp = bn = None
        if "pos_lo" not in arrays or "tile_sums" not in arrays:
            # Materialize each compressed array once (npz re-decompresses
            # on every access).
            bp = np.asarray(data["bins_pos"])
            bn = np.asarray(data["bins_neg"])
        if "pos_lo" not in arrays:
            from sketches_tpu.batched import occupied_bounds_np

            for name, bins in (("pos", bp), ("neg", bn)):
                lo, hi = occupied_bounds_np(bins)
                arrays[f"{name}_lo"] = jnp.asarray(lo)
                arrays[f"{name}_hi"] = jnp.asarray(hi)
            arrays["neg_total"] = jnp.asarray(
                bn.sum(axis=-1).astype(bn.dtype)
            )
        if "tile_sums" not in arrays:  # r <= 3 checkpoints
            from sketches_tpu.batched import tile_sums_np

            arrays["tile_sums"] = jnp.asarray(
                tile_sums_np(bp, bn).astype(bp.dtype)
            )
        state = SketchState(**arrays)
    return spec, state, stored_fp


def save(
    path: str,
    sketch: Union[BatchedDDSketch, "DistributedDDSketch"],  # noqa: F821
    partials: bool = False,
) -> None:
    """Checkpoint a batched (or distributed -- folded first) sketch facade.

    ``partials=True`` (distributed facades only; ``SpecError``
    otherwise) saves the STACKED ``[K, n_streams, ...]`` partials pytree
    instead of the fold -- the elastic-resume format:
    ``restore_distributed(..., live_mask=...)`` can then drop dead
    shards at restore time with exact per-shard accounting, which a
    folded checkpoint cannot (the shards are already summed).
    """
    from sketches_tpu.parallel import DistributedDDSketch

    if isinstance(sketch, DistributedDDSketch):
        if partials:
            save_state(path, sketch.spec, sketch.partials)
        else:
            save_state(path, sketch.spec, sketch.merged_state())
    else:
        if partials:
            from sketches_tpu.resilience import SpecError

            raise SpecError(
                "partials=True needs a DistributedDDSketch (a batched"
                " facade has no shard axis)"
            )
        save_state(path, sketch.spec, sketch.state)


def restore(path: str, engine: str = "auto"):
    """Resume a checkpoint as the facade matching its backend.

    Dense checkpoints restore a ``BatchedDDSketch`` (engine re-selected
    here); ``uniform_collapse``/``moment`` checkpoints restore their
    backend facades with levels/moments intact.  Corrupt archives raise
    ``CheckpointCorrupt`` via :func:`restore_state`.
    """
    spec, state = restore_state(path)
    if spec.backend != "dense":
        from sketches_tpu.backends import facade_for

        return facade_for(
            state.n_streams, spec=spec, state=state, engine=engine
        )
    return BatchedDDSketch(
        state.n_streams, spec=spec, state=state, engine=engine
    )


def restore_distributed(
    path: str,
    mesh=None,
    value_axis="values",
    stream_axis=None,
    engine: str = "auto",
    live_mask=None,
    n_hosts=None,
):
    """Resume a checkpoint as a mesh-sharded ``DistributedDDSketch``.

    The saved state is the FOLDED batch (``save`` folds partials before
    writing); ``DistributedDDSketch.from_merged_state`` loads it into
    value-shard 0's partial (the other shards hold the fold's
    identities), so the psum fold reproduces the saved totals exactly and
    subsequent ingest spreads new mass across shards as usual.  The
    mesh/axes may differ -- in SIZE too -- from the mesh the checkpoint
    was written under (the wire carries no topology; state is
    topology-free by design): this is the elastic resume path, and with
    the integrity layer armed the checkpoint's embedded fingerprint is
    re-verified on the restored state before the new fleet folds it.

    A ``save(..., partials=True)`` checkpoint restores the stacked
    partials instead; ``live_mask`` (a ``[K]`` bool) then drops dead
    shards at restore time with their mass accounted
    (``resilience.health()``), and a mask over a folded checkpoint
    raises ``SketchValueError``.  A torn or corrupted file raises
    ``CheckpointCorrupt`` -- an interrupted reshard can never silently
    lose mass, because the previous checkpoint is still intact
    (atomic writes) and a damaged one refuses to load.
    """
    from sketches_tpu.parallel import DistributedDDSketch

    spec, state = restore_state(path)
    return DistributedDDSketch.from_merged_state(
        state,
        spec,
        mesh=mesh,
        value_axis=value_axis,
        stream_axis=stream_axis,
        engine=engine,
        live_mask=live_mask,
        n_hosts=n_hosts,
    )
