"""Checkpoint / resume for device-tier sketch batches.

The reference's only durable format is the protobuf round-trip (SURVEY.md
section 5, checkpoint row); that stays the cross-language edge
(``sketches_tpu.pb``).  Bulk checkpoints of a ``[n_streams, n_bins]`` batch
go through this module instead: one ``device_get`` into a compressed npz of
the raw state arrays plus the spec, and ``device_put`` back on restore --
sketch state is one dense pytree, so checkpoint/resume is exactly an array
save/load, no orchestration needed.

Durability contract (r7):

* **Atomic writes.**  ``save_state`` serializes to memory, writes a
  same-directory temp file, fsyncs, and ``os.replace``s it into place --
  a crash mid-write leaves the previous checkpoint intact, never a torn
  file at ``path``.
* **Validated restores.**  The npz carries a content checksum (sha256
  over the spec json + every state array's name/dtype/shape/bytes).
  ``restore_state`` turns ANY restore failure -- truncated or
  corrupted archive, checksum mismatch, missing fields -- into a
  :class:`~sketches_tpu.resilience.CheckpointCorrupt` with the path and
  cause, never a bare numpy/zipfile stack trace.  Pre-r7 checkpoints
  (no checksum member) still restore; they just skip the content check.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import os
from typing import Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from sketches_tpu import faults, integrity, telemetry
from sketches_tpu.batched import BatchedDDSketch, SketchSpec, SketchState
from sketches_tpu.resilience import CheckpointCorrupt

__all__ = [
    "save", "restore", "restore_distributed", "save_state",
    "restore_state", "save_windowed", "restore_windowed",
]

_FIELDS = [f.name for f in dataclasses.fields(SketchState)]

#: Moment-backend state fields (``backends.moment.MomentState``);
#: imported lazily at save/restore so the checkpoint module stays
#: light for dense-only users.
_MOMENT_FIELDS = [
    "count", "zero_count", "neg_count", "sum", "min", "max", "powers",
    "log_powers",
]


def _state_arrays(spec: SketchSpec, state) -> dict:
    """Flatten any backend state to the npz array dict (the save-side
    twin of :func:`_arrays_to_backend_state`); raises ``SpecError``
    when the state type disagrees with ``spec.backend``."""
    from sketches_tpu.resilience import SpecError

    if spec.backend == "uniform_collapse":
        if not hasattr(state, "base"):
            raise SpecError(
                "uniform_collapse checkpoint needs an AdaptiveState;"
                f" got {type(state).__name__}"
            )
        arrays = {
            name: np.asarray(jax.device_get(getattr(state.base, name)))
            for name in _FIELDS
        }
        arrays["level"] = np.asarray(jax.device_get(state.level))
        return arrays
    if spec.backend == "moment":
        if not hasattr(state, "powers"):
            raise SpecError(
                "moment checkpoint needs a MomentState;"
                f" got {type(state).__name__}"
            )
        return {
            name: np.asarray(jax.device_get(getattr(state, name)))
            for name in _MOMENT_FIELDS
        }
    return {
        name: np.asarray(jax.device_get(getattr(state, name)))
        for name in _FIELDS
    }


def _arrays_to_backend_state(spec: SketchSpec, arrays: dict):
    """npz arrays -> the spec's backend state type (restore-side twin
    of :func:`_state_arrays`); a missing backend-specific member raises
    through the caller's ``CheckpointCorrupt`` wrapper."""
    if spec.backend == "uniform_collapse":
        from sketches_tpu.backends.uniform import AdaptiveState

        level = arrays.pop("level")
        return AdaptiveState(
            base=SketchState(**arrays), level=jnp.asarray(level, jnp.int32)
        )
    if spec.backend == "moment":
        from sketches_tpu.backends.moment import MomentState

        return MomentState(**arrays)
    return SketchState(**arrays)


def _spec_json(spec: SketchSpec) -> str:
    """The spec's canonical checkpoint-metadata JSON (shared by the
    batched and windowed checkpoint formats); never raises on a valid
    spec."""
    return json.dumps(
        {
            "relative_accuracy": spec.relative_accuracy,
            "mapping_name": spec.mapping_name,
            "n_bins": spec.n_bins,
            "key_offset": spec.key_offset,
            "dtype": jnp.dtype(spec.dtype).name,
            "bin_dtype": jnp.dtype(spec.bin_dtype).name,
            "backend": spec.backend,
            "collapse_threshold": spec.collapse_threshold,
            "max_collapses": spec.max_collapses,
            "n_moments": spec.n_moments,
        }
    )


def _spec_from_meta(meta: dict) -> SketchSpec:
    """Rebuild a spec from checkpoint metadata (the restore-side twin
    of :func:`_spec_json`; missing pre-round fields take their
    historical defaults).  Invalid field values raise ``SpecError``
    through the ``SketchSpec`` constructor."""
    return SketchSpec(
        relative_accuracy=meta["relative_accuracy"],
        mapping_name=meta["mapping_name"],
        n_bins=meta["n_bins"],
        key_offset=meta["key_offset"],
        dtype=jnp.dtype(meta["dtype"]),
        # Pre-r3 checkpoints carry no bin_dtype: bins followed dtype.
        bin_dtype=jnp.dtype(meta.get("bin_dtype", meta["dtype"])),
        # Pre-r15 checkpoints carry no backend: every state was dense.
        backend=meta.get("backend", "dense"),
        collapse_threshold=meta.get("collapse_threshold", 0.01),
        max_collapses=meta.get("max_collapses", 10),
        n_moments=meta.get("n_moments", 12),
    )


def _digest(spec_json: str, arrays: dict) -> str:
    """Content checksum over the spec + every array's identity and bytes."""
    h = hashlib.sha256()
    h.update(spec_json.encode())
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def save_state(path: str, spec: SketchSpec, state: SketchState) -> None:
    """Write spec + state to ``path`` (npz; compressed, checksummed,
    atomically renamed into place)."""
    _t0 = telemetry.clock() if telemetry._ACTIVE else None
    if integrity._ACTIVE:
        # Guarded seam: refuse to persist an already-corrupted state
        # (raise/quarantine per the armed mode).
        integrity.verify_state(spec, state, seam="checkpoint.save")
    arrays = _state_arrays(spec, state)
    spec_json = _spec_json(spec)
    # Serialize to memory first: the bytes hit disk in one write, so the
    # only torn-write window left is the filesystem's own, which the
    # tmp+rename below closes.  (Write through a file object: np.savez on
    # a bare path silently appends '.npz', which would break the
    # save()/restore() round-trip for any other suffix.)
    extra = {}
    if integrity._ACTIVE:
        # Per-stream content fingerprint rides along so an armed restore
        # can verify the state across the save->restore boundary even on
        # pre-checksum readers (sha256 covers bytes; this covers
        # content).  ``integrity.fingerprint`` dispatches per backend
        # state type (dense / adaptive / moment).
        extra["__fingerprint__"] = integrity.fingerprint(spec, state)
    buf = io.BytesIO()
    np.savez_compressed(
        buf,
        __spec__=np.frombuffer(spec_json.encode(), np.uint8),
        __checksum__=np.frombuffer(_digest(spec_json, arrays).encode(), np.uint8),
        **extra,
        **arrays,
    )
    data = buf.getvalue()
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        if faults._ACTIVE:
            # "truncate" simulates a torn write reaching the final path;
            # "raise" simulates a crash before the rename (the previous
            # checkpoint must survive either way).
            data = faults.inject(faults.CHECKPOINT_WRITE, payload=data)
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        if _t0 is not None:
            telemetry.finish_span("checkpoint.save_s", _t0)
            telemetry.gauge_set("checkpoint.bytes", float(len(data)))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def restore_state(path: str) -> Tuple[SketchSpec, SketchState]:
    """Load (spec, state) previously written by ``save_state``.

    Raises :class:`CheckpointCorrupt` on any integrity failure (torn
    file, bad archive, checksum mismatch, missing members); a missing
    file stays ``FileNotFoundError``.
    """
    _t0 = telemetry.clock() if telemetry._ACTIVE else None
    try:
        spec, state, stored_fp = _restore_state_inner(path)
    except (FileNotFoundError, CheckpointCorrupt):
        raise
    except Exception as e:
        raise CheckpointCorrupt(
            f"checkpoint {path!r} failed to restore"
            f" ({type(e).__name__}: {e})"
        ) from e
    if integrity._ACTIVE:
        # Guarded seam: invariant-check the restored state, and when the
        # archive carries a content fingerprint (armed save), verify it
        # across the save->restore boundary (IntegrityError/quarantine
        # per the armed mode; distinct from CheckpointCorrupt, which
        # covers the archive's own validation above).
        integrity.verify_restore(
            spec, state, stored_fp, seam="checkpoint.restore"
        )
    if _t0 is not None:
        telemetry.finish_span("checkpoint.restore_s", _t0)
    return spec, state


def _restore_state_inner(path: str):
    with np.load(path) as data:
        stored_fp = (
            np.asarray(data["__fingerprint__"])
            if "__fingerprint__" in data.files
            else None
        )
        meta_json = bytes(data["__spec__"]).decode()
        meta = json.loads(meta_json)
        spec = _spec_from_meta(meta)
        if spec.backend == "moment":
            fields = list(_MOMENT_FIELDS)
        elif spec.backend == "uniform_collapse":
            fields = _FIELDS + ["level"]
        else:
            fields = list(_FIELDS)
        if "__checksum__" in data.files:
            stored = bytes(data["__checksum__"]).decode()
            arrays_np = {
                name: np.asarray(data[name])
                for name in fields
                if name in data.files
            }
            got = _digest(meta_json, arrays_np)
            if got != stored:
                raise CheckpointCorrupt(
                    f"checkpoint {path!r} checksum mismatch"
                    f" (stored {stored[:12]}..., recomputed {got[:12]}...):"
                    " content corrupted after write"
                )
        arrays = {
            name: jnp.asarray(data[name]) for name in fields if name in data
        }
        if spec.backend != "dense":
            missing = [n for n in fields if n not in arrays]
            if missing:
                raise CheckpointCorrupt(
                    f"checkpoint {path!r} ({spec.backend} backend) is"
                    f" missing state members {missing}"
                )
            state = _arrays_to_backend_state(spec, arrays)
            return spec, state, stored_fp
        # Pre-adaptive-window checkpoints (round <= 2) carry no per-stream
        # offsets: every stream was on the spec default.
        if "key_offset" not in arrays:
            arrays["key_offset"] = jnp.full(
                arrays["count"].shape, spec.key_offset, dtype=jnp.int32
            )
        # Pre-occupied-bounds / pre-tile-summary checkpoints: derive the
        # missing arrays from the bins (host-side, one pass; exact).
        bp = bn = None
        if "pos_lo" not in arrays or "tile_sums" not in arrays:
            # Materialize each compressed array once (npz re-decompresses
            # on every access).
            bp = np.asarray(data["bins_pos"])
            bn = np.asarray(data["bins_neg"])
        if "pos_lo" not in arrays:
            from sketches_tpu.batched import occupied_bounds_np

            for name, bins in (("pos", bp), ("neg", bn)):
                lo, hi = occupied_bounds_np(bins)
                arrays[f"{name}_lo"] = jnp.asarray(lo)
                arrays[f"{name}_hi"] = jnp.asarray(hi)
            arrays["neg_total"] = jnp.asarray(
                bn.sum(axis=-1).astype(bn.dtype)
            )
        if "tile_sums" not in arrays:  # r <= 3 checkpoints
            from sketches_tpu.batched import tile_sums_np

            arrays["tile_sums"] = jnp.asarray(
                tile_sums_np(bp, bn).astype(bp.dtype)
            )
        state = SketchState(**arrays)
    return spec, state, stored_fp


def save(
    path: str,
    sketch: Union[BatchedDDSketch, "DistributedDDSketch"],  # noqa: F821
    partials: bool = False,
) -> None:
    """Checkpoint a batched (or distributed -- folded first) sketch facade.

    ``partials=True`` (distributed facades only; ``SpecError``
    otherwise) saves the STACKED ``[K, n_streams, ...]`` partials pytree
    instead of the fold -- the elastic-resume format:
    ``restore_distributed(..., live_mask=...)`` can then drop dead
    shards at restore time with exact per-shard accounting, which a
    folded checkpoint cannot (the shards are already summed).
    """
    from sketches_tpu.parallel import DistributedDDSketch

    if isinstance(sketch, DistributedDDSketch):
        if partials:
            save_state(path, sketch.spec, sketch.partials)
        else:
            save_state(path, sketch.spec, sketch.merged_state())
    else:
        if partials:
            from sketches_tpu.resilience import SpecError

            raise SpecError(
                "partials=True needs a DistributedDDSketch (a batched"
                " facade has no shard axis)"
            )
        save_state(path, sketch.spec, sketch.state)


def restore(path: str, engine: str = "auto"):
    """Resume a checkpoint as the facade matching its backend.

    Dense checkpoints restore a ``BatchedDDSketch`` (engine re-selected
    here); ``uniform_collapse``/``moment`` checkpoints restore their
    backend facades with levels/moments intact.  Corrupt archives raise
    ``CheckpointCorrupt`` via :func:`restore_state`.
    """
    spec, state = restore_state(path)
    if spec.backend != "dense":
        from sketches_tpu.backends import facade_for

        return facade_for(
            state.n_streams, spec=spec, state=state, engine=engine
        )
    return BatchedDDSketch(
        state.n_streams, spec=spec, state=state, engine=engine
    )


def restore_distributed(
    path: str,
    mesh=None,
    value_axis="values",
    stream_axis=None,
    engine: str = "auto",
    live_mask=None,
    n_hosts=None,
):
    """Resume a checkpoint as a mesh-sharded ``DistributedDDSketch``.

    The saved state is the FOLDED batch (``save`` folds partials before
    writing); ``DistributedDDSketch.from_merged_state`` loads it into
    value-shard 0's partial (the other shards hold the fold's
    identities), so the psum fold reproduces the saved totals exactly and
    subsequent ingest spreads new mass across shards as usual.  The
    mesh/axes may differ -- in SIZE too -- from the mesh the checkpoint
    was written under (the wire carries no topology; state is
    topology-free by design): this is the elastic resume path, and with
    the integrity layer armed the checkpoint's embedded fingerprint is
    re-verified on the restored state before the new fleet folds it.

    A ``save(..., partials=True)`` checkpoint restores the stacked
    partials instead; ``live_mask`` (a ``[K]`` bool) then drops dead
    shards at restore time with their mass accounted
    (``resilience.health()``), and a mask over a folded checkpoint
    raises ``SketchValueError``.  A torn or corrupted file raises
    ``CheckpointCorrupt`` -- an interrupted reshard can never silently
    lose mass, because the previous checkpoint is still intact
    (atomic writes) and a damaged one refuses to load.
    """
    from sketches_tpu.parallel import DistributedDDSketch

    spec, state = restore_state(path)
    return DistributedDDSketch.from_merged_state(
        state,
        spec,
        mesh=mesh,
        value_axis=value_axis,
        stream_axis=stream_axis,
        engine=engine,
        live_mask=live_mask,
        n_hosts=n_hosts,
    )


# ---------------------------------------------------------------------------
# Windowed ring checkpoints (ring + ladder + ledger, atomically)
# ---------------------------------------------------------------------------


def _windowed_doc(wsk) -> Tuple[str, dict]:
    """Flatten a WindowedSketch to (meta json, array dict) -- the
    save-side half of the windowed checkpoint format.  Bucket ``k``'s
    state arrays live under ``b{k}.<field>``; the meta carries the
    spec, the ladder config, the per-bucket ledger entries (the live
    bucket flagged), and the retired/total mass."""
    spec = wsk.spec
    buckets_meta = []
    arrays: dict = {}
    k = 0
    for rung in range(wsk.config.n_rungs):
        for bid in sorted(wsk._rungs[rung]):
            b = wsk._rungs[rung][bid]
            for name, arr in _state_arrays(spec, b.state).items():
                arrays[f"b{k}.{name}"] = arr
            buckets_meta.append(
                {"rung": rung, "id": bid, "mass": b.mass, "live": False}
            )
            k += 1
    if wsk._live_id is not None:
        live_state = wsk._snapshot_state(wsk._live.state)
        for name, arr in _state_arrays(spec, live_state).items():
            arrays[f"b{k}.{name}"] = arr
        buckets_meta.append(
            {
                "rung": 0, "id": wsk._live_id, "mass": wsk._live_mass,
                "live": True,
            }
        )
        k += 1
    meta = {
        "format": "windowed-v1",
        "spec": json.loads(_spec_json(spec)),
        "config": {
            "slices_s": list(wsk.config.slices_s),
            "lengths": list(wsk.config.lengths),
            "collapse_levels": (
                None if wsk.config.collapse_levels is None
                else list(wsk.config.collapse_levels)
            ),
        },
        "n_streams": wsk.n_streams,
        "buckets": buckets_meta,
        "total": wsk._total,
        "retired": wsk._retired,
        "rotations": wsk._rotations,
        "ladder_collapses": wsk._ladder_collapses,
        "cur": wsk._cur,
    }
    return json.dumps(meta, sort_keys=True), arrays


def save_windowed(path: str, wsk) -> None:
    """Checkpoint a ``WindowedSketch``: ring + ladder + exact mass
    ledger in ONE atomically-renamed npz, so a crash mid-write can
    never tear the ring apart from its ledger.

    Same durability contract as :func:`save_state`: serialize to
    memory, tmp + fsync + ``os.replace``, sha256 content checksum over
    the meta and every bucket array; the armed integrity layer
    verifies every bucket state before anything hits disk and embeds
    per-bucket fingerprints for the restore-side re-verification.  The
    armed ``checkpoint.write`` fault site tears/aborts exactly like the
    batched path (the previous checkpoint survives).  Raises
    ``SpecError`` for a non-windowed argument.
    """
    from sketches_tpu.resilience import SpecError
    from sketches_tpu.windows import WindowedSketch

    if not isinstance(wsk, WindowedSketch):
        raise SpecError(
            f"save_windowed needs a WindowedSketch; got"
            f" {type(wsk).__name__} (use save() for plain facades)"
        )
    _t0 = telemetry.clock() if telemetry._ACTIVE else None
    meta_json, arrays = _windowed_doc(wsk)
    extra = {}
    if integrity._ACTIVE:
        k = 0
        for rung in range(wsk.config.n_rungs):
            for bid in sorted(wsk._rungs[rung]):
                b = wsk._rungs[rung][bid]
                integrity.verify_state(
                    wsk.spec, b.state, seam="checkpoint.save_windowed"
                )
                extra[f"__fp_b{k}__"] = integrity.fingerprint(
                    wsk.spec, b.state
                )
                k += 1
        if wsk._live_id is not None:
            live_state = wsk._snapshot_state(wsk._live.state)
            integrity.verify_state(
                wsk.spec, live_state, seam="checkpoint.save_windowed"
            )
            extra[f"__fp_b{k}__"] = integrity.fingerprint(
                wsk.spec, live_state
            )
    buf = io.BytesIO()
    np.savez_compressed(
        buf,
        __window__=np.frombuffer(meta_json.encode(), np.uint8),
        __checksum__=np.frombuffer(
            _digest(meta_json, arrays).encode(), np.uint8
        ),
        **extra,
        **arrays,
    )
    data = buf.getvalue()
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        if faults._ACTIVE:
            data = faults.inject(faults.CHECKPOINT_WRITE, payload=data)
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        if _t0 is not None:
            telemetry.finish_span("checkpoint.save_s", _t0)
            telemetry.gauge_set("checkpoint.bytes", float(len(data)))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def restore_windowed(
    path: str,
    *,
    clock=None,
    mesh=None,
    value_axis=None,
    stream_axis=None,
    engine: str = "auto",
):
    """Resume a :func:`save_windowed` checkpoint -> a ``WindowedSketch``
    with its ring, ladder positions, and exact mass ledger intact.

    ``clock`` must be consistent with the timeline the ring was saved
    under (a virtual clock restores deterministically; rotation resumes
    from the saved slice positions).  Passing ``mesh``/``value_axis``
    re-homes the live bucket on a mesh-sharded fleet (frozen buckets
    are topology-free and load anywhere -- the elastic resume
    property).  Any torn/corrupted archive raises
    :class:`CheckpointCorrupt`; an armed integrity layer re-verifies
    every bucket state against its embedded fingerprint; a missing file
    stays ``FileNotFoundError``; ``SKETCHES_TPU_WINDOWED=0`` refuses
    via the ``WindowedSketch`` constructor.
    """
    _t0 = telemetry.clock() if telemetry._ACTIVE else None
    try:
        wsk = _restore_windowed_inner(
            path, clock=clock, mesh=mesh, value_axis=value_axis,
            stream_axis=stream_axis, engine=engine,
        )
    except (FileNotFoundError, CheckpointCorrupt):
        raise
    except Exception as e:
        raise CheckpointCorrupt(
            f"windowed checkpoint {path!r} failed to restore"
            f" ({type(e).__name__}: {e})"
        ) from e
    if _t0 is not None:
        telemetry.finish_span("checkpoint.restore_s", _t0)
    return wsk


def _restore_windowed_inner(
    path, *, clock, mesh, value_axis, stream_axis, engine
):
    from sketches_tpu.windows import WindowConfig, WindowedSketch, _Bucket

    with np.load(path) as data:
        if "__window__" not in data.files:
            raise CheckpointCorrupt(
                f"checkpoint {path!r} is not a windowed checkpoint"
                " (no __window__ member); use restore() instead"
            )
        meta_json = bytes(data["__window__"]).decode()
        meta = json.loads(meta_json)
        spec = _spec_from_meta(meta["spec"])
        cfg = meta["config"]
        config = WindowConfig(
            slices_s=tuple(cfg["slices_s"]),
            lengths=tuple(cfg["lengths"]),
            collapse_levels=(
                None if cfg["collapse_levels"] is None
                else tuple(cfg["collapse_levels"])
            ),
        )
        n_buckets = len(meta["buckets"])
        fields = (
            _FIELDS + ["level"] if spec.backend == "uniform_collapse"
            else list(_MOMENT_FIELDS) if spec.backend == "moment"
            else list(_FIELDS)
        )
        arrays_np = {}
        for k in range(n_buckets):
            for name in fields:
                key = f"b{k}.{name}"
                if key not in data.files:
                    raise CheckpointCorrupt(
                        f"windowed checkpoint {path!r} is missing"
                        f" bucket array {key!r}"
                    )
                arrays_np[key] = np.asarray(data[key])
        if "__checksum__" in data.files:
            stored = bytes(data["__checksum__"]).decode()
            got = _digest(meta_json, arrays_np)
            if got != stored:
                raise CheckpointCorrupt(
                    f"windowed checkpoint {path!r} checksum mismatch"
                    f" (stored {stored[:12]}..., recomputed"
                    f" {got[:12]}...): content corrupted after write"
                )
        wsk = WindowedSketch(
            int(meta["n_streams"]), spec=spec, config=config,
            clock=clock, mesh=mesh, value_axis=value_axis,
            stream_axis=stream_axis, engine=engine,
        )
        for k, bm in enumerate(meta["buckets"]):
            arrays = {
                name: jnp.asarray(arrays_np[f"b{k}.{name}"])
                for name in fields
            }
            state = _arrays_to_backend_state(spec, arrays)
            if integrity._ACTIVE and f"__fp_b{k}__" in data.files:
                integrity.verify_restore(
                    spec, state, np.asarray(data[f"__fp_b{k}__"]),
                    seam="checkpoint.restore_windowed",
                )
            if bm["live"]:
                wsk._set_live_state(state)
                wsk._live_id = int(bm["id"])
                wsk._live_mass = float(bm["mass"])
            else:
                wsk._rungs[int(bm["rung"])][int(bm["id"])] = _Bucket(
                    rung=int(bm["rung"]), id=int(bm["id"]),
                    state=state, mass=float(bm["mass"]),
                )
        wsk._total = float(meta["total"])
        wsk._retired = float(meta["retired"])
        wsk._rotations = int(meta.get("rotations", 0))
        wsk._ladder_collapses = int(meta.get("ladder_collapses", 0))
        wsk._cur = None if meta["cur"] is None else int(meta["cur"])
        # The two-stacks window aggregates are DERIVED state: they are
        # never serialized, and the rungs above were assigned behind the
        # constructor's back -- drop the fresh stacks so the first plan
        # rebuilds them from the restored ring (counted as a rebuild).
        wsk._agg_invalidate()
    return wsk
