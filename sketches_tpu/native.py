"""ctypes bindings for the native host-tier engine (native/ddsketch_host.cpp).

The reference has no native code (SURVEY.md section 2); this engine exists
for the host side of the TPU framework -- data-loader threads and collector
processes that pre-aggregate before device upload.  It shares the device
tier's static-window semantics, so ``to_state`` lifts a native sketch
directly into a ``[1, n_bins]`` batched state (and ``from_state`` back).

The shared library builds on demand with ``make -C native`` (plain C ABI,
no pybind11).  ``available()`` reports whether a toolchain/library exists;
everything degrades gracefully to the pure-Python tier when it does not.
"""

from __future__ import annotations

import binascii
import ctypes
import math
import os
import subprocess
import threading
import time
import typing

import numpy as np

from sketches_tpu import faults, resilience, telemetry
from sketches_tpu.analysis import registry
from sketches_tpu.resilience import EngineUnavailable, SpecError

__all__ = [
    "available",
    "reset",
    "wire_scanner",
    "NativeDDSketch",
    "NATIVE_ENV",
    "WIRE_ABI_VERSION",
]

#: Environment kill switch: ``SKETCHES_TPU_NATIVE=0`` forces the native
#: engine unavailable (pure-Python host tier), for degraded-mode CI and
#: for operating around a broken toolchain without a code change.
#: Declared in ``analysis/registry.py`` (the kill-switch inventory);
#: this alias keeps the historical import path working.
NATIVE_ENV = registry.NATIVE.name

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libddsketch_host.so")
_lock = threading.Lock()
_lib: typing.Optional[ctypes.CDLL] = None
_build_error: typing.Optional[str] = None
_wire_ok = False

#: Expected value of the library's ``ddsk_wire_abi_version()`` symbol.
#: The bulk wire scanner's C ABI (argument layouts, status codes, output
#: array shapes) is versioned so a STALE ``.so`` -- older sources whose
#: mtime comparison lied (copied artifacts, clock skew, prebuilt caches)
#: -- degrades the wire fast path to the pure-Python walker instead of
#: corrupting decodes through a mismatched layout.  Bump in lockstep
#: with ``kWireAbiVersion`` in ``native/ddsketch_wire.cpp``.
WIRE_ABI_VERSION = 1

#: Build/load attempts before the engine degrades for the process, and
#: the capped exponential backoff between them.  Retries cover transient
#: failures (NFS hiccough on the .so, a concurrent build holding the
#: file); a hard toolchain absence just fails fast three times.
_MAX_LOAD_ATTEMPTS = 3
_BACKOFF_BASE_S = 0.05
_BACKOFF_CAP_S = 0.2


def _backoff_jitter(key: int, attempt: int) -> float:
    """Deterministic per-(key, attempt) jitter factor in [0.5, 1.0).

    Co-starting processes that all fail the same load would otherwise
    retry in lockstep and re-collide on the shared .so/NFS path; scaling
    each process's capped exponential sleep by a hash of its pid keeps
    the backoff fully deterministic (no clock, no RNG -- a failing
    sequence still replays exactly within a process) while de-phasing
    the fleet.  Never raises; pure function of its arguments.
    """
    h = binascii.crc32(f"{key}:{attempt}".encode()) & 0xFFFFFFFF
    return 0.5 + 0.5 * (h / 2**32)


_SRC_PATH = os.path.join(_NATIVE_DIR, "ddsketch_host.cpp")


def _stale() -> bool:
    """Library missing, or older than its source/Makefile (rebuild on edits)."""
    if not os.path.exists(_LIB_PATH):
        return True
    try:
        built = os.path.getmtime(_LIB_PATH)
        return any(
            os.path.getmtime(os.path.join(_NATIVE_DIR, f)) > built
            for f in ("ddsketch_host.cpp", "ddsketch_wire.cpp", "Makefile")
        )
    except OSError:
        return False


def _load() -> typing.Optional[ctypes.CDLL]:
    """Build (if needed) and load the shared library, with bounded retry.

    Transient failures (injected or real) retry up to
    ``_MAX_LOAD_ATTEMPTS`` times with capped exponential backoff; a
    still-failing load then degrades the process to the pure-Python host
    tier -- cached (no per-call rebuild storms), observable as a
    ``native -> python`` downgrade in ``resilience.health()``, and
    clearable with :func:`reset`.
    """
    global _lib, _build_error, _wire_ok
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        if not registry.enabled(registry.NATIVE):
            _build_error = f"disabled via {NATIVE_ENV}=0"
            resilience.record_downgrade(
                "native", "native", "python", _build_error
            )
            return None
        last_error = None
        _t0 = telemetry.clock() if telemetry._ACTIVE else None
        for attempt in range(_MAX_LOAD_ATTEMPTS):
            if attempt:
                time.sleep(
                    min(_BACKOFF_BASE_S * 2 ** (attempt - 1), _BACKOFF_CAP_S)
                    * _backoff_jitter(os.getpid(), attempt)
                )
            try:
                if faults._ACTIVE:
                    faults.inject(faults.NATIVE_LOAD)
                if _t0 is not None:
                    telemetry.counter_inc("native.load_attempts")
                if _stale():
                    subprocess.run(
                        ["make", "-C", _NATIVE_DIR],
                        check=True,
                        capture_output=True,
                        text=True,
                    )
                _lib = _bind(ctypes.CDLL(_LIB_PATH))
                _wire_ok = _bind_wire(_lib)
                if not _wire_ok:
                    # The host-tier engine loaded but the bulk wire
                    # scanner is missing or speaks a different ABI (a
                    # stale .so the mtime check could not catch): the
                    # wire fast path degrades to the pure-Python walker
                    # while NativeDDSketch stays available.
                    resilience.record_downgrade(
                        "native.wire",
                        "native",
                        "python",
                        "wire scanner unavailable: ddsk_wire_abi_version"
                        f" != {WIRE_ABI_VERSION} or symbols missing"
                        " (stale/ABI-mismatched library; rebuild with"
                        " `make -C native`)",
                    )
                if _t0 is not None:
                    telemetry.finish_span("native.load_s", _t0)
                return _lib
            except (
                OSError,
                subprocess.CalledProcessError,
                resilience.InjectedFault,
            ) as e:
                last_error = getattr(e, "stderr", None) or str(e)
        _build_error = last_error or "unknown load failure"
        resilience.record_downgrade(
            "native",
            "native",
            "python",
            f"load failed after {_MAX_LOAD_ATTEMPTS} attempts: {_build_error}",
        )
        return None


def reset() -> None:
    """Forget the cached load outcome (the next ``available()`` retries).

    Test/ops hook: lets a process recover the native tier after the
    condition behind a degradation (toolchain, env var, injected fault)
    is fixed.  Live ``NativeDDSketch`` objects keep their own library
    handle and are unaffected.
    """
    global _lib, _build_error, _wire_ok
    with _lock:
        _lib = None
        _build_error = None
        _wire_ok = False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    """Declare the C ABI on a freshly loaded library handle."""
    lib.sketch_create.restype = ctypes.c_void_p
    lib.sketch_create.argtypes = [
        ctypes.c_double,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.sketch_destroy.argtypes = [ctypes.c_void_p]
    lib.sketch_add.argtypes = [ctypes.c_void_p, ctypes.c_double, ctypes.c_double]
    lib.sketch_add_batch.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
        ctypes.c_size_t,
    ]
    lib.sketch_quantile.restype = ctypes.c_double
    lib.sketch_quantile.argtypes = [ctypes.c_void_p, ctypes.c_double]
    lib.sketch_merge.restype = ctypes.c_int
    lib.sketch_merge.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.sketch_counters.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.sketch_bins.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.sketch_load_bins.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
    ]
    return lib


def _bind_wire(lib: ctypes.CDLL) -> bool:
    """Declare the bulk wire scanner's C ABI on a loaded handle.

    Returns ``False`` (never raises) when the symbols are absent or the
    library's ``ddsk_wire_abi_version()`` disagrees with this module's
    :data:`WIRE_ABI_VERSION` -- a stale or foreign ``.so`` -- or when
    the host is not little-endian (the scanner memcpys LE wire doubles
    verbatim).  Argtypes are declared BEFORE the version call so a
    mismatched library is never entered with an unchecked signature.
    """
    import sys

    if sys.byteorder != "little":  # pragma: no cover - LE hosts only
        return False
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64 = ctypes.c_longlong
    i64p = ctypes.POINTER(ctypes.c_longlong)
    dp = ctypes.POINTER(ctypes.c_double)
    try:
        lib.ddsk_wire_abi_version.restype = ctypes.c_int
        lib.ddsk_wire_abi_version.argtypes = []
        lib.ddsk_wire_scan_dense.restype = i64
        lib.ddsk_wire_scan_dense.argtypes = [
            ctypes.c_char_p, i64, i64p,      # buf, n, offsets
            ctypes.c_char_p, i64,            # prefix, prefix_len
            i64,                             # base
            u8p, dp, i64p, i64p, i64p, dp,   # status, zc, pos, len, j0, out
        ]
        lib.ddsk_wire_scan_envelope.restype = i64
        lib.ddsk_wire_scan_envelope.argtypes = [
            ctypes.c_char_p, i64, i64p,      # buf, n, offsets
            i64,                             # expected_backend
            u8p, i64p, i64p, i64p,           # status, level, dense off/len
        ]
        lib.ddsk_wire_scan_moment.restype = i64
        lib.ddsk_wire_scan_moment.argtypes = [
            ctypes.c_char_p, i64, i64p,      # buf, n, offsets
            i64, i64,                        # expected_backend, k
            u8p, dp, dp, dp,                 # status, scalars, powers, logs
        ]
    except AttributeError:
        return False
    return lib.ddsk_wire_abi_version() == WIRE_ABI_VERSION


def available() -> bool:
    """True iff the native engine can be built/loaded on this machine."""
    return _load() is not None


def wire_scanner() -> typing.Optional[ctypes.CDLL]:
    """The wire-scan-capable native library handle, or ``None``.

    The bulk decoders (``pb/wire.py``, ``backends/wirefmt.py``) call
    this before taking the C++ structural-scan fast path.  Failure
    modes: returns ``None`` -- never raises -- when the library cannot
    build/load, when ``SKETCHES_TPU_NATIVE=0`` disables the engine, or
    when the loaded ``.so`` predates (or postdates) this module's wire
    ABI (:data:`WIRE_ABI_VERSION` vs the versioned
    ``ddsk_wire_abi_version`` symbol); callers then decode through the
    pure-Python canonical walker bit-identically, and the degradation is
    recorded once in ``resilience.health()`` as a ``native.wire``
    downgrade.  :func:`reset` clears the cached outcome.
    """
    if _load() is None:
        return None
    return _lib if _wire_ok else None


def _u8ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i64ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong))


def _dptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


_MAPPING_KINDS = {
    "logarithmic": 0,
    "linear_interpolated": 1,
    "cubic_interpolated": 2,
    "quadratic_interpolated": 3,
}


class NativeDDSketch:
    """Reference-shaped single sketch backed by the C++ engine.

    Same static-window semantics as the device tier: keys clamp into
    ``[key_offset, key_offset + n_bins)``; ``add_batch`` is the fast path.
    All four mappings are supported (the engine keys values with the same
    scalar-path semantics as ``sketches_tpu.mapping``), so the host
    pre-aggregator can feed a device batch of any mapping -- including the
    cubic mapping of the flagship 1M-stream config (VERDICT r2 item 5).
    """

    def __init__(
        self,
        relative_accuracy: float = 0.01,
        n_bins: int = 2048,
        key_offset: typing.Optional[int] = None,
        mapping: str = "logarithmic",
    ):
        lib = _load()
        if lib is None:
            raise EngineUnavailable(
                f"native engine unavailable: {_build_error or 'no toolchain'}"
            )
        if key_offset is None:
            key_offset = -(n_bins // 2)
        if mapping not in _MAPPING_KINDS:
            raise SpecError(
                f"Unknown mapping {mapping!r}; expected one of"
                f" {sorted(_MAPPING_KINDS)}"
            )
        self._lib = lib
        self._handle = lib.sketch_create(
            relative_accuracy, n_bins, key_offset, _MAPPING_KINDS[mapping]
        )
        if not self._handle:
            raise SpecError("invalid sketch parameters")
        self.relative_accuracy = relative_accuracy
        self.n_bins = n_bins
        self.key_offset = key_offset
        self.mapping = mapping
        mantissa = 2.0 * relative_accuracy / (1.0 - relative_accuracy)
        self.gamma = 1.0 + mantissa

    def __del__(self):
        # Finalizer-safe against partially-initialized objects: a ctor
        # failure (unavailable engine, bad mapping, injected fault) can
        # leave _handle and/or _lib unset, and __del__ still runs.
        handle = getattr(self, "_handle", None)
        lib = getattr(self, "_lib", None)
        if handle and lib is not None:
            lib.sketch_destroy(handle)
            self._handle = None

    # -- core API ----------------------------------------------------------
    def add(self, val: float, weight: float = 1.0) -> None:
        if weight <= 0.0:
            raise resilience.SketchValueError("weight must be positive")
        self._lib.sketch_add(self._handle, float(val), float(weight))

    def add_batch(
        self,
        values: np.ndarray,
        weights: typing.Optional[np.ndarray] = None,
    ) -> "NativeDDSketch":
        values = np.ascontiguousarray(values, dtype=np.float64).ravel()
        wptr = None
        if weights is not None:
            weights = np.ascontiguousarray(weights, dtype=np.float64).ravel()
            if weights.shape != values.shape:
                raise resilience.SketchValueError(
                    "weights shape must match values"
                )
            wptr = _dptr(weights)
        self._lib.sketch_add_batch(self._handle, _dptr(values), wptr, values.size)
        return self

    def get_quantile_value(self, quantile: float) -> typing.Optional[float]:
        out = self._lib.sketch_quantile(self._handle, float(quantile))
        return None if math.isnan(out) else out

    def merge(self, other: "NativeDDSketch") -> None:
        from sketches_tpu.ddsketch import UnequalSketchParametersError

        if not self.mergeable(other):
            raise UnequalSketchParametersError(
                "Cannot merge native sketches with different parameters"
            )
        if self._lib.sketch_merge(self._handle, other._handle) != 0:
            raise UnequalSketchParametersError("Incompatible native sketches")

    def mergeable(self, other: "NativeDDSketch") -> bool:
        # Mapping identity required, not just gamma: all three mappings share
        # the gamma formula at equal alpha but key values differently (same
        # rule as the host and device tiers).
        return (
            self.gamma == other.gamma
            and self.n_bins == other.n_bins
            and self.key_offset == other.key_offset
            and self.mapping == other.mapping
        )

    # -- accessors ---------------------------------------------------------
    def _counters(self) -> np.ndarray:
        out = np.empty(7, np.float64)
        self._lib.sketch_counters(self._handle, _dptr(out))
        return out

    @property
    def zero_count(self) -> float:
        return float(self._counters()[0])

    @property
    def count(self) -> float:
        return float(self._counters()[1])

    num_values = count

    @property
    def sum(self) -> float:  # noqa: A003 - reference API name
        return float(self._counters()[2])

    @property
    def avg(self) -> float:
        c = self._counters()
        return float(c[2] / c[1])

    @property
    def collapsed_low(self) -> float:
        return float(self._counters()[5])

    @property
    def collapsed_high(self) -> float:
        return float(self._counters()[6])

    def bins(self) -> typing.Tuple[np.ndarray, np.ndarray]:
        pos = np.empty(self.n_bins, np.float64)
        neg = np.empty(self.n_bins, np.float64)
        self._lib.sketch_bins(self._handle, _dptr(pos), _dptr(neg))
        return pos, neg

    # -- device interop ----------------------------------------------------
    def to_state(self):
        """Lift into a 1-stream batched device state (same window layout)."""
        import jax.numpy as jnp

        from sketches_tpu.batched import SketchState

        pos, neg = self.bins()
        c = self._counters()
        as_row = lambda x: jnp.asarray(x, jnp.float32)[None]
        from sketches_tpu.batched import occupied_bounds_np, tile_sums_np

        (pos_lo, pos_hi), (neg_lo, neg_hi) = (
            occupied_bounds_np(pos), occupied_bounds_np(neg)
        )
        return SketchState(
            bins_pos=as_row(pos),
            bins_neg=as_row(neg),
            zero_count=jnp.asarray([c[0]], jnp.float32),
            count=jnp.asarray([c[1]], jnp.float32),
            sum=jnp.asarray([c[2]], jnp.float32),
            min=jnp.asarray([c[3]], jnp.float32),
            max=jnp.asarray([c[4]], jnp.float32),
            collapsed_low=jnp.asarray([c[5]], jnp.float32),
            collapsed_high=jnp.asarray([c[6]], jnp.float32),
            key_offset=jnp.asarray([self.key_offset], jnp.int32),
            pos_lo=jnp.asarray([pos_lo], jnp.int32),
            pos_hi=jnp.asarray([pos_hi], jnp.int32),
            neg_lo=jnp.asarray([neg_lo], jnp.int32),
            neg_hi=jnp.asarray([neg_hi], jnp.int32),
            neg_total=jnp.asarray([neg.sum()], jnp.float32),
            tile_sums=jnp.asarray(
                tile_sums_np(pos[None], neg[None]), jnp.float32
            ),
        )

    @classmethod
    def from_state(cls, spec, state, stream: int = 0) -> "NativeDDSketch":
        """Extract one stream of a batched state into a native sketch."""
        import jax

        host = jax.device_get(state)
        # The stream's window may have drifted from the spec default via
        # recentering -- the native sketch adopts the per-stream offset.
        sk = cls(
            spec.relative_accuracy,
            spec.n_bins,
            int(host.key_offset[stream]),
            mapping=spec.mapping_name,
        )
        counters = np.asarray(
            [
                host.zero_count[stream], host.count[stream], host.sum[stream],
                host.min[stream], host.max[stream],
                host.collapsed_low[stream], host.collapsed_high[stream],
            ],
            np.float64,
        )
        pos = np.ascontiguousarray(host.bins_pos[stream], np.float64)
        neg = np.ascontiguousarray(host.bins_neg[stream], np.float64)
        sk._lib.sketch_load_bins(sk._handle, _dptr(pos), _dptr(neg), _dptr(counters))
        return sk
