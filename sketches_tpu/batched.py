"""Device tier: batched DDSketch as struct-of-arrays on TPU.

This is the TPU-native redesign of the reference's object-per-sketch model
(reference seams: ``ddsketch/ddsketch.py . BaseDDSketch`` +
``ddsketch/store.py . CollapsingLowestDenseStore`` -- SURVEY.md sections 2, 7).
One *batch* of ``n_streams`` independent sketches is a single pytree of
device arrays:

    bins_pos, bins_neg : f32[n_streams, n_bins]
    zero_count, count, sum, min, max : f32[n_streams]

and every operation is a pure function ``state -> state`` (ingest, merge) or
``state -> values`` (query), jit/vmap/shard_map-safe:

* **Static shapes, adaptive windows.** The reference grows stores
  dynamically (``DenseStore._extend_range``); XLA wants static shapes, so
  the device store is *always-collapsing*: keys clamp into the per-stream
  window ``[key_offset[n], key_offset[n] + n_bins)``.  Clamping at the low
  edge is exactly ``CollapsingLowestDenseStore`` semantics; clamping at the
  high edge is ``CollapsingHighestDenseStore`` semantics; both edges are
  live at once and per-stream collapsed-mass counters surface the (silent,
  in the reference) resolution loss.  With the default alpha = 0.01 and
  n_bins = 2048 the window spans ~18 decades.  The window's *shape* is
  static but its *position* is state (``SketchState.key_offset``): the
  facades center each stream's window on its first batch, :func:`recenter`
  slides it (mass-conserving, traced shifts), and
  :meth:`BatchedDDSketch.maybe_recenter` chases regime drift -- the
  reference stores' follow-the-data behavior, without dynamic shapes
  (docs/DESIGN.md section 1b).
* **Branch-free three-way split.** The reference branches per value
  (positive / negative / zero); here masks + ``jnp.where`` route every value
  through the same arithmetic (SURVEY.md section 7 "hard parts").
* **Ingest is one scatter-add per store.** ``values -> keys -> clamp ->
  scatter-add``, vmapped over streams.  XLA scatter-add is deterministic-sum:
  duplicate keys within one batch accumulate exactly (tested).
* **Query is cumsum + mask-count rank selection.** The reference's linear
  ``key_at_rank`` walk becomes one prefix-sum reused across all requested
  quantiles, with ``#(cum <= rank)`` as a fused broadcast-compare-reduce
  (vmapped ``searchsorted`` lowers to serial gathers -- 13.5x slower).
* **Merge is elementwise add.** Offset alignment vanishes with a shared
  static window, so ``merge`` is ``a + b`` on bins and counters -- and the
  distributed merge is literally ``lax.psum`` (``sketches_tpu/parallel.py``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sketches_tpu import (
    accuracy,
    faults,
    integrity,
    profiling,
    resilience,
    telemetry,
    tracing,
)
from sketches_tpu.mapping import KeyMapping, mapping_from_name
from sketches_tpu.mapping import zero_threshold as mapping_zero_threshold
from sketches_tpu.resilience import SketchValueError, SpecError

__all__ = [
    "SketchSpec",
    "SketchState",
    "init",
    "add",
    "quantile",
    "get_quantile_value",
    "merge",
    "merge_aligned",
    "merge_axis",
    "recenter",
    "recenter_to_data",
    "auto_offset",
    "overflow_risk",
    "BatchedDDSketch",
]

DEFAULT_REL_ACC = 0.01
DEFAULT_N_BINS = 2048

# Column-tile width of the bin axis: the TPU lane width, and the granule of
# the per-tile mass summaries (``SketchState.tile_sums``) every query tier
# uses for hierarchical rank selection.  Must match ``kernels.LO``.
TILE = 128


@dataclasses.dataclass(frozen=True)
class SketchSpec:
    """Static (hashable, trace-time) configuration of a sketch batch.

    Plays the role of the reference's constructor arguments
    (``relative_accuracy``, ``bin_limit``, mapping choice) plus the one
    TPU-specific knob the reference cannot have: ``key_offset``, the low edge
    of the static key window.  Two batches are mergeable iff their specs are
    equal (the reference's same-gamma check, made total).

    Failure modes: invalid configuration (``relative_accuracy`` outside
    (0, 1), ``n_bins < 2``, an unknown mapping name) raises ``SpecError``
    at construction; merging across unequal specs raises
    ``UnequalSketchParametersError``.
    """

    relative_accuracy: float = DEFAULT_REL_ACC
    mapping_name: str = "logarithmic"
    n_bins: int = DEFAULT_N_BINS
    # Low edge of the representable key window.  The default centers the
    # window on key(1.0) = 0, covering values in roughly
    # [gamma**key_offset, gamma**(key_offset + n_bins)).
    key_offset: Optional[int] = None
    # Working dtype for values and the sum/min/max bookkeeping.  f32 mass
    # accumulation is exact only up to 2**24 (~16.7M) per bin/counter:
    # beyond that, unit adds round away (x + 1 == x) and quantiles bias
    # silently.  For exactness past that ceiling set ``bin_dtype=jnp.int32``
    # below; jnp.float64 also works but is emulated and slow on TPU.
    dtype: jnp.dtype = jnp.float32
    # Dtype of the bins and mass counters (zero_count/count/collapsed_*).
    # None follows ``dtype``.  ``jnp.int32`` gives *exact* accumulation to
    # 2**31 - 1 (~2.1e9) per bin -- the escape hatch for unit/integer-weight
    # workloads whose hot bins cross f32's 2**24 exact ceiling (VERDICT r2
    # item 3).  Integer mode requires integer-valued weights (fractional
    # weights truncate); sum/min/max stay in ``dtype``.  The Pallas engine
    # still ingests *unit-weight* calls (its per-call f32 histogram deltas
    # are exact integers bounded by the batch width, then accumulate into
    # the integer state); weighted calls and all queries take the XLA
    # path, whose integer scatter/cumsum/rank-select never rounds.
    bin_dtype: Optional[jnp.dtype] = None
    # Accuracy/memory backend contract (``sketches_tpu.backends``):
    # ``"dense"`` is the classic dense-bin store above;
    # ``"uniform_collapse"`` is the UDDSketch-style adaptive store (same
    # dense state + a per-stream collapse level -- alpha degrades
    # gamma -> gamma**2 per collapse instead of mass corrupting the
    # window edges; logarithmic mapping only); ``"moment"`` is the
    # compact moment summary (~n_moments power sums per stream, no bins).
    backend: str = "dense"
    # Uniform-collapse trigger: a stream whose edge-clamped mass fraction
    # (collapsed_low+high over binned mass) crosses this collapses once.
    collapse_threshold: float = 0.01
    # Uniform-collapse level cap: gamma_eff = gamma**(2**level) -- 10
    # doublings at alpha=0.01 already put alpha_eff past 0.99, so deeper
    # levels only lose information.  Hitting the cap stops collapsing
    # (mass then clamps at the edges again, counted as usual).
    max_collapses: int = 10
    # Moment backend: number of power sums kept per stream (per basis).
    n_moments: int = 12

    def __post_init__(self):
        if not 0.0 < self.relative_accuracy < 1.0:
            raise SpecError("Relative accuracy must be between 0 and 1.")
        if self.n_bins < 2:
            raise SpecError("n_bins must be >= 2")
        if self.backend not in ("dense", "uniform_collapse", "moment"):
            raise SpecError(
                f"Unknown backend {self.backend!r}: expected one of"
                " 'dense', 'uniform_collapse', 'moment'"
            )
        if self.backend == "uniform_collapse":
            if self.mapping_name != "logarithmic":
                raise SpecError(
                    "uniform_collapse backend requires the logarithmic"
                    " mapping (gamma -> gamma**2 collapse algebra only"
                    " composes on exact log keys); got"
                    f" {self.mapping_name!r}"
                )
            if not 0.0 < self.collapse_threshold < 1.0:
                raise SpecError("collapse_threshold must be in (0, 1)")
            if self.max_collapses < 1:
                raise SpecError("max_collapses must be >= 1")
        if self.backend == "moment" and not 2 <= self.n_moments <= 16:
            raise SpecError(
                "n_moments must be in [2, 16] (f32 power sums past 16"
                " carry no usable signal)"
            )
        if self.key_offset is None:
            object.__setattr__(self, "key_offset", -(self.n_bins // 2))
        if self.bin_dtype is None:
            object.__setattr__(self, "bin_dtype", self.dtype)
        # Windows wider than the f32-representable value range are fine:
        # bins beyond what f32 ingest can reach stay empty, and
        # ``KeyMapping.value_array`` saturates its decode to the positive
        # finite f32 range, so quantiles remain finite for any window.

    @property
    def bins_integer(self) -> bool:
        """Whether the bins/counters accumulate in an integer dtype."""
        return jnp.issubdtype(jnp.dtype(self.bin_dtype), jnp.integer)

    @property
    def n_tiles(self) -> int:
        """Column tiles per store: ``ceil(n_bins / 128)`` (ragged last tile
        for non-128-multiple bin counts)."""
        return -(-self.n_bins // TILE)

    @functools.cached_property
    def mapping(self) -> KeyMapping:
        return mapping_from_name(self.mapping_name, self.relative_accuracy)

    @property
    def gamma(self) -> float:
        return self.mapping.gamma

    @property
    def min_value(self) -> float:
        """Smallest positive value representable without low-edge collapse."""
        return self.mapping.value(self.key_offset)

    @property
    def max_value(self) -> float:
        """Largest positive value representable without high-edge collapse."""
        return self.mapping.value(self.key_offset + self.n_bins - 1)

    def __hash__(self):  # jnp dtypes hash fine; dataclass default is fine too
        return hash(
            (
                self.relative_accuracy,
                self.mapping_name,
                self.n_bins,
                self.key_offset,
                jnp.dtype(self.dtype).name,
                jnp.dtype(self.bin_dtype).name,
                self.backend,
                self.collapse_threshold,
                self.max_collapses,
                self.n_moments,
            )
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SketchState:
    """Per-batch device state: the struct-of-arrays sketch.

    Field-for-field image of the reference's ``BaseDDSketch`` instance state
    (pos store bins, neg store bins, zero_count, _count/_min/_max/_sum),
    plus collapsed-mass observability counters (SURVEY.md section 5,
    metrics row).
    """

    bins_pos: jax.Array  # [n_streams, n_bins]
    bins_neg: jax.Array  # [n_streams, n_bins]
    zero_count: jax.Array  # [n_streams]
    count: jax.Array  # [n_streams]
    sum: jax.Array  # [n_streams]
    min: jax.Array  # [n_streams]
    max: jax.Array  # [n_streams]
    collapsed_low: jax.Array  # [n_streams] mass clamped into the low edge
    collapsed_high: jax.Array  # [n_streams] mass clamped into the high edge
    # Per-stream low edge of the key window (int32).  Initialized to
    # ``spec.key_offset`` and *dynamic* thereafter: :func:`recenter` slides
    # each stream's window independently, recovering the reference stores'
    # follow-the-data behavior (``DenseStore._shift_bins``) that a purely
    # static window cannot give (VERDICT r2 item 2).  ``spec.key_offset``
    # remains the construction-time default.
    key_offset: jax.Array  # [n_streams]
    # Per-store occupied-bin bounds (int32, window-relative): the
    # smallest/largest bin index that may hold mass in each store --
    # ``(n_bins, -1)`` for an empty store.  Maintained during ingest (the
    # min/max over each batch's bin indices is nearly free) so a query can
    # restrict its HBM traffic to the globally occupied span instead of
    # streaming every bin, and clip degenerate ranks to the exact occupied
    # edge without re-deriving bounds from the bins (VERDICT r2 item 1c).
    # Exact for float bins (every ``w > 0`` lane deposits mass); a
    # conservative superset in the integer-mode truncation corner (a lane
    # whose mass truncates to 0 still widens the span).
    pos_lo: jax.Array  # [n_streams]
    pos_hi: jax.Array  # [n_streams]
    neg_lo: jax.Array  # [n_streams]
    neg_hi: jax.Array  # [n_streams]
    # Total mass in the negative store (bin dtype) == ``bins_neg.sum(-1)``.
    # Carried as a counter so rank thresholds (which need the negative
    # total *before* any bin is read) are available to single-pass windowed
    # query kernels without a pre-scan of ``bins_neg``.
    neg_total: jax.Array  # [n_streams]
    # Per-tile mass summaries: ``tile_sums[:, t]`` is the total mass of
    # ``bins_pos[:, t*128:(t+1)*128]`` for ``t < n_tiles``, and of the
    # matching ``bins_neg`` tile for ``t >= n_tiles`` -- one [N, 2*T] array
    # (both stores share one 128-lane HBM stripe).  Maintained incrementally
    # by every ingest engine (VERDICT r3 item 1: nearly free next to the
    # histogram build) so a query can do *hierarchical rank selection*:
    # locate each (stream, q)'s crossing tile from the summaries alone and
    # read only that 128-bin tile of the store -- worst-case query HBM
    # bytes become occupancy-independent.  In float mode the per-call delta
    # accumulation can differ from ``bins.reshape(...).sum(-1)`` by ULPs
    # (different summation order; exact for unit-weight/integer masses) --
    # consumers treat a summary-derived crossing as at-most-one-bucket
    # approximate, the same contract as the engines' shared one-ULP rank
    # divergence (ADVICE r3).
    tile_sums: jax.Array  # [n_streams, 2 * n_tiles]

    # Combined-store window bounds (derived): what a windowed query plans
    # its HBM read against.
    @property
    def occ_lo(self) -> jax.Array:
        return jnp.minimum(self.pos_lo, self.neg_lo)

    @property
    def occ_hi(self) -> jax.Array:
        return jnp.maximum(self.pos_hi, self.neg_hi)

    @property
    def n_streams(self) -> int:
        return self.bins_pos.shape[-2]

    @property
    def n_bins(self) -> int:
        return self.bins_pos.shape[-1]


def init(spec: SketchSpec, n_streams: int) -> SketchState:
    """Allocate an empty batch of ``n_streams`` sketches (all shapes static)."""
    dt = spec.dtype
    bd = spec.bin_dtype
    zeros2 = jnp.zeros((n_streams, spec.n_bins), dtype=bd)
    zeros1 = jnp.zeros((n_streams,), dtype=bd)
    return SketchState(
        bins_pos=zeros2,
        bins_neg=jnp.zeros_like(zeros2),
        zero_count=zeros1,
        count=jnp.zeros_like(zeros1),
        sum=jnp.zeros((n_streams,), dtype=dt),
        min=jnp.full((n_streams,), jnp.inf, dtype=dt),
        max=jnp.full((n_streams,), -jnp.inf, dtype=dt),
        collapsed_low=jnp.zeros_like(zeros1),
        collapsed_high=jnp.zeros_like(zeros1),
        key_offset=jnp.full((n_streams,), spec.key_offset, dtype=jnp.int32),
        pos_lo=jnp.full((n_streams,), spec.n_bins, dtype=jnp.int32),
        pos_hi=jnp.full((n_streams,), -1, dtype=jnp.int32),
        neg_lo=jnp.full((n_streams,), spec.n_bins, dtype=jnp.int32),
        neg_hi=jnp.full((n_streams,), -1, dtype=jnp.int32),
        neg_total=jnp.zeros_like(zeros1),
        tile_sums=jnp.zeros((n_streams, 2 * spec.n_tiles), dtype=bd),
    )


def tile_sums_of(bins_pos: jax.Array, bins_neg: jax.Array) -> jax.Array:
    """Recompute the [N, 2*T] per-tile masses from the bins (device).

    The from-scratch twin of the incremental maintenance in the ingest
    engines -- used where the bins are being streamed anyway (recenter,
    checkpoint backfill).  Ragged bin counts zero-pad the last tile.
    """
    n, b = bins_pos.shape
    t = -(-b // TILE)
    pad = t * TILE - b

    def tiles(x):
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad)))
        return x.reshape(n, t, TILE).sum(-1)

    return jnp.concatenate([tiles(bins_pos), tiles(bins_neg)], axis=1)


def tile_sums_np(bins_pos: np.ndarray, bins_neg: np.ndarray) -> np.ndarray:
    """Host (numpy) twin of :func:`tile_sums_of` for interop/restore paths."""
    n, b = bins_pos.shape
    t = -(-b // TILE)
    pad = t * TILE - b

    def tiles(x):
        if pad:
            x = np.pad(x, ((0, 0), (0, pad)))
        return x.reshape(n, t, TILE).sum(-1)

    return np.concatenate([tiles(bins_pos), tiles(bins_neg)], axis=1)


def _occupied_bounds(bins: jax.Array):
    """Exact occupied span of one store -> (lo [N], hi [N]) int32.

    ``(n_bins, -1)`` for empty rows -- the state's empty-span sentinels.
    Used where the bins are being streamed anyway (recenter, host interop);
    ingest maintains the running bounds incrementally instead.
    """
    n_bins = bins.shape[-1]
    occ = bins > 0
    iota = jnp.arange(n_bins, dtype=jnp.int32)
    lo = jnp.min(jnp.where(occ, iota, n_bins), axis=-1).astype(jnp.int32)
    hi = jnp.max(jnp.where(occ, iota, -1), axis=-1).astype(jnp.int32)
    return lo, hi


def occupied_bounds_np(bins: np.ndarray):
    """Host-side (numpy) twin of :func:`_occupied_bounds`, any batch shape.

    The ONE implementation of the ``(n_bins, -1)`` sentinel contract for
    host interop paths (checkpoint restore, host-sketch packing, native
    lift); the windowed query's clipping depends on every producer
    agreeing on these sentinels.
    """
    n_bins = bins.shape[-1]
    occ = bins > 0
    any_ = occ.any(axis=-1)
    # argmax on bool = first/last True: fewer and smaller temps than the
    # where(iota) min/max formulation (bulk-serde hot path).
    lo = np.where(any_, occ.argmax(axis=-1), n_bins).astype(np.int32)
    hi = np.where(
        any_, n_bins - 1 - occ[..., ::-1].argmax(axis=-1), -1
    ).astype(np.int32)
    return lo, hi


def _keys_and_masks(spec: SketchSpec, key_offset: jax.Array, values: jax.Array):
    """values [N, S] -> (clamped bin index [N, S] int32, masks, clamp masks).

    The branch-free analog of ``BaseDDSketch.add``'s three-way dispatch.
    The zero bucket is defined *explicitly* as |v| below the smallest
    positive normal of the working dtype -- not left to the backend's
    flush-to-zero behavior -- so classification is identical on TPU, CPU,
    and non-FTZ backends.  NaNs fail both comparisons and land in the zero
    path, matching the host tier.  ``key_offset`` is the per-stream window
    low edge ([N] int32, from the state), broadcast against the value lanes.
    """
    # jnp conversion first: the threshold must follow the *canonicalized*
    # dtype (with x64 off, a float64 spec runs in f32), and a raw numpy f64
    # input would otherwise carry a threshold that truncates to 0.
    v = jnp.asarray(values).astype(spec.dtype)
    tiny = jnp.asarray(mapping_zero_threshold(v.dtype), v.dtype)
    is_pos = v >= tiny
    is_neg = v <= -tiny
    is_zero = jnp.logical_not(jnp.logical_or(is_pos, is_neg))
    # Neutral operand keeps log() finite on masked lanes.
    absv = jnp.where(is_zero, jnp.asarray(1.0, spec.dtype), jnp.abs(v))
    keys = spec.mapping.key_array(absv)
    lo = key_offset[:, None].astype(jnp.int32)  # [N, 1]
    hi = lo + jnp.int32(spec.n_bins - 1)
    clamped_low = keys < lo
    clamped_high = keys > hi
    idx = jnp.clip(keys, lo, hi) - lo
    return idx, is_pos, is_neg, is_zero, clamped_low, clamped_high


def _row_scatter_add(bins: jax.Array, idx: jax.Array, w: jax.Array) -> jax.Array:
    """bins [B], idx [S], w [S] -> bins with w scattered (duplicate idx sum)."""
    return bins.at[idx].add(w)


def add(
    spec: SketchSpec,
    state: SketchState,
    values: jax.Array,
    weights: Optional[jax.Array] = None,
) -> SketchState:
    """Ingest ``values[n_streams, S]`` (optionally weighted) into the batch.

    Pure function; jit with ``donate_argnums`` on ``state`` so XLA updates the
    bins in place (SURVEY.md section 7: donation or 1B/s dies on copies).
    Entries with ``weights <= 0`` are inert padding: they contribute to no
    counter, min/max included -- this is the static-shape idiom for ragged
    per-stream batch sizes.  (The host tier raises ValueError on non-positive
    weights; under jit there is no raising, so the device tier defines them
    as padding instead -- documented divergence.)  NaN values land in the
    zero-count path with min/max untouched and ``sum`` poisoned to NaN,
    matching the host tier exactly.
    """
    v = jnp.asarray(values).astype(spec.dtype)
    if weights is None:
        w = jnp.ones_like(v)
    else:
        w = jnp.broadcast_to(jnp.asarray(weights, spec.dtype), v.shape)

    idx, is_pos, is_neg, is_zero, clamped_low, clamped_high = _keys_and_masks(
        spec, state.key_offset, v
    )
    live = w > 0
    w_pos = jnp.where(jnp.logical_and(is_pos, live), w, 0)
    w_neg = jnp.where(jnp.logical_and(is_neg, live), w, 0)
    w_zero = jnp.where(jnp.logical_and(is_zero, live), w, 0)
    w_live = w_pos + w_neg + w_zero

    # Mass accumulates in the bin dtype: a no-op cast in the default f32
    # mode; in integer mode (exact past f32's 2**24 ceiling) the cast-then-
    # sum order keeps every partial integral (fractional weights truncate
    # -- integer mode's documented contract).
    bd = jnp.dtype(spec.bin_dtype)
    wb_pos = w_pos.astype(bd)
    wb_neg = w_neg.astype(bd)
    wb_zero = w_zero.astype(bd)
    scatter = jax.vmap(_row_scatter_add)
    signed = wb_pos + wb_neg  # mass that hits a store (pos or neg)
    inf = jnp.asarray(jnp.inf, spec.dtype)
    # NaN values must not poison min/max (host tier: NaN comparisons are
    # false, so _min/_max stay untouched) -- mask them out of the extrema.
    finite_live = jnp.logical_and(live, jnp.logical_not(jnp.isnan(v)))
    zero_b = jnp.asarray(0, bd)
    hits_pos = jnp.logical_and(live, is_pos)
    hits_neg = jnp.logical_and(live, is_neg)
    # Tile-summary maintenance: one extra (tiny) scatter into [N, 2*T] --
    # the same per-lane mass, keyed by the bin's column tile, with negative
    # hits offset into the upper T columns.  Dead/zero lanes carry zero
    # mass, so their (dummy) target tile is harmless.
    tile_tgt = idx // TILE + jnp.where(is_neg, jnp.int32(spec.n_tiles), 0)
    return SketchState(
        bins_pos=scatter(state.bins_pos, idx, wb_pos),
        bins_neg=scatter(state.bins_neg, idx, wb_neg),
        zero_count=state.zero_count + wb_zero.sum(-1),
        count=state.count + (wb_pos + wb_neg + wb_zero).sum(-1),
        # Mask dead lanes out of v (not just the weight): NaN/inf padding with
        # weight 0 would otherwise poison the product (NaN * 0 = NaN).  Live
        # NaNs still poison sum, which is host-tier parity.
        sum=state.sum + (jnp.where(live, v, 0) * w_live).sum(-1),
        min=jnp.minimum(state.min, jnp.where(finite_live, v, inf).min(-1)),
        max=jnp.maximum(state.max, jnp.where(finite_live, v, -inf).max(-1)),
        collapsed_low=state.collapsed_low
        + jnp.where(clamped_low, signed, zero_b).sum(-1),
        collapsed_high=state.collapsed_high
        + jnp.where(clamped_high, signed, zero_b).sum(-1),
        key_offset=state.key_offset,
        # Running per-store occupied bounds: min/max of this batch's bin
        # indices over the lanes that hit each store (w > 0).  Exact for
        # float bins; conservative under integer-mode weight truncation (a
        # lane whose mass truncates to 0 still widens the span) -- superset
        # is the contract.
        pos_lo=jnp.minimum(
            state.pos_lo,
            jnp.min(
                jnp.where(hits_pos, idx, jnp.int32(spec.n_bins)), axis=-1
            ).astype(jnp.int32),
        ),
        pos_hi=jnp.maximum(
            state.pos_hi,
            jnp.max(jnp.where(hits_pos, idx, jnp.int32(-1)), axis=-1).astype(
                jnp.int32
            ),
        ),
        neg_lo=jnp.minimum(
            state.neg_lo,
            jnp.min(
                jnp.where(hits_neg, idx, jnp.int32(spec.n_bins)), axis=-1
            ).astype(jnp.int32),
        ),
        neg_hi=jnp.maximum(
            state.neg_hi,
            jnp.max(jnp.where(hits_neg, idx, jnp.int32(-1)), axis=-1).astype(
                jnp.int32
            ),
        ),
        neg_total=state.neg_total + wb_neg.sum(-1),
        tile_sums=scatter(state.tile_sums, tile_tgt, signed),
    )


def _last_occupied(bins: jax.Array) -> jax.Array:
    """Per row: largest index with bins > 0 (0 if the row is empty)."""
    n_bins = bins.shape[-1]
    iota = jnp.arange(n_bins, dtype=jnp.int32)
    return jnp.max(jnp.where(bins > 0, iota, 0), axis=-1)


def _first_occupied(bins: jax.Array) -> jax.Array:
    """Per row: smallest index with bins > 0 (n_bins - 1 if empty)."""
    n_bins = bins.shape[-1]
    iota = jnp.arange(n_bins, dtype=jnp.int32)
    return jnp.min(jnp.where(bins > 0, iota, n_bins - 1), axis=-1)


def quantile(spec: SketchSpec, state: SketchState, qs: jax.Array) -> jax.Array:
    """Quantile values for ``qs[Q]`` across the whole batch -> ``[n_streams, Q]``.

    One cumsum per store reused across every requested quantile -- the fused
    multi-quantile query (SURVEY.md section 3.3).  The reference's per-branch
    control flow (negative store / zero / positive store) becomes a
    three-way ``jnp.where`` select.  Out-of-range q or an empty stream yields
    NaN (the array-world stand-in for the reference's ``None``).
    """
    qs = jnp.atleast_1d(jnp.asarray(qs, spec.dtype))
    if qs.shape[0] == 0:  # empty quantile list: [N, 0], nothing to select
        return jnp.zeros((state.n_streams, 0), spec.dtype)
    # ``neg_total`` is the ONE definition of the negative-store mass shared
    # with the windowed/tiled kernels (ADVICE r3: recomputing
    # ``bins_neg.sum(-1)`` here accumulated in a different order, so rank
    # thresholds near exact boundaries could differ by one bucket between
    # engines).  It also saves the bin pre-scan the counter exists to avoid.
    neg_count = state.neg_total  # [N]
    count = state.count
    rank = qs[None, :] * (count[:, None] - 1)  # [N, Q]

    cum_pos = jnp.cumsum(state.bins_pos, axis=-1)  # [N, B]
    cum_neg = jnp.cumsum(state.bins_neg, axis=-1)

    # Rank selection as mask-counts over the monotone cumsums -- a fused
    # broadcast-compare-reduce XLA vectorizes, where vmapped searchsorted
    # lowers to serial gathers (measured 13.5x slower at 1M x 512 on v5e).
    # The Q axis unrolls as a static Python loop: peak memory stays at the
    # cumsum's O(N*B) instead of an O(N*Q*B) boolean intermediate, which on
    # backends that fail to fuse the 3-D compare+reduce (large-N CPU runs)
    # would materialize gigabytes (ADVICE r2).  Q is small (typically <= 8),
    # so the unrolled reduces cost the same as the broadcast form.
    #
    # Integer-bin mode compares in *integer space*: casting a cum past 2**24
    # to f32 would round the very masses the mode exists to keep exact, so
    # the float thresholds become integer ones via the integer-cum
    # identities  cum < x  <=>  cum <= ceil(x) - 1  and
    # cum <= r  <=>  cum <= floor(r).
    #
    # Negative branch (reference: key_at_rank(neg_count - 1 - rank,
    # lower=False), i.e. smallest key with cum >= r + 1 = #(cum < r + 1)).
    rev_rank = neg_count.astype(spec.dtype)[:, None] - 1 - rank
    q_total = rank.shape[1]
    int_mode = spec.bins_integer
    # Guard the float->int threshold casts against the dtype edge (count at
    # the very ceiling): f32 values at/above 2**31 would overflow the cast.
    _int_safe = float(2**31 - 256)
    if int_mode:
        thr_neg = jnp.clip(
            jnp.ceil(rev_rank + 1) - 1, -_int_safe, _int_safe
        ).astype(cum_neg.dtype)
        masks_neg = [
            cum_neg <= thr_neg[:, qi : qi + 1] for qi in range(q_total)
        ]
    else:
        masks_neg = [
            cum_neg < rev_rank[:, qi : qi + 1] + 1 for qi in range(q_total)
        ]
    idx_neg = jnp.stack(
        [m.sum(-1).astype(jnp.int32) for m in masks_neg], axis=1
    )
    idx_neg = jnp.clip(idx_neg, _first_occupied(state.bins_neg)[:, None],
                       _last_occupied(state.bins_neg)[:, None])

    # Positive branch (lower=True -> smallest key with cum > r = #(cum <= r)).
    pos_rank = rank - (state.zero_count + neg_count).astype(spec.dtype)[:, None]
    if int_mode:
        thr_pos = jnp.clip(
            jnp.floor(pos_rank), -_int_safe, _int_safe
        ).astype(cum_pos.dtype)
        masks_pos = [
            cum_pos <= thr_pos[:, qi : qi + 1] for qi in range(q_total)
        ]
    else:
        masks_pos = [
            cum_pos <= pos_rank[:, qi : qi + 1] for qi in range(q_total)
        ]
    idx_pos = jnp.stack(
        [m.sum(-1).astype(jnp.int32) for m in masks_pos], axis=1
    )
    idx_pos = jnp.clip(idx_pos, _first_occupied(state.bins_pos)[:, None],
                       _last_occupied(state.bins_pos)[:, None])

    key_lo = state.key_offset[:, None].astype(jnp.int32)  # [N, 1]
    val_neg = -spec.mapping.value_array(idx_neg + key_lo, dtype=spec.dtype)
    val_pos = spec.mapping.value_array(idx_pos + key_lo, dtype=spec.dtype)

    in_neg = rank < neg_count.astype(spec.dtype)[:, None]
    in_zero = rank < (neg_count + state.zero_count).astype(spec.dtype)[:, None]
    out = jnp.where(in_neg, val_neg, jnp.where(in_zero, 0.0, val_pos))

    valid = jnp.logical_and(
        jnp.logical_and(qs >= 0, qs <= 1)[None, :], (count > 0)[:, None]
    )
    return jnp.where(valid, out, jnp.nan)


def get_quantile_value(
    spec: SketchSpec, state: SketchState, q: float
) -> jax.Array:
    """Single-quantile convenience: ``[n_streams]`` of values (NaN if empty)."""
    return quantile(spec, state, jnp.asarray([q]))[:, 0]


def merge(spec: SketchSpec, a: SketchState, b: SketchState) -> SketchState:
    """Merged batch equivalent to having ingested both streams.

    The reference's ``BaseDDSketch.merge`` + ``DenseStore.merge`` with all
    offset alignment gone: a shared static window makes merge elementwise.
    Same-spec (same-gamma) checking lives on the host facade -- inside jit
    both operands were traced with one ``spec``, so it holds by construction.

    Requires ``a.key_offset == b.key_offset`` (both sides still on their
    construction windows, or recentered identically); use
    :func:`merge_aligned` when the windows may have drifted apart.
    """
    return SketchState(
        bins_pos=a.bins_pos + b.bins_pos,
        bins_neg=a.bins_neg + b.bins_neg,
        zero_count=a.zero_count + b.zero_count,
        count=a.count + b.count,
        sum=a.sum + b.sum,
        min=jnp.minimum(a.min, b.min),
        max=jnp.maximum(a.max, b.max),
        collapsed_low=a.collapsed_low + b.collapsed_low,
        collapsed_high=a.collapsed_high + b.collapsed_high,
        key_offset=a.key_offset,
        pos_lo=jnp.minimum(a.pos_lo, b.pos_lo),
        pos_hi=jnp.maximum(a.pos_hi, b.pos_hi),
        neg_lo=jnp.minimum(a.neg_lo, b.neg_lo),
        neg_hi=jnp.maximum(a.neg_hi, b.neg_hi),
        neg_total=a.neg_total + b.neg_total,
        tile_sums=a.tile_sums + b.tile_sums,
    )


def merge_axis(spec: SketchSpec, state: SketchState, axis: int = 0) -> SketchState:
    """Reduce a stacked ``[..., K, n_streams, n_bins]`` state over ``axis``.

    The tree-reduction form of ``merge`` for folding K partial batches
    (e.g. per-shard partial histograms) into one.  Partials must share
    per-stream window offsets (they do by construction: the distributed
    tier broadcasts one ``init`` and never recenters partials
    independently), so the fold keeps slice 0's offsets.
    """
    return SketchState(
        bins_pos=state.bins_pos.sum(axis),
        bins_neg=state.bins_neg.sum(axis),
        zero_count=state.zero_count.sum(axis),
        count=state.count.sum(axis),
        sum=state.sum.sum(axis),
        min=state.min.min(axis),
        max=state.max.max(axis),
        collapsed_low=state.collapsed_low.sum(axis),
        collapsed_high=state.collapsed_high.sum(axis),
        key_offset=jax.lax.index_in_dim(
            state.key_offset, 0, axis, keepdims=False
        ),
        pos_lo=state.pos_lo.min(axis),
        pos_hi=state.pos_hi.max(axis),
        neg_lo=state.neg_lo.min(axis),
        neg_hi=state.neg_hi.max(axis),
        neg_total=state.neg_total.sum(axis),
        tile_sums=state.tile_sums.sum(axis),
    )


def overflow_risk(spec: SketchSpec, state: SketchState):
    """Per-stream largest accumulator mass vs the exact-accumulation ceiling.

    Returns ``(max_mass[N], fraction[N])`` where ``max_mass`` is the
    largest bin-dtype accumulator of the stream -- the hottest bin, the
    zero bucket, ``neg_total``, and ``count`` itself (total mass, which
    always saturates/wraps first) -- and the ceiling is the bin dtype's
    exact-accumulation bound: 2**24 for f32 (unit adds round away past
    it), ``iinfo.max`` for integer bins.  The overflow analog of the
    collapse counters (VERDICT r2 item 3): poll it between batches and
    switch to ``bin_dtype=jnp.int32`` when the f32 fraction approaches 1.
    Integer-bin headroom is a *hard* bound on the whole stream including
    any later merges -- int32 addition wraps silently, so a fold of shards
    must keep every merged bin/counter under ``iinfo.max`` (budget
    per-shard headroom by the planned fan-in; f32 bins merely lose unit
    precision past their ceiling, int32 bins corrupt).
    """
    m = jnp.maximum(state.bins_pos.max(-1), state.bins_neg.max(-1))
    m = jnp.maximum(m, state.zero_count)
    # count (total mass) is itself a bin-dtype accumulator and is >= any
    # single bin, so it always saturates/wraps first -- monitoring only the
    # hottest bin would understate risk by up to n_bins x.
    m = jnp.maximum(m, jnp.maximum(state.count, state.neg_total)).astype(
        spec.dtype
    )
    if spec.bins_integer:
        ceiling = float(jnp.iinfo(spec.bin_dtype).max)
    else:
        # Exact integer accumulation holds through 2**(mantissa bits + 1).
        ceiling = float(2 ** (jnp.finfo(spec.bin_dtype).nmant + 1))
    return m, m / jnp.asarray(ceiling, spec.dtype)


# ---------------------------------------------------------------------------
# Adaptive window: recenter / auto-offset (VERDICT r2 item 2)
# ---------------------------------------------------------------------------


# Temp budget for stream-chunked ops: the recenter scatter and the ingest
# kernel's histogram delta materialize O(chunk x n_bins) f32/int32
# intermediates; 2**25 elements keeps each around 128 MB.  At 1M x 512 the
# UNchunked scatter's temps alone are ~8.5 GB -- a 1M-stream merge_aligned
# ran out of HBM outright (measured: "Used 16.57G of 15.75G hbm") before
# chunking, and two live 1M facades left ingest no headroom either.
_CHUNK_ELEMS = 1 << 25


def _stream_chunk(n_streams: int, n_bins: int) -> int:
    """Chunk length for bounded-memory stream chunking; 0 = don't chunk.

    The SINGLE place the chunking policy lives.  Chunks are 128-aligned
    (the Pallas engines' stream-block quantum: full chunks stay
    kernel-eligible, and when ``n_streams`` is itself 128-aligned so is
    the remainder chunk).  Chunking only engages when it buys at least a
    2x temp reduction -- any stream count qualifies, remainder included
    (1,000,000 = 7 x 131,072 + 82,432, not just powers of two).
    """
    target = max(128, (_CHUNK_ELEMS // max(n_bins, 1)) // 128 * 128)
    if n_streams <= 2 * target:
        return 0
    return target


def _map_stream_chunks(fn, n_streams: int, n_bins: int, *operands):
    """Run a per-stream-independent op in bounded-memory stream chunks.

    ``fn(*chunk_operands)`` maps over ``lax.map`` chunks of the leading
    (stream) axis (XLA sequences them, bounding peak temp memory at one
    chunk's worth), with a ragged tail handled by one direct call.  No-op
    (direct call) when the whole batch fits the budget.
    """
    chunk = _stream_chunk(n_streams, n_bins)
    if not chunk:
        return fn(*operands)
    k, rem = divmod(n_streams, chunk)
    head = n_streams - rem

    # Slice chunks INSIDE the mapped body (dynamic_slice per step), never
    # via upfront reshape copies of the operands -- those would add a full
    # state footprint per operand and defeat the bounded-memory goal.
    def one_chunk(start):
        sl = lambda x: jax.lax.dynamic_slice_in_dim(x, start, chunk, axis=0)
        return fn(*(jax.tree.map(sl, o) for o in operands))

    out = jax.lax.map(one_chunk, jnp.arange(k, dtype=jnp.int32) * chunk)
    out = jax.tree.map(
        lambda x: x.reshape((head,) + x.shape[2:]), out
    )
    if not rem:
        return out
    tail = fn(*(jax.tree.map(lambda x: x[head:], o) for o in operands))
    return jax.tree.map(
        lambda a, b: jnp.concatenate([a, b], axis=0), out, tail
    )


def recenter(
    spec: SketchSpec, state: SketchState, new_key_offset: jax.Array
) -> SketchState:
    """Slide each stream's key window to ``new_key_offset`` (scalar or [N]).

    The device analog of the reference stores' ``_shift_bins`` /
    ``_center_bins``: bin mass moves to its new position within the window;
    mass whose key falls outside the new window folds into the nearest edge
    bin (mass conserved -- the collapsing-store invariant), and the collapse
    counters record it.  ``new_key_offset`` is a *traced* value, so one
    compilation serves every shift, including per-stream shifts.

    Counter note: mass that was already collapsed into an edge bin is
    indistinguishable from true edge-key mass, so a fold re-counts it --
    ``collapsed_low/high`` are upper bounds on resolution-lost mass once a
    window has both collapsed and recentered.

    Cost: one scatter-add pass per store, in bounded-memory stream chunks
    (rare op; pair with the facade policies rather than calling per batch).
    """
    new_off = jnp.broadcast_to(
        jnp.asarray(new_key_offset, jnp.int32), state.key_offset.shape
    )
    return _map_stream_chunks(
        functools.partial(_recenter_body, spec),
        state.n_streams,
        spec.n_bins,
        state,
        new_off,
    )


def _recenter_body(
    spec: SketchSpec, state: SketchState, new_off: jax.Array
) -> SketchState:
    shift = new_off - state.key_offset  # [N]; new_idx = old_idx - shift
    n_bins = spec.n_bins
    iota = jnp.arange(n_bins, dtype=jnp.int32)
    tgt = iota[None, :] - shift[:, None]  # [N, B] target index of old bin i
    below = tgt < 0
    above = tgt > n_bins - 1
    idx = jnp.clip(tgt, 0, n_bins - 1)

    def _roll_row(bins_row, idx_row):
        return jnp.zeros_like(bins_row).at[idx_row].add(bins_row)

    roll = jax.vmap(_roll_row)
    signed = state.bins_pos + state.bins_neg
    new_pos = roll(state.bins_pos, idx)
    new_neg = roll(state.bins_neg, idx)
    # Recenter streams every bin anyway, so the occupied bounds re-derive
    # exactly from the rolled bins (tighter than shifting the old bounds,
    # which would keep conservative slack across repeated recenters).
    pos_lo, pos_hi = _occupied_bounds(new_pos)
    neg_lo, neg_hi = _occupied_bounds(new_neg)
    return SketchState(
        bins_pos=new_pos,
        bins_neg=new_neg,
        zero_count=state.zero_count,
        count=state.count,
        sum=state.sum,
        min=state.min,
        max=state.max,
        collapsed_low=state.collapsed_low + jnp.where(below, signed, 0).sum(-1),
        collapsed_high=state.collapsed_high
        + jnp.where(above, signed, 0).sum(-1),
        key_offset=new_off,
        pos_lo=pos_lo,
        pos_hi=pos_hi,
        neg_lo=neg_lo,
        neg_hi=neg_hi,
        neg_total=state.neg_total,
        # The roll streams every bin anyway: recompute the summaries
        # exactly from the rolled bins (also resets any accumulated ULP
        # drift between summaries and bins in float mode).
        tile_sums=tile_sums_of(new_pos, new_neg),
    )


def merge_aligned(spec: SketchSpec, a: SketchState, b: SketchState) -> SketchState:
    """``merge`` for operands whose windows may have drifted apart.

    Both operands recenter onto a common per-stream target window, then
    merge elementwise.  The target is ``a``'s offset where ``a`` holds any
    binned mass, else ``b``'s -- so merging into an empty (e.g. freshly
    constructed, auto-center still pending) batch adopts the occupied
    operand's window instead of dragging its mass back to the default
    window's edges.  Where offsets already agree the shifts are no-ops.
    This is the alignment-safe semantics every merge seam carries
    (``BatchedDDSketch.merge`` streams the same body through its chunked
    in-place dispatch): adaptive windows make equal offsets a runtime
    property, not a spec-level guarantee.
    """
    # Chunked over streams: the two recenter scatters' temps would
    # otherwise stack on top of both full operands (OOM at 1M x 512).
    return _map_stream_chunks(
        functools.partial(_merge_aligned_body, spec), a.n_streams,
        spec.n_bins, a, b,
    )


def _merge_aligned_body(
    spec: SketchSpec, a_: SketchState, b_: SketchState
) -> SketchState:
    a_binned = (a_.count - a_.zero_count) > 0
    target = jnp.where(a_binned, a_.key_offset, b_.key_offset).astype(
        jnp.int32
    )
    return merge(
        spec,
        _recenter_body(spec, a_, jnp.broadcast_to(target, a_.key_offset.shape)),
        _recenter_body(spec, b_, jnp.broadcast_to(target, b_.key_offset.shape)),
    )


def _center_bin(spec: SketchSpec) -> int:
    """The bin auto-centering targets: the *midpoint of a 128-bin tile*.

    ``n_bins // 2`` itself is a tile boundary (128 | n_bins), so centering
    a tight distribution there makes its occupancy straddle two of the
    windowed query's column tiles and double its HBM read.  Nudging the
    target to the adjacent tile midpoint keeps any span <= 128 bins inside
    ONE tile (measured: the straddle costs ~2x query latency on
    concentrated telemetry) at the cost of 64 bins of asymmetric headroom
    -- irrelevant to collapse behavior at 512+ bins.  Narrow windows
    (< 512 bins) keep the symmetric center: they span few tiles anyway and
    64 bins of lost headroom would matter.
    """
    half = spec.n_bins // 2
    return half - 64 if spec.n_bins >= 512 else half


def auto_offset(
    spec: SketchSpec,
    state: SketchState,
    values: jax.Array,
    weights: Optional[jax.Array] = None,
) -> jax.Array:
    """Per-stream window offsets centered on a value batch -> [N] int32.

    The first-batch policy (VERDICT r2 item 2 / weak 3): center each
    stream's window on the *median* key of its first batch (robust against
    outliers; a mean would let one 1e30 drag the window off the data).
    ``weights <= 0`` lanes are padding (same contract as :func:`add`) and
    are excluded from the median, so ragged batches padded per the
    documented recipe do not drag the window toward the pad value.  Streams
    with no live nonzero finite values in the batch keep their current
    offset.  Derive-then-ingest: pass the result through :func:`recenter`
    (trivially cheap on an empty state) before the first :func:`add`.
    """
    v = jnp.asarray(values).astype(spec.dtype)
    tiny = jnp.asarray(mapping_zero_threshold(v.dtype), v.dtype)
    nonzero = jnp.abs(v) >= tiny  # NaN fails -> excluded
    if weights is not None:
        live = jnp.broadcast_to(jnp.asarray(weights, spec.dtype), v.shape) > 0
        nonzero = jnp.logical_and(nonzero, live)
    absv = jnp.where(nonzero, jnp.abs(v), jnp.asarray(1.0, spec.dtype))
    keys = spec.mapping.key_array(absv)
    # Median via sort with +BIG padding on dead lanes: the live values pack
    # to the left, so the median of n live lanes sits at index (n-1)//2.
    big = jnp.int32(2**30)
    ksort = jnp.sort(jnp.where(nonzero, keys, big), axis=-1)
    n_live = nonzero.sum(-1)  # [N]
    mid = jnp.maximum((n_live - 1) // 2, 0)
    med = jnp.take_along_axis(ksort, mid[:, None].astype(jnp.int32), axis=-1)[:, 0]
    centered = med - jnp.int32(_center_bin(spec))
    return jnp.where(n_live > 0, centered, state.key_offset).astype(jnp.int32)


def data_center_offsets(spec: SketchSpec, state: SketchState) -> jax.Array:
    """Window offsets centering each stream on its binned-mass median key.

    The derivation half of :func:`recenter_to_data`, exposed so the
    distributed tier can compute targets from a FOLDED state and broadcast
    one recenter to every partial.  Streams with no binned mass keep their
    offset.
    """
    mass = state.bins_pos + state.bins_neg  # [N, B]
    total = mass.sum(-1)
    cum = jnp.cumsum(mass, axis=-1)
    # Smallest index with cum >= total/2 = #(cum < total/2).
    center = (cum < total[:, None] * 0.5).sum(-1).astype(jnp.int32)
    return jnp.where(
        total > 0,
        state.key_offset + center - jnp.int32(_center_bin(spec)),
        state.key_offset,
    )


def recenter_to_data(spec: SketchSpec, state: SketchState) -> SketchState:
    """Recenter each stream's window on its binned-mass median key.

    The steady-state policy: after collapse counters report loss (window
    mispositioned for the data that followed), recentering repositions the
    window for *future* ingest -- mass already folded into an edge bin stays
    there (resolution, once lost, is lost; same as the reference's
    collapsing stores).  Centering on the *mass median* (not the occupied
    span's midpoint) makes the policy converge when recent data piles up at
    one edge: the median chases the pile, and a following
    :func:`maybe_recenter <BatchedDDSketch.maybe_recenter>` round brings the
    window fully onto it.
    """
    return recenter(spec, state, data_center_offsets(spec, state))


# ---------------------------------------------------------------------------
# Facade
# ---------------------------------------------------------------------------


class BatchedDDSketch:
    """Stateful facade over the pure batched kernel functions.

    The device-tier public API: reference-shaped method names
    (``add`` / ``get_quantile_value`` / ``merge`` -- SURVEY.md section 2 row
    2), vectorized over ``n_streams`` sketches.  Ingest donates the state
    pytree so XLA mutates bins in place.

    Failure modes (docs/DESIGN.md section 8): a Pallas query
    lowering/compile failure degrades down the
    ``overlap -> tiles -> windowed -> wxla -> xla`` ladder (recorded in
    ``resilience.health()``; only an ``xla``-floor failure re-raises), a
    Pallas ingest failure demotes to the XLA scatter path and replays
    the batch -- a non-stock ingest construction rung
    (``kernels.INGEST_VARIANTS``) failing first demotes to the stock
    rung, also ledger-recorded; empty streams and out-of-range
    quantiles answer NaN;
    invalid construction raises ``SpecError`` and unequal-spec merges
    raise ``UnequalSketchParametersError``.
    """

    def __init__(
        self,
        n_streams: int,
        relative_accuracy: float = DEFAULT_REL_ACC,
        mapping: str = "logarithmic",
        n_bins: int = DEFAULT_N_BINS,
        key_offset: Optional[int] = None,
        spec: Optional[SketchSpec] = None,
        state: Optional[SketchState] = None,
        engine: str = "auto",
        auto_recenter: Optional[bool] = None,
        bin_dtype=None,
    ):
        # Auto-recenter policy: center each stream's window on its first
        # batch (median key) unless the caller pinned the window explicitly
        # -- an explicit ``key_offset`` (or full spec / pre-built state) is a
        # deliberate window choice and is honored as-is.
        if auto_recenter is None:
            auto_recenter = key_offset is None and spec is None and state is None
        if spec is None:
            spec = SketchSpec(
                relative_accuracy=relative_accuracy,
                mapping_name=mapping,
                n_bins=n_bins,
                key_offset=key_offset,
                bin_dtype=bin_dtype,
            )
        self.spec = spec
        self._state = init(spec, n_streams) if state is None else state
        self._auto_recenter_pending = bool(auto_recenter) and state is None
        self._policy_stale = False
        from sketches_tpu import kernels

        use_pallas, interpret = kernels.select_engine(spec, n_streams, engine)
        self.engine = "pallas" if use_pallas else "xla"
        self._op_jits = {}
        # The XLA add stays available even on the Pallas engine: it takes
        # the non-128-aligned batch widths the kernels do not.
        self._add_xla = functools.partial(add, spec)
        if use_pallas:
            # One ingest body per construction rung (kernels.INGEST_VARIANTS)
            # so the jit cache keys on the variant; ``_add_pallas`` is the
            # stock rung and doubles as the engine-alive flag.
            self._add_pallas = functools.partial(
                kernels.add, spec, interpret=interpret, variant="stock"
            )
            self._add_pallas_variant = lambda v: functools.partial(
                kernels.add, spec, interpret=interpret, variant=v
            )
            self._batch_ok = lambda s: kernels.supports(spec, n_streams, s)
        else:
            self._add_pallas = None
            self._add_pallas_variant = None
            self._batch_ok = lambda s: False
        # Ingest construction-rung ladder state: a variant lowering failure
        # demotes this facade to the stock rung for good (recorded in
        # resilience.health()), mirroring the query ladder's discipline.
        self._ingest_variant_demoted = False
        # Query engines, fastest-eligible first (see _query_fn):
        # * overlap Pallas kernel -- the tile-list walk with manual
        #   double-buffered async copies (DMA ring + cross-block
        #   lookahead), hiding the fold/count/decode under the strided
        #   reads (same plan + parity contract as the tile engine);
        # * tile-list Pallas kernel -- hierarchical rank selection off the
        #   state's tile summaries; HBM bytes scale with the number of
        #   distinct crossing tiles (float bins, TPU, small Q);
        # * windowed Pallas kernel -- walks the occupied span (float bins,
        #   TPU, wide Q);
        # * windowed XLA -- occupied-span slice of the portable rank walk
        #   (any engine; THE path for integer bins, whose compare runs in
        #   integer space, exact past 2**24);
        # * full XLA quantile -- ragged n_bins fallback.
        # Plans (window position, store participation, tile-list width)
        # each cost one tiny host fetch after a state mutation and are
        # cached until the next ingest/merge/recenter.
        self._pallas_query = use_pallas and not spec.bins_integer
        self._interpret = interpret
        # Engine-health ladder state: tiers this facade demoted away from
        # after a lowering/compile failure (resilience.QUERY_LADDER order).
        # Every demotion is recorded in resilience.health(); the floor
        # (the portable full-XLA quantile) never demotes -- it re-raises.
        self._query_disabled: set = set()
        self._health_component = "batched"
        self._windowed_jits = {}
        self._tiles_jits = {}
        self._overlap_jits = {}
        self._wxla_jits = {}
        self._window_plan = None
        self._tile_plans = {}
        self._wxla_ok = spec.n_bins % 128 == 0
        self._quantile = jax.jit(functools.partial(quantile, spec))
        self._merge = jax.jit(
            functools.partial(merge, spec), donate_argnums=(0,)
        )
        self._merge_body = functools.partial(_merge_aligned_body, spec)
        # Derive-offsets-from-this-batch, recenter masked streams, ingest --
        # one dispatch.  Used for the first batch (mask = still-empty
        # streams) and for maybe_recenter's armed follow-up (mask = drifting
        # streams, mass and all -- drift chasing moves occupied windows on
        # purpose).
        def _recenter_add(st, values, weights, mask):
            offs = auto_offset(spec, st, values, weights)
            st = recenter(spec, st, jnp.where(mask, offs, st.key_offset))
            return add(spec, st, values, weights)

        self._add_recentering = _recenter_add
        self._pending_recenter_mask: Optional[np.ndarray] = None
        # Collapse/binned-mass snapshots for maybe_recenter's delta test.
        self._policy_collapsed = np.zeros((n_streams,), np.float64)
        self._policy_binned = np.zeros((n_streams,), np.float64)
        self._recenter = jax.jit(
            functools.partial(recenter, spec), donate_argnums=(0,)
        )
        self._recenter_to_data = jax.jit(
            functools.partial(recenter_to_data, spec), donate_argnums=(0,)
        )

    # -- core API (reference-shaped, batched) ------------------------------
    def add(self, values, weights=None) -> "BatchedDDSketch":
        """Ingest ``values[n_streams, S]``; returns self for chaining.

        A 1-D ``values`` means one value per stream.  ``weights <= 0`` entries
        are inert padding (see :func:`add`); pass ``validate=True`` via
        :meth:`add_validated` to reject negative weights eagerly instead.
        """
        _t0 = telemetry.clock() if telemetry._ACTIVE else None
        _p0 = telemetry.clock() if profiling._ACTIVE else None
        _eng = "xla"
        values = jnp.asarray(values)
        if weights is not None:
            # Keep the weights' own dtype (the kernel casts to spec.dtype);
            # casting to values.dtype would truncate fractional weights when
            # values are integer-typed.
            weights = jnp.asarray(weights, self.spec.dtype)
            if weights.ndim == 1:  # per-stream weights, like 1-D values
                weights = weights[:, None]
        if values.ndim == 1:
            values = values[:, None]
        if self._auto_recenter_pending or self._pending_recenter_mask is not None:
            # First batch, or a maybe_recenter-armed batch: derive per-stream
            # offsets from THIS batch's median keys, recenter the masked
            # streams, and ingest -- one fused dispatch.  Subsequent adds
            # take the fast paths.
            armed_by_policy = self._pending_recenter_mask is not None
            if self._auto_recenter_pending:
                # First-batch auto-center applies only to streams with no
                # binned mass: a populated state assigned after construction
                # (checkpoint restore via ``sk.state = ...``) must keep its
                # windows -- recentering it onto this batch's medians would
                # silently collapse the restored mass (review r4).  On a
                # truly fresh facade this is the all-ones mask it always was.
                st = self.state
                mask = (st.count - st.zero_count) <= 0
                if armed_by_policy:
                    mask = jnp.logical_or(
                        mask, jnp.asarray(self._pending_recenter_mask)
                    )
            else:
                mask = jnp.asarray(self._pending_recenter_mask)
            self._auto_recenter_pending = False
            self._pending_recenter_mask = None
            _eng = "recenter"
            self._stream_op("recenter_add", self._add_recentering, values, weights, mask)
            if armed_by_policy:
                # Re-baseline the policy snapshots past the fold the armed
                # recenter itself produced (old edge piles leaving the new
                # window count as collapse); without this the next
                # maybe_recenter misreads the fold as fresh collapse and
                # fires one spurious extra round.  One host sync, on the
                # (rare) armed add only.
                self._policy_collapsed = np.asarray(
                    self.state.collapsed_low + self.state.collapsed_high,
                    np.float64,
                )
                self._policy_binned = np.asarray(
                    self.state.count - self.state.zero_count, np.float64
                )
        elif (
            self._add_pallas is not None
            and self._batch_ok(values.shape[-1])
            # Weighted integer-mode calls need the XLA path: the kernel's
            # f32 deltas are only guaranteed exact for unit weights (see
            # kernels.add).
            and not (self.spec.bins_integer and weights is not None)
        ):
            from sketches_tpu import kernels

            variant = (
                "stock"
                if self._ingest_variant_demoted
                else kernels.choose_ingest_engine(
                    self.spec, weighted=weights is not None
                )
            )
            try:
                # The whole-kernel fault site sits ABOVE the rung ladder:
                # a pallas.ingest fault means "this engine is gone" and
                # demotes straight to XLA, whatever rung was selected.
                if faults._ACTIVE:
                    faults.inject(faults.PALLAS_INGEST)
                if variant != "stock":
                    # Non-stock construction rung: a lowering/compile
                    # failure here demotes to the stock rung (health
                    # ledger), NOT all the way to XLA -- the rungs are
                    # bit-identical, so the replay is exact (failures
                    # surface at compile time).
                    try:
                        if faults._ACTIVE:
                            faults.inject(
                                faults.PALLAS_INGEST_VARIANT, tier=variant
                            )
                        _eng = f"pallas:{variant}"
                        self._stream_op(
                            f"add_pallas:{variant}",
                            self._add_pallas_variant(variant),
                            values, weights,
                        )
                    except Exception as ev:
                        self._ingest_variant_demoted = True
                        resilience.record_downgrade(
                            f"{self._health_component}.ingest_variant",
                            variant, "stock", repr(ev),
                        )
                        variant = "stock"
                if variant == "stock":
                    _eng = "pallas"
                    self._stream_op(
                        "add_pallas", self._add_pallas, values, weights
                    )
            except Exception as e:
                # Pallas ingest lost (lowering/compile failure or
                # injected fault): demote this facade to the XLA
                # scatter path for good and replay the batch.
                # Failures surface at compile time -- before any
                # donated buffer executes -- so the state is untouched
                # and the replay is exact; the one pathological
                # exception (an *execution* failure between chunks of
                # a chunked dispatch) leaves donated buffers consumed,
                # which the replay below then reports loudly instead
                # of double-ingesting.
                self._add_pallas = None
                self._batch_ok = lambda s: False
                resilience.record_downgrade(
                    f"{self._health_component}.ingest", "pallas", "xla",
                    repr(e),
                )
                try:
                    _eng = "xla"
                    self._stream_op(
                        "add_xla", self._add_xla, values, weights
                    )
                except Exception as e2:
                    raise resilience.EngineUnavailable(
                        "ingest failed on both the Pallas and XLA"
                        " engines; state may be partial"
                    ) from e2
        else:
            self._stream_op("add_xla", self._add_xla, values, weights)
        self._invalidate_plans()
        if _t0 is not None:
            telemetry.finish_span(
                "ingest_s", _t0, component="batched", engine=_eng
            )
            telemetry.counter_inc("batched.ingest_batches")
            # Which construction rung actually served (README metric rows
            # ``ingest.variant.*``): the forensic answer to "was this
            # fleet on the packed construction".  Literal names per rung:
            # the telemetry-names lint cross-checks each against the
            # declared inventory.
            if _eng == "pallas":
                telemetry.counter_inc("ingest.variant.stock")
            elif _eng == "pallas:packed":
                telemetry.counter_inc("ingest.variant.packed")
            elif _eng == "pallas:hifold":
                telemetry.counter_inc("ingest.variant.hifold")
            elif _eng == "pallas:cmpfree":
                telemetry.counter_inc("ingest.variant.cmpfree")
        if tracing._ACTIVE:
            tracing.record_event(
                "engine.ingest", engine=_eng, component="batched"
            )
        # Device-clocked attribution AFTER the host span closes: the
        # telemetry span keeps measuring submission, the profiling
        # record blocks for execution.
        if _p0 is not None:
            profiling.record("ingest", _eng, _p0, self.state)
        if accuracy._ACTIVE:
            accuracy.observe_ingest(self, values, weights)
        return self

    def add_validated(self, values, weights=None) -> "BatchedDDSketch":
        """Like :meth:`add` but raises on negative weights (host-tier parity).

        Costs a host sync on ``weights``; keep off the hot path.
        """
        if weights is not None and bool(jnp.any(jnp.asarray(weights) < 0)):
            raise SketchValueError("weights must be non-negative (0 = padding)")
        return self.add(values, weights)

    def _invalidate_plans(self) -> None:
        self._window_plan = None
        self._tile_plans = {}

    def _query_fn(self, qs_tuple: tuple):
        """The dispatched query callable (engine ladder in ``__init__``)."""
        return self._query_choice(qs_tuple)[1]

    def _query_choice(self, qs_tuple: tuple, extra_disabled: frozenset = frozenset()):
        """The query dispatch -> ``(tier, fn)`` (engine ladder in
        ``__init__``; ``tier`` names the resilience ladder rung so a
        failure can demote exactly the engine that failed).

        ``extra_disabled`` adds caller-scoped tier exclusions on top of
        the facade's own health ladder -- the serving tier's circuit
        breaker and deadline floor-skip ride this without mutating the
        facade's persistent demotion state.  Each plan costs one small
        host fetch the first query after a state mutation; repeat
        queries reuse it.  Jits cache per static plan shape -- a
        window/tile-list that merely *slides* recompiles nothing
        (positions are traced).
        """
        from sketches_tpu import kernels

        q_total = len(qs_tuple)
        disabled = self._query_disabled
        if extra_disabled:
            disabled = self._query_disabled | extra_disabled
        if self._pallas_query and "windowed" not in disabled:
            if self._window_plan is None:
                self._window_plan = kernels.plan_state_window(
                    self.spec, self.state
                )
            lo_w, n_w, w_t, with_neg = self._window_plan
            # Eligibility and engine choice both live in kernels
            # (tile_query_eligible / choose_query_engine) so the two
            # facades can never drift apart on the policy (ADVICE r4).
            if "tiles" not in disabled and kernels.tile_query_eligible(
                self.spec, q_total, self._window_plan
            ):
                # Tile-list plan (list width + store participation)
                # depends on the requested quantiles: cached per qs tuple.
                plan = self._tile_plans.get(qs_tuple)
                if plan is None:
                    plan = kernels.plan_tile_query(
                        self.spec, self.state, jnp.asarray(qs_tuple)
                    )
                    self._tile_plans[qs_tuple] = plan
                k_tiles, with_neg_t = plan
                pick = kernels.choose_query_engine(
                    self._window_plan, plan,
                    overlap_ok=kernels.overlap_enabled()
                    and "overlap" not in disabled,
                )
                if pick == "overlap":
                    key = (k_tiles, with_neg_t, q_total)
                    fn = self._overlap_jits.get(key)
                    if fn is None:
                        fn = jax.jit(
                            functools.partial(
                                kernels.fused_quantile_tiles_overlap,
                                self.spec,
                                k_tiles=k_tiles,
                                with_neg=with_neg_t,
                                interpret=self._interpret,
                            )
                        )
                        self._overlap_jits[key] = fn
                    return ("overlap", fn)
                if pick == "tiles":
                    key = (k_tiles, with_neg_t, q_total)
                    fn = self._tiles_jits.get(key)
                    if fn is None:
                        fn = jax.jit(
                            functools.partial(
                                kernels.fused_quantile_tiles,
                                self.spec,
                                k_tiles=k_tiles,
                                with_neg=with_neg_t,
                                interpret=self._interpret,
                            )
                        )
                        self._tiles_jits[key] = fn
                    return ("tiles", fn)
            key = (n_w, w_t, with_neg, q_total)
            fn = self._windowed_jits.get(key)
            if fn is None:
                fn = jax.jit(
                    functools.partial(
                        kernels.fused_quantile_windowed,
                        self.spec,
                        n_wblocks=n_w,
                        w_tiles=w_t,
                        with_neg=with_neg,
                        interpret=self._interpret,
                    )
                )
                self._windowed_jits[key] = fn
            return (
                "windowed",
                functools.partial(
                    lambda f, lo, state, qs: f(state, qs, lo), fn, lo_w
                ),
            )
        if self._wxla_ok and "wxla" not in disabled:
            if self._window_plan is None:
                self._window_plan = kernels.plan_state_window(
                    self.spec, self.state
                )
            lo_w, n_w, w_t, with_neg = self._window_plan
            tiles_window = n_w * w_t
            key = (tiles_window, with_neg, q_total)
            fn = self._wxla_jits.get(key)
            if fn is None:
                fn = jax.jit(
                    functools.partial(
                        kernels.quantile_windowed_xla,
                        self.spec,
                        n_tiles_window=tiles_window,
                        with_neg=with_neg,
                    )
                )
                self._wxla_jits[key] = fn
            return (
                "wxla",
                functools.partial(
                    lambda f, lo, state, qs: f(state, qs, lo), fn, lo_w * w_t
                ),
            )
        return ("xla", self._quantile)

    def _run_query(self, qs_tuple: tuple, qs_arr: jax.Array) -> jax.Array:
        """Dispatch a query down the engine ladder, degrading on failure.

        A lowering/compile failure on a Pallas tier (or an injected
        ``pallas.lowering`` fault) demotes this facade to the next tier
        -- recorded in ``resilience.health()`` -- and retries; the floor
        tier re-raises.  Queries are pure (no state mutation), so a retry
        after any failure is always sound.
        """
        return self._run_query_tiered(qs_tuple, qs_arr)[1]

    def _run_query_tiered(
        self, qs_tuple: tuple, qs_arr: jax.Array,
        extra_disabled: frozenset = frozenset(),
    ):
        """:meth:`_run_query` that also reports the resolved tier ->
        ``(tier, values)``; failures degrade identically (the floor
        re-raises)."""
        while True:
            tier, fn = self._query_choice(qs_tuple, extra_disabled)
            try:
                if faults._ACTIVE:
                    faults.inject(faults.PALLAS_LOWERING, tier=tier)
                _t0 = telemetry.clock() if telemetry._ACTIVE else None
                _p0 = telemetry.clock() if profiling._ACTIVE else None
                out = fn(self.state, qs_arr)
                if _t0 is not None:
                    telemetry.finish_span(
                        "query_s", _t0, component="batched", tier=tier
                    )
                if _p0 is not None:
                    profiling.record("query", tier, _p0, out)
                if tracing._ACTIVE:
                    # The resolved rung, on the request's trace: the
                    # forensic answer to "which engine actually served".
                    tracing.record_event(
                        "engine.query", tier=tier, component="batched"
                    )
                return tier, out
            except Exception as e:
                if not self._demote_query(tier, e):
                    raise

    def _demote_query(self, tier: str, exc: BaseException) -> bool:
        nxt = resilience.demote_query_tier(self._query_disabled, tier)
        if nxt is None:
            return False
        resilience.record_downgrade(
            f"{self._health_component}.query", tier, nxt, repr(exc)
        )
        return True

    def get_quantile_value(self, quantile: float) -> jax.Array:
        """Per-stream value at ``quantile`` -> ``[n_streams]`` (NaN if empty)."""
        return self._run_query(
            (float(quantile),), jnp.asarray([quantile])
        )[:, 0]

    def get_quantile_values(self, quantiles: Sequence[float]) -> jax.Array:
        """Fused multi-quantile (e.g. p50/p90/p99/p999) -> ``[n_streams, Q]``."""
        qs = [float(q) for q in quantiles]
        return self._run_query(tuple(qs), jnp.asarray(qs))

    def get_quantile_values_resolved(
        self, quantiles: Sequence[float], disabled_tiers: Sequence[str] = (),
    ):
        """Fused multi-quantile that also names the engine tier that
        answered -> ``(tier, [n_streams, Q])``.

        ``disabled_tiers`` excludes ladder rungs for THIS call only (the
        serving tier's circuit breaker / deadline floor-skip), without
        touching the facade's persistent health-ladder state.  Failures
        degrade down the remaining rungs exactly like
        :meth:`get_quantile_values`; disabling everything above the
        ``xla`` floor is always answerable, and a floor failure still
        re-raises.  Empty streams answer NaN.
        """
        qs = [float(q) for q in quantiles]
        return self._run_query_tiered(
            tuple(qs), jnp.asarray(qs), frozenset(disabled_tiers)
        )

    def merge(self, other: "BatchedDDSketch") -> "BatchedDDSketch":
        """Fold ``other`` into self (consumes neither spec; checks mergeability).

        Always alignment-safe: the operands recenter onto a common
        per-stream window first (a no-op shift where the windows already
        agree).  The ground truth for alignment is the *state's* per-stream
        offsets -- never a host-side flag, which a checkpoint restore or
        ``BatchedDDSketch(state=...)`` rebuild would lose.
        """
        if not self.mergeable(other):
            from sketches_tpu.ddsketch import UnequalSketchParametersError

            raise UnequalSketchParametersError(
                "Cannot merge two batched sketches with different specs"
            )
        _t0 = telemetry.clock() if telemetry._ACTIVE else None
        _p0 = telemetry.clock() if profiling._ACTIVE else None
        # Guarded integrity seam: snapshot operand fingerprints before
        # the donated merge consumes the buffers, verify the result
        # against them after (raise/quarantine per the armed mode).
        _ipre = (
            integrity.premerge(self.spec, self.state, other.state)
            if integrity._ACTIVE
            else None
        )
        self._stream_op("merge_aligned", self._merge_body, other.state)
        if _ipre is not None:
            integrity.postmerge(self.spec, self.state, _ipre, seam="batched.merge")
        if _t0 is not None:
            telemetry.finish_span("merge_s", _t0, component="batched")
        if _p0 is not None:
            profiling.record("fold", "merge", _p0, self.state)
        self._invalidate_plans()
        # A merge that brings mass populates the batch: a still-pending
        # first-batch auto-center would recenter away from that mass.  An
        # empty operand (e.g. a reduce's identity element) leaves the
        # pending center intact.
        if self._auto_recenter_pending and bool(jnp.any(other.state.count > 0)):
            self._auto_recenter_pending = False
        return self

    def _stream_op(self, key, body, *args) -> None:
        """``state <- body(state, *args)``, chunked over streams when large.

        Full-batch device ops materialize O(n_streams x n_bins) temps (the
        ingest kernel's histogram delta alone equals the state size, and a
        whole-batch merge keeps THREE full states live -- measured OOM on
        a 16 GB chip at 1M x 512 with two facades).  Big batches therefore
        run as K dispatches, each slicing a stream chunk, applying
        ``body``, and updating the donated full state in place; a ragged
        tail runs as one extra dispatch at its own static width.  Small
        batches keep the original single-dispatch graph.  ``args`` may be
        arrays or pytrees (e.g. another SketchState); every leaf with a
        leading stream axis is sliced per chunk, everything else passes
        through whole.
        """
        chunk = _stream_chunk(self.n_streams, self.spec.n_bins)
        if not chunk:
            fn = self._op_jits.get(key)
            if fn is None:
                fn = jax.jit(body, donate_argnums=(0,))
                self._op_jits[key] = fn
            # Internal mutators assign _state directly (callers clear the
            # window plan themselves); the ``state`` setter is the external
            # choke point and also arms the policy re-baseline, which must
            # NOT fire on ordinary ingest.
            self._state = fn(self.state, *args)
            return
        n = self.n_streams

        def make(chunk_len):
            def chunked(full_state, start, *full_args):
                sl = lambda x: jax.lax.dynamic_slice_in_dim(
                    x, start, chunk_len, axis=0
                )
                sl_leaf = lambda x: (
                    sl(x)
                    if getattr(x, "ndim", 0) >= 1 and x.shape[0] == n
                    else x
                )
                out = body(
                    jax.tree.map(sl, full_state),
                    *(jax.tree.map(sl_leaf, a) for a in full_args),
                )
                upd = lambda x, u: jax.lax.dynamic_update_slice_in_dim(
                    x, u, start, axis=0
                )
                return jax.tree.map(upd, full_state, out)

            return jax.jit(chunked, donate_argnums=(0,))

        k, rem = divmod(n, chunk)
        fn = self._op_jits.get((key, chunk))
        if fn is None:
            fn = self._op_jits[(key, chunk)] = make(chunk)
        st = self.state
        for i in range(k):
            st = fn(st, i * chunk, *args)
        if rem:
            fn_rem = self._op_jits.get((key, rem))
            if fn_rem is None:
                fn_rem = self._op_jits[(key, rem)] = make(rem)
            st = fn_rem(st, k * chunk, *args)
        self._state = st

    # -- adaptive window ---------------------------------------------------
    def recenter(self, new_key_offset) -> "BatchedDDSketch":
        """Slide the window(s) to ``new_key_offset`` (scalar or [n_streams])."""
        self._state = self._recenter(self.state, jnp.asarray(new_key_offset))
        self._invalidate_plans()
        return self

    def recenter_to_data(self) -> "BatchedDDSketch":
        """Recenter each stream's window on its binned-mass median key."""
        self._state = self._recenter_to_data(self.state)
        self._invalidate_plans()
        return self

    def overflow_risk(self):
        """(max_bin_mass[N], fraction-of-exact-ceiling[N]) -- see
        :func:`overflow_risk`.  Poll between batches like the collapse
        counters; a fraction near 1 calls for ``bin_dtype=jnp.int32``."""
        return overflow_risk(self.spec, self.state)

    def collapsed_fraction(self) -> jax.Array:
        """Per-stream fraction of binned mass that hit a window edge -> [N].

        The observability signal for the recenter policy; reading it forces
        a host sync, so poll it between batches, not per add.
        """
        binned = (self.state.count - self.state.zero_count).astype(
            self.spec.dtype
        )
        collapsed = (
            self.state.collapsed_low + self.state.collapsed_high
        ).astype(self.spec.dtype)
        return collapsed / jnp.maximum(binned, 1)

    def maybe_recenter(self, threshold: float = 0.01) -> bool:
        """Arm a recenter for streams whose *recent* collapse exceeds ``threshold``.

        Compares collapse growth against binned-mass growth since the
        previous call (deltas, not cumulative counters -- one bad episode
        must not keep the policy firing forever).  Streams over the
        threshold recenter on their **next** batch's median key (the next
        real data is the one sound signal for where the new regime lives;
        mass already folded into an edge carries a phantom key and would
        anchor any state-derived center on history).  Convergence is
        therefore one step: arm -> next add recenters onto that batch.

        Returns whether any stream armed.  One host sync per call; a
        typical ingest loop calls this every K batches.  Recentering
        repositions the window for future ingest -- mass already at an edge
        stays there (resolution, once lost, is lost; same as the
        reference's collapsing stores).
        """
        clow = np.asarray(self.state.collapsed_low, np.float64)
        chigh = np.asarray(self.state.collapsed_high, np.float64)
        binned = np.asarray(
            self.state.count - self.state.zero_count, np.float64
        )
        collapsed = clow + chigh
        d_coll = collapsed - self._policy_collapsed
        d_binned = binned - self._policy_binned
        self._policy_collapsed = collapsed
        self._policy_binned = binned
        if self._policy_stale:
            # The state was assigned wholesale since the last baseline
            # (external ``sk.state = ...``): the deltas above compare
            # against a different state's history.  Re-baseline (just done)
            # and start measuring drift from here.
            self._policy_stale = False
            return False
        mask = d_coll > threshold * np.maximum(d_binned, 1.0)
        if mask.any():
            prev = self._pending_recenter_mask
            self._pending_recenter_mask = (
                mask if prev is None else np.logical_or(prev, mask)
            )
            return True
        return False

    def mergeable(self, other: "BatchedDDSketch") -> bool:
        return self.spec == other.spec

    # -- accessors ---------------------------------------------------------
    @property
    def state(self) -> SketchState:
        return self._state

    @state.setter
    def state(self, new_state: SketchState) -> None:
        # ``state`` is deliberately assignable (checkpoint restore, tests,
        # power users) -- the setter is the EXTERNAL choke point that keeps
        # every cache describing the old state honest (internal mutators
        # assign ``_state`` directly and manage their own caches):
        # * the window plan (a stale plan makes the windowed query silently
        #   truncate quantile mass -- ADVICE r3);
        # * the maybe_recenter delta baselines (stale snapshots would
        #   misread the new state's pre-existing collapse as fresh drift and
        #   fire a spurious recenter -- review r4); the next maybe_recenter
        #   call re-baselines instead of comparing.
        # A pending first-batch auto-center needs no flag handling here: its
        # mask excludes streams that already hold binned mass, so an
        # assigned populated state keeps its windows.  An ARMED drift mask,
        # however, was derived from the old state's deltas and would
        # recenter the new state's streams on the next add -- drop it.
        self._state = new_state
        self._invalidate_plans()
        self._policy_stale = True
        self._pending_recenter_mask = None

    @property
    def n_streams(self) -> int:
        return self.state.n_streams

    @property
    def count(self) -> jax.Array:
        return self.state.count

    @property
    def num_values(self) -> jax.Array:
        return self.state.count

    @property
    def sum(self) -> jax.Array:  # noqa: A003 - reference API name
        return self.state.sum

    @property
    def avg(self) -> jax.Array:
        return self.state.sum / self.state.count

    @property
    def relative_accuracy(self) -> float:
        return self.spec.relative_accuracy

    def copy(self) -> "BatchedDDSketch":
        new = BatchedDDSketch(
            self.n_streams,
            spec=self.spec,
            state=jax.tree.map(jnp.copy, self.state),
        )
        # Behavioral state rides along: a copy taken before the first add
        # must still auto-center, an armed recenter must still fire, and the
        # policy's delta baselines must not reset (or the next
        # maybe_recenter would misread cumulative history as fresh growth).
        new._auto_recenter_pending = self._auto_recenter_pending
        new._pending_recenter_mask = (
            None
            if self._pending_recenter_mask is None
            else self._pending_recenter_mask.copy()
        )
        new._policy_collapsed = self._policy_collapsed.copy()
        new._policy_binned = self._policy_binned.copy()
        new._policy_stale = self._policy_stale
        # A demoted engine stays demoted in the copy (the failure that
        # demoted it is a property of the environment, not the instance).
        new._query_disabled = set(self._query_disabled)
        return new

    def __repr__(self) -> str:
        return (
            f"BatchedDDSketch(n_streams={self.n_streams},"
            f" n_bins={self.spec.n_bins},"
            f" relative_accuracy={self.spec.relative_accuracy},"
            f" mapping={self.spec.mapping_name!r})"
        )


# ---------------------------------------------------------------------------
# Host interop
# ---------------------------------------------------------------------------


def to_host_sketches(spec: SketchSpec, state: SketchState):
    """Materialize each stream as a host-tier sketch (for serde / interop).

    Returns a list of ``BaseDDSketch`` with the *spec's* mapping and
    collapsing-lowest stores holding the same bin masses at the same keys;
    quantile queries agree with the device path up to fp rounding.  The
    device-only collapse counters ride along as ``_collapsed_low`` /
    ``_collapsed_high`` attributes so ``from_host_sketches`` can round-trip
    them.

    Bulk path (VERDICT r4 item 6): stores are constructed directly from
    numpy row slices of the occupied span -- the exact state organic
    ``store.add`` growth would reach, without the per-stream per-bin
    Python loop that made 1M-stream materialization take minutes.
    """
    from sketches_tpu.ddsketch import BaseDDSketch
    from sketches_tpu.store import CollapsingLowestDenseStore

    host = jax.device_get(
        (state.bins_pos, state.bins_neg, state.zero_count, state.count,
         state.sum, state.min, state.max, state.collapsed_low,
         state.collapsed_high, state.key_offset)
    )
    (bins_pos, bins_neg, zero_count, count, total, vmin, vmax,
     clow, chigh, koff) = (np.asarray(a) for a in host)
    bins_pos = bins_pos.astype(np.float64)
    bins_neg = bins_neg.astype(np.float64)
    plo, phi = occupied_bounds_np(bins_pos)
    nlo, nhi = occupied_bounds_np(bins_neg)
    # Per-store masses once, vectorized (counters may disagree with the
    # bins by design only in f32 rounding; stores carry the bins' truth).
    pos_count = bins_pos.sum(axis=-1)
    neg_count = bins_neg.sum(axis=-1)
    mapping = mapping_from_name(spec.mapping_name, spec.relative_accuracy)

    def load_store(store, row, lo, hi, mass, off):
        if hi < 0:  # empty store
            return
        lo_k, hi_k = int(lo + off), int(hi + off)
        length = store._get_new_length(lo_k, hi_k)
        seg = np.zeros(length, np.float64)
        seg[: hi - lo + 1] = row[lo : hi + 1]
        store.bins = seg.tolist()
        store.offset = lo_k
        store.min_key = lo_k
        store.max_key = hi_k
        store.count = float(mass)

    sketches = []
    for i in range(state.n_streams):
        sk = BaseDDSketch(
            mapping=mapping,
            store=CollapsingLowestDenseStore(spec.n_bins),
            negative_store=CollapsingLowestDenseStore(spec.n_bins),
        )
        off = int(koff[i])
        load_store(sk.store, bins_pos[i], plo[i], phi[i], pos_count[i], off)
        load_store(
            sk.negative_store, bins_neg[i], nlo[i], nhi[i], neg_count[i], off
        )
        sk._zero_count = float(zero_count[i])
        sk._count = float(count[i])
        sk._sum = float(total[i])
        sk._min = float(vmin[i])
        sk._max = float(vmax[i])
        sk._collapsed_low = float(clow[i])
        sk._collapsed_high = float(chigh[i])
        sketches.append(sk)
    return sketches


def from_host_sketches(spec: SketchSpec, sketches) -> SketchState:
    """Pack host-tier sketches into one batched device state.

    Keys outside the spec window clamp to the edge bins (mass conserved),
    mirroring ingest-side collapse.
    """
    n = len(sketches)
    # f64 staging: the host tier's masses are exact Python floats, and an
    # f32 intermediate would round counts past 2**24 *before* the final
    # cast -- defeating integer-bin specs on this interop path.
    bins_pos = np.zeros((n, spec.n_bins), dtype=np.float64)
    bins_neg = np.zeros((n, spec.n_bins), dtype=np.float64)
    zero = np.zeros((n,), dtype=np.float64)
    count = np.zeros((n,), dtype=np.float64)
    total = np.zeros((n,), dtype=np.float64)
    vmin = np.full((n,), np.inf, dtype=np.float64)
    vmax = np.full((n,), -np.inf, dtype=np.float64)
    clow = np.zeros((n,), dtype=np.float64)
    chigh = np.zeros((n,), dtype=np.float64)
    for i, sk in enumerate(sketches):
        # Same gamma is not enough: all three mappings share gamma at equal
        # alpha but scale the key multiplier differently, so keys are only
        # compatible between identical mapping types.
        if sk.mapping != spec.mapping:
            from sketches_tpu.ddsketch import UnequalSketchParametersError

            raise UnequalSketchParametersError(
                f"Host sketch mapping {sk.mapping!r} does not match batched"
                f" spec mapping {spec.mapping!r}"
            )
        for arr, store in ((bins_pos, sk.store), (bins_neg, sk.negative_store)):
            # Whole-store numpy placement (VERDICT r4 item 6): the store's
            # dense run lands as one slice, with out-of-window mass folded
            # into the edge bins (clamped-ingest semantics).
            row = np.asarray(store.bins, np.float64)
            if row.size == 0:
                continue
            j = np.arange(row.size) + (store.offset - spec.key_offset)
            low = j < 0
            high = j >= spec.n_bins
            mid = ~(low | high)
            low_mass = float(row[low].sum())
            high_mass = float(row[high].sum())
            arr[i, 0] += low_mass
            clow[i] += low_mass
            arr[i, -1] += high_mass
            chigh[i] += high_mass
            arr[i, j[mid]] += row[mid]  # consecutive (unique) indices
        zero[i] = sk.zero_count
        count[i] = sk.count
        total[i] = sk.sum
        vmin[i] = sk._min
        vmax[i] = sk._max
        # Round-trip the device-only collapse counters when present.
        clow[i] += getattr(sk, "_collapsed_low", 0.0)
        chigh[i] += getattr(sk, "_collapsed_high", 0.0)
    return arrays_to_state(
        spec, bins_pos, bins_neg, zero, count, total, vmin, vmax, clow, chigh
    )


def arrays_to_state(
    spec: SketchSpec,
    bins_pos: np.ndarray,
    bins_neg: np.ndarray,
    zero: np.ndarray,
    count: np.ndarray,
    total: np.ndarray,
    vmin: np.ndarray,
    vmax: np.ndarray,
    clow: np.ndarray,
    chigh: np.ndarray,
) -> SketchState:
    """Pack host (f64) interop arrays into a device state on the spec's
    default window -- the shared tail of every host->device lift
    (:func:`from_host_sketches`, ``pb.wire``'s bulk decode): derived
    counters (occupied bounds, neg_total, tile summaries) recompute from
    the bins, and masses cast to the spec's bin dtype (rounded for integer
    bins -- fractional host weights are outside integer mode's contract).
    """
    n = bins_pos.shape[0]
    bd = np.dtype(jnp.dtype(spec.bin_dtype).name)
    if np.issubdtype(bd, np.integer):
        cast = lambda a: jnp.asarray(np.rint(a).astype(bd))
    else:
        cast = lambda a: jnp.asarray(a.astype(bd))
    dt = np.dtype(jnp.dtype(spec.dtype).name)
    f32 = lambda a: jnp.asarray(a.astype(dt))
    pos_lo, pos_hi = occupied_bounds_np(bins_pos)
    neg_lo, neg_hi = occupied_bounds_np(bins_neg)
    return SketchState(
        bins_pos=cast(bins_pos),
        bins_neg=cast(bins_neg),
        zero_count=cast(zero),
        count=cast(count),
        sum=f32(total),
        min=f32(vmin),
        max=f32(vmax),
        collapsed_low=cast(clow),
        collapsed_high=cast(chigh),
        key_offset=jnp.full((n,), spec.key_offset, dtype=jnp.int32),
        pos_lo=jnp.asarray(pos_lo),
        pos_hi=jnp.asarray(pos_hi),
        neg_lo=jnp.asarray(neg_lo),
        neg_hi=jnp.asarray(neg_hi),
        neg_total=cast(bins_neg.sum(axis=-1)),
        tile_sums=cast(tile_sums_np(bins_pos, bins_neg)),
    )
