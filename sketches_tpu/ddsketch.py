"""DDSketch control layer: add / get_quantile_value / merge.

Parity target: reference ``ddsketch/ddsketch.py`` (BaseDDSketch, DDSketch,
LogCollapsingLowestDenseDDSketch, LogCollapsingHighestDenseDDSketch --
SURVEY.md section 2 rows 2-3).  A sketch owns one positive store, one negative
store (holding keys of ``-value``), and a scalar ``zero_count``, plus
count/min/max/sum bookkeeping.

Accuracy contract: for any quantile q and value stream S,
``|get_quantile_value(q) - exact_quantile(S, q)| <= alpha * |exact|``.
Mergeability contract: ``sketch(A).merge(sketch(B)) == sketch(A + B)`` up to
the same accuracy bound, for sketches with equal gamma.

Backend seam (BASELINE.json north star: "backend='jax' selects the new path
with no public-API change"): ``DDSketch(..., backend="jax")`` keeps this exact
API but stores its state as a 1-stream slice of the batched device
representation (``sketches_tpu.batched``).  For maintaining millions of
sketches, use ``BatchedDDSketch`` directly.
"""

from __future__ import annotations

import functools
import math
import sys
import typing

import numpy as np

from sketches_tpu import integrity, telemetry
from sketches_tpu.mapping import KeyMapping, LogarithmicMapping, zero_threshold
from sketches_tpu.resilience import (
    SketchValueError,
    SpecError,
    UnequalSketchParametersError,
)
from sketches_tpu.store import (
    CollapsingHighestDenseStore,
    CollapsingLowestDenseStore,
    DenseStore,
    Store,
)

__all__ = [
    "UnequalSketchParametersError",
    "BaseDDSketch",
    "DDSketch",
    "JaxDDSketch",
    "LogCollapsingLowestDenseDDSketch",
    "LogCollapsingHighestDenseDDSketch",
]

DEFAULT_REL_ACC = 0.01
DEFAULT_BIN_LIMIT = 2048
_F32_TINY = zero_threshold(np.float32)  # shared zero-bucket threshold


# UnequalSketchParametersError lives in sketches_tpu.resilience since r7
# (the structured error taxonomy); re-exported here so the historical
# ``from sketches_tpu.ddsketch import UnequalSketchParametersError`` import
# path -- and ``except ValueError`` handlers -- keep working.


class BaseDDSketch:
    """Quantile sketch with relative-error guarantee alpha.

    Reference seam: ``ddsketch/ddsketch.py . BaseDDSketch``.
    """

    def __init__(
        self,
        mapping: KeyMapping,
        store: Store,
        negative_store: Store,
        zero_count: float = 0.0,
    ):
        self._mapping = mapping
        self._store = store
        self._negative_store = negative_store
        self._zero_count = zero_count

        self._relative_accuracy = mapping.relative_accuracy
        self._count = self._zero_count + self._store.count + self._negative_store.count
        self._min = math.inf
        self._max = -math.inf
        self._sum = 0.0

    def __repr__(self) -> str:
        return (
            f"{type(self).__name__}(count={self._count}, sum={self._sum},"
            f" min={self._min}, max={self._max},"
            f" relative_accuracy={self._relative_accuracy})"
        )

    # -- accessors --------------------------------------------------------
    @property
    def mapping(self) -> KeyMapping:
        return self._mapping

    @property
    def store(self) -> Store:
        return self._store

    @property
    def negative_store(self) -> Store:
        return self._negative_store

    @property
    def zero_count(self) -> float:
        return self._zero_count

    @property
    def count(self) -> float:
        return self._count

    @property
    def num_values(self) -> float:
        return self._count

    @property
    def sum(self) -> float:  # noqa: A003 - reference API name
        return self._sum

    @property
    def avg(self) -> float:
        return self._sum / self._count

    @property
    def relative_accuracy(self) -> float:
        return self._relative_accuracy

    # -- core API ---------------------------------------------------------
    def add(self, val: float, weight: float = 1.0) -> None:
        """Ingest ``val`` with multiplicity ``weight`` (> 0)."""
        if weight <= 0.0:
            raise SketchValueError("weight must be positive")

        if val > self._mapping.min_possible:
            self._store.add(self._mapping.key(val), weight)
        elif val < -self._mapping.min_possible:
            self._negative_store.add(self._mapping.key(-val), weight)
        else:
            self._zero_count += weight

        self._count += weight
        self._sum += val * weight
        if val < self._min:
            self._min = val
        if val > self._max:
            self._max = val

    def get_quantile_value(self, quantile: float) -> typing.Optional[float]:
        """Value at quantile ``q`` in [0, 1], within relative accuracy alpha.

        Returns None for q outside [0, 1] or an empty sketch.
        """
        if quantile < 0 or quantile > 1 or self._count == 0:
            return None

        rank = quantile * (self._count - 1)
        if rank < self._negative_store.count:
            reversed_rank = self._negative_store.count - 1 - rank
            key = self._negative_store.key_at_rank(reversed_rank, lower=False)
            quantile_value = -self._mapping.value(key)
        elif rank < self._zero_count + self._negative_store.count:
            return 0.0
        else:
            key = self._store.key_at_rank(
                rank - self._zero_count - self._negative_store.count
            )
            quantile_value = self._mapping.value(key)
        return quantile_value

    def merge(self, sketch: "BaseDDSketch") -> None:
        """Fold ``sketch`` into self; equivalent to having ingested its stream."""
        if not self.mergeable(sketch):
            raise UnequalSketchParametersError(
                "Cannot merge two DDSketches with different parameters"
            )
        # A jax-backed operand defers its scalar bookkeeping to flush time;
        # settle it before reading the private fields below.
        flush = getattr(sketch, "_flush", None)
        if flush is not None:
            flush()
        if integrity._ACTIVE:
            # Guarded seam: a corrupted operand must be caught BEFORE it
            # is averaged into self (raises IntegrityError / records a
            # report per the armed mode).
            integrity.verify(sketch, seam="host.merge.operand")
        if sketch._count == 0:
            return

        # Public accessors, not _store: a jax-backed operand materializes its
        # device bins as host stores through these properties.  An empty self
        # takes the same path (Store.merge re-bins through self's own store
        # type), so merging never swaps in the operand's store class or its
        # collapse semantics.
        self._store.merge(sketch.store)
        self._negative_store.merge(sketch.negative_store)
        self._zero_count += sketch._zero_count

        self._count += sketch._count
        self._sum += sketch._sum
        if sketch._min < self._min:
            self._min = sketch._min
        if sketch._max > self._max:
            self._max = sketch._max
        if integrity._ACTIVE:
            integrity.verify(self, seam="host.merge")

    def mergeable(self, other: "BaseDDSketch") -> bool:
        """Two sketches are mergeable iff their mappings are identical.

        Deliberately stricter than the reference's same-gamma check: all
        three mapping types share the gamma formula at equal alpha but key
        values differently, so same-gamma-different-type merges would add
        incompatible bin indices and silently corrupt quantiles.  Identity =
        same type, gamma, and offset (``KeyMapping.__eq__``), which also
        keeps the check symmetric with ``JaxDDSketch.mergeable``.
        """
        return self._mapping == other._mapping

    def _copy(self, sketch: "BaseDDSketch") -> None:
        self._store = sketch.store.copy()
        self._negative_store = sketch.negative_store.copy()
        self._zero_count = sketch._zero_count
        self._count = sketch._count
        self._sum = sketch._sum
        self._min = sketch._min
        self._max = sketch._max

    def copy(self) -> "BaseDDSketch":
        new = type(self).__new__(type(self))
        new.__dict__.update(self.__dict__)
        new._copy(self)
        return new


class JaxDDSketch(BaseDDSketch):
    """Single-sketch facade over the device tier: reference API, JAX bins.

    The ``backend='jax'`` seam (BASELINE.json north star: same public API,
    device path underneath).  Scalar ``add`` calls buffer on the host and
    flush to a 1-stream slice of the batched device state in fixed-size
    chunks (fixed so one jit compilation serves every flush); queries and
    merges flush first.

    Throughput note (r5): scalar bookkeeping is deferred to the vectorized
    flush (every accessor flushes first), leaving ``add`` as two list
    appends (~2.9 M add/s for the loop alone).  When the native C++ engine
    builds (``sketches_tpu.native.available()``), each flush chunk feeds
    ``NativeDDSketch.add_batch`` (~57 M add/s) instead of paying a device
    dispatch, and the accumulated native bins lift onto the device state
    lazily -- once per query/merge/store-view, not once per 16k adds
    (VERDICT r4 item 4: through this repo's tunnel-attached chip the
    per-flush dispatch cost ~4.5 ms, capping the old path at ~0.8 M add/s,
    *below* the pure-Python tier's ~1.4 M; native-buffered it measures
    above the Python tier, since the tunnel is paid per query rather than
    per chunk).  Without a native toolchain the flush dispatches to the
    device per chunk as before.  Scalar bookkeeping (count/sum/min/max)
    stays in host float64 -- strictly more precise than the reference's --
    while bin mass lives on device in float32, which accumulates exactly
    only up to 2**24 (~16.7M) mass per bin (see ``SketchSpec.dtype``).
    The native buffer keys values with the scalar (f64) mapping path,
    which may differ from the device's f32 ``key_array`` by one bucket at
    bucket edges -- the tiers' documented, alpha-safe divergence
    (``tests/test_mapping.py::test_scalar_array_key_parity``).

    Deliberately *not* a subclass of ``DDSketch``: ``DDSketch.__new__``
    returns one of these when asked for the jax backend, and Python then
    skips ``DDSketch.__init__`` because the returned object is not a
    ``DDSketch`` instance.

    Failure modes: mirrors the host tier -- non-positive weights raise
    ``SketchValueError``, unequal-parameter merges raise
    ``UnequalSketchParametersError``, empty-sketch quantiles return
    ``None`` -- plus the device tier's degradations: a native-engine
    build/load failure silently falls back to per-chunk device flushes
    (recorded in ``resilience.health()``), and mass beyond the static
    window collapses into the edge bins (surfaced via the collapse
    counters, never silently lost).
    """

    # One jit compilation serves every flush, so the chunk is a fixed
    # shape.  16k balances dispatch amortization (the dominant cost of the
    # scalar loop once bookkeeping deferred to flush) against first-flush
    # latency; the auto-center median only improves with a bigger first
    # buffer.
    _FLUSH_CHUNK = 16384

    @staticmethod
    @functools.lru_cache(maxsize=None)
    def _jitted_ops(spec):
        """One set of compiled (add, first_add, quantile, merge) per spec,
        shared by every instance (and every ``copy()``) with that spec.
        ``first_add`` centers the window on the first chunk's median key
        before ingesting (skipped when the user pinned ``key_offset``);
        ``merge`` realigns the operand's window onto self's, so sketches
        whose adaptive windows drifted apart stay mergeable."""
        import jax

        from sketches_tpu import batched

        def _first_add(st, values, weights):
            st = batched.recenter(
                spec, st, batched.auto_offset(spec, st, values)
            )
            return batched.add(spec, st, values, weights)

        return (
            jax.jit(functools.partial(batched.add, spec), donate_argnums=(0,)),
            jax.jit(_first_add, donate_argnums=(0,)),
            jax.jit(functools.partial(batched.get_quantile_value, spec)),
            jax.jit(
                functools.partial(batched.merge_aligned, spec),
                donate_argnums=(0,),
            ),
        )

    def __init__(
        self,
        relative_accuracy: typing.Optional[float] = None,
        n_bins: typing.Optional[int] = None,
        mapping: str = "logarithmic",
        key_offset: typing.Optional[int] = None,
    ):
        from sketches_tpu import batched
        from sketches_tpu.mapping import mapping_from_name

        if relative_accuracy is None:
            relative_accuracy = DEFAULT_REL_ACC
        self._spec = batched.SketchSpec(
            relative_accuracy=relative_accuracy,
            mapping_name=mapping,
            n_bins=DEFAULT_BIN_LIMIT if n_bins is None else n_bins,
            key_offset=key_offset,
        )
        self._mapping = mapping_from_name(mapping, relative_accuracy)
        self._relative_accuracy = relative_accuracy
        self._state = batched.init(self._spec, 1)
        (
            self._flush_fn,
            self._first_flush_fn,
            self._quantile_fn,
            self._merge_fn,
        ) = self._jitted_ops(self._spec)
        # First flush centers the window on the data unless the caller
        # pinned it (an explicit key_offset is a deliberate window choice).
        self._auto_center_pending = key_offset is None
        self._pending_vals: list = []
        self._pending_weights: list = []
        self._host_cache: typing.Optional[BaseDDSketch] = None
        # Native (C++) flush buffer: bins accumulate at ~57 M add/s on the
        # host and lift onto the device state once per settle, not once per
        # chunk.  None when the toolchain is unavailable (pure device-flush
        # fallback) or until the first flush establishes the window.
        self._native_acc = None
        self._use_native = self._native_available()
        # The established window's low edge, known host-side once the first
        # flush (or a merge into an empty self) fixes it; the native buffer
        # must share the device window so clamp-to-edge collapse agrees.
        self._window_offset: typing.Optional[int] = (
            None if key_offset is None else int(self._spec.key_offset)
        )
        self._zero_count = 0.0
        self._count = 0.0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    @staticmethod
    def _native_available() -> bool:
        from sketches_tpu import native

        return native.available()

    # -- core API ----------------------------------------------------------
    def add(self, val: float, weight: float = 1.0) -> None:
        if weight <= 0.0:
            raise SketchValueError("weight must be positive")
        # EVERY piece of scalar bookkeeping happens vectorized at flush
        # time: the per-add Python arithmetic (and especially the
        # ``np.float32(val)`` scalar cast zero classification used to do
        # here) cost several times this whole method.  Measured in this
        # repo's tunnel-attached environment: 0.16-0.32 M add/s before
        # (r3/r4 runs of bench c0_jax_scalar) -> ~0.8 M after, with the
        # add loop itself at ~2.9 M/s (the rest is per-flush dispatch).
        # Every accessor (incl. __repr__ and the store views) flushes
        # first, so no counter is ever observably stale.
        self._pending_vals.append(val)
        self._pending_weights.append(weight)
        if len(self._pending_vals) >= self._FLUSH_CHUNK:
            self._flush()

    def add_many(self, values, weights=None) -> None:
        """Vectorized bulk add: one numpy pass instead of N ``add`` calls.

        Semantically N scalar ``add`` calls (same zero classification,
        same f64 bookkeeping, same auto-centering on the first data this
        sketch sees), but the values feed the native buffer / device
        flush directly -- the ~2.9 M/s Python append loop is bypassed, so
        throughput is the engine's own (VERDICT r5 item 7; measured in
        ``bench c0_jax_scalar.add_many_per_s``).  ``weights`` broadcasts
        against ``values`` and must be strictly positive, like the scalar
        ``add``'s weight.  Values are flattened; any pending scalar adds
        flush first so arrival order is preserved.
        """
        v64 = np.asarray(values, np.float64).ravel()
        if weights is None:
            w64 = np.ones_like(v64)
        else:
            w64 = np.broadcast_to(
                np.asarray(weights, np.float64), v64.shape
            )
            if v64.size and not (w64 > 0.0).all():
                raise SketchValueError("weight must be positive")
        if v64.size == 0:
            return
        self._flush()  # drain buffered scalar adds ahead of this batch
        _t0 = telemetry.clock() if telemetry._ACTIVE else None
        self._host_cache = None
        # Device-semantics zero classification, identical to _flush.
        v32 = v64.astype(np.float32)
        zero_lanes = ~(np.abs(v32) >= _F32_TINY)
        if self._use_native:
            self._flush_native(v64, w64, zero_lanes)
            self._auto_center_pending = False
        else:
            # Device fallback: feed _FLUSH_CHUNK-shaped slices through the
            # same fixed-shape flush jits, zero-weight entries as padding
            # (inert in batched.add).
            chunk = self._FLUSH_CHUNK
            for s in range(0, v64.size, chunk):
                vv = np.zeros((1, chunk), np.float32)
                ww = np.zeros((1, chunk), np.float32)
                piece = slice(s, min(s + chunk, v64.size))
                ln = piece.stop - piece.start
                vv[0, :ln] = v32[piece]
                ww[0, :ln] = w64[piece]
                if self._auto_center_pending:
                    self._state = self._first_flush_fn(self._state, vv, ww)
                    self._auto_center_pending = False
                else:
                    self._state = self._flush_fn(self._state, vv, ww)
        # Scalar bookkeeping, vectorized over the whole batch (the f64
        # master copies -- mirrors _flush exactly, NaN poisoning included).
        self._count += float(w64.sum())
        self._sum += float((v64 * w64).sum())
        finite = ~np.isnan(v64)
        if finite.any():
            self._min = min(self._min, float(v64[finite].min()))
            self._max = max(self._max, float(v64[finite].max()))
        if zero_lanes.any():
            self._zero_count += float(w64[zero_lanes].sum())
        if _t0 is not None:
            telemetry.finish_span("scalar.ingest_s", _t0, path="add_many")
            telemetry.counter_inc("scalar.values", float(v64.size))

    def _flush(self) -> None:
        if not self._pending_vals:
            return
        _t0 = telemetry.clock() if telemetry._ACTIVE else None
        _n = len(self._pending_vals)
        self._host_cache = None
        while self._pending_vals:
            chunk_v = self._pending_vals[: self._FLUSH_CHUNK]
            chunk_w = self._pending_weights[: self._FLUSH_CHUNK]
            # ONE Python-list walk per chunk: the f64 arrays are the
            # master copies, and the f32 device buffers derive from them
            # by numpy downcast (bit-identical to casting the list
            # directly, so the device zero-classification semantics below
            # are unchanged).
            v64 = np.asarray(chunk_v, np.float64)
            w64 = np.asarray(chunk_w, np.float64)
            # Classify zeros with the *device's* semantics -- the f32 cast
            # plus the TPU/XLA flush-to-zero treatment of subnormals --
            # not the host mapping's f64 min_possible: anything the device
            # lands in its zero path must count as zero here too, or
            # cross-backend merges drop that mass.  Subnormal f32
            # magnitudes (< ~1.18e-38) flush on device; NaN fails the >=
            # comparison and counts as zero as well.
            v32 = v64.astype(np.float32)
            zero_lanes = ~(np.abs(v32) >= _F32_TINY)
            # The engine call runs BEFORE any counter/buffer mutation: a
            # failed chunk (device OOM, native build raced away) leaves
            # the pending buffer and every host counter untouched, so the
            # sketch stays self-consistent and the flush is retryable
            # (ADVICE r4 item 1).
            if self._use_native:
                self._flush_native(v64, w64, zero_lanes)
            else:
                values = np.zeros((1, self._FLUSH_CHUNK), np.float32)
                weights = np.zeros((1, self._FLUSH_CHUNK), np.float32)
                values[0, : len(chunk_v)] = v32
                weights[0, : len(chunk_w)] = w64
                if self._auto_center_pending:
                    self._state = self._first_flush_fn(
                        self._state, values, weights
                    )
                else:
                    self._state = self._flush_fn(self._state, values, weights)
            self._auto_center_pending = False
            del self._pending_vals[: self._FLUSH_CHUNK]
            del self._pending_weights[: self._FLUSH_CHUNK]
            self._count += float(w64.sum())
            self._sum += float((v64 * w64).sum())  # NaN poisons, as before
            finite = ~np.isnan(v64)
            if finite.any():
                self._min = min(self._min, float(v64[finite].min()))
                self._max = max(self._max, float(v64[finite].max()))
            if zero_lanes.any():
                self._zero_count += float(w64[zero_lanes].sum())
        if _t0 is not None:
            telemetry.finish_span("scalar.ingest_s", _t0, path="flush")
            telemetry.counter_inc("scalar.values", float(_n))

    def _flush_native(self, v64, w64, zero_lanes) -> None:
        """Feed one chunk to the native (C++) accumulator.

        Values below the device zero threshold (f32 subnormals, NaN) are
        fed as literal zeros so the native engine's zero bucket matches the
        device classification exactly; everything else keys through the
        scalar (f64) mapping path.
        """
        from sketches_tpu import native

        if self._native_acc is None:
            if self._auto_center_pending and self._window_offset is None:
                self._window_offset = self._auto_center_offset(
                    v64, zero_lanes
                )
            if self._window_offset is None:
                self._window_offset = int(self._spec.key_offset)
            self._native_acc = native.NativeDDSketch(
                self._spec.relative_accuracy,
                n_bins=self._spec.n_bins,
                key_offset=self._window_offset,
                mapping=self._spec.mapping_name,
            )
        feed = v64.copy()
        feed[zero_lanes] = 0.0
        self._native_acc.add_batch(feed, w64)

    def _auto_center_offset(self, v64, zero_lanes) -> int:
        """First-batch window center, host twin of ``batched.auto_offset``:
        the median *key* of the chunk's live nonzero values.  Keys are a
        monotone function of |v|, so key(median |v|) == median(key) --
        computed with one sort and one scalar ``mapping.key`` call (the
        f64 scalar path; at most one bucket from the device's f32
        derivation, immaterial to a 2048-bin window position)."""
        live = ~zero_lanes
        if not live.any():
            return int(self._spec.key_offset)
        a = np.sort(np.abs(v64[live]))
        med = float(a[(a.size - 1) // 2])
        if not math.isfinite(med):
            # An infinite median (majority-inf chunk) has no key --
            # center on the largest representable magnitude instead, so
            # the window saturates at the top like the device path's
            # int32-saturating key would (review r5).
            med = sys.float_info.max
        from sketches_tpu.batched import _center_bin

        return int(self._mapping.key(med)) - _center_bin(self._spec)

    def _settle(self) -> None:
        """Flush, then lift any native-buffered mass onto the device state.

        The device dispatch happens HERE -- once per query/merge/view --
        rather than once per flush chunk; ``merge_aligned`` adopts the
        buffer's window when the device state is still empty and realigns
        otherwise (windows agree by construction after the first settle).
        """
        self._flush()
        acc = self._native_acc
        if acc is not None and acc.count > 0:
            self._state = self._merge_fn(self._state, acc.to_state())
            self._native_acc = None
            self._host_cache = None

    def get_quantile_value(self, quantile: float) -> typing.Optional[float]:
        self._settle()  # also settles the deferred _count bookkeeping
        if quantile < 0 or quantile > 1 or self._count == 0:
            return None
        out = float(self._quantile_fn(self._state, float(quantile))[0])
        return out

    def mergeable(self, other: "BaseDDSketch") -> bool:
        """Jax-backed sketches need the full spec (gamma AND window) to
        match; cross-backend merges need the identical mapping (type, gamma,
        offset) -- same-gamma alone is not enough, since all mapping types
        share the gamma formula while keying differently.  The host bins are
        then packed into this sketch's window, clamping at the edges."""
        if isinstance(other, JaxDDSketch):
            return self._spec == other._spec
        return self._mapping == other._mapping

    def merge(self, sketch: "BaseDDSketch") -> None:
        if not self.mergeable(sketch):
            raise UnequalSketchParametersError(
                "Cannot merge two DDSketches with different parameters"
            )
        if sketch.count == 0:
            return
        self._settle()
        if isinstance(sketch, JaxDDSketch):
            sketch._settle()
            other_state = sketch._state
        else:
            # Cross-backend: pack the pure-Python sketch's bins into a
            # 1-stream device state (mass outside the window clamps to the
            # edge bins, like ingest-side collapse).
            from sketches_tpu.batched import from_host_sketches

            other_state = from_host_sketches(self._spec, [sketch])
        _ipre = (
            integrity.premerge(self._spec, self._state, other_state)
            if integrity._ACTIVE
            else None
        )
        self._state = self._merge_fn(self._state, other_state)
        if _ipre is not None:
            # Guarded seam: fingerprint/conservation check of the merged
            # device state against the operand snapshot.
            integrity.postmerge(self._spec, self._state, _ipre, seam="jax.merge")
        # The merge populated the device state; a still-pending auto-center
        # on the next flush would recenter away from the merged mass.  The
        # merged-in window is now the established one (merge_aligned keeps
        # self's offsets when self held mass, adopts the operand's when
        # empty) -- pin the native buffer's window to it.
        self._auto_center_pending = False
        if self._window_offset is None:
            if isinstance(sketch, JaxDDSketch) and sketch._window_offset is not None:
                self._window_offset = sketch._window_offset
            else:
                self._window_offset = int(
                    np.asarray(self._state.key_offset)[0]
                )
        self._host_cache = None
        self._zero_count += sketch._zero_count
        self._count += sketch._count
        self._sum += sketch._sum
        self._min = min(self._min, sketch._min)
        self._max = max(self._max, sketch._max)

    def copy(self) -> "JaxDDSketch":
        import jax

        self._settle()
        new = JaxDDSketch(
            self._relative_accuracy,
            n_bins=self._spec.n_bins,
            mapping=self._spec.mapping_name,
            key_offset=self._spec.key_offset,
        )
        new._state = jax.tree.map(jax.numpy.copy, self._state)
        new._auto_center_pending = self._auto_center_pending
        new._window_offset = self._window_offset
        new._zero_count = self._zero_count
        new._count = self._count
        new._sum = self._sum
        new._min = self._min
        new._max = self._max
        return new

    # -- accessors (BaseDDSketch properties read these fields) -------------
    @property
    def zero_count(self) -> float:
        # ALL scalar bookkeeping happens at flush time (vectorized); each
        # accessor flushes so no counter is observably stale.
        self._flush()
        return self._zero_count

    @property
    def count(self) -> float:
        self._flush()
        return self._count

    @property
    def num_values(self) -> float:
        self._flush()
        return self._count

    @property
    def sum(self) -> float:  # noqa: A003 - reference API name
        self._flush()
        return self._sum

    @property
    def avg(self) -> float:
        self._flush()
        return self._sum / self._count

    def __repr__(self) -> str:
        self._flush()  # the inherited repr reads the deferred counters
        return super().__repr__()

    def _host_view(self) -> "BaseDDSketch":
        """Host materialization of the device bins, cached until the next
        mutation so back-to-back store/negative_store reads pay for one
        device transfer, not two.  Settle FIRST, unconditionally: it clears
        the cache whenever adds were pending, so a view can never miss
        buffered values (review r4)."""
        self._settle()
        if self._host_cache is None:
            from sketches_tpu.batched import to_host_sketches

            self._host_cache = to_host_sketches(self._spec, self._state)[0]
        return self._host_cache

    @property
    def store(self):
        return self._host_view().store

    @property
    def negative_store(self):
        return self._host_view().negative_store


class DDSketch(BaseDDSketch):
    """Default preset: LogarithmicMapping + unbounded DenseStore (pos & neg).

    Reference seam: ``ddsketch/ddsketch.py . DDSketch``.  Pass
    ``backend='jax'`` to get the same API running on the device tier
    (:class:`JaxDDSketch`); the default pure-Python backend doubles as the
    oracle the device path is parity-tested against.

    Failure modes: invalid configuration raises ``SpecError``;
    non-positive weights raise ``SketchValueError``; quantiles of an
    empty sketch return ``None``; merging sketches with different
    mapping parameters raises ``UnequalSketchParametersError``.
    """

    def __new__(
        cls,
        relative_accuracy: typing.Optional[float] = None,
        backend: str = "py",
        *,
        mapping: typing.Optional[str] = None,
        n_bins: typing.Optional[int] = None,
        key_offset: typing.Optional[int] = None,
    ):
        if backend == "jax":
            if cls is not DDSketch:
                raise NotImplementedError(
                    f"backend='jax' is not inherited by subclass {cls.__name__};"
                    " construct JaxDDSketch directly"
                )
            return JaxDDSketch(
                relative_accuracy,
                n_bins=n_bins,
                mapping=mapping or "logarithmic",
                key_offset=key_offset,
            )
        if backend != "py":
            raise SpecError(f"Unknown backend {backend!r}")
        _reject_jax_only_kwargs(mapping=mapping, n_bins=n_bins, key_offset=key_offset)
        return super().__new__(cls)

    def __init__(
        self,
        relative_accuracy: typing.Optional[float] = None,
        backend: str = "py",
        *,
        mapping: typing.Optional[str] = None,
        n_bins: typing.Optional[int] = None,
        key_offset: typing.Optional[int] = None,
    ):
        if relative_accuracy is None:
            relative_accuracy = DEFAULT_REL_ACC
        super().__init__(
            mapping=LogarithmicMapping(relative_accuracy),
            store=DenseStore(),
            negative_store=DenseStore(),
        )


def _reject_jax_only_kwargs(**kwargs) -> None:
    """The py presets are reference-shaped (LogarithmicMapping + the preset's
    store class); the device-tier knobs only apply to ``backend='jax'``.
    Compose ``BaseDDSketch`` directly for a non-default pure-Python sketch."""
    passed = [k for k, v in kwargs.items() if v is not None]
    if passed:
        raise SpecError(
            f"{', '.join(passed)} only apply to backend='jax'; for a custom"
            " pure-Python sketch compose BaseDDSketch(mapping=..., store=...)"
        )


def _jax_collapsing_sketch(
    relative_accuracy: typing.Optional[float],
    bin_limit: typing.Optional[int],
    mapping: typing.Optional[str] = None,
    key_offset: typing.Optional[int] = None,
) -> "JaxDDSketch":
    """The jax backend for both collapsing presets.

    The device tier is *always*-collapsing (static ``bin_limit``-bin window,
    mass clamping at both edges with observability counters), which bounds
    memory exactly like the reference presets.  The difference -- documented,
    inherent to static shapes -- is that the py presets slide their window
    to follow the data (pinning the kept end) while the device window is
    fixed at construction, centered on ``key(1.0) = 0``.
    """
    # Degenerate limits (< 2, incl. the py tier's accepted 0/1) fall back to
    # the default, same as negative values: the device window needs >= 2 bins.
    if bin_limit is None or bin_limit < 2:
        bin_limit = DEFAULT_BIN_LIMIT
    return JaxDDSketch(
        relative_accuracy,
        n_bins=bin_limit,
        mapping=mapping or "logarithmic",
        key_offset=key_offset,
    )


class LogCollapsingLowestDenseDDSketch(BaseDDSketch):
    """LogarithmicMapping + CollapsingLowestDenseStore (bounded memory).

    Reference seam: ``ddsketch/ddsketch.py . LogCollapsingLowestDenseDDSketch``.
    ``backend='jax'`` bounds memory with the device tier's static window
    (see ``_jax_collapsing_sketch``).
    """

    def __new__(
        cls,
        relative_accuracy: typing.Optional[float] = None,
        bin_limit: typing.Optional[int] = None,
        backend: str = "py",
        *,
        mapping: typing.Optional[str] = None,
        key_offset: typing.Optional[int] = None,
    ):
        if backend == "jax":
            if cls is not LogCollapsingLowestDenseDDSketch:
                raise NotImplementedError(
                    f"backend='jax' is not inherited by subclass {cls.__name__};"
                    " construct JaxDDSketch directly"
                )
            return _jax_collapsing_sketch(
                relative_accuracy, bin_limit, mapping, key_offset
            )
        if backend != "py":
            raise SpecError(f"Unknown backend {backend!r}")
        _reject_jax_only_kwargs(mapping=mapping, key_offset=key_offset)
        return super().__new__(cls)

    def __init__(
        self,
        relative_accuracy: typing.Optional[float] = None,
        bin_limit: typing.Optional[int] = None,
        backend: str = "py",
        *,
        mapping: typing.Optional[str] = None,
        key_offset: typing.Optional[int] = None,
    ):
        if relative_accuracy is None:
            relative_accuracy = DEFAULT_REL_ACC
        if bin_limit is None or bin_limit < 0:
            bin_limit = DEFAULT_BIN_LIMIT
        super().__init__(
            mapping=LogarithmicMapping(relative_accuracy),
            store=CollapsingLowestDenseStore(bin_limit),
            negative_store=CollapsingLowestDenseStore(bin_limit),
        )


class LogCollapsingHighestDenseDDSketch(BaseDDSketch):
    """LogarithmicMapping + CollapsingHighestDenseStore (bounded memory).

    Reference seam: ``ddsketch/ddsketch.py . LogCollapsingHighestDenseDDSketch``.
    ``backend='jax'`` bounds memory with the device tier's static window
    (see ``_jax_collapsing_sketch``).
    """

    def __new__(
        cls,
        relative_accuracy: typing.Optional[float] = None,
        bin_limit: typing.Optional[int] = None,
        backend: str = "py",
        *,
        mapping: typing.Optional[str] = None,
        key_offset: typing.Optional[int] = None,
    ):
        if backend == "jax":
            if cls is not LogCollapsingHighestDenseDDSketch:
                raise NotImplementedError(
                    f"backend='jax' is not inherited by subclass {cls.__name__};"
                    " construct JaxDDSketch directly"
                )
            return _jax_collapsing_sketch(
                relative_accuracy, bin_limit, mapping, key_offset
            )
        if backend != "py":
            raise SpecError(f"Unknown backend {backend!r}")
        _reject_jax_only_kwargs(mapping=mapping, key_offset=key_offset)
        return super().__new__(cls)

    def __init__(
        self,
        relative_accuracy: typing.Optional[float] = None,
        bin_limit: typing.Optional[int] = None,
        backend: str = "py",
        *,
        mapping: typing.Optional[str] = None,
        key_offset: typing.Optional[int] = None,
    ):
        if relative_accuracy is None:
            relative_accuracy = DEFAULT_REL_ACC
        if bin_limit is None or bin_limit < 0:
            bin_limit = DEFAULT_BIN_LIMIT
        super().__init__(
            mapping=LogarithmicMapping(relative_accuracy),
            store=CollapsingHighestDenseStore(bin_limit),
            negative_store=CollapsingHighestDenseStore(bin_limit),
        )
