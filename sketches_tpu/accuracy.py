"""Accuracy-drift shadow audit: is the alpha contract still true?

A DDSketch's relative-error guarantee is structural -- until mass
collapses into the static window's edge bins (the silent-degradation
failure mode UDDSketch, arXiv:2004.08604, exists to fix), or until a
bug anywhere in the ingest/merge/query stack bends the answers.  The
integrity layer (PR 5) proves the *state* is well-formed; this layer
proves the *answers* are still accurate, online:

* :func:`watch` registers a sketch facade for auditing.  Each watched
  stream keeps a **bounded reservoir sample** of its ingested values
  (deterministic splitmix-hash reservoir -- no global RNG, so a failing
  sequence replays exactly; ``faults.py`` discipline).
* Every ``interval`` ingests the auditor replays the contract: the
  facade's p50/p90/p99 must land inside the reservoir's order-statistic
  bracket widened by alpha -- the realized-rank-error test -- and the
  per-stream ``collapsed_mass_frac`` (edge-clamped mass over total) is
  tracked for drift.
* Breaches emit the declared ``accuracy.*`` telemetry metrics and
  ring-bounded :class:`DriftReport` records (the quarantine discipline
  from ``integrity.py``: bounded memory, drops counted, never an
  unbounded list).

Arming: OFF by default.  ``SKETCHES_TPU_ACCURACY_AUDIT=1`` (declared in
``analysis/registry.py``) arms at process start; :func:`enable` /
:func:`disable` arm programmatically.  Cost discipline: the ingest seam
guards on ``accuracy._ACTIVE`` -- one attribute read + bool test per
dispatch disarmed -- and an armed ingest of an *unwatched* facade costs
one dict lookup.  Audits themselves run a real (device) quantile query
against the watched facade: that is the shadow read the layer is
opt-in for.

Failure modes: watching an object without a quantile API raises
``SketchValueError``; a garbage-collected watched facade is silently
unwatched at its next audit; streams whose reservoir holds fewer than
``MIN_SAMPLE`` values are skipped (too few points to bracket a p99);
weighted ingests are audited by value with weights ignored (weight > 0
admits the value once -- the documented approximation); the report ring
is bounded at 1024 with further reports counted, never stored.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import weakref
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sketches_tpu import telemetry
from sketches_tpu.analysis import registry

__all__ = [
    "ACCURACY_ENV",
    "RESERVOIR_CAP",
    "MIN_SAMPLE",
    "AUDIT_QS",
    "DriftReport",
    "enable",
    "disable",
    "enabled",
    "reset",
    "watch",
    "unwatch",
    "observe_ingest",
    "audit_now",
    "reports",
    "summary",
]

#: Declared in ``analysis/registry.py`` (the kill-switch inventory).
ACCURACY_ENV = registry.ACCURACY_AUDIT.name

#: Per-stream reservoir bound: enough that a p99 bracket is a few
#: sample ranks wide, small enough that auditing costs KBs per stream.
RESERVOIR_CAP = 4096

#: Streams with fewer reservoir values than this are skipped: order
#: statistics this sparse cannot bracket a tail quantile honestly.
MIN_SAMPLE = 64

#: Quantiles every audit pass replays against the contract.
AUDIT_QS: Tuple[float, ...] = (0.5, 0.9, 0.99)

#: Default ingest calls between audit passes per watched facade.
DEFAULT_INTERVAL = 16

#: collapsed_mass_frac growth between consecutive audits that counts as
#: drift (reported even when the quantile bracket still holds -- the
#: UDDSketch early warning).
COLLAPSE_DRIFT = 0.01

_MAX_REPORTS = 1024

_ACTIVE = registry.enabled(registry.ACCURACY_AUDIT)

_lock = threading.Lock()
_watches: Dict[str, "_Watch"] = {}
_by_id: Dict[int, str] = {}
_reports: List["DriftReport"] = []
_reports_dropped = 0
_audits_total = 0
_violations_total = 0


@dataclasses.dataclass(frozen=True)
class DriftReport:
    """One recorded accuracy breach or collapse-drift observation.

    ``kind`` is ``"rank-error"`` (a realized quantile left the
    alpha-widened order-statistic bracket) or ``"collapse-drift"``
    (edge-clamped mass fraction jumped by more than
    :data:`COLLAPSE_DRIFT` since the previous audit).  ``wall_time``
    is operator-facing only (``telemetry.wall_time``).
    """

    name: str
    stream: int
    kind: str
    quantile: float
    sketch_value: float
    sample_value: float
    rel_err: float
    collapsed_frac: float
    sample_size: int
    audit_index: int
    wall_time: float


class _Reservoir:
    """Bounded uniform sample with deterministic replacement.

    Algorithm R with the coin flips taken from a splitmix64 hash of the
    (seed, absolute position) pair instead of an RNG: the kept set is a
    pure function of the stream contents and arrival order, so a
    failing audit replays exactly.
    """

    __slots__ = ("cap", "seed", "buf", "n")

    def __init__(self, cap: int, seed: int):
        self.cap = cap
        self.seed = np.uint64(
            (seed * 0x9E3779B97F4A7C15 + 1) & 0xFFFFFFFFFFFFFFFF
        )
        self.buf: List[float] = []
        self.n = 0

    def extend(self, values: np.ndarray) -> None:
        vals = np.asarray(values, np.float64).ravel()
        vals = vals[~np.isnan(vals)]
        m = int(vals.size)
        if not m:
            return
        take = min(self.cap - len(self.buf), m)
        if take > 0:
            self.buf.extend(float(v) for v in vals[:take])
        rest = vals[take:]
        if rest.size:
            pos = (
                np.arange(self.n + take, self.n + m, dtype=np.uint64)
                ^ self.seed
            )
            j = _splitmix64(pos) % np.uint64(self.cap)
            keep = _splitmix64(pos + np.uint64(0x632BE59BD9B4E019)) % (
                np.arange(self.n + take, self.n + m, dtype=np.uint64)
                + np.uint64(1)
            )
            sel = np.nonzero(keep < np.uint64(self.cap))[0]
            for i in sel:
                self.buf[int(j[i])] = float(rest[i])
        self.n += m

    def sorted_sample(self) -> np.ndarray:
        return np.sort(np.asarray(self.buf, np.float64))


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer (uint64 in, uint64 out)."""
    x = x + np.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


class _Watch:
    __slots__ = (
        "name", "ref", "streams", "interval", "rel_acc", "reservoirs",
        "ingest_calls", "audits", "last_collapsed",
    )

    def __init__(self, name, ref, streams, interval, rel_acc):
        self.name = name
        self.ref = ref
        self.streams = streams
        self.interval = interval
        self.rel_acc = rel_acc
        import binascii

        # crc32, not hash(): string hashing is salted per process, and
        # the reservoir seed must be stable so multi-process audits of
        # the same stream replay identically (faults.py discipline).
        self.reservoirs: Dict[int, _Reservoir] = {
            s: _Reservoir(
                RESERVOIR_CAP,
                seed=binascii.crc32(f"{name}:{s}".encode()) & 0x7FFFFFFF,
            )
            for s in streams
        }
        self.ingest_calls = 0
        self.audits = 0
        self.last_collapsed: Dict[int, float] = {}


def _raise_value_error(msg: str) -> None:
    from sketches_tpu.resilience import SketchValueError

    raise SketchValueError(msg)


def enable(on: bool = True) -> None:
    """Arm (or, with ``on=False``, disarm) the shadow audit.  Never
    raises; watches and recorded reports are kept (:func:`reset`
    clears)."""
    global _ACTIVE
    _ACTIVE = bool(on)


def disable() -> None:
    """Disarm the shadow audit (the ingest seam goes back to one bool
    test; watches/reports are kept, never lost)."""
    enable(False)


def enabled() -> bool:
    """Whether the audit is armed (env switch or :func:`enable`);
    False -- the default -- means no ingest is shadowed."""
    return _ACTIVE


def reset() -> None:
    """Drop every watch, reservoir, and report (test isolation hook).
    Never raises."""
    global _reports_dropped, _audits_total, _violations_total
    with _lock:
        _watches.clear()
        _by_id.clear()
        _reports.clear()
        _reports_dropped = 0
        _audits_total = 0
        _violations_total = 0


def watch(
    facade: Any,
    name: str,
    streams: Optional[Sequence[int]] = None,
    interval: int = DEFAULT_INTERVAL,
) -> str:
    """Register ``facade`` (a ``BatchedDDSketch`` / ``DistributedDDSketch``
    or anything with ``get_quantile_values``) for shadow auditing.

    ``streams`` selects which stream rows keep reservoirs (default: the
    first 8 -- auditing a million streams would cost a million
    reservoirs; pick representatives).  The facade is held weakly: a
    collected facade is silently unwatched.  Raises ``SketchValueError``
    for an object without a quantile API, a non-positive ``interval``,
    or a duplicate ``name``.
    """
    if not hasattr(facade, "get_quantile_values") and not hasattr(
        facade, "get_quantile_value"
    ):
        _raise_value_error(
            f"cannot watch {type(facade).__name__}: no quantile API"
        )
    if interval <= 0:
        _raise_value_error("interval must be positive")
    n_streams = int(getattr(facade, "n_streams", 1))
    if streams is None:
        streams = tuple(range(min(n_streams, 8)))
    else:
        streams = tuple(int(s) for s in streams)
        bad = [s for s in streams if not 0 <= s < max(n_streams, 1)]
        if bad:
            _raise_value_error(
                f"watched streams {bad} out of range for {n_streams} streams"
            )
    spec = getattr(facade, "spec", None)
    rel_acc = float(
        getattr(spec, "relative_accuracy", None)
        or getattr(facade, "relative_accuracy", 0.01)
    )
    fid = id(facade)

    def _collect(_ref, _fid=fid, _name=name):
        with _lock:
            _by_id.pop(_fid, None)
            _watches.pop(_name, None)

    with _lock:
        if name in _watches:
            _raise_value_error(f"already watching a sketch named {name!r}")
        _watches[name] = _Watch(
            name, weakref.ref(facade, _collect), streams, int(interval),
            rel_acc,
        )
        _by_id[fid] = name
    return name


def unwatch(name: str) -> None:
    """Stop auditing ``name`` (unknown names are a no-op, never an
    error); its reservoirs are dropped, its reports kept."""
    with _lock:
        w = _watches.pop(name, None)
        if w is not None:
            _by_id_inv = [k for k, v in _by_id.items() if v == name]
            for k in _by_id_inv:
                _by_id.pop(k, None)


def observe_ingest(facade: Any, values, weights=None) -> None:
    """The ingest seam: feed a watched facade's batch into its
    reservoirs and run the periodic audit.

    No-op (after one dict lookup) for unwatched facades; no-op entirely
    while disarmed.  Values with ``weights <= 0`` (padding) and NaNs
    are excluded from the sample; positive weights admit the value once
    (the documented weighted-ingest approximation).  Never raises from
    the sampling path; audit failures land in reports, not exceptions.
    """
    if not _ACTIVE:
        return
    name = _by_id.get(id(facade))
    if name is None:
        return
    with _lock:
        w = _watches.get(name)
    if w is None:
        return
    vals = np.asarray(values)
    if vals.ndim == 1:
        vals = vals[:, None]
    wts = None
    if weights is not None:
        wts = np.asarray(weights)
        if wts.ndim == 1:
            wts = wts[:, None]
        wts = np.broadcast_to(wts, vals.shape)
    for s in w.streams:
        if s >= vals.shape[0]:
            continue
        row = np.asarray(vals[s], np.float64).ravel()
        if wts is not None:
            row = row[np.asarray(wts[s]).ravel() > 0]
        w.reservoirs[s].extend(row)
    w.ingest_calls += 1
    if w.ingest_calls % w.interval == 0:
        _audit(w)


def audit_now(name: str) -> int:
    """Run one audit pass for watch ``name`` immediately -> number of
    violations found (0 is the healthy answer).  Raises
    ``SketchValueError`` for an unknown name."""
    with _lock:
        w = _watches.get(name)
    if w is None:
        _raise_value_error(f"no watch named {name!r}")
    return _audit(w)


def _facade_collapsed(facade) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(collapsed_low+high, count) per stream, or None when the facade
    has no inspectable state (host-tier sketches never collapse)."""
    st = getattr(facade, "state", None)
    if st is None and hasattr(facade, "merged_state"):
        try:
            st = facade.merged_state()
        except Exception:  # noqa: BLE001 - collapse metric is best-effort
            return None
    if st is None:
        return None
    try:
        collapsed = np.asarray(
            st.collapsed_low, np.float64
        ) + np.asarray(st.collapsed_high, np.float64)
        count = np.asarray(st.count, np.float64)
        return collapsed, count
    except Exception:  # noqa: BLE001
        return None


def _sketch_quantiles(facade) -> Optional[np.ndarray]:
    """The facade's values at :data:`AUDIT_QS` -> ``[n_streams, Q]``."""
    try:
        if hasattr(facade, "get_quantile_values"):
            arr = np.asarray(facade.get_quantile_values(list(AUDIT_QS)))
            if arr.ndim == 1:
                arr = arr[None, :]
            return arr
        vals = [facade.get_quantile_value(q) for q in AUDIT_QS]
        if any(v is None for v in vals):
            return None
        return np.asarray(vals, np.float64)[None, :]
    except Exception:  # noqa: BLE001 - an unanswerable facade audits as absent
        return None


def _audit(w: _Watch) -> int:
    """One audit pass: realized-rank-error + collapse-drift checks."""
    global _audits_total, _violations_total, _reports_dropped
    facade = w.ref()
    if facade is None:
        unwatch(w.name)
        return 0
    sk_q = _sketch_quantiles(facade)
    if sk_q is None:
        return 0
    collapsed = _facade_collapsed(facade)
    spec = getattr(facade, "spec", None)
    # Streams whose backend CAN collapse handle threshold crossings
    # themselves (the uniform-collapse trigger); for everything else the
    # crossing becomes a declared counter instead of dying in the gauge.
    _recommendable = getattr(spec, "backend", "dense") != "uniform_collapse"
    _collapse_thr = float(getattr(spec, "collapse_threshold", 0.01))
    w.audits += 1
    violations = 0
    worst_rel_err: Dict[int, float] = {}
    now = telemetry.wall_time()
    new_reports: List[DriftReport] = []
    for s in w.streams:
        sample = w.reservoirs[s].sorted_sample()
        m = int(sample.size)
        frac = 0.0
        if collapsed is not None and s < collapsed[0].size:
            cnt = float(collapsed[1][s])
            frac = float(collapsed[0][s]) / cnt if cnt > 0 else 0.0
        if m >= MIN_SAMPLE:
            row = sk_q[min(s, sk_q.shape[0] - 1)]
            for qi, q in enumerate(AUDIT_QS):
                got = float(row[qi])
                if not math.isfinite(got):
                    continue
                idx = q * (m - 1)
                # Order-statistic bracket: +-2 sigma of the binomial
                # rank noise a uniform m-sample carries at quantile q,
                # then widened by the alpha contract itself.
                slack = 2.0 * math.sqrt(m * q * (1.0 - q)) + 1.0
                lo_i = int(max(0, math.floor(idx - slack)))
                hi_i = int(min(m - 1, math.ceil(idx + slack)))
                lo_v, hi_v = float(sample[lo_i]), float(sample[hi_i])
                a = w.rel_acc
                lo_b = min(lo_v * (1 - a), lo_v * (1 + a))
                hi_b = max(hi_v * (1 - a), hi_v * (1 + a))
                exact = float(sample[int(round(idx))])
                rel = abs(got - exact) / max(abs(exact), 1e-12)
                worst_rel_err[s] = max(worst_rel_err.get(s, 0.0), rel)
                if not (lo_b - 1e-9 <= got <= hi_b + 1e-9):
                    violations += 1
                    new_reports.append(DriftReport(
                        name=w.name, stream=s, kind="rank-error",
                        quantile=q, sketch_value=got, sample_value=exact,
                        rel_err=rel, collapsed_frac=frac, sample_size=m,
                        audit_index=w.audits, wall_time=now,
                    ))
        prev = w.last_collapsed.get(s, 0.0)
        if _recommendable and prev <= _collapse_thr < frac:
            # Edge-clamped mass crossed the threshold on a stream that
            # cannot collapse: recommend the adaptive backend (counted
            # once per crossing, not per audit -- prev gates re-fires).
            telemetry.counter_inc(
                "accuracy.collapse_recommended", stream=s
            )
        if frac - prev > COLLAPSE_DRIFT:
            new_reports.append(DriftReport(
                name=w.name, stream=s, kind="collapse-drift",
                quantile=float("nan"), sketch_value=float("nan"),
                sample_value=float("nan"), rel_err=float("nan"),
                collapsed_frac=frac, sample_size=m,
                audit_index=w.audits, wall_time=now,
            ))
        w.last_collapsed[s] = frac
        telemetry.gauge_set(
            "accuracy.collapsed_mass_frac", frac, stream=s
        )
        if s in worst_rel_err:
            telemetry.gauge_set(
                "accuracy.rel_err", worst_rel_err[s], stream=s
            )
    with _lock:
        _audits_total += 1
        _violations_total += violations
        for r in new_reports:
            if len(_reports) < _MAX_REPORTS:
                _reports.append(r)
            else:
                _reports_dropped += 1
    telemetry.counter_inc("accuracy.audits")
    if violations:
        telemetry.counter_inc("accuracy.violations", float(violations))
    return violations


def reports() -> List[DriftReport]:
    """The recorded drift reports, oldest first (bounded at 1024; the
    overflow count is in :func:`summary`).  An empty list is the
    healthy steady state."""
    with _lock:
        return list(_reports)


def summary() -> dict:
    """JSON-safe audit summary (rides ``telemetry.snapshot()`` when the
    layer is armed).  Zero audits with watches registered means the
    interval has not elapsed yet, not a failure."""
    with _lock:
        return {
            "watched": len(_watches),
            "audits": _audits_total,
            "violations": _violations_total,
            "reports": len(_reports),
            "reports_dropped": _reports_dropped,
        }
