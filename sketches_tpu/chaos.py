"""Seeded chaos-soak harness: every injected fault must be detected or
provably harmless.

``python -m sketches_tpu.chaos --steps N --seed S`` drives a mixed
ingest / merge / query / checkpoint / wire workload against a small
sketch fleet (two value-partial batches, the distributed fold's shape)
with the **integrity layer armed**, while a seeded campaign scheduler
injects faults from the ``sketches_tpu.faults`` sites:

========================  =================================================
site                      expected accounting
========================  =================================================
``wire.blob``             quarantine decode isolates exactly the corrupted
                          blobs; valid blobs decode bit-identically
``checkpoint.write``      torn write -> ``CheckpointCorrupt`` on restore
                          (previous checkpoint intact); crashed write ->
                          ``InjectedFault`` raised, previous file intact
``pallas.lowering``       query answers through the engine ladder with the
                          demotion recorded, or the floor re-raises
``pallas.ingest_variant`` a non-stock ingest construction rung fails to
                          lower -> the facade degrades to the stock rung
                          (health-ledger recorded), the replayed batch's
                          mass is exact, and no fault escapes
``mesh.shard``            the live-mask fold accounts the dead partial's
                          mass exactly (survivors stay an exact sketch)
``state.bitflip``         the integrity checker / fingerprint lane catches
                          the corruption -- or the answers are proven
                          unchanged within the alpha contract (harmless)
========================  =================================================

Every fault event lands in the verdict JSON as ``detected``,
``harmless``, or (the failure mode the harness exists to catch)
``undetected``; any ``undetected`` event -- or any workload-level
bookkeeping mismatch -- makes the campaign exit non-zero.  The whole
campaign is seeded (``np.random.default_rng(seed)`` plus the fault
plans' own seeds): a failing run replays exactly.

``--campaign elastic`` runs the ELASTIC campaign: one live fleet keeps
ingesting while shards and whole hosts are killed mid-stream and the
mesh is regrown onto 1/2/4/8 devices (``mesh.shard`` /
``mesh.host_loss`` / ``dcn.partition`` / ``reshard.torn``); every fault
must be **detected** or **recovered** -- the survivors' fold carries the
expected surviving mass bit-exactly, the dead capacity's mass is
itemized per stream, torn reshards leave the original fleet intact, and
the armed integrity layer's fingerprint lane verifies every reshard
boundary.

``--campaign serve`` runs the SERVING campaign instead: a seeded Zipf
tenant mix drives a :class:`sketches_tpu.serve.SketchServer` (ingest /
query / batched flush) while the ``serve.*`` sites inject stragglers,
forced queue overflows, and cache poison.  The accounting contract is
the serving tier's robustness envelope: every injected fault must be
**shed** (``ServeOverload``, structured reason), **hedged** around
(answer bit-identical to a direct engine query), or **detected**
(poisoned cache entry quarantined and recomputed, answer exact) -- and
the tenants' total mass must be conserved.  Anything else is
``undetected`` and fails the run.

``--campaign windowed`` runs the TIME-WINDOW campaign: windowed rings
(a serve-fronted dense ring, an adaptive ladder ring, and -- given >= 2
devices -- a mesh-backed ring) rotate under a virtual clock while
``window.rotate_torn`` tears rotations mid-ingest, checkpoint writes
tear, wire envelopes corrupt, reshards tear mid-rotation, and the kill
switch flips.  The accounting contract: every window query is
bit-identical to the host-side oracle merge of its covered buckets,
the per-bucket mass ledger is EXACT (``==``, never approximately) at
every step, a torn rotation/reshard leaves the ring bit-identical, and
a poisoned serve cache entry recomputes -- anything else is
``undetected`` and fails the run.

``--campaign fabric`` runs the SHARDED-SERVE campaign: a 4-host
:class:`sketches_tpu.fabric.ServeFabric` serves 4 tenants at
replication 3 while hosts are killed mid-ingest (tenants must re-home
onto fingerprint-verified replicas, dropped mass itemized EXACTLY per
stream), primaries are partitioned (reads degrade to declared-staleness
replicas, writes refuse, beyond-bound replicas refuse loudly), replica
state is silently corrupted (``fabric.replica_stale`` -- a corrupt
replica must NEVER serve), partition heals and replica handoffs tear
atomically, and the ``SKETCHES_TPU_FABRIC`` kill switch flips.  The
accounting contract: every served answer bit-identical to its oracle
fold, the mass ledger closes with ``==`` at every step and every
failover -- anything else is ``undetected`` and fails the run.  With
the switch disarmed the campaign probes that every construction
refuses loudly instead.

Failure modes: the harness itself raises ``SketchValueError`` on
invalid arguments; a campaign that cannot complete (unexpected
exception escaping an un-faulted op) records the error in the verdict
and exits 1 rather than crashing silently.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import Any, Dict, List, Optional

import numpy as np

from sketches_tpu import faults, integrity, resilience, telemetry, tracing
from sketches_tpu.resilience import (
    CheckpointCorrupt,
    InjectedFault,
    IntegrityError,
    SketchError,
    SketchValueError,
)

__all__ = [
    "run_campaign",
    "run_serve_campaign",
    "run_elastic_campaign",
    "run_adaptive_campaign",
    "run_windowed_campaign",
    "run_fabric_campaign",
    "main",
]

#: Campaign shape: small enough that a 500+-step soak runs in CI
#: minutes, big enough that every store/seam carries real mass.
_N_STREAMS = 16
_N_BINS = 128
_BATCH = 32
_REL_ACC = 0.02

#: Per-step fault probability (when a step's op has a compatible site).
_FAULT_P = 0.25

#: Quantiles the harmless-verification compares.
_QS = (0.5, 0.9, 0.99)


@dataclasses.dataclass
class _Campaign:
    """Mutable campaign state: the two value-partials, the bookkeeping
    the verdict is audited against, and the fault event log."""

    spec: Any
    partials: List[Any]
    rng: Any  # a seeded np.random.default_rng(seed) Generator
    tmpdir: str
    expected_count: float = 0.0
    dropped_count: float = 0.0  # mass accounted lost (dead shards)
    last_good_ckpt: Optional[str] = None
    last_good_count: float = 0.0
    events: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    errors: List[str] = dataclasses.field(default_factory=list)


def _stack_partials(c: _Campaign):
    """The two partial states as one stacked [2, N, B] pytree."""
    import jax
    import jax.numpy as jnp

    return jax.tree.map(
        lambda *xs: jnp.stack(xs), c.partials[0].state, c.partials[1].state
    )


def _fold(c: _Campaign, live=None):
    from sketches_tpu.parallel import fold_live_partials

    if live is None:
        live = np.ones((2,), bool)
    return fold_live_partials(c.spec, _stack_partials(c), live)


def _quantiles(c: _Campaign, state) -> np.ndarray:
    from sketches_tpu.batched import quantile
    import jax.numpy as jnp

    return np.asarray(quantile(c.spec, state, jnp.asarray(_QS)))


def _total_count(c: _Campaign) -> float:
    return float(
        np.asarray(c.partials[0].state.count, np.float64).sum()
        + np.asarray(c.partials[1].state.count, np.float64).sum()
    )


def _event(c: _Campaign, step: int, site: str, outcome: str, detail: str = ""):
    c.events.append(
        {"step": step, "site": site, "outcome": outcome, "detail": detail}
    )


# ---------------------------------------------------------------------------
# Workload ops (no fault armed)
# ---------------------------------------------------------------------------


def _op_ingest(c: _Campaign, step: int) -> None:
    vals = c.rng.lognormal(0.0, 0.5, (_N_STREAMS, _BATCH)).astype(np.float32)
    c.partials[step % 2].add(vals)
    c.expected_count += _N_STREAMS * _BATCH


def _op_query(c: _Campaign, step: int) -> None:
    folded = _fold(c)
    q = _quantiles(c, folded)
    live = q[np.asarray(folded.count) > 0]
    if live.size and not np.isfinite(live).all():
        raise SketchError("query returned non-finite quantiles")


def _op_merge(c: _Campaign, step: int) -> None:
    from sketches_tpu.batched import BatchedDDSketch

    other = BatchedDDSketch(_N_STREAMS, spec=c.spec)
    vals = c.rng.lognormal(0.0, 0.5, (_N_STREAMS, _BATCH)).astype(np.float32)
    other.add(vals)
    c.partials[step % 2].merge(other)
    c.expected_count += _N_STREAMS * _BATCH


def _op_checkpoint(c: _Campaign, step: int) -> None:
    from sketches_tpu import checkpoint

    path = os.path.join(c.tmpdir, "soak.ckpt")
    folded = _fold(c)
    checkpoint.save_state(path, c.spec, folded)
    spec2, state2 = checkpoint.restore_state(path)
    if abs(
        float(np.asarray(state2.count, np.float64).sum()) - _total_count(c)
    ) > 1.0:
        raise SketchError("checkpoint round trip lost mass")
    c.last_good_ckpt = path
    c.last_good_count = _total_count(c)


def _op_wire(c: _Campaign, step: int) -> None:
    from sketches_tpu.pb import wire

    p = c.partials[step % 2]
    blobs = wire.state_to_bytes(c.spec, p.state)
    _, report = wire.bytes_to_state(c.spec, blobs, errors="quarantine")
    if report:
        raise SketchError(
            f"clean wire round trip quarantined {report.n_quarantined} blobs"
        )


_OPS = (_op_ingest, _op_query, _op_merge, _op_checkpoint, _op_wire)
_OP_WEIGHTS = (0.45, 0.2, 0.15, 0.1, 0.1)


# ---------------------------------------------------------------------------
# Fault drivers: arm a site, drive the workload through it, classify
# ---------------------------------------------------------------------------


def _fault_wire_blob(c: _Campaign, step: int) -> str:
    from sketches_tpu.pb import wire

    p = c.partials[step % 2]
    blobs = wire.state_to_bytes(c.spec, p.state)
    with faults.active(
        {faults.WIRE_BLOB: dict(mode="corrupt", fraction=0.2, seed=step)}
    ) as plans:
        _, report = wire.bytes_to_state(c.spec, blobs, errors="quarantine")
        fired = plans[faults.WIRE_BLOB].fired
    if fired == 0:
        return "skipped"
    return "detected" if report.n_quarantined == fired else "undetected"


def _fault_checkpoint(c: _Campaign, step: int) -> str:
    from sketches_tpu import checkpoint

    path = os.path.join(c.tmpdir, "torn.ckpt")
    folded = _fold(c)
    checkpoint.save_state(path, c.spec, folded)  # a good previous file
    mode = "truncate" if step % 2 else "raise"
    with faults.active({faults.CHECKPOINT_WRITE: dict(mode=mode, times=1)}):
        try:
            checkpoint.save_state(path, c.spec, folded)
            crashed = False
        except InjectedFault:
            crashed = True  # crash before the atomic rename
    if crashed:
        # The previous checkpoint must have survived the crash intact.
        checkpoint.restore_state(path)
        return "detected"
    try:
        checkpoint.restore_state(path)
    except CheckpointCorrupt:
        return "detected"
    return "undetected"


def _fault_lowering(c: _Campaign, step: int) -> str:
    # Query through a FACADE (not the pure quantile function): the
    # lowering-fault seam lives in the facade's engine-ladder dispatch.
    p = c.partials[step % 2]
    before = resilience.health()["counters"].get("downgrades", 0)
    with faults.active({faults.PALLAS_LOWERING: dict(times=1)}) as plans:
        try:
            q = np.asarray(p.get_quantile_values(list(_QS)))
            if not np.isfinite(q[np.asarray(p.state.count) > 0]).all():
                return "undetected"
        except (InjectedFault, resilience.EngineUnavailable):
            return "detected"  # the floor re-raised, loudly
        fired = plans[faults.PALLAS_LOWERING].fired
    if fired == 0:
        return "skipped"
    after = resilience.health()["counters"].get("downgrades", 0)
    return "detected" if after > before else "undetected"


def _fault_ingest_variant(c: _Campaign, step: int) -> str:
    # The ingest construction-rung ladder (DESIGN.md 2-r17): a variant
    # lowering failure must degrade to the stock rung -- recorded in the
    # health ledger -- with the replayed batch's mass exact, never a
    # fault escaping or a demotion all the way to XLA.  The campaign's
    # own partials are 16-stream (XLA engine), so this driver runs a
    # kernel-shaped facade of its own; after the first demotion the
    # facade pins to stock and later draws report "skipped".
    from sketches_tpu import kernels
    from sketches_tpu.batched import BatchedDDSketch

    if kernels.choose_ingest_engine(c.spec, weighted=False) == "stock":
        return "skipped"  # kill switch pinned the ladder to stock
    sk = getattr(c, "_variant_sk", None)
    if sk is None:
        sk = BatchedDDSketch(
            128, relative_accuracy=_REL_ACC, n_bins=_N_BINS, engine="pallas"
        )
        c._variant_sk = sk
    vals = np.exp(
        c.rng.normal(0.0, 1.0, (128, _BATCH * 4))
    ).astype(np.float32)
    before_count = float(np.asarray(sk.state.count, np.float64).sum())
    before = resilience.health()["counters"].get("downgrades", 0)
    with faults.active(
        {faults.PALLAS_INGEST_VARIANT: dict(times=1)}
    ) as plans:
        try:
            sk.add(vals)
        except (InjectedFault, resilience.EngineUnavailable):
            return "undetected"  # the rung must degrade, not raise
        fired = plans[faults.PALLAS_INGEST_VARIANT].fired
    if fired == 0:
        return "skipped"  # first add recenters (XLA) / already demoted
    after = resilience.health()["counters"].get("downgrades", 0)
    if after <= before or sk._add_pallas is None:
        return "undetected"
    total = float(np.asarray(sk.state.count, np.float64).sum())
    exact = abs(total - before_count - float(vals.size)) <= 1e-6 * vals.size
    return "detected" if exact else "undetected"


def _fault_mesh_shard(c: _Campaign, step: int) -> str:
    dead = step % 2
    live = np.ones((2,), bool)
    live[dead] = False
    dead_count = float(
        np.asarray(c.partials[dead].state.count, np.float64).sum()
    )
    survived = _fold(c, live=live)
    got = float(np.asarray(survived.count, np.float64).sum())
    if abs(got + dead_count - _total_count(c)) > 1.0:
        return "undetected"
    # Account the loss the way merge_partial does, then restore the
    # partial (simulation: the "dead" shard is still readable).
    resilience.bump("mesh.dead_shards", 1)
    return "detected"


def _fault_bitflip(c: _Campaign, step: int) -> str:
    p = c.partials[step % 2]
    pre_state = p.state  # keep the uncorrupted pytree (flips copy)
    pre_q = _quantiles(c, _fold(c))
    fp_pre = integrity.fingerprint(c.spec, pre_state)
    with faults.active({faults.STATE_BITFLIP: dict(seed=step, times=1)}):
        flips = faults.state_bitflips(_N_STREAMS, _N_BINS)
    corrupted = faults.apply_state_bitflips(pre_state, flips)
    outcome = "undetected"
    try:
        report = integrity.verify_state(
            c.spec, corrupted, seam="chaos.bitflip", errors="quarantine"
        )
        if report:
            outcome = "detected"  # the standalone invariant checker
        else:
            # Invariants intact: the cross-boundary fingerprint (the
            # checkpoint/fold lane's comparison against the pre-flip
            # reference) is the second detector.
            try:
                fp_rep = integrity.verify_restore(
                    c.spec, corrupted, stored_fp=fp_pre,
                    seam="chaos.bitflip.fp",
                )
                if fp_rep:
                    outcome = "detected"  # quarantine mode: reported
                else:
                    # Both detectors passed: prove the flip harmless --
                    # the answers are unchanged within the alpha contract.
                    p.state = corrupted
                    post_q = _quantiles(c, _fold(c))
                    same = np.allclose(
                        post_q, pre_q, rtol=4 * _REL_ACC, atol=1e-6,
                        equal_nan=True,
                    )
                    outcome = "harmless" if same else "undetected"
            except IntegrityError:
                outcome = "detected"
    except IntegrityError:
        outcome = "detected"
    finally:
        # Repair must make the corrupted state consistent again, then
        # the campaign resumes from the uncorrupted original.
        fixed, _rep = integrity.repair(c.spec, corrupted)
        if integrity.check_state(c.spec, fixed):
            outcome = "undetected"
        p.state = pre_state
    return outcome


_FAULT_DRIVERS = {
    faults.WIRE_BLOB: _fault_wire_blob,
    faults.CHECKPOINT_WRITE: _fault_checkpoint,
    faults.PALLAS_LOWERING: _fault_lowering,
    faults.PALLAS_INGEST_VARIANT: _fault_ingest_variant,
    faults.MESH_SHARD: _fault_mesh_shard,
    faults.STATE_BITFLIP: _fault_bitflip,
}


def _classify_forensics(site: str, outcome: str, step: int) -> None:
    """Every fault classification dumps a forensic bundle while the
    flight recorder is armed: the bundle's triggering trace is the most
    recent request trace (the serve campaign's in-flight request; the
    core campaign runs untraced ops, so its bundles carry recorder
    events without a trigger trace).  Disarmed this is one bool test;
    a dump failure is swallowed -- forensics never fail a campaign."""
    if not tracing._ACTIVE:
        return
    try:
        tracing.dump_forensics(
            f"chaos.{site}",
            trace=tracing.last_trace(),
            detail={"site": site, "outcome": outcome, "step": step},
        )
    except Exception:  # noqa: BLE001 - forensics must not fail the soak
        pass


# ---------------------------------------------------------------------------
# Campaign
# ---------------------------------------------------------------------------


def run_campaign(
    steps: int,
    seed: int,
    mode: str = "raise",
    tmpdir: Optional[str] = None,
) -> Dict[str, Any]:
    """Run a seeded chaos campaign -> the verdict document (JSON-safe).

    Arms the integrity layer (``mode``: ``"raise"`` or ``"quarantine"``)
    for the duration and restores the prior arming state on exit.  The
    verdict's ``ok`` is True iff every injected fault was accounted
    ``detected`` or ``harmless``, the final fold conserves the expected
    total mass, and no unexpected error escaped an op.  Raises
    ``SketchValueError`` for non-positive ``steps``; campaign-level
    failures are *reported*, not raised.
    """
    if steps <= 0:
        raise SketchValueError("steps must be positive")
    from sketches_tpu.batched import BatchedDDSketch, SketchSpec

    was_active, was_mode = integrity.enabled(), integrity.mode()
    faults.disarm()
    integrity.arm(mode)
    own_tmp = None
    if tmpdir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="sketches_chaos_")
        tmpdir = own_tmp.name
    try:
        spec = SketchSpec(relative_accuracy=_REL_ACC, n_bins=_N_BINS)
        c = _Campaign(
            spec=spec,
            partials=[
                BatchedDDSketch(_N_STREAMS, spec=spec) for _ in range(2)
            ],
            rng=np.random.default_rng(seed),
            tmpdir=tmpdir,
        )
        sites = tuple(_FAULT_DRIVERS)
        for step in range(steps):
            op = c.rng.choice(len(_OPS), p=_OP_WEIGHTS)
            try:
                _OPS[op](c, step)
            except Exception as e:  # un-faulted op must not fail
                c.errors.append(f"step {step} op {_OPS[op].__name__}: {e!r}")
                break
            if c.rng.random() < _FAULT_P:
                site = sites[int(c.rng.integers(len(sites)))]
                try:
                    outcome = _FAULT_DRIVERS[site](c, step)
                except Exception as e:
                    outcome = "undetected"
                    c.errors.append(f"step {step} site {site}: {e!r}")
                if outcome != "skipped":
                    _event(c, step, site, outcome)
                    _classify_forensics(site, outcome, step)
        # Final audit: the fold conserves every ingested value.
        final = float(np.asarray(_fold(c).count, np.float64).sum())
        conserved = abs(final - c.expected_count) <= max(
            1.0, 1e-5 * c.expected_count
        )
        if not conserved:
            c.errors.append(
                f"final mass {final:g} != expected {c.expected_count:g}"
            )
        outcomes: Dict[str, int] = {}
        for ev in c.events:
            outcomes[ev["outcome"]] = outcomes.get(ev["outcome"], 0) + 1
        ok = (
            conserved
            and not c.errors
            and outcomes.get("undetected", 0) == 0
        )
        return {
            "steps": steps,
            "seed": seed,
            "mode": mode,
            "ok": ok,
            "n_faults": len(c.events),
            "outcomes": outcomes,
            "events": c.events,
            "errors": c.errors,
            "expected_count": c.expected_count,
            "final_count": final,
            "integrity_reports": len(integrity.reports()),
            "forensics": tracing.stats() if tracing.enabled() else None,
            "health": resilience.health(),
            # The end-of-campaign telemetry snapshot rides the verdict
            # when the metrics layer is armed (the CI chaos job), so the
            # artifact carries the integrity.*/resilience.* counters --
            # and stays mergeable with the other jobs' snapshots.  None
            # (not {}) when disarmed: an absent layer, not an idle one.
            "telemetry": telemetry.snapshot() if telemetry.enabled() else None,
        }
    finally:
        faults.disarm()
        if was_active:
            integrity.arm(was_mode)
        else:
            integrity.disarm()
        if own_tmp is not None:
            own_tmp.cleanup()


# ---------------------------------------------------------------------------
# Serving campaign (the serve.* sites)
# ---------------------------------------------------------------------------

#: Serving-campaign shape: a few tenants (two sharing a spec, so the
#: cross-tenant fused dispatch path is exercised), small states.
_SERVE_TENANTS = ("alpha", "beta", "gamma", "delta")
_SERVE_STREAMS = 8
_SERVE_QS = ((0.5,), (0.9,), (0.5, 0.99), (0.25, 0.5, 0.9, 0.99))


def _serve_direct(server, tenant: str, qs) -> np.ndarray:
    """The oracle for a served answer: the tenant facade's own fused
    query (bit-identical is the contract -- serving must never change
    an answer, only its latency)."""
    return np.asarray(server.tenant(tenant).get_quantile_values(list(qs)))


def _serve_fault_straggler(server, rng, counts) -> str:
    from sketches_tpu.resilience import SketchError

    tenant = _SERVE_TENANTS[int(rng.integers(len(_SERVE_TENANTS)))]
    qs = _SERVE_QS[int(rng.integers(len(_SERVE_QS)))]
    before = server.stats()["hedges"]
    with faults.active({faults.SERVE_STRAGGLER: dict(times=1)}) as plans:
        try:
            result = server.query(tenant, qs)
        except SketchError:
            return "undetected"  # a straggler must be hedged, not failed
        fired = plans[faults.SERVE_STRAGGLER].fired
    if fired == 0:
        return "skipped"  # answered from cache: no dispatch to straggle
    hedged = server.stats()["hedges"] > before
    exact = np.array_equal(
        result.values, _serve_direct(server, tenant, qs), equal_nan=True
    )
    return "hedged" if (hedged and exact) else "undetected"


def _serve_fault_overflow(server, rng, counts) -> str:
    from sketches_tpu.resilience import ServeOverload

    tenant = _SERVE_TENANTS[int(rng.integers(len(_SERVE_TENANTS)))]
    before = server.stats()["shed"]
    with faults.active({faults.SERVE_QUEUE_OVERFLOW: dict(times=1)}) as plans:
        try:
            # A fresh quantile defeats the admission cache so the
            # request reaches the overflow seam.
            server.submit(tenant, (0.013 + 0.02 * (counts["overflow"] % 17),))
            fired = plans[faults.SERVE_QUEUE_OVERFLOW].fired
            if fired == 0:
                return "skipped"
            return "undetected"  # the forced overflow was not shed
        except ServeOverload as e:
            counts["overflow"] += 1
            shed_counted = server.stats()["shed"] > before
            return (
                "shed" if (e.reason == "injected" and shed_counted)
                else "undetected"
            )
    return "undetected"


def _serve_fault_cache_poison(server, rng, counts) -> str:
    tenant = _SERVE_TENANTS[int(rng.integers(len(_SERVE_TENANTS)))]
    qs = _SERVE_QS[int(rng.integers(len(_SERVE_QS)))]
    server.query(tenant, qs)  # ensure the entry exists (fill or hit)
    before = server.stats()["cache_poisoned"]
    with faults.active({faults.SERVE_CACHE_POISON: dict(times=1)}) as plans:
        result = server.query(tenant, qs)
        fired = plans[faults.SERVE_CACHE_POISON].fired
    if fired == 0:
        return "skipped"  # cache disarmed / entry evicted: nothing to poison
    detected = server.stats()["cache_poisoned"] > before
    exact = np.array_equal(
        result.values, _serve_direct(server, tenant, qs), equal_nan=True
    )
    return "detected" if (detected and exact and not result.cached) \
        else "undetected"


_SERVE_FAULT_DRIVERS = {
    faults.SERVE_STRAGGLER: _serve_fault_straggler,
    faults.SERVE_QUEUE_OVERFLOW: _serve_fault_overflow,
    faults.SERVE_CACHE_POISON: _serve_fault_cache_poison,
}


def run_serve_campaign(steps: int, seed: int) -> Dict[str, Any]:
    """Run the seeded serving chaos campaign -> the verdict document.

    Drives a 4-tenant :class:`~sketches_tpu.serve.SketchServer` (two
    tenants share a spec, exercising the cross-tenant fused dispatch)
    with a seeded mixed read/write workload while the three ``serve.*``
    fault sites inject.  ``ok`` is True iff every injected fault was
    shed, hedged around, or detected (answers bit-identical to a direct
    engine query), total tenant mass is conserved, and no unexpected
    error escaped.  Raises ``SketchValueError`` for non-positive
    ``steps``; campaign-level failures are reported, not raised.
    """
    if steps <= 0:
        raise SketchValueError("steps must be positive")
    from sketches_tpu import serve
    from sketches_tpu.batched import SketchSpec

    faults.disarm()
    rng = np.random.default_rng(seed)
    shared = SketchSpec(relative_accuracy=_REL_ACC, n_bins=_N_BINS)
    own = SketchSpec(relative_accuracy=0.01, n_bins=_N_BINS)
    server = serve.SketchServer(
        serve.ServeConfig(max_queue_depth=64, tenant_quota=16)
    )
    specs = {"alpha": shared, "beta": shared, "gamma": own, "delta": own}
    for name in _SERVE_TENANTS:
        server.add_tenant(name, _SERVE_STREAMS, spec=specs[name])
    expected = {name: 0.0 for name in _SERVE_TENANTS}
    events: List[Dict[str, Any]] = []
    errors: List[str] = []
    counts = {"overflow": 0}
    sites = tuple(_SERVE_FAULT_DRIVERS)

    def _ingest(step: int) -> None:
        name = _SERVE_TENANTS[int(rng.integers(len(_SERVE_TENANTS)))]
        vals = rng.lognormal(0.0, 0.5, (_SERVE_STREAMS, _BATCH))
        server.ingest(name, vals.astype(np.float32))
        expected[name] += _SERVE_STREAMS * _BATCH

    def _query(step: int) -> None:
        name = _SERVE_TENANTS[int(rng.integers(len(_SERVE_TENANTS)))]
        qs = _SERVE_QS[int(rng.integers(len(_SERVE_QS)))]
        result = server.query(name, qs)
        if not np.array_equal(
            result.values, _serve_direct(server, name, qs), equal_nan=True
        ):
            raise SketchError(
                f"served answer for {name!r} diverged from the engine"
            )

    def _batch(step: int) -> None:
        tickets = []
        for name in _SERVE_TENANTS:
            qs = _SERVE_QS[int(rng.integers(len(_SERVE_QS)))]
            tickets.append(server.submit(name, qs))
        results = server.flush()
        for tk in tickets:
            if tk.result is None and tk.id not in results:
                raise SketchError("an admitted ticket went unanswered")

    ops = (_ingest, _query, _batch)
    weights = (0.4, 0.4, 0.2)
    for step in range(steps):
        op = int(rng.choice(len(ops), p=weights))
        try:
            ops[op](step)
        except Exception as e:  # un-faulted serving op must not fail
            errors.append(f"step {step} op {ops[op].__name__}: {e!r}")
            break
        if rng.random() < _FAULT_P:
            site = sites[int(rng.integers(len(sites)))]
            try:
                outcome = _SERVE_FAULT_DRIVERS[site](server, rng, counts)
            except Exception as e:
                outcome = "undetected"
                errors.append(f"step {step} site {site}: {e!r}")
            if outcome != "skipped":
                events.append({"step": step, "site": site, "outcome": outcome})
                _classify_forensics(site, outcome, step)
    # Mass audit: every ingested value is still in its tenant's sketch.
    conserved = True
    for name in _SERVE_TENANTS:
        got = float(
            np.asarray(
                server.tenant(name).state.count, np.float64
            ).sum()
        )
        if abs(got - expected[name]) > max(1.0, 1e-5 * expected[name]):
            conserved = False
            errors.append(
                f"tenant {name!r} mass {got:g} != expected {expected[name]:g}"
            )
    outcomes: Dict[str, int] = {}
    for ev in events:
        outcomes[ev["outcome"]] = outcomes.get(ev["outcome"], 0) + 1
    ok = conserved and not errors and outcomes.get("undetected", 0) == 0
    return {
        "campaign": "serve",
        "steps": steps,
        "seed": seed,
        "ok": ok,
        "n_faults": len(events),
        "outcomes": outcomes,
        "events": events,
        "errors": errors,
        "expected_count": sum(expected.values()),
        "serve_stats": server.stats(),
        "health": resilience.health(),
        "telemetry": telemetry.snapshot() if telemetry.enabled() else None,
        # Recorder accounting rides the verdict when armed (None when
        # the layer is absent, matching the telemetry convention).
        "forensics": tracing.stats() if tracing.enabled() else None,
    }


# ---------------------------------------------------------------------------
# Elastic campaign (kill-and-regrow across mesh sizes)
# ---------------------------------------------------------------------------

#: Elastic-campaign shape: small states, batch width divisible by every
#: mesh size the campaign regrows onto (1/2/4/8).
_ELASTIC_STREAMS = 8
_ELASTIC_BATCH = 32


def _elastic_sizes() -> List[int]:
    """Mesh sizes the campaign cycles over: the 1/2/4/8 curve clipped to
    the devices this process actually has (the CI job provisions an
    8-device virtual CPU mesh; a 1-device host still soaks the
    fold/accounting invariants, just without growth)."""
    import jax

    n = len(jax.devices())
    return [k for k in (1, 2, 4, 8) if k <= n]


@dataclasses.dataclass
class _ElasticCampaign:
    """Mutable elastic-campaign state: ONE live fleet that keeps being
    killed and regrown, a 'remote host' batched partial for the DCN
    fold, and the exact per-stream mass ledgers the verdict audits."""

    spec: Any
    fleet: Any  # the current DistributedDDSketch
    remote: Any  # a BatchedDDSketch standing in for a second host
    rng: Any
    tmpdir: str
    expected: Any = None  # np [N] f64: mass the LIVE fleet must hold
    remote_expected: Any = None  # np [N] f64: the remote host's mass
    dropped: Any = None  # np [N] f64: mass itemized lost to dead shards
    reshards: int = 0
    sizes_visited: Any = dataclasses.field(default_factory=set)
    events: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    errors: List[str] = dataclasses.field(default_factory=list)


def _elastic_fleet_count(c: _ElasticCampaign) -> np.ndarray:
    import jax

    return np.asarray(
        jax.device_get(c.fleet.merged_state().count), np.float64
    )


def _elastic_audit(c: _ElasticCampaign, where: str) -> bool:
    """Exact per-stream mass accounting: the live fleet's fold must hold
    EXACTLY the expected surviving mass (unit weights -> integer-valued
    f32 counts, compared exactly)."""
    got = _elastic_fleet_count(c)
    if np.array_equal(got, c.expected):
        return True
    c.errors.append(
        f"{where}: fleet mass {got.sum():g} != expected"
        f" {c.expected.sum():g} (first bad stream"
        f" {int(np.nonzero(got != c.expected)[0][0])})"
    )
    return False


def _elastic_ingest(c: _ElasticCampaign, step: int) -> None:
    vals = c.rng.lognormal(0.0, 0.5, (_ELASTIC_STREAMS, _ELASTIC_BATCH))
    c.fleet.add(vals.astype(np.float32))
    c.expected = c.expected + _ELASTIC_BATCH


def _elastic_query(c: _ElasticCampaign, step: int) -> None:
    q = np.asarray(c.fleet.get_quantile_values(list(_QS)))
    live = q[_elastic_fleet_count(c) > 0]
    if live.size and not np.isfinite(live).all():
        raise SketchError("elastic query returned non-finite quantiles")


def _elastic_remote_ingest(c: _ElasticCampaign, step: int) -> None:
    vals = c.rng.lognormal(0.0, 0.5, (_ELASTIC_STREAMS, _ELASTIC_BATCH))
    c.remote.add(vals.astype(np.float32))
    c.remote_expected = c.remote_expected + _ELASTIC_BATCH


def _elastic_reshard(c: _ElasticCampaign, step: int) -> None:
    """Clean grow/shrink: regrow onto the next seeded mesh size with
    ZERO lost mass (report must say exact, no dead shards)."""
    from sketches_tpu.parallel import SketchMesh

    sizes = _elastic_sizes()
    k = int(sizes[int(c.rng.integers(len(sizes)))])
    fleet, report = c.fleet.reshard(
        mesh=SketchMesh(k, n_hosts=2 if k >= 2 else 1)
    )
    c.fleet = fleet
    c.reshards += 1
    c.sizes_visited.add(k)
    if report.n_dead or not report.exact:
        raise SketchError(
            f"clean reshard to {k} devices reported n_dead="
            f"{report.n_dead} exact={report.exact}"
        )
    _elastic_audit(c, f"step {step} reshard->{k}")


def _elastic_checkpoint(c: _ElasticCampaign, step: int) -> None:
    """Partials checkpoint -> restore onto a DIFFERENT mesh size; the
    restored fold must carry the exact expected mass."""
    from sketches_tpu import checkpoint
    from sketches_tpu.parallel import SketchMesh

    sizes = _elastic_sizes()
    k = int(sizes[int(c.rng.integers(len(sizes)))])
    path = os.path.join(c.tmpdir, "elastic.ckpt")
    checkpoint.save(path, c.fleet, partials=True)
    c.fleet = checkpoint.restore_distributed(
        path, mesh=SketchMesh(k, n_hosts=2 if k >= 2 else 1)
    )
    c.sizes_visited.add(k)
    _elastic_audit(c, f"step {step} ckpt-restore->{k}")


_ELASTIC_OPS = (
    _elastic_ingest, _elastic_query, _elastic_remote_ingest,
    _elastic_reshard, _elastic_checkpoint,
)
_ELASTIC_OP_WEIGHTS = (0.45, 0.15, 0.1, 0.2, 0.1)


def _elastic_fault_shard(c: _ElasticCampaign, step: int) -> str:
    """Kill one value shard mid-stream, regrow onto a different mesh
    size: the survivors' fold must be exact and the dead shard's mass
    itemized per stream -- 'recovered', anything else undetected."""
    import jax

    from sketches_tpu.parallel import SketchMesh

    k_now = c.fleet.n_value_shards
    if k_now < 2:
        return "skipped"
    dead = int(c.rng.integers(k_now))
    part_counts = np.asarray(
        jax.device_get(c.fleet.partials.count), np.float64
    )
    sizes = _elastic_sizes()
    k_next = int(sizes[int(c.rng.integers(len(sizes)))])
    with faults.active({faults.MESH_SHARD: dict(shards=(dead,))}):
        fleet, report = c.fleet.reshard(
            mesh=SketchMesh(k_next, n_hosts=2 if k_next >= 2 else 1)
        )
    c.fleet = fleet
    c.reshards += 1
    c.sizes_visited.add(k_next)
    if report.dead_shards != [dead] or not report.exact:
        return "undetected"
    if not np.array_equal(report.dropped_count, part_counts[dead]):
        return "undetected"  # itemization must match the shard exactly
    if report.fingerprints_match is False:
        return "undetected"
    c.expected = c.expected - report.dropped_count
    c.dropped = c.dropped + report.dropped_count
    return (
        "recovered"
        if _elastic_audit(c, f"step {step} kill-shard-{dead}->{k_next}")
        else "undetected"
    )


def _elastic_fault_host_loss(c: _ElasticCampaign, step: int) -> str:
    """Kill a whole host (every shard in one ICI group), regrow: same
    exactness contract as a single dead shard, host itemized."""
    import jax

    from sketches_tpu.parallel import SketchMesh

    if c.fleet.n_hosts < 2:
        return "skipped"
    host = int(c.rng.integers(c.fleet.n_hosts))
    shards = list(c.fleet._host_shards(host))
    part_counts = np.asarray(
        jax.device_get(c.fleet.partials.count), np.float64
    )
    sizes = _elastic_sizes()
    k_next = int(sizes[int(c.rng.integers(len(sizes)))])
    with faults.active({faults.MESH_HOST_LOSS: dict(shards=(host,))}):
        fleet, report = c.fleet.reshard(
            mesh=SketchMesh(k_next, n_hosts=2 if k_next >= 2 else 1)
        )
    c.fleet = fleet
    c.reshards += 1
    c.sizes_visited.add(k_next)
    if report.lost_hosts != (host,) or report.dead_shards != shards:
        return "undetected"
    if not report.exact or not np.array_equal(
        report.dropped_count, part_counts[shards].sum(axis=0)
    ):
        return "undetected"
    c.expected = c.expected - report.dropped_count
    c.dropped = c.dropped + report.dropped_count
    return (
        "recovered"
        if _elastic_audit(c, f"step {step} host-loss-{host}->{k_next}")
        else "undetected"
    )


def _elastic_fault_partition(c: _ElasticCampaign, step: int) -> str:
    """DCN partition at the cross-host fold: the unreachable host's
    partial is folded AROUND with its mass accounted -- detected, never
    silently zeroed.  Campaign state is untouched (the fold is a read)."""
    from sketches_tpu.parallel import fold_hosts

    before = resilience.health()["counters"].get("dcn.partitions", 0)
    with faults.active({faults.DCN_PARTITION: dict(shards=(1,))}):
        folded, report = fold_hosts(
            c.spec, [c.fleet.merged_state(), c.remote.state]
        )
    got = np.asarray(folded.count, np.float64)
    counted = resilience.health()["counters"].get("dcn.partitions", 0)
    ok = (
        report.n_dead == 1
        and report.dead_shards == [1]
        and np.array_equal(got, c.expected)
        and np.array_equal(report.dropped_count, c.remote_expected)
        and counted > before
    )
    return "detected" if ok else "undetected"


def _elastic_fault_torn(c: _ElasticCampaign, step: int) -> str:
    """A reshard torn between the survivor fold and the regrow must
    raise AND leave the original fleet fully intact (atomic reshard)."""
    sizes = _elastic_sizes()
    k_next = int(sizes[int(c.rng.integers(len(sizes)))])
    try:
        with faults.active({faults.RESHARD_TORN: dict(times=1)}):
            c.fleet.reshard(n_devices=k_next)
        return "undetected"  # the tear did not surface
    except InjectedFault:
        pass
    return (
        "detected"
        if _elastic_audit(c, f"step {step} torn-reshard->{k_next}")
        else "undetected"
    )


_ELASTIC_FAULT_DRIVERS = {
    faults.MESH_SHARD: _elastic_fault_shard,
    faults.MESH_HOST_LOSS: _elastic_fault_host_loss,
    faults.DCN_PARTITION: _elastic_fault_partition,
    faults.RESHARD_TORN: _elastic_fault_torn,
}


def run_elastic_campaign(
    steps: int,
    seed: int,
    mode: str = "raise",
    tmpdir: Optional[str] = None,
) -> Dict[str, Any]:
    """Run the seeded ELASTIC chaos campaign -> the verdict document.

    One live fleet ingests while the campaign kills shards and whole
    hosts mid-stream, regrows onto 1/2/4/8-device meshes (clipped to
    the devices this process has), round-trips partials checkpoints
    onto different mesh sizes, and crosses a simulated DCN fold --
    with the integrity layer armed (``mode``) so every reshard
    boundary's fingerprint lane verifies.  ``ok`` is True iff every
    injected fault was ``detected`` or ``recovered`` (kill-and-regrow
    with exact per-stream mass accounting: survivors' fold equals the
    expected surviving mass bit-exactly, dropped mass itemized), the
    final fold conserves the ledger, and no unexpected error escaped.
    Raises ``SketchValueError`` for non-positive ``steps``;
    campaign-level failures are reported, not raised.
    """
    if steps <= 0:
        raise SketchValueError("steps must be positive")
    from sketches_tpu.batched import BatchedDDSketch, SketchSpec
    from sketches_tpu.parallel import DistributedDDSketch, SketchMesh

    was_active, was_mode = integrity.enabled(), integrity.mode()
    faults.disarm()
    integrity.arm(mode)
    own_tmp = None
    if tmpdir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="sketches_elastic_")
        tmpdir = own_tmp.name
    try:
        spec = SketchSpec(relative_accuracy=_REL_ACC, n_bins=_N_BINS)
        sizes = _elastic_sizes()
        k0 = sizes[-1] if len(sizes) > 1 else sizes[0]
        c = _ElasticCampaign(
            spec=spec,
            fleet=DistributedDDSketch(
                _ELASTIC_STREAMS, spec=spec,
                mesh=SketchMesh(k0, n_hosts=2 if k0 >= 2 else 1),
            ),
            remote=BatchedDDSketch(_ELASTIC_STREAMS, spec=spec),
            rng=np.random.default_rng(seed),
            tmpdir=tmpdir,
            expected=np.zeros((_ELASTIC_STREAMS,), np.float64),
            remote_expected=np.zeros((_ELASTIC_STREAMS,), np.float64),
            dropped=np.zeros((_ELASTIC_STREAMS,), np.float64),
        )
        c.sizes_visited.add(k0)
        fault_sites = tuple(_ELASTIC_FAULT_DRIVERS)
        for step in range(steps):
            op = c.rng.choice(len(_ELASTIC_OPS), p=_ELASTIC_OP_WEIGHTS)
            try:
                _ELASTIC_OPS[op](c, step)
            except Exception as e:  # un-faulted op must not fail
                c.errors.append(
                    f"step {step} op {_ELASTIC_OPS[op].__name__}: {e!r}"
                )
                break
            if c.rng.random() < _FAULT_P:
                site = fault_sites[int(c.rng.integers(len(fault_sites)))]
                try:
                    outcome = _ELASTIC_FAULT_DRIVERS[site](c, step)
                except Exception as e:
                    outcome = "undetected"
                    c.errors.append(f"step {step} site {site}: {e!r}")
                if outcome != "skipped":
                    c.events.append(
                        {"step": step, "site": site, "outcome": outcome}
                    )
                    _classify_forensics(site, outcome, step)
        # Final audit: surviving mass exact, dropped mass itemized --
        # every ingested value is either in the fleet or in the ledger.
        conserved = _elastic_audit(c, "final")
        outcomes: Dict[str, int] = {}
        for ev in c.events:
            outcomes[ev["outcome"]] = outcomes.get(ev["outcome"], 0) + 1
        ok = (
            conserved
            and not c.errors
            and outcomes.get("undetected", 0) == 0
        )
        return {
            "campaign": "elastic",
            "steps": steps,
            "seed": seed,
            "mode": mode,
            "ok": ok,
            "n_faults": len(c.events),
            "outcomes": outcomes,
            "events": c.events,
            "errors": c.errors,
            "reshards": c.reshards,
            "mesh_sizes_visited": sorted(int(k) for k in c.sizes_visited),
            "expected_count": float(c.expected.sum()),
            "final_count": float(_elastic_fleet_count(c).sum()),
            "dropped_count": float(c.dropped.sum()),
            "integrity_reports": len(integrity.reports()),
            "health": resilience.health(),
            "forensics": tracing.stats() if tracing.enabled() else None,
            "telemetry": telemetry.snapshot() if telemetry.enabled() else None,
        }
    finally:
        faults.disarm()
        if was_active:
            integrity.arm(was_mode)
        else:
            integrity.disarm()
        if own_tmp is not None:
            own_tmp.cleanup()


# ---------------------------------------------------------------------------
# Adaptive campaign (the accuracy-backend soak)
# ---------------------------------------------------------------------------

#: Adaptive-campaign shape: few streams, narrow windows (so regime
#: drift genuinely forces collapses), bounded per-stream value logs for
#: the alpha-contract audit.
_AD_STREAMS = 8
_AD_BINS = 128
_AD_BATCH = 32
_AD_THRESHOLD = 0.05
_AD_QS = (0.25, 0.5, 0.9)


def _ad_quantile_audit(c, step: int) -> None:
    """The alpha-contract audit: the adaptive facade's answers must sit
    within the *effective* alpha of the exact quantiles of every value
    it ever ingested (widened by the edge-clamped fraction -- clamped
    mass legitimately carries phantom ranks; raises ``SketchError`` on
    a breach)."""
    sk = c["adaptive"]
    q = np.asarray(sk.get_quantile_values(list(_AD_QS)), np.float64)
    ea = np.asarray(sk.effective_alpha(), np.float64)
    cf = np.asarray(sk.collapsed_fraction(), np.float64)
    for s, vals in enumerate(c["values"]):
        if len(vals) < 8:
            continue
        arr = np.asarray(vals, np.float64)
        # Clamped mass shifts ranks by up to its fraction: audit the
        # quantile against the exact-rank bracket widened by that shift,
        # then by the effective alpha.
        for qi, qq in enumerate(_AD_QS):
            got = float(q[s, qi])
            lo_r = max(0.0, qq - cf[s] - 0.02)
            hi_r = min(1.0, qq + cf[s] + 0.02)
            lo_v = float(np.quantile(arr, lo_r, method="lower"))
            hi_v = float(np.quantile(arr, hi_r, method="higher"))
            lo_b = lo_v - ea[s] * abs(lo_v) - 1e-6
            hi_b = hi_v + ea[s] * abs(hi_v) + 1e-6
            if not lo_b <= got <= hi_b:
                raise SketchError(
                    f"alpha contract breach: stream {s} q{qq} = {got:g}"
                    f" outside [{lo_b:g}, {hi_b:g}] at effective alpha"
                    f" {ea[s]:.4f} (collapsed frac {cf[s]:.4f})"
                )


def _ad_expected_counts(c) -> float:
    return float(
        sum(len(v) for v in c["values"]) + c["moment_count"]
    )


def _ad_actual_counts(c) -> float:
    return float(
        np.asarray(c["adaptive"].count, np.float64).sum()
        + np.asarray(c["moment"].count, np.float64).sum()
    )


def run_adaptive_campaign(
    steps: int, seed: int, tmpdir: Optional[str] = None
) -> Dict[str, Any]:
    """Run the seeded adaptive-backend chaos campaign -> the verdict.

    One uniform-collapse facade rides a regime-drifting workload
    (location drift + seeded scale explosions force collapses
    MID-INGEST) next to one moment facade, with the integrity layer
    armed.  Every step the campaign may: ingest, audit the alpha
    contract at the *effective* alpha (exact-value ledger), merge a
    mixed-gamma operand (count conserved exactly), round-trip the
    backend wire envelope, or checkpoint/restore -- and with
    probability the armed fault sites corrupt the wire blobs, flip
    state bits, tear checkpoint writes, or flip the
    ``SKETCHES_TPU_ADAPTIVE`` kill switch under a firing trigger
    (which must refuse loudly).  ``ok`` iff every injected fault is
    detected or provably harmless, mass is EXACTLY conserved, and the
    alpha audit never breaches.  Raises ``SketchValueError`` for
    non-positive ``steps``; campaign-level failures are reported, not
    raised.
    """
    if steps <= 0:
        raise SketchValueError("steps must be positive")
    import os as _os

    from sketches_tpu.backends.moment import MomentDDSketch
    from sketches_tpu.backends.uniform import AdaptiveDDSketch
    from sketches_tpu.backends.wirefmt import (
        payload_from_bytes,
        payload_to_bytes,
    )
    from sketches_tpu.batched import SketchSpec
    from sketches_tpu.resilience import SpecError, WireDecodeError

    was_active, was_mode = integrity.enabled(), integrity.mode()
    faults.disarm()
    integrity.arm("quarantine")
    own_tmp = None
    if tmpdir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="sketches_adaptive_")
        tmpdir = own_tmp.name
    rng = np.random.default_rng(seed)
    aspec = SketchSpec(
        relative_accuracy=_REL_ACC, n_bins=_AD_BINS,
        backend="uniform_collapse", collapse_threshold=_AD_THRESHOLD,
    )
    mspec = SketchSpec(
        relative_accuracy=_REL_ACC, backend="moment", n_moments=8
    )
    c: Dict[str, Any] = {
        "adaptive": AdaptiveDDSketch(_AD_STREAMS, spec=aspec),
        "moment": MomentDDSketch(_AD_STREAMS, spec=mspec),
        "values": [[] for _ in range(_AD_STREAMS)],
        "moment_count": 0.0,
        "drift": 0.0,
        "scale": 0.6,
    }
    events: List[Dict[str, Any]] = []
    errors: List[str] = []

    def _ingest(step: int) -> None:
        # Regime drift: the location random-walks; seeded scale
        # explosions (~6% of steps) force mid-ingest collapses.
        c["drift"] += float(rng.normal(0.0, 0.25))
        if rng.random() < 0.06:
            c["scale"] = min(c["scale"] * 2.5, 8.0)
        vals = rng.lognormal(
            c["drift"], c["scale"], (_AD_STREAMS, _AD_BATCH)
        ).astype(np.float32)
        c["adaptive"].add(vals)
        c["moment"].add(vals)
        c["moment_count"] += vals.size
        for s in range(_AD_STREAMS):
            c["values"][s].extend(float(x) for x in vals[s])

    def _merge_mixed(step: int) -> None:
        # A fresh operand at a DIFFERENT gamma (explicitly collapsed
        # once) merges in: the mixed-gamma path, count conserved
        # exactly.
        other = AdaptiveDDSketch(_AD_STREAMS, spec=aspec)
        vals = rng.lognormal(
            c["drift"], 0.6, (_AD_STREAMS, _AD_BATCH)
        ).astype(np.float32)
        other.add(vals)
        other.collapse()
        before = np.asarray(c["adaptive"].count, np.float64).sum()
        c["adaptive"].merge(other)
        after = np.asarray(c["adaptive"].count, np.float64).sum()
        if after != before + vals.size:
            raise SketchError(
                f"mixed-gamma merge lost mass: {after:g} !="
                f" {before + vals.size:g}"
            )
        for s in range(_AD_STREAMS):
            c["values"][s].extend(float(x) for x in vals[s])

    def _wire_roundtrip(step: int) -> None:
        for spec, facade in ((aspec, c["adaptive"]), (mspec, c["moment"])):
            blobs = payload_to_bytes(spec, facade.state)
            st2 = payload_from_bytes(spec, blobs)
            got = float(np.asarray(st2.count, np.float64).sum())
            want = float(np.asarray(facade.count, np.float64).sum())
            if abs(got - want) > 0.5:
                raise SketchError(
                    f"{spec.backend} wire round trip lost mass:"
                    f" {got:g} != {want:g}"
                )

    def _checkpoint_roundtrip(step: int) -> None:
        from sketches_tpu import checkpoint

        for name in ("adaptive", "moment"):
            path = _os.path.join(tmpdir, f"{name}.ckpt")
            checkpoint.save(path, c[name])
            restored = checkpoint.restore(path)
            got = float(np.asarray(restored.count, np.float64).sum())
            want = float(np.asarray(c[name].count, np.float64).sum())
            if abs(got - want) > 0.5:
                raise SketchError(
                    f"{name} checkpoint round trip lost mass"
                )

    def _fault_wire(step: int) -> str:
        spec, facade = (
            (aspec, c["adaptive"]) if step % 2 else (mspec, c["moment"])
        )
        blobs = payload_to_bytes(spec, facade.state)
        idx = int(rng.integers(len(blobs)))
        blob = bytearray(blobs[idx])
        if not blob:
            return "skipped"
        pos = int(rng.integers(len(blob)))
        blob[pos] ^= 1 << int(rng.integers(8))
        corrupted = list(blobs)
        corrupted[idx] = bytes(blob)
        try:
            st2 = payload_from_bytes(spec, corrupted)
        except WireDecodeError:
            return "detected"  # structural damage refused loudly
        except Exception:  # noqa: BLE001 - any loud failure is detection
            return "detected"
        got = float(np.asarray(st2.count, np.float64).sum())
        want = float(np.asarray(facade.count, np.float64).sum())
        if abs(got - want) <= 0.5:
            fp_a = integrity.fingerprint(spec, st2)
            fp_b = integrity.fingerprint(spec, facade.state)
            fin = np.isfinite(fp_a) & np.isfinite(fp_b)
            if np.allclose(fp_a[fin], fp_b[fin], rtol=1e-6, atol=1e-3):
                return "harmless"  # flipped a byte the format ignores
        return "detected" if _ad_fp_differs(spec, facade, st2) else \
            "undetected"

    def _ad_fp_differs(spec, facade, st2) -> bool:
        # Content changed: the fingerprint lane must notice (that IS
        # the detection -- a serve cache keyed on it would miss, never
        # serve the corrupted answer as the original).
        fp_a = integrity.fingerprint(spec, st2)
        fp_b = integrity.fingerprint(spec, facade.state)
        fin = np.isfinite(fp_a) & np.isfinite(fp_b)
        return not np.allclose(
            fp_a[fin], fp_b[fin], rtol=1e-6, atol=1e-3
        ) or bool((~fin).any())

    def _fault_bitflip(step: int) -> str:
        sk = c["adaptive"]
        pre = sk.state
        fp_pre = integrity.fingerprint(aspec, pre)
        with faults.active(
            {faults.STATE_BITFLIP: dict(seed=step, times=1)}
        ):
            flips = faults.state_bitflips(_AD_STREAMS, _AD_BINS)
        if not flips:
            return "skipped"
        from sketches_tpu.backends.uniform import AdaptiveState

        corrupted = AdaptiveState(
            faults.apply_state_bitflips(pre.base, flips), pre.level
        )
        report = integrity.verify_state(
            aspec, corrupted, seam="chaos.adaptive.bitflip",
            errors="quarantine",
        )
        if report:
            return "detected"
        fp_post = integrity.fingerprint(aspec, corrupted)
        if not np.allclose(fp_post, fp_pre, rtol=1e-6, atol=1e-3):
            return "detected"  # the fingerprint lane
        q_pre = np.asarray(sk.get_quantile_values(list(_AD_QS)))
        sk.state = corrupted
        q_post = np.asarray(sk.get_quantile_values(list(_AD_QS)))
        sk.state = pre
        same = np.allclose(
            q_post, q_pre, rtol=4 * _REL_ACC, atol=1e-6, equal_nan=True
        )
        return "harmless" if same else "undetected"

    def _fault_ckpt(step: int) -> str:
        from sketches_tpu import checkpoint
        from sketches_tpu.resilience import CheckpointCorrupt

        path = _os.path.join(tmpdir, "torn_adaptive.ckpt")
        checkpoint.save(path, c["adaptive"])  # good previous file
        mode = "truncate" if step % 2 else "raise"
        with faults.active(
            {faults.CHECKPOINT_WRITE: dict(mode=mode, times=1)}
        ):
            try:
                checkpoint.save(path, c["adaptive"])
                crashed = False
            except InjectedFault:
                crashed = True
        if crashed:
            checkpoint.restore(path)  # previous file must survive
            return "detected"
        try:
            checkpoint.restore(path)
        except CheckpointCorrupt:
            return "detected"
        return "undetected"

    def _fault_kill_switch(step: int) -> str:
        # Arm a collapse-worthy batch under SKETCHES_TPU_ADAPTIVE=0:
        # the trigger must refuse LOUDLY (SpecError), and the refused
        # ingest must leave the facade's mass unchanged.
        sk = c["adaptive"]
        wide = rng.lognormal(
            c["drift"], 8.0, (_AD_STREAMS, _AD_BATCH)
        ).astype(np.float32)
        before = float(np.asarray(sk.count, np.float64).sum())
        from sketches_tpu.analysis import registry as _registry

        _switch = _registry.ADAPTIVE.name
        prior = _os.environ.get(_switch)
        _os.environ[_switch] = "0"
        try:
            try:
                sk.add(wide)
            except SpecError:
                after = float(np.asarray(sk.count, np.float64).sum())
                return "detected" if after == before else "undetected"
            # No collapse was needed for this batch: the switch had
            # nothing to refuse -- ingest went through legitimately.
            for s in range(_AD_STREAMS):
                c["values"][s].extend(float(x) for x in wide[s])
            return "harmless"
        finally:
            if prior is None:
                _os.environ.pop(_switch, None)
            else:
                _os.environ[_switch] = prior

    def _audit(step: int) -> None:
        _ad_quantile_audit(c, step)

    ops = (
        (_ingest, 0.45),
        (_audit, 0.2),
        (_merge_mixed, 0.15),
        (_wire_roundtrip, 0.1),
        (_checkpoint_roundtrip, 0.1),
    )
    op_fns = [o[0] for o in ops]
    op_ps = np.asarray([o[1] for o in ops])
    op_ps = op_ps / op_ps.sum()
    fault_sites = {
        "wire.blob": _fault_wire,
        "state.bitflip": _fault_bitflip,
        "checkpoint.write": _fault_ckpt,
        "adaptive.kill_switch": _fault_kill_switch,
    }
    site_names = tuple(fault_sites)
    try:
        for step in range(steps):
            op = int(rng.choice(len(op_fns), p=op_ps))
            try:
                op_fns[op](step)
            except Exception as e:  # un-faulted op must not fail
                errors.append(f"step {step} op {op}: {e!r}")
                break
            if rng.random() < _FAULT_P:
                site = site_names[int(rng.integers(len(site_names)))]
                try:
                    outcome = fault_sites[site](step)
                except Exception as e:
                    outcome = "undetected"
                    errors.append(f"step {step} site {site}: {e!r}")
                if outcome != "skipped":
                    events.append(
                        {"step": step, "site": site, "outcome": outcome}
                    )
                    _classify_forensics(site, outcome, step)
        expected = _ad_expected_counts(c)
        actual = _ad_actual_counts(c)
        conserved = actual == expected  # EXACT: integer-valued ledger
        if not conserved:
            errors.append(
                f"mass ledger broke: actual {actual:g} != expected"
                f" {expected:g}"
            )
        outcomes: Dict[str, int] = {}
        for ev in events:
            outcomes[ev["outcome"]] = outcomes.get(ev["outcome"], 0) + 1
        ok = (
            conserved and not errors
            and outcomes.get("undetected", 0) == 0
        )
        return {
            "campaign": "adaptive",
            "steps": steps,
            "seed": seed,
            "ok": ok,
            "n_faults": len(events),
            "outcomes": outcomes,
            "events": events,
            "errors": errors,
            "expected_count": expected,
            "final_count": actual,
            "final_levels": np.asarray(
                c["adaptive"].level
            ).tolist(),
            "final_effective_alpha": np.asarray(
                c["adaptive"].effective_alpha(), np.float64
            ).round(5).tolist(),
            "integrity_reports": len(integrity.reports()),
            "health": resilience.health(),
            "telemetry": telemetry.snapshot() if telemetry.enabled()
            else None,
        }
    finally:
        faults.disarm()
        if was_active:
            integrity.arm(was_mode)
        else:
            integrity.disarm()
        if own_tmp is not None:
            own_tmp.cleanup()


# ---------------------------------------------------------------------------
# Windowed campaign (the time-window soak)
# ---------------------------------------------------------------------------

#: Windowed-campaign shape: tiny rings (bounded fused-fold arity keeps
#: the per-arity compile count CI-sized), short virtual slices so a few
#: hundred steps cross many rotation boundaries.
_WD_STREAMS = 8
_WD_BINS = 128
_WD_BATCH = 16
_WD_QS = (0.5, 0.99)
_WD_WINDOWS = (7.0, 30.0, None)


def _wd_audit_ring(name: str, wsk, expected_total: float) -> None:
    """The exact mass-ledger audit (== everywhere, the acceptance
    contract): total == live + retired, every bucket's ledger entry ==
    its device mass, the ring's total == the campaign's expectation,
    and every CACHED maintained aggregate matches its raw-state fold
    bit-for-bit (the two-stacks consistency audit -- a no-op when the
    ``SKETCHES_TPU_WINDOW_AGG`` layer is off or the stacks are
    dropped).  Raises ``SketchError`` on any breach."""
    led = wsk.ledger()
    if led["total"] != led["live"] + led["retired"]:
        raise SketchError(
            f"{name}: ledger broke: total {led['total']:g} != live"
            f" {led['live']:g} + retired {led['retired']:g}"
        )
    if led["total"] != expected_total:
        raise SketchError(
            f"{name}: ledger total {led['total']:g} != expected"
            f" {expected_total:g}"
        )
    device = wsk.device_masses()
    for rung, bid, mass in wsk.buckets():
        got = device.get((rung, bid))
        if got != mass:
            raise SketchError(
                f"{name}: bucket (rung {rung}, id {bid}) ledger"
                f" {mass:g} != device {got}"
            )
    for detail in wsk._agg_audit():
        raise SketchError(f"{name}: stack audit: {detail}")


def run_windowed_campaign(
    steps: int, seed: int, tmpdir: Optional[str] = None
) -> Dict[str, Any]:
    """Run the seeded time-window chaos campaign -> the verdict.

    Three rings rotate under one virtual clock: a dense ring served
    THROUGH the serving tier (fingerprint-set cache keys, poison
    detection), an adaptive ring with a collapse-on-retire ladder, and
    -- when this process has >= 2 devices -- a mesh-backed ring that
    reshards live.  Every step may ingest (clock advances), query a
    window and compare bit-identically against the host-side oracle
    merge, round-trip the windowed checkpoint or wire envelope, or
    reshard; armed fault sites tear rotations mid-ingest
    (``window.rotate_torn``), tear the two-stacks aggregate sync
    (``window.stack_torn`` -- the tear must be swallowed, the stacks
    dropped into the health ledger, and the answers stay oracle-exact),
    silently corrupt a cached maintained aggregate
    (``window.agg_stale`` -- only the stack-consistency audit can see
    it; raw buckets stay clean), tear checkpoint writes, corrupt wire
    envelopes, tear reshards mid-rotation, poison the serve cache, and
    flip the ``SKETCHES_TPU_WINDOWED`` kill switch (which must refuse
    loudly).  The per-bucket mass ledger AND the two-stacks consistency
    audit run with ``==`` after EVERY step.  ``ok`` iff every fault is detected or provably
    harmless, every oracle comparison is bit-identical, and the ledger
    never breaks.  Raises ``SketchValueError`` for non-positive
    ``steps``; campaign-level failures are reported in the verdict,
    not raised.
    """
    if steps <= 0:
        raise SketchValueError("steps must be positive")
    import os as _os

    import jax

    from sketches_tpu import checkpoint, serve
    from sketches_tpu.analysis import registry as _registry
    from sketches_tpu.backends.wirefmt import (
        windowed_from_bytes,
        windowed_to_bytes,
    )
    from sketches_tpu.batched import SketchSpec
    from sketches_tpu.resilience import SpecError, WireDecodeError
    from sketches_tpu.windows import (
        VirtualClock,
        WindowConfig,
        WindowedSketch,
        oracle_quantile,
    )

    was_active, was_mode = integrity.enabled(), integrity.mode()
    faults.disarm()
    integrity.arm("quarantine")
    own_tmp = None
    if tmpdir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="sketches_windowed_")
        tmpdir = own_tmp.name
    rng = np.random.default_rng(seed)
    clock = VirtualClock(0.0)
    dense_spec = SketchSpec(relative_accuracy=_REL_ACC, n_bins=_WD_BINS)
    ad_spec = SketchSpec(
        relative_accuracy=_REL_ACC, n_bins=_WD_BINS,
        backend="uniform_collapse", collapse_threshold=0.05,
    )
    cfg = WindowConfig(slices_s=(5.0, 20.0), lengths=(3, 3))
    ad_cfg = WindowConfig(
        slices_s=(5.0, 20.0), lengths=(2, 2), collapse_levels=(0, 2)
    )
    srv = serve.SketchServer(clock=clock)
    srv.add_tenant("w", _WD_STREAMS, window=cfg, spec=dense_spec)
    rings: Dict[str, Any] = {
        "dense": srv.tenant("w"),
        "adaptive": WindowedSketch(
            _WD_STREAMS, spec=ad_spec, config=ad_cfg, clock=clock
        ),
    }
    n_devices = len(jax.devices())
    if n_devices >= 2:
        from sketches_tpu.parallel import SketchMesh

        rings["mesh"] = WindowedSketch(
            _WD_STREAMS, spec=dense_spec, config=cfg, clock=clock,
            mesh=SketchMesh(2),
        )
    expected: Dict[str, float] = {k: 0.0 for k in rings}
    events: List[Dict[str, Any]] = []
    errors: List[str] = []

    def _batch():
        return rng.lognormal(
            float(rng.normal(0.0, 0.5)), 0.7, (_WD_STREAMS, _WD_BATCH)
        ).astype(np.float32)

    def _ingest(step: int) -> None:
        clock.advance(float(rng.uniform(0.5, 4.0)))
        for name, wsk in rings.items():
            wsk.add(_batch())
            expected[name] += _WD_STREAMS * _WD_BATCH

    def _query_oracle(step: int) -> None:
        name = ("dense", "adaptive")[step % 2]
        win = _WD_WINDOWS[int(rng.integers(len(_WD_WINDOWS)))]
        wsk = rings[name]
        got = np.asarray(wsk.quantile(_WD_QS, window=win))
        want = np.asarray(oracle_quantile(wsk, _WD_QS, window=win))
        if not np.array_equal(got, want, equal_nan=True):
            raise SketchError(
                f"{name}: window query diverged from the oracle merge"
                f" (window={win}, max |diff|"
                f" {np.nanmax(np.abs(got - want)):g})"
            )

    def _serve_query(step: int) -> None:
        win = _WD_WINDOWS[int(rng.integers(len(_WD_WINDOWS)))]
        res = srv.quantile("w", list(_WD_QS), window=win)
        direct = np.asarray(rings["dense"].quantile(_WD_QS, window=win))
        if not np.array_equal(res.values, direct, equal_nan=True):
            raise SketchError(
                f"serve window answer diverged from the ring"
                f" (tier={res.tier}, window={win})"
            )

    def _checkpoint_roundtrip(step: int) -> None:
        path = _os.path.join(tmpdir, "windowed.ckpt")
        wsk = rings["dense"]
        checkpoint.save_windowed(path, wsk)
        restored = checkpoint.restore_windowed(
            path, clock=VirtualClock(clock.t)
        )
        if restored.ledger() != wsk.ledger() \
                or restored.buckets() != wsk.buckets():
            raise SketchError("windowed checkpoint round trip drifted")

    def _wire_roundtrip(step: int) -> None:
        wsk = rings["adaptive"]
        blob = windowed_to_bytes(wsk)
        restored = windowed_from_bytes(
            ad_spec, blob, clock=VirtualClock(clock.t)
        )
        if restored.ledger() != wsk.ledger() \
                or restored.buckets() != wsk.buckets():
            raise SketchError("windowed wire round trip drifted")

    def _reshard(step: int) -> None:
        wsk = rings.get("mesh")
        if wsk is None:
            return
        target = (1, 2)[step % 2]
        report = wsk.reshard(n_devices=target)
        if report.n_dead:
            raise SketchError("clean windowed reshard reported dead shards")

    def _fault_rotate_torn(step: int) -> str:
        name = ("dense", "adaptive")[step % 2]
        wsk = rings[name]
        clock.advance(float(rng.uniform(5.0, 12.0)))  # rotation now due
        before_led = wsk.ledger()
        before_buckets = wsk.buckets()
        faults.arm(faults.WINDOW_ROTATE_TORN, times=1)
        try:
            wsk.add(_batch())
            return "undetected"  # the tear did not surface
        except InjectedFault:
            pass
        finally:
            faults.disarm()
        if wsk.ledger() != before_led or wsk.buckets() != before_buckets:
            return "undetected"  # the tear mutated the ring
        # The interrupted rotation must complete cleanly afterwards.
        wsk.add(_batch())
        expected[name] += _WD_STREAMS * _WD_BATCH
        return "detected"

    def _fault_ckpt(step: int) -> str:
        path = _os.path.join(tmpdir, "torn_windowed.ckpt")
        wsk = rings["dense"]
        checkpoint.save_windowed(path, wsk)  # good previous file
        mode = "truncate" if step % 2 else "raise"
        with faults.active(
            {faults.CHECKPOINT_WRITE: dict(mode=mode, times=1)}
        ):
            try:
                checkpoint.save_windowed(path, wsk)
                crashed = False
            except InjectedFault:
                crashed = True
        if crashed:
            checkpoint.restore_windowed(
                path, clock=VirtualClock(clock.t)
            )  # previous file must survive
            return "detected"
        try:
            checkpoint.restore_windowed(path, clock=VirtualClock(clock.t))
        except CheckpointCorrupt:
            return "detected"
        return "undetected"

    def _fault_wire(step: int) -> str:
        wsk = rings["dense"]
        blob = bytearray(windowed_to_bytes(wsk))
        if not blob:
            return "skipped"
        pos = int(rng.integers(len(blob)))
        blob[pos] ^= 1 << int(rng.integers(8))
        try:
            restored = windowed_from_bytes(
                dense_spec, bytes(blob), clock=VirtualClock(clock.t)
            )
        except (WireDecodeError, SpecError):
            return "detected"  # structural damage refused loudly
        except Exception:  # noqa: BLE001 - any loud failure is detection
            return "detected"
        if restored.ledger() == wsk.ledger() \
                and restored.buckets() == wsk.buckets():
            same_fp = restored.window_plan(None).digest \
                == wsk.window_plan(None).digest
            if same_fp:
                return "harmless"  # flipped a byte the format ignores
            return "detected"  # content moved: the fingerprint lane sees it
        return "detected"  # ledger drifted visibly

    def _fault_reshard_torn(step: int) -> str:
        wsk = rings.get("mesh")
        if wsk is None:
            return "skipped"
        clock.advance(float(rng.uniform(5.0, 9.0)))  # rotation pending
        before_led = wsk.ledger()
        faults.arm(faults.RESHARD_TORN, times=1)
        try:
            wsk.reshard(n_devices=2 if step % 2 else 1)
            return "undetected"
        except InjectedFault:
            pass
        finally:
            faults.disarm()
        if wsk.ledger() != before_led:
            return "undetected"
        got = np.asarray(wsk.quantile(_WD_QS, window=30.0))
        want = np.asarray(oracle_quantile(wsk, _WD_QS, window=30.0))
        return (
            "detected"
            if np.array_equal(got, want, equal_nan=True)
            else "undetected"
        )

    def _fault_cache_poison(step: int) -> str:
        win = 30.0
        srv.quantile("w", list(_WD_QS), window=win)  # fill the entry
        direct = np.asarray(rings["dense"].quantile(_WD_QS, window=win))
        before = srv.stats()["cache_poisoned"]
        faults.arm(faults.SERVE_CACHE_POISON, times=1)
        try:
            res = srv.quantile("w", list(_WD_QS), window=win)
        finally:
            faults.disarm()
        if res.cached and srv.stats()["cache_poisoned"] == before:
            # The poison flip may land on a bit the checksum round-trips
            # identically only if it never fired; a served hit must have
            # re-verified clean against the live fingerprint.
            return (
                "harmless"
                if np.array_equal(res.values, direct, equal_nan=True)
                else "undetected"
            )
        return (
            "detected"
            if np.array_equal(res.values, direct, equal_nan=True)
            and srv.stats()["cache_poisoned"] == before + 1
            else "undetected"
        )

    def _fault_kill_switch(step: int) -> str:
        _switch = _registry.WINDOWED.name
        prior = _os.environ.get(_switch)
        _os.environ[_switch] = "0"
        try:
            try:
                WindowedSketch(2, spec=dense_spec, clock=clock)
                return "undetected"
            except SpecError:
                pass
            try:
                srv.add_tenant(f"k{step}", 2, window=True, spec=dense_spec)
                return "undetected"
            except SpecError:
                return "detected"
        finally:
            if prior is None:
                _os.environ.pop(_switch, None)
            else:
                _os.environ[_switch] = prior

    def _fault_stack_torn(step: int) -> str:
        name = ("dense", "adaptive")[step % 2]
        wsk = rings[name]
        if not wsk._agg_enabled:
            return "skipped"  # kill-switch lane: the site never fires
        clock.advance(float(rng.uniform(5.0, 12.0)))  # rotation due
        before = resilience.health()["counters"].get(
            "window.stack_torn", 0
        )
        faults.arm(faults.WINDOW_STACK_TORN, times=1)
        try:
            wsk.add(_batch())  # sync tears AFTER the rotation commit
        finally:
            faults.disarm()
        expected[name] += _WD_STREAMS * _WD_BATCH
        if wsk._agg_stacks is not None:
            return "undetected"  # torn sync left stale stacks behind
        after = resilience.health()["counters"].get(
            "window.stack_torn", 0
        )
        if after != before + 1:
            return "undetected"  # the tear went unaccounted
        # The degraded path must still answer oracle-exactly (the next
        # plan rebuilds the stacks lazily, zero upfront merges).
        got = np.asarray(wsk.quantile(_WD_QS, window=30.0))
        want = np.asarray(oracle_quantile(wsk, _WD_QS, window=30.0))
        return (
            "detected"
            if np.array_equal(got, want, equal_nan=True)
            else "undetected"
        )

    def _fault_agg_stale(step: int) -> str:
        name = ("dense", "adaptive")[step % 2]
        wsk = rings[name]
        if not wsk._agg_enabled:
            return "skipped"  # kill-switch lane: no aggregates exist
        wsk.quantile(_WD_QS, window=30.0)  # warm the aggregate caches
        stacks = wsk._agg_stacks
        if not stacks or (
            wsk._agg_fold_cache is None and not any(
                s._combined or s._tails or s.front for s in stacks
            )
        ):
            return "skipped"  # nothing cached yet to corrupt
        faults.arm(faults.WINDOW_AGG_STALE, times=1)
        try:
            wsk.window_plan(30.0)  # plan time applies the stale flips
        finally:
            faults.disarm()
        violations = wsk._agg_audit()
        if not violations:
            # The flip landed invisibly to exact content comparison
            # (the sign bit of a zero count: -0.0 == 0.0) -- then the
            # answer must still be oracle-exact, or the audit MISSED
            # real corruption.
            got = np.asarray(wsk.quantile(_WD_QS, window=30.0))
            want = np.asarray(oracle_quantile(wsk, _WD_QS, window=30.0))
            return (
                "harmless"
                if np.array_equal(got, want, equal_nan=True)
                else "undetected"
            )
        # Derived state: drop the poisoned caches, rebuild lazily, and
        # the ring must answer oracle-exactly again.
        wsk._agg_invalidate()
        got = np.asarray(wsk.quantile(_WD_QS, window=30.0))
        want = np.asarray(oracle_quantile(wsk, _WD_QS, window=30.0))
        ok = not wsk._agg_audit() \
            and np.array_equal(got, want, equal_nan=True)
        return "detected" if ok else "undetected"

    ops = (
        (_ingest, 0.4),
        (_query_oracle, 0.2),
        (_serve_query, 0.15),
        (_checkpoint_roundtrip, 0.08),
        (_wire_roundtrip, 0.07),
        (_reshard, 0.1),
    )
    op_fns = [o[0] for o in ops]
    op_ps = np.asarray([o[1] for o in ops])
    op_ps = op_ps / op_ps.sum()
    fault_sites = {
        "window.rotate_torn": _fault_rotate_torn,
        "checkpoint.write": _fault_ckpt,
        "wire.blob": _fault_wire,
        "reshard.torn": _fault_reshard_torn,
        "serve.cache_poison": _fault_cache_poison,
        "windowed.kill_switch": _fault_kill_switch,
        "window.stack_torn": _fault_stack_torn,
        "window.agg_stale": _fault_agg_stale,
    }
    site_names = tuple(fault_sites)
    try:
        for step in range(steps):
            op = int(rng.choice(len(op_fns), p=op_ps))
            try:
                op_fns[op](step)
            except Exception as e:  # un-faulted op must not fail
                errors.append(f"step {step} op {op}: {e!r}")
                break
            if rng.random() < _FAULT_P:
                site = site_names[int(rng.integers(len(site_names)))]
                try:
                    outcome = fault_sites[site](step)
                except Exception as e:
                    outcome = "undetected"
                    errors.append(f"step {step} site {site}: {e!r}")
                if outcome != "skipped":
                    events.append(
                        {"step": step, "site": site, "outcome": outcome}
                    )
                    _classify_forensics(site, outcome, step)
            # The acceptance contract: the ledger is exact at EVERY
            # step, not just at the end.
            try:
                for name, wsk in rings.items():
                    _wd_audit_ring(name, wsk, expected[name])
            except SketchError as e:
                errors.append(f"step {step} audit: {e!r}")
                break
        outcomes: Dict[str, int] = {}
        for ev in events:
            outcomes[ev["outcome"]] = outcomes.get(ev["outcome"], 0) + 1
        ok = not errors and outcomes.get("undetected", 0) == 0
        ledgers = {name: wsk.ledger() for name, wsk in rings.items()}
        return {
            "campaign": "windowed",
            "steps": steps,
            "seed": seed,
            "ok": ok,
            "n_faults": len(events),
            "outcomes": outcomes,
            "events": events,
            "errors": errors,
            "virtual_clock_s": clock.t,
            "ledgers": ledgers,
            "expected": expected,
            "rung_effective_alpha": rings[
                "adaptive"
            ].rung_effective_alpha(),
            "serve_stats": srv.stats(),
            "integrity_reports": len(integrity.reports()),
            "health": resilience.health(),
            "telemetry": telemetry.snapshot() if telemetry.enabled()
            else None,
        }
    finally:
        faults.disarm()
        if was_active:
            integrity.arm(was_mode)
        else:
            integrity.disarm()
        if own_tmp is not None:
            own_tmp.cleanup()


# ---------------------------------------------------------------------------
# Fabric campaign (the sharded-serve soak)
# ---------------------------------------------------------------------------

#: Fabric-campaign fleet shape: 4 virtual hosts, 3 copies per tenant --
#: small enough for a CPU soak, big enough that every host kill leaves
#: both a promotable verified replica and a survivor set to re-provision
#: onto.
_FB_HOSTS = 4
_FB_REPLICATION = 3
_FB_STREAMS = 4
_FB_BINS = 128
_FB_BATCH = 16
_FB_QS = (0.5, 0.99)
_FB_TENANTS = ("alpha", "beta", "gamma", "delta")
_FB_STALENESS_S = 600.0


def run_fabric_campaign(steps: int, seed: int) -> Dict[str, Any]:
    """Run the seeded SHARDED-SERVE-FABRIC campaign -> the verdict.

    A 4-host fabric serves 4 tenants at replication 3 under a virtual
    clock while the campaign kills whole hosts mid-ingest (the primary's
    tenants must re-home onto fingerprint-verified replicas with the
    dropped mass itemized EXACTLY), partitions primaries (reads must
    degrade to declared-staleness replicas, writes must refuse,
    beyond-bound replicas must refuse loudly), silently corrupts replica
    state (``fabric.replica_stale`` -- only the serve-time fingerprint
    gate may catch it; a corrupt replica must NEVER serve), tears
    partition heals (``mesh.partition_heal`` -- the host must stay
    partitioned, never half-healed) and replica handoffs
    (``reshard.torn`` -- the source replica must stay intact), and flips
    the ``SKETCHES_TPU_FABRIC`` kill switch (which must refuse
    construction loudly).

    The accounting contract: every served answer is bit-identical to
    the oracle fold of the mass it declares to cover (the live mirror
    for primary reads, the canonical synced snapshot for replica
    reads), and the per-stream mass ledger closes EXACTLY
    (``expected + dropped == ingested``, ``==`` never approximately)
    after every step AND every failover.  Anything else is
    ``undetected`` and fails the run.

    Under ``SKETCHES_TPU_FABRIC=0`` the campaign runs the disarmed
    drill instead: every construction probe must refuse loudly
    (``SpecError``) while single-process serving stays available; the
    verdict carries ``disarmed: True``.  Raises ``SketchValueError``
    for non-positive ``steps``; campaign-level failures land in the
    verdict's ``errors`` list, never raised.
    """
    if steps <= 0:
        raise SketchValueError("steps must be positive")
    import os as _os

    from sketches_tpu import serve
    from sketches_tpu.analysis import registry as _registry
    from sketches_tpu.batched import BatchedDDSketch, SketchSpec
    from sketches_tpu.fabric import FabricConfig, ServeFabric
    from sketches_tpu.resilience import (
        FabricUnavailable,
        ReplicaStale,
        SpecError,
    )
    from sketches_tpu.windows import VirtualClock

    if not _registry.enabled(_registry.FABRIC):
        # Disarmed lane: the switch must make every fabric construction
        # refuse loudly -- and leave single-process serving untouched.
        events: List[Dict[str, Any]] = []
        errors: List[str] = []
        for step in range(steps):
            try:
                ServeFabric(FabricConfig(n_hosts=2))
                errors.append(
                    f"step {step}: disarmed fabric constructed silently"
                )
                outcome = "undetected"
            except SpecError:
                outcome = "detected"
            events.append(
                {"step": step, "site": "fabric.kill_switch",
                 "outcome": outcome}
            )
        try:
            solo = serve.SketchServer()
            solo.add_tenant("solo", 2, relative_accuracy=_REL_ACC)
            solo.ingest("solo", np.ones((2, 4), np.float32))
            solo.query("solo", (0.5,))
        except Exception as e:  # noqa: BLE001 - any break is a finding
            errors.append(f"disarmed single-process serving broke: {e!r}")
        outcomes: Dict[str, int] = {}
        for ev in events:
            outcomes[ev["outcome"]] = outcomes.get(ev["outcome"], 0) + 1
        return {
            "campaign": "fabric",
            "steps": steps,
            "seed": seed,
            "disarmed": True,
            "ok": not errors and outcomes.get("undetected", 0) == 0,
            "n_faults": len(events),
            "outcomes": outcomes,
            "events": events[:16],  # one probe per step; keep it short
            "errors": errors,
            "health": resilience.health(),
            "telemetry": telemetry.snapshot() if telemetry.enabled()
            else None,
        }

    from sketches_tpu.backends.wirefmt import (
        payload_from_bytes,
        payload_to_bytes,
    )

    was_active, was_mode = integrity.enabled(), integrity.mode()
    faults.disarm()
    integrity.arm("quarantine")
    rng = np.random.default_rng(seed)
    clock = VirtualClock(0.0)
    spec = SketchSpec(relative_accuracy=_REL_ACC, n_bins=_FB_BINS)
    fab = ServeFabric(
        FabricConfig(
            n_hosts=_FB_HOSTS, replication=_FB_REPLICATION,
            staleness_s=_FB_STALENESS_S,
        ),
        clock=clock,
    )
    # The oracle: a live mirror per tenant (fed bit-identical batches ->
    # bit-identical primary state) plus the canonical synced snapshot
    # per (tenant, replica host) -- exactly what the fabric's sync
    # ledger promises each replica holds.
    mirror: Dict[str, BatchedDDSketch] = {}
    synced: Dict[str, Dict[int, Any]] = {}
    expected: Dict[str, np.ndarray] = {}
    dropped: Dict[str, np.ndarray] = {}
    total_in: Dict[str, float] = {}
    events = []
    errors = []

    def _canon(state):
        # The wire seam's normalizing round trip: content-identical,
        # canonical key window -- bit-identical to what a replica holds.
        return payload_from_bytes(spec, payload_to_bytes(spec, state))

    def _snap(nm: str):
        return (_canon(mirror[nm].state), expected[nm].copy())

    def _usable(h: int) -> bool:
        return h in fab.live_hosts()

    def _model_sync(nm: str, n_synced: int) -> None:
        reps = [h for h in fab.placement(nm)[1:] if _usable(h)]
        if n_synced != len(reps):
            raise SketchError(
                f"{nm}: fabric synced {n_synced} replicas, model expected"
                f" {len(reps)}"
            )
        snap = _snap(nm)
        for h in reps:
            synced[nm][h] = snap

    def _model_refresh(nm: str) -> None:
        # Reconcile the model's replica set with the fabric's placement
        # right after a verb that re-provisioned: a NEW replica was
        # synced from the live primary at provision time.
        reps = set(fab.placement(nm)[1:])
        for h in list(synced[nm]):
            if h not in reps:
                del synced[nm][h]
        fresh = [h for h in reps if h not in synced[nm] and _usable(h)]
        if fresh:
            snap = _snap(nm)
            for h in fresh:
                synced[nm][h] = snap

    def _model_heal(host_id: int) -> None:
        # heal_partition resynced every replica ON the healed host whose
        # primary was reachable at plan time.
        for nm in _FB_TENANTS:
            pl = fab.placement(nm)
            if host_id in pl[1:] and pl[0] != host_id:
                synced[nm][host_id] = _snap(nm)

    def _oracle_quantiles(state) -> np.ndarray:
        return np.asarray(
            BatchedDDSketch(
                _FB_STREAMS, spec=spec, state=state
            ).get_quantile_values(list(_FB_QS))
        )

    def _batch():
        return rng.lognormal(
            float(rng.normal(0.0, 0.5)), 0.7, (_FB_STREAMS, _FB_BATCH)
        ).astype(np.float32)

    def _pick() -> str:
        return _FB_TENANTS[int(rng.integers(len(_FB_TENANTS)))]

    for nm in _FB_TENANTS:
        fab.add_tenant(nm, _FB_STREAMS, spec=spec)
        mirror[nm] = BatchedDDSketch(_FB_STREAMS, spec=spec)
        synced[nm] = {}
        expected[nm] = np.zeros(_FB_STREAMS, np.float64)
        dropped[nm] = np.zeros(_FB_STREAMS, np.float64)
        total_in[nm] = 0.0
        # Seed every tenant with mass and a sync point so replicas are
        # promotable from step 0.
        b = _batch()
        fab.ingest(nm, b)
        mirror[nm].add(b)
        expected[nm] += float(_FB_BATCH)
        total_in[nm] += float(_FB_STREAMS * _FB_BATCH)
        _model_sync(nm, fab.sync(nm))

    def _ingest(step: int) -> None:
        clock.advance(float(rng.uniform(0.5, 4.0)))
        nm = _pick()
        b = _batch()
        fab.ingest(nm, b)
        mirror[nm].add(b)
        expected[nm] += float(_FB_BATCH)
        total_in[nm] += float(_FB_STREAMS * _FB_BATCH)

    def _read(step: int) -> None:
        nm = _pick()
        res = fab.quantile(nm, _FB_QS)
        if res.role not in ("primary", "cache") or res.hedged:
            raise SketchError(
                f"{nm}: healthy-fleet read served role={res.role}"
                f" hedged={res.hedged}"
            )
        want = np.asarray(mirror[nm].get_quantile_values(list(_FB_QS)))
        if not np.array_equal(
            np.asarray(res.values), want, equal_nan=True
        ):
            raise SketchError(
                f"{nm}: primary answer diverged from the live oracle"
            )

    def _sync(step: int) -> None:
        nm = _pick()
        _model_sync(nm, fab.sync(nm))

    def _rebalance(step: int) -> None:
        nm = _pick()
        pl = fab.placement(nm)
        free = [h for h in fab.live_hosts() if h not in pl]
        srcs = [h for h in pl[1:] if _usable(h) and h in synced[nm]]
        if not free or not srcs:
            return
        src = srcs[int(rng.integers(len(srcs)))]
        dst = free[int(rng.integers(len(free)))]
        fab.handoff_replica(nm, src, dst)
        synced[nm][dst] = synced[nm].pop(src)

    def _audit(step: int) -> None:
        for nm in _FB_TENANTS:
            led = fab.ledger(nm)
            if not np.array_equal(led["expected_count"], expected[nm]):
                raise SketchError(f"{nm}: expected_count ledger drifted")
            if not np.array_equal(led["dropped_count"], dropped[nm]):
                raise SketchError(f"{nm}: dropped_count ledger drifted")
            if led["expected_total"] + led["dropped_total"] \
                    != total_in[nm]:
                raise SketchError(
                    f"{nm}: mass not conserved:"
                    f" {led['expected_total']} + {led['dropped_total']}"
                    f" != {total_in[nm]}"
                )
            live = np.asarray(mirror[nm].state.count, np.float64)
            if not np.array_equal(live, expected[nm]):
                raise SketchError(f"{nm}: oracle mirror count drifted")

    def _fault_host_kill(step: int) -> str:
        live = fab.live_hosts()
        if len(live) < 3:
            return "skipped"
        victim = int(live[int(rng.integers(len(live)))])
        prims = sorted(
            nm for nm in _FB_TENANTS if fab.placement(nm)[0] == victim
        )
        reports = fab.kill_host(victim)
        if sorted(r.tenant for r in reports) != prims:
            return "undetected"
        for r in reports:
            nm = r.tenant
            snap = synced[nm].get(r.to_host)
            if snap is None:
                return "undetected"  # promoted a never-synced copy
            state_syn, count_syn = snap
            want_drop = expected[nm] - count_syn
            if not r.exact or not np.array_equal(
                r.dropped_count, want_drop
            ):
                return "undetected"  # the itemized dropped mass is wrong
            dropped[nm] = dropped[nm] + want_drop
            expected[nm] = count_syn.copy()
            # The promoted replica IS the tenant now: the live oracle
            # resets to the canonical synced snapshot.
            mirror[nm] = BatchedDDSketch(
                _FB_STREAMS, spec=spec, state=state_syn
            )
        for nm in _FB_TENANTS:
            _model_refresh(nm)
        for r in reports:
            res = fab.quantile(r.tenant, _FB_QS)
            want = np.asarray(
                mirror[r.tenant].get_quantile_values(list(_FB_QS))
            )
            if not np.array_equal(
                np.asarray(res.values), want, equal_nan=True
            ):
                return "undetected"  # wrong answer after failover
        # A replacement process joins under the dead host's id; every
        # under-replicated tenant re-provisions through the sync path.
        fab.revive_host(victim)
        for nm in _FB_TENANTS:
            _model_refresh(nm)
        return "re-homed"

    def _fault_partition(step: int) -> str:
        nm = _pick()
        _model_sync(nm, fab.sync(nm))
        p = fab.placement(nm)[0]
        fab.partition_host(p)
        ok = True
        try:
            res = fab.quantile(nm, _FB_QS)
            if not (res.degraded and res.role == "replica"):
                ok = False
            else:
                state_syn, _ = synced[nm][res.host]
                if not np.array_equal(
                    np.asarray(res.values),
                    _oracle_quantiles(state_syn),
                    equal_nan=True,
                ):
                    ok = False  # degraded answer != synced oracle fold
            try:
                fab.ingest(nm, _batch())
                ok = False  # a partitioned primary must refuse writes
            except FabricUnavailable:
                pass
            # Beyond the declared bound the replica must refuse loudly,
            # never serve silently stale.
            clock.advance(_FB_STALENESS_S + 1.0)
            try:
                fab.quantile(nm, _FB_QS)
                ok = False
            except ReplicaStale as e:
                if e.reason != "staleness":
                    ok = False
        finally:
            fab.heal_partition(p)
        _model_heal(p)
        res = fab.quantile(nm, _FB_QS)
        want = np.asarray(mirror[nm].get_quantile_values(list(_FB_QS)))
        if res.role not in ("primary", "cache") or not np.array_equal(
            np.asarray(res.values), want, equal_nan=True
        ):
            ok = False
        return "degraded" if ok else "undetected"

    def _fault_replica_stale(step: int) -> str:
        nm = _pick()
        _model_sync(nm, fab.sync(nm))
        p = fab.placement(nm)[0]
        fab.partition_host(p)
        before = fab.stats()["stale_refusals"]
        # Fresh seed per firing: the corruption coordinates must roam,
        # not re-flip the same bit of the same bin every time.
        faults.arm(faults.FABRIC_REPLICA_STALE, times=1, seed=seed + step)
        served = None
        try:
            try:
                served = fab.quantile(nm, _FB_QS)
            except ReplicaStale:
                pass  # every reachable replica refused: loud is correct
        finally:
            faults.disarm()
        refusals = fab.stats()["stale_refusals"] - before
        if served is not None:
            state_syn, _ = synced[nm][served.host]
            right = np.array_equal(
                np.asarray(served.values),
                _oracle_quantiles(state_syn),
                equal_nan=True,
            )
        else:
            right = True  # refusing everywhere is never a wrong answer
        fab.heal_partition(p)
        _model_heal(p)
        # Repair the corrupted copy through the sync path before the
        # next step touches it.
        _model_sync(nm, fab.sync(nm))
        if not right:
            return "undetected"  # a corrupt replica SERVED: booby trap failed
        if refusals == 0:
            # The flip landed invisibly to the content fingerprint (the
            # sign bit of a zero count): the served answer was proven
            # bit-identical above, so the corruption is harmless.
            return "harmless" if served is not None else "undetected"
        return "detected"

    def _fault_heal_torn(step: int) -> str:
        live = fab.live_hosts()
        if len(live) < 2:
            return "skipped"
        h = int(live[int(rng.integers(len(live)))])
        fab.partition_host(h)
        faults.arm(faults.MESH_PARTITION_HEAL, times=1)
        try:
            fab.heal_partition(h)
            torn = False
        except InjectedFault:
            torn = True
        finally:
            faults.disarm()
        if not torn:
            return "undetected"  # the armed tear never surfaced
        if h in fab.live_hosts():
            return "undetected"  # a torn heal half-committed
        fab.heal_partition(h)
        _model_heal(h)
        return "detected"

    def _fault_handoff_torn(step: int) -> str:
        nm = _pick()
        pl = fab.placement(nm)
        free = [h for h in fab.live_hosts() if h not in pl]
        srcs = [h for h in pl[1:] if _usable(h) and h in synced[nm]]
        if not free or not srcs:
            return "skipped"
        src = srcs[int(rng.integers(len(srcs)))]
        dst = free[int(rng.integers(len(free)))]
        faults.arm(faults.RESHARD_TORN, times=1)
        try:
            fab.handoff_replica(nm, src, dst)
            torn = False
        except InjectedFault:
            torn = True
        except SpecError:
            return "skipped"  # source had no ledger to move
        finally:
            faults.disarm()
        if not torn:
            return "undetected"
        if fab.placement(nm) != pl:
            return "undetected"  # the torn handoff moved the replica
        # The interrupted handoff must complete cleanly afterwards,
        # carrying the fingerprint (and the cache keyed on it) along.
        rep = fab.handoff_replica(nm, src, dst)
        synced[nm][dst] = synced[nm].pop(src)
        if not rep.cache_preserved:
            return "undetected"
        return "detected"

    def _fault_kill_switch(step: int) -> str:
        _switch = _registry.FABRIC.name
        prior = _os.environ.get(_switch)
        _os.environ[_switch] = "0"
        try:
            try:
                ServeFabric(FabricConfig(n_hosts=2))
                return "undetected"
            except SpecError:
                pass
        finally:
            if prior is None:
                _os.environ.pop(_switch, None)
            else:
                _os.environ[_switch] = prior
        # The switch gates construction, not the running fleet: the
        # armed fabric must still answer correctly.
        nm = _pick()
        res = fab.quantile(nm, _FB_QS)
        want = np.asarray(mirror[nm].get_quantile_values(list(_FB_QS)))
        return (
            "detected"
            if np.array_equal(np.asarray(res.values), want, equal_nan=True)
            else "undetected"
        )

    ops = (
        (_ingest, 0.4),
        (_read, 0.3),
        (_sync, 0.2),
        (_rebalance, 0.1),
    )
    op_fns = [o[0] for o in ops]
    op_ps = np.asarray([o[1] for o in ops])
    op_ps = op_ps / op_ps.sum()
    fault_sites = {
        "mesh.host_loss": _fault_host_kill,
        "dcn.partition": _fault_partition,
        "fabric.replica_stale": _fault_replica_stale,
        "mesh.partition_heal": _fault_heal_torn,
        "reshard.torn": _fault_handoff_torn,
        "fabric.kill_switch": _fault_kill_switch,
    }
    site_names = tuple(fault_sites)
    try:
        for step in range(steps):
            op = int(rng.choice(len(op_fns), p=op_ps))
            try:
                op_fns[op](step)
            except Exception as e:  # un-faulted op must not fail
                errors.append(f"step {step} op {op}: {e!r}")
                break
            if rng.random() < _FAULT_P:
                site = site_names[int(rng.integers(len(site_names)))]
                try:
                    outcome = fault_sites[site](step)
                except Exception as e:
                    outcome = "undetected"
                    errors.append(f"step {step} site {site}: {e!r}")
                if outcome != "skipped":
                    events.append(
                        {"step": step, "site": site, "outcome": outcome}
                    )
                    _classify_forensics(site, outcome, step)
            # The acceptance contract: the mass ledger closes exactly at
            # EVERY step, not just at the end.
            try:
                _audit(step)
            except SketchError as e:
                errors.append(f"step {step} audit: {e!r}")
                break
        outcomes = {}
        for ev in events:
            outcomes[ev["outcome"]] = outcomes.get(ev["outcome"], 0) + 1
        ok = not errors and outcomes.get("undetected", 0) == 0
        ledgers = {}
        for nm in _FB_TENANTS:
            led = fab.ledger(nm)
            ledgers[nm] = {
                "expected_total": led["expected_total"],
                "dropped_total": led["dropped_total"],
                "ingested_total": total_in[nm],
                "hosts": list(led["hosts"]),
                "fingerprint": led.get("fingerprint"),
            }
        return {
            "campaign": "fabric",
            "steps": steps,
            "seed": seed,
            "disarmed": False,
            "ok": ok,
            "n_faults": len(events),
            "outcomes": outcomes,
            "events": events,
            "errors": errors,
            "virtual_clock_s": clock.t,
            "ledgers": ledgers,
            "fabric_stats": fab.stats(),
            "integrity_reports": len(integrity.reports()),
            "health": resilience.health(),
            "telemetry": telemetry.snapshot() if telemetry.enabled()
            else None,
        }
    finally:
        faults.disarm()
        if was_active:
            integrity.arm(was_mode)
        else:
            integrity.disarm()


def main(argv: Optional[List[str]] = None) -> int:
    """CLI entry point: run the campaign, write the verdict, exit 0 iff
    every injected fault was accounted for (1 otherwise).

    ``--platform`` pins the JAX platform via ``jax.config`` (default
    ``cpu`` -- the soak is a CPU-sized drill; pass ``""`` to keep the
    environment's backend).  Unexpected campaign errors land in the
    verdict's ``errors`` list and fail the run rather than crashing.
    """
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m sketches_tpu.chaos",
        description="seeded chaos-soak campaign: inject faults with the"
        " integrity layer armed; every fault must be detected or harmless",
    )
    parser.add_argument("--steps", type=int, default=500)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--campaign",
        choices=("core", "serve", "elastic", "adaptive", "windowed", "fabric"),
        default="core",
        help="core: the integrity soak over the storage/engine sites;"
        " serve: the serving-tier soak over the serve.* sites (every"
        " fault shed, hedged, or detected); elastic: the kill-and-regrow"
        " soak over the mesh.shard/mesh.host_loss/dcn.partition/"
        "reshard.torn sites across 1/2/4/8-device meshes (every fault"
        " detected or recovered with exact mass accounting); adaptive:"
        " the accuracy-backend soak (collapse mid-ingest, mixed-gamma"
        " merges, backend wire round-trips under injected corruption,"
        " kill-switch refusal -- alpha contract audited at the"
        " effective alpha, mass ledger exact); windowed: the"
        " time-window soak (rotation-mid-ingest tears, torn windowed"
        " checkpoints, wire corruption, reshard-during-rotation, serve"
        " cache poison, kill-switch refusal -- window queries"
        " bit-identical to the oracle merge, per-bucket mass ledger"
        " exact at every step); fabric: the sharded-serve soak (host"
        " kills with fingerprint-verified failover and exact"
        " dropped-mass itemization, primary partitions degrading to"
        " declared-staleness replica reads, silent replica corruption"
        " that must never serve, torn heals and handoffs, kill-switch"
        " refusal -- every answer bit-identical to its oracle fold)",
    )
    parser.add_argument(
        "--mode", choices=("raise", "quarantine"), default="raise",
        help="armed integrity behavior during the (core) soak",
    )
    parser.add_argument(
        "--out", default=None, metavar="PATH",
        help="write the verdict JSON here (stdout always gets a summary)",
    )
    parser.add_argument(
        "--forensics", default=None, metavar="PATH",
        help="write the campaign's most recent forensic bundle here"
        " (requires the flight recorder armed, i.e."
        " SKETCHES_TPU_TELEMETRY=1; explain it with"
        " python -m sketches_tpu.tracing --explain PATH trigger)",
    )
    parser.add_argument("--platform", default="cpu")
    args = parser.parse_args(argv)

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    if args.campaign == "serve":
        verdict = run_serve_campaign(args.steps, args.seed)
    elif args.campaign == "elastic":
        verdict = run_elastic_campaign(args.steps, args.seed, mode=args.mode)
    elif args.campaign == "adaptive":
        verdict = run_adaptive_campaign(args.steps, args.seed)
    elif args.campaign == "windowed":
        verdict = run_windowed_campaign(args.steps, args.seed)
    elif args.campaign == "fabric":
        verdict = run_fabric_campaign(args.steps, args.seed)
    else:
        verdict = run_campaign(args.steps, args.seed, mode=args.mode)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(verdict, f, indent=1, sort_keys=True)
            f.write("\n")
    if args.forensics:
        bundle = tracing.last_bundle()
        if bundle is not None:
            with open(args.forensics, "w", encoding="utf-8") as f:
                json.dump(bundle, f, indent=1, sort_keys=True)
                f.write("\n")
            print(f"chaos: forensic bundle -> {args.forensics}")
        else:
            print(
                "chaos: no forensic bundle recorded (flight recorder"
                " disarmed? arm with SKETCHES_TPU_TELEMETRY=1)"
            )
    print(
        f"chaos: {verdict['steps']} steps, seed {verdict['seed']},"
        f" {verdict['n_faults']} faults injected, outcomes"
        f" {verdict['outcomes']}, ok={verdict['ok']}"
    )
    for err in verdict["errors"]:
        print(f"chaos error: {err}")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
