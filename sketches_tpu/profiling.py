"""Device-time attribution: where the accelerator's time actually goes.

The telemetry layer (``telemetry.py``) host-times every dispatch -- but
an async dispatch returns before the device finishes, so host spans
measure *submission*, not *execution*.  This layer wraps the engine
dispatch seams in **device-clocked** timers using ``bench.py``'s
``block_until_ready`` discipline: when armed, each instrumented
dispatch blocks until its result materializes and the elapsed time is
attributed per **engine tier** (overlap / tiles / windowed / wxla /
xla / pallas / psum) and per **phase** (ingest / fold / query /
decode).  Three surfaces:

* :func:`attribution` -- the measured table (calls, total/mean/min/max
  seconds per ``phase/tier``) joined against a **roofline estimate**
  per engine entry point: the traced jaxprs from
  ``analysis/jaxpr_audit.py``'s audited surface are walked for
  estimated flops and top-level boundary bytes, giving
  ``max(bytes/peak_bw, flops/peak_flops)`` as the light-speed time and
  ``x_roofline`` as how far each measured mean sits above it.
* ``telemetry.snapshot()["profiling"]`` -- the same table rides every
  armed snapshot (and survives :func:`telemetry.merge_snapshots`:
  measured calls/time fold by sum, fleet-wide device-time percentiles
  come from the ``profiling.device_s`` histogram this layer feeds).
* ``telemetry.chrome_trace()`` -- armed dispatches append ``X`` events
  on a second process track (``telemetry.CHROME_PID_DEVICE``, one
  thread per engine tier -- the declared collision-free pid scheme): the
  device timeline next to the host spans in one viewer.

Arming: OFF by default.  ``SKETCHES_TPU_PROFILING=1`` (declared in
``analysis/registry.py``) arms at process start; :func:`enable` /
:func:`disable` arm programmatically.  Cost discipline mirrors
``faults``/``telemetry``: every seam guards on ``profiling._ACTIVE``,
so the disarmed layer costs one attribute read + bool test per
dispatch -- no clock read, no allocation, and crucially **no forced
device sync** (blocking is the whole point when armed, and the whole
hazard when not).

Failure modes: the roofline estimator traces on demand and NEVER takes
the process down -- a trace failure lands as an ``"error"`` entry in
the roofline table instead of raising; the event ring is bounded (65k)
and drops-with-count like the telemetry span ring; peak numbers are
*declared* nominal hardware ceilings (TPU v4 by default), so
``x_roofline`` on other backends is a relative, not absolute, measure.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, List, Optional, Tuple

from sketches_tpu import telemetry
from sketches_tpu.analysis import registry

__all__ = [
    "PROFILING_ENV",
    "PEAK_FLOPS_PER_S",
    "PEAK_HBM_BYTES_PER_S",
    "enable",
    "disable",
    "enabled",
    "reset",
    "record",
    "attribution",
    "roofline",
    "chrome_events",
]

#: Declared in ``analysis/registry.py`` (the kill-switch inventory).
PROFILING_ENV = registry.PROFILING.name

#: Nominal peak arithmetic throughput the roofline is drawn against
#: (TPU v4 bf16 peak).  On other backends ``x_roofline`` stays a
#: relative measure against this declared ceiling.
PEAK_FLOPS_PER_S = 275e12

#: Nominal HBM read bandwidth the roofline is drawn against (TPU v4).
PEAK_HBM_BYTES_PER_S = 1.2e12

#: Fast-path guard: instrumented seams check this module flag before
#: doing any profiling work (one bool test per dispatch disarmed).
_ACTIVE = registry.enabled(registry.PROFILING)

_MAX_EVENTS = 65536

_lock = threading.Lock()
_stats: Dict[Tuple[str, str], Dict[str, float]] = {}
_events: List[dict] = []
_events_dropped = 0
_tier_tids: Dict[str, int] = {}
_roofline_cache: Optional[Dict[str, dict]] = None

#: Which audited entry point (``analysis/jaxpr_audit.py``) each measured
#: ``(phase, tier)`` pair dispatches into -- the join key between the
#: measured table and the roofline table.
_TIER_ENTRY: Dict[Tuple[str, str], str] = {
    ("query", "overlap"): "kernels.fused_quantile_tiles_overlap",
    ("query", "tiles"): "kernels.fused_quantile_tiles",
    ("query", "windowed"): "kernels.fused_quantile_windowed",
    ("query", "wxla"): "kernels.quantile_windowed_xla",
    ("query", "xla"): "batched.quantile",
    ("ingest", "pallas"): "kernels.ingest_histogram",
    # Construction-variant rungs (kernels.INGEST_VARIANTS): each maps to
    # its own audited entry so the roofline join names the rung that
    # actually served (same bytes, different construction width).
    ("ingest", "pallas:packed"): "kernels.ingest_histogram:packed",
    ("ingest", "pallas:hifold"): "kernels.ingest_histogram:hifold",
    ("ingest", "pallas:cmpfree"): "kernels.ingest_histogram:cmpfree",
    ("ingest", "xla"): "batched.add",
    ("ingest", "recenter"): "batched.add",
    ("ingest", "shard_map"): "batched.add",
    ("fold", "merge"): "batched.merge",
    ("fold", "psum"): "batched.merge",
}


def enable(on: bool = True) -> None:
    """Arm (or, with ``on=False``, disarm) device-time attribution.

    Never raises; recorded attribution is kept (:func:`reset` clears).
    Arming makes every instrumented dispatch BLOCK until the device
    finishes -- that synchronization is the measurement, and the reason
    the layer is off by default.
    """
    global _ACTIVE
    _ACTIVE = bool(on)


def disable() -> None:
    """Disarm the profiling layer (seams go back to one bool test per
    dispatch, no forced device sync; recorded state is kept)."""
    enable(False)


def enabled() -> bool:
    """Whether the layer is armed (env switch or :func:`enable`);
    False -- the default -- means no seam blocks or records anything."""
    return _ACTIVE


def reset() -> None:
    """Clear the measured table and the device-track event ring (test
    isolation hook; the roofline cache is kept -- it is static per
    build).  Never raises."""
    global _events_dropped
    with _lock:
        _stats.clear()
        _events.clear()
        _tier_tids.clear()
        _events_dropped = 0


def record(phase: str, tier: str, t0: float, sync: Any = None) -> float:
    """Close a device-clocked dispatch opened at ``t0 = telemetry.clock()``.

    Blocks until ``sync`` (the dispatch's output pytree; ``None`` for
    host-side phases like the wire codec) is ready -- bench.py's
    ``block_until_ready`` discipline -- then attributes the elapsed
    time to ``(phase, tier)``, feeds the mergeable
    ``profiling.device_s`` telemetry histogram, and appends one
    device-track trace event.  The seam idiom mirrors the hot-path
    telemetry spans::

        _p0 = telemetry.clock() if profiling._ACTIVE else None
        out = fn(...)
        if _p0 is not None:
            profiling.record("query", tier, _p0, out)

    Returns the measured seconds.  Never raises on an unsyncable
    ``sync`` (a host value passes through); while disarmed it records
    nothing and returns 0.0.
    """
    global _events_dropped
    if not _ACTIVE:
        return 0.0
    if sync is not None:
        try:
            import jax

            jax.block_until_ready(sync)
        except Exception:  # noqa: BLE001 - host values pass through unsynced
            pass
    now = telemetry.clock()
    dur = max(now - t0, 0.0)
    key = (phase, tier)
    with _lock:
        st = _stats.get(key)
        if st is None:
            st = _stats[key] = {
                "calls": 0.0, "total_s": 0.0,
                "min_s": math.inf, "max_s": -math.inf,
            }
        st["calls"] += 1.0
        st["total_s"] += dur
        if dur < st["min_s"]:
            st["min_s"] = dur
        if dur > st["max_s"]:
            st["max_s"] = dur
        tid = _tier_tids.get(tier)
        if tid is None:
            tid = _tier_tids[tier] = len(_tier_tids) + 1
        if len(_events) < _MAX_EVENTS:
            _events.append(
                {
                    "name": f"{phase}/{tier}",
                    "cat": "sketches_tpu.device",
                    "ph": "X",
                    "ts": (t0 - telemetry._epoch_pc) * 1e6,
                    "dur": dur * 1e6,
                    "pid": telemetry.CHROME_PID_DEVICE,
                    "tid": tid,
                    "args": {"phase": phase, "tier": tier},
                }
            )
        else:
            _events_dropped += 1
    telemetry.observe("profiling.device_s", dur, phase=phase, tier=tier)
    return dur


def chrome_events() -> List[dict]:
    """The device-track Chrome-trace events (pid 2 metadata + ``X``
    events), ready to splice into ``telemetry.chrome_trace()``.  An
    empty list (bar the process metadata) is the idle steady state."""
    with _lock:
        events = list(_events)
        tids = dict(_tier_tids)
    meta: List[dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": telemetry.CHROME_PID_DEVICE,
            "args": {"name": "sketches_tpu device (profiling)"},
        }
    ]
    for tier, tid in sorted(tids.items(), key=lambda kv: kv[1]):
        meta.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": telemetry.CHROME_PID_DEVICE,
                "tid": tid,
                "args": {"name": f"tier-{tier}"},
            }
        )
    return meta + events


# ---------------------------------------------------------------------------
# Roofline estimation (reuses the jaxpr-audit traced surface)
# ---------------------------------------------------------------------------


def _eqn_flops(eqn) -> float:
    """Rough per-equation flop estimate: 1 op per output element for
    elementwise work, ``2*out*K`` for ``dot_general`` (the MXU path),
    input-sized for reductions/scans.  An *estimate* by construction --
    good to well under the order of magnitude the roofline needs."""
    import numpy as np

    def size(v) -> int:
        aval = getattr(v, "aval", None)
        shape = getattr(aval, "shape", None)
        if shape is None:
            return 0
        return int(np.prod(shape)) if shape else 1

    prim = eqn.primitive.name
    out = sum(size(v) for v in eqn.outvars)
    if prim == "dot_general":
        dnums = eqn.params.get("dimension_numbers")
        try:
            (lhs_contract, _), _ = dnums
            lhs_shape = eqn.invars[0].aval.shape
            k = 1
            for d in lhs_contract:
                k *= int(lhs_shape[d])
            return 2.0 * out * k
        except Exception:  # noqa: BLE001 - fall back to elementwise cost
            return float(out)
    if prim.startswith(("reduce_", "cum", "argm", "scan", "sort")):
        return float(sum(size(v) for v in eqn.invars))
    return float(out)


def _entry_costs(name: str, fn, args) -> dict:
    """Trace one audited entry point -> estimated flops, boundary bytes,
    arithmetic intensity, and roofline seconds at the audited shape.
    A trace failure is reported in-row (``{"error": ...}``), not raised.
    """
    import jax
    import numpy as np

    from sketches_tpu.analysis.jaxpr_audit import _iter_jaxprs

    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # noqa: BLE001 - the row carries the failure
        return {"error": f"{type(e).__name__}: {str(e)[:200]}"}
    flops = 0.0
    for sub in _iter_jaxprs(closed.jaxpr):
        for eqn in sub.eqns:
            flops += _eqn_flops(eqn)

    def nbytes(v) -> int:
        aval = getattr(v, "aval", None)
        shape = getattr(aval, "shape", None)
        dtype = getattr(aval, "dtype", None)
        if shape is None or dtype is None:
            return 0
        n = int(np.prod(shape)) if shape else 1
        return n * np.dtype(dtype).itemsize

    bytes_ = float(
        sum(nbytes(v) for v in closed.jaxpr.invars)
        + sum(nbytes(v) for v in closed.jaxpr.outvars)
    )
    roofline_s = max(
        flops / PEAK_FLOPS_PER_S, bytes_ / PEAK_HBM_BYTES_PER_S
    )
    return {
        "flops": flops,
        "bytes": bytes_,
        "intensity_flops_per_byte": (flops / bytes_) if bytes_ else None,
        "roofline_s": roofline_s,
    }


def roofline(refresh: bool = False) -> Dict[str, dict]:
    """Per-entry-point roofline table over the jaxpr-audit surface
    (``analysis/jaxpr_audit.default_entry_points``), cached after the
    first call.  Entry points that fail to trace carry an ``"error"``
    row instead of raising; an entirely untraceable surface (no jax)
    returns ``{"error": ...}``."""
    global _roofline_cache
    if _roofline_cache is not None and not refresh:
        return _roofline_cache
    try:
        from sketches_tpu.analysis.jaxpr_audit import default_entry_points

        table = {
            name: _entry_costs(name, fn, args)
            for name, fn, args in default_entry_points()
        }
    except Exception as e:  # noqa: BLE001 - attribution must not crash
        table = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
    _roofline_cache = table
    return table


def attribution() -> dict:
    """The measured-vs-roofline attribution table (JSON-safe).

    ``measured`` maps ``"phase/tier"`` to call counts and device-clocked
    seconds; ``attribution`` joins each measured row against its entry
    point's roofline estimate (``x_roofline`` = measured mean over the
    light-speed time -- how far the dispatch sits above the declared
    hardware ceiling).  Empty tables are the disarmed/idle steady
    state; roofline rows may carry ``"error"`` entries for entry points
    that failed to trace (never raises).
    """
    with _lock:
        measured = {
            f"{phase}/{tier}": {
                "phase": phase,
                "tier": tier,
                "calls": st["calls"],
                "total_s": st["total_s"],
                "mean_s": st["total_s"] / st["calls"] if st["calls"] else None,
                "min_s": None if math.isinf(st["min_s"]) else st["min_s"],
                "max_s": None if math.isinf(st["max_s"]) else st["max_s"],
            }
            for (phase, tier), st in _stats.items()
        }
        dropped = _events_dropped
    roof = roofline()
    rows = []
    for key, row in sorted(measured.items()):
        entry = _TIER_ENTRY.get((row["phase"], row["tier"]))
        r = roof.get(entry) if entry else None
        roofline_s = r.get("roofline_s") if isinstance(r, dict) else None
        mean = row["mean_s"]
        rows.append(
            {
                "phase": row["phase"],
                "tier": row["tier"],
                "entry": entry,
                "calls": row["calls"],
                "total_s": row["total_s"],
                "mean_s": mean,
                "roofline_s": roofline_s,
                "x_roofline": (
                    mean / roofline_s
                    if mean is not None and roofline_s
                    else None
                ),
            }
        )
    return {
        "measured": measured,
        "roofline": roof,
        "attribution": rows,
        "peaks": {
            "flops_per_s": PEAK_FLOPS_PER_S,
            "hbm_bytes_per_s": PEAK_HBM_BYTES_PER_S,
        },
        "events_dropped": dropped,
    }
