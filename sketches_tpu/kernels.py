"""Pallas TPU kernels: fused ingest and fused multi-quantile.

These are the performance play of SURVEY.md section 7 stage 6 -- same
``[n_streams, n_bins]`` state as ``sketches_tpu.batched``, different engine:

**Ingest** (``ingest_histogram``).  XLA's scatter-add serializes colliding
updates and streams bins through HBM every step (~0.1 G values/s measured on
v5e).  The kernel instead builds the histogram as MXU matmuls entirely in
VMEM: split each clamped key into ``hi = key // 128`` and ``lo = key % 128``,
form per-chunk one-hot operands ``A[n, hi, s] = onehot(hi) * w`` and
``L[n, s, lo] = onehot(lo)``, and accumulate ``A @ L -> [n, hi, lo]`` -- which
*is* the ``[n, n_bins]`` histogram -- into the output block that stays
resident in VMEM across the whole value stream.  One HBM read of the values,
one HBM write of the histogram; the one-hots never exist in HBM.  (The
matmul does n_bins x the minimal FLOPs, but the MXU is exactly the unit with
that headroom -- this is the classic TPU histogram trick.)

**Query** (``fused_quantile``).  The kernel fuses cumsum + rank selection
in VMEM: triangular-matmul prefix scans (streams as the M dimension,
pos+neg rows folded into one call), ``index = sum_b(cum[b] <= rank)`` as
one bf16 matvec per mask, then the three-way negative/zero/positive select
and the gamma**k decode, for all requested quantiles in one pass;
first/last-occupied clip bounds are plain iota min/max lane reductions.
Measured ~58 ms sustained for 1M x 512 on v5e -- ~2.2x the vectorized XLA
path (127 ms; the original vmapped-searchsorted formulation was 1.73 s)
and within ~2x of the chip's measured full-state HBM read time (the hard
floor for any exact query).

All three mappings run in-kernel (the interpolated ones extract
exponent/mantissa by int32 bitcast -- ``mapping._frexp_array`` -- which
lowers in Mosaic where ``jnp.frexp`` does not).  Weighted ingest splits each
f32 weight into three bf16 terms (successive rounding residuals: 3 x 8
mantissa bits cover f32's 24) and accumulates one bf16 matmul per term --
full f32 weight precision at the unit path's VMEM footprint.  Shapes must be
128-aligned; ``supports(spec, ...)`` reports eligibility and the facade
falls back to the XLA path otherwise.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from sketches_tpu.analysis import registry
from sketches_tpu.batched import SketchSpec, SketchState
from sketches_tpu.mapping import zero_threshold
from sketches_tpu.resilience import SketchValueError, SpecError

__all__ = [
    "supports",
    "select_engine",
    "INGEST_VARIANTS",
    "packed_ingest_enabled",
    "ingest_variant_supported",
    "choose_ingest_engine",
    "ingest_histogram",
    "fused_quantile",
    "fused_quantile_windowed",
    "fused_quantile_tiles",
    "fused_quantile_tiles_overlap",
    "quantile_windowed_xla",
    "plan_tile_query",
    "tile_query_eligible",
    "choose_query_engine",
    "add",
]

LO = 128  # lane width: low radix of the key split
_BN = 128  # streams per block
_BS = 128  # values per chunk

#: The ingest construction-variant ladder (DESIGN.md 2-r17).  All four
#: rungs emit BIT-IDENTICAL histograms and scalar columns; they differ
#: only in how the one-hot matmul operands are built:
#:
#: * ``stock``   -- the r4 formulation: (LO + 2*HI) int8 compare+cast
#:   rows per value (the §2-r5 construction-issue bound).
#: * ``packed``  -- two LO bins per bf16 lane: lanes ``r`` and ``r + 64``
#:   share one packed row (digit weights 1 / 256; per-subchunk counts
#:   <= 128 < 256 keep the base-256 digits carry-free, so the split is
#:   exact), halving the lo rows to 64; fixed [64 -> 128] placement
#:   matrices contracted on the MXU re-expand the digit planes so the
#:   VPU never touches a sub-128-lane reshape.
#: * ``hifold``  -- pos/neg stores share the hi rows: one [HI] operand
#:   with digit weights 1 (pos) / 256 (neg), unpacked the same way --
#:   2*HI rows collapse to HI.
#: * ``cmpfree`` -- stock layout, compare-free rows: saturating iota
#:   arithmetic (``1 - min(key ^ iota, 1)``) emits the 0/1 bits without
#:   a vector-mask select (the §2-r5 escape (b); see the 2-r17 dead
#:   list for the measured verdict).
#:
#: Packed rungs apply to UNIT-WEIGHT calls only: the digit unpack needs
#: integer per-cell masses, and arbitrary f32 weights destroy the digit
#: separation (2-r17 dead-list entry).  Weighted calls always build the
#: stock 3-term bf16 construction, whatever the selected variant.
INGEST_VARIANTS = ("stock", "packed", "hifold", "cmpfree")

#: Environment kill switch for the packed construction rungs: set to "0"
#: to pin every facade to the stock construction without a code change.
#: Declared in ``analysis/registry.py`` (the kill-switch inventory).
INGEST_PACKED_ENV = registry.INGEST_PACKED.name


def packed_ingest_enabled() -> bool:
    """Whether the facades may select a non-stock ingest construction.

    Reads the registered ``SKETCHES_TPU_INGEST_PACKED`` kill switch;
    with it set to ``0`` every auto pick degrades to the stock rung
    (never an error -- the rungs are bit-identical by construction)."""
    return registry.enabled(registry.INGEST_PACKED)


def ingest_variant_supported(
    spec: SketchSpec, variant: str, weighted: bool
) -> bool:
    """Whether ``variant`` can serve this (spec, weightedness) at all.

    ``stock`` serves everything the Pallas engine supports; the packed /
    folded / compare-free rungs are unit-weight constructions (see
    :data:`INGEST_VARIANTS`): f32-weighted masses break the base-256
    digit algebra, so weighted calls are served by the stock rung.
    """
    if variant not in INGEST_VARIANTS:
        raise SpecError(
            f"Unknown ingest variant {variant!r}; expected one of"
            f" {INGEST_VARIANTS}"
        )
    return variant == "stock" or not weighted


def choose_ingest_engine(
    spec: SketchSpec, weighted: bool, variant: Optional[str] = None
) -> str:
    """The facades' ingest construction-rung policy, in ONE place.

    ``variant=None`` is the auto pick: the packed rung (the analytically
    narrowest construction -- 64 + 2*HI rows vs the stock 128 + 2*HI)
    for unit-weight calls when the ``SKETCHES_TPU_INGEST_PACKED`` kill
    switch allows it, the stock rung otherwise.  An explicit ``variant``
    is validated against :func:`ingest_variant_supported` and honored
    (bench stage strips and the parity suite address rungs directly).
    Both ``BatchedDDSketch`` and ``DistributedDDSketch`` route through
    this so the two tiers can never diverge on the policy.
    """
    if variant is not None:
        if not ingest_variant_supported(spec, variant, weighted):
            raise SpecError(
                f"ingest variant {variant!r} does not support"
                f" weighted={weighted} (unit-weight construction only)"
            )
        return variant
    if weighted or not packed_ingest_enabled():
        return "stock"
    return "packed"


def _wide_block(dim: int, n_bins: int, base: int, gate: int = 1024) -> int:
    """Double a block dimension when divisibility and VMEM allow.

    Wider blocks amortize grid-iteration overhead; the narrow-bins gate
    keeps each caller's working set inside the 16 MB VMEM budget.  The
    default gate (1024 bins) is sized for the legacy full-window query's
    concat-scan; ingest passes a wider gate (its one-hot operands build in
    _BS-wide sub-chunks, so peak VMEM stays flat as the value block
    widens -- measured +21% ingest at 2048 bins with 256-wide chunks).
    """
    return 2 * base if dim % (2 * base) == 0 and n_bins <= gate else base


def supports(spec: SketchSpec, n_streams: int, batch: Optional[int] = None) -> bool:
    """Whether the Pallas engine can run this configuration."""
    return (
        spec.n_bins % LO == 0
        and spec.n_bins >= LO
        and jnp.dtype(spec.dtype) == jnp.float32
        and n_streams % _BN == 0
        and (batch is None or batch % _BS == 0)
    )


def select_engine(spec: SketchSpec, n_streams: int, engine: str):
    """Shared engine-selection policy -> (use_pallas, interpret).

    'auto' picks the kernels on TPU when the configuration qualifies;
    'pallas' forces them (interpreter mode off-TPU, for tests) and raises
    on unsupported configurations; 'xla' always takes the portable path.
    Both ``BatchedDDSketch`` and ``DistributedDDSketch`` route through
    this so the two tiers can never diverge on the policy.
    """
    if engine not in ("auto", "xla", "pallas"):
        raise SpecError(f"Unknown engine {engine!r}")
    supported = supports(spec, n_streams)
    if engine == "pallas" and not supported:
        raise SpecError(
            "engine='pallas' requires f32 state, 128-aligned n_bins, and a"
            " 128-aligned stream count (per-shard, when sharded over a"
            f" mesh); got {spec} with n_streams={n_streams}"
        )
    use_pallas = engine == "pallas" or (
        engine == "auto" and jax.default_backend() == "tpu" and supported
    )
    return use_pallas, jax.default_backend() != "tpu"


# Packed scalar-column layout of the ingest kernel's third output: one
# [n_streams, 16 + 2T] f32 block instead of many skinny outputs -- TPU HBM
# layout pads the minor dimension to the 128-lane tile, so every skinny
# column would cost a full 128-lane stripe (0.5 GB each at 1M streams;
# twelve of them broke the 1M compile outright), while widening the one
# already-padded block is free.  Bounds ride as f32 (exact integers far
# below 2**24).  Columns 16..16+2T carry the per-tile histogram masses of
# this call (pos tiles then neg tiles -- the ``SketchState.tile_sums``
# delta), emitted from the same VMEM histogram block the matmuls build.
_COL = {
    "zero": 0, "count": 1, "sum": 2, "min": 3, "max": 4,
    "clow": 5, "chigh": 6, "pos_lo": 7, "pos_hi": 8,
    "neg_lo": 9, "neg_hi": 10, "neg_total": 11,
}
_TILE0 = 16  # first tile-sum column (12 scalars + 4 pad)


def _ncols(n_tiles: int) -> int:
    """Packed-cols width for a spec: 16 scalar lanes + 2T tile lanes,
    rounded up to a multiple of 8 (sublane-friendly)."""
    return _TILE0 + ((2 * n_tiles + 7) // 8) * 8


def _ingest_kernel(
    values_ref,
    weights_ref,
    key_offset_ref,
    hist_pos_ref,
    hist_neg_ref,
    cols_ref,
    *,
    spec: SketchSpec,
    weighted: bool,
    variant: str = "stock",
):
    """One (stream-block, value-chunk) grid cell of the fused ingest.

    Emits the scalar bookkeeping (zero/count/sum/min/max/collapse/bounds)
    as one packed [block, 16] column output (layout ``_COL``) alongside the
    histograms, so the values make exactly one trip from HBM.
    ``variant`` selects the one-hot construction rung (see
    :data:`INGEST_VARIANTS`); every rung emits bit-identical outputs.
    """
    j = pl.program_id(1)
    n_bins = spec.n_bins
    hi_size = n_bins // LO

    v = values_ref[:]  # [BN, BS] f32
    w = weights_ref[:]

    # Branch-free three-way split + key computation, sharing the mapping's
    # own array path so bucket boundaries are bit-identical to the XLA
    # engine's _keys_and_masks -- including its explicit subnormals-are-zero
    # predicate (backend-independent, not hardware flush-to-zero).
    tiny = jnp.float32(zero_threshold(jnp.float32))
    is_pos = v >= tiny
    is_neg = v <= -tiny
    is_zero = jnp.logical_not(jnp.logical_or(is_pos, is_neg))
    absv = jnp.where(is_zero, 1.0, jnp.abs(v))
    keys = spec.mapping.key_array(absv)
    # Per-stream window low edge ([BN, 1] i32 column from the state),
    # broadcast against the value lanes -- the adaptive-window seam.
    key_lo = key_offset_ref[:]
    key_hi = key_lo + jnp.int32(n_bins - 1)
    clamped_low = keys < key_lo
    clamped_high = keys > key_hi
    idx = jnp.clip(keys, key_lo, key_hi) - key_lo

    live = w > 0.0
    w_pos = jnp.where(jnp.logical_and(is_pos, live), w, 0.0)
    w_neg = jnp.where(jnp.logical_and(is_neg, live), w, 0.0)
    w_zero = jnp.where(jnp.logical_and(is_zero, live), w, 0.0)
    w_live = w_pos + w_neg + w_zero
    signed = w_pos + w_neg
    finite_live = jnp.logical_and(live, jnp.logical_not(jnp.isnan(v)))

    # Pos and neg stores build as ONE histogram over 2*hi_size chunk rows
    # (neg keys offset by hi_size): per-stream batched matmuls dominate the
    # kernel, so folding the two stores into one matmul halves them.
    hi = idx // LO + jnp.where(is_neg, hi_size, 0)  # [BN, BS] in [0, 2*HI)
    lo = idx % LO

    bn, bs = v.shape

    bn_rows = values_ref.shape[0]

    ncols = cols_ref.shape[1]

    @pl.when(j == 0)
    def _():
        hist_pos_ref[:] = jnp.zeros_like(hist_pos_ref)
        hist_neg_ref[:] = jnp.zeros_like(hist_neg_ref)
        # Identity row built from lane selects (a jnp constant array would
        # be a captured const, which pallas rejects).  Tile-sum and pad
        # lanes are add-type: identity 0, the iota default.
        lane0 = jax.lax.broadcasted_iota(jnp.int32, (bn_rows, ncols), 1)
        ident = jnp.where(
            lane0 == _COL["min"],
            jnp.inf,
            jnp.where(
                lane0 == _COL["max"],
                -jnp.inf,
                jnp.where(
                    jnp.logical_or(
                        lane0 == _COL["pos_lo"], lane0 == _COL["neg_lo"]
                    ),
                    jnp.float32(n_bins),
                    jnp.where(
                        jnp.logical_or(
                            lane0 == _COL["pos_hi"], lane0 == _COL["neg_hi"]
                        ),
                        jnp.float32(-1.0),
                        jnp.float32(0.0),
                    ),
                ),
            ),
        )
        cols_ref[:] = ident.astype(jnp.float32)

    # A[n, h, s] = (hi[n, s] == h) * w[n, s].  UNIT-weight calls build both
    # one-hots in INT8 and accumulate on the MXU's int8 path with int32
    # output -- measured 5x the bf16 matmul throughput (36 vs 7.3 B
    # bins/s on the isolated histogram at 131k x 512) and exact by
    # construction (the live/sign mask folds into the hi one-hot, since
    # unit weights are 0/1).  Arbitrary f32 weights are split into three
    # bf16 terms (w = p0 + p1 + p2, successive rounding residuals: 3 x 8
    # mantissa bits >= f32's 24, so the split is exact) and the histogram
    # accumulates one bf16 matmul per term -- full f32 weight precision at
    # bf16 VMEM footprint, cheaper than a HIGHEST f32 matmul.  Blocks
    # wider than _BS process in _BS-value sub-chunks: one-hot operands are
    # built (and die) per sub-chunk, so peak VMEM stays at the
    # narrow-block level while the grid-iteration count still shrinks.
    #
    # BOTH one-hots lay the value axis on the LANES ([.., ., _BS], iota
    # over the sublane dim) and the matmul contracts the last dims of both
    # operands ("NT" form).  The earlier [BN, _BS, LO] lo one-hot --
    # values on sublanes -- built the same bits 3.5x slower (measured:
    # 153 -> 43 ms per 268M-value pass at 1M x 512); one-hot construction
    # is ~95% of ingest, so the layout IS the throughput.
    hi_iota = jax.lax.broadcasted_iota(jnp.int32, (bn, 2 * hi_size, _BS), 1)
    lo_iota = jax.lax.broadcasted_iota(jnp.int32, (bn, LO, _BS), 1)
    nt_dims = (((2,), (2,)), ((0,), (0,)))  # contract lanes; batch streams
    unit_variant = variant if not weighted else "stock"
    acc_dt = (
        jnp.float32
        if weighted or unit_variant in ("packed", "hifold")
        else jnp.int32
    )
    if unit_variant == "packed":
        # Pair lanes r and r + 64 into one packed row (digit weights
        # 1 / 256); the fixed placement matrices below re-expand the two
        # digit planes on the MXU, so the kernel never reshapes a
        # sub-128-lane block (the §2-r5 layout trap).
        pk_iota = jax.lax.broadcasted_iota(jnp.int32, (bn, LO // 2, _BS), 1)
        u_r = jax.lax.broadcasted_iota(jnp.int32, (LO // 2, LO), 0)
        u_l = jax.lax.broadcasted_iota(jnp.int32, (LO // 2, LO), 1)
        unpack_low = (u_l == u_r).astype(jnp.bfloat16)  # [64, 128]
        unpack_high = (u_l == u_r + LO // 2).astype(jnp.bfloat16)
        u_dims = (((2,), (0,)), ((), ()))  # [bn, R, 64] @ [64, 128]
    elif unit_variant == "hifold":
        # Pos/neg share the hi rows: digit weights 1 (pos) / 256 (neg),
        # zero for dead/zero/NaN lanes (signed == 0 there, same masking
        # as the stock live fold).
        hp = idx // LO  # [BN, BS] in [0, HI) -- no store offset
        sscale = jnp.where(
            signed > 0.0,
            jnp.where(is_neg, jnp.float32(256.0), jnp.float32(1.0)),
            0.0,
        )
        hp_iota = jax.lax.broadcasted_iota(jnp.int32, (bn, hi_size, _BS), 1)
    c = jnp.zeros((bn, 2 * hi_size, LO), acc_dt)
    for t in range(bs // _BS):
        # lax.slice_in_dim, not mixed None+slice getitem: the latter takes
        # jnp's gather path, which has no general Mosaic lowering.
        hi_t = jax.lax.slice_in_dim(hi, t * _BS, (t + 1) * _BS, axis=1)
        lo_t = jax.lax.slice_in_dim(lo, t * _BS, (t + 1) * _BS, axis=1)
        w_t = jax.lax.slice_in_dim(signed, t * _BS, (t + 1) * _BS, axis=1)
        if weighted:
            onehot_hi = (hi_t[:, None, :] == hi_iota).astype(jnp.bfloat16)
            onehot_lo = (lo_t[:, None, :] == lo_iota).astype(jnp.bfloat16)
            for part in _exact_bf16_terms(w_t, 3):
                # bf16 multiply by a 0/1 one-hot is exact.
                a = onehot_hi * part[:, None, :]  # [BN, 2HI, _BS] bf16
                c = c + jax.lax.dot_general(
                    a, onehot_lo, nt_dims, preferred_element_type=jnp.float32
                )  # [BN, 2HI, LO]
        elif unit_variant == "packed":
            # 64 packed lo rows instead of 128: row r carries 1 for
            # lo == r and 256 for lo == r + 64 (both bf16-exact; the two
            # cases are exclusive per value, so no lane ever holds 257).
            # Per-subchunk counts are <= _BS = 128 < 256, so the f32
            # accumulator's base-256 digits never carry and the integer
            # split below is exact -- bit-identical to the stock rung.
            pr_t = jnp.bitwise_and(lo_t, LO // 2 - 1)  # lo mod 64
            hb_t = jnp.right_shift(lo_t, 6)  # lo >= 64 flag (0/1)
            # Per-VALUE amplitude (live fold + digit weight in one [BN,
            # _BS] vector, O(1) ops per value -- NOT per row): the rows
            # below stay 2-op compare+cast / compare+select, which is
            # where the width halving actually lands.
            amp_t = jnp.where(
                w_t > 0.0,
                jnp.where(hb_t == 1, jnp.float32(256.0), jnp.float32(1.0)),
                0.0,
            )
            a16 = jnp.where(
                hi_t[:, None, :] == hi_iota, amp_t[:, None, :], 0.0
            ).astype(jnp.bfloat16)  # [BN, 2HI, _BS]
            p16 = (pr_t[:, None, :] == pk_iota).astype(
                jnp.bfloat16
            )  # [BN, 64, _BS]: 64 rows, 2 ops each -- half the stock lo
            cp = jax.lax.dot_general(
                a16, p16, nt_dims, preferred_element_type=jnp.float32
            )  # [BN, 2HI, 64]: low digit + 256 * high digit, exact ints
            oi = cp.astype(jnp.int32)
            lowd = jnp.bitwise_and(oi, 255).astype(jnp.bfloat16)
            highd = jnp.right_shift(oi, 8).astype(jnp.bfloat16)
            # MXU-absorbed unpack: place digit plane r at lane r (low)
            # and lane r + 64 (high) -- two [64 -> 128] matmuls instead
            # of any sub-128-minor reshape/concat (no Mosaic lowering).
            c = c + jax.lax.dot_general(
                lowd, unpack_low, u_dims, preferred_element_type=jnp.float32
            )
            c = c + jax.lax.dot_general(
                highd, unpack_high, u_dims, preferred_element_type=jnp.float32
            )
        elif unit_variant == "hifold":
            # HI hi rows instead of 2*HI: pos counts ride the 1s digit,
            # neg counts the 256s digit of one folded matmul; the split
            # is exact by the same per-subchunk <= 128 < 256 bound.
            hp_t = jax.lax.slice_in_dim(hp, t * _BS, (t + 1) * _BS, axis=1)
            ssc_t = jax.lax.slice_in_dim(
                sscale, t * _BS, (t + 1) * _BS, axis=1
            )
            a16 = jnp.where(
                hp_t[:, None, :] == hp_iota, ssc_t[:, None, :], 0.0
            ).astype(jnp.bfloat16)  # [BN, HI, _BS]
            b16 = (lo_t[:, None, :] == lo_iota).astype(jnp.bfloat16)
            cp = jax.lax.dot_general(
                a16, b16, nt_dims, preferred_element_type=jnp.float32
            )  # [BN, HI, LO]
            oi = cp.astype(jnp.int32)
            posd = jnp.bitwise_and(oi, 255).astype(jnp.float32)
            negd = jnp.right_shift(oi, 8).astype(jnp.float32)
            # Sublane concat (pos rows then neg rows) -- matches the
            # stock 2*HI row layout exactly; lane offsets agree.
            c = c + jnp.concatenate([posd, negd], axis=1)
        elif unit_variant == "cmpfree":
            # Stock layout, compare-free rows: 1 - min(key ^ iota, 1)
            # emits the same 0/1 bits from saturating integer arithmetic
            # (no vector-mask select).  Kept as a rung for the stage
            # strips; the 2-r17 dead list records the measured verdict.
            live8 = (w_t > 0.0)[:, None, :].astype(jnp.int8)
            xh = jnp.bitwise_xor(hi_t[:, None, :], hi_iota)
            a8 = (1 - jnp.minimum(xh, 1)).astype(jnp.int8) * live8
            xl = jnp.bitwise_xor(lo_t[:, None, :], lo_iota)
            b8 = (1 - jnp.minimum(xl, 1)).astype(jnp.int8)
            c = c + jax.lax.dot_general(
                a8, b8, nt_dims, preferred_element_type=jnp.int32
            )
        else:
            live_t = (w_t > 0.0)[:, None, :]
            a8 = jnp.logical_and(
                hi_t[:, None, :] == hi_iota, live_t
            ).astype(jnp.int8)
            b8 = (lo_t[:, None, :] == lo_iota).astype(jnp.int8)
            c = c + jax.lax.dot_general(
                a8, b8, nt_dims, preferred_element_type=jnp.int32
            )
    if c.dtype != jnp.float32:
        # Exact: per-call counts are bounded by the batch width << 2**31.
        c = c.astype(jnp.float32)
    # Per-tile masses of this chunk's histogram: a lane reduction over the
    # [bn, 2*HI, LO] block the matmuls just built -- the tile-summary delta
    # (pos rows then neg rows, matching ``SketchState.tile_sums`` layout)
    # for (nearly) free, before the block flattens into the bin axis.
    tile_delta = c.sum(-1)  # [bn, 2*hi_size] f32
    c = c.reshape(bn, 2 * n_bins)
    hist_pos_ref[:] += c[:, :n_bins]
    hist_neg_ref[:] += c[:, n_bins:]

    # Per-store occupied-bounds deltas (VERDICT r3 query-byte-cut seam) in
    # f32: min/max of this chunk's bin indices per store, same contract as
    # batched.add.
    hits_pos = jnp.logical_and(live, is_pos)
    hits_neg = jnp.logical_and(live, is_neg)
    idx_f = idx.astype(jnp.float32)
    nb_f, neg1 = jnp.float32(n_bins), jnp.float32(-1.0)
    # One packed [bn, ncols] delta block, folded into the output columns
    # with a single min/max/add pass per identity class.
    delta = [None] * _TILE0
    delta[_COL["zero"]] = jnp.sum(w_zero, axis=1, keepdims=True)
    delta[_COL["count"]] = jnp.sum(w_live, axis=1, keepdims=True)
    delta[_COL["sum"]] = jnp.sum(
        jnp.where(live, v, 0.0) * w_live, axis=1, keepdims=True
    )
    delta[_COL["min"]] = jnp.min(
        jnp.where(finite_live, v, jnp.inf), axis=1, keepdims=True
    )
    delta[_COL["max"]] = jnp.max(
        jnp.where(finite_live, v, -jnp.inf), axis=1, keepdims=True
    )
    delta[_COL["clow"]] = jnp.sum(
        jnp.where(clamped_low, signed, 0.0), axis=1, keepdims=True
    )
    delta[_COL["chigh"]] = jnp.sum(
        jnp.where(clamped_high, signed, 0.0), axis=1, keepdims=True
    )
    delta[_COL["pos_lo"]] = jnp.min(
        jnp.where(hits_pos, idx_f, nb_f), axis=1, keepdims=True
    )
    delta[_COL["pos_hi"]] = jnp.max(
        jnp.where(hits_pos, idx_f, neg1), axis=1, keepdims=True
    )
    delta[_COL["neg_lo"]] = jnp.min(
        jnp.where(hits_neg, idx_f, nb_f), axis=1, keepdims=True
    )
    delta[_COL["neg_hi"]] = jnp.max(
        jnp.where(hits_neg, idx_f, neg1), axis=1, keepdims=True
    )
    delta[_COL["neg_total"]] = jnp.sum(w_neg, axis=1, keepdims=True)
    zeros_col = jnp.zeros((bn_rows, 1), jnp.float32)
    for ci in range(_TILE0):
        if delta[ci] is None:
            delta[ci] = zeros_col
    # Tile-sum lanes ride after the scalars; trailing lanes pad to ncols.
    parts = delta[:_TILE0] + [tile_delta]
    tail = ncols - _TILE0 - 2 * hi_size
    if tail:
        parts.append(jnp.zeros((bn_rows, tail), jnp.float32))
    dblock = jnp.concatenate(parts, axis=1)  # [bn, ncols]
    prev = cols_ref[:]
    lane = jax.lax.broadcasted_iota(jnp.int32, (bn_rows, ncols), 1)
    is_min = jnp.logical_or(
        lane == _COL["min"],
        jnp.logical_or(lane == _COL["pos_lo"], lane == _COL["neg_lo"]),
    )
    is_max = jnp.logical_or(
        lane == _COL["max"],
        jnp.logical_or(lane == _COL["pos_hi"], lane == _COL["neg_hi"]),
    )
    cols_ref[:] = jnp.where(
        is_min,
        jnp.minimum(prev, dblock),
        jnp.where(is_max, jnp.maximum(prev, dblock), prev + dblock),
    )


def ingest_histogram(
    spec: SketchSpec,
    values: jax.Array,
    weights: jax.Array,
    key_offset: jax.Array,
    *,
    weighted: bool = True,
    interpret: bool = False,
    variant: str = "stock",
) -> Tuple[jax.Array, ...]:
    """One fused pass over a value batch -> histograms + scalar bookkeeping.

    ``values``/``weights``: [n_streams, batch] f32; ``key_offset``:
    [n_streams] i32 per-stream window edges (``state.key_offset``).  Returns
    ``(hist_pos, hist_neg, cols)`` -- the two [n_streams, n_bins]
    histograms of this batch plus the packed [n_streams, 16] per-stream
    counter deltas (column layout ``_COL``: zero/count/sum/min/max/
    collapse/per-store occupied bounds/negative total), all from a single
    HBM read of the values.  ``variant`` picks the construction rung
    (:data:`INGEST_VARIANTS`; bit-identical outputs by construction).
    """
    if not ingest_variant_supported(spec, variant, weighted):
        raise SpecError(
            f"ingest variant {variant!r} does not support weighted calls"
            " (unit-weight construction only); the facades route these"
            " to the stock rung automatically"
        )
    n, s = values.shape
    bs = _wide_block(s, spec.n_bins, _BS, gate=2048)
    grid = (n // _BN, s // bs)
    ncols = _ncols(spec.n_bins // LO)
    hist_shape = jax.ShapeDtypeStruct((n, spec.n_bins), jnp.float32)
    hist_spec = pl.BlockSpec(
        (_BN, spec.n_bins), lambda i, j: (i, 0), memory_space=pltpu.VMEM
    )
    cols_spec = pl.BlockSpec(
        (_BN, ncols), lambda i, j: (i, 0), memory_space=pltpu.VMEM
    )
    return pl.pallas_call(
        functools.partial(
            _ingest_kernel, spec=spec, weighted=weighted, variant=variant
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BN, bs), lambda i, j: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BN, bs), lambda i, j: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BN, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[hist_spec, hist_spec, cols_spec],
        out_shape=[
            hist_shape,
            hist_shape,
            jax.ShapeDtypeStruct((n, ncols), jnp.float32),
        ],
        interpret=interpret,
    )(values, weights, key_offset[:, None].astype(jnp.int32))


_BF16_MAX = 3.3895314e38  # plain float: jnp constants would be captured consts in pallas


def _exact_bf16_terms(x: jax.Array, n_terms: int) -> list:
    """Split f32 ``x`` into ``n_terms`` bf16 values summing exactly to x.

    Successive round-to-nearest residuals: each term captures the next 8
    mantissa bits, so 3 terms cover f32's 24.  Each term is clamped into
    bf16's finite range: finite f32 values above bf16 max (~3.3895e38, a
    sliver below f32 max -- reachable as weighted bin masses) would round
    to inf and poison everything downstream; clamped, they split across
    terms with ~2e-10 relative error instead.
    """
    terms = []
    rem = x
    for _ in range(n_terms):
        p = jnp.clip(rem, -_BF16_MAX, _BF16_MAX).astype(jnp.bfloat16)
        rem = rem - p.astype(jnp.float32)
        terms.append(p)
    return terms


def _cumsum_bins(x: jax.Array, n_terms: int = 3) -> jax.Array:
    """Inclusive prefix sum along the bin axis, as full-tile MXU matmuls.

    ``jnp.cumsum`` has no Mosaic lowering; a triangular-ones matmul does the
    same job and feeds the MXU: block-local cumsum over 128-lane tiles, then
    an exclusive cumsum of tile totals added back as offsets.

    Two layout/precision choices matter (~10x together at 1M streams):

    * The local scan contracts as ``[HI, BN, LO] @ [LO, LO]`` -- *streams*
      are the M dimension, batched over the HI tiles.  The transposed form
      ``[BN, HI, LO] @ [LO, LO]`` is BN small matmuls of M = HI rows (3% of
      an MXU tile at 512 bins); this form is HI full 128x128 tiles.
    * Exactness comes from a manual 3-term bf16 split of the counts (24
      mantissa bits, matching f32) against the exactly-representable 0/1
      triangle, with f32 accumulation -- half the passes of
      ``Precision.HIGHEST`` and exact for counts < 2**24, the state dtype's
      own exactness ceiling.
    """
    bn, n_bins = x.shape
    hi_size = n_bins // LO
    x3t = x.reshape(bn, hi_size, LO).swapaxes(0, 1)  # [HI, BN, LO]
    tri = (
        jax.lax.broadcasted_iota(jnp.int32, (LO, LO), 0)
        <= jax.lax.broadcasted_iota(jnp.int32, (LO, LO), 1)
    ).astype(jnp.bfloat16)
    dims = (((2,), (0,)), ((), ()))  # contract LO; HI stays batched via loop
    local = jnp.zeros((hi_size, bn, LO), jnp.float32)
    for p in _exact_bf16_terms(x3t, n_terms):
        local = local + jax.lax.dot_general(
            p, tri, dims, preferred_element_type=jnp.float32
        )  # [HI, BN, LO] block-local inclusive cumsum
    totals = local[:, :, LO - 1].swapaxes(0, 1)  # [BN, HI]
    tri_excl = (
        jax.lax.broadcasted_iota(jnp.int32, (hi_size, hi_size), 0)
        < jax.lax.broadcasted_iota(jnp.int32, (hi_size, hi_size), 1)
    ).astype(jnp.float32)
    offsets = jnp.zeros((bn, hi_size), jnp.float32)
    for p in _exact_bf16_terms(totals, n_terms):
        offsets = offsets + jax.lax.dot_general(
            p.astype(jnp.float32), tri_excl, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BN, HI] exclusive cumsum of block totals (M = BN: full tiles)
    return (
        (local.swapaxes(0, 1) + offsets[:, :, None]).reshape(bn, n_bins)
    )


def _first_last_occupied(x: jax.Array):
    """Index of the first and last occupied bin per row -> ([R,1], [R,1]) i32.

    Plain VPU lane reductions over an occupancy-selected iota: measured ~5x
    cheaper than a suffix-count matmul scan (+2 ms vs +10 ms over the HBM
    floor at 1M x 512), and exact by construction.  Empty rows give
    (n_bins, -1) -- the same degenerate clip bounds the mask formulation
    produced, discarded downstream by the three-way select.
    """
    r, n_bins = x.shape
    occ = x > 0.0
    iota = jax.lax.broadcasted_iota(jnp.int32, (r, n_bins), 1)
    last = jnp.max(jnp.where(occ, iota, -1), axis=1, keepdims=True)
    first = jnp.min(jnp.where(occ, iota, n_bins), axis=1, keepdims=True)
    return first, last


def _select_quantiles(spec, bins_pos, bins_neg, zero_count, count, key_lo, qs):
    """The rank-selection math shared by the standalone query kernel and the
    fused ingest+query kernel -> values [BN, Q].

    Rank walks are *mask-matmuls*: each index is "count of bins whose
    cumulative mass is below a threshold", contracted against ones on the
    MXU (one 2D matvec per mask -- see the comment below) instead of the
    VPU's slow many-lane-axis reductions.  First/last-occupied clip bounds
    come from plain iota min/max lane reductions (cheap at 2 reductions).
    """
    bn, n_bins = bins_pos.shape
    q_total = qs.shape[1]

    # Pos and neg stores process as one [2*BN, B] call when VMEM allows:
    # rows are independent, so concatenating them halves the Mosaic matmul
    # invocations.  At wide bins the doubled scan working set blows the
    # 16 MB VMEM budget -- fall back to per-store scans there.
    if bn * n_bins <= 128 * 1024:
        both = jnp.concatenate([bins_pos, bins_neg], axis=0)
        cum_both = _cumsum_bins(both)
        first_both, last_both = _first_last_occupied(both)
        cum_pos, cum_neg = cum_both[:bn], cum_both[bn:]
        first_pos, first_neg = first_both[:bn], first_both[bn:]
        last_pos, last_neg = last_both[:bn], last_both[bn:]
    else:
        cum_pos = _cumsum_bins(bins_pos)
        cum_neg = _cumsum_bins(bins_neg)
        first_pos, last_pos = _first_last_occupied(bins_pos)
        first_neg, last_neg = _first_last_occupied(bins_neg)
    neg_count = cum_neg[:, n_bins - 1 :]  # [BN, 1]
    rank = qs * (count - 1.0)  # [BN, Q]

    # Rank masks, each [BN, B] bf16 (0/1 exact):
    #   0..Q-1: idx_neg per q;  Q..2Q-1: idx_pos per q
    rev = neg_count - 1.0 - rank  # [BN, Q]
    pos_rank = rank - zero_count - neg_count
    masks = []
    for qi in range(q_total):
        masks.append(cum_neg < rev[:, qi][:, None] + 1.0)
    for qi in range(q_total):
        masks.append(cum_pos <= pos_rank[:, qi][:, None])
    # One [BN, B] @ [B, 8] matvec per mask.  Measured on v5e: grouping the
    # masks into a stacked [BN, 8, B] @ [B, 8] 3D dot_general is ~6x slower
    # (Mosaic lowers the 3D contraction pathologically), while per-mask 2D
    # matvecs cost ~0.5 ms total at 1M streams.
    ones = jnp.ones((n_bins, 8), jnp.bfloat16)  # 8 lanes: MXU-friendly matvec
    parts = [
        jax.lax.dot_general(
            m.astype(jnp.bfloat16), ones, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )[:, :1]
        for m in masks
    ]
    counts = jnp.concatenate(parts, axis=1).astype(jnp.int32)  # [BN, 2Q]

    idx_neg = jnp.clip(counts[:, :q_total], first_neg, last_neg)
    idx_pos = jnp.clip(counts[:, q_total:], first_pos, last_pos)

    # Decode all Q indices at once through the mapping's own array path
    # (bit-identical bucket representatives to the XLA engine); key_lo is
    # the per-stream [BN, 1] i32 window edge, broadcast over the Q axis.
    val_neg = -spec.mapping.value_array(idx_neg + key_lo)  # [BN, Q]
    val_pos = spec.mapping.value_array(idx_pos + key_lo)

    val = jnp.where(
        rank < neg_count,
        val_neg,
        jnp.where(rank < neg_count + zero_count, 0.0, val_pos),
    )
    valid = jnp.logical_and(
        jnp.logical_and(qs >= 0.0, qs <= 1.0), count > 0.0
    )
    return jnp.where(valid, val, jnp.nan)  # [BN, Q]


def _quantile_kernel(
    bins_pos_ref,
    bins_neg_ref,
    zero_count_ref,
    count_ref,
    key_offset_ref,
    qs_ref,
    out_ref,
    *,
    spec: SketchSpec,
):
    """One stream-block of the fused multi-quantile query."""
    out_ref[:] = _select_quantiles(
        spec,
        bins_pos_ref[:],
        bins_neg_ref[:],
        zero_count_ref[:],
        count_ref[:],
        key_offset_ref[:],
        qs_ref[:],
    )


def fused_quantile(
    spec: SketchSpec,
    state: SketchState,
    qs: jax.Array,
    *,
    interpret: bool = False,
) -> jax.Array:
    """All requested quantiles for every stream -> [n_streams, Q].

    Semantics identical to ``batched.quantile`` (NaN for empty streams or
    q outside [0, 1]); one VMEM pass over the bins instead of a cumsum +
    vmapped binary search through HBM.
    """
    n = state.n_streams
    if spec.bins_integer:
        # The VMEM scan's bf16-term splits are exact only for f32-ceiling
        # masses; integer-bin (exact > 2**24) queries take the XLA path,
        # whose integer cumsum + integer rank compare never rounds.
        raise NotImplementedError(
            "fused_quantile requires float bins; integer-bin specs query"
            " via batched.quantile (the facades route this automatically)"
        )
    qs = jnp.atleast_1d(jnp.asarray(qs, jnp.float32))
    q_total = qs.shape[0]
    if q_total == 0:  # empty quantile list: nothing to launch
        return jnp.zeros((n, 0), jnp.float32)
    bn = _wide_block(n, spec.n_bins, _BN)
    bins_spec = pl.BlockSpec(
        (bn, spec.n_bins), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    col_spec = pl.BlockSpec((bn, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_quantile_kernel, spec=spec),
        grid=(n // bn,),
        in_specs=[
            bins_spec,
            bins_spec,
            col_spec,
            col_spec,
            col_spec,
            pl.BlockSpec((1, q_total), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (bn, q_total), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((n, q_total), jnp.float32),
        interpret=interpret,
    )(
        state.bins_pos,
        state.bins_neg,
        state.zero_count[:, None],
        state.count[:, None],
        state.key_offset[:, None].astype(jnp.int32),
        qs[None, :],
    )


# ---------------------------------------------------------------------------
# Windowed multi-quantile query (VERDICT r3 item 1: read only the occupied
# span, skip the negative store when it is empty)
# ---------------------------------------------------------------------------


def _cumsum_tile(x: jax.Array, n_terms: int = 3) -> jax.Array:
    """Inclusive prefix sum of one 128-lane tile ``[rows, 128]`` on the MXU.

    Same exact 3-term bf16 split as :func:`_cumsum_bins`, but single-tile:
    the cross-tile offsets are the caller's carry (the windowed kernel
    accumulates them across its column grid instead of a second matmul).
    """
    tri = (
        jax.lax.broadcasted_iota(jnp.int32, (LO, LO), 0)
        <= jax.lax.broadcasted_iota(jnp.int32, (LO, LO), 1)
    ).astype(jnp.bfloat16)
    out = jnp.zeros(x.shape, jnp.float32)
    for p in _exact_bf16_terms(x, n_terms):
        out = out + jax.lax.dot_general(
            p, tri, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    return out


def _windowed_kernel(
    lo_ref,  # scalar prefetch: [1] i32, window start in w_tiles-wide blocks
    *refs,
    spec: SketchSpec,
    w_tiles: int,
    with_neg: bool,
    q_total: int,
    bn: int,
):
    """One (stream-block, column-tile) cell of the windowed query.

    The grid walks the occupied window's 128-bin column tiles sequentially
    (j fastest); VMEM scratch carries the running prefix totals, the
    per-threshold rank counts, and the exact per-store occupied bounds
    across tiles, and the final tile decodes.  Bins outside the window are
    provably empty (the state's ``occ_lo/occ_hi`` invariant), so their
    cumulative mass is either 0 (below) or the store total (above) -- the
    decode accounts for the ``below`` prefix by offsetting counts with the
    window start and clipping into the exact occupied bounds.

    All per-stream rank thresholds arrive pre-packed in ONE column block
    (``thr_ref``: pos_rank[Q] | rev_rank+1[Q] | key_offset) -- computed
    once in XLA by the caller.  Column blocks are ``w_tiles`` 128-lane
    tiles wide (wider DMAs stream ~3x faster than single-tile blocks,
    measured), walked as an in-cell loop; rank counts are mask-matvecs on
    the MXU (measured 4x cheaper than VPU lane-axis reductions).
    """
    if with_neg:
        (bp_ref, bn_ref, thr_ref, out_ref, carry, counts) = refs
    else:
        (bp_ref, thr_ref, out_ref, carry, counts) = refs
    j = pl.program_id(1)
    n_wblocks = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        carry[:] = jnp.zeros_like(carry)
        counts[:] = jnp.zeros_like(counts)

    thr = thr_ref[:]  # [bn, 2Q + 5]
    pos_rank = thr[:, :q_total]
    rev_p1 = thr[:, q_total : 2 * q_total]
    ones8 = jnp.ones((LO, 8), jnp.bfloat16)

    def one_store(block, carry_col, thresholds, strict):
        acc = jnp.zeros((bn, q_total), jnp.float32)
        for t in range(w_tiles):
            bins = jax.lax.slice_in_dim(block, t * LO, (t + 1) * LO, axis=1)
            local = _cumsum_tile(bins)
            cum = local + carry[:, carry_col : carry_col + 1]
            cols = []
            for qi in range(q_total):
                th = thresholds[:, qi : qi + 1]
                m = (cum < th) if strict else (cum <= th)
                cols.append(
                    jax.lax.dot_general(
                        m.astype(jnp.bfloat16), ones8,
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )[:, :1]
                )
            acc = acc + jnp.concatenate(cols, axis=1)
            carry[:, carry_col : carry_col + 1] += local[:, LO - 1 :]
        return acc  # [bn, Q]

    # Positive store: smallest key with cum > r  ==  #(cum <= pos_rank).
    counts[:, :q_total] += one_store(bp_ref[:], 0, pos_rank, False)
    if with_neg:
        # Negative store (lower=False): #(cum < rev_rank + 1).
        counts[:, q_total:] += one_store(bn_ref[:], 1, rev_p1, True)

    @pl.when(j == n_wblocks - 1)
    def _():
        window_lo = lo_ref[0] * jnp.int32(w_tiles * LO)
        # Exact per-store occupied bounds ride in the packed block (state
        # counters -- no per-tile bounds work in the kernel); degenerate
        # ranks clip into them.  Empty stores carry the (n_bins, -1)
        # sentinels: the clip then yields index n_bins -- one past the
        # window -- whose decode stays finite only because value_array
        # saturates out-of-range keys; the branch select discards it.  (A
        # decode via table gather would need an explicit in-range clamp
        # here first.)
        bds = thr[:, 2 * q_total + 1 :].astype(jnp.int32)  # [bn, 4]
        first_pos = bds[:, 0:1]
        last_pos = jnp.maximum(bds[:, 1:2], first_pos)
        cts = counts[:].astype(jnp.int32)
        # Bins below the window hold zero mass: each counts toward any
        # threshold >= 0, hence the window_lo offset; the exact-bounds clip
        # then absorbs every degenerate case (negative thresholds,
        # rank-past-total rounding, empty stores).
        idx_pos = jnp.clip(window_lo + cts[:, :q_total], first_pos, last_pos)
        key_lo = thr[:, 2 * q_total : 2 * q_total + 1].astype(jnp.int32)
        # Branch predicates from the packed thresholds alone:
        #   rank < neg_count        <=>  rev_p1 > 0
        #   rank < neg_count + zero <=>  pos_rank < 0
        if with_neg:
            # ONE decode chain for both stores (see _tiles_kernel): select
            # the branch's index/clip bounds BEFORE the expensive
            # [bn, Q]-shaped value_array chain, apply the sign after.
            first_neg = bds[:, 2:3]
            last_neg = jnp.maximum(bds[:, 3:4], first_neg)
            idx_neg = jnp.clip(
                window_lo + cts[:, q_total:], first_neg, last_neg
            )
            in_neg = rev_p1 > 0.0
            idx_sel = jnp.where(in_neg, idx_neg, idx_pos)
            sign = jnp.where(in_neg, jnp.float32(-1.0), jnp.float32(1.0))
            dec = sign * spec.mapping.value_array(idx_sel + key_lo)
            val = jnp.where(
                jnp.logical_and(
                    jnp.logical_not(in_neg), pos_rank < 0.0
                ),
                0.0,
                dec,
            )
        else:
            val_pos = spec.mapping.value_array(idx_pos + key_lo)
            val = jnp.where(pos_rank < 0.0, 0.0, val_pos)
        out_ref[:] = val


def plan_window(spec: SketchSpec, occ_lo_min: int, occ_hi_max: int):
    """Host-side window plan from globally folded occupied bounds.

    Returns ``(lo_wblock, n_wblocks, w_tiles)`` for
    :func:`fused_quantile_windowed`: the widest column-block width in
    {4, 2, 1} tiles that the span warrants (wider blocks stream ~3x faster;
    a 1-tile span should not pay a 4-tile window), aligned so the dynamic
    block index is exact.  An empty batch (``occ_hi_max < 0``) plans the
    minimal window at position 0.
    """
    tiles_total = spec.n_bins // LO
    if occ_hi_max < 0:
        lo_t = hi_t = 0
    else:
        lo_t = max(0, min(occ_lo_min, occ_hi_max)) // LO
        hi_t = min(occ_hi_max // LO, tiles_total - 1)
    # Pick the width that reads the fewest tiles (alignment can force a
    # wide-block window to cover up to w-1 extra tiles on each side --
    # measured 2.4x query cost on a 2-tile span whose wide window read 4);
    # ties go to the wider block (wider DMAs stream faster).
    best = None
    for w in (4, 2, 1):
        if tiles_total % w:
            continue
        lo_w = lo_t // w
        n_w = hi_t // w - lo_w + 1
        if best is None or n_w * w < best[1] * best[2]:
            best = (lo_w, n_w, w)
    return best


_PLAN_STATS = None


def plan_state_window(spec: SketchSpec, state: SketchState):
    """Fetch a window plan from a live state -> (lo_w, n_w, w_t, with_neg).

    ONE device round trip: the three plan scalars (global occupied min/max,
    any-negative-mass flag) fold in a single jitted reduce and come back in
    one ``device_get`` -- per-scalar fetches would pay the host-sync floor
    three times per state mutation.
    """
    global _PLAN_STATS
    if _PLAN_STATS is None:
        _PLAN_STATS = jax.jit(
            lambda lo, hi, nt: jnp.stack(
                [
                    jnp.min(lo),
                    jnp.max(hi),
                    jnp.max((nt > 0).astype(jnp.int32)),
                ]
            )
        )
    glo, ghi, neg_any = jax.device_get(
        _PLAN_STATS(state.occ_lo, state.occ_hi, state.neg_total)
    )
    lo_w, n_w, w_t = plan_window(spec, int(glo), int(ghi))
    return lo_w, n_w, w_t, bool(neg_any)


def fused_quantile_windowed(
    spec: SketchSpec,
    state: SketchState,
    qs: jax.Array,
    lo_wblock,
    *,
    n_wblocks: int,
    w_tiles: int = 1,
    with_neg: bool = True,
    block_streams: int = 0,
    interpret: bool = False,
) -> jax.Array:
    """Multi-quantile query reading only the occupied bin window.

    The window is ``n_wblocks`` column blocks of ``w_tiles`` 128-bin tiles
    starting at block index ``lo_wblock`` (traced scalar/[1] i32 -- one
    compilation serves every window position); the caller guarantees every
    occupied bin of every stream lies inside it -- exactly what the state's
    ``occ_lo/occ_hi`` invariant certifies after a global fold, and what
    :func:`plan_window` computes.  With ``with_neg=False`` the negative
    store is not even read (its emptiness is certified by
    ``state.neg_total == 0``), halving HBM traffic on positive-only
    workloads.  HBM bytes scale with the occupied span instead of
    ``n_bins`` (VERDICT r3 item 1c).

    Semantics match :func:`batched.quantile` exactly on the certified
    window (parity-tested across spans, stores, and empty streams).
    """
    n = state.n_streams
    if spec.bins_integer:
        raise NotImplementedError(
            "windowed quantile requires float bins; integer-bin specs query"
            " via batched.quantile (the facades route this automatically)"
        )
    qs = jnp.atleast_1d(jnp.asarray(qs, jnp.float32))
    q_total = qs.shape[0]
    if q_total == 0:
        return jnp.zeros((n, 0), jnp.float32)
    bn = block_streams or next(
        (b for b in (512, 256, 128) if n % b == 0), _BN
    )
    if n % bn != 0:
        # An oversized stream block would silently read past the arrays
        # (garbage, not an error, on both TPU and interpret backends).
        raise SketchValueError(
            f"n_streams={n} must be a multiple of the stream block"
            f" ({bn}); pad the batch or pass block_streams"
        )
    # Static window-plan validity (ADVICE r3): a caller-supplied plan whose
    # blocks are misaligned or overrun the bin array would make the BlockSpec
    # index map point past the arrays, which TPU Pallas silently clamps to
    # the last block (duplicated reads, wrong counts) instead of raising.
    # The dynamic part (lo_wblock) is checked at the same trace-time bound:
    # the in-repo plan producers always satisfy lo + n <= tiles, and a
    # traced lo cannot be validated without a host sync, so the static
    # guards bound the exposure to a window that at worst re-reads the last
    # in-range block.
    if w_tiles not in (1, 2, 4) or spec.n_bins % (w_tiles * LO) != 0:
        raise SpecError(
            f"w_tiles={w_tiles} must divide the {spec.n_bins}-bin array"
            " into whole column blocks (and be one of 1/2/4)"
        )
    if not 1 <= n_wblocks <= spec.n_bins // (w_tiles * LO):
        raise SpecError(
            f"n_wblocks={n_wblocks} window ({n_wblocks * w_tiles * LO} bins)"
            f" exceeds the {spec.n_bins}-bin array"
        )
    # The dynamic window start clamps into range ONCE, before both the
    # index map and the kernel's decode read it (ADVICE r3): an out-of-range
    # traced lo_wblock then reads a self-consistent in-range window (wrong
    # answer caught by parity tests) instead of Pallas's silent per-block
    # clamping leaving the decode offset pointing at blocks never read.
    max_lo = spec.n_bins // (w_tiles * LO) - n_wblocks
    lo_tile = jnp.clip(
        jnp.reshape(jnp.asarray(lo_wblock, jnp.int32), (1,)), 0, max_lo
    )

    # Pre-packed per-stream thresholds (one XLA pass over [N] vectors --
    # negligible next to the bins read): pos_rank | rev_rank + 1 | key lo.
    # key_offset rides as f32 (exact for |k| < 2**24, far beyond any real
    # window position).
    neg_count = state.neg_total.astype(jnp.float32)[:, None]
    rank = qs[None, :] * (state.count.astype(jnp.float32)[:, None] - 1.0)
    pos_rank = rank - state.zero_count.astype(jnp.float32)[:, None] - neg_count
    rev_p1 = neg_count - rank
    f32col = lambda x: x.astype(jnp.float32)[:, None]
    packed = jnp.concatenate(
        [
            pos_rank, rev_p1, f32col(state.key_offset),
            f32col(state.pos_lo), f32col(state.pos_hi),
            f32col(state.neg_lo), f32col(state.neg_hi),
        ],
        axis=1,
    )

    tile_spec = pl.BlockSpec(
        (bn, w_tiles * LO), lambda i, j, lo: (i, lo[0] + j)
    )
    in_specs = [tile_spec] + ([tile_spec] if with_neg else []) + [
        pl.BlockSpec((bn, 2 * q_total + 5), lambda i, j, lo: (i, 0)),
    ]
    operands = [state.bins_pos] + (
        [state.bins_neg] if with_neg else []
    ) + [packed]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // bn, n_wblocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bn, q_total), lambda i, j, lo: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((bn, 2), jnp.float32),        # prefix carries
            pltpu.VMEM((bn, 2 * q_total), jnp.float32),  # rank counts
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _windowed_kernel,
            spec=spec,
            w_tiles=w_tiles,
            with_neg=with_neg,
            q_total=q_total,
            bn=bn,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, q_total), jnp.float32),
        interpret=interpret,
    )(lo_tile, *operands)
    # Validity (q in [0, 1], non-empty stream) applies outside the kernel:
    # one fused elementwise pass over the [N, Q] result.
    valid = jnp.logical_and(
        jnp.logical_and(qs >= 0.0, qs <= 1.0)[None, :],
        (state.count > 0)[:, None],
    )
    return jnp.where(valid, out, jnp.nan)


# ---------------------------------------------------------------------------
# Tile-list multi-quantile query: hierarchical rank selection (VERDICT r4
# item 1).  Phase 1 (XLA, in the same jit) locates each (stream, q)'s
# crossing tile from the state's per-tile mass summaries alone; phase 2 (the
# kernel) reads ONLY the tiles some stream in the block actually needs --
# worst-case HBM bytes scale with the number of distinct crossing tiles, not
# with occupancy or n_bins.
# ---------------------------------------------------------------------------


def _stream_block(n: int) -> int:
    """Default stream-block width shared by the tile-list query paths."""
    return next((b for b in (1024, 512, 256, 128) if n % b == 0), _BN)


def _invalid_mask(state: SketchState, qs: jax.Array) -> jax.Array:
    """[N, Q] bool: ranks whose output is NaN (empty stream / q outside
    [0, 1]) -- the ONE definition shared by the tile plan, the list
    builder, and the kernel's packed nanflag."""
    return jnp.logical_not(
        jnp.logical_and(
            jnp.logical_and(qs >= 0.0, qs <= 1.0)[None, :],
            (state.count > 0)[:, None],
        )
    )


def tile_query_eligible(spec: SketchSpec, q_total: int, window_plan) -> bool:
    """Whether the tile-list engine can serve this (spec, Q, window) at all
    -- the ONE home of the eligibility predicate both facades consult
    (ADVICE r4: the gate used to be duplicated verbatim in
    ``BatchedDDSketch._query_fn`` and ``DistributedDDSketch._query_fn``).

    Bounds: Q <= 8 keeps the kernel's [Q*bn, 128] accumulator slab inside
    the VMEM budget at every stream-block width; >= 2 tiles per store is
    where a tile list can beat reading the window outright; a single-tile
    occupied window is the windowed kernel's best case (one wide DMA, no
    list machinery).  The old n_tiles <= 31 int32-bitmask cap is gone:
    needed-tile sets ride as multi-word uint32 masks (VERDICT r4 item 7),
    so any 128-aligned bin count qualifies.
    """
    if window_plan is None:
        return False
    _, n_w, w_t, _ = window_plan
    return (
        q_total <= 8
        and spec.n_tiles >= 2
        and spec.n_bins % LO == 0
        and n_w * w_t > 1
    )


#: Environment kill switch for the overlap engine: set to "0" to make both
#: facades fall back to the r5 windowed/tiles ladder without a code change
#: (the measured-dead escape hatch -- DESIGN.md 3c-r6).  Declared in
#: ``analysis/registry.py`` (the kill-switch inventory); this alias keeps
#: the historical import path working.
OVERLAP_ENV = registry.OVERLAP.name


def overlap_enabled() -> bool:
    """Whether the facades may route eligible queries to the overlap engine.

    Reads the registered ``SKETCHES_TPU_OVERLAP`` kill switch; with it
    set to ``0`` every eligible pick degrades to the tiles/windowed
    ladder (never an error -- the engines are answer-identical).
    """
    return registry.enabled(registry.OVERLAP)


def choose_query_engine(window_plan, tile_plan, overlap_ok: bool = False) -> str:
    """The facades' windowed/tiles/overlap policy, in ONE place.

    ``window_plan`` = (lo_w, n_w, w_tiles, with_neg) from
    :func:`plan_state_window`; ``tile_plan`` = (k_tiles, with_neg) from
    :func:`plan_tile_query` (or None when ineligible).  Measured basis
    (131k x 512 v5e shard; tie-break re-verified r5 DEVICE-CLOCKED after
    the decode cut -- a sustained-number reading briefly suggested tiles
    should take equal-byte ties, but the per-call device track says
    otherwise: windowed 1.41 ms vs tiles 1.67 ms at the 4-tile
    positive-only window; sustained readings of that shape swung
    0.99-1.52 ms between runs): a single-tile occupied window is the
    windowed kernel's best case (one wide DMA, no list machinery); wider
    spans go to the tile-list kernel when its per-block needed-tile bound
    strictly beats the span (bytes) or when the negative store
    participates (the windowed kernel then scans BOTH spans; the tile
    fold's per-tile compute is far cheaper).

    ``overlap_ok`` admits the manually double-buffered variant of the
    tile engine (:func:`fused_quantile_tiles_overlap` -- same bytes, same
    plan, explicit DMA/compute overlap; DESIGN.md 3c-r6).  With it set,
    every case the tile engine would take goes to the overlap engine, and
    so does the equal-byte positive-only tie the windowed kernel used to
    win: that tie-break measured the tile engine's *serialized* final
    cell, which is exactly the compute the overlap engine hides under the
    next block's reads.
    """
    if tile_plan is None:
        return "windowed"
    _, n_w, w_t, with_neg_w = window_plan
    k_tiles, with_neg_t = tile_plan
    span = n_w * w_t
    if span <= 1:
        return "windowed"
    k_eff = k_tiles * (2 if with_neg_t else 1)
    win_eff = span * (2 if with_neg_w else 1)
    if overlap_ok and (with_neg_t or k_eff <= win_eff):
        return "overlap"
    return "tiles" if (with_neg_t or k_eff < win_eff) else "windowed"


def _tile_targets(spec: SketchSpec, state: SketchState, qs: jax.Array):
    """Per-(stream, q) crossing tiles + thresholds from the summaries.

    Pure XLA on [N, T]-sized arrays -- no bin is read.  Returns
    ``(utile, thr_adj, zflag, rank)`` where ``utile`` is the
    branch-selected tile id in the unified [0, 2T) space (negative-store
    tiles offset by T), ``thr_adj`` the within-tile rank threshold
    (``carry`` already subtracted), ``zflag`` (f32 0/1) marks zero-bucket
    ranks, and ``rank`` is the raw [N, Q] rank array.
    All deliberately GATHER-FREE: ``take_along_axis`` with per-row indices
    lowers pathologically on TPU (measured 8 ms for a [131k, 4] gather), so
    every per-(stream, q) lookup is a one-hot contraction over the tiny T
    axis instead.
    """
    t = spec.n_tiles
    f32 = jnp.float32
    tiles = state.tile_sums.astype(f32)
    tp, tn = tiles[:, :t], tiles[:, t:]
    cum_tp = jnp.cumsum(tp, axis=-1)
    cum_tn = jnp.cumsum(tn, axis=-1)
    excl_tp = cum_tp - tp
    excl_tn = cum_tn - tn

    neg_count = state.neg_total.astype(f32)[:, None]  # [N, 1]
    rank = qs[None, :] * (state.count.astype(f32)[:, None] - 1.0)  # [N, Q]
    pos_rank = rank - state.zero_count.astype(f32)[:, None] - neg_count
    rev_p1 = neg_count - rank  # strict-< threshold (lower=False walk)

    # Crossing tile = #(tile cum <cmp> threshold), clipped into [0, T);
    # degenerate ranks saturate and the kernel's occupied-bounds clip
    # absorbs them (same contract as the windowed kernel).
    g_pos = jnp.clip(
        (cum_tp[:, None, :] <= pos_rank[:, :, None]).sum(-1), 0, t - 1
    ).astype(jnp.int32)  # [N, Q]
    g_neg = jnp.clip(
        (cum_tn[:, None, :] < rev_p1[:, :, None]).sum(-1), 0, t - 1
    ).astype(jnp.int32)
    oh_pos = g_pos[:, :, None] == jnp.arange(t, dtype=jnp.int32)[None, None]
    oh_neg = g_neg[:, :, None] == jnp.arange(t, dtype=jnp.int32)[None, None]
    carry_pos = jnp.where(oh_pos, excl_tp[:, None, :], 0.0).sum(-1)
    carry_neg = jnp.where(oh_neg, excl_tn[:, None, :], 0.0).sum(-1)

    in_neg = rev_p1 > 0.0  # rank < neg_count (quantile()'s branch order)
    in_zero = jnp.logical_and(jnp.logical_not(in_neg), pos_rank < 0.0)
    utile = jnp.where(in_neg, g_neg + t, g_pos)  # [N, Q] in [0, 2T)
    thr_adj = jnp.where(in_neg, rev_p1 - carry_neg, pos_rank - carry_pos)
    return utile, thr_adj, in_zero.astype(f32), rank


_WORD = 32  # tiles per needed-tile bitmask word


def _n_words(n_tiles: int) -> int:
    return -(-n_tiles // _WORD)


def _tile_bits(utile, zflag, nanflag, n_tiles):
    """Per-stream needed-tile BITMASKS -> ([N, W], [N, W]) uint32 words,
    one set per store (bit u % 32 of word u // 32 of the pos masks = some q
    targets pos tile u; likewise neg), W = ceil(T / 32).

    [N, W]-shaped word folds instead of a [N, Q, 2T] one-hot: minor-dim-
    padded [N, small, small] intermediates each cost a full 128-lane HBM
    stripe when they materialize at the pallas barrier (measured ~0.25 ms
    at 131k streams), while the word fold fuses to a few thin vectors.
    Multi-word masks lift the old single-int32 cap (n_tiles <= 31, i.e.
    n_bins <= 3968 -- VERDICT r4 item 7): 4096- and 8192-bin windows ride
    in 1-2 extra words.  Zero-bucket AND invalid (empty-stream /
    out-of-range q) ranks contribute no tile: their outputs ignore the
    accumulator, and an empty stream's saturated crossing would otherwise
    add the last tile of each store to every block it sits in (review r4).
    """
    q_total = utile.shape[1]
    t = n_tiles
    nw = _n_words(t)
    live = jnp.logical_and(zflag < 0.5, jnp.logical_not(nanflag))
    n = utile.shape[0]
    words = jnp.arange(nw, dtype=jnp.int32)[None, :]  # [1, W]
    zero_w = jnp.uint32(0)
    bits_pos = jnp.zeros((n, nw), jnp.uint32)
    bits_neg = jnp.zeros((n, nw), jnp.uint32)
    for q in range(q_total):
        u = utile[:, q].astype(jnp.int32)
        is_neg = u >= t
        idx = u - jnp.where(is_neg, jnp.int32(t), 0)
        bit = (jnp.uint32(1) << (idx % _WORD).astype(jnp.uint32))[:, None]
        hit = (idx // _WORD)[:, None] == words  # [N, W]
        lp = jnp.logical_and(live[:, q], jnp.logical_not(is_neg))[:, None]
        ln = jnp.logical_and(live[:, q], is_neg)[:, None]
        bits_pos = jnp.bitwise_or(
            bits_pos, jnp.where(jnp.logical_and(hit, lp), bit, zero_w)
        )
        bits_neg = jnp.bitwise_or(
            bits_neg, jnp.where(jnp.logical_and(hit, ln), bit, zero_w)
        )
    return bits_pos, bits_neg


def _block_tile_lists(bits_pos, bits_neg, n_tiles, bn, k_tiles):
    """Per-stream-block sorted-unique needed-tile lists -> ([nb, K], [nb, K]).

    Lists are padded at the END by repeating the last real entry --
    consecutive equal block indices elide the DMA on TPU (measured), and
    the kernel's fresh-flag keeps repeats from double-accumulating.
    Zero-branch ranks contribute no tile (their output ignores the
    accumulator).
    """
    n = bits_pos.shape[0]
    nb = n // bn
    t = n_tiles
    nw = _n_words(t)

    def compact(bits):  # [N, W] uint32 -> [nb, K] i32 sorted, end-padded
        block_bits = jax.lax.reduce(
            bits.reshape(nb, bn, nw), jnp.uint32(0),
            jax.lax.bitwise_or, (1,),
        )  # [nb, W]
        mask = (
            (
                block_bits[:, :, None]
                >> jnp.arange(_WORD, dtype=jnp.uint32)[None, None, :]
            )
            & 1
        ).reshape(nb, nw * _WORD)[:, :t] > 0  # [nb, T] -- tiny
        ids = jnp.where(mask, jnp.arange(t, dtype=jnp.int32), t)
        ids = jnp.sort(ids, axis=-1)[:, :k_tiles]
        last = jnp.max(
            jnp.where(mask, jnp.arange(t, dtype=jnp.int32), -1), axis=-1
        )
        return jnp.where(ids == t, jnp.maximum(last, 0)[:, None], ids)

    return compact(bits_pos), compact(bits_neg)


# Plan-stats jits, keyed by (spec, Q, bn).  Bounded (ADVICE r4): long-lived
# processes constructing many distinct specs/batch shapes would otherwise
# accumulate compiled plan functions forever; simple FIFO eviction -- the
# working set of real deployments is a handful of specs, and re-jitting a
# dropped key costs one retrace against XLA's own compile cache.
_TILE_PLAN_JITS = {}
_TILE_PLAN_JITS_MAX = 64


def plan_tile_query(
    spec: SketchSpec, state: SketchState, qs, bn: Optional[int] = None
) -> tuple:
    """Host-side plan for :func:`fused_quantile_tiles` -> (k_tiles, with_neg).

    ONE device round trip (like :func:`plan_state_window`): folds the
    per-block needed-tile union sizes and the any-negative-mass flag in a
    single jitted reduce.  ``k_tiles`` is the max union rounded up to a
    power of two (bounds the jit cache); the list compaction pads blocks
    with smaller unions by repetition, whose DMAs elide.  ``bn`` overrides
    the stream-block width the unions are judged at (the distributed tier
    plans against the full folded state but blocks shard-locally; shard
    boundaries are block-aligned, so the global fold IS the max over
    shard-local blocks).
    """
    qs = jnp.atleast_1d(jnp.asarray(qs, jnp.float32))
    if bn is None:
        bn = _stream_block(state.n_streams)
    key = (spec, qs.shape[0], bn)
    fn = _TILE_PLAN_JITS.get(key)
    if fn is None:

        def stats(st, qv):
            utile, _, zflag, _ = _tile_targets(spec, st, qv)
            nanflag = _invalid_mask(st, qv)
            bits_pos, bits_neg = _tile_bits(
                utile, zflag, nanflag, spec.n_tiles
            )
            nb = st.n_streams // bn
            nw = _n_words(spec.n_tiles)

            def max_union(bits):
                block_bits = jax.lax.reduce(
                    bits.reshape(nb, bn, nw), jnp.uint32(0),
                    jax.lax.bitwise_or, (1,),
                )  # [nb, W]
                return jax.lax.population_count(block_bits).sum(-1).max()

            return jnp.stack(
                [
                    max_union(bits_pos).astype(jnp.int32),
                    max_union(bits_neg).astype(jnp.int32),
                    (st.neg_total > 0).any().astype(jnp.int32),
                ]
            )

        while len(_TILE_PLAN_JITS) >= _TILE_PLAN_JITS_MAX:
            _TILE_PLAN_JITS.pop(next(iter(_TILE_PLAN_JITS)))
        fn = _TILE_PLAN_JITS[key] = jax.jit(stats)
    k_pos, k_neg, neg_any = (int(x) for x in jax.device_get(fn(state, qs)))
    with_neg = bool(neg_any)
    k = max(k_pos, k_neg if with_neg else 0, 1)
    k_tiles = 1 << (k - 1).bit_length()  # next pow2: bounded jit cache
    return min(k_tiles, spec.n_tiles), with_neg


def _tiles_kernel(
    *refs,
    spec: SketchSpec,
    q_total: int,
    bn: int,
    with_neg: bool,
):
    """One (stream-block, list-slot) cell of the tile-list query.

    Per cell: fold the fetched 128-bin tile into each q's accumulator slab
    where that (stream, q) targets this tile -- two VPU ops per q, no
    matmuls.  The accumulator stacks the Q per-quantile rows on SUBLANES
    (``[Q*bn, 128]``), so the final cell runs ONE 3-term exact cumsum for
    every quantile at once, then per-q mask-matvec count COLUMNS and the
    in-kernel [bn, Q]-batched decode (``_count_and_decode``).
    """
    if with_neg:
        (lp_ref, ln_ref, packed_ref, bp_ref, bn_ref, out_ref, acc) = refs
    else:
        (lp_ref, packed_ref, bp_ref, out_ref, acc) = refs
    i = pl.program_id(0)
    j = pl.program_id(1)
    t = spec.n_tiles

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    pk = packed_ref[:]  # [bn, 2Q(+pad)]: thr_adj | utile
    utile = pk[:, q_total : 2 * q_total]  # f32 unified tile ids, [bn, Q]

    def fold(list_ref, blk, id_offset):
        pid = list_ref[i, j]
        # First-occurrence gate: list pads repeat their predecessor (the
        # repeat's DMA elides), and a repeated tile must not re-fold.
        fresh = jnp.logical_or(
            j == 0, pid != list_ref[i, jnp.maximum(j - 1, 0)]
        )
        pid_f = (pid + id_offset).astype(jnp.float32)
        # ONE thin compare+cast for all Q (narrow [bn, Q] vectors occupy
        # the same vreg count as [bn, 1]); measured runtime-neutral at the
        # worst-case shard shape -- the wide per-q mask-mult-adds below
        # fully dominate the fold -- but it keeps the per-cell IR minimal.
        mf = jnp.where(fresh, (utile == pid_f).astype(jnp.float32), 0.0)
        for q in range(q_total):
            # Mask-multiply-accumulate, deliberately: each slab row
            # receives at most one tile, so a select-copy
            # (``where(m, blk, acc)``) is semantically equal -- but it
            # measures 0.45 ms SLOWER device-clocked at the worst-case
            # shard shape (2.75 vs 2.30 ms): the VPU fuses the
            # mask-mult-add, while the select forces a read-modify-write.
            acc[q * bn : (q + 1) * bn, :] += mf[:, q : q + 1] * blk

    fold(lp_ref, bp_ref[:], 0)
    if with_neg:
        fold(ln_ref, bn_ref[:], t)

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        out_ref[:] = _count_and_decode(
            acc[:], pk, spec=spec, q_total=q_total, bn=bn, with_neg=with_neg
        )


def _count_and_decode(slab, pk, *, spec, q_total, bn, with_neg):
    """The tile-list kernel's accumulator-slab finalization: ONE 3-term
    scan for every quantile at once, per-q mask-matvec count columns,
    then the in-kernel [bn, Q]-batched decode -> final values.  (Factored
    out of ``_tiles_kernel`` during the r5 span-fold experiment -- that
    kernel measured a wash and was removed, DESIGN.md 3c-r5 -- and kept
    separate: the finalization is the single largest compute block and
    reads as a unit.)

    Branch-specific compare per q: pos walks lower=True (<=), neg
    lower=False (strict <) -- identical to batched.quantile.  The
    compares are cheap full-lane VPU ops; their [bn, 128] results
    sublane-concat back into one slab (lane offsets agree -- Mosaic
    rejects sublane concat of lane-offset [bn, 1] slices) so the rank
    count is ONE mask-matvec for every quantile.  Selects run in bf16,
    not i1 (no Mosaic select on boolean vectors).  The decode emits
    FINAL values (zero branch, sign, NaN validity included) so no
    [N, Q]-shaped XLA work exists after the pallas barrier: alternatives
    measured and rejected at 131k streams -- decode in XLA at [N, Q]
    (chain left unfused with transposed-layout copies: +3 ms),
    flatten-to-1-D (physical relayout of the lane-padded stripe: +3 ms),
    per-q in-kernel decode (Q chains of [bn, 1]-shaped ops: +2.7 ms).
    """
    t = spec.n_tiles
    local = _cumsum_tile(slab)  # [Q*bn, 128]: ONE scan for all q
    parts = []
    for q in range(q_total):
        lq = jax.lax.slice_in_dim(local, q * bn, (q + 1) * bn, axis=0)
        tq = pk[:, q : q + 1]
        isn = pk[:, q_total + q : q_total + q + 1] >= jnp.float32(t)
        parts.append(
            jnp.where(
                isn,
                (lq < tq).astype(jnp.bfloat16),
                (lq <= tq).astype(jnp.bfloat16),
            )
        )
    # Per-q mask-matvecs emitting [bn, 1] count COLUMNS, lane-concatenated
    # to [bn, Q], with the tile math done once at [bn, Q] width -- instead
    # of one matvec over a sublane-concatenated [Q*bn, 128] mask plus a
    # per-q chain of [bn, 1] slices (ut/isn/tile/cnt-slice/idx, 5 narrow
    # ops x Q, each costing 128 vregs regardless of width).  Measured
    # r5 on the worst-case shard: 1.82 vs 1.97 ms device-clocked p50=p99
    # -- the extra Q-1 matmul invocations are far cheaper than the
    # narrow-op chains and the big sublane concat they replace.
    ones8 = jnp.ones((LO, 8), jnp.bfloat16)
    cnt_cols = [
        jax.lax.dot_general(
            m, ones8, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )[:, :1]
        for m in parts
    ]
    cnt = jnp.concatenate(cnt_cols, axis=1)  # [bn, Q]
    ut_all = pk[:, q_total : 2 * q_total]
    is_neg = ut_all >= jnp.float32(t)
    tile_all = ut_all - jnp.where(is_neg, jnp.float32(t), jnp.float32(0.0))
    idx = tile_all * 128.0 + cnt  # [bn, Q] f32-exact
    zflag = pk[:, 2 * q_total : 3 * q_total]
    nanflag = pk[:, 3 * q_total : 4 * q_total]
    base = 4 * q_total
    koff = pk[:, base : base + 1]
    first_pos = pk[:, base + 1 : base + 2]
    last_pos = jnp.maximum(pk[:, base + 2 : base + 3], first_pos)
    if with_neg:
        # ONE decode chain for both stores (r5: the [bn, Q]-shaped
        # lane-padded value_array chain measured 0.85 ms of the worst
        # case's 2.30 -- the largest single compute term; the pos and neg
        # decodes differ only in clip bounds and sign, so branch-select
        # the bounds BEFORE the chain and the sign after, halving it).
        first_neg = pk[:, base + 3 : base + 4]
        last_neg = jnp.maximum(pk[:, base + 4 : base + 5], first_neg)
        first = jnp.where(is_neg, first_neg, first_pos)
        last = jnp.where(is_neg, last_neg, last_pos)
        sign = jnp.where(is_neg, jnp.float32(-1.0), jnp.float32(1.0))
        dec = sign * spec.mapping.value_array(
            jnp.clip(idx, first, last) + koff
        )
        # zflag and is_neg are mutually exclusive (the zero branch is
        # "not negative and rank below zero_count"), so one select
        # recovers the three-way branch.
        val = jnp.where(zflag > 0.5, 0.0, dec)
    else:
        # neg_total == 0 everywhere: any negative-branch rank belongs to
        # an empty stream, NaN'd below -- the with_neg=False contract.
        val_pos = spec.mapping.value_array(
            jnp.clip(idx, first_pos, last_pos) + koff
        )
        val = jnp.where(zflag > 0.5, 0.0, val_pos)
    return jnp.where(nanflag > 0.5, jnp.float32(jnp.nan), val)


def fused_quantile_tiles(
    spec: SketchSpec,
    state: SketchState,
    qs: jax.Array,
    *,
    k_tiles: int,
    with_neg: bool = True,
    block_streams: int = 0,
    interpret: bool = False,
) -> jax.Array:
    """Hierarchical multi-quantile query -> [n_streams, Q].

    Semantics match :func:`batched.quantile` up to the tile-summary
    contract: in float mode the summaries can differ from the bins by ULPs
    (per-call accumulation order), which can move a crossing by at most one
    bucket at exact rank boundaries -- inside the sketch's alpha contract
    and exactly the engines' documented shared divergence.  Unit-weight /
    integer-mass batches are exact.

    ``k_tiles`` must be >= every stream block's needed-tile union per store
    (:func:`plan_tile_query` computes it); ``with_neg=False`` (certified by
    ``neg_total == 0``) drops the negative operand entirely.
    """
    n = state.n_streams
    t = spec.n_tiles
    if spec.bins_integer:
        raise NotImplementedError(
            "fused_quantile_tiles requires float bins; integer-bin specs"
            " query via quantile_windowed_xla (exact integer compare)"
        )
    if spec.n_bins % LO != 0:
        raise SpecError("tile-list query requires 128-aligned n_bins")
    qs = jnp.atleast_1d(jnp.asarray(qs, jnp.float32))
    q_total = qs.shape[0]
    if q_total == 0:
        return jnp.zeros((n, 0), jnp.float32)
    bn = block_streams or _stream_block(n)
    if n % bn != 0:
        raise SketchValueError(
            f"n_streams={n} must be a multiple of the stream block ({bn})"
        )
    if not 1 <= k_tiles <= t:
        raise SpecError(f"k_tiles={k_tiles} outside [1, {t}]")

    lists_pos, lists_neg, packed = _tile_query_operands(
        spec, state, qs, bn, k_tiles
    )
    wp = packed.shape[1]

    n_prefetch = 2 if with_neg else 1
    pk_spec = pl.BlockSpec((bn, wp), lambda i, j, *_: (i, 0))
    tile_spec = lambda which: pl.BlockSpec(
        (bn, LO), lambda i, j, *lists: (i, lists[which][i, j])
    )
    in_specs = [pk_spec, tile_spec(0)] + (
        [tile_spec(1)] if with_neg else []
    )
    operands = [packed, state.bins_pos] + (
        [state.bins_neg] if with_neg else []
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,
        grid=(n // bn, k_tiles),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bn, q_total), lambda i, j, *_: (i, 0)),
        scratch_shapes=[pltpu.VMEM((q_total * bn, 128), jnp.float32)],
    )
    prefetch = [lists_pos] + ([lists_neg] if with_neg else [])
    return pl.pallas_call(
        functools.partial(
            _tiles_kernel,
            spec=spec,
            q_total=q_total,
            bn=bn,
            with_neg=with_neg,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, q_total), jnp.float32),
        interpret=interpret,
    )(*prefetch, *operands)


# ---------------------------------------------------------------------------
# Overlap query engine: the tile-list walk with MANUAL double buffering
# (VERDICT r5 next #1 / DESIGN.md 3c-r6).  Same plan, same bytes, same
# finalization as fused_quantile_tiles; the difference is who schedules the
# DMAs.  The automatic Mosaic pipeline at the (block, list-slot) cell shape
# overlaps nothing (the r5 P1->P3 additivity proof), so this engine walks
# ONE grid cell per stream block, keeps the bins operands in ANY memory,
# and issues explicit async copies into a `depth`-deep VMEM ring: while
# tile j folds, tiles j+1..j+depth-1 stream -- including ACROSS the block
# boundary, so the final cell's count/decode (the largest serialized
# compute term, ~0.37 ms of the r5 worst case) runs under the next
# block's reads instead of after its own.
# ---------------------------------------------------------------------------


def _tile_query_operands(spec, state, qs, bn, k_tiles):
    """The tile-family kernels' shared XLA-side inputs ->
    ``(lists_pos, lists_neg, packed)``.

    Everything the final cell's decode needs rides in the packed block:
    the kernels emit FINAL values (incl. NaN validity), because any
    [N, Q]-shaped XLA work after the pallas barrier is left unfused with
    layout-copy chains (measured 3 ms of 3.8 ms total at 131k streams).
    """
    t = spec.n_tiles
    utile, thr_adj, zflag, _ = _tile_targets(spec, state, qs)
    nanflag = _invalid_mask(state, qs)
    bits_pos, bits_neg = _tile_bits(utile, zflag, nanflag, t)
    lists_pos, lists_neg = _block_tile_lists(
        bits_pos, bits_neg, t, bn, k_tiles
    )
    f32col = lambda x: x.astype(jnp.float32)[:, None]
    packed = jnp.concatenate(
        [
            thr_adj,
            utile.astype(jnp.float32),
            zflag,
            nanflag.astype(jnp.float32),
            f32col(state.key_offset),
            f32col(state.pos_lo), f32col(state.pos_hi),
            f32col(state.neg_lo), f32col(state.neg_hi),
        ],
        axis=1,
    )  # [N, 4Q + 5]
    w = packed.shape[1]
    wp = ((w + 7) // 8) * 8
    if wp != w:
        packed = jnp.pad(packed, ((0, 0), (0, wp - w)))
    return lists_pos, lists_neg, packed


def _overlap_depth(n_steps: int, requested: int) -> int:
    """Ring depth: largest divisor of ``n_steps`` not above ``requested``.

    The divisibility requirement keeps every global step's ring slot
    static (``slot = step % depth`` with ``depth | steps-per-block`` means
    the slot depends only on the in-block step index, never the traced
    block id) -- dynamic slot arithmetic would force traced indexing into
    the VMEM ring.
    """
    for d in (8, 4, 2, 1):
        if d <= requested and d <= n_steps and n_steps % d == 0:
            return d
    return 1


def _overlap_kernel(
    *refs,
    spec: SketchSpec,
    q_total: int,
    bn: int,
    with_neg: bool,
    k_tiles: int,
    depth: int,
    strip: Optional[str],
):
    """One stream block of the overlap query (grid is 1-D over blocks).

    Per block: ``n_steps`` = k_tiles (pos) or 2*k_tiles (pos then neg)
    list slots, each one explicit async copy of a [bn, 128] tile slab from
    the ANY-space bins into ring slot ``j % depth``.  Step j waits its
    slot, folds (the tile kernel's mask-mult-add, fresh-gated against
    list pads), then refills the slot with the DMA for step ``j + depth``
    -- whose block index may be ``i + 1``: the lists are scalar-prefetch
    SMEM arrays, indexable at any block, so the lookahead runs past the
    block boundary and the finalization below executes with up to
    ``depth - 1`` of the NEXT block's reads in flight.  The finalization
    itself is byte-identical work to the tile kernel's
    (:func:`_count_and_decode`).

    ``strip`` serves bench.py's P1-style decomposition ONLY (DESIGN.md
    3c-r5 protocol): 'dma' keeps the copies + one plain add per fetched
    tile (the reads cannot be elided); 'fold' keeps the full fold but
    replaces the finalization with a slab slice.  Parity holds only for
    ``strip=None``.
    """
    if with_neg:
        (lp_ref, ln_ref, packed_ref, bp_hbm, bn_hbm, out_ref,
         acc, ring, sem) = refs
    else:
        (lp_ref, packed_ref, bp_hbm, out_ref, acc, ring, sem) = refs
    i = pl.program_id(0)
    nb = pl.num_programs(0)
    t = spec.n_tiles
    n_steps = (2 if with_neg else 1) * k_tiles

    def list_and_store(j):
        # Static per step: which list/operand serves it, and the in-list
        # slot.  Pos steps first, then (with_neg) the neg steps.
        if with_neg and j >= k_tiles:
            return ln_ref, bn_hbm, j - k_tiles
        return lp_ref, bp_hbm, j

    def make_dma(j, ib, slot):
        lref, hbm, jj = list_and_store(j)
        pid = lref[ib, jj]
        return pltpu.make_async_copy(
            hbm.at[pl.ds(ib * bn, bn), pl.ds(pid * LO, LO)],
            ring.at[slot],
            sem.at[slot],
        )

    acc[:] = jnp.zeros_like(acc)

    @pl.when(i == 0)
    def _():  # warm-up: the first block has no predecessor to prefetch it
        for g in range(depth):
            make_dma(g, jnp.int32(0), g).start()

    pk = packed_ref[:]  # [bn, 4Q + 5 (+pad)]
    utile = pk[:, q_total : 2 * q_total]

    for j in range(n_steps):
        slot = j % depth  # static: depth | n_steps (see _overlap_depth)
        make_dma(j, i, slot).wait()
        blk = ring[slot]
        lref, _, jj = list_and_store(j)
        if strip == "dma":
            # P1: reads + one plain add/store; no per-q fold, no decode.
            acc[:bn, :] += blk
        else:
            pid_f = (lref[i, jj] + (t if with_neg and j >= k_tiles else 0)
                     ).astype(jnp.float32)
            mf = (utile == pid_f).astype(jnp.float32)
            if jj > 0:
                # Fresh-occurrence gate (list pads repeat their
                # predecessor and must not re-fold); the repeat's DMA is
                # re-issued here, unlike the auto-pipeline's elision --
                # its bytes are the price of manual scheduling, zero in
                # the window-filling worst case (full unions, no pads).
                fresh = lref[i, jj] != lref[i, jj - 1]
                mf = jnp.where(fresh, mf, 0.0)
            for q in range(q_total):
                acc[q * bn : (q + 1) * bn, :] += mf[:, q : q + 1] * blk
        g = j + depth
        ib = i + g // n_steps
        jn = g % n_steps

        @pl.when(ib < nb)
        def _(ib=ib, jn=jn, slot=slot):
            make_dma(jn, ib, slot).start()

    if strip is None:
        out_ref[:] = _count_and_decode(
            acc[:], pk, spec=spec, q_total=q_total, bn=bn, with_neg=with_neg
        )
    else:
        # Stripped finalization: one slab slice so the folds stay live.
        out_ref[:] = acc[:bn, :q_total]


def fused_quantile_tiles_overlap(
    spec: SketchSpec,
    state: SketchState,
    qs: jax.Array,
    *,
    k_tiles: int,
    with_neg: bool = True,
    block_streams: int = 0,
    lookahead: int = 8,
    interpret: bool = False,
    _strip: Optional[str] = None,
) -> jax.Array:
    """Tile-list multi-quantile query with manual DMA/compute overlap.

    Semantics and plan contract are identical to
    :func:`fused_quantile_tiles` (same ``plan_tile_query`` output, same
    tile-summary exactness tiers, same NaN semantics) -- the two engines
    share the XLA-side operand prep and the in-kernel finalization, and
    differ only in DMA scheduling.  ``lookahead`` bounds the VMEM ring
    depth (actual depth = its largest divisor of the step count); the
    ring costs ``depth * bn * 512`` bytes of VMEM next to the
    ``[Q*bn, 128]`` accumulator slab.  ``_strip`` is bench-only (see
    :func:`_overlap_kernel`).
    """
    n = state.n_streams
    t = spec.n_tiles
    if spec.bins_integer:
        raise NotImplementedError(
            "fused_quantile_tiles_overlap requires float bins; integer-bin"
            " specs query via quantile_windowed_xla (exact integer compare)"
        )
    if spec.n_bins % LO != 0:
        raise SpecError("tile-list query requires 128-aligned n_bins")
    qs = jnp.atleast_1d(jnp.asarray(qs, jnp.float32))
    q_total = qs.shape[0]
    if q_total == 0:
        return jnp.zeros((n, 0), jnp.float32)
    bn = block_streams or _stream_block(n)
    if n % bn != 0:
        raise SketchValueError(
            f"n_streams={n} must be a multiple of the stream block ({bn})"
        )
    if not 1 <= k_tiles <= t:
        raise SpecError(f"k_tiles={k_tiles} outside [1, {t}]")
    if lookahead < 1:
        raise SpecError(f"lookahead={lookahead} must be >= 1")
    n_steps = (2 if with_neg else 1) * k_tiles
    depth = _overlap_depth(n_steps, lookahead)

    lists_pos, lists_neg, packed = _tile_query_operands(
        spec, state, qs, bn, k_tiles
    )
    wp = packed.shape[1]

    n_prefetch = 2 if with_neg else 1
    pk_spec = pl.BlockSpec((bn, wp), lambda i, *_: (i, 0))
    any_spec = pl.BlockSpec(memory_space=pltpu.ANY)
    in_specs = [pk_spec, any_spec] + ([any_spec] if with_neg else [])
    operands = [packed, state.bins_pos] + (
        [state.bins_neg] if with_neg else []
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,
        grid=(n // bn,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bn, q_total), lambda i, *_: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((q_total * bn, 128), jnp.float32),  # rank slab
            pltpu.VMEM((depth, bn, LO), jnp.float32),      # DMA ring
            pltpu.SemaphoreType.DMA((depth,)),
        ],
    )
    prefetch = [lists_pos] + ([lists_neg] if with_neg else [])
    return pl.pallas_call(
        functools.partial(
            _overlap_kernel,
            spec=spec,
            q_total=q_total,
            bn=bn,
            with_neg=with_neg,
            k_tiles=k_tiles,
            depth=depth,
            strip=_strip,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, q_total), jnp.float32),
        interpret=interpret,
    )(*prefetch, *operands)


def quantile_windowed_xla(
    spec: SketchSpec,
    state: SketchState,
    qs: jax.Array,
    lo_tile,
    *,
    n_tiles_window: int,
    with_neg: bool = True,
) -> jax.Array:
    """Portable occupied-window multi-quantile query (any engine, any dtype).

    The XLA twin of the windowed kernel: slice both stores to the certified
    occupied window (``n_tiles_window`` 128-bin tiles starting at traced
    tile ``lo_tile``), run the cumsum + mask-count rank walk on the slice,
    and offset the decode by the window start.  Bins outside the window
    hold zero mass by the occupied-bounds invariant, so the slice's cumsum
    IS the full cumsum restricted to the window.  Integer-bin specs compare
    in integer space (exact past 2**24) -- this is the fast path that closes
    the r3 integer-query gap (VERDICT r4 item 5): HBM traffic scales with
    the occupied span, and an empty negative store is never read.
    """
    n = state.n_streams
    qs = jnp.atleast_1d(jnp.asarray(qs, spec.dtype))
    q_total = qs.shape[0]
    if q_total == 0:
        return jnp.zeros((n, 0), spec.dtype)
    if spec.n_bins % LO != 0:
        raise SpecError("windowed XLA query requires 128-aligned n_bins")
    tiles_total = spec.n_bins // LO
    if not 1 <= n_tiles_window <= tiles_total:
        raise SpecError(
            f"n_tiles_window={n_tiles_window} outside [1, {tiles_total}]"
        )
    width = n_tiles_window * LO
    lo_bin = (
        jnp.clip(
            jnp.asarray(lo_tile, jnp.int32), 0, tiles_total - n_tiles_window
        )
        * LO
    )

    win = lambda b: jax.lax.dynamic_slice_in_dim(b, lo_bin, width, axis=1)
    bins_pos = win(state.bins_pos)
    neg_count = state.neg_total
    count = state.count
    rank = qs[None, :] * (count[:, None].astype(spec.dtype) - 1)

    int_mode = spec.bins_integer
    _int_safe = float(2**31 - 256)

    def walk(bins, thr, strict):
        cum = jnp.cumsum(bins, axis=-1)
        if int_mode:
            it = jnp.clip(
                jnp.ceil(thr) - 1 if strict else jnp.floor(thr),
                -_int_safe, _int_safe,
            ).astype(cum.dtype)
            masks = [
                cum <= it[:, qi : qi + 1] for qi in range(q_total)
            ]
        elif strict:
            masks = [cum < thr[:, qi : qi + 1] for qi in range(q_total)]
        else:
            masks = [cum <= thr[:, qi : qi + 1] for qi in range(q_total)]
        return jnp.stack(
            [m.sum(-1).astype(jnp.int32) for m in masks], axis=1
        )  # [N, Q] index within window

    pos_rank = rank - (state.zero_count + neg_count).astype(spec.dtype)[:, None]
    idx_pos = lo_bin + walk(bins_pos, pos_rank, strict=False)
    idx_pos = jnp.clip(
        idx_pos,
        state.pos_lo[:, None],
        jnp.maximum(state.pos_hi, state.pos_lo)[:, None],
    )
    key_lo = state.key_offset[:, None].astype(jnp.int32)
    val_pos = spec.mapping.value_array(idx_pos + key_lo, dtype=spec.dtype)

    in_neg = rank < neg_count.astype(spec.dtype)[:, None]
    in_zero = rank < (neg_count + state.zero_count).astype(spec.dtype)[:, None]
    if with_neg:
        rev_p1 = neg_count.astype(spec.dtype)[:, None] - rank
        idx_neg = lo_bin + walk(win(state.bins_neg), rev_p1, strict=True)
        idx_neg = jnp.clip(
            idx_neg,
            state.neg_lo[:, None],
            jnp.maximum(state.neg_hi, state.neg_lo)[:, None],
        )
        val_neg = -spec.mapping.value_array(idx_neg + key_lo, dtype=spec.dtype)
        out = jnp.where(in_neg, val_neg, jnp.where(in_zero, 0.0, val_pos))
    else:
        out = jnp.where(in_zero, 0.0, val_pos)
    valid = jnp.logical_and(
        jnp.logical_and(qs >= 0, qs <= 1)[None, :], (count > 0)[:, None]
    )
    return jnp.where(valid, out, jnp.nan)


def add(
    spec: SketchSpec,
    state: SketchState,
    values: jax.Array,
    weights: Optional[jax.Array] = None,
    *,
    interpret: bool = False,
    variant: Optional[str] = None,
) -> SketchState:
    """Drop-in replacement for ``batched.add`` using the fused Pallas pass.

    Unit-weight calls (``weights=None``) take the single-term bf16 one-hot
    path; explicit weights use the exact three-term bf16 split (see module
    docstring), so arbitrary f32 weights accumulate without quantization.
    ``variant=None`` resolves the construction rung through
    :func:`choose_ingest_engine` (kill-switch-aware); an explicit rung is
    honored after validation.
    """
    v = values.astype(spec.dtype)
    if spec.bins_integer:
        # Integer-bin exactness holds only when this call's f32 deltas are
        # themselves exact integers below 2**24.  Unit-weight calls satisfy
        # that by construction (per-bin/per-counter mass <= the static batch
        # width); weighted calls can concentrate arbitrary mass into one
        # bin in one call, which would round in f32 *before* the integer
        # cast -- route those through batched.add, whose weights cast to
        # the integer dtype before the scatter (the facades do this
        # automatically).
        if weights is not None:
            raise NotImplementedError(
                "Pallas add with integer bins supports unit-weight calls"
                " only; weighted integer-mode ingest uses batched.add"
            )
        if values.shape[-1] >= 1 << 24:
            raise NotImplementedError(
                "Pallas add with integer bins needs per-call batch width"
                " < 2**24 to keep f32 deltas exact"
            )
    if weights is None:
        w = jnp.ones_like(v)
    else:
        w = jnp.broadcast_to(jnp.asarray(weights, spec.dtype), v.shape)

    hist_pos, hist_neg, cols = ingest_histogram(
        spec, v, w, state.key_offset,
        weighted=weights is not None, interpret=interpret,
        variant=choose_ingest_engine(spec, weights is not None, variant),
    )
    col = lambda name: cols[:, _COL[name]]
    zero, count, total = col("zero"), col("count"), col("sum")
    vmin, vmax = col("min"), col("max")
    clow, chigh = col("clow"), col("chigh")
    plo = col("pos_lo").astype(jnp.int32)
    phi = col("pos_hi").astype(jnp.int32)
    nlo = col("neg_lo").astype(jnp.int32)
    nhi = col("neg_hi").astype(jnp.int32)
    negc = col("neg_total")
    # The kernel emits f32 per-call deltas; accumulation into the state
    # happens here in the state's own bin dtype.  For integer-bin specs the
    # guards above make every delta an exact integer below 2**24, so the
    # cast is lossless and the int32 state stays exact past f32's ceiling.
    bd = state.bins_pos.dtype
    return SketchState(
        bins_pos=state.bins_pos + hist_pos.astype(bd),
        bins_neg=state.bins_neg + hist_neg.astype(bd),
        zero_count=state.zero_count + zero.astype(bd),
        count=state.count + count.astype(bd),
        sum=state.sum + total,
        min=jnp.minimum(state.min, vmin),
        max=jnp.maximum(state.max, vmax),
        collapsed_low=state.collapsed_low + clow.astype(bd),
        collapsed_high=state.collapsed_high + chigh.astype(bd),
        key_offset=state.key_offset,
        pos_lo=jnp.minimum(state.pos_lo, plo),
        pos_hi=jnp.maximum(state.pos_hi, phi),
        neg_lo=jnp.minimum(state.neg_lo, nlo),
        neg_hi=jnp.maximum(state.neg_hi, nhi),
        neg_total=state.neg_total + negc.astype(bd),
        tile_sums=state.tile_sums
        + cols[:, _TILE0 : _TILE0 + 2 * spec.n_tiles].astype(bd),
    )
