"""Pallas TPU kernels: fused ingest and fused multi-quantile.

These are the performance play of SURVEY.md section 7 stage 6 -- same
``[n_streams, n_bins]`` state as ``sketches_tpu.batched``, different engine:

**Ingest** (``ingest_histogram``).  XLA's scatter-add serializes colliding
updates and streams bins through HBM every step (~0.1 G values/s measured on
v5e).  The kernel instead builds the histogram as MXU matmuls entirely in
VMEM: split each clamped key into ``hi = key // 128`` and ``lo = key % 128``,
form per-chunk one-hot operands ``A[n, hi, s] = onehot(hi) * w`` and
``L[n, s, lo] = onehot(lo)``, and accumulate ``A @ L -> [n, hi, lo]`` -- which
*is* the ``[n, n_bins]`` histogram -- into the output block that stays
resident in VMEM across the whole value stream.  One HBM read of the values,
one HBM write of the histogram; the one-hots never exist in HBM.  (The
matmul does n_bins x the minimal FLOPs, but the MXU is exactly the unit with
that headroom -- this is the classic TPU histogram trick.)

**Query** (``fused_quantile``).  The kernel fuses cumsum + rank selection
in VMEM: triangular-matmul prefix scans (streams as the M dimension,
pos+neg rows folded into one call), ``index = sum_b(cum[b] <= rank)`` as
one bf16 matvec per mask, then the three-way negative/zero/positive select
and the gamma**k decode, for all requested quantiles in one pass;
first/last-occupied clip bounds are plain iota min/max lane reductions.
Measured ~58 ms sustained for 1M x 512 on v5e -- ~2.2x the vectorized XLA
path (127 ms; the original vmapped-searchsorted formulation was 1.73 s)
and within ~2x of the chip's measured full-state HBM read time (the hard
floor for any exact query).

All three mappings run in-kernel (the interpolated ones extract
exponent/mantissa by int32 bitcast -- ``mapping._frexp_array`` -- which
lowers in Mosaic where ``jnp.frexp`` does not).  Weighted ingest splits each
f32 weight into three bf16 terms (successive rounding residuals: 3 x 8
mantissa bits cover f32's 24) and accumulates one bf16 matmul per term --
full f32 weight precision at the unit path's VMEM footprint.  Shapes must be
128-aligned; ``supports(spec, ...)`` reports eligibility and the facade
falls back to the XLA path otherwise.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from sketches_tpu.batched import SketchSpec, SketchState
from sketches_tpu.mapping import zero_threshold

__all__ = ["supports", "select_engine", "ingest_histogram", "fused_quantile", "add"]

LO = 128  # lane width: low radix of the key split
_BN = 128  # streams per block
_BS = 128  # values per chunk


def _wide_block(dim: int, n_bins: int, base: int) -> int:
    """Double a block dimension when divisibility and VMEM allow.

    Wider blocks amortize grid-iteration overhead (measured ~10 ms off the
    1M x 512 query and +7% on its ingest, single-dispatch); the narrow-bins
    gate keeps the scan/histogram working sets inside the 16 MB VMEM
    budget.  Shared by ingest and query so the policy cannot diverge.
    """
    return 2 * base if dim % (2 * base) == 0 and n_bins <= 1024 else base


def supports(spec: SketchSpec, n_streams: int, batch: Optional[int] = None) -> bool:
    """Whether the Pallas engine can run this configuration."""
    return (
        spec.n_bins % LO == 0
        and spec.n_bins >= LO
        and jnp.dtype(spec.dtype) == jnp.float32
        and n_streams % _BN == 0
        and (batch is None or batch % _BS == 0)
    )


def select_engine(spec: SketchSpec, n_streams: int, engine: str):
    """Shared engine-selection policy -> (use_pallas, interpret).

    'auto' picks the kernels on TPU when the configuration qualifies;
    'pallas' forces them (interpreter mode off-TPU, for tests) and raises
    on unsupported configurations; 'xla' always takes the portable path.
    Both ``BatchedDDSketch`` and ``DistributedDDSketch`` route through
    this so the two tiers can never diverge on the policy.
    """
    if engine not in ("auto", "xla", "pallas"):
        raise ValueError(f"Unknown engine {engine!r}")
    supported = supports(spec, n_streams)
    if engine == "pallas" and not supported:
        raise ValueError(
            "engine='pallas' requires f32 state, 128-aligned n_bins, and a"
            " 128-aligned stream count (per-shard, when sharded over a"
            f" mesh); got {spec} with n_streams={n_streams}"
        )
    use_pallas = engine == "pallas" or (
        engine == "auto" and jax.default_backend() == "tpu" and supported
    )
    return use_pallas, jax.default_backend() != "tpu"


def _ingest_kernel(
    values_ref,
    weights_ref,
    key_offset_ref,
    hist_pos_ref,
    hist_neg_ref,
    zero_ref,
    count_ref,
    sum_ref,
    min_ref,
    max_ref,
    clow_ref,
    chigh_ref,
    olo_ref,
    ohi_ref,
    negc_ref,
    *,
    spec: SketchSpec,
    weighted: bool,
):
    """One (stream-block, value-chunk) grid cell of the fused ingest.

    Emits the scalar bookkeeping (zero/count/sum/min/max/collapse) as
    per-stream column outputs alongside the histograms, so the values make
    exactly one trip from HBM.
    """
    j = pl.program_id(1)
    n_bins = spec.n_bins
    hi_size = n_bins // LO

    v = values_ref[:]  # [BN, BS] f32
    w = weights_ref[:]

    # Branch-free three-way split + key computation, sharing the mapping's
    # own array path so bucket boundaries are bit-identical to the XLA
    # engine's _keys_and_masks -- including its explicit subnormals-are-zero
    # predicate (backend-independent, not hardware flush-to-zero).
    tiny = jnp.float32(zero_threshold(jnp.float32))
    is_pos = v >= tiny
    is_neg = v <= -tiny
    is_zero = jnp.logical_not(jnp.logical_or(is_pos, is_neg))
    absv = jnp.where(is_zero, 1.0, jnp.abs(v))
    keys = spec.mapping.key_array(absv)
    # Per-stream window low edge ([BN, 1] i32 column from the state),
    # broadcast against the value lanes -- the adaptive-window seam.
    key_lo = key_offset_ref[:]
    key_hi = key_lo + jnp.int32(n_bins - 1)
    clamped_low = keys < key_lo
    clamped_high = keys > key_hi
    idx = jnp.clip(keys, key_lo, key_hi) - key_lo

    live = w > 0.0
    w_pos = jnp.where(jnp.logical_and(is_pos, live), w, 0.0)
    w_neg = jnp.where(jnp.logical_and(is_neg, live), w, 0.0)
    w_zero = jnp.where(jnp.logical_and(is_zero, live), w, 0.0)
    w_live = w_pos + w_neg + w_zero
    signed = w_pos + w_neg
    finite_live = jnp.logical_and(live, jnp.logical_not(jnp.isnan(v)))

    # Pos and neg stores build as ONE histogram over 2*hi_size chunk rows
    # (neg keys offset by hi_size): per-stream batched matmuls dominate the
    # kernel, so folding the two stores into one matmul halves them.
    hi = idx // LO + jnp.where(is_neg, hi_size, 0)  # [BN, BS] in [0, 2*HI)
    lo = idx % LO

    bn, bs = v.shape
    dims = (((2,), (1,)), ((0,), (0,)))  # contract s; batch n

    @pl.when(j == 0)
    def _():
        hist_pos_ref[:] = jnp.zeros_like(hist_pos_ref)
        hist_neg_ref[:] = jnp.zeros_like(hist_neg_ref)
        zero_ref[:] = jnp.zeros_like(zero_ref)
        count_ref[:] = jnp.zeros_like(count_ref)
        sum_ref[:] = jnp.zeros_like(sum_ref)
        min_ref[:] = jnp.full_like(min_ref, jnp.inf)
        max_ref[:] = jnp.full_like(max_ref, -jnp.inf)
        clow_ref[:] = jnp.zeros_like(clow_ref)
        chigh_ref[:] = jnp.zeros_like(chigh_ref)
        olo_ref[:] = jnp.full_like(olo_ref, n_bins)
        ohi_ref[:] = jnp.full_like(ohi_ref, -1)
        negc_ref[:] = jnp.zeros_like(negc_ref)

    # A[n, h, s] = (hi[n, s] == h) * w[n, s] in bf16.  Unit weights (w = 1)
    # are exact in one bf16 term.  Arbitrary f32 weights are split into
    # three bf16 terms (w = p0 + p1 + p2, successive rounding residuals:
    # 3 x 8 mantissa bits >= f32's 24, so the split is exact) and the
    # histogram accumulates one bf16 matmul per term -- full f32 weight
    # precision at bf16 VMEM footprint, cheaper than a HIGHEST f32 matmul.
    # Blocks wider than _BS process in _BS-value sub-chunks: one-hot
    # operands are built (and die) per sub-chunk, so peak VMEM stays at the
    # narrow-block level while the grid-iteration count still shrinks.
    n_terms = 3 if weighted else 1
    hi_iota = jax.lax.broadcasted_iota(jnp.int32, (bn, 2 * hi_size, _BS), 1)
    lo_iota = jax.lax.broadcasted_iota(jnp.int32, (bn, _BS, LO), 2)
    c = jnp.zeros((bn, 2 * hi_size, LO), jnp.float32)
    for t in range(bs // _BS):
        # lax.slice_in_dim, not mixed None+slice getitem: the latter takes
        # jnp's gather path, which has no general Mosaic lowering.
        hi_t = jax.lax.slice_in_dim(hi, t * _BS, (t + 1) * _BS, axis=1)
        lo_t = jax.lax.slice_in_dim(lo, t * _BS, (t + 1) * _BS, axis=1)
        w_t = jax.lax.slice_in_dim(signed, t * _BS, (t + 1) * _BS, axis=1)
        onehot_hi = (hi_t[:, None, :] == hi_iota).astype(jnp.bfloat16)
        onehot_lo = (lo_t[:, :, None] == lo_iota).astype(jnp.bfloat16)
        for part in _exact_bf16_terms(w_t, n_terms):
            # bf16 multiply by a 0/1 one-hot is exact.
            a = onehot_hi * part[:, None, :]  # [BN, 2HI, _BS] bf16
            c = c + jax.lax.dot_general(
                a, onehot_lo, dims, preferred_element_type=jnp.float32
            )  # [BN, 2HI, LO]
    c = c.reshape(bn, 2 * n_bins)
    hist_pos_ref[:] += c[:, :n_bins]
    hist_neg_ref[:] += c[:, n_bins:]

    zero_ref[:] += jnp.sum(w_zero, axis=1, keepdims=True)
    count_ref[:] += jnp.sum(w_live, axis=1, keepdims=True)
    sum_ref[:] += jnp.sum(jnp.where(live, v, 0.0) * w_live, axis=1, keepdims=True)
    min_ref[:] = jnp.minimum(
        min_ref[:],
        jnp.min(jnp.where(finite_live, v, jnp.inf), axis=1, keepdims=True),
    )
    max_ref[:] = jnp.maximum(
        max_ref[:],
        jnp.max(jnp.where(finite_live, v, -jnp.inf), axis=1, keepdims=True),
    )
    clow_ref[:] += jnp.sum(
        jnp.where(clamped_low, signed, 0.0), axis=1, keepdims=True
    )
    chigh_ref[:] += jnp.sum(
        jnp.where(clamped_high, signed, 0.0), axis=1, keepdims=True
    )
    # Occupied-bounds deltas (VERDICT r3 query-byte-cut seam): min/max of
    # this chunk's store-hitting indices, same contract as batched.add.
    hits = jnp.logical_and(live, jnp.logical_or(is_pos, is_neg))
    olo_ref[:] = jnp.minimum(
        olo_ref[:],
        jnp.min(jnp.where(hits, idx, n_bins), axis=1, keepdims=True),
    )
    ohi_ref[:] = jnp.maximum(
        ohi_ref[:],
        jnp.max(jnp.where(hits, idx, -1), axis=1, keepdims=True),
    )
    negc_ref[:] += jnp.sum(w_neg, axis=1, keepdims=True)


def ingest_histogram(
    spec: SketchSpec,
    values: jax.Array,
    weights: jax.Array,
    key_offset: jax.Array,
    *,
    weighted: bool = True,
    interpret: bool = False,
) -> Tuple[jax.Array, ...]:
    """One fused pass over a value batch -> histograms + scalar bookkeeping.

    ``values``/``weights``: [n_streams, batch] f32; ``key_offset``:
    [n_streams] i32 per-stream window edges (``state.key_offset``).  Returns
    ``(hist_pos, hist_neg, zero, count, sum, min, max, clow, chigh,
    occ_lo, occ_hi, neg_total)`` -- the two [n_streams, n_bins] histograms
    of this batch plus the per-stream [n_streams, 1] counter deltas
    (occupied bounds as i32 columns), all from a single HBM read of the
    values.
    """
    n, s = values.shape
    # The kernel builds its one-hots in _BS-wide sub-chunks, so peak VMEM
    # stays flat when the value block widens.
    bs = _wide_block(s, spec.n_bins, _BS)
    grid = (n // _BN, s // bs)
    hist_shape = jax.ShapeDtypeStruct((n, spec.n_bins), jnp.float32)
    col_shape = jax.ShapeDtypeStruct((n, 1), jnp.float32)
    icol_shape = jax.ShapeDtypeStruct((n, 1), jnp.int32)
    hist_spec = pl.BlockSpec(
        (_BN, spec.n_bins), lambda i, j: (i, 0), memory_space=pltpu.VMEM
    )
    col_spec = pl.BlockSpec((_BN, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_ingest_kernel, spec=spec, weighted=weighted),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BN, bs), lambda i, j: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BN, bs), lambda i, j: (i, j), memory_space=pltpu.VMEM),
            col_spec,
        ],
        out_specs=[hist_spec, hist_spec] + [col_spec] * 10,
        out_shape=[hist_shape, hist_shape] + [col_shape] * 7
        + [icol_shape, icol_shape, col_shape],
        interpret=interpret,
    )(values, weights, key_offset[:, None].astype(jnp.int32))


_BF16_MAX = 3.3895314e38  # plain float: jnp constants would be captured consts in pallas


def _exact_bf16_terms(x: jax.Array, n_terms: int) -> list:
    """Split f32 ``x`` into ``n_terms`` bf16 values summing exactly to x.

    Successive round-to-nearest residuals: each term captures the next 8
    mantissa bits, so 3 terms cover f32's 24.  Each term is clamped into
    bf16's finite range: finite f32 values above bf16 max (~3.3895e38, a
    sliver below f32 max -- reachable as weighted bin masses) would round
    to inf and poison everything downstream; clamped, they split across
    terms with ~2e-10 relative error instead.
    """
    terms = []
    rem = x
    for _ in range(n_terms):
        p = jnp.clip(rem, -_BF16_MAX, _BF16_MAX).astype(jnp.bfloat16)
        rem = rem - p.astype(jnp.float32)
        terms.append(p)
    return terms


def _cumsum_bins(x: jax.Array, n_terms: int = 3) -> jax.Array:
    """Inclusive prefix sum along the bin axis, as full-tile MXU matmuls.

    ``jnp.cumsum`` has no Mosaic lowering; a triangular-ones matmul does the
    same job and feeds the MXU: block-local cumsum over 128-lane tiles, then
    an exclusive cumsum of tile totals added back as offsets.

    Two layout/precision choices matter (~10x together at 1M streams):

    * The local scan contracts as ``[HI, BN, LO] @ [LO, LO]`` -- *streams*
      are the M dimension, batched over the HI tiles.  The transposed form
      ``[BN, HI, LO] @ [LO, LO]`` is BN small matmuls of M = HI rows (3% of
      an MXU tile at 512 bins); this form is HI full 128x128 tiles.
    * Exactness comes from a manual 3-term bf16 split of the counts (24
      mantissa bits, matching f32) against the exactly-representable 0/1
      triangle, with f32 accumulation -- half the passes of
      ``Precision.HIGHEST`` and exact for counts < 2**24, the state dtype's
      own exactness ceiling.
    """
    bn, n_bins = x.shape
    hi_size = n_bins // LO
    x3t = x.reshape(bn, hi_size, LO).swapaxes(0, 1)  # [HI, BN, LO]
    tri = (
        jax.lax.broadcasted_iota(jnp.int32, (LO, LO), 0)
        <= jax.lax.broadcasted_iota(jnp.int32, (LO, LO), 1)
    ).astype(jnp.bfloat16)
    dims = (((2,), (0,)), ((), ()))  # contract LO; HI stays batched via loop
    local = jnp.zeros((hi_size, bn, LO), jnp.float32)
    for p in _exact_bf16_terms(x3t, n_terms):
        local = local + jax.lax.dot_general(
            p, tri, dims, preferred_element_type=jnp.float32
        )  # [HI, BN, LO] block-local inclusive cumsum
    totals = local[:, :, LO - 1].swapaxes(0, 1)  # [BN, HI]
    tri_excl = (
        jax.lax.broadcasted_iota(jnp.int32, (hi_size, hi_size), 0)
        < jax.lax.broadcasted_iota(jnp.int32, (hi_size, hi_size), 1)
    ).astype(jnp.float32)
    offsets = jnp.zeros((bn, hi_size), jnp.float32)
    for p in _exact_bf16_terms(totals, n_terms):
        offsets = offsets + jax.lax.dot_general(
            p.astype(jnp.float32), tri_excl, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BN, HI] exclusive cumsum of block totals (M = BN: full tiles)
    return (
        (local.swapaxes(0, 1) + offsets[:, :, None]).reshape(bn, n_bins)
    )


def _first_last_occupied(x: jax.Array):
    """Index of the first and last occupied bin per row -> ([R,1], [R,1]) i32.

    Plain VPU lane reductions over an occupancy-selected iota: measured ~5x
    cheaper than a suffix-count matmul scan (+2 ms vs +10 ms over the HBM
    floor at 1M x 512), and exact by construction.  Empty rows give
    (n_bins, -1) -- the same degenerate clip bounds the mask formulation
    produced, discarded downstream by the three-way select.
    """
    r, n_bins = x.shape
    occ = x > 0.0
    iota = jax.lax.broadcasted_iota(jnp.int32, (r, n_bins), 1)
    last = jnp.max(jnp.where(occ, iota, -1), axis=1, keepdims=True)
    first = jnp.min(jnp.where(occ, iota, n_bins), axis=1, keepdims=True)
    return first, last


def _select_quantiles(spec, bins_pos, bins_neg, zero_count, count, key_lo, qs):
    """The rank-selection math shared by the standalone query kernel and the
    fused ingest+query kernel -> values [BN, Q].

    Rank walks are *mask-matmuls*: each index is "count of bins whose
    cumulative mass is below a threshold", contracted against ones on the
    MXU (one 2D matvec per mask -- see the comment below) instead of the
    VPU's slow many-lane-axis reductions.  First/last-occupied clip bounds
    come from plain iota min/max lane reductions (cheap at 2 reductions).
    """
    bn, n_bins = bins_pos.shape
    q_total = qs.shape[1]

    # Pos and neg stores process as one [2*BN, B] call when VMEM allows:
    # rows are independent, so concatenating them halves the Mosaic matmul
    # invocations.  At wide bins the doubled scan working set blows the
    # 16 MB VMEM budget -- fall back to per-store scans there.
    if bn * n_bins <= 128 * 1024:
        both = jnp.concatenate([bins_pos, bins_neg], axis=0)
        cum_both = _cumsum_bins(both)
        first_both, last_both = _first_last_occupied(both)
        cum_pos, cum_neg = cum_both[:bn], cum_both[bn:]
        first_pos, first_neg = first_both[:bn], first_both[bn:]
        last_pos, last_neg = last_both[:bn], last_both[bn:]
    else:
        cum_pos = _cumsum_bins(bins_pos)
        cum_neg = _cumsum_bins(bins_neg)
        first_pos, last_pos = _first_last_occupied(bins_pos)
        first_neg, last_neg = _first_last_occupied(bins_neg)
    neg_count = cum_neg[:, n_bins - 1 :]  # [BN, 1]
    rank = qs * (count - 1.0)  # [BN, Q]

    # Rank masks, each [BN, B] bf16 (0/1 exact):
    #   0..Q-1: idx_neg per q;  Q..2Q-1: idx_pos per q
    rev = neg_count - 1.0 - rank  # [BN, Q]
    pos_rank = rank - zero_count - neg_count
    masks = []
    for qi in range(q_total):
        masks.append(cum_neg < rev[:, qi][:, None] + 1.0)
    for qi in range(q_total):
        masks.append(cum_pos <= pos_rank[:, qi][:, None])
    # One [BN, B] @ [B, 8] matvec per mask.  Measured on v5e: grouping the
    # masks into a stacked [BN, 8, B] @ [B, 8] 3D dot_general is ~6x slower
    # (Mosaic lowers the 3D contraction pathologically), while per-mask 2D
    # matvecs cost ~0.5 ms total at 1M streams.
    ones = jnp.ones((n_bins, 8), jnp.bfloat16)  # 8 lanes: MXU-friendly matvec
    parts = [
        jax.lax.dot_general(
            m.astype(jnp.bfloat16), ones, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )[:, :1]
        for m in masks
    ]
    counts = jnp.concatenate(parts, axis=1).astype(jnp.int32)  # [BN, 2Q]

    idx_neg = jnp.clip(counts[:, :q_total], first_neg, last_neg)
    idx_pos = jnp.clip(counts[:, q_total:], first_pos, last_pos)

    # Decode all Q indices at once through the mapping's own array path
    # (bit-identical bucket representatives to the XLA engine); key_lo is
    # the per-stream [BN, 1] i32 window edge, broadcast over the Q axis.
    val_neg = -spec.mapping.value_array(idx_neg + key_lo)  # [BN, Q]
    val_pos = spec.mapping.value_array(idx_pos + key_lo)

    val = jnp.where(
        rank < neg_count,
        val_neg,
        jnp.where(rank < neg_count + zero_count, 0.0, val_pos),
    )
    valid = jnp.logical_and(
        jnp.logical_and(qs >= 0.0, qs <= 1.0), count > 0.0
    )
    return jnp.where(valid, val, jnp.nan)  # [BN, Q]


def _quantile_kernel(
    bins_pos_ref,
    bins_neg_ref,
    zero_count_ref,
    count_ref,
    key_offset_ref,
    qs_ref,
    out_ref,
    *,
    spec: SketchSpec,
):
    """One stream-block of the fused multi-quantile query."""
    out_ref[:] = _select_quantiles(
        spec,
        bins_pos_ref[:],
        bins_neg_ref[:],
        zero_count_ref[:],
        count_ref[:],
        key_offset_ref[:],
        qs_ref[:],
    )


def fused_quantile(
    spec: SketchSpec,
    state: SketchState,
    qs: jax.Array,
    *,
    interpret: bool = False,
) -> jax.Array:
    """All requested quantiles for every stream -> [n_streams, Q].

    Semantics identical to ``batched.quantile`` (NaN for empty streams or
    q outside [0, 1]); one VMEM pass over the bins instead of a cumsum +
    vmapped binary search through HBM.
    """
    n = state.n_streams
    if spec.bins_integer:
        # The VMEM scan's bf16-term splits are exact only for f32-ceiling
        # masses; integer-bin (exact > 2**24) queries take the XLA path,
        # whose integer cumsum + integer rank compare never rounds.
        raise NotImplementedError(
            "fused_quantile requires float bins; integer-bin specs query"
            " via batched.quantile (the facades route this automatically)"
        )
    qs = jnp.atleast_1d(jnp.asarray(qs, jnp.float32))
    q_total = qs.shape[0]
    if q_total == 0:  # empty quantile list: nothing to launch
        return jnp.zeros((n, 0), jnp.float32)
    bn = _wide_block(n, spec.n_bins, _BN)
    bins_spec = pl.BlockSpec(
        (bn, spec.n_bins), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    col_spec = pl.BlockSpec((bn, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_quantile_kernel, spec=spec),
        grid=(n // bn,),
        in_specs=[
            bins_spec,
            bins_spec,
            col_spec,
            col_spec,
            col_spec,
            pl.BlockSpec((1, q_total), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (bn, q_total), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((n, q_total), jnp.float32),
        interpret=interpret,
    )(
        state.bins_pos,
        state.bins_neg,
        state.zero_count[:, None],
        state.count[:, None],
        state.key_offset[:, None].astype(jnp.int32),
        qs[None, :],
    )


def add(
    spec: SketchSpec,
    state: SketchState,
    values: jax.Array,
    weights: Optional[jax.Array] = None,
    *,
    interpret: bool = False,
) -> SketchState:
    """Drop-in replacement for ``batched.add`` using the fused Pallas pass.

    Unit-weight calls (``weights=None``) take the single-term bf16 one-hot
    path; explicit weights use the exact three-term bf16 split (see module
    docstring), so arbitrary f32 weights accumulate without quantization.
    """
    v = values.astype(spec.dtype)
    if spec.bins_integer:
        # Integer-bin exactness holds only when this call's f32 deltas are
        # themselves exact integers below 2**24.  Unit-weight calls satisfy
        # that by construction (per-bin/per-counter mass <= the static batch
        # width); weighted calls can concentrate arbitrary mass into one
        # bin in one call, which would round in f32 *before* the integer
        # cast -- route those through batched.add, whose weights cast to
        # the integer dtype before the scatter (the facades do this
        # automatically).
        if weights is not None:
            raise NotImplementedError(
                "Pallas add with integer bins supports unit-weight calls"
                " only; weighted integer-mode ingest uses batched.add"
            )
        if values.shape[-1] >= 1 << 24:
            raise NotImplementedError(
                "Pallas add with integer bins needs per-call batch width"
                " < 2**24 to keep f32 deltas exact"
            )
    if weights is None:
        w = jnp.ones_like(v)
    else:
        w = jnp.broadcast_to(jnp.asarray(weights, spec.dtype), v.shape)

    (
        hist_pos, hist_neg, zero, count, total, vmin, vmax, clow, chigh,
        olo, ohi, negc,
    ) = ingest_histogram(
        spec, v, w, state.key_offset,
        weighted=weights is not None, interpret=interpret,
    )
    # The kernel emits f32 per-call deltas; accumulation into the state
    # happens here in the state's own bin dtype.  For integer-bin specs the
    # guards above make every delta an exact integer below 2**24, so the
    # cast is lossless and the int32 state stays exact past f32's ceiling.
    bd = state.bins_pos.dtype
    return SketchState(
        bins_pos=state.bins_pos + hist_pos.astype(bd),
        bins_neg=state.bins_neg + hist_neg.astype(bd),
        zero_count=state.zero_count + zero[:, 0].astype(bd),
        count=state.count + count[:, 0].astype(bd),
        sum=state.sum + total[:, 0],
        min=jnp.minimum(state.min, vmin[:, 0]),
        max=jnp.maximum(state.max, vmax[:, 0]),
        collapsed_low=state.collapsed_low + clow[:, 0].astype(bd),
        collapsed_high=state.collapsed_high + chigh[:, 0].astype(bd),
        key_offset=state.key_offset,
        occ_lo=jnp.minimum(state.occ_lo, olo[:, 0]),
        occ_hi=jnp.maximum(state.occ_hi, ohi[:, 0]),
        neg_total=state.neg_total + negc[:, 0].astype(bd),
    )
