"""Time-windowed quantiles: "p99 over the last 5 minutes" as a query.

Every real dashboard query against a quantile fleet is time-scoped, and
DDSketch's full mergeability (PAPER.md) makes windowing nearly free: a
window query is just a merge over the bucket sketches that cover it.
This module is that composition, built entirely from seams earlier
rounds landed:

* **Ring of time-slice buckets.**  A :class:`WindowedSketch` routes
  ingest to the *current* bucket of a ring of ``B`` time slices (one
  backend sketch per slice, any ``SketchSpec`` backend -- dense,
  ``uniform_collapse``, ``moment``, or a mesh-sharded distributed
  fleet).  The clock is injectable (defaults to ``telemetry.clock``),
  so every rotation/query replays exactly under a virtual clock -- no
  code here sleeps or reads wall time.
* **Window queries are ONE fused stacked-merge dispatch.**  A query for
  ``quantile(q, window=W)`` selects the buckets whose time slices
  intersect ``[now - W, now)`` and folds them with the backend's own
  merge algebra inside one jitted dispatch (the serve tier's same-spec
  stacking shape): dense buckets fold through
  ``batched.merge_aligned``, adaptive buckets through
  ``backends.uniform.merge`` (levels align first), moment buckets
  through the elementwise ``backends.moment.merge``.  The answer is
  bit-identical to a host-side sequential merge of the covered buckets
  -- the oracle the tests pin.
* **Eviction is rotation, with an exact mass ledger.**  When a bucket
  ages out of its ring it *retires* into the next rung of a
  hierarchical coarsening ladder (e.g. 5s -> 1m -> 1h slices): its
  mass merges into the coarser bucket covering its interval, optionally
  collapsing first (``uniform_collapse`` backend:
  ``collapse_to(rung level)``, so ``effective_alpha`` per rung is the
  DECLARED accuracy contract -- old data gracefully loses precision
  instead of space).  Mass falling off the last rung is dropped and
  recorded.  The per-bucket mass ledger is **exact**: every ingested
  unit of weight is in exactly one live bucket or in ``retired_mass``,
  and the chaos campaign asserts the ledger with ``==``, never
  approximately.
* **Atomic rotation.**  A rotation plans functionally (new ring dicts,
  new folded states) and commits by reference swap; the
  ``window.rotate_torn`` fault site fires between plan and commit, so
  a torn rotation leaves the ring, the ledger, and the live bucket
  bit-identical (chaos-proven).

Failure modes: constructing a :class:`WindowedSketch` (or querying one)
with ``SKETCHES_TPU_WINDOWED=0`` raises ``SpecError`` -- the kill
switch refuses loudly; invalid ladder configurations (non-divisible
slice widths, non-positive lengths, collapse levels on a non-adaptive
backend) raise ``SpecError`` at construction; a window with no covered
mass answers NaN exactly like an empty sketch; merging mismatched
configs raises ``UnequalSketchParametersError``; a torn rotation
(injected) raises ``InjectedFault`` with nothing mutated.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from sketches_tpu import batched, faults, integrity, telemetry
from sketches_tpu.analysis import registry
from sketches_tpu.batched import SketchSpec
from sketches_tpu.resilience import (
    SketchValueError,
    SpecError,
    UnequalSketchParametersError,
)

__all__ = [
    "WindowConfig",
    "WindowedSketch",
    "VirtualClock",
    "DEFAULT_LADDER",
]


class VirtualClock:
    """A deterministic, manually-advanced clock for tests and drills.

    ``clock()`` semantics (monotone seconds) without any wall-time read:
    call the instance to read ``t``, :meth:`advance` to move it.  Never
    raises; time never goes backwards (negative deltas raise
    ``SketchValueError`` -- a backwards window clock would silently
    re-open retired buckets).
    """

    __slots__ = ("t",)

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        """Move the clock forward by ``dt`` seconds -> the new time."""
        if dt < 0:
            raise SketchValueError("VirtualClock cannot run backwards")
        self.t += float(dt)
        return self.t


@dataclasses.dataclass(frozen=True)
class WindowConfig:
    """The ring/ladder layout: per-rung slice widths and ring lengths.

    ``slices_s[r]`` is rung ``r``'s time-slice width in seconds (rung 0
    is the fine rung ingest lands in); ``lengths[r]`` is how many
    slices rung ``r`` retains before a bucket retires into rung
    ``r + 1`` (or, off the last rung, is dropped with its mass recorded
    in ``retired_mass``).  ``collapse_levels[r]`` (``uniform_collapse``
    backend only) is the collapse level a bucket is brought to when it
    *enters* rung ``r`` -- the rung's declared ``effective_alpha``
    contract.

    Failure modes: non-positive widths/lengths, a coarser slice that is
    not an integer multiple of the finer one (buckets must nest), a
    ``collapse_levels`` tuple of the wrong length or decreasing order
    all raise ``SpecError`` at construction.
    """

    slices_s: Tuple[float, ...] = (5.0, 60.0, 3600.0)
    lengths: Tuple[int, ...] = (12, 60, 24)
    collapse_levels: Optional[Tuple[int, ...]] = None

    def __post_init__(self):
        slices = tuple(float(s) for s in self.slices_s)
        lengths = tuple(int(n) for n in self.lengths)
        object.__setattr__(self, "slices_s", slices)
        object.__setattr__(self, "lengths", lengths)
        if not slices or len(slices) != len(lengths):
            raise SpecError(
                "WindowConfig needs one (slice width, ring length) pair"
                f" per rung; got {len(slices)} widths, {len(lengths)}"
                " lengths"
            )
        if any(s <= 0 for s in slices) or any(n <= 0 for n in lengths):
            raise SpecError("slice widths and ring lengths must be positive")
        for fine, coarse in zip(slices, slices[1:]):
            ratio = coarse / fine
            if coarse <= fine or abs(ratio - round(ratio)) > 1e-9:
                raise SpecError(
                    "ladder slices must be strictly coarsening integer"
                    f" multiples; got {fine}s -> {coarse}s"
                )
        if self.collapse_levels is not None:
            levels = tuple(int(v) for v in self.collapse_levels)
            object.__setattr__(self, "collapse_levels", levels)
            if len(levels) != len(slices):
                raise SpecError(
                    "collapse_levels needs one level per rung; got"
                    f" {len(levels)} for {len(slices)} rungs"
                )
            if any(v < 0 for v in levels) or list(levels) != sorted(levels):
                raise SpecError(
                    "collapse_levels must be non-negative and"
                    " non-decreasing (coarser rungs never regain"
                    " resolution)"
                )

    @property
    def n_rungs(self) -> int:
        return len(self.slices_s)

    def horizon_s(self) -> float:
        """Total retained history in seconds (sum of every rung's span);
        never raises."""
        return float(
            sum(s * n for s, n in zip(self.slices_s, self.lengths))
        )


#: The dashboard-shaped default ladder: 12 x 5 s (the live minute),
#: 60 x 1 m (the hour), 24 x 1 h (the day).
DEFAULT_LADDER = WindowConfig()


@dataclasses.dataclass
class _Bucket:
    """One frozen time-slice bucket: its ring position, its backend
    state pytree, and its exact mass ledger entry.  ``fp`` memoizes the
    content fingerprint (frozen states are immutable, so once computed
    it never changes)."""

    rung: int
    id: int
    state: Any
    mass: float
    fp: Optional[np.ndarray] = None


@dataclasses.dataclass(frozen=True)
class WindowPlan:
    """One resolved window query: the covered buckets' states (frozen
    at plan time, so a rotation between plan and dispatch cannot skew
    the answer), their combined content fingerprint, and the cache-key
    digest derived from the covered-bucket fingerprint *set*.  Obtained
    from :meth:`WindowedSketch.window_plan`; an empty plan (no covered
    mass) answers NaN.

    Validity: a plan must be consumed before the ring's next WRITE --
    ingest donates the live bucket's device buffers (the engines'
    in-place update discipline), so a plan held across an ``add``
    may reference deleted buffers and the dispatch then fails loudly
    (``RuntimeError``), never answers silently wrong.  The serving
    tier plans and dispatches under one lock, so this cannot happen
    there."""

    window_s: Optional[float]
    now: float
    keys: Tuple[Tuple[int, int], ...]  # (rung, bucket id), coverage order
    states: Tuple[Any, ...]
    fingerprint: np.ndarray
    digest: bytes

    @property
    def n_covered(self) -> int:
        return len(self.states)


#: Process-wide fused-fold cache: one ``{mode: jitted callable}`` per
#: spec (jit retraces per covered-bucket arity under the same callable,
#: so every ring sharing a spec shares every compilation).  Dense specs
#: carry two modes -- ``"aligned"`` (all covered windows share one
#: per-stream offset: elementwise merge, no recenter scatters) and
#: ``"general"`` (drifted windows: ``merge_aligned`` chain) -- chosen
#: HOST-SIDE from the plan's offsets; the oracle applies the identical
#: choice, so bit-identity is by symmetry, not by luck.
_FOLD_CACHE: Dict[SketchSpec, Dict[str, Callable]] = {}


def _plan_aligned(spec: SketchSpec, states) -> bool:
    """Whether every covered dense state shares one per-stream window
    offset (the common case: buckets that never recentered apart).
    Non-dense backends answer False (their folds self-align)."""
    if spec.backend != "dense" or len(states) < 2:
        return spec.backend == "dense"
    first = np.asarray(jax.device_get(states[0].key_offset))
    for st in states[1:]:
        if not np.array_equal(
            first, np.asarray(jax.device_get(st.key_offset))
        ):
            return False
    return True


def _fold_for(spec: SketchSpec) -> Dict[str, Callable]:
    fns = _FOLD_CACHE.get(spec)
    if fns is not None:
        return fns
    if spec.backend == "uniform_collapse":
        from sketches_tpu.backends import uniform

        def fold(states, qs):
            acc = states[0]
            for st in states[1:]:
                acc = uniform.merge(spec, acc, st)
            return uniform.quantile(spec, acc, qs)

        fns = {"general": jax.jit(fold)}
    elif spec.backend == "moment":
        from sketches_tpu.backends import moment

        def merge_chain(states):
            acc = states[0]
            for st in states[1:]:
                acc = moment.merge(spec, acc, st)
            return acc

        merged = jax.jit(merge_chain)

        def host_solve(states, qs):  # host maxent after one fused merge
            return moment.quantile(spec, merged(states), qs)

        fns = {"general": host_solve}
    else:

        def fold_general(states, qs):
            acc = states[0]
            for st in states[1:]:
                acc = batched.merge_aligned(spec, acc, st)
            return batched.quantile(spec, acc, qs)

        def fold_aligned(states, qs):
            acc = states[0]
            for st in states[1:]:
                acc = batched.merge(spec, acc, st)
            return batched.quantile(spec, acc, qs)

        fns = {
            "general": jax.jit(fold_general),
            "aligned": jax.jit(fold_aligned),
        }
    _FOLD_CACHE[spec] = fns
    return fns


def _fold_mode(spec: SketchSpec, states) -> str:
    fns = _fold_for(spec)
    if "aligned" in fns and _plan_aligned(spec, states):
        return "aligned"
    return "general"


def _batch_mass(spec: SketchSpec, values, weights) -> float:
    """Exact host-side mass of one ingest batch, matching the device
    tier's ``count`` delta: the sum of positive weights (``w <= 0``
    lanes are padding; NaN values still count -- they land in the
    zero path).  Integer bin mode truncates fractional weights exactly
    as the device cast does.  Never raises on well-formed arrays."""
    v = np.asarray(values)
    if weights is None:
        return float(v.size)
    w = np.broadcast_to(
        np.asarray(weights, np.float64), v.shape
    )
    live = w > 0
    if spec.bins_integer:
        return float(np.trunc(w[live]).sum())
    return float(w[live].sum())


class WindowedSketch:
    """Per-tenant ring of time-slice bucket sketches with a coarsening
    ladder (module docstring for the full design).

    ``spec``/``**kwargs`` select the backend exactly like
    :func:`sketches_tpu.backends.facade_for`; passing ``mesh``/
    ``value_axis``/``stream_axis`` backs the live bucket with a
    mesh-sharded ``DistributedDDSketch`` (dense backend only -- frozen
    buckets are topology-free merged states, so they survive
    :meth:`reshard` untouched).

    Failure modes: ``SKETCHES_TPU_WINDOWED=0`` raises ``SpecError`` at
    construction (loud refusal, one env read); ``collapse_levels`` on a
    non-``uniform_collapse`` backend raises ``SpecError``;
    :meth:`merge` across unequal specs/configs raises
    ``UnequalSketchParametersError``; :meth:`reshard` of a
    non-distributed ring raises ``SpecError``; empty windows answer
    NaN; an injected torn rotation raises ``InjectedFault`` with the
    ring left bit-identical.
    """

    def __init__(
        self,
        n_streams: int,
        *,
        spec: Optional[SketchSpec] = None,
        config: Optional[WindowConfig] = None,
        clock: Optional[Callable[[], float]] = None,
        mesh=None,
        value_axis=None,
        stream_axis=None,
        engine: str = "auto",
        **kwargs,
    ):
        if not registry.enabled(registry.WINDOWED):
            raise SpecError(
                "time-windowed sketches are disabled"
                " (SKETCHES_TPU_WINDOWED=0): refusing to construct a"
                " WindowedSketch rather than silently serving"
                " unwindowed answers"
            )
        self.config = config or DEFAULT_LADDER
        if spec is None:
            backend = kwargs.pop("backend", "dense")
            spec = SketchSpec(backend=backend, **kwargs)
            kwargs = {}
        self.spec = spec
        self._n_streams = int(n_streams)
        self._clock = clock if clock is not None else telemetry.clock
        if self.config.collapse_levels is not None:
            if spec.backend != "uniform_collapse":
                raise SpecError(
                    "collapse_levels need backend='uniform_collapse';"
                    f" got {spec.backend!r}"
                )
            if max(self.config.collapse_levels) > spec.max_collapses:
                raise SpecError(
                    "collapse_levels exceed spec.max_collapses"
                    f" ({max(self.config.collapse_levels)} >"
                    f" {spec.max_collapses})"
                )
        self._distributed = (
            mesh is not None or value_axis is not None
            or stream_axis is not None
        )
        self._engine = engine
        self._mesh = mesh
        self._dist_axes = (value_axis, stream_axis)
        self._live = self._make_live()
        self._live_id: Optional[int] = None
        self._live_mass = 0.0
        self._rungs: List[Dict[int, _Bucket]] = [
            {} for _ in range(self.config.n_rungs)
        ]
        self._total = 0.0
        self._retired = 0.0
        self._rotations = 0
        self._ladder_collapses = 0
        self._cur: Optional[int] = None
        self._version = 0  # bumped on every content change (live fp cache)
        self._live_fp: Optional[Tuple[int, np.ndarray]] = None

    # -- construction helpers ---------------------------------------------

    def _make_live(self):
        if self._distributed:
            from sketches_tpu.parallel import DistributedDDSketch

            value_axis, stream_axis = self._dist_axes
            if self._mesh is None and value_axis is None \
                    and stream_axis is None:
                value_axis = "values"
            return DistributedDDSketch(
                self._n_streams, mesh=self._mesh, value_axis=value_axis,
                stream_axis=stream_axis, spec=self.spec,
                engine=self._engine,
            )
        from sketches_tpu.backends import facade_for

        return facade_for(
            self._n_streams, spec=self.spec, engine=self._engine
        )

    def _reset_live(self) -> None:
        """Empty the live bucket's facade (cheap state swap for host
        facades; a mesh-backed live bucket rebuilds on its current
        mesh -- rotation cadence is seconds, so the rebuild is cold-path
        by construction)."""
        if self._distributed:
            self._live = self._make_live()
            return
        self._live.state = self._empty_state()
        if hasattr(self._live, "_auto_recenter_pending"):
            # A fresh bucket re-centers its window on its first batch,
            # exactly like a fresh facade would.
            self._live._auto_recenter_pending = True

    def _set_live_state(self, state) -> None:
        """Assign merged content to the live bucket (merge path)."""
        if self._distributed:
            from sketches_tpu.parallel import DistributedDDSketch

            value_axis, stream_axis = self._dist_axes
            self._live = DistributedDDSketch.from_merged_state(
                state, self.spec, mesh=self._mesh,
                value_axis=value_axis or "values",
                stream_axis=stream_axis, engine=self._engine,
            )
            return
        self._live.state = state

    def _snapshot_state(self, state):
        """Freeze a bucket state for the ring.  Mesh-backed rings
        normalize to host-committed (unsharded) arrays: frozen buckets
        are topology-free by contract (they must survive reshard), and
        the fused fold must stay bit-identical to the host-side oracle
        -- sharded operands can compile to different (1-ULP) decode
        fusions.  Host facades pass through untouched (their states are
        already single-device)."""
        if not self._distributed:
            return state
        return jax.tree.map(
            lambda a: jnp.asarray(np.asarray(jax.device_get(a))), state
        )

    def _empty_state(self):
        if self.spec.backend == "uniform_collapse":
            from sketches_tpu.backends.uniform import AdaptiveState

            return AdaptiveState(
                base=batched.init(self.spec, self._n_streams),
                level=jnp.zeros((self._n_streams,), jnp.int32),
            )
        if self.spec.backend == "moment":
            from sketches_tpu.backends import moment

            return moment.init(self.spec, self._n_streams)
        return batched.init(self.spec, self._n_streams)

    def _merge_states(self, a, b):
        """Functional backend merge of two bucket states (pure).
        Dense operands sharing one per-stream window merge elementwise
        (the ladder-fold twin of the fused fold's aligned mode -- no
        recenter rolls); drifted windows take ``merge_aligned``."""
        if self.spec.backend == "uniform_collapse":
            from sketches_tpu.backends import uniform

            return uniform.merge(self.spec, a, b)
        if self.spec.backend == "moment":
            from sketches_tpu.backends import moment

            return moment.merge(self.spec, a, b)
        if _plan_aligned(self.spec, (a, b)):
            return batched.merge(self.spec, a, b)
        return batched.merge_aligned(self.spec, a, b)

    # -- time arithmetic ---------------------------------------------------

    def _id_at(self, rung: int, now: float) -> int:
        return int(math.floor(now / self.config.slices_s[rung]))

    def _interval(self, rung: int, bucket_id: int) -> Tuple[float, float]:
        s = self.config.slices_s[rung]
        return bucket_id * s, (bucket_id + 1) * s

    # -- rotation ----------------------------------------------------------

    def _roll(self, now: float) -> None:
        """Advance the ring to ``now``: freeze an aged-out live bucket,
        cascade retirements down the ladder, drop mass off the last
        rung.  Plans functionally, injects ``window.rotate_torn``, then
        commits by reference swap -- a tear mutates nothing."""
        cur = self._id_at(0, now)
        if cur == self._cur and (
            self._live_id is None or self._live_id == cur
        ):
            return
        freeze = (
            self._live_id is not None and self._live_id != cur
        )
        new_rungs = [dict(r) for r in self._rungs]
        rotations = 0
        collapses = 0
        retired = 0.0
        retired_buckets: List[Tuple[int, int]] = []
        if freeze:
            state = self._snapshot_state(self._live.state)
            new_rungs[0][self._live_id] = _Bucket(
                rung=0, id=self._live_id, state=state,
                mass=self._live_mass,
            )
            rotations += 1
        # Cascade: rung r keeps its newest ``lengths[r]`` slices; older
        # buckets fold into the coarser bucket covering their interval.
        levels = self.config.collapse_levels
        for r in range(self.config.n_rungs):
            cur_r = self._id_at(r, now)
            floor_id = cur_r - self.config.lengths[r] + 1
            for bid in sorted(new_rungs[r]):
                if bid >= floor_id:
                    continue
                b = new_rungs[r].pop(bid)
                retired_buckets.append((r, bid))
                if r + 1 >= self.config.n_rungs:
                    retired += b.mass
                    continue
                state = b.state
                if levels is not None and levels[r + 1] > 0:
                    from sketches_tpu.backends import uniform

                    state = uniform.collapse_to(
                        self.spec, state,
                        jnp.maximum(
                            state.level, jnp.int32(levels[r + 1])
                        ),
                    )
                    collapses += 1
                start, _ = self._interval(r, bid)
                tgt = self._id_at(r + 1, start)
                existing = new_rungs[r + 1].get(tgt)
                if existing is None:
                    new_rungs[r + 1][tgt] = _Bucket(
                        rung=r + 1, id=tgt, state=state, mass=b.mass
                    )
                else:
                    new_rungs[r + 1][tgt] = _Bucket(
                        rung=r + 1, id=tgt,
                        state=self._merge_states(existing.state, state),
                        mass=existing.mass + b.mass,
                    )
        if faults._ACTIVE:
            # The adversary's window: everything above is functional
            # (new dicts, new states); nothing observable has mutated
            # yet, so a tear here proves rotation atomicity.
            faults.inject(faults.WINDOW_ROTATE_TORN)
        # -- commit (reference swaps only) --
        self._rungs = new_rungs
        if freeze:
            self._reset_live()
            self._live_id = None
            self._live_mass = 0.0
        self._cur = cur
        self._rotations += rotations
        self._ladder_collapses += collapses
        self._retired += retired
        self._version += 1
        self._live_fp = None
        if telemetry._ACTIVE:
            if rotations:
                telemetry.counter_inc("window.rotations", float(rotations))
            if collapses:
                telemetry.counter_inc(
                    "window.ladder_collapses", float(collapses)
                )
            if retired:
                telemetry.counter_inc("window.retired_mass", retired)

    # -- write path --------------------------------------------------------

    def add(self, values, weights=None) -> "WindowedSketch":
        """Ingest ``values[n_streams, S]`` into the current time
        slice's bucket; returns self for chaining.

        Rotates first (the injectable clock decides the bucket), then
        rides the backend facade's ingest unchanged -- engine ladder,
        degradations, and refusals are exactly the facade's.  The exact
        batch mass (positive weights; truncated in integer-bin mode)
        lands in the bucket's ledger entry.
        """
        now = self._clock()
        self._roll(now)
        if self._live_id is None:
            self._live_id = self._id_at(0, now)
        self._live.add(values, weights)
        mass = _batch_mass(self.spec, values, weights)
        self._live_mass += mass
        self._total += mass
        self._version += 1
        self._live_fp = None
        return self

    def merge(self, other: "WindowedSketch") -> "WindowedSketch":
        """Fold another windowed ring into this one (same spec, same
        ladder config, clock-aligned bucket ids) -- the cross-host fold
        for windowed fleets: every bucket merges with its same-id twin
        through the backend merge algebra, ledgers add exactly.
        Unequal specs or configs raise
        ``UnequalSketchParametersError``.
        """
        if other.spec != self.spec or other.config != self.config:
            raise UnequalSketchParametersError(
                "cannot merge windowed sketches with different specs or"
                " ladder configs"
            )
        for r in range(self.config.n_rungs):
            for bid, b in sorted(other._rungs[r].items()):
                mine = self._rungs[r].get(bid)
                if mine is None:
                    self._rungs[r][bid] = _Bucket(
                        rung=r, id=bid, state=b.state, mass=b.mass
                    )
                else:
                    self._rungs[r][bid] = _Bucket(
                        rung=r, id=bid,
                        state=self._merge_states(mine.state, b.state),
                        mass=mine.mass + b.mass,
                    )
        if other._live_id is not None:
            if self._live_id is None:
                self._live_id = other._live_id
                self._set_live_state(other._live.state)
                self._live_mass = other._live_mass
            elif self._live_id == other._live_id:
                self._set_live_state(
                    self._merge_states(
                        self._live.state, other._live.state
                    )
                )
                self._live_mass += other._live_mass
            else:
                # Different current slices: the other's live bucket is
                # frozen history from this ring's point of view.
                self._rungs[0][other._live_id] = _Bucket(
                    rung=0, id=other._live_id,
                    state=other._live.state, mass=other._live_mass,
                )
        self._total += other._total
        self._retired += other._retired
        self._version += 1
        self._live_fp = None
        return self

    def reshard(self, mesh=None, n_devices: Optional[int] = None,
                *, live_mask=None):
        """Resize a mesh-backed live bucket LIVE -> its
        ``ReshardReport``; frozen buckets are topology-free merged
        states and survive untouched.

        Raises ``SpecError`` for a non-distributed ring; a torn reshard
        (injected) raises and leaves the live fleet bit-identical --
        reshard stays atomic even mid-rotation.  Dropped mass (dead
        shards) is subtracted from the live bucket's ledger entry and
        from ``total_mass`` exactly, so the ledger survives lossy
        reshards too.
        """
        if not self._distributed:
            raise SpecError(
                "reshard needs a mesh-backed WindowedSketch (pass"
                " mesh=/value_axis= at construction)"
            )
        new_facade, report = self._live.reshard(
            mesh=mesh, n_devices=n_devices, live_mask=live_mask
        )
        self._live = new_facade
        self._mesh = getattr(new_facade, "_sketch_mesh", self._mesh)
        if report.n_dead:
            dropped = float(
                np.asarray(report.dropped_count, np.float64).sum()
            )
            self._live_mass -= dropped
            self._total -= dropped
        self._version += 1
        self._live_fp = None
        return report

    # -- read path ---------------------------------------------------------

    def _covered(
        self, window_s: Optional[float], now: float
    ) -> List[Tuple[int, int, Any, Optional[_Bucket]]]:
        """Buckets whose time slice intersects ``[now - W, now)`` in
        deterministic (start time, rung) order -> list of
        ``(rung, id, state, bucket-or-None-for-live)``."""
        t0 = -math.inf if window_s is None else now - float(window_s)
        out = []
        for r in range(self.config.n_rungs):
            for bid, b in self._rungs[r].items():
                start, end = self._interval(r, bid)
                if end > t0 and start <= now:
                    out.append((start, r, bid, b.state, b))
        if self._live_id is not None:
            # ``start <= now``: the current slice's bucket starts AT the
            # boundary when now sits exactly on it -- data ingested "right
            # now" is always part of "the last W seconds".
            start, end = self._interval(0, self._live_id)
            if end > t0 and start <= now:
                out.append((
                    start, 0, self._live_id,
                    self._snapshot_state(self._live.state), None,
                ))
        out.sort(key=lambda e: (e[0], e[1]))
        return [(r, bid, st, b) for _, r, bid, st, b in out]

    def _bucket_fp(self, bucket: Optional[_Bucket], state) -> np.ndarray:
        if bucket is not None:
            if bucket.fp is None:
                bucket.fp = integrity.fingerprint(self.spec, bucket.state)
            return bucket.fp
        cached = self._live_fp
        if cached is not None and cached[0] == self._version:
            return cached[1]
        fp = integrity.fingerprint(self.spec, state)
        self._live_fp = (self._version, fp)
        return fp

    def window_plan(self, window_s: Optional[float] = None) -> WindowPlan:
        """Resolve a window query: rotate, select the covered buckets,
        and derive the fingerprint-set digest -> a :class:`WindowPlan`.

        The digest is the cache-key contract the serving tier keys on:
        it hashes every covered bucket's ``(rung, id, fingerprint)``,
        so a rotation, an ingest, or any content change moves it -- a
        stale cache entry can only MISS, never read stale-wrong.  An
        empty coverage yields a plan whose query answers NaN.
        """
        now = self._clock()
        self._roll(now)
        covered = self._covered(window_s, now)
        fps = [self._bucket_fp(b, st) for (_, _, st, b) in covered]
        h = hashlib.sha256()
        h.update(b"window")
        for (r, bid, _, _), fp in zip(covered, fps):
            h.update(np.int64(r).tobytes())
            h.update(np.int64(bid).tobytes())
            h.update(np.ascontiguousarray(fp).tobytes())
        fingerprint = (
            np.concatenate([np.atleast_1d(f) for f in fps])
            if fps else np.zeros((0,), np.float64)
        )
        if telemetry._ACTIVE:
            telemetry.gauge_set(
                "window.covered_buckets", float(len(covered))
            )
        return WindowPlan(
            window_s=window_s,
            now=now,
            keys=tuple((r, bid) for r, bid, _, _ in covered),
            states=tuple(st for _, _, st, _ in covered),
            fingerprint=fingerprint,
            digest=h.digest(),
        )


    def query_plan(self, plan: WindowPlan, quantiles: Sequence[float]):
        """Answer ``quantiles`` over a resolved :class:`WindowPlan` ->
        ``[n_streams, Q]`` (NaN for empty coverage / empty streams).
        The plan's states are frozen references, so a rotation between
        planning and dispatch cannot change the answer."""
        qs = tuple(float(q) for q in quantiles)
        if not plan.states:
            return np.full(
                (self._n_streams, len(qs)), np.nan,
                np.dtype(jnp.dtype(self.spec.dtype).name),
            )
        mode = _fold_mode(self.spec, plan.states)
        return _fold_for(self.spec)[mode](
            plan.states, jnp.asarray(qs, self.spec.dtype)
        )

    def quantile(
        self, quantiles: Sequence[float],
        window: Optional[float] = None,
    ):
        """``quantile(qs, window=W)``: the fused window query ->
        ``[n_streams, Q]``.

        ``window=None`` covers the whole retained horizon.  Bit-
        identical to a host-side sequential merge of the covered
        buckets (the tested oracle); empty windows/streams answer NaN.
        """
        return self.query_plan(self.window_plan(window), quantiles)

    def get_quantile_values(self, quantiles: Sequence[float]):
        """Facade-parity alias: full-horizon fused multi-quantile ->
        ``[n_streams, Q]`` (NaN when empty)."""
        return self.quantile(quantiles, window=None)

    # -- introspection -----------------------------------------------------

    @property
    def n_streams(self) -> int:
        return self._n_streams

    @property
    def total_mass(self) -> float:
        """Exact mass ever ingested (minus reshard-dropped mass);
        equals live ledger + ``retired_mass`` -- the invariant
        :func:`sketches_tpu.integrity.check_window` verifies with
        ``==``.  Never raises."""
        return self._total

    @property
    def retired_mass(self) -> float:
        """Exact mass dropped off the last ladder rung; never raises."""
        return self._retired

    def buckets(self) -> List[Tuple[int, int, float]]:
        """The live ledger: ``(rung, bucket id, exact mass)`` per live
        bucket (the current ingest bucket included), coverage-ordered.
        Empty before the first ingest; never raises."""
        out = [
            (r, bid, b.mass)
            for r in range(self.config.n_rungs)
            for bid, b in sorted(self._rungs[r].items())
        ]
        if self._live_id is not None:
            out.append((0, self._live_id, self._live_mass))
        return sorted(out, key=lambda e: (e[0], e[1]))

    def ledger(self) -> Dict[str, float]:
        """The mass ledger summary: ``total`` (ever ingested),
        ``live`` (sum of live bucket entries), ``retired`` (dropped off
        the last rung), ``rotations``, ``ladder_collapses``.  The exact
        invariant is ``total == live + retired``; never raises."""
        live = sum(m for _, _, m in self.buckets())
        return {
            "total": self._total,
            "live": live,
            "retired": self._retired,
            "rotations": float(self._rotations),
            "ladder_collapses": float(self._ladder_collapses),
        }

    def rung_effective_alpha(self) -> List[float]:
        """Each rung's declared accuracy contract: the worst-case
        relative error of a bucket that has been coarsened into that
        rung (``uniform_collapse``: ``effective_alpha`` at the rung's
        collapse level; other backends: the spec alpha everywhere).
        Never raises."""
        if (
            self.spec.backend == "uniform_collapse"
            and self.config.collapse_levels is not None
        ):
            from sketches_tpu.backends.uniform import effective_alpha

            return [
                float(
                    np.asarray(
                        effective_alpha(
                            self.spec, jnp.int32(level)
                        )
                    )
                )
                for level in self.config.collapse_levels
            ]
        return [
            self.spec.relative_accuracy
            for _ in range(self.config.n_rungs)
        ]

    def device_masses(self) -> Dict[Tuple[int, int], float]:
        """Per-bucket device-side mass (sum of each bucket state's
        ``count``) -- the audit-side twin of :meth:`buckets` the chaos
        campaign compares with ``==``.  Forces a device fetch per
        bucket; empty ring returns ``{}``; never raises."""
        out: Dict[Tuple[int, int], float] = {}
        for r in range(self.config.n_rungs):
            for bid, b in self._rungs[r].items():
                count = getattr(b.state, "count", None)
                if count is None:  # pragma: no cover - defensive
                    continue
                out[(r, bid)] = float(
                    np.asarray(jax.device_get(count), np.float64).sum()
                )
        if self._live_id is not None:
            out[(0, self._live_id)] = float(
                np.asarray(
                    jax.device_get(self._live.count), np.float64
                ).sum()
            )
        return out

    def __repr__(self) -> str:
        return (
            f"WindowedSketch(n_streams={self._n_streams},"
            f" backend={self.spec.backend!r},"
            f" rungs={[f'{s:g}s x {n}' for s, n in zip(self.config.slices_s, self.config.lengths)]},"
            f" live_buckets={len(self.buckets())},"
            f" total_mass={self._total:g})"
        )


#: Oracle-side jitted quantile per spec (the decode any facade query
#: would run; cached so repeated oracle audits do not recompile).
_ORACLE_Q_CACHE: Dict[SketchSpec, Callable] = {}


def oracle_quantile(
    wsk: WindowedSketch,
    quantiles: Sequence[float],
    window: Optional[float] = None,
):
    """The host-driven oracle: sequentially merge the covered buckets
    with the backend's own merge (one eager dispatch per pair) and
    answer the fused quantile -> ``[n_streams, Q]``.

    The windowed query must be bit-identical to this -- the exactness
    contract ``tests/test_windows.py`` and the chaos campaign pin.
    Empty coverage answers NaN like the query itself; never mutates
    the ring beyond the same rotation the query would perform.
    """
    plan = wsk.window_plan(window)
    qs = tuple(float(q) for q in quantiles)
    if not plan.states:
        return np.full(
            (wsk.n_streams, len(qs)), np.nan,
            np.dtype(jnp.dtype(wsk.spec.dtype).name),
        )
    spec = wsk.spec
    if _fold_mode(spec, plan.states) == "aligned":
        # The identical host-side mode choice the fused fold makes:
        # aligned dense windows merge elementwise (no recenter rolls).
        acc = functools.reduce(
            functools.partial(batched.merge, spec), plan.states
        )
    else:
        acc = functools.reduce(wsk._merge_states, plan.states)
    if spec.backend == "moment":
        from sketches_tpu.backends import moment

        return moment.quantile(spec, acc, qs)
    # The merged state decodes through the standard JITTED quantile --
    # exactly what any facade query runs (the eager merge chain is
    # bit-identical to the fused fold's; quantile is always a jitted
    # dispatch in this library, so the oracle holds it to that).
    qfn = _ORACLE_Q_CACHE.get(spec)
    if qfn is None:
        if spec.backend == "uniform_collapse":
            from sketches_tpu.backends import uniform

            qfn = jax.jit(functools.partial(uniform.quantile, spec))
        else:
            qfn = jax.jit(functools.partial(batched.quantile, spec))
        _ORACLE_Q_CACHE[spec] = qfn
    return qfn(acc, jnp.asarray(qs, spec.dtype))
