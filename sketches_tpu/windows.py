"""Time-windowed quantiles: "p99 over the last 5 minutes" as a query.

Every real dashboard query against a quantile fleet is time-scoped, and
DDSketch's full mergeability (PAPER.md) makes windowing nearly free: a
window query is just a merge over the bucket sketches that cover it.
This module is that composition, built entirely from seams earlier
rounds landed:

* **Ring of time-slice buckets.**  A :class:`WindowedSketch` routes
  ingest to the *current* bucket of a ring of ``B`` time slices (one
  backend sketch per slice, any ``SketchSpec`` backend -- dense,
  ``uniform_collapse``, ``moment``, or a mesh-sharded distributed
  fleet).  The clock is injectable (defaults to ``telemetry.clock``),
  so every rotation/query replays exactly under a virtual clock -- no
  code here sleeps or reads wall time.
* **Window queries are ONE fused stacked-merge dispatch.**  A query for
  ``quantile(q, window=W)`` selects the buckets whose time slices
  intersect ``[now - W, now)`` and folds them with the backend's own
  merge algebra inside one jitted dispatch (the serve tier's same-spec
  stacking shape): dense buckets fold through
  ``batched.merge_aligned``, adaptive buckets through
  ``backends.uniform.merge`` (levels align first), moment buckets
  through the elementwise ``backends.moment.merge``.  The answer is
  bit-identical to a host-side sequential merge of the covered buckets
  -- the oracle the tests pin.
* **Eviction is rotation, with an exact mass ledger.**  When a bucket
  ages out of its ring it *retires* into the next rung of a
  hierarchical coarsening ladder (e.g. 5s -> 1m -> 1h slices): its
  mass merges into the coarser bucket covering its interval, optionally
  collapsing first (``uniform_collapse`` backend:
  ``collapse_to(rung level)``, so ``effective_alpha`` per rung is the
  DECLARED accuracy contract -- old data gracefully loses precision
  instead of space).  Mass falling off the last rung is dropped and
  recorded.  The per-bucket mass ledger is **exact**: every ingested
  unit of weight is in exactly one live bucket or in ``retired_mass``,
  and the chaos campaign asserts the ledger with ``==``, never
  approximately.
* **Atomic rotation.**  A rotation plans functionally (new ring dicts,
  new folded states) and commits by reference swap; the
  ``window.rotate_torn`` fault site fires between plan and commit, so
  a torn rotation leaves the ring, the ledger, and the live bucket
  bit-identical (chaos-proven).

Failure modes: constructing a :class:`WindowedSketch` (or querying one)
with ``SKETCHES_TPU_WINDOWED=0`` raises ``SpecError`` -- the kill
switch refuses loudly; invalid ladder configurations (non-divisible
slice widths, non-positive lengths, collapse levels on a non-adaptive
backend) raise ``SpecError`` at construction; a window with no covered
mass answers NaN exactly like an empty sketch; merging mismatched
configs raises ``UnequalSketchParametersError``; a torn rotation
(injected) raises ``InjectedFault`` with nothing mutated.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from sketches_tpu import batched, faults, integrity, telemetry
from sketches_tpu.analysis import registry
from sketches_tpu.batched import SketchSpec
from sketches_tpu.resilience import (
    SketchValueError,
    SpecError,
    UnequalSketchParametersError,
    bump,
    record_downgrade,
)

__all__ = [
    "WindowConfig",
    "WindowedSketch",
    "VirtualClock",
    "DEFAULT_LADDER",
]


class VirtualClock:
    """A deterministic, manually-advanced clock for tests and drills.

    ``clock()`` semantics (monotone seconds) without any wall-time read:
    call the instance to read ``t``, :meth:`advance` to move it.  Never
    raises; time never goes backwards (negative deltas raise
    ``SketchValueError`` -- a backwards window clock would silently
    re-open retired buckets).
    """

    __slots__ = ("t",)

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        """Move the clock forward by ``dt`` seconds -> the new time."""
        if dt < 0:
            raise SketchValueError("VirtualClock cannot run backwards")
        self.t += float(dt)
        return self.t


@dataclasses.dataclass(frozen=True)
class WindowConfig:
    """The ring/ladder layout: per-rung slice widths and ring lengths.

    ``slices_s[r]`` is rung ``r``'s time-slice width in seconds (rung 0
    is the fine rung ingest lands in); ``lengths[r]`` is how many
    slices rung ``r`` retains before a bucket retires into rung
    ``r + 1`` (or, off the last rung, is dropped with its mass recorded
    in ``retired_mass``).  ``collapse_levels[r]`` (``uniform_collapse``
    backend only) is the collapse level a bucket is brought to when it
    *enters* rung ``r`` -- the rung's declared ``effective_alpha``
    contract.

    Failure modes: non-positive widths/lengths, a coarser slice that is
    not an integer multiple of the finer one (buckets must nest), a
    ``collapse_levels`` tuple of the wrong length or decreasing order
    all raise ``SpecError`` at construction.
    """

    slices_s: Tuple[float, ...] = (5.0, 60.0, 3600.0)
    lengths: Tuple[int, ...] = (12, 60, 24)
    collapse_levels: Optional[Tuple[int, ...]] = None

    def __post_init__(self):
        slices = tuple(float(s) for s in self.slices_s)
        lengths = tuple(int(n) for n in self.lengths)
        object.__setattr__(self, "slices_s", slices)
        object.__setattr__(self, "lengths", lengths)
        if not slices or len(slices) != len(lengths):
            raise SpecError(
                "WindowConfig needs one (slice width, ring length) pair"
                f" per rung; got {len(slices)} widths, {len(lengths)}"
                " lengths"
            )
        if any(s <= 0 for s in slices) or any(n <= 0 for n in lengths):
            raise SpecError("slice widths and ring lengths must be positive")
        for fine, coarse in zip(slices, slices[1:]):
            ratio = coarse / fine
            if coarse <= fine or abs(ratio - round(ratio)) > 1e-9:
                raise SpecError(
                    "ladder slices must be strictly coarsening integer"
                    f" multiples; got {fine}s -> {coarse}s"
                )
        if self.collapse_levels is not None:
            levels = tuple(int(v) for v in self.collapse_levels)
            object.__setattr__(self, "collapse_levels", levels)
            if len(levels) != len(slices):
                raise SpecError(
                    "collapse_levels needs one level per rung; got"
                    f" {len(levels)} for {len(slices)} rungs"
                )
            if any(v < 0 for v in levels) or list(levels) != sorted(levels):
                raise SpecError(
                    "collapse_levels must be non-negative and"
                    " non-decreasing (coarser rungs never regain"
                    " resolution)"
                )

    @property
    def n_rungs(self) -> int:
        return len(self.slices_s)

    def horizon_s(self) -> float:
        """Total retained history in seconds (sum of every rung's span);
        never raises."""
        return float(
            sum(s * n for s, n in zip(self.slices_s, self.lengths))
        )


#: The dashboard-shaped default ladder: 12 x 5 s (the live minute),
#: 60 x 1 m (the hour), 24 x 1 h (the day).
DEFAULT_LADDER = WindowConfig()


@dataclasses.dataclass
class _Bucket:
    """One frozen time-slice bucket: its ring position, its backend
    state pytree, and its exact mass ledger entry.  ``fp`` memoizes the
    content fingerprint (frozen states are immutable, so once computed
    it never changes)."""

    rung: int
    id: int
    state: Any
    mass: float
    fp: Optional[np.ndarray] = None


@dataclasses.dataclass(frozen=True)
class WindowPlan:
    """One resolved window query: the covered buckets' states (frozen
    at plan time, so a rotation between plan and dispatch cannot skew
    the answer), their combined content fingerprint, and the cache-key
    digest derived from the covered-bucket fingerprint *set*.  Obtained
    from :meth:`WindowedSketch.window_plan`; an empty plan (no covered
    mass) answers NaN.

    Validity: a plan must be consumed before the ring's next WRITE --
    ingest donates the live bucket's device buffers (the engines'
    in-place update discipline), so a plan held across an ``add``
    may reference deleted buffers and the dispatch then fails loudly
    (``RuntimeError``), never answers silently wrong.  The serving
    tier plans and dispatches under one lock, so this cannot happen
    there."""

    window_s: Optional[float]
    now: float
    keys: Tuple[Tuple[int, int], ...]  # (rung, bucket id), coverage order
    states: Tuple[Any, ...]
    fingerprint: np.ndarray
    digest: bytes
    #: Maintained-aggregate fast path (``SKETCHES_TPU_WINDOW_AGG=1``):
    #: the pre-merged component states the fused fold runs over instead
    #: of every covered bucket, plus one recipe per component naming
    #: exactly which ``states`` indices it folds and in what tree shape
    #: (``("raw", i)`` or ``("fold", rung, front idxs, back idxs)``) --
    #: the contract :func:`oracle_quantile` replays eagerly.  ``None``
    #: when the kill switch routes through the full re-merge path.
    components: Optional[Tuple[Any, ...]] = None
    recipes: Optional[Tuple[Tuple, ...]] = None

    @property
    def n_covered(self) -> int:
        return len(self.states)


#: Process-wide fused-fold cache: one ``{mode: jitted callable}`` per
#: spec (jit retraces per covered-bucket arity under the same callable,
#: so every ring sharing a spec shares every compilation).  Dense specs
#: carry two modes -- ``"aligned"`` (all covered windows share one
#: per-stream offset: elementwise merge, no recenter scatters) and
#: ``"general"`` (drifted windows: ``merge_aligned`` chain) -- chosen
#: HOST-SIDE from the plan's offsets; the oracle applies the identical
#: choice, so bit-identity is by symmetry, not by luck.
_FOLD_CACHE: Dict[SketchSpec, Dict[str, Callable]] = {}


def _plan_aligned(spec: SketchSpec, states) -> bool:
    """Whether every covered dense state shares one per-stream window
    offset (the common case: buckets that never recentered apart).
    Non-dense backends answer False (their folds self-align)."""
    if spec.backend != "dense" or len(states) < 2:
        return spec.backend == "dense"
    first = np.asarray(jax.device_get(states[0].key_offset))
    for st in states[1:]:
        if not np.array_equal(
            first, np.asarray(jax.device_get(st.key_offset))
        ):
            return False
    return True


def _fold_for(spec: SketchSpec) -> Dict[str, Callable]:
    fns = _FOLD_CACHE.get(spec)
    if fns is not None:
        return fns
    if spec.backend == "uniform_collapse":
        from sketches_tpu.backends import uniform

        def fold(states, qs):
            acc = states[0]
            for st in states[1:]:
                acc = uniform.merge(spec, acc, st)
            return uniform.quantile(spec, acc, qs)

        fns = {"general": jax.jit(fold)}
    elif spec.backend == "moment":
        from sketches_tpu.backends import moment

        def merge_chain(states):
            acc = states[0]
            for st in states[1:]:
                acc = moment.merge(spec, acc, st)
            return acc

        merged = jax.jit(merge_chain)

        def host_solve(states, qs):  # host maxent after one fused merge
            return moment.quantile(spec, merged(states), qs)

        fns = {"general": host_solve}
    else:

        def fold_general(states, qs):
            acc = states[0]
            for st in states[1:]:
                acc = batched.merge_aligned(spec, acc, st)
            return batched.quantile(spec, acc, qs)

        def fold_aligned(states, qs):
            acc = states[0]
            for st in states[1:]:
                acc = batched.merge(spec, acc, st)
            return batched.quantile(spec, acc, qs)

        fns = {
            "general": jax.jit(fold_general),
            "aligned": jax.jit(fold_aligned),
        }
    _FOLD_CACHE[spec] = fns
    return fns


def _fold_mode(spec: SketchSpec, states) -> str:
    fns = _fold_for(spec)
    if "aligned" in fns and _plan_aligned(spec, states):
        return "aligned"
    return "general"


#: Fold-to-STATE twin of :data:`_FOLD_CACHE` for the serve tier's
#: windowed stacking: same per-mode merge chains, but returning the
#: folded state instead of decoding quantiles -- the per-tenant reduce
#: that lets same-spec windowed tenants share ONE stacked quantile
#: dispatch.  Same jit-per-arity sharing discipline.
_FOLD_STATE_CACHE: Dict[SketchSpec, Dict[str, Callable]] = {}


def _fold_state_for(spec: SketchSpec) -> Dict[str, Callable]:
    fns = _FOLD_STATE_CACHE.get(spec)
    if fns is not None:
        return fns
    if spec.backend == "uniform_collapse":
        from sketches_tpu.backends import uniform

        def fold(states):
            acc = states[0]
            for st in states[1:]:
                acc = uniform.merge(spec, acc, st)
            return acc

        fns = {"general": jax.jit(fold)}
    elif spec.backend == "moment":
        from sketches_tpu.backends import moment

        def fold_m(states):
            acc = states[0]
            for st in states[1:]:
                acc = moment.merge(spec, acc, st)
            return acc

        fns = {"general": jax.jit(fold_m)}
    else:

        def fold_general(states):
            acc = states[0]
            for st in states[1:]:
                acc = batched.merge_aligned(spec, acc, st)
            return acc

        def fold_aligned(states):
            acc = states[0]
            for st in states[1:]:
                acc = batched.merge(spec, acc, st)
            return acc

        fns = {
            "general": jax.jit(fold_general),
            "aligned": jax.jit(fold_aligned),
        }
    _FOLD_STATE_CACHE[spec] = fns
    return fns


#: Single-state quantile twin: decode quantiles from ONE (already
#: folded) state.  With the per-digest folded-window cache this is the
#: entire cost of a repeated window query -- the same dispatch a plain
#: unwindowed facade pays.
_QUANTILE_CACHE: Dict[SketchSpec, Callable] = {}


def _quantile_for(spec: SketchSpec) -> Callable:
    fn = _QUANTILE_CACHE.get(spec)
    if fn is not None:
        return fn
    if spec.backend == "uniform_collapse":
        from sketches_tpu.backends import uniform

        fn = jax.jit(functools.partial(uniform.quantile, spec))
    elif spec.backend == "moment":
        from sketches_tpu.backends import moment

        def fn(st, qs):  # host maxent solve, like _fold_for's twin
            return moment.quantile(spec, st, qs)
    else:
        fn = jax.jit(functools.partial(batched.quantile, spec))
    _QUANTILE_CACHE[spec] = fn
    return fn


def _batch_mass(spec: SketchSpec, values, weights) -> float:
    """Exact host-side mass of one ingest batch, matching the device
    tier's ``count`` delta: the sum of positive weights (``w <= 0``
    lanes are padding; NaN values still count -- they land in the
    zero path).  Integer bin mode truncates fractional weights exactly
    as the device cast does.  Never raises on well-formed arrays."""
    v = np.asarray(values)
    if weights is None:
        return float(v.size)
    w = np.broadcast_to(
        np.asarray(weights, np.float64), v.shape
    )
    live = w > 0
    if spec.bins_integer:
        return float(np.trunc(w[live]).sum())
    return float(w[live].sum())


class _TwoStacks:
    """Two-stacks incremental aggregator over ONE rung's *sealed*
    buckets (the SWAG/DABA shape: arxiv 2101.06758's fold-over-partials
    framing made O(1) amortized).

    ``front`` holds the older buckets as ``(id, raw state, suffix
    state)`` entries, oldest first, where ``suffix[j]`` is the RIGHT
    fold ``raw[j] + (raw[j+1] + (... ))`` over the rest of the front --
    evicting the oldest entry leaves every remaining suffix valid.
    ``back`` holds the newer buckets as ``(id, raw state)`` with lazily
    maintained LEFT-fold tails (``_tails[start id] = (n folded,
    state)``), each extended by ONE merge when a new bucket lands.
    When an eviction finds the front empty, the whole back flips into
    the front (computing its suffixes) -- the classic amortization:
    every pushed bucket is merged at most once by a flip and at most
    once by a tail extension, so maintenance costs <= 2 backend merges
    per rotation amortized.  A window answer over the rung is then ONE
    merge -- ``front suffix + back tail`` -- plus reuse of whatever is
    already cached.

    The merge-tree SHAPE is the bit-identity contract: backend merges
    are deterministic but not associative in floating point, so
    :meth:`suffix` reports exactly which ids sit in the front/back legs
    and :func:`oracle_quantile` replays the identical ``right-fold
    (front) + left-fold(back)`` association eagerly.  All merges go
    through the owner's counted wrapper; cached states are derived --
    dropping them is always safe (rebuild is lazy and merge-free).
    """

    __slots__ = ("_owner", "rung", "front", "back", "_tails", "_combined")

    def __init__(self, owner: "WindowedSketch", rung: int):
        self._owner = owner
        self.rung = rung
        self.front: List[Tuple[int, Any, Any]] = []
        self.back: List[Tuple[int, Any]] = []
        #: back start id -> (entries folded from there, left-fold state)
        self._tails: Dict[int, Tuple[int, Any]] = {}
        #: front id -> (back length folded, suffix+back-tail state)
        self._combined: Dict[int, Tuple[int, Any]] = {}

    def ids(self) -> List[int]:
        return [e[0] for e in self.front] + [e[0] for e in self.back]

    def _merge(self, a, b):
        o = self._owner
        o._agg_maint_merges += 1
        return o._merge_states(a, b)

    def push(self, bid: int, state) -> None:
        """Append a newly sealed bucket (no merges: tails extend lazily)."""
        if self.back and bid <= self.back[-1][0]:
            raise SketchValueError(
                f"two-stacks push out of order: {bid} after"
                f" {self.back[-1][0]}"
            )
        if self.front and bid <= self.front[-1][0]:
            raise SketchValueError(
                f"two-stacks push out of order: {bid} behind front"
            )
        self.back.append((bid, state))

    def evict(self, bid: int) -> None:
        """Drop the oldest sealed bucket (it retired off the rung)."""
        if not self.front:
            self._flip()
        if not self.front or self.front[0][0] != bid:
            raise SketchValueError(
                f"two-stacks evict out of order: {bid} is not the oldest"
            )
        self.front.pop(0)
        self._combined.pop(bid, None)

    def _flip(self) -> None:
        """Move the whole back into the front, computing right-fold
        suffixes (one merge per entry beyond the first -- the amortized
        cost every pushed bucket pays at most once)."""
        acc = None
        rev: List[Tuple[int, Any, Any]] = []
        for bid, raw in reversed(self.back):
            acc = raw if acc is None else self._merge(raw, acc)
            rev.append((bid, raw, acc))
        self.front = list(reversed(rev))
        self.back = []
        self._tails.clear()
        self._combined.clear()

    def _back_tail(self, t: int):
        """Left fold of ``back[t:]``, maintained incrementally: a cached
        tail extends by one merge per newly pushed bucket."""
        bid = self.back[t][0]
        n = len(self.back) - t
        cached = self._tails.get(bid)
        if cached is not None and cached[0] == n:
            return cached[1]
        if cached is not None and 0 < cached[0] < n:
            done, acc = cached
        else:
            done, acc = 1, self.back[t][1]
        for i in range(t + done, len(self.back)):
            acc = self._merge(acc, self.back[i][1])
        self._tails[bid] = (n, acc)
        return acc

    def suffix(self, start_bid: int):
        """The maintained fold of sealed buckets ``start_bid..newest``
        -> ``(state, front ids folded, back ids folded)`` or ``None``
        when ``start_bid`` is not a stacked id.  Tree shape: right fold
        over the front leg ``+`` left fold over the back leg -- the
        association the oracle replays."""
        o = self._owner
        for j, (bid, _raw, sfx) in enumerate(self.front):
            if bid == start_bid:
                front_ids = tuple(e[0] for e in self.front[j:])
                back_ids = tuple(e[0] for e in self.back)
                if not self.back:
                    return sfx, front_ids, back_ids
                cached = self._combined.get(bid)
                if cached is not None and cached[0] == len(self.back):
                    return cached[1], front_ids, back_ids
                tail = self._back_tail(0)
                o._agg_query_merges += 1
                st = o._merge_states(sfx, tail)
                self._combined[bid] = (len(self.back), st)
                return st, front_ids, back_ids
        for t, (bid, _raw) in enumerate(self.back):
            if bid == start_bid:
                return (
                    self._back_tail(t), (),
                    tuple(e[0] for e in self.back[t:]),
                )
        return None


class WindowedSketch:
    """Per-tenant ring of time-slice bucket sketches with a coarsening
    ladder (module docstring for the full design).

    ``spec``/``**kwargs`` select the backend exactly like
    :func:`sketches_tpu.backends.facade_for`; passing ``mesh``/
    ``value_axis``/``stream_axis`` backs the live bucket with a
    mesh-sharded ``DistributedDDSketch`` (dense backend only -- frozen
    buckets are topology-free merged states, so they survive
    :meth:`reshard` untouched).

    Failure modes: ``SKETCHES_TPU_WINDOWED=0`` raises ``SpecError`` at
    construction (loud refusal, one env read); ``collapse_levels`` on a
    non-``uniform_collapse`` backend raises ``SpecError``;
    :meth:`merge` across unequal specs/configs raises
    ``UnequalSketchParametersError``; :meth:`reshard` of a
    non-distributed ring raises ``SpecError``; empty windows answer
    NaN; an injected torn rotation raises ``InjectedFault`` with the
    ring left bit-identical.
    """

    def __init__(
        self,
        n_streams: int,
        *,
        spec: Optional[SketchSpec] = None,
        config: Optional[WindowConfig] = None,
        clock: Optional[Callable[[], float]] = None,
        mesh=None,
        value_axis=None,
        stream_axis=None,
        engine: str = "auto",
        **kwargs,
    ):
        if not registry.enabled(registry.WINDOWED):
            raise SpecError(
                "time-windowed sketches are disabled"
                " (SKETCHES_TPU_WINDOWED=0): refusing to construct a"
                " WindowedSketch rather than silently serving"
                " unwindowed answers"
            )
        self.config = config or DEFAULT_LADDER
        if spec is None:
            backend = kwargs.pop("backend", "dense")
            spec = SketchSpec(backend=backend, **kwargs)
            kwargs = {}
        self.spec = spec
        self._n_streams = int(n_streams)
        self._clock = clock if clock is not None else telemetry.clock
        if self.config.collapse_levels is not None:
            if spec.backend != "uniform_collapse":
                raise SpecError(
                    "collapse_levels need backend='uniform_collapse';"
                    f" got {spec.backend!r}"
                )
            if max(self.config.collapse_levels) > spec.max_collapses:
                raise SpecError(
                    "collapse_levels exceed spec.max_collapses"
                    f" ({max(self.config.collapse_levels)} >"
                    f" {spec.max_collapses})"
                )
        self._distributed = (
            mesh is not None or value_axis is not None
            or stream_axis is not None
        )
        self._engine = engine
        self._mesh = mesh
        self._dist_axes = (value_axis, stream_axis)
        self._live = self._make_live()
        self._live_id: Optional[int] = None
        self._live_mass = 0.0
        self._rungs: List[Dict[int, _Bucket]] = [
            {} for _ in range(self.config.n_rungs)
        ]
        self._total = 0.0
        self._retired = 0.0
        self._rotations = 0
        self._ladder_collapses = 0
        self._cur: Optional[int] = None
        self._version = 0  # bumped on every content change (live fp cache)
        self._live_fp: Optional[Tuple[int, np.ndarray]] = None
        # -- maintained two-stacks window aggregates (derived state) --
        self._agg_enabled = registry.enabled(registry.WINDOW_AGG)
        self._agg_stacks: Optional[List[_TwoStacks]] = (
            [_TwoStacks(self, r) for r in range(self.config.n_rungs)]
            if self._agg_enabled else None
        )
        self._agg_maint_merges = 0
        self._agg_query_merges = 0
        self._agg_reuse = 0
        self._agg_rebuilds = 0
        # (digest, folded state, component states, fold mode, decode
        # facade or None) -- the per-plan-digest folded-window cache;
        # derived, never serialized.
        self._agg_fold_cache: Optional[
            Tuple[bytes, Any, Tuple, str, Any]
        ] = None

    # -- construction helpers ---------------------------------------------

    def _make_live(self):
        if self._distributed:
            from sketches_tpu.parallel import DistributedDDSketch

            value_axis, stream_axis = self._dist_axes
            if self._mesh is None and value_axis is None \
                    and stream_axis is None:
                value_axis = "values"
            return DistributedDDSketch(
                self._n_streams, mesh=self._mesh, value_axis=value_axis,
                stream_axis=stream_axis, spec=self.spec,
                engine=self._engine,
            )
        from sketches_tpu.backends import facade_for

        return facade_for(
            self._n_streams, spec=self.spec, engine=self._engine
        )

    def _reset_live(self) -> None:
        """Empty the live bucket's facade (cheap state swap for host
        facades; a mesh-backed live bucket rebuilds on its current
        mesh -- rotation cadence is seconds, so the rebuild is cold-path
        by construction)."""
        if self._distributed:
            self._live = self._make_live()
            return
        self._live.state = self._empty_state()
        if hasattr(self._live, "_auto_recenter_pending"):
            # A fresh bucket re-centers its window on its first batch,
            # exactly like a fresh facade would.
            self._live._auto_recenter_pending = True

    def _set_live_state(self, state) -> None:
        """Assign merged content to the live bucket (merge path)."""
        if self._distributed:
            from sketches_tpu.parallel import DistributedDDSketch

            value_axis, stream_axis = self._dist_axes
            self._live = DistributedDDSketch.from_merged_state(
                state, self.spec, mesh=self._mesh,
                value_axis=value_axis or "values",
                stream_axis=stream_axis, engine=self._engine,
            )
            return
        self._live.state = state

    def _snapshot_state(self, state):
        """Freeze a bucket state for the ring.  Mesh-backed rings
        normalize to host-committed (unsharded) arrays: frozen buckets
        are topology-free by contract (they must survive reshard), and
        the fused fold must stay bit-identical to the host-side oracle
        -- sharded operands can compile to different (1-ULP) decode
        fusions.  Host facades pass through untouched (their states are
        already single-device)."""
        if not self._distributed:
            return state
        return jax.tree.map(
            lambda a: jnp.asarray(np.asarray(jax.device_get(a))), state
        )

    def _empty_state(self):
        if self.spec.backend == "uniform_collapse":
            from sketches_tpu.backends.uniform import AdaptiveState

            return AdaptiveState(
                base=batched.init(self.spec, self._n_streams),
                level=jnp.zeros((self._n_streams,), jnp.int32),
            )
        if self.spec.backend == "moment":
            from sketches_tpu.backends import moment

            return moment.init(self.spec, self._n_streams)
        return batched.init(self.spec, self._n_streams)

    def _merge_states(self, a, b):
        """Functional backend merge of two bucket states (pure).
        Dense operands sharing one per-stream window merge elementwise
        (the ladder-fold twin of the fused fold's aligned mode -- no
        recenter rolls); drifted windows take ``merge_aligned``."""
        if self.spec.backend == "uniform_collapse":
            from sketches_tpu.backends import uniform

            return uniform.merge(self.spec, a, b)
        if self.spec.backend == "moment":
            from sketches_tpu.backends import moment

            return moment.merge(self.spec, a, b)
        if _plan_aligned(self.spec, (a, b)):
            return batched.merge(self.spec, a, b)
        return batched.merge_aligned(self.spec, a, b)

    # -- time arithmetic ---------------------------------------------------

    def _id_at(self, rung: int, now: float) -> int:
        return int(math.floor(now / self.config.slices_s[rung]))

    def _interval(self, rung: int, bucket_id: int) -> Tuple[float, float]:
        s = self.config.slices_s[rung]
        return bucket_id * s, (bucket_id + 1) * s

    # -- maintained two-stacks aggregates (derived state) ------------------

    def _seal_cutoff(self, rung: int, now: float) -> int:
        """Bucket ids of ``rung`` strictly below this can never receive
        another retirement merge -- they are *sealed* and safe to enter
        the two-stacks aggregator.  Rung 0's frozen buckets are sealed
        the moment they freeze (ingest never revisits them); a coarser
        bucket is sealed once every finer constituent slice has retired
        past rung ``rung - 1``'s floor (``(bid + 1) * ratio <= floor``).
        Unsealed ("absorbing") buckets stay out of the stacks and ride
        the plan as raw components."""
        if rung == 0:
            return self._id_at(0, now)
        floor_finer = (
            self._id_at(rung - 1, now) - self.config.lengths[rung - 1] + 1
        )
        ratio = round(
            self.config.slices_s[rung] / self.config.slices_s[rung - 1]
        )
        # bid is sealed iff every constituent retired past the finer
        # floor: (bid + 1) * ratio <= floor  <=>  bid < floor // ratio.
        return floor_finer // ratio

    def _agg_invalidate(self) -> None:
        """Drop the maintained stacks (merge of rings, restore from a
        checkpoint/wire image, torn sync): they are derived state, so
        the next plan rebuilds them lazily with zero upfront merges."""
        if self._agg_enabled:
            self._agg_stacks = None
            self._agg_fold_cache = None

    def _agg_sync(self, now: float) -> None:
        """Bring the per-rung stacks up to date with the ring: push
        newly sealed buckets, evict retired ones.  Runs after the
        rotation COMMIT (and at plan time), so a torn rotation never
        sees half-updated stacks.  Any failure here -- including the
        injected ``window.stack_torn`` tear -- is swallowed: the stacks
        are dropped and rebuilt lazily, recorded in the health ledger;
        a query can get slower, never wrong and never refused."""
        if not self._agg_enabled:
            return
        try:
            if faults._ACTIVE:
                faults.inject(faults.WINDOW_STACK_TORN)
            if self._agg_stacks is None:
                self._agg_stacks = [
                    _TwoStacks(self, r) for r in range(self.config.n_rungs)
                ]
                self._agg_rebuilds += 1
                if telemetry._ACTIVE:
                    telemetry.counter_inc("window.agg_rebuilds")
            for r, stack in enumerate(self._agg_stacks):
                cutoff = self._seal_cutoff(r, now)
                sealed = sorted(
                    bid for bid in self._rungs[r] if bid < cutoff
                )
                cur_ids = stack.ids()
                if cur_ids == sealed:
                    continue
                sealed_set = set(sealed)
                gone = [b for b in cur_ids if b not in sealed_set]
                keep = cur_ids[len(gone):]
                if cur_ids[: len(gone)] == gone \
                        and sealed[: len(keep)] == keep:
                    for bid in gone:
                        stack.evict(bid)
                    for bid in sealed[len(keep):]:
                        stack.push(bid, self._rungs[r][bid].state)
                else:
                    # Non-incremental drift (a ring merge or restore
                    # slipped past the invalidate hooks): rebuild this
                    # rung's stack from scratch, zero upfront merges.
                    fresh = _TwoStacks(self, r)
                    for bid in sealed:
                        fresh.push(bid, self._rungs[r][bid].state)
                    self._agg_stacks[r] = fresh
                    self._agg_rebuilds += 1
                    if telemetry._ACTIVE:
                        telemetry.counter_inc("window.agg_rebuilds")
        except Exception as e:  # noqa: BLE001 - derived state must degrade
            self._agg_stacks = None
            bump("window.stack_torn")
            record_downgrade(
                "windows.agg", "two-stacks", "rebuild",
                reason=f"stack sync torn: {e!r}",
            )

    def _agg_assemble(self, covered):
        """Assemble the maintained-component list for a covered-bucket
        plan -> ``(components, recipes)`` -- or ``(None, None)`` when
        the maintained path cannot serve it (stacks dropped mid-plan).

        Component order is PINNED (the other half of the tree-shape
        contract): rungs coarsest to finest -- per rung one maintained
        sealed aggregate (when the covered sealed ids form the stack's
        newest suffix) then the absorbing raw buckets in id order --
        and the live bucket last.  Each recipe names the ``covered``
        indices its component folds, so the oracle replays the exact
        association from the raw states."""
        stacks = self._agg_stacks
        if stacks is None:
            return None, None
        components: List[Any] = []
        recipes: List[Tuple] = []
        by_rung: Dict[int, List[int]] = {}
        live_idx: Optional[int] = None
        for i, (r, _bid, _st, b) in enumerate(covered):
            if b is None:
                live_idx = i
            else:
                by_rung.setdefault(r, []).append(i)
        for r in sorted(by_rung, reverse=True):
            stack = stacks[r]
            stacked = set(stack.ids())
            sealed = [i for i in by_rung[r] if covered[i][1] in stacked]
            loose = [i for i in by_rung[r] if covered[i][1] not in stacked]
            if sealed:
                ids_cov = [covered[i][1] for i in sealed]
                sids = stack.ids()
                hit = None
                if sids[-len(ids_cov):] == ids_cov:
                    before = (
                        self._agg_maint_merges + self._agg_query_merges
                    )
                    hit = stack.suffix(ids_cov[0])
                if hit is not None:
                    state, front_ids, back_ids = hit
                    if before == (
                        self._agg_maint_merges + self._agg_query_merges
                    ):
                        self._agg_reuse += 1
                        if telemetry._ACTIVE:
                            telemetry.counter_inc("window.agg_reuse")
                    idx_of = {covered[i][1]: i for i in sealed}
                    components.append(state)
                    recipes.append((
                        "fold", r,
                        tuple(idx_of[b] for b in front_ids),
                        tuple(idx_of[b] for b in back_ids),
                    ))
                else:
                    # Covered sealed ids are not the stack's newest
                    # suffix (a window ending in the past would do
                    # this); fall back to raw buckets for this rung.
                    loose = sealed + loose
            for i in sorted(loose, key=lambda i: covered[i][1]):
                components.append(covered[i][2])
                recipes.append(("raw", i))
        if live_idx is not None:
            components.append(covered[live_idx][2])
            recipes.append(("raw", live_idx))
        return tuple(components), tuple(recipes)

    def _agg_fold(self, plan: "WindowPlan"):
        """Fold a maintained-component plan to ONE state, cached by the
        plan digest.  The digest hashes every covered bucket's
        ``(rung, id, fingerprint)``, so any rotation, ingest, or
        restore moves it -- a stale entry can only MISS, never answer
        wrong.  A hit is the O(1)-merges endgame: a repeat query on an
        unchanged window decodes straight from the cached folded state,
        zero merges -- the same single-state dispatch a plain
        unwindowed facade pays.  The fold itself reuses the per-mode
        fold-to-state jits, so the merge-tree shape (and hence the
        bit-exact answer) is identical to the fused fold+quantile
        path."""
        states = plan.components
        if len(states) == 1:
            return states[0]
        cached = self._agg_fold_cache
        if cached is not None and cached[0] == plan.digest:
            self._agg_reuse += 1
            if telemetry._ACTIVE:
                telemetry.counter_inc("window.agg_reuse")
            return cached[1]
        mode = _fold_mode(self.spec, states)
        folded = _fold_state_for(self.spec)[mode](states)
        self._agg_query_merges += len(states) - 1
        if telemetry._ACTIVE:
            telemetry.counter_inc(
                "window.query_merges", float(len(states) - 1)
            )
        self._agg_fold_cache = (
            plan.digest, folded, states, mode,
            self._agg_decode_facade(folded),
        )
        return folded

    def _agg_decode_facade(self, folded):
        """Wrap a folded dense window state in a throwaway facade so a
        fold-cache HIT decodes through the facade's engine ladder (the
        state-window-planned quantile the single-sketch baseline pays)
        instead of the full-width decode -- the tiers are answer-
        identical, so bit-identity to the oracle is unchanged.  Non-
        dense and mesh-sharded states decode through their own
        single-state twins; returns None for those."""
        if self.spec.backend != "dense" or self._distributed:
            return None
        return batched.BatchedDDSketch(
            self._n_streams, spec=self.spec, state=folded
        )

    def _agg_corrupt(self, flips) -> bool:
        """Apply ``window.agg_stale`` flip coordinates to the first
        cached maintained aggregate (raw bucket states stay clean --
        only the stack-consistency audit can catch the divergence).
        The folded-window cache is corrupted first when present: it is
        the most query-visible cached aggregate.  Returns whether
        anything was corrupted; moment states carry no bin stores to
        flip, so the site no-ops there."""
        if not flips or self._agg_stacks is None \
                or self.spec.backend == "moment":
            return False

        def corrupt(st):
            if self.spec.backend == "uniform_collapse":
                return dataclasses.replace(
                    st, base=faults.apply_state_bitflips(st.base, flips)
                )
            return faults.apply_state_bitflips(st, flips)

        if self._agg_fold_cache is not None:
            digest, folded, states, mode, _fac = self._agg_fold_cache
            bad = corrupt(folded)
            # Rebuild the decode facade around the corrupted state so
            # the corruption stays query-visible, not just audit-visible.
            self._agg_fold_cache = (
                digest, bad, states, mode, self._agg_decode_facade(bad)
            )
            return True
        for stack in self._agg_stacks:
            if stack._combined:
                bid, (n, st) = sorted(stack._combined.items())[0]
                stack._combined[bid] = (n, corrupt(st))
                return True
            if stack._tails:
                bid, (n, st) = sorted(stack._tails.items())[0]
                stack._tails[bid] = (n, corrupt(st))
                return True
            if stack.front:
                bid, raw, sfx = stack.front[0]
                stack.front[0] = (bid, raw, corrupt(sfx))
                return True
        return False

    def _agg_audit(self) -> List[str]:
        """Stack-consistency audit: recompute every CACHED maintained
        aggregate from its raw constituent states through the identical
        merge tree and compare content leaf-for-leaf exactly (the
        recomputation is deterministic, so a clean cache matches
        bit-for-bit; the weighted-sum fingerprint digest would absorb a
        low-bit flip on an empty bin into float64 rounding, so the
        audit compares the raw buffers instead).  Returns violation
        detail strings; disabled or dropped stacks audit clean (there
        is nothing cached to trust).  Never mutates the ring."""
        out: List[str] = []
        if not self._agg_enabled or self._agg_stacks is None:
            return out

        def mismatch(expect, got) -> bool:
            ea, ga = jax.tree.leaves(expect), jax.tree.leaves(got)
            return len(ea) != len(ga) or any(
                not np.array_equal(
                    np.asarray(jax.device_get(x)),
                    np.asarray(jax.device_get(y)),
                )
                for x, y in zip(ea, ga)
            )

        for stack in self._agg_stacks:
            r = stack.rung
            # Front suffixes: suffix[j] == right fold of front raws [j:].
            acc = None
            for bid, raw, sfx in reversed(stack.front):
                acc = raw if acc is None else self._merge_states(raw, acc)
                if mismatch(acc, sfx):
                    out.append(
                        f"rung {r} front suffix @{bid} diverges from its"
                        " raw right-fold"
                    )
            # Back tails: _tails[bid] == left fold of back raws from bid.
            back_pos = {b: t for t, (b, _s) in enumerate(stack.back)}
            for bid, (n, st) in sorted(stack._tails.items()):
                t = back_pos.get(bid)
                if t is None or t + n > len(stack.back):
                    out.append(f"rung {r} back tail @{bid} orphaned")
                    continue
                acc = stack.back[t][1]
                for i in range(t + 1, t + n):
                    acc = self._merge_states(acc, stack.back[i][1])
                if mismatch(acc, st):
                    out.append(
                        f"rung {r} back tail @{bid} diverges from its"
                        " raw left-fold"
                    )
            # Combined: _combined[bid] == suffix(bid) + left fold of
            # the first ``n`` back raws (the recorded back length).
            front_pos = {b: j for j, (b, _r, _s) in enumerate(stack.front)}
            for bid, (n, st) in sorted(stack._combined.items()):
                j = front_pos.get(bid)
                if j is None or n > len(stack.back) or n < 1:
                    out.append(f"rung {r} combined @{bid} orphaned")
                    continue
                acc = None
                for fbid, raw, _s in reversed(stack.front[j:]):
                    acc = (
                        raw if acc is None
                        else self._merge_states(raw, acc)
                    )
                tail = stack.back[0][1]
                for i in range(1, n):
                    tail = self._merge_states(tail, stack.back[i][1])
                exp = self._merge_states(acc, tail)
                if mismatch(exp, st):
                    out.append(
                        f"rung {r} combined @{bid} diverges from its"
                        " raw fold"
                    )
        cache = self._agg_fold_cache
        if cache is not None:
            _digest, folded, comp_states, mode, _fac = cache
            exp = _fold_state_for(self.spec)[mode](comp_states)
            if mismatch(exp, folded):
                out.append(
                    "folded-window cache diverges from its component"
                    " re-fold"
                )
        return out

    def agg_stats(self) -> Dict[str, float]:
        """The maintained-aggregate scoreboard: whether the layer is on,
        merges spent maintaining the stacks (flips + tail extensions --
        the <= 2-per-rotation amortized budget the tests pin), merges
        spent answering queries, component reuses, and stack rebuilds.
        Never raises."""
        return {
            "enabled": float(self._agg_enabled),
            "maintenance_merges": float(self._agg_maint_merges),
            "query_merges": float(self._agg_query_merges),
            "reuse": float(self._agg_reuse),
            "rebuilds": float(self._agg_rebuilds),
        }

    # -- rotation ----------------------------------------------------------

    def _roll(self, now: float) -> None:
        """Advance the ring to ``now``: freeze an aged-out live bucket,
        cascade retirements down the ladder, drop mass off the last
        rung.  Plans functionally, injects ``window.rotate_torn``, then
        commits by reference swap -- a tear mutates nothing."""
        cur = self._id_at(0, now)
        if cur == self._cur and (
            self._live_id is None or self._live_id == cur
        ):
            return
        freeze = (
            self._live_id is not None and self._live_id != cur
        )
        new_rungs = [dict(r) for r in self._rungs]
        rotations = 0
        collapses = 0
        retired = 0.0
        retired_buckets: List[Tuple[int, int]] = []
        if freeze:
            state = self._snapshot_state(self._live.state)
            new_rungs[0][self._live_id] = _Bucket(
                rung=0, id=self._live_id, state=state,
                mass=self._live_mass,
            )
            rotations += 1
        # Cascade: rung r keeps its newest ``lengths[r]`` slices; older
        # buckets fold into the coarser bucket covering their interval.
        levels = self.config.collapse_levels
        for r in range(self.config.n_rungs):
            cur_r = self._id_at(r, now)
            floor_id = cur_r - self.config.lengths[r] + 1
            for bid in sorted(new_rungs[r]):
                if bid >= floor_id:
                    continue
                b = new_rungs[r].pop(bid)
                retired_buckets.append((r, bid))
                if r + 1 >= self.config.n_rungs:
                    retired += b.mass
                    continue
                state = b.state
                if levels is not None and levels[r + 1] > 0:
                    from sketches_tpu.backends import uniform

                    state = uniform.collapse_to(
                        self.spec, state,
                        jnp.maximum(
                            state.level, jnp.int32(levels[r + 1])
                        ),
                    )
                    collapses += 1
                start, _ = self._interval(r, bid)
                tgt = self._id_at(r + 1, start)
                existing = new_rungs[r + 1].get(tgt)
                if existing is None:
                    new_rungs[r + 1][tgt] = _Bucket(
                        rung=r + 1, id=tgt, state=state, mass=b.mass
                    )
                else:
                    new_rungs[r + 1][tgt] = _Bucket(
                        rung=r + 1, id=tgt,
                        state=self._merge_states(existing.state, state),
                        mass=existing.mass + b.mass,
                    )
        if faults._ACTIVE:
            # The adversary's window: everything above is functional
            # (new dicts, new states); nothing observable has mutated
            # yet, so a tear here proves rotation atomicity.
            faults.inject(faults.WINDOW_ROTATE_TORN)
        # -- commit (reference swaps only) --
        self._rungs = new_rungs
        if freeze:
            self._reset_live()
            self._live_id = None
            self._live_mass = 0.0
        self._cur = cur
        self._rotations += rotations
        self._ladder_collapses += collapses
        self._retired += retired
        self._version += 1
        self._live_fp = None
        # Content moved between buckets -> the plan digest moves; the
        # folded-window cache could only miss, so drop it now.
        self._agg_fold_cache = None
        if telemetry._ACTIVE:
            if rotations:
                telemetry.counter_inc("window.rotations", float(rotations))
            if collapses:
                telemetry.counter_inc(
                    "window.ladder_collapses", float(collapses)
                )
            if retired:
                telemetry.counter_inc("window.retired_mass", retired)
        # Stacks sync strictly AFTER the commit: a torn rotation above
        # never sees half-updated aggregates, and a torn sync here only
        # drops derived state (rebuilt lazily), never the ring.
        self._agg_sync(now)

    # -- write path --------------------------------------------------------

    def add(self, values, weights=None) -> "WindowedSketch":
        """Ingest ``values[n_streams, S]`` into the current time
        slice's bucket; returns self for chaining.

        Rotates first (the injectable clock decides the bucket), then
        rides the backend facade's ingest unchanged -- engine ladder,
        degradations, and refusals are exactly the facade's.  The exact
        batch mass (positive weights; truncated in integer-bin mode)
        lands in the bucket's ledger entry.
        """
        now = self._clock()
        self._roll(now)
        if self._live_id is None:
            self._live_id = self._id_at(0, now)
        self._live.add(values, weights)
        mass = _batch_mass(self.spec, values, weights)
        self._live_mass += mass
        self._total += mass
        self._version += 1
        self._live_fp = None
        # Ingest donates the live state's buffers and moves the plan
        # digest, so a cached folded window is both dead (can only
        # miss) and unsafe to re-audit: drop it.
        self._agg_fold_cache = None
        return self

    def merge(self, other: "WindowedSketch") -> "WindowedSketch":
        """Fold another windowed ring into this one (same spec, same
        ladder config, clock-aligned bucket ids) -- the cross-host fold
        for windowed fleets: every bucket merges with its same-id twin
        through the backend merge algebra, ledgers add exactly.
        Unequal specs or configs raise
        ``UnequalSketchParametersError``.
        """
        if other.spec != self.spec or other.config != self.config:
            raise UnequalSketchParametersError(
                "cannot merge windowed sketches with different specs or"
                " ladder configs"
            )
        for r in range(self.config.n_rungs):
            for bid, b in sorted(other._rungs[r].items()):
                mine = self._rungs[r].get(bid)
                if mine is None:
                    self._rungs[r][bid] = _Bucket(
                        rung=r, id=bid, state=b.state, mass=b.mass
                    )
                else:
                    self._rungs[r][bid] = _Bucket(
                        rung=r, id=bid,
                        state=self._merge_states(mine.state, b.state),
                        mass=mine.mass + b.mass,
                    )
        if other._live_id is not None:
            if self._live_id is None:
                self._live_id = other._live_id
                self._set_live_state(other._live.state)
                self._live_mass = other._live_mass
            elif self._live_id == other._live_id:
                self._set_live_state(
                    self._merge_states(
                        self._live.state, other._live.state
                    )
                )
                self._live_mass += other._live_mass
            else:
                # Different current slices: the other's live bucket is
                # frozen history from this ring's point of view.
                self._rungs[0][other._live_id] = _Bucket(
                    rung=0, id=other._live_id,
                    state=other._live.state, mass=other._live_mass,
                )
        self._total += other._total
        self._retired += other._retired
        self._version += 1
        self._live_fp = None
        # A ring merge rewrites sealed states in place (same-id twins
        # fold); the stacks hold stale references -- drop and rebuild.
        self._agg_invalidate()
        return self

    def reshard(self, mesh=None, n_devices: Optional[int] = None,
                *, live_mask=None):
        """Resize a mesh-backed live bucket LIVE -> its
        ``ReshardReport``; frozen buckets are topology-free merged
        states and survive untouched.

        Raises ``SpecError`` for a non-distributed ring; a torn reshard
        (injected) raises and leaves the live fleet bit-identical --
        reshard stays atomic even mid-rotation.  Dropped mass (dead
        shards) is subtracted from the live bucket's ledger entry and
        from ``total_mass`` exactly, so the ledger survives lossy
        reshards too.
        """
        if not self._distributed:
            raise SpecError(
                "reshard needs a mesh-backed WindowedSketch (pass"
                " mesh=/value_axis= at construction)"
            )
        new_facade, report = self._live.reshard(
            mesh=mesh, n_devices=n_devices, live_mask=live_mask
        )
        self._live = new_facade
        self._mesh = getattr(new_facade, "_sketch_mesh", self._mesh)
        if report.n_dead:
            dropped = float(
                np.asarray(report.dropped_count, np.float64).sum()
            )
            self._live_mass -= dropped
            self._total -= dropped
        self._version += 1
        self._live_fp = None
        return report

    # -- read path ---------------------------------------------------------

    def _covered(
        self, window_s: Optional[float], now: float
    ) -> List[Tuple[int, int, Any, Optional[_Bucket]]]:
        """Buckets whose time slice intersects ``[now - W, now)`` in
        deterministic (start time, rung) order -> list of
        ``(rung, id, state, bucket-or-None-for-live)``."""
        t0 = -math.inf if window_s is None else now - float(window_s)
        out = []
        for r in range(self.config.n_rungs):
            for bid, b in self._rungs[r].items():
                start, end = self._interval(r, bid)
                if end > t0 and start <= now:
                    out.append((start, r, bid, b.state, b))
        if self._live_id is not None:
            # ``start <= now``: the current slice's bucket starts AT the
            # boundary when now sits exactly on it -- data ingested "right
            # now" is always part of "the last W seconds".
            start, end = self._interval(0, self._live_id)
            if end > t0 and start <= now:
                out.append((
                    start, 0, self._live_id,
                    self._snapshot_state(self._live.state), None,
                ))
        out.sort(key=lambda e: (e[0], e[1]))
        return [(r, bid, st, b) for _, r, bid, st, b in out]

    def _bucket_fp(self, bucket: Optional[_Bucket], state) -> np.ndarray:
        if bucket is not None:
            if bucket.fp is None:
                bucket.fp = integrity.fingerprint(self.spec, bucket.state)
            return bucket.fp
        cached = self._live_fp
        if cached is not None and cached[0] == self._version:
            return cached[1]
        fp = integrity.fingerprint(self.spec, state)
        self._live_fp = (self._version, fp)
        return fp

    def window_plan(self, window_s: Optional[float] = None) -> WindowPlan:
        """Resolve a window query: rotate, select the covered buckets,
        and derive the fingerprint-set digest -> a :class:`WindowPlan`.

        The digest is the cache-key contract the serving tier keys on:
        it hashes every covered bucket's ``(rung, id, fingerprint)``,
        so a rotation, an ingest, or any content change moves it -- a
        stale cache entry can only MISS, never read stale-wrong.  An
        empty coverage yields a plan whose query answers NaN.
        """
        now = self._clock()
        self._roll(now)
        components = recipes = None
        if self._agg_enabled:
            self._agg_sync(now)  # rebuild if dropped; no-op when current
            if faults._ACTIVE:
                flips = faults.agg_stale_flips(
                    self._n_streams, getattr(self.spec, "n_bins", 1)
                )
                if flips:
                    self._agg_corrupt(flips)
        covered = self._covered(window_s, now)
        if self._agg_enabled:
            components, recipes = self._agg_assemble(covered)
        fps = [self._bucket_fp(b, st) for (_, _, st, b) in covered]
        h = hashlib.sha256()
        h.update(b"window")
        for (r, bid, _, _), fp in zip(covered, fps):
            h.update(np.int64(r).tobytes())
            h.update(np.int64(bid).tobytes())
            h.update(np.ascontiguousarray(fp).tobytes())
        fingerprint = (
            np.concatenate([np.atleast_1d(f) for f in fps])
            if fps else np.zeros((0,), np.float64)
        )
        if telemetry._ACTIVE:
            telemetry.gauge_set(
                "window.covered_buckets", float(len(covered))
            )
        return WindowPlan(
            window_s=window_s,
            now=now,
            keys=tuple((r, bid) for r, bid, _, _ in covered),
            states=tuple(st for _, _, st, _ in covered),
            fingerprint=fingerprint,
            digest=h.digest(),
            components=components,
            recipes=recipes,
        )


    def query_plan(self, plan: WindowPlan, quantiles: Sequence[float]):
        """Answer ``quantiles`` over a resolved :class:`WindowPlan` ->
        ``[n_streams, Q]`` (NaN for empty coverage / empty streams).
        The plan's states are frozen references, so a rotation between
        planning and dispatch cannot change the answer."""
        qs = tuple(float(q) for q in quantiles)
        if not plan.states:
            return np.full(
                (self._n_streams, len(qs)), np.nan,
                np.dtype(jnp.dtype(self.spec.dtype).name),
            )
        states = plan.states
        if plan.components is not None:
            # Maintained-aggregate path: fold the O(1) pre-merged
            # components once per plan digest, then decode from the
            # single folded state; a repeat query on an unchanged
            # window hits the fold cache and pays zero merges -- and
            # (dense) rides the cached facade's engine ladder, the
            # exact dispatch a plain unwindowed query pays.
            folded = self._agg_fold(plan)
            cache = self._agg_fold_cache
            if (
                cache is not None
                and cache[0] == plan.digest
                and cache[4] is not None
            ):
                return cache[4].get_quantile_values(qs)
            return _quantile_for(self.spec)(
                folded, jnp.asarray(qs, self.spec.dtype)
            )
        mode = _fold_mode(self.spec, states)
        return _fold_for(self.spec)[mode](
            states, jnp.asarray(qs, self.spec.dtype)
        )

    def quantile(
        self, quantiles: Sequence[float],
        window: Optional[float] = None,
    ):
        """``quantile(qs, window=W)``: the fused window query ->
        ``[n_streams, Q]``.

        ``window=None`` covers the whole retained horizon.  Bit-
        identical to a host-side sequential merge of the covered
        buckets (the tested oracle); empty windows/streams answer NaN.
        """
        return self.query_plan(self.window_plan(window), quantiles)

    def get_quantile_values(self, quantiles: Sequence[float]):
        """Facade-parity alias: full-horizon fused multi-quantile ->
        ``[n_streams, Q]`` (NaN when empty)."""
        return self.quantile(quantiles, window=None)

    # -- introspection -----------------------------------------------------

    @property
    def n_streams(self) -> int:
        return self._n_streams

    @property
    def total_mass(self) -> float:
        """Exact mass ever ingested (minus reshard-dropped mass);
        equals live ledger + ``retired_mass`` -- the invariant
        :func:`sketches_tpu.integrity.check_window` verifies with
        ``==``.  Never raises."""
        return self._total

    @property
    def retired_mass(self) -> float:
        """Exact mass dropped off the last ladder rung; never raises."""
        return self._retired

    def buckets(self) -> List[Tuple[int, int, float]]:
        """The live ledger: ``(rung, bucket id, exact mass)`` per live
        bucket (the current ingest bucket included), coverage-ordered.
        Empty before the first ingest; never raises."""
        out = [
            (r, bid, b.mass)
            for r in range(self.config.n_rungs)
            for bid, b in sorted(self._rungs[r].items())
        ]
        if self._live_id is not None:
            out.append((0, self._live_id, self._live_mass))
        return sorted(out, key=lambda e: (e[0], e[1]))

    def ledger(self) -> Dict[str, float]:
        """The mass ledger summary: ``total`` (ever ingested),
        ``live`` (sum of live bucket entries), ``retired`` (dropped off
        the last rung), ``rotations``, ``ladder_collapses``.  The exact
        invariant is ``total == live + retired``; never raises."""
        live = sum(m for _, _, m in self.buckets())
        return {
            "total": self._total,
            "live": live,
            "retired": self._retired,
            "rotations": float(self._rotations),
            "ladder_collapses": float(self._ladder_collapses),
        }

    def rung_effective_alpha(self) -> List[float]:
        """Each rung's declared accuracy contract: the worst-case
        relative error of a bucket that has been coarsened into that
        rung (``uniform_collapse``: ``effective_alpha`` at the rung's
        collapse level; other backends: the spec alpha everywhere).
        Never raises."""
        if (
            self.spec.backend == "uniform_collapse"
            and self.config.collapse_levels is not None
        ):
            from sketches_tpu.backends.uniform import effective_alpha

            return [
                float(
                    np.asarray(
                        effective_alpha(
                            self.spec, jnp.int32(level)
                        )
                    )
                )
                for level in self.config.collapse_levels
            ]
        return [
            self.spec.relative_accuracy
            for _ in range(self.config.n_rungs)
        ]

    def device_masses(self) -> Dict[Tuple[int, int], float]:
        """Per-bucket device-side mass (sum of each bucket state's
        ``count``) -- the audit-side twin of :meth:`buckets` the chaos
        campaign compares with ``==``.  Forces a device fetch per
        bucket; empty ring returns ``{}``; never raises."""
        out: Dict[Tuple[int, int], float] = {}
        for r in range(self.config.n_rungs):
            for bid, b in self._rungs[r].items():
                count = getattr(b.state, "count", None)
                if count is None:  # pragma: no cover - defensive
                    continue
                out[(r, bid)] = float(
                    np.asarray(jax.device_get(count), np.float64).sum()
                )
        if self._live_id is not None:
            out[(0, self._live_id)] = float(
                np.asarray(
                    jax.device_get(self._live.count), np.float64
                ).sum()
            )
        return out

    def __repr__(self) -> str:
        return (
            f"WindowedSketch(n_streams={self._n_streams},"
            f" backend={self.spec.backend!r},"
            f" rungs={[f'{s:g}s x {n}' for s, n in zip(self.config.slices_s, self.config.lengths)]},"
            f" live_buckets={len(self.buckets())},"
            f" total_mass={self._total:g})"
        )


#: Oracle-side jitted quantile per spec (the decode any facade query
#: would run; cached so repeated oracle audits do not recompile).
_ORACLE_Q_CACHE: Dict[SketchSpec, Callable] = {}


def oracle_quantile(
    wsk: WindowedSketch,
    quantiles: Sequence[float],
    window: Optional[float] = None,
):
    """The host-driven oracle: sequentially merge the covered buckets
    with the backend's own merge (one eager dispatch per pair) and
    answer the fused quantile -> ``[n_streams, Q]``.

    The windowed query must be bit-identical to this -- the exactness
    contract ``tests/test_windows.py`` and the chaos campaign pin.
    Under the maintained-aggregate path (``SKETCHES_TPU_WINDOW_AGG=1``)
    the oracle replays the plan's component recipes EAGERLY from the
    raw covered states -- right fold over each sealed front leg, left
    fold over each back leg, then the component chain -- the identical
    association the two-stacks layer maintains, so bit-identity holds
    by symmetry whether an answer came from cache or was just rebuilt.
    Empty coverage answers NaN like the query itself; never mutates
    the ring beyond the same rotation the query would perform.
    """
    plan = wsk.window_plan(window)
    qs = tuple(float(q) for q in quantiles)
    if not plan.states:
        return np.full(
            (wsk.n_streams, len(qs)), np.nan,
            np.dtype(jnp.dtype(wsk.spec.dtype).name),
        )
    spec = wsk.spec
    if plan.recipes is not None:
        comps = []
        for rcp in plan.recipes:
            if rcp[0] == "raw":
                comps.append(plan.states[rcp[1]])
                continue
            _, _r, front_idx, back_idx = rcp
            acc = None
            for i in reversed(front_idx):  # right fold over the front leg
                st = plan.states[i]
                acc = st if acc is None else wsk._merge_states(st, acc)
            tail = None
            for i in back_idx:  # left fold over the back leg
                st = plan.states[i]
                tail = st if tail is None else wsk._merge_states(tail, st)
            if acc is None:
                comps.append(tail)
            elif tail is None:
                comps.append(acc)
            else:
                comps.append(wsk._merge_states(acc, tail))
        states = tuple(comps)
    else:
        states = plan.states
    if _fold_mode(spec, states) == "aligned":
        # The identical host-side mode choice the fused fold makes:
        # aligned dense windows merge elementwise (no recenter rolls).
        acc = functools.reduce(
            functools.partial(batched.merge, spec), states
        )
    else:
        acc = functools.reduce(wsk._merge_states, states)
    if spec.backend == "moment":
        from sketches_tpu.backends import moment

        return moment.quantile(spec, acc, qs)
    # The merged state decodes through the standard JITTED quantile --
    # exactly what any facade query runs (the eager merge chain is
    # bit-identical to the fused fold's; quantile is always a jitted
    # dispatch in this library, so the oracle holds it to that).
    qfn = _ORACLE_Q_CACHE.get(spec)
    if qfn is None:
        if spec.backend == "uniform_collapse":
            from sketches_tpu.backends import uniform

            qfn = jax.jit(functools.partial(uniform.quantile, spec))
        else:
            qfn = jax.jit(functools.partial(batched.quantile, spec))
        _ORACLE_Q_CACHE[spec] = qfn
    return qfn(acc, jnp.asarray(qs, spec.dtype))
