"""Protobuf serialization: the cross-language wire format + checkpoints.

Reference seams: ``ddsketch/pb/ddsketch.proto``, ``ddsketch/pb/proto.py``
(SURVEY.md section 2 rows 6-8).  Kept at the host edge: device state is
``device_get`` into numpy first, then encoded (SURVEY.md section 3.5).
"""

from sketches_tpu.pb.proto import (
    DDSketchProto,
    KeyMappingProto,
    StoreProto,
    batched_from_bytes,
    batched_from_proto,
    batched_to_bytes,
    batched_to_proto,
)

__all__ = [
    "DDSketchProto",
    "KeyMappingProto",
    "StoreProto",
    "batched_to_proto",
    "batched_from_proto",
    "batched_to_bytes",
    "batched_from_bytes",
]
