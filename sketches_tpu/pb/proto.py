"""Bridge between sketch objects and the DDSketch protobuf wire format.

Parity target: reference ``ddsketch/pb/proto.py`` (``DDSketchProto``,
``KeyMappingProto``, ``StoreProto`` -- SURVEY.md section 2 row 7): the
interpolation enum maps to the mapping subclass, dense store runs map to
``contiguousBinCounts`` + offset.  Additions for the device tier:
``batched_to_proto`` / ``batched_from_proto`` serialize every stream of a
``[n_streams, n_bins]`` batch (via the host-interop layer), so protobuf
remains the cross-language edge while bulk checkpoints use
``sketches_tpu.checkpoint``'s array format.
"""

from __future__ import annotations

from typing import List

from sketches_tpu.ddsketch import BaseDDSketch, DDSketch
from sketches_tpu.resilience import SketchValueError, WireDecodeError
from sketches_tpu.mapping import (
    CubicallyInterpolatedMapping,
    KeyMapping,
    LinearlyInterpolatedMapping,
    LogarithmicMapping,
    QuadraticallyInterpolatedMapping,
)
from sketches_tpu.store import DenseStore, Store

from sketches_tpu.pb import ddsketch_pb2 as pb

__all__ = [
    "KeyMappingProto",
    "StoreProto",
    "DDSketchProto",
    "batched_to_proto",
    "batched_from_proto",
    "batched_to_bytes",
    "batched_from_bytes",
]

_INTERPOLATION_TO_MAPPING = {
    pb.IndexMapping.NONE: LogarithmicMapping,
    pb.IndexMapping.LINEAR: LinearlyInterpolatedMapping,
    pb.IndexMapping.QUADRATIC: QuadraticallyInterpolatedMapping,
    pb.IndexMapping.CUBIC: CubicallyInterpolatedMapping,
}
_MAPPING_TO_INTERPOLATION = {
    LogarithmicMapping: pb.IndexMapping.NONE,
    LinearlyInterpolatedMapping: pb.IndexMapping.LINEAR,
    QuadraticallyInterpolatedMapping: pb.IndexMapping.QUADRATIC,
    CubicallyInterpolatedMapping: pb.IndexMapping.CUBIC,
}


class KeyMappingProto:
    """mapping <-> IndexMapping{gamma, indexOffset, interpolation}."""

    @classmethod
    def to_proto(cls, mapping: KeyMapping) -> pb.IndexMapping:
        try:
            interpolation = _MAPPING_TO_INTERPOLATION[type(mapping)]
        except KeyError:
            raise SketchValueError(
                f"No proto interpolation for mapping {type(mapping).__name__}"
            ) from None
        return pb.IndexMapping(
            gamma=mapping.gamma,
            indexOffset=mapping._offset,
            interpolation=interpolation,
        )

    @classmethod
    def from_proto(
        cls, proto: pb.IndexMapping, *, assume_native_linear: bool = False
    ) -> KeyMapping:
        """Decode an IndexMapping.

        NONE (exact logarithmic), QUADRATIC, and CUBIC decode
        unconditionally: their key functions are mathematically forced by
        the (gamma, interpolation) pair -- ``ceil(log_gamma v)``, the
        unique alpha-optimal quadratic s*(4-s)/3 with the 3/4 multiplier
        correction (see ``mapping.QuadraticallyInterpolatedMapping`` for
        the forcing argument), and the A/B/C cubic with the 7/10 multiplier
        correction -- so same-enum emitters agree on bucket boundaries.

        LINEAR **raises by default**: this implementation's linear mapping
        keeps the base 1/ln(gamma) multiplier UNSCALED (alpha-safe -- see
        ``mapping.LinearlyInterpolatedMapping``), and whether upstream
        family emitters share that convention could not be verified against
        a reference tree (SURVEY.md provenance warning).  Decoding foreign
        LINEAR bins with a mismatched key function would silently return
        wrong quantiles -- a loud error is the only safe default.  Pass
        ``assume_native_linear=True`` to decode bytes KNOWN to be produced
        by this library's own LINEAR mapping (round-trips are tested).
        """
        try:
            mapping_cls = _INTERPOLATION_TO_MAPPING[proto.interpolation]
        except KeyError:
            # proto3 open enums parse unknown values through: refuse
            # LOUDLY, naming the enum and the value -- decoding bins
            # under a guessed key function would silently corrupt every
            # quantile (same forward-compat contract as the
            # SketchPayload.Backend enum in backends.wirefmt).
            known = sorted(int(v) for v in _INTERPOLATION_TO_MAPPING)
            raise WireDecodeError(
                "unknown IndexMapping.Interpolation enum value"
                f" {int(proto.interpolation)}: refusing to decode"
                f" (emitter is newer than this reader; known values"
                f" {known})"
            ) from None
        if (
            mapping_cls is LinearlyInterpolatedMapping
            and not assume_native_linear
        ):
            raise WireDecodeError(
                "Refusing to decode a LINEAR IndexMapping from foreign"
                " bytes: the linear-interpolation key-multiplier convention"
                " is implementation-defined and a mismatch silently"
                " misdecodes every bin.  If these bytes were produced by"
                " sketches_tpu itself, pass assume_native_linear=True."
                " (LOG and CUBIC interop are convention-free and decode"
                " unconditionally.)"
            )
        # Invert gamma = (1 + alpha) / (1 - alpha).
        relative_accuracy = (proto.gamma - 1.0) / (proto.gamma + 1.0)
        return mapping_cls(relative_accuracy, offset=proto.indexOffset)


class StoreProto:
    """store <-> Store{contiguousBinCounts, contiguousBinIndexOffset}.

    Encodes the dense run; decodes both the dense run and the sparse
    ``binCounts`` map (other languages may emit either).
    """

    @classmethod
    def to_proto(cls, store: Store) -> pb.Store:
        if not isinstance(store, DenseStore):
            raise TypeError(f"Cannot serialize {type(store).__name__}")
        return pb.Store(
            contiguousBinCounts=store.bins,
            contiguousBinIndexOffset=store.offset,
        )

    @classmethod
    def merge_into(cls, proto: pb.Store, store: Store) -> None:
        """Decode ``proto``'s mass into an existing store (additive)."""
        for key, weight in proto.binCounts.items():
            store.add(key, weight)
        for i, weight in enumerate(proto.contiguousBinCounts):
            if weight > 0:
                store.add(i + proto.contiguousBinIndexOffset, weight)


class DDSketchProto:
    """sketch <-> DDSketch{mapping, positiveValues, negativeValues, zeroCount}.

    Note (matching reference behavior): count/min/max/sum bookkeeping is not
    part of the wire format; ``from_proto`` reconstructs ``count`` from bin
    masses, while min/max/sum/avg are undefined on a decoded sketch.
    """

    @classmethod
    def to_proto(cls, sketch: BaseDDSketch) -> pb.DDSketch:
        return pb.DDSketch(
            mapping=KeyMappingProto.to_proto(sketch.mapping),
            positiveValues=StoreProto.to_proto(sketch.store),
            negativeValues=StoreProto.to_proto(sketch.negative_store),
            zeroCount=sketch.zero_count,
        )

    @classmethod
    def from_proto(
        cls, proto: pb.DDSketch, *, assume_native_linear: bool = False
    ) -> DDSketch:
        mapping = KeyMappingProto.from_proto(
            proto.mapping, assume_native_linear=assume_native_linear
        )
        sketch = DDSketch(mapping.relative_accuracy)
        sketch._mapping = mapping
        sketch._relative_accuracy = mapping.relative_accuracy
        StoreProto.merge_into(proto.positiveValues, sketch.store)
        StoreProto.merge_into(proto.negativeValues, sketch.negative_store)
        sketch._zero_count = proto.zeroCount
        sketch._count = (
            sketch.store.count + sketch.negative_store.count + proto.zeroCount
        )
        return sketch


def batched_to_bytes(spec, state) -> List[bytes]:
    """Serialize every stream of a device batch straight to wire BYTES --
    the bulk fast path (VERDICT r4 item 2): a vectorized encoder emitting
    protobuf output byte-identical to ``to_proto + SerializeToString``
    without materializing host sketches or message objects.

    Non-dense backends (``spec.backend`` of ``uniform_collapse`` /
    ``moment``) emit backend-tagged ``SketchPayload`` envelopes instead
    (``sketches_tpu.backends.wirefmt``) -- self-describing, refused
    loudly by readers that do not know the backend enum value; a state
    type that disagrees with the spec's backend raises ``SpecError``.
    """
    if getattr(spec, "backend", "dense") != "dense":
        from sketches_tpu.backends.wirefmt import payload_to_bytes

        return payload_to_bytes(spec, state)
    from sketches_tpu.pb.wire import state_to_bytes

    return state_to_bytes(spec, state)


def batched_to_proto(spec, state) -> List[pb.DDSketch]:
    """Serialize every stream of a device batch to wire-format messages.

    Message objects come from parsing the vectorized encoder's bytes with
    the C++ ``FromString`` (~2 us/stream) rather than Python field
    assembly (~100 us/stream through host sketches -- VERDICT r4 item 2);
    the resulting messages are identical (the bytes are).
    """
    return [pb.DDSketch.FromString(b) for b in batched_to_bytes(spec, state)]


def batched_from_proto(
    spec, protos, *, assume_native_linear: bool = False
) -> "SketchState":  # noqa: F821
    """Decode wire-format messages into one device batch (keys clamp into
    the spec window, mass conserved)."""
    from sketches_tpu.pb.wire import protos_to_state

    return protos_to_state(
        spec, protos, assume_native_linear=assume_native_linear
    )


def batched_from_bytes(
    spec, blobs, *, assume_native_linear: bool = False
):
    """Decode raw wire blobs into one device batch -- the bulk fast path
    (foreign-emitter wire quirks handled by the C++ parser).

    Non-dense specs decode ``SketchPayload`` envelopes into their
    backend state (``AdaptiveState`` / ``MomentState``); an unknown
    backend enum value, a backend/spec mismatch, or structural damage
    raises ``WireDecodeError`` naming the problem.
    """
    if getattr(spec, "backend", "dense") != "dense":
        from sketches_tpu.backends.wirefmt import payload_from_bytes

        return payload_from_bytes(
            spec, blobs, assume_native_linear=assume_native_linear
        )
    from sketches_tpu.pb.wire import bytes_to_state

    return bytes_to_state(
        spec, blobs, assume_native_linear=assume_native_linear
    )
