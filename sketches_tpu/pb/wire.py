"""Vectorized bulk wire-format serde for batched sketch states.

The cross-language edge (SURVEY.md section 2 rows 6-7) at device scale.
``batched_to_proto`` / ``batched_from_proto`` used to materialize every
stream as a host-tier sketch and assemble protobuf python objects field by
field (~100 us/stream of Python -- 8.5-21 s per direction at 100k streams,
VERDICT r4 weak 3 / item 2).  This module replaces the per-stream work
with group-vectorized numpy:

* **encode** (:func:`state_to_bytes`): streams group by their store's
  chunk-padded run length; each group's payload bytes come from ONE fancy-
  indexed gather + ``tobytes`` (f64, C order -- row ``i``'s doubles are a
  contiguous slice), and the per-stream remainder is a handful of cached
  varints joined around the payload slices.  The output is
  **byte-identical** to ``DDSketchProto.to_proto(sk).SerializeToString()``
  over ``to_host_sketches`` (tested byte-for-byte in
  ``tests/test_wire_bulk.py``): same chunk-padded contiguous runs, same
  field order, same proto3 default-skipping.
* **decode** (:func:`bytes_to_state`): two interchangeable batch
  drivers behind one contract.  The **native driver** (r16, default
  when ``native/libddsketch_host.so`` carries the versioned wire-codec
  ABI) packs the batch into one buffer and hands the whole canonical
  walk -- prefix memcmp, store framing, varint/zigzag scanning,
  zero-padding trim, payload-offset extraction -- to ONE
  ``ddsk_wire_scan_dense`` call (``native/ddsketch_wire.cpp``), then
  group-scatters the returned (offset, length, window-start) arrays in
  numpy.  The **pure-Python driver** walks each blob with the
  hand-rolled parser plus a structural-template memo; it is both the
  fallback tier (no toolchain, ``SKETCHES_TPU_NATIVE=0``, stale ``.so``)
  and the semantic oracle the native driver is differential-fuzzed
  against.  Either way, anything non-canonical -- sparse ``binCounts``
  maps, unpacked repeated doubles, foreign field orders, unknown
  fields, damaged bytes -- falls back per-message to the C++
  ``FromString`` parser plus a careful scalar placement with identical
  semantics to ``batched.from_host_sketches`` (out-of-window mass folds
  into the edge bins with collapse counters), so both drivers produce
  bit-identical states and record-identical quarantine reports.
  Negative dense masses stay on the group path: ``place_block`` clips
  them with ``merge_into``-equivalent semantics (mass counted
  post-clip), so no fallback is needed for them.

Mapping gates are shared with ``pb.proto.KeyMappingProto``: LINEAR foreign
bytes refuse by default, unknown enum values raise, NONE/QUADRATIC/CUBIC
decode unconditionally.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence

import numpy as np

from sketches_tpu import faults, integrity, profiling, resilience, telemetry
from sketches_tpu.batched import (
    SketchSpec,
    SketchState,
    arrays_to_state,
    occupied_bounds_np,
)
from sketches_tpu.pb import ddsketch_pb2 as pb
from sketches_tpu.resilience import (
    BlobTooLarge,
    QuarantineReport,
    SketchValueError,
    UnequalSketchParametersError,
)

__all__ = ["state_to_bytes", "bytes_to_state", "protos_to_state"]

_CHUNK = 128  # DenseStore growth quantum (store.py CHUNK_SIZE)


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag32(n: int) -> int:
    return ((n << 1) ^ (n >> 31)) & 0xFFFFFFFF


class _VarintMemo(dict):
    """varint bytes memoized by value -- offsets/lengths repeat heavily."""

    def __missing__(self, n):
        b = self[n] = _varint(n)
        return b


def _mapping_field(spec: SketchSpec) -> bytes:
    """Serialized ``mapping`` field (1) -- identical for every stream, so
    built once per call through the SAME enum table the object bridge uses."""
    from sketches_tpu.pb.proto import _MAPPING_TO_INTERPOLATION

    mapping = spec.mapping
    interpolation = _MAPPING_TO_INTERPOLATION[type(mapping)]
    body = b"\x09" + struct.pack("<d", mapping.gamma)
    if mapping._offset:  # proto3 skips the 0.0 default
        body += b"\x11" + struct.pack("<d", mapping._offset)
    if interpolation:
        body += b"\x18" + _varint(interpolation)
    return b"\x0a" + _varint(len(body)) + body


# ---------------------------------------------------------------------------
# Encode
# ---------------------------------------------------------------------------


def _padded_payloads(src: np.ndarray, rows: np.ndarray, lo: np.ndarray, length: int) -> bytes:
    """Wire payload bytes for one same-padded-length group.

    Gathers ``length`` f64 columns starting at each row's run start in ONE
    fancy-indexed op.  Columns past ``n_bins`` read as zeros (the host
    store's chunk padding); columns inside the array but past the run are
    zeros already by the occupied-bounds invariant.  Row ``i``'s doubles
    are bytes ``[i*8L, (i+1)*8L)`` of the C-order buffer.
    """
    n_bins = src.shape[1]
    cols = lo[:, None] + np.arange(length)  # [k, L]
    valid = cols < n_bins
    block = src[rows[:, None], np.minimum(cols, n_bins - 1)].astype(np.float64)
    if not valid.all():
        block *= valid
    return block.tobytes()


def _encode_store_parts(src, plo, phi, koff, vmemo):
    """Per-stream store-field pieces for one store of the whole batch ->
    (header list, payload bytes list, offset-suffix list), to be joined
    around the group payload slices.  Empty stores get the canonical empty
    submessage (present, zero fields)."""
    n, n_bins = src.shape
    run = phi - plo + 1  # <= 0 for empty stores
    length = np.minimum(-(-run // _CHUNK) * _CHUNK, n_bins)
    offs = plo + koff
    headers: list = [None] * n
    payloads: list = [None] * n
    suffixes: list = [None] * n
    empty = phi < 0
    # Group streams by padded length; one gather + tobytes per group.
    for L in np.unique(length[~empty]):
        Li = int(L)
        rows = np.nonzero((length == L) & ~empty)[0]
        buf = _padded_payloads(src, rows, plo[rows], Li)
        packed_prefix = b"\x12" + vmemo[8 * Li]
        step = 8 * Li
        for g, i in enumerate(rows):
            off = int(offs[i])
            suffix = b"\x18" + vmemo[_zigzag32(off)] if off else b""
            body_len = len(packed_prefix) + step + len(suffix)
            headers[i] = vmemo[body_len] + packed_prefix
            payloads[i] = buf[g * step : (g + 1) * step]
            suffixes[i] = suffix
    return headers, payloads, suffixes, empty


def state_to_bytes(spec: SketchSpec, state: SketchState) -> List[bytes]:
    """Serialize every stream -> wire bytes, byte-identical to the object
    bridge's ``to_proto(...).SerializeToString()``."""
    import jax

    _t0 = telemetry.clock() if telemetry._ACTIVE else None
    _p0 = telemetry.clock() if profiling._ACTIVE else None
    if integrity._ACTIVE:
        # Guarded seam: refuse to ship a corrupted state onto the wire
        # (raise/quarantine per the armed mode).  The wire format itself
        # carries no checksum slot (upstream compatibility), so the
        # encode-side check is the last armed gate before the bytes
        # leave the process; ship integrity.fingerprint() out of band to
        # verify the other end.
        integrity.verify_state(spec, state, seam="wire.encode")

    bins_pos, bins_neg, zero, koff = (
        np.asarray(a)
        for a in jax.device_get(
            (state.bins_pos, state.bins_neg, state.zero_count, state.key_offset)
        )
    )
    koff = koff.astype(np.int64)
    plo, phi = occupied_bounds_np(bins_pos)
    nlo, nhi = occupied_bounds_np(bins_neg)
    mapping_field = _mapping_field(spec)
    vmemo = _VarintMemo()
    ph, pp, ps, pe = _encode_store_parts(
        bins_pos, plo.astype(np.int64), phi.astype(np.int64), koff, vmemo
    )
    nh, np_, ns, ne = _encode_store_parts(
        bins_neg, nlo.astype(np.int64), nhi.astype(np.int64), koff, vmemo
    )
    zero64 = zero.astype(np.float64)
    has_zero = zero64 != 0.0
    n = state.n_streams
    blobs = []
    empty_store = b"\x00"
    for i in range(n):
        parts = [mapping_field, b"\x12"]
        if pe[i]:
            parts.append(empty_store)
        else:
            parts += (ph[i], pp[i], ps[i])
        parts.append(b"\x1a")
        if ne[i]:
            parts.append(empty_store)
        else:
            parts += (nh[i], np_[i], ns[i])
        if has_zero[i]:
            parts.append(b"\x21" + struct.pack("<d", zero64[i]))
        blobs.append(b"".join(parts))
    if _t0 is not None:
        telemetry.finish_span("wire.encode_s", _t0)
        telemetry.counter_inc("wire.blobs_encoded", float(len(blobs)))
    if _p0 is not None:
        # The device_get above already synced; attribute the host-side
        # codec walk to the decode phase's encode tier.
        profiling.record("decode", "encode", _p0)
    return blobs


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------


def _read_varint(blob: bytes, i: int):
    r = 0
    shift = 0
    while True:
        b = blob[i]
        i += 1
        r |= (b & 0x7F) << shift
        if not b & 0x80:
            return r, i
        shift += 7


def _careful_place(arr, i, store_proto, base, n_bins):
    """Scalar placement with ``StoreProto.merge_into`` + window-clamp
    semantics (the from_host_sketches path) -> (mass, low fold, high fold).
    Dense entries place only when strictly positive; sparse map entries add
    unconditionally."""
    mass = low = high = 0.0
    counts = store_proto.contiguousBinCounts
    ln = len(counts)
    if ln:
        row = np.fromiter(counts, np.float64, ln)
        np.clip(row, 0.0, None, out=row)
        j0 = store_proto.contiguousBinIndexOffset - base
        mass = float(row.sum())
        lo_cut = max(0, -j0)
        hi_cut = max(0, min(ln, n_bins - j0))
        if lo_cut:
            low = float(row[:lo_cut].sum())
            arr[i, 0] += low
        if hi_cut < ln:
            high = float(row[hi_cut:].sum())
            arr[i, n_bins - 1] += high
        if hi_cut > lo_cut:
            arr[i, j0 + lo_cut : j0 + hi_cut] += row[lo_cut:hi_cut]
    for key, weight in store_proto.binCounts.items():
        mass += weight
        j = key - base
        if j < 0:
            arr[i, 0] += weight
            low += weight
        elif j >= n_bins:
            arr[i, n_bins - 1] += weight
            high += weight
        else:
            arr[i, j] += weight
    return mass, low, high


class _Decoder:
    """Accumulates one batch's decode: canonical runs group-vectorized,
    everything else through the careful scalar path.

    Memory discipline matters more than op count here: this host's kernel
    throttles anonymous-page faults ~10x once a process holds a few GB
    (measured 0.9 s -> 12.4 s for the same 2 GB memset as residency
    grows), so the decoder (a) trims each run's all-zero chunk padding at
    parse time (the payload's ``rstrip`` view -- no spill columns, no
    staging pre-fault), (b) holds zero-copy memoryviews into the input
    blobs rather than slice copies, and (c) flushes groups incrementally
    so join/scatter temps stay ~100 MB and recycle.
    """

    #: flush the pending groups when their payload bytes exceed this.
    _FLUSH_BYTES = 1 << 27

    def __init__(self, spec: SketchSpec, n: int):
        self.spec = spec
        self.n_bins = spec.n_bins
        self.base = spec.key_offset
        self.bins_pos = np.zeros((n, self.n_bins), np.float64)
        self.bins_neg = np.zeros((n, self.n_bins), np.float64)
        self.zero = np.zeros((n,), np.float64)
        self.count = np.zeros((n,), np.float64)
        self.clow = np.zeros((n,), np.float64)
        self.chigh = np.zeros((n,), np.float64)
        # Canonical runs grouped by (store, trimmed length): lists of
        # (stream index, window start, payload memoryview).
        self.groups: dict = {}
        self.pending_bytes = 0
        self.mapping_cache: dict = {}

    def flush_groups(self) -> None:
        for (which, ln), items in self.groups.items():
            if not items:
                continue
            k = len(items)
            idx = np.fromiter((it[0] for it in items), np.int64, k)
            j0s = np.fromiter((it[1] for it in items), np.int64, k)
            # One frombuffer over the joined payload views: C-speed
            # assembly of the [k, ln] block (np.stack over k tiny views is
            # ~2x slower; bytes.join accepts buffer objects).
            block = np.frombuffer(
                b"".join([it[2] for it in items]), np.float64
            ).reshape(k, ln)
            self.place_block(which, idx, j0s, block, ln)
        self.groups = {}
        self.pending_bytes = 0

    def place_block(self, which, idx, j0s, block, ln: int) -> None:
        """Place one same-length group block ``[k, ln]`` into store
        ``which`` (0 = positive, 1 = negative).  The single placement
        authority for both parse paths: the pure-Python group flush and
        the native scanner feed it identical payload doubles, so the
        resulting states are bit-identical by construction.  Stream rows
        must be unique within the block (one canonical run per (stream,
        store)), so the fancy ``+=`` cannot collide."""
        arr = (self.bins_pos, self.bins_neg)[which]
        nb = self.n_bins
        if block.min() < 0.0:
            # Dense entries place only when strictly positive
            # (StoreProto.merge_into) and mass counts post-clip.
            block = np.clip(block, 0.0, None)
        self.count[idx] += block.sum(axis=1)
        easy = (j0s >= 0) & (j0s + ln <= nb)
        e = np.nonzero(easy)[0]
        # Scatter in bounded row chunks: chunking keeps the
        # advanced-indexing broadcast temps recycled instead of
        # faulting fresh GBs.
        cstep = max(1, (1 << 23) // max(ln, 1))
        lane = np.arange(ln)
        for s in range(0, e.size, cstep):
            es = e[s : s + cstep]
            arr[idx[es][:, None], j0s[es][:, None] + lane] += block[es]
        for h in np.nonzero(~easy)[0]:
            # Foreign-shaped run overlapping/outside the window: fold
            # the overhangs into the edge bins with collapse counters.
            i, j0 = int(idx[h]), int(j0s[h])
            row = block[h]
            lo_cut = max(0, -j0)
            hi_cut = max(0, min(ln, nb - j0))
            if lo_cut:
                low = float(row[:lo_cut].sum())
                arr[i, 0] += low
                self.clow[i] += low
            if hi_cut < ln:
                high = float(row[hi_cut:].sum())
                arr[i, nb - 1] += high
                self.chigh[i] += high
            if hi_cut > lo_cut:
                arr[i, j0 + lo_cut : j0 + hi_cut] += row[lo_cut:hi_cut]

    def careful_message(self, i: int, msg, assume_native_linear: bool) -> None:
        from sketches_tpu.pb.proto import KeyMappingProto

        key = (msg.mapping.gamma, msg.mapping.indexOffset, msg.mapping.interpolation)
        m = self.mapping_cache.get(key)
        if m is None:
            m = self.mapping_cache[key] = KeyMappingProto.from_proto(
                msg.mapping, assume_native_linear=assume_native_linear
            )
        if m != self.spec.mapping:
            from sketches_tpu.ddsketch import UnequalSketchParametersError

            raise UnequalSketchParametersError(
                f"Decoded mapping {m!r} does not match batched spec mapping"
                f" {self.spec.mapping!r}"
            )
        pm, pl, ph = _careful_place(
            self.bins_pos, i, msg.positiveValues, self.base, self.n_bins
        )
        nm, nl, nh = _careful_place(
            self.bins_neg, i, msg.negativeValues, self.base, self.n_bins
        )
        self.zero[i] = msg.zeroCount
        self.count[i] += pm + nm + msg.zeroCount
        self.clow[i] += pl + nl
        self.chigh[i] += ph + nh

    def finish(self) -> SketchState:
        self.flush_groups()
        n = self.count.shape[0]
        inf = np.full((n,), np.inf)
        return arrays_to_state(
            self.spec, self.bins_pos, self.bins_neg,
            self.zero, self.count,
            np.zeros((n,)), inf, -inf, self.clow, self.chigh,
        )


def _parse_canonical(blob: bytes, start: int, i: int, base: int):
    """Walk one canonical blob past its mapping prefix.

    Returns ``(pending, zero_count, store_positions, zc_pos)`` --
    ``pending`` holds ``((is_neg, trimmed_len), (stream, window_start,
    payload view))`` per store run; ``store_positions`` /``zc_pos`` are
    the absolute byte positions a :class:`_Template` needs -- or ``None``
    for ANY non-canonical shape: unknown fields, repeated store fields
    (legal protobuf, but the group scatter assumes one run per
    (stream, store)), and declared lengths that leave the blob (review
    r5: a truncated blob must reach the careful path, whose
    ``FromString`` raises DecodeError, never be silently slice-clamped
    into a shorter run).
    """
    end = len(blob)
    j = start
    pending: list = []
    zc = 0.0
    zc_pos = -1
    positions: list = []
    seen = 0  # store fields already parsed (bit 0 pos, bit 1 neg)
    while j < end:
        tag = blob[j]
        if tag == 0x12 or tag == 0x1A:  # positiveValues / negativeValues
            bit = 1 if tag == 0x12 else 2
            if seen & bit or j + 1 >= end:
                return None
            seen |= bit
            # Inlined varints (canonical store bodies are `0x12 <len>
            # <payload> [0x18 <zigzag off>]`; anything else falls back).
            b = blob[j + 1]
            if b < 0x80:
                ln = b
                j += 2
            else:
                ln, j = _read_varint(blob, j + 1)
            end_body = j + ln
            if end_body > end:
                return None
            if ln == 0:  # empty store submessage
                continue
            if blob[j] != 0x12 or j + 1 >= end_body:
                return None
            b = blob[j + 1]
            if b < 0x80:
                pl = b
                p0 = j + 2
            else:
                pl, p0 = _read_varint(blob, j + 1)
            pend = p0 + pl
            if pend > end_body or pl & 7:
                return None
            key_off = 0
            off_a = off_b = -1
            if pend < end_body:
                if blob[pend] != 0x18 or pend + 1 >= end_body:
                    return None
                z, nxt = _read_varint(blob, pend + 1)
                # Protobuf sint32 semantics: the varint TRUNCATES to its
                # low 32 bits before zigzag decode (a >32-bit offset
                # varint is legal on the wire; the C++ FromString path
                # truncates, so the fast path must too or the two decode
                # paths diverge on the same foreign bytes -- ADVICE r5).
                z &= 0xFFFFFFFF
                key_off = (z >> 1) ^ -(z & 1)
                if nxt != end_body:
                    return None
                off_a, off_b = pend + 1, nxt
            positions.append((tag == 0x1A, p0, pend, off_a, off_b))
            # Trim the run's trailing all-zero doubles (the host store's
            # chunk padding): shorter groups, no out-of-window zero
            # overhang, and the group block shrinks to the real mass.
            # rstrip is C-speed; the kept view slices the ORIGINAL blob
            # (zero copy) at the 8-byte-rounded cut, so a double with any
            # nonzero byte survives whole.
            stripped = blob[p0:pend].rstrip(b"\x00")
            t_len = (len(stripped) + 7) >> 3
            if t_len:
                pending.append(
                    (
                        (tag == 0x1A, t_len),
                        (
                            i,
                            key_off - base,
                            memoryview(blob)[p0 : p0 + 8 * t_len],
                        ),
                    )
                )
            j = end_body
        elif tag == 0x21:  # zeroCount double
            if j + 9 > end:
                return None
            zc = struct.unpack_from("<d", blob, j + 1)[0]
            zc_pos = j
            j += 9
        else:
            return None
    return pending, zc, positions, zc_pos


class _Template:
    """Structural fast path for same-shaped canonical blobs.

    Bulk batches are highly homogeneous: most blobs share byte-identical
    STRUCTURE (field tags, length varints, offset-varint widths) and
    differ only in the payload doubles, the offset-varint values, and the
    zeroCount value.  A template memorizes one fully-parsed blob's
    structural byte ranges; a candidate of the same length whose
    structural bytes match byte-for-byte skips the field walk (one memcmp
    per range + per-store varint/rstrip).  Any mismatch -- including a
    same-length blob with compensating structural differences -- simply
    misses and takes the full walker, so the template is a pure
    optimization with no acceptance risk.
    """

    __slots__ = ("struct_slices", "stores", "zc_pos")

    def __init__(self, blob: bytes, start: int, stores, zc_pos: int):
        self.stores = stores
        self.zc_pos = zc_pos
        value_ranges = []  # byte ranges whose CONTENT may differ per blob
        for (_, p0, pend, off_a, off_b) in stores:
            value_ranges.append((p0, pend))
            if off_a >= 0:
                value_ranges.append((off_a, off_b))
        if zc_pos >= 0:
            value_ranges.append((zc_pos + 1, zc_pos + 9))
        value_ranges.sort()
        slices = []
        prev = start
        for a, b in value_ranges:
            if a > prev:
                slices.append((prev, blob[prev:a]))
            prev = b
        if prev < len(blob):
            slices.append((prev, blob[prev:]))
        self.struct_slices = slices

    def extract(self, blob: bytes, i: int, base: int):
        """(pending, zc) for a structurally matching blob, else None."""
        for a, ref in self.struct_slices:
            if blob[a : a + len(ref)] != ref:
                return None
        pending = []
        mv = memoryview(blob)
        for (is_neg, p0, pend, off_a, off_b) in self.stores:
            key_off = 0
            if off_a >= 0:
                # Same offset-varint WIDTH is structural; the value is
                # free.  The continuation pattern must terminate exactly
                # at off_b or the structure differs after all.
                if blob[off_b - 1] & 0x80:
                    return None
                for k in range(off_a, off_b - 1):
                    if not blob[k] & 0x80:
                        return None
                z, _ = _read_varint(blob, off_a)
                z &= 0xFFFFFFFF  # protobuf sint32 truncation (see above)
                key_off = (z >> 1) ^ -(z & 1)
            stripped = blob[p0:pend].rstrip(b"\x00")
            t_len = (len(stripped) + 7) >> 3
            if t_len:
                pending.append(
                    (
                        (is_neg, t_len),
                        (i, key_off - base, mv[p0 : p0 + 8 * t_len]),
                    )
                )
        zc = 0.0
        if self.zc_pos >= 0:
            zc = struct.unpack_from("<d", blob, self.zc_pos + 1)[0]
        return pending, zc


def _scan_dense_native(scanner, blobs, expected_mapping: bytes, base: int,
                       status: np.ndarray):
    """One C++ structural scan over the packed batch.

    Packs ``blobs`` into a single buffer, hands the canonical walk
    (prefix memcmp, store framing, varint/zigzag decode, zero-padding
    trim) to ``ddsk_wire_scan_dense``, and returns the per-blob fact
    arrays plus the aligned payload staging buffer.  ``status`` entries
    nonzero on entry are skipped by the scanner (pre-marked admission
    failures); on return nonzero entries are the careful-path handoffs.
    """
    from sketches_tpu.native import _dptr, _i64ptr, _u8ptr

    n = len(blobs)
    lens = np.fromiter((len(b) for b in blobs), np.int64, n)
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    buf = b"".join(blobs)
    zc = np.zeros(n, np.float64)
    run_pos = np.zeros(2 * n, np.int64)
    run_len = np.zeros(2 * n, np.int64)
    run_j0 = np.zeros(2 * n, np.int64)
    payload = np.empty(max(1, len(buf) // 8), np.float64)
    n_careful = scanner.ddsk_wire_scan_dense(
        buf, n, _i64ptr(offsets), expected_mapping, len(expected_mapping),
        base, _u8ptr(status), _dptr(zc), _i64ptr(run_pos),
        _i64ptr(run_len), _i64ptr(run_j0), _dptr(payload),
    )
    if n_careful < 0:  # defensive: the scanner refused its arguments
        status[:] = 1
        n_careful = n
    return zc, run_pos, run_len, run_j0, payload, int(n_careful)


def _place_native_runs(dec: "_Decoder", ok: np.ndarray, run_pos, run_len,
                       run_j0, payload: np.ndarray) -> None:
    """Group-scatter the native scanner's runs through the decoder.

    The same (store, trimmed-length) grouping as the pure-Python flush,
    but the group block assembles as ONE fancy gather out of the aligned
    payload staging buffer instead of a join over per-blob memoryviews.
    Placement goes through ``_Decoder.place_block`` (the single
    placement authority), chunked so gather temps stay bounded.
    """
    n = ok.shape[0]
    sel = np.repeat(ok, 2) & (run_len > 0)
    if not sel.any():
        return
    stream2 = np.repeat(np.arange(n, dtype=np.int64), 2)
    neg2 = np.tile(np.array([False, True]), n)
    for which in (0, 1):
        m = sel & (neg2 if which else ~neg2)
        if not m.any():
            continue
        idx = stream2[m]
        j0s = run_j0[m]
        lens = run_len[m]
        pos = run_pos[m]
        # One stable sort groups the runs by trimmed length (cheaper
        # than a boolean scan per distinct length when lengths spread).
        order = np.argsort(lens, kind="stable")
        lens = lens[order]
        bounds = np.nonzero(np.diff(lens))[0] + 1
        starts = np.concatenate(([0], bounds))
        stops = np.concatenate((bounds, [lens.size]))
        for a, b in zip(starts.tolist(), stops.tolist()):
            g = order[a:b]
            ln = int(lens[a])
            lane = np.arange(ln)
            rstep = max(1, (1 << 23) // ln)
            for s in range(0, g.size, rstep):
                gs = g[s : s + rstep]
                block = payload[pos[gs][:, None] + lane]
                dec.place_block(which, idx[gs], j0s[gs], block, ln)


def _quarantine_kind(exc: BaseException) -> str:
    """Stable reason slug for one quarantined blob's failure."""
    if isinstance(exc, BlobTooLarge):
        return "over_limit"
    if isinstance(exc, UnequalSketchParametersError):
        return "mapping_mismatch"
    if type(exc).__name__ == "DecodeError":  # google.protobuf DecodeError
        return "unparseable"
    if isinstance(exc, ValueError):
        return "invalid"
    return "error"


def _careful_blob(dec: "_Decoder", i: int, blob: bytes,
                  assume_native_linear: bool, report) -> None:
    """One blob through the protobuf reference path (shared by both batch
    drivers).  Quarantine admission: every raiser -- ``FromString``'s
    DecodeError, the mapping gates -- fires BEFORE any placement into the
    decode arrays, so a quarantined stream's row stays exactly empty."""
    if report is None:
        dec.careful_message(
            i, pb.DDSketch.FromString(blob), assume_native_linear
        )
    else:
        try:
            dec.careful_message(
                i, pb.DDSketch.FromString(blob), assume_native_linear
            )
        except Exception as e:
            report.add(i, _quarantine_kind(e), e)


def _decode_batch_python(dec: "_Decoder", blobs, expected_mapping: bytes,
                         base: int, fast_ok: bool,
                         assume_native_linear: bool, report,
                         max_blob_bytes: Optional[int]) -> None:
    """The pure-Python batch driver: per-blob canonical walk with the
    structural-template memo, group staging with incremental flushes, and
    per-blob careful fallback.  This is the fallback tier when the native
    scanner is unavailable (no toolchain, ``SKETCHES_TPU_NATIVE=0``,
    stale/ABI-mismatched ``.so``) -- and the semantic oracle the native
    driver is differential-tested against."""
    mlen = len(expected_mapping)
    zeros: list = []  # (stream, zeroCount) -- vector-assigned at the end
    templates: dict = {}  # blob length -> _Template
    for i, blob in enumerate(blobs):
        if faults._ACTIVE:
            # Injected blob corruption (deterministic per index) -- the
            # quarantine path must then catch what it produces.
            blob = faults.inject(faults.WIRE_BLOB, payload=blob, index=i)
        if max_blob_bytes is not None and len(blob) > max_blob_bytes:
            exc = BlobTooLarge(
                f"blob {i}: {len(blob)} bytes exceeds"
                f" max_blob_bytes={max_blob_bytes}"
            )
            if report is None:
                raise exc
            report.add(i, "over_limit", exc)
            continue
        parsed = None
        if fast_ok and blob.startswith(expected_mapping):
            t = templates.get(len(blob))
            if t is not None:
                parsed = t.extract(blob, i, base)
            if parsed is None:
                # IndexError backstop: a malformed varint whose
                # continuation bits run off the blob end must land on the
                # careful path (DecodeError), not escape as IndexError.
                try:
                    full = _parse_canonical(blob, mlen, i, base)
                except IndexError:
                    full = None
                if full is not None:
                    pending_f, zc_f, positions, zc_pos = full
                    parsed = (pending_f, zc_f)
                    if t is None:
                        templates[len(blob)] = _Template(
                            blob, mlen, positions, zc_pos
                        )
        if parsed is None:
            _careful_blob(dec, i, blob, assume_native_linear, report)
            continue
        pending, zc = parsed
        groups = dec.groups
        for key, entry in pending:
            g = groups.get(key)
            if g is None:
                g = groups[key] = []
            g.append(entry)
            dec.pending_bytes += key[1] << 3
        if zc:
            zeros.append((i, zc))
        if dec.pending_bytes >= dec._FLUSH_BYTES:
            dec.flush_groups()
    if zeros:
        zi = np.fromiter((z[0] for z in zeros), np.int64, len(zeros))
        zv = np.fromiter((z[1] for z in zeros), np.float64, len(zeros))
        dec.zero[zi] = zv
        dec.count[zi] += zv


def _decode_batch_native(scanner, dec: "_Decoder", blobs,
                         expected_mapping: bytes, base: int,
                         assume_native_linear: bool, report,
                         max_blob_bytes: Optional[int]) -> None:
    """The native batch driver: one C++ structural scan over the packed
    batch, vectorized group placement, then the careful-path handoffs in
    batch order.

    Decodes bit-identically to :func:`_decode_batch_python` by
    construction: fast-parsed blobs yield the identical payload doubles /
    window starts / zero counts (the scanner mirrors
    ``_parse_canonical``) placed by the same ``place_block`` authority,
    and careful blobs take the identical per-blob protobuf path in the
    identical order, so error types, quarantine records, and admission
    checks line up record-for-record.
    """
    blob_list = list(blobs)
    n = len(blob_list)
    if faults._ACTIVE:
        # Injected blob corruption fires before packing, so the scanner
        # sees exactly the bytes the pure-Python driver would (the
        # injection is deterministic per index) and the fault lands on
        # the careful/quarantine path through the native scan.
        blob_list = [
            faults.inject(faults.WIRE_BLOB, payload=b, index=i)
            for i, b in enumerate(blob_list)
        ]
    status = np.zeros(n, np.uint8)
    if max_blob_bytes is not None:
        lens = np.fromiter((len(b) for b in blob_list), np.int64, n)
        status[lens > max_blob_bytes] = 3  # admission failure: pre-marked
    zc, run_pos, run_len, run_j0, payload, n_careful = _scan_dense_native(
        scanner, blob_list, expected_mapping, base, status,
    )
    if telemetry._ACTIVE:
        telemetry.counter_inc("wire.native.decode_calls")
        if n_careful:
            telemetry.counter_inc(
                "wire.native.careful_fallbacks", float(n_careful)
            )
            misses = int((status == 2).sum())
            if misses:
                telemetry.counter_inc(
                    "wire.native.template_miss", float(misses)
                )
    ok = status == 0
    oki = np.nonzero(ok)[0]
    zsel = oki[zc[oki] != 0.0]
    dec.zero[zsel] = zc[zsel]
    dec.count[zsel] += zc[zsel]
    _place_native_runs(dec, ok, run_pos, run_len, run_j0, payload)
    if not n_careful:
        return
    for i in np.nonzero(status)[0].tolist():
        blob = blob_list[i]
        if status[i] == 3:  # over the admission cap
            exc = BlobTooLarge(
                f"blob {i}: {len(blob)} bytes exceeds"
                f" max_blob_bytes={max_blob_bytes}"
            )
            if report is None:
                raise exc
            report.add(i, "over_limit", exc)
            continue
        _careful_blob(dec, i, blob, assume_native_linear, report)


def bytes_to_state(
    spec: SketchSpec,
    blobs: Sequence[bytes],
    *,
    assume_native_linear: bool = False,
    errors: str = "raise",
    max_blob_bytes: Optional[int] = None,
):
    """Decode raw wire blobs into one device batch.

    Canonical blobs (this library's own encoder shape: expected mapping
    prefix, packed runs, sint32 offsets, trailing zeroCount) parse with the
    hand-rolled walker and place group-vectorized; anything else falls back
    per-message to the C++ parser + careful placement, so foreign wire
    quirks (sparse maps, unpacked doubles, unknown fields) decode with the
    object bridge's exact semantics.

    Error policy (r7 quarantine contract):

    * ``errors="raise"`` (default): the pre-r7 behavior -- the first bad
      blob raises (protobuf ``DecodeError``, mapping-gate ``ValueError``,
      :class:`BlobTooLarge`) and the whole batch is lost.
    * ``errors="quarantine"``: returns ``(state, QuarantineReport)``.
      Bad blobs -- unparseable bytes, mapping mismatches/refusals, blobs
      over ``max_blob_bytes`` -- are isolated into the report (index +
      structured reason) and decode as EMPTY streams; every other stream
      decodes **bit-identically** to a clean decode of the same blob
      (quarantine changes admission, never placement).  The failure
      counts also land in ``resilience.health()``'s counters.  Limit of
      the contract: corruption that yields *structurally valid* protobuf
      is undetectable (the wire format carries no checksum) -- it decodes
      as whatever sketch the bytes describe.

    ``max_blob_bytes`` is the admission cap against oversized/hostile
    blobs (``None`` = no cap); it applies in both error modes.
    """
    from sketches_tpu.mapping import LinearlyInterpolatedMapping

    if errors not in ("raise", "quarantine"):
        raise SketchValueError(
            f"Unknown errors mode {errors!r}; expected 'raise' or"
            " 'quarantine'"
        )
    _t0 = telemetry.clock() if telemetry._ACTIVE else None
    _p0 = telemetry.clock() if profiling._ACTIVE else None
    report = QuarantineReport(total=len(blobs)) if errors == "quarantine" else None
    dec = _Decoder(spec, len(blobs))
    expected_mapping = _mapping_field(spec)
    # A canonical-prefix match normally certifies the spec's own mapping;
    # for a LINEAR spec it cannot distinguish native bytes from a foreign
    # emitter that happens to share the serialization, so the refusal gate
    # must still run (through the careful path) unless the caller vouches.
    fast_ok = not (
        isinstance(spec.mapping, LinearlyInterpolatedMapping)
        and not assume_native_linear
    )
    base = spec.key_offset
    scanner = None
    if fast_ok and len(blobs):
        from sketches_tpu import native

        scanner = native.wire_scanner()
    if scanner is not None:
        _decode_batch_native(
            scanner, dec, blobs, expected_mapping, base,
            assume_native_linear, report, max_blob_bytes,
        )
    else:
        _decode_batch_python(
            dec, blobs, expected_mapping, base, fast_ok,
            assume_native_linear, report, max_blob_bytes,
        )
    state = dec.finish()
    if integrity._ACTIVE:
        # Guarded seam: invariant-check the decoded batch.  Structurally
        # valid corruption that forges a *consistent* sketch remains the
        # wire format's documented limit (no checksum slot); compare an
        # out-of-band integrity.fingerprint() to close it.
        integrity.verify_state(spec, state, seam="wire.decode")
    if _t0 is not None:
        telemetry.finish_span("wire.decode_s", _t0, errors=errors)
        telemetry.counter_inc("wire.blobs_decoded", float(len(blobs)))
    if _p0 is not None:
        profiling.record("decode", "decode", _p0, state)
    if report is None:
        return state
    if report.n_quarantined:
        resilience.bump("wire.quarantined", report.n_quarantined)
        for kind, n in report.counters.items():
            resilience.bump(f"wire.quarantined.{kind}", n)
        if telemetry._ACTIVE:
            telemetry.counter_inc(
                "wire.blobs_quarantined", float(report.n_quarantined)
            )
    return state, report


def protos_to_state(
    spec: SketchSpec,
    protos: Sequence["pb.DDSketch"],
    *,
    assume_native_linear: bool = False,
    errors: str = "raise",
    max_blob_bytes: Optional[int] = None,
):
    """Decode parsed messages into one device batch.

    Re-serializing through the C++ serializer (~1 us/message) canonicalizes
    the wire, so the group-vectorized bytes path serves message inputs too
    (error policy included -- see :func:`bytes_to_state`).  Messages that
    originated from bytes (the fleet-ingest shape) therefore ride the
    SAME native offset-extraction fast path as :func:`bytes_to_state`:
    the round-trip through ``SerializeToString`` re-emits the canonical
    template the scanner matches, so message inputs inherit the C++
    structural scan without a second implementation (docs/DESIGN.md
    section 17).
    """
    return bytes_to_state(
        spec,
        [m.SerializeToString() for m in protos],
        assume_native_linear=assume_native_linear,
        errors=errors,
        max_blob_bytes=max_blob_bytes,
    )
