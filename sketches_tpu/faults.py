"""Deterministic fault-injection harness (off by default, zero hot-path cost).

Resilience claims are only as good as the failures they were tested
against, so the failure modes are first-class, *injectable* events:

======================  ====================================================
site                    simulates
======================  ====================================================
``wire.blob``           blob corruption in the bulk decode (mutates the
                        blob bytes per index, deterministically)
``native.load``         native-library build/load failure (raises inside
                        ``native._load``; transient when ``times`` caps it)
``pallas.lowering``     a Pallas query-kernel lowering/compile failure
                        (raises at the facade dispatch, per engine ``tier``)
``pallas.ingest``       a Pallas ingest-kernel failure
``pallas.ingest_variant``  a lowering/compile failure of a non-stock
                        ingest construction variant (the r17 packed /
                        hifold / cmpfree rungs; the facade must degrade
                        to the stock rung, recorded in the health
                        ledger -- ``tier`` restricts to one variant)
``checkpoint.write``    a torn checkpoint write (``mode="truncate"``) or a
                        crash before the atomic rename (``mode="raise"``)
``mesh.shard``          dead value shard(s) -- consumed by
                        ``DistributedDDSketch.merge_partial`` /
                        ``reshard`` via :func:`dead_shards`
``mesh.host_loss``      a whole lost host (every value shard in one ICI
                        group dies at once) -- consumed by
                        ``DistributedDDSketch.reshard`` via
                        :func:`lost_hosts`
``dcn.partition``       a DCN network partition: some process-local
                        merged partials are unreachable at the
                        cross-host fold -- consumed by
                        ``parallel.fold_hosts`` via
                        :func:`partitioned_hosts`
``reshard.torn``        an elastic reshard interrupted between the
                        survivor fold and the regrown mesh (raises at
                        the reshard seam; the ORIGINAL fleet must
                        survive intact -- reshard is atomic)
``state.bitflip``       silent device-state corruption: a bit flipped in a
                        bin vector -- consumed by the chaos harness via
                        :func:`state_bitflips` + :func:`apply_state_bitflips`
                        (the integrity layer's adversary)
``serve.straggler``     a straggling query dispatch in the serving tier
                        (raises at the serve dispatch, per engine ``tier``)
                        -- the hedged-retry adversary
``serve.cache_poison``  silent corruption of a cached serving result --
                        consumed by the serve cache via
                        :func:`cache_poison_flip` (returns flip
                        coordinates rather than raising)
``serve.queue_overflow``  forced admission-queue overflow in the serving
                        tier (raises at admission; the request must be
                        shed with a structured error, never hang)
``window.rotate_torn``  a windowed-ring rotation interrupted between the
                        retirement plan and the commit (raises at the
                        rotation seam; the ring, ledger, and live bucket
                        must survive bit-identical -- rotation is atomic)
``window.stack_torn``   a two-stacks aggregate sync interrupted mid-update
                        (raises inside the sync; the stacks are DERIVED
                        state, so the ring must swallow the tear, drop
                        the stacks, and rebuild lazily -- recorded in the
                        health ledger, never surfaced to the query)
``window.agg_stale``    silent corruption of a maintained window
                        aggregate -- consumed by ``WindowedSketch`` via
                        :func:`agg_stale_flips` (returns flip coordinates
                        rather than raising; the stack-consistency
                        integrity audit's adversary)
``mesh.partition_heal``  a partition heal interrupted between replica
                        reconciliation and the un-partition commit
                        (raises at the fabric heal seam; the host must
                        stay partitioned -- degraded but consistent --
                        never half-healed)
``fabric.replica_stale``  silent corruption of a synced read replica --
                        consumed by the serve fabric via
                        :func:`replica_stale_flips` (returns flip
                        coordinates rather than raising; the
                        fingerprint-verified replica read's adversary)
======================  ====================================================

Arming: programmatically via :func:`arm` / :func:`active` (tests), or at
process start via the ``SKETCHES_TPU_FAULTS`` environment variable --
semicolon-separated ``site[:key=value,...]`` entries, e.g.
``SKETCHES_TPU_FAULTS="native.load;wire.blob:fraction=0.01,seed=7"``.
Both are OFF by default.

Cost discipline: every injection seam guards on the module flag
(``if faults._ACTIVE: faults.inject(...)``), so the disabled harness
costs one attribute read + bool test per *dispatch* (not per value) --
unmeasurable next to a device dispatch.  Determinism: a plan fires on a
call-count cap (``times``) or on a seeded per-index hash (``fraction`` +
``seed``); no wall-clock, no global RNG, so a failing sequence replays
exactly.
"""

from __future__ import annotations

import binascii
import contextlib
import threading
from typing import Dict, Iterator, Optional, Sequence, Tuple

from sketches_tpu import tracing
from sketches_tpu.analysis import registry
from sketches_tpu.resilience import InjectedFault, SpecError, bump

__all__ = [
    "FAULTS_ENV",
    "NATIVE_LOAD",
    "PALLAS_LOWERING",
    "PALLAS_INGEST",
    "PALLAS_INGEST_VARIANT",
    "WIRE_BLOB",
    "CHECKPOINT_WRITE",
    "MESH_SHARD",
    "MESH_HOST_LOSS",
    "DCN_PARTITION",
    "RESHARD_TORN",
    "STATE_BITFLIP",
    "SERVE_STRAGGLER",
    "SERVE_CACHE_POISON",
    "SERVE_QUEUE_OVERFLOW",
    "WINDOW_ROTATE_TORN",
    "WINDOW_STACK_TORN",
    "WINDOW_AGG_STALE",
    "MESH_PARTITION_HEAL",
    "FABRIC_REPLICA_STALE",
    "SITES",
    "arm",
    "disarm",
    "active",
    "inject",
    "dead_shards",
    "lost_hosts",
    "partitioned_hosts",
    "state_bitflips",
    "apply_state_bitflips",
    "cache_poison_flip",
    "agg_stale_flips",
    "replica_stale_flips",
    "stats",
    "corrupt_blobs",
]

#: Declared in ``analysis/registry.py`` (the kill-switch inventory);
#: this alias keeps the historical import path working.
FAULTS_ENV = registry.FAULTS.name

NATIVE_LOAD = "native.load"
PALLAS_LOWERING = "pallas.lowering"
PALLAS_INGEST = "pallas.ingest"
PALLAS_INGEST_VARIANT = "pallas.ingest_variant"
WIRE_BLOB = "wire.blob"
CHECKPOINT_WRITE = "checkpoint.write"
MESH_SHARD = "mesh.shard"
MESH_HOST_LOSS = "mesh.host_loss"
DCN_PARTITION = "dcn.partition"
RESHARD_TORN = "reshard.torn"
STATE_BITFLIP = "state.bitflip"
SERVE_STRAGGLER = "serve.straggler"
SERVE_CACHE_POISON = "serve.cache_poison"
SERVE_QUEUE_OVERFLOW = "serve.queue_overflow"
WINDOW_ROTATE_TORN = "window.rotate_torn"
WINDOW_STACK_TORN = "window.stack_torn"
WINDOW_AGG_STALE = "window.agg_stale"
MESH_PARTITION_HEAL = "mesh.partition_heal"
FABRIC_REPLICA_STALE = "fabric.replica_stale"

SITES = (
    NATIVE_LOAD,
    PALLAS_LOWERING,
    PALLAS_INGEST,
    PALLAS_INGEST_VARIANT,
    WIRE_BLOB,
    CHECKPOINT_WRITE,
    MESH_SHARD,
    MESH_HOST_LOSS,
    DCN_PARTITION,
    RESHARD_TORN,
    STATE_BITFLIP,
    SERVE_STRAGGLER,
    SERVE_CACHE_POISON,
    SERVE_QUEUE_OVERFLOW,
    WINDOW_ROTATE_TORN,
    WINDOW_STACK_TORN,
    WINDOW_AGG_STALE,
    MESH_PARTITION_HEAL,
    FABRIC_REPLICA_STALE,
)

#: Torn-write seams: sites whose enclosing method promises the
#: atomic-commit contract (zero ``self`` mutations before the inject,
#: commit by reference swap after it).  The contract is proven
#: structurally by ``analysis/seams.py`` and probed dynamically by the
#: chaos campaigns; a new torn site MUST be listed here or sketchlint's
#: ``seam-sites`` rule fails the build.
ATOMIC_SITES = (
    CHECKPOINT_WRITE,
    RESHARD_TORN,
    WINDOW_ROTATE_TORN,
    WINDOW_STACK_TORN,
    MESH_PARTITION_HEAL,
)

#: Fast-path guard: seams check this module flag before calling
#: :func:`inject`, so a fully disarmed harness costs one bool test.
_ACTIVE = False

_lock = threading.Lock()


class _Plan:
    """One armed site: when to fire and what to do.

    ``times=None`` fires on every matching call; ``times=k`` fires on the
    first k.  ``fraction`` + ``seed`` instead select per-``index``
    deterministically (the blob-corruption style).  ``tier`` restricts a
    ``pallas.lowering`` plan to one engine tier (or a tuple of tiers).
    ``mode`` is what firing does: ``"raise"`` (default, raises ``exc`` or
    :class:`InjectedFault`), ``"corrupt"`` / ``"truncate"`` (mutate the
    payload bytes and return them).
    """

    __slots__ = (
        "site", "times", "fraction", "seed", "mode", "tier", "shards",
        "exc", "fired", "calls",
    )

    def __init__(
        self,
        site: str,
        times: Optional[int] = None,
        fraction: Optional[float] = None,
        seed: int = 0,
        mode: str = "raise",
        tier=None,
        shards: Sequence[int] = (),
        exc: Optional[BaseException] = None,
    ):
        if mode not in ("raise", "corrupt", "truncate"):
            raise SpecError(f"Unknown fault mode {mode!r}")
        self.site = site
        self.times = times
        self.fraction = fraction
        self.seed = int(seed)
        self.mode = mode
        self.tier = (tier,) if isinstance(tier, str) else tier
        self.shards = tuple(int(s) for s in shards)
        self.exc = exc
        self.fired = 0
        self.calls = 0


_plans: Dict[str, _Plan] = {}


def arm(site: str, **kwargs) -> None:
    """Arm ``site`` with a :class:`_Plan` (see its docstring for knobs)."""
    global _ACTIVE
    if site not in SITES:
        raise SpecError(f"Unknown fault site {site!r}; expected one of {SITES}")
    with _lock:
        _plans[site] = _Plan(site, **kwargs)
        _ACTIVE = True


def disarm(site: Optional[str] = None) -> None:
    """Disarm one site (or all of them with no argument)."""
    global _ACTIVE
    with _lock:
        if site is None:
            _plans.clear()
        else:
            _plans.pop(site, None)
        _ACTIVE = bool(_plans)


@contextlib.contextmanager
def active(spec: Dict[str, Optional[dict]]) -> Iterator[Dict[str, _Plan]]:
    """Arm ``{site: kwargs}`` for the block; disarm on exit.

    Yields the armed plans so callers can assert on ``fired`` counts.
    """
    armed = []
    try:
        for site, kw in spec.items():
            arm(site, **(kw or {}))
            armed.append(site)
        yield {s: _plans[s] for s in armed}
    finally:
        for s in armed:
            disarm(s)


def stats() -> Dict[str, Tuple[int, int]]:
    """Per-armed-site ``(calls seen, faults fired)``."""
    with _lock:
        return {s: (p.calls, p.fired) for s, p in _plans.items()}


def _selected(seed: int, index: int, fraction: float) -> bool:
    """Deterministic per-index coin flip at rate ``fraction``."""
    h = binascii.crc32(f"{seed}:{index}".encode()) & 0xFFFFFFFF
    return h < fraction * 2**32


def inject(site: str, payload=None, index: Optional[int] = None, tier=None):
    """The seam call: fire the armed plan for ``site``, if any.

    Returns ``payload`` (possibly mutated for byte-mutation modes);
    raises the plan's exception in ``"raise"`` mode.  A disarmed site is
    a no-op returning ``payload`` unchanged.
    """
    plan = _plans.get(site)
    if plan is None:
        return payload
    plan.calls += 1
    if plan.tier is not None and tier is not None and tier not in plan.tier:
        return payload
    if plan.fraction is not None:
        if index is None or not _selected(plan.seed, index, plan.fraction):
            return payload
    elif plan.times is not None and plan.fired >= plan.times:
        return payload
    plan.fired += 1
    bump("faults." + site)
    if tracing._ACTIVE:
        # Injected faults are exactly the events a forensic bundle must
        # carry: the adversary's move, on the victim request's trace.
        tracing.record_event(
            "fault.injected", site=site, mode=plan.mode,
            tier=str(tier) if tier is not None else None,
        )
    if plan.mode == "raise":
        if plan.exc is not None:
            raise plan.exc
        raise InjectedFault(
            f"injected fault at {site}" + (f" (tier={tier})" if tier else "")
        )
    if plan.mode == "truncate":
        return payload[: max(1, len(payload) // 2)]
    return _corrupt(payload, plan.seed, index or 0)


def dead_shards(n_shards: int) -> Tuple[int, ...]:
    """Armed dead value-shard indices within ``[0, n_shards)`` -- the
    ``mesh.shard`` site's consumer-side read (it returns data rather than
    raising, so it does not go through :func:`inject`)."""
    if not _ACTIVE:
        return ()
    plan = _plans.get(MESH_SHARD)
    if plan is None:
        return ()
    plan.calls += 1
    dead = tuple(s for s in plan.shards if 0 <= s < n_shards)
    if dead:
        plan.fired += 1
        bump("faults." + MESH_SHARD)
    return dead


def _armed_indices(site: str, n: int) -> Tuple[int, ...]:
    """Shared consumer-side read for the index-set sites (``mesh.shard``
    style): the armed plan's in-range ``shards`` indices, counted and
    bumped when any fire.  Disarmed it returns ``()`` after one bool
    test; an empty/out-of-range plan fires nothing."""
    if not _ACTIVE:
        return ()
    plan = _plans.get(site)
    if plan is None:
        return ()
    plan.calls += 1
    hit = tuple(s for s in plan.shards if 0 <= s < n)
    if hit:
        plan.fired += 1
        bump("faults." + site)
        if tracing._ACTIVE:
            tracing.record_event(
                "fault.injected", site=site, indices=str(hit)
            )
    return hit


def lost_hosts(n_hosts: int) -> Tuple[int, ...]:
    """Armed lost-host indices within ``[0, n_hosts)`` -- the
    ``mesh.host_loss`` site's consumer-side read (returns data rather
    than raising, like :func:`dead_shards`; every value shard in a lost
    host's ICI group is treated as dead at the next reshard/fold).
    Disarmed (the default) it returns ``()`` after one bool test."""
    return _armed_indices(MESH_HOST_LOSS, n_hosts)


def partitioned_hosts(n_hosts: int) -> Tuple[int, ...]:
    """Armed DCN-partitioned host indices within ``[0, n_hosts)`` -- the
    ``dcn.partition`` site's consumer-side read (returns data rather
    than raising): those hosts' process-local merged partials are
    unreachable at the cross-host fold and must be folded around with
    their mass accounted, never silently averaged as zeros.  Disarmed
    (the default) it returns ``()`` after one bool test."""
    return _armed_indices(DCN_PARTITION, n_hosts)


def state_bitflips(n_streams: int, n_bins: int) -> Tuple[Tuple[int, int, int, int], ...]:
    """Armed device-state bit-flip coordinates -- the ``state.bitflip``
    site's consumer-side read (it returns data rather than raising, like
    :func:`dead_shards`).

    Each firing yields one ``(store, stream, bin, bit)`` tuple (store 0
    = positive bins, 1 = negative bins; bit indexes the 32-bit lane of
    the bin's dtype), derived deterministically from the plan's seed and
    its running call count, so a failing sequence replays exactly.
    Disarmed (the default) it returns ``()`` after one bool test.
    Respects the plan's ``times`` cap.
    """
    if not _ACTIVE:
        return ()
    plan = _plans.get(STATE_BITFLIP)
    if plan is None:
        return ()
    plan.calls += 1
    if plan.times is not None and plan.fired >= plan.times:
        return ()
    h = binascii.crc32(f"{plan.seed}:{plan.calls}".encode()) & 0xFFFFFFFF
    store = h & 1
    stream = (h >> 1) % max(n_streams, 1)
    bin_ = (h >> 11) % max(n_bins, 1)
    bit = (h >> 25) % 32
    plan.fired += 1
    bump("faults." + STATE_BITFLIP)
    return ((store, stream, bin_, bit),)


def apply_state_bitflips(state, flips):
    """Apply :func:`state_bitflips` coordinates to a batched state ->
    a corrupted COPY (the input pytree is untouched).

    XORs the named bit of the named bin through a 32-bit integer view
    (f32 and int32 bins both), the chaos harness's model of silent
    in-memory corruption; the flipped value may be negative, huge, or
    NaN -- whatever the bit pattern decodes to.  No-op (returns
    ``state`` unchanged) for an empty flip list.
    """
    if not flips:
        return state
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp
    import numpy as np

    arrays = [
        np.asarray(jax.device_get(a)).copy()
        for a in (state.bins_pos, state.bins_neg)
    ]
    for store, stream, bin_, bit in flips:
        view = arrays[store].view(np.uint32)
        view[stream, bin_] ^= np.uint32(1) << np.uint32(bit)
    return _dc.replace(
        state,
        bins_pos=jnp.asarray(arrays[0]),
        bins_neg=jnp.asarray(arrays[1]),
    )


def agg_stale_flips(
    n_streams: int, n_bins: int
) -> Tuple[Tuple[int, int, int, int], ...]:
    """Armed maintained-aggregate corruption coordinates -- the
    ``window.agg_stale`` site's consumer-side read (it returns data
    rather than raising, like :func:`state_bitflips`).

    Same coordinate scheme as :func:`state_bitflips` -- each firing
    yields one ``(store, stream, bin, bit)`` tuple derived
    deterministically from the plan's seed and its running call count --
    but aimed at a CACHED two-stacks window aggregate instead of a live
    bucket state: the raw bucket stays clean, so only the
    stack-consistency integrity audit can tell the cached answer went
    stale.  Disarmed (the default) it returns ``()`` after one bool
    test.  Respects the plan's ``times`` cap.
    """
    if not _ACTIVE:
        return ()
    plan = _plans.get(WINDOW_AGG_STALE)
    if plan is None:
        return ()
    plan.calls += 1
    if plan.times is not None and plan.fired >= plan.times:
        return ()
    h = binascii.crc32(f"{plan.seed}:{plan.calls}".encode()) & 0xFFFFFFFF
    store = h & 1
    stream = (h >> 1) % max(n_streams, 1)
    bin_ = (h >> 11) % max(n_bins, 1)
    bit = (h >> 25) % 32
    plan.fired += 1
    bump("faults." + WINDOW_AGG_STALE)
    if tracing._ACTIVE:
        tracing.record_event(
            "fault.injected", site=WINDOW_AGG_STALE,
            coords=str((store, stream, bin_, bit)),
        )
    return ((store, stream, bin_, bit),)


def replica_stale_flips(
    n_streams: int, n_bins: int
) -> Tuple[Tuple[int, int, int, int], ...]:
    """Armed read-replica corruption coordinates -- the
    ``fabric.replica_stale`` site's consumer-side read (it returns data
    rather than raising, like :func:`state_bitflips`).

    Same coordinate scheme as :func:`state_bitflips` -- each firing
    yields one ``(store, stream, bin, bit)`` tuple derived
    deterministically from the plan's seed and its running call count --
    but aimed at a serve-fabric READ REPLICA after its sync: the
    primary stays clean, so only the fingerprint-vs-ledger verification
    at serve time can tell the replica went stale-wrong.  The flipped
    bit is drawn from the magnitude-bearing float32 bits (top mantissa,
    high exponent) so the corruption is material whenever the bin
    carries mass -- and the high-exponent pick is material even on an
    empty bin; a uniformly random low bit would vanish into the
    fingerprint sum's rounding and drill nothing.  Disarmed (the
    default) it returns ``()`` after one bool test.  Respects the
    plan's ``times`` cap.
    """
    if not _ACTIVE:
        return ()
    plan = _plans.get(FABRIC_REPLICA_STALE)
    if plan is None:
        return ()
    plan.calls += 1
    if plan.times is not None and plan.fired >= plan.times:
        return ()
    h = binascii.crc32(f"{plan.seed}:{plan.calls}".encode()) & 0xFFFFFFFF
    store = h & 1
    stream = (h >> 1) % max(n_streams, 1)
    bin_ = (h >> 11) % max(n_bins, 1)
    bit = (21, 22, 30)[(h >> 25) % 3]
    plan.fired += 1
    bump("faults." + FABRIC_REPLICA_STALE)
    if tracing._ACTIVE:
        tracing.record_event(
            "fault.injected", site=FABRIC_REPLICA_STALE,
            coords=str((store, stream, bin_, bit)),
        )
    return ((store, stream, bin_, bit),)


def cache_poison_flip(n_bytes: int) -> Optional[Tuple[int, int]]:
    """Armed cached-result corruption coordinates -- the
    ``serve.cache_poison`` site's consumer-side read (it returns data
    rather than raising, like :func:`state_bitflips`).

    Each firing yields one ``(byte, bit)`` coordinate into a cached
    payload of ``n_bytes`` bytes, derived deterministically from the
    plan's seed and its running call count, so a failing sequence
    replays exactly.  Disarmed (the default) it returns ``None`` after
    one bool test; an empty payload also returns ``None`` (nothing to
    corrupt).  Respects the plan's ``times`` cap.
    """
    if not _ACTIVE:
        return None
    plan = _plans.get(SERVE_CACHE_POISON)
    if plan is None or n_bytes <= 0:
        return None
    plan.calls += 1
    if plan.times is not None and plan.fired >= plan.times:
        return None
    h = binascii.crc32(f"{plan.seed}:{plan.calls}".encode()) & 0xFFFFFFFF
    byte = h % n_bytes
    bit = (h >> 24) % 8
    plan.fired += 1
    bump("faults." + SERVE_CACHE_POISON)
    if tracing._ACTIVE:
        tracing.record_event(
            "fault.injected", site=SERVE_CACHE_POISON, byte=byte, bit=bit
        )
    return (byte, bit)


# ---------------------------------------------------------------------------
# Deterministic blob corruption
# ---------------------------------------------------------------------------


def _corrupt(blob: bytes, seed: int, index: int) -> bytes:
    """Structurally-invalid corruption of one wire blob, by (seed, index).

    Every mode is GUARANTEED unparseable by any protobuf parser (invalid
    wire type 7 tag, or the illegal field number 0), so a corrupted blob
    is always *detected* -- the corruption model for quarantine tests.
    (A bit flip that yields different-but-valid bytes is undetectable
    without a content checksum the DDSketch wire format does not carry;
    that is the documented limit of the quarantine contract.)
    """
    mode = (seed + index) % 3
    if mode == 0:
        return b"\xff" + blob[1:]  # tag 0xff: wire type 7 (invalid)
    if mode == 1:
        return blob + b"\xff\xff\xff\xff\xff"  # trailing invalid tag
    return b"\x00" + blob  # field number 0 (illegal)


def corrupt_blobs(
    blobs: Sequence[bytes], fraction: float, seed: int = 0
) -> Tuple[list, list]:
    """Corrupt a deterministic ~``fraction`` of ``blobs`` -> (new list,
    corrupted indices).  Test/benchmark helper sharing the exact
    selection + mutation the armed ``wire.blob`` site applies."""
    out, idx = [], []
    for i, b in enumerate(blobs):
        if _selected(seed, i, fraction):
            out.append(_corrupt(b, seed, i))
            idx.append(i)
        else:
            out.append(b)
    return out, idx


# ---------------------------------------------------------------------------
# Environment arming (process-level, for CI degraded-mode jobs)
# ---------------------------------------------------------------------------


def _coerce(v: str):
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        return v


def _parse_env(value: str) -> None:
    for part in value.split(";"):
        part = part.strip()
        if not part:
            continue
        site, _, kvs = part.partition(":")
        kwargs: dict = {}
        for kv in filter(None, (s.strip() for s in kvs.split(","))):
            k, _, v = kv.partition("=")
            if k == "shards":
                kwargs[k] = tuple(int(s) for s in v.split("|") if s)
            else:
                kwargs[k] = _coerce(v)
        arm(site.strip(), **kwargs)


_env = registry.get(registry.FAULTS)
if _env:  # pragma: no cover - exercised via subprocess in CI degraded jobs
    _parse_env(_env)
