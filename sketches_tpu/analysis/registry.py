"""The kill-switch inventory: every ``SKETCHES_TPU_*`` environment variable.

PR 1 and PR 2 grew three process-level operational levers (native-engine
kill switch, overlap-engine kill switch, fault arming) as ad-hoc
``os.environ`` reads scattered across modules.  This registry is the ONE
place such a variable may be declared and read: each entry carries the
name, the default, the owning module, and a one-line doc (the README
kill-switch table is generated from -- and lint-checked against -- these
entries; see ``analysis/rules/env_registry.py``).

Adding a lever means adding an :class:`EnvVar` here and reading it via
:func:`get`/:func:`enabled`; a raw ``os.environ`` read of a
``SKETCHES_TPU_*`` name anywhere else in the package is a lint violation
(rule ``env-read``), as is a registry entry missing from the README
table (rule ``registry-doc``).

This module is stdlib-only and imports nothing from the rest of the
package (it sits below ``faults``/``native``/``kernels``, which read it
at import time), so any module may import it without cycles.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Tuple

__all__ = [
    "EnvVar",
    "NATIVE",
    "OVERLAP",
    "FAULTS",
    "TELEMETRY",
    "INTEGRITY",
    "PROFILING",
    "ACCURACY_AUDIT",
    "SERVE_CACHE",
    "SERVE_HEDGE",
    "ELASTIC",
    "FLIGHT_RECORDER",
    "INGEST_PACKED",
    "ADAPTIVE",
    "WINDOWED",
    "WINDOW_AGG",
    "FABRIC",
    "REGISTRY",
    "declared",
    "get",
    "enabled",
    "lookup",
]


@dataclasses.dataclass(frozen=True)
class EnvVar:
    """One declared environment variable.

    ``default`` is the exact string :func:`get` returns when the process
    environment does not set the variable (``None`` means "unset", for
    variables like the fault spec whose mere presence arms behavior).
    ``owner`` is the module whose behavior the variable controls;
    ``doc`` is the one-line description the README table must carry.
    """

    name: str
    default: Optional[str]
    owner: str
    doc: str


#: The native-engine kill switch (``sketches_tpu.native``).
NATIVE = EnvVar(
    name="SKETCHES_TPU_NATIVE",
    default="1",
    owner="sketches_tpu.native",
    doc=(
        "Set to 0 to force the native C++ host engine unavailable"
        " (pure-Python host tier); the degraded-mode CI lever."
    ),
)

#: The overlap-query-engine kill switch (``sketches_tpu.kernels``).
OVERLAP = EnvVar(
    name="SKETCHES_TPU_OVERLAP",
    default="1",
    owner="sketches_tpu.kernels",
    doc=(
        "Set to 0 to disconnect the overlap query engine; facades"
        " answer through the windowed/tiles ladder instead."
    ),
)

#: Process-start fault arming (``sketches_tpu.faults``).
FAULTS = EnvVar(
    name="SKETCHES_TPU_FAULTS",
    default=None,
    owner="sketches_tpu.faults",
    doc=(
        "Semicolon-separated fault-site plans armed at process"
        " start (e.g. native.load;wire.blob:fraction=0.01,seed=7);"
        " unset/empty means no injection."
    ),
)

#: Telemetry arming (``sketches_tpu.telemetry``).
TELEMETRY = EnvVar(
    name="SKETCHES_TPU_TELEMETRY",
    default="0",
    owner="sketches_tpu.telemetry",
    doc=(
        "Set to 1 to arm the self-sketching telemetry layer (metric"
        " registry + trace spans); 0/unset leaves it off -- one bool"
        " test per instrumented dispatch."
    ),
)

#: Integrity-layer arming (``sketches_tpu.integrity``).
INTEGRITY = EnvVar(
    name="SKETCHES_TPU_INTEGRITY",
    default="0",
    owner="sketches_tpu.integrity",
    doc=(
        "Set to 1 to arm the self-verifying integrity layer (invariant"
        " checks + fingerprints at the guarded seams; violations raise"
        " IntegrityError) or to quarantine to report instead of raising;"
        " 0/unset leaves it off -- one bool test per guarded seam."
    ),
)

#: Device-time profiling arming (``sketches_tpu.profiling``).
PROFILING = EnvVar(
    name="SKETCHES_TPU_PROFILING",
    default="0",
    owner="sketches_tpu.profiling",
    doc=(
        "Set to 1 to arm device-time attribution: every engine dispatch"
        " blocks until the device finishes and the time is attributed per"
        " engine tier and phase; 0/unset leaves it off -- one bool test"
        " per dispatch."
    ),
)

#: Accuracy-drift shadow audit arming (``sketches_tpu.accuracy``).
ACCURACY_AUDIT = EnvVar(
    name="SKETCHES_TPU_ACCURACY_AUDIT",
    default="0",
    owner="sketches_tpu.accuracy",
    doc=(
        "Set to 1 to arm the accuracy-drift shadow audit: watched sketches"
        " keep a bounded reservoir sample and periodically verify realized"
        " quantile error against the alpha contract; 0/unset leaves it off"
        " -- one bool test per ingest."
    ),
)

#: Serving-tier result-cache kill switch (``sketches_tpu.serve``).
SERVE_CACHE = EnvVar(
    name="SKETCHES_TPU_SERVE_CACHE",
    default="1",
    owner="sketches_tpu.serve",
    doc=(
        "Set to 0 to disable the serving tier's fingerprint-keyed"
        " result cache (every query recomputes; no fingerprint fetch,"
        " no poison checks)."
    ),
)

#: Flight-recorder / request-tracing kill switch (``sketches_tpu.tracing``).
FLIGHT_RECORDER = EnvVar(
    name="SKETCHES_TPU_FLIGHT_RECORDER",
    default="1",
    owner="sketches_tpu.tracing",
    doc=(
        "Set to 0 to keep the flight recorder and request tracing"
        " disarmed even while telemetry is armed (no trace contexts, no"
        " event ring, no forensic dumps); any other value arms them"
        " together with the telemetry layer."
    ),
)

#: Elastic-resharding kill switch (``sketches_tpu.parallel``).
ELASTIC = EnvVar(
    name="SKETCHES_TPU_ELASTIC",
    default="1",
    owner="sketches_tpu.parallel",
    doc=(
        "Set to 0 to refuse live elastic resharding"
        " (DistributedDDSketch.reshard raises SpecError; the fleet"
        " keeps its fixed topology -- checkpoint/restore still works)."
    ),
)

#: Serving-tier hedged-retry kill switch (``sketches_tpu.serve``).
SERVE_HEDGE = EnvVar(
    name="SKETCHES_TPU_SERVE_HEDGE",
    default="1",
    owner="sketches_tpu.serve",
    doc=(
        "Set to 0 to disable hedged retries for straggling serve"
        " dispatches; a straggler's failure then surfaces to the"
        " request as a structured error instead of being hedged around."
    ),
)

#: Packed-ingest-variant kill switch (``sketches_tpu.kernels``).
INGEST_PACKED = EnvVar(
    name="SKETCHES_TPU_INGEST_PACKED",
    default="1",
    owner="sketches_tpu.kernels",
    doc=(
        "Set to 0 to pin the fused ingest kernel to the stock int8"
        " one-hot construction; facades then never select the packed"
        " sub-byte / folded construction variants (the measured-dead"
        " escape hatch for the r17 construction-width rungs)."
    ),
)

#: Adaptive-accuracy backend kill switch (``sketches_tpu.backends``).
ADAPTIVE = EnvVar(
    name="SKETCHES_TPU_ADAPTIVE",
    default="1",
    owner="sketches_tpu.backends",
    doc=(
        "Set to 0 to refuse adaptive-accuracy collapses: a"
        " uniform-collapse trigger (or explicit collapse call) raises"
        " SpecError instead of degrading alpha; dense and moment"
        " backends are unaffected."
    ),
)

#: Time-windowed-quantile kill switch (``sketches_tpu.windows``).
WINDOWED = EnvVar(
    name="SKETCHES_TPU_WINDOWED",
    default="1",
    owner="sketches_tpu.windows",
    doc=(
        "Set to 0 to refuse time-windowed sketches: constructing a"
        " WindowedSketch (or serving a window= query) raises SpecError"
        " instead of silently answering unwindowed; plain facades are"
        " unaffected."
    ),
)

#: Incremental window-aggregation kill switch (``sketches_tpu.windows``).
WINDOW_AGG = EnvVar(
    name="SKETCHES_TPU_WINDOW_AGG",
    default="1",
    owner="sketches_tpu.windows",
    doc=(
        "Set to 0 to disable the maintained two-stacks window"
        " aggregates: every window query falls back to the full"
        " re-merge over the covered buckets (the pre-aggregation"
        " path); answers stay correct, only the per-query merge count"
        " grows back to O(covered buckets)."
    ),
)

#: Sharded-serve-fabric kill switch (``sketches_tpu.fabric``).
FABRIC = EnvVar(
    name="SKETCHES_TPU_FABRIC",
    default="1",
    owner="sketches_tpu.fabric",
    doc=(
        "Set to 0 to refuse the sharded serve fabric: constructing a"
        " ServeFabric raises SpecError instead of silently serving"
        " unreplicated; single-process SketchServer tenants are"
        " unaffected."
    ),
)

#: Every SKETCHES_TPU_* variable the package reads, by name.  Keep the
#: docs in sync with the README "Kill switches" table -- the ``registry-doc``
#: lint rule cross-checks both directions.
REGISTRY: Dict[str, EnvVar] = {
    v.name: v
    for v in (
        NATIVE, OVERLAP, FAULTS, TELEMETRY, INTEGRITY, PROFILING,
        ACCURACY_AUDIT, SERVE_CACHE, SERVE_HEDGE, ELASTIC,
        FLIGHT_RECORDER, INGEST_PACKED, ADAPTIVE, WINDOWED, WINDOW_AGG,
        FABRIC,
    )
}


def declared() -> Tuple[EnvVar, ...]:
    """Every registered variable, in declaration order."""
    return tuple(REGISTRY.values())


def lookup(name: str) -> EnvVar:
    """The :class:`EnvVar` declared under ``name`` (KeyError if absent)."""
    return REGISTRY[name]


def _resolve(var) -> EnvVar:
    if isinstance(var, EnvVar):
        return REGISTRY[var.name]  # refuse undeclared ad-hoc instances too
    return REGISTRY[var]


def get(var) -> Optional[str]:
    """Read a registered variable (:class:`EnvVar` or name) from the
    process environment.

    Returns the declared default when the environment does not set the
    variable.  Raises ``KeyError`` for an undeclared variable -- reading
    an unregistered kill switch is exactly the bug this registry exists
    to make impossible.
    """
    v = _resolve(var)
    return os.environ.get(v.name, v.default)


def enabled(var) -> bool:
    """Flag-style read: True unless the variable is set to ``"0"``.

    The shared convention of the ``SKETCHES_TPU_NATIVE`` /
    ``SKETCHES_TPU_OVERLAP`` kill switches: any value other than the
    literal string ``0`` (including unset) leaves the feature on.
    """
    return get(var) != "0"
