"""Rule ``telemetry-names``: metric names come from the declared inventory.

The telemetry layer's value is that an operator can enumerate what the
process measures; a stringly-typed metric name invented at a call site
(or fat-fingered once) silently forks that inventory.  Mirroring the
kill-switch registry rules:

* every ``telemetry.counter_inc/gauge_set/observe/finish_span/span/
  event(...)`` call in the package must pass a **string literal** first
  argument that matches a ``Metric(...)`` declared in ``telemetry.py``
  (a computed name cannot be checked and is flagged as such);
* ``telemetry.declare(...)`` is the *user-space* extension hook --
  library code calling it is drift by construction and is flagged.

``telemetry.py`` itself is exempt (it IS the inventory, and its API
implementation passes names through variables).  Fixture trees without
a ``telemetry.py`` simply have an empty inventory, so any telemetry
call there is flagged -- which is what the rule's own acceptance tests
exercise.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional

from sketches_tpu.analysis.lint import Finding, LintContext, rule

_TELEMETRY_FILE = "telemetry.py"
_NAMED_APIS = (
    "counter_inc",
    "gauge_set",
    "observe",
    "finish_span",
    "span",
    "event",
)


def _declared_metrics(ctx: LintContext) -> Dict[str, int]:
    """Metric names declared via ``Metric(...)`` in ``telemetry.py`` ->
    line number (parsed, never imported)."""
    sf = ctx.file_in_package(_TELEMETRY_FILE)
    out: Dict[str, int] = {}
    if sf is None or sf.tree is None:
        return out
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else ""
        )
        if name != "Metric":
            continue
        metric: Optional[str] = None
        if node.args and isinstance(node.args[0], ast.Constant) and isinstance(
            node.args[0].value, str
        ):
            metric = node.args[0].value
        for kw in node.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                metric = kw.value.value
        if isinstance(metric, str):
            out[metric] = node.lineno
    return out


@rule("telemetry-names")
def check(ctx: LintContext) -> Iterable[Finding]:
    declared = _declared_metrics(ctx)
    out: List[Finding] = []
    for sf in ctx.iter_files(exclude_in_pkg=(_TELEMETRY_FILE,)):
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "telemetry"
            ):
                continue
            if fn.attr == "declare":
                out.append(
                    Finding(
                        "telemetry-names",
                        sf.path,
                        node.lineno,
                        "telemetry.declare() in library code; library"
                        " metrics belong in the static inventory"
                        " (telemetry.METRICS), declare() is for user code",
                    )
                )
                continue
            if fn.attr not in _NAMED_APIS:
                continue
            first = node.args[0] if node.args else None
            if not (
                isinstance(first, ast.Constant)
                and isinstance(first.value, str)
            ):
                out.append(
                    Finding(
                        "telemetry-names",
                        sf.path,
                        node.lineno,
                        f"telemetry.{fn.attr}(...) metric name must be a"
                        " string literal from the declared inventory (a"
                        " computed name cannot be cross-checked)",
                    )
                )
                continue
            if first.value not in declared:
                out.append(
                    Finding(
                        "telemetry-names",
                        sf.path,
                        node.lineno,
                        f"telemetry metric {first.value!r} is not declared"
                        " in telemetry.py's Metric inventory -- stringly-"
                        "typed metric drift",
                    )
                )
    return out
