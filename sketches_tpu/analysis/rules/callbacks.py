"""Rule ``host-callback``: no host callbacks in library code.

``jax.pure_callback`` / ``jax.experimental.io_callback`` /
``host_callback`` round-trip through the host on every execution --
inside a query or ingest path that is a silent device->host sync that
caps throughput at PCIe/gRPC latency and breaks the overlap engine's
whole premise.  The AST layer flags imports and attribute uses; the
jaxpr audit (layer 2) catches callbacks that arrive indirectly through
a library call.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from sketches_tpu.analysis.lint import Finding, LintContext, rule

_FORBIDDEN = ("pure_callback", "io_callback", "host_callback", "call_tf")


@rule("host-callback")
def check(ctx: LintContext) -> Iterable[Finding]:
    out: List[Finding] = []
    for sf in ctx.iter_files():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            name = None
            if isinstance(node, ast.Attribute) and node.attr in _FORBIDDEN:
                name = node.attr
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name in _FORBIDDEN:
                        name = a.name
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.split(".")[-1] in _FORBIDDEN:
                        name = a.name
            if name is not None:
                out.append(
                    Finding(
                        "host-callback",
                        sf.path,
                        node.lineno,
                        f"host callback {name!r} in library code: every"
                        " execution round-trips through the host, which"
                        " serializes the device pipeline",
                    )
                )
    return out
