"""Rule ``determinism``: library code takes no wall-clock reads and no
unseeded global randomness.

The fault harness's replay guarantee ("a failing sequence replays
exactly" -- faults.py) and the benchmarks' comparability both die the
moment a hot path consults ``time.time`` or the global numpy RNG.
Flagged:

* ``time.time`` / ``time.time_ns`` / ``time.monotonic`` /
  ``time.perf_counter`` / ``datetime.now`` / ``datetime.utcnow``
  (``time.sleep`` is allowed: backoff delays affect *when*, not *what*);
* any ``np.random.*`` / ``numpy.random.*`` use except constructing an
  explicitly seeded generator (``default_rng(seed)`` /
  ``RandomState(seed)`` with at least one argument).

The one legitimate clock home is ``telemetry.py`` -- the telemetry
layer IS the package's clock boundary (``telemetry.clock`` /
``telemetry.wall_time``), so it carries an explicit rule carve-out
(:data:`_CLOCK_ALLOWED_FILES`) rather than inline suppressions: every
other module that needs a timestamp must route through telemetry, and a
clock read anywhere else stays a finding.  The RNG check applies
everywhere, carve-out included.  Tests and benches are out of scope
(the analyzer scans the package tree only).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from sketches_tpu.analysis.lint import Finding, LintContext, rule

_CLOCK_ATTRS = {
    "time": ("time", "time_ns", "monotonic", "monotonic_ns", "perf_counter",
             "perf_counter_ns"),
    "datetime": ("now", "utcnow", "today"),
}

_SEEDED_CTORS = ("default_rng", "RandomState", "Generator", "SeedSequence")

#: Package-relative files allowed to read wall clocks: the telemetry
#: module owns clock()/wall_time() and every instrumented seam calls
#: those instead of ``time`` -- confining the replay hazard to one file.
_CLOCK_ALLOWED_FILES = ("telemetry.py",)


def _attr_chain(node: ast.Attribute) -> List[str]:
    parts: List[str] = []
    cur: ast.AST = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    return parts[::-1]


@rule("determinism")
def check(ctx: LintContext) -> Iterable[Finding]:
    out: List[Finding] = []
    for sf in ctx.iter_files():
        if sf.tree is None:
            continue
        clock_allowed = ctx.rel_in_package(sf.path) in _CLOCK_ALLOWED_FILES
        # Pre-pass: seeded-generator constructions are the sanctioned RNG
        # pattern.  Their func nodes are exempted by identity below.
        seeded_funcs: set = set()
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _SEEDED_CTORS
                and (node.args or node.keywords)
            ):
                for sub in ast.walk(node.func):
                    seeded_funcs.add(id(sub))
        consumed = set()  # sub-attributes of already-flagged chains
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Attribute) or id(node) in seeded_funcs:
                continue
            if id(node) in consumed:
                continue
            chain = _attr_chain(node)
            if len(chain) < 2:
                continue
            root, rest = chain[0], chain[1:]
            if root in _CLOCK_ATTRS and rest[-1] in _CLOCK_ATTRS[root]:
                if clock_allowed:
                    continue
                out.append(
                    Finding(
                        "determinism",
                        sf.path,
                        node.lineno,
                        f"wall-clock read {'.'.join(chain)} in library code;"
                        " route timestamps through sketches_tpu.telemetry"
                        " (the carved-out clock boundary)",
                    )
                )
            elif root in ("np", "numpy") and rest[0] == "random":
                for sub in ast.walk(node):
                    if sub is not node:
                        consumed.add(id(sub))
                out.append(
                    Finding(
                        "determinism",
                        sf.path,
                        node.lineno,
                        f"global-RNG use {'.'.join(chain)} in library code;"
                        " construct an explicitly seeded"
                        " np.random.default_rng(seed) instead",
                    )
                )
    return out
