"""Rule ``failure-docstring``: public API documents its failure modes.

The README's failure-modes table promises that every failure path is a
*documented degradation*; this rule pushes the same discipline down to
the symbol level: every name exported through the package
``__init__.py``'s ``__all__`` must carry a docstring that says what
happens when things go wrong -- what it raises, what degrades, what an
empty/NaN result means.

"Mentions its failure modes" is checked as: the docstring of the object
(or, for classes, of the class or its ``__init__``) matches at least
one failure-vocabulary token (raise/error/fail/NaN/empty/invalid/
degrad.../quarantin.../collaps.../clamp/corrupt/unavailable/refus...).
Shallow by construction -- a lint can check vocabulary, not truth --
but it catches the common rot: a new public symbol landing with no
failure story at all.

Dunder exports (``__version__``) and module re-exports (``resilience``,
``faults``) are exempt: modules document themselves.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Tuple

from sketches_tpu.analysis.lint import Finding, LintContext, rule

_FAILURE_VOCAB = re.compile(
    r"(?i)\b(rais\w*|error\w*|exception\w*|fail\w*|nan|empty|invalid|"
    r"unavailable|corrupt\w*|degrad\w*|quarantin\w*|collaps\w*|clamp\w*|"
    r"refus\w*|fallback|fall\s+back|retr(?:y|ies)|undefined|none)\b"
)


def _exported_names(init_tree: ast.AST) -> List[Tuple[str, int]]:
    for node in ast.walk(init_tree):
        if isinstance(node, ast.Assign):
            targets = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if "__all__" in targets and isinstance(
                node.value, (ast.List, ast.Tuple)
            ):
                return [
                    (e.value, e.lineno)
                    for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                ]
    return []


def _top_level_defs(
    tree: ast.AST,
) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}
    for node in ast.iter_child_nodes(tree):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            out[node.name] = node
    return out


def _docstring_of(node: ast.AST) -> Optional[str]:
    doc = ast.get_docstring(node)
    if doc:
        return doc
    if isinstance(node, ast.ClassDef):
        for child in node.body:
            if isinstance(child, ast.FunctionDef) and child.name == "__init__":
                return ast.get_docstring(child)
    return None


@rule("failure-docstring")
def check(ctx: LintContext) -> Iterable[Finding]:
    init_sf = ctx.file_in_package("__init__.py")
    if init_sf is None or init_sf.tree is None:
        return []
    exported = _exported_names(init_sf.tree)
    if not exported:
        return []

    # Index every top-level def/class in the tree (the export may live in
    # any module; __init__ re-exports it).
    defs: Dict[str, Tuple[str, ast.AST]] = {}
    module_names: set = set()
    for sf in ctx.iter_files():
        if sf.tree is None:
            continue
        in_pkg = ctx.rel_in_package(sf.path)
        stem = in_pkg.rsplit("/", 1)[-1][: -len(".py")]
        module_names.add(stem if stem != "__init__" else in_pkg.split("/")[0])
        if "/" in in_pkg:
            module_names.add(in_pkg.split("/")[0])
        for name, node in _top_level_defs(sf.tree).items():
            defs.setdefault(name, (sf.path, node))

    out: List[Finding] = []
    for name, lineno in exported:
        if name.startswith("__") or name in module_names:
            continue
        hit = defs.get(name)
        if hit is None:
            # Aliased or dynamically-built exports can't be resolved
            # statically; absence from every module is its own problem
            # but not this rule's.
            continue
        path, node = hit
        doc = _docstring_of(node)
        if not doc:
            out.append(
                Finding(
                    "failure-docstring",
                    path,
                    node.lineno,
                    f"public export {name!r} has no docstring; document"
                    " what it raises / how it degrades",
                )
            )
        elif not _FAILURE_VOCAB.search(doc):
            out.append(
                Finding(
                    "failure-docstring",
                    path,
                    node.lineno,
                    f"public export {name!r} docstring never mentions a"
                    " failure mode (what it raises, what degrades, what an"
                    " empty/NaN result means)",
                )
            )
    return out
