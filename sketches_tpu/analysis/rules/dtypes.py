"""Rule ``jnp-f64``: the device tier is f32-only; no float64 construction
on jnp paths.

TPU has no native f64 (ops are emulated, slowly), and the kernels'
bf16-split tricks assume f32 ceilings, so a ``float64`` that sneaks
into a jnp expression either silently demotes (x64 off -- masking the
author's intent) or silently de-optimizes (x64 on).  Host-side numpy
f64 is fine and idiomatic (the host tier is *deliberately* f64); the
rule therefore flags only f64 **construction** on jnp expressions:

* a direct ``jnp.float64`` / ``"float64"`` argument to a ``jnp.*`` call
  (``jnp.asarray(x, jnp.float64)``),
* a ``dtype=`` keyword resolving to f64 on any call in a jnp-importing
  module,
* ``.astype(jnp.float64)`` / ``.astype("float64")``.

Reads and comparisons (``v.dtype == jnp.float64`` -- the mapping layer's
f64-layout support) are allowed: inspecting f64 is not creating it.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from sketches_tpu.analysis.lint import Finding, LintContext, rule


def _imports_jnp(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name == "jax.numpy" for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax" and any(
                a.name == "numpy" for a in node.names
            ):
                return True
            if node.module == "jax.numpy":
                return True
    return False


def _is_f64(node: ast.AST) -> bool:
    """``jnp.float64`` or the ``"float64"`` string.  ``np.float64`` is
    deliberately NOT matched: host-side numpy f64 is the host tier's
    idiom, and the device tier never consumes a numpy dtype object
    without an explicit jnp cast the rule would catch instead."""
    if isinstance(node, ast.Attribute) and node.attr == "float64":
        return isinstance(node.value, ast.Name) and node.value.id == "jnp"
    return isinstance(node, ast.Constant) and node.value == "float64"


def _call_root(node: ast.Call) -> str:
    fn = node.func
    while isinstance(fn, ast.Attribute):
        fn = fn.value
    return fn.id if isinstance(fn, ast.Name) else ""


@rule("jnp-f64")
def check(ctx: LintContext) -> Iterable[Finding]:
    out: List[Finding] = []
    for sf in ctx.iter_files():
        if sf.tree is None or not _imports_jnp(sf.tree):
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            is_astype = isinstance(fn, ast.Attribute) and fn.attr == "astype"
            is_jnp_call = _call_root(node) == "jnp"
            flagged = False
            if is_astype or is_jnp_call:
                flagged = any(_is_f64(a) for a in node.args)
            if not flagged:
                flagged = any(
                    kw.arg == "dtype" and _is_f64(kw.value)
                    for kw in node.keywords
                )
            if flagged:
                out.append(
                    Finding(
                        "jnp-f64",
                        sf.path,
                        node.lineno,
                        "float64 construction on a jnp path; the device"
                        " tier is f32-only (f64 silently demotes with x64"
                        " off and silently de-optimizes on TPU with it on)",
                    )
                )
    return out
