"""Rules ``env-read`` / ``env-literal`` / ``registry-doc``: the kill-switch
inventory stays closed.

UDDSketch (arXiv:2004.08604) shows how silently-drifting configuration
corrupts a sketch's guarantee; our process-level configuration surface
is the ``SKETCHES_TPU_*`` environment variables, and these rules keep
that surface enumerable:

* ``env-read`` -- ``os.environ`` / ``os.getenv`` may be touched ONLY by
  ``analysis/registry.py``.  Any other module must read its lever
  through ``registry.get``/``registry.enabled`` (which refuse
  undeclared names at runtime).
* ``env-literal`` -- a string literal that IS a ``SKETCHES_TPU_*`` name
  outside the registry must match a declared entry: a typo'd or
  undeclared switch is exactly the silent-drift bug.
* ``registry-doc`` -- the README kill-switch table and the registry
  agree in both directions (every declared variable is documented;
  every documented variable is declared).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List

from sketches_tpu.analysis.lint import Finding, LintContext, rule

_ENV_NAME = re.compile(r"^SKETCHES_TPU_[A-Z0-9_]+$")
_README_TOKEN = re.compile(r"\bSKETCHES_TPU_[A-Z0-9_]+\b")

_REGISTRY_FILE = "analysis/registry.py"


def _is_environ_access(node: ast.AST) -> bool:
    """``os.environ`` (any use) or ``os.getenv``/``os.putenv`` call."""
    if isinstance(node, ast.Attribute) and node.attr in (
        "environ",
        "getenv",
        "putenv",
    ):
        base = node.value
        return isinstance(base, ast.Name) and base.id == "os"
    return False


@rule("env-read")
def check_reads(ctx: LintContext) -> Iterable[Finding]:
    out: List[Finding] = []
    for sf in ctx.iter_files(exclude_in_pkg=(_REGISTRY_FILE,)):
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if _is_environ_access(node):
                out.append(
                    Finding(
                        "env-read",
                        sf.path,
                        node.lineno,
                        "environment access outside analysis/registry.py;"
                        " declare the variable there and read it via"
                        " registry.get/registry.enabled",
                    )
                )
    return out


@rule("env-literal")
def check_literals(ctx: LintContext) -> Iterable[Finding]:
    declared = set(ctx.declared_env_vars())
    out: List[Finding] = []
    for sf in ctx.iter_files(exclude_in_pkg=(_REGISTRY_FILE,)):
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _ENV_NAME.match(node.value)
            ):
                continue
            if node.value in declared:
                msg = (
                    f"raw {node.value!r} literal duplicates the registry;"
                    " reference registry.<VAR>.name (or the module's"
                    " re-exported *_ENV alias) instead"
                )
            else:
                msg = (
                    f"{node.value!r} is not declared in"
                    " analysis/registry.py -- an unregistered kill switch"
                )
            out.append(Finding("env-literal", sf.path, node.lineno, msg))
    return out


@rule("registry-doc")
def check_readme(ctx: LintContext) -> Iterable[Finding]:
    registry_sf = ctx.file_in_package(_REGISTRY_FILE)
    if registry_sf is None:
        return []  # fixture trees without a registry have nothing to check
    declared = ctx.declared_env_vars()
    out: List[Finding] = []
    if ctx.readme is None:
        if declared:
            out.append(
                Finding(
                    "registry-doc",
                    registry_sf.path,
                    min(declared.values()),
                    "registry declares kill switches but no README.md was"
                    " found to document them",
                )
            )
        return out
    documented = set(_README_TOKEN.findall(ctx.readme))
    for name, lineno in sorted(declared.items()):
        if name not in documented:
            out.append(
                Finding(
                    "registry-doc",
                    registry_sf.path,
                    lineno,
                    f"registered variable {name} is missing from the README"
                    " kill-switch table",
                )
            )
    for name in sorted(documented - set(declared)):
        out.append(
            Finding(
                "registry-doc",
                registry_sf.path,
                1,
                f"README documents {name} but analysis/registry.py does not"
                " declare it",
            )
        )
    return out
