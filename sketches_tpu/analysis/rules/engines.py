"""Rule ``engine-ladder``: the query-engine policy, ladder, and fault
dispatch cannot drift apart.

Three places must agree on the set of query engines:

1. ``kernels.choose_query_engine`` -- the ONE policy function both
   facades consult; the engines it can return are the string constants
   in its ``return`` statements.
2. ``resilience.QUERY_LADDER`` -- the degradation order.  Every
   returnable engine must be a rung, and every non-floor rung must be
   demotable by ``resilience.demote_query_tier`` (the ``tier == "..."``
   branches), or a lowering failure on that engine would re-raise
   instead of degrading.
3. The facades' fault dispatch -- ``batched.py`` and ``parallel.py``
   must each carry a ``faults.inject(faults.PALLAS_LOWERING, ...)``
   seam at query dispatch, or injected lowering faults cannot exercise
   the ladder at all.

All checks are AST-level (constants extracted, nothing imported), so a
rename in one place is caught even when the tree still imports cleanly.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from sketches_tpu.analysis.lint import Finding, LintContext, rule

_FACADES = ("batched.py", "parallel.py")


def _find_function(
    tree: ast.AST, name: str
) -> Optional[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def _string_returns(fn: ast.FunctionDef) -> Set[Tuple[str, int]]:
    """String constants a function can return, including strings inside
    conditional expressions (``"a" if c else "b"``)."""
    out: Set[Tuple[str, int]] = set()

    def collect(expr: Optional[ast.AST], lineno: int) -> None:
        if expr is None:
            return
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            out.add((expr.value, lineno))
        elif isinstance(expr, ast.IfExp):
            collect(expr.body, lineno)
            collect(expr.orelse, lineno)

    for node in ast.walk(fn):
        if isinstance(node, ast.Return):
            collect(node.value, node.lineno)
    return out


def _tuple_assignment(tree: ast.AST, name: str) -> Set[str]:
    """String elements of a module-level ``NAME = ("a", "b", ...)``."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if name in targets and isinstance(
                node.value, (ast.Tuple, ast.List)
            ):
                return {
                    e.value
                    for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }
    return set()


def _compared_tiers(fn: ast.FunctionDef) -> Set[str]:
    """String constants a function compares its ``tier`` argument against."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare):
            for comp in node.comparators:
                if isinstance(comp, ast.Constant) and isinstance(
                    comp.value, str
                ):
                    out.add(comp.value)
    return out


def _has_lowering_dispatch(tree: ast.AST) -> bool:
    """Whether the module calls ``faults.inject(faults.PALLAS_LOWERING, ...)``."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (
            isinstance(fn, ast.Attribute)
            and fn.attr == "inject"
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "faults"
        ):
            continue
        for arg in node.args:
            if isinstance(arg, ast.Attribute) and arg.attr == "PALLAS_LOWERING":
                return True
    return False


@rule("engine-ladder")
def check(ctx: LintContext) -> Iterable[Finding]:
    out: List[Finding] = []
    kernels = ctx.file_in_package("kernels.py")
    resilience = ctx.file_in_package("resilience.py")
    if kernels is None or kernels.tree is None:
        return out
    chooser = _find_function(kernels.tree, "choose_query_engine")
    if chooser is None:
        out.append(
            Finding(
                "engine-ladder",
                kernels.path,
                1,
                "kernels.py no longer defines choose_query_engine; the"
                " engine-policy single source of truth is gone",
            )
        )
        return out
    returns = _string_returns(chooser)
    if not returns:
        out.append(
            Finding(
                "engine-ladder",
                kernels.path,
                chooser.lineno,
                "choose_query_engine returns no string engine constants;"
                " the ladder cross-check cannot see its policy",
            )
        )
        return out

    ladder: Set[str] = set()
    demotable: Set[str] = set()
    if resilience is not None and resilience.tree is not None:
        ladder = _tuple_assignment(resilience.tree, "QUERY_LADDER")
        demote = _find_function(resilience.tree, "demote_query_tier")
        if demote is not None:
            demotable = _compared_tiers(demote)

    floor = None
    if ladder:
        # The floor (last rung) re-raises instead of demoting, by design.
        # AST sets lose order, so recover it from the source tuple.
        for engine in ("xla",):
            if engine in ladder:
                floor = engine
    for engine, lineno in sorted(returns):
        if ladder and engine not in ladder:
            out.append(
                Finding(
                    "engine-ladder",
                    kernels.path,
                    lineno,
                    f"choose_query_engine can return {engine!r}, which is"
                    " not a rung of resilience.QUERY_LADDER",
                )
            )
        if demotable and engine not in demotable and engine != floor:
            out.append(
                Finding(
                    "engine-ladder",
                    kernels.path,
                    lineno,
                    f"choose_query_engine can return {engine!r}, which"
                    " resilience.demote_query_tier cannot demote -- a"
                    " lowering failure there would re-raise instead of"
                    " degrading",
                )
            )

    for facade in _FACADES:
        sf = ctx.file_in_package(facade)
        if sf is None or sf.tree is None:
            continue
        if not _has_lowering_dispatch(sf.tree):
            out.append(
                Finding(
                    "engine-ladder",
                    sf.path,
                    1,
                    f"{facade} has no faults.inject(faults.PALLAS_LOWERING,"
                    " ...) dispatch seam; injected lowering faults cannot"
                    " exercise its query ladder",
                )
            )
    return out
