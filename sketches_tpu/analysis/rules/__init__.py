"""sketchlint rules: each module encodes one repo invariant.

Importing this package registers every rule with the engine
(``lint.rule`` decorator); ``lint.all_rules()`` triggers the import.

Rule inventory (ids double as the inline-ignore tags):

==================  ======================================================
id                  invariant
==================  ======================================================
taxonomy-raise      no bare ``ValueError``/``RuntimeError`` raises outside
                    ``resilience.py`` -- everything derives from
                    ``SketchError``
env-read            ``os.environ``/``os.getenv`` reads only inside
                    ``analysis/registry.py``
env-literal         every ``SKETCHES_TPU_*`` string literal outside the
                    registry must be a *declared* variable's name
registry-doc        registry entries and the README kill-switch table
                    agree in both directions
engine-ladder       every engine ``choose_query_engine`` can return is a
                    rung of ``resilience.QUERY_LADDER``, demotable by
                    ``demote_query_tier``, and fault-dispatched in both
                    facades
jnp-f64             no ``float64`` construction on jnp paths (f32-only
                    device tier)
determinism         no ``time.time``-family wall-clock reads or unseeded
                    ``np.random`` in library code (``telemetry.py`` is the
                    one carved-out clock boundary)
failure-docstring   every public ``__all__`` symbol documents its failure
                    modes
host-callback       no ``pure_callback``/``io_callback``/``host_callback``
                    in library code (hot paths must not sync to host)
telemetry-names     every telemetry metric/span name in the package is a
                    string literal declared in ``telemetry.py``'s
                    ``Metric`` inventory (no stringly-typed drift)
lock-discipline     attributes accessed under a class's instance lock are
                    accessed under it *everywhere* (whole-class inference,
                    ``analysis/concurrency.py``)
lock-escape         lock-guarded objects never leak raw out of the lock
                    region (returned or stored onto a foreign object)
seam-premutation    methods passing a torn ``faults.ATOMIC_SITES`` site
                    mutate no ``self`` state before the seam
                    (``analysis/seams.py``)
seam-commit         the first post-seam ``self`` mutation is a single
                    reference swap, not an in-place edit
seam-sites          ``ATOMIC_SITES`` is a subset of ``SITES`` and every
                    ``*_TORN`` inject site is declared atomic
site-detector       every ``faults.SITES`` member has a
                    ``_SITE_DETECTORS`` entry in
                    ``tests/test_integrity.py`` (and no stale keys)
metric-doc          every declared ``Metric`` has a backticked README row
campaign-ci         every chaos ``--campaign`` choice is exercised by a CI
                    workflow
==================  ======================================================
"""

from sketches_tpu.analysis import (  # noqa: F401  (import = register)
    concurrency,
    seams,
)
from sketches_tpu.analysis.rules import (  # noqa: F401  (import = register)
    callbacks,
    closure,
    determinism,
    docstrings,
    dtypes,
    engines,
    env_registry,
    raises,
    telemetry_names,
)

__all__ = [
    "callbacks",
    "closure",
    "concurrency",
    "determinism",
    "docstrings",
    "dtypes",
    "engines",
    "env_registry",
    "raises",
    "seams",
    "telemetry_names",
]
