"""Rules ``site-detector`` / ``metric-doc`` / ``campaign-ci``: test-time
inventories become lint-time failures.

Three closure properties the repo already asserts *dynamically* --
``tests/test_integrity.py``'s ``test_every_site_has_a_detector``, the
telemetry README table, and the chaos campaign matrix -- are promoted
to ``python -m sketches_tpu.analysis`` failures so a gap fails the
static-analysis job (seconds) instead of a soak job (minutes), and
fails it even when the test suite is filtered:

* ``site-detector`` -- every ``faults.SITES`` member appears as a
  ``faults.<CONST>`` key of ``tests/test_integrity.py``'s
  ``_SITE_DETECTORS`` table, and every detector key is a declared site
  (a stale key is a detector probing nothing).
* ``metric-doc`` -- every ``Metric(...)`` declared in ``telemetry.py``
  has a README row.  README tokens are backticked; a ``{...}`` suffix
  is either a label set (``ingest_s{component,engine}`` -> strip) or a
  brace expansion (``ingest.variant.{stock,packed}`` -> one row per
  member), and both readings are accepted.
* ``campaign-ci`` -- every ``chaos --campaign`` choice is exercised by
  some CI workflow: an explicit ``--campaign <name>`` occurrence, or --
  for the argparse default only -- any bare ``sketches_tpu.chaos``
  invocation.

Failure modes: the aux inventories live *outside* the package, so a
scan of an installed package (no ``tests/``, no ``.github/``) reports
the missing inventory as a finding rather than silently passing --
suppress with the usual inline/baseline machinery if such a scan is
ever intended.  Fixture trees without ``faults.py`` / ``telemetry.py``
/ ``chaos.py`` skip the corresponding rule (nothing is declared).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set

from sketches_tpu.analysis.lint import Finding, LintContext, rule

_INTEGRITY_AUX = "tests/test_integrity.py"
_BACKTICK = re.compile(r"`([^`\s][^`]*)`")
_EXPANSION = re.compile(r"^(.*)\{([^{}]+)\}(.*)$")


def _sites_decl(ctx: LintContext) -> Dict[str, int]:
    """``faults.SITES`` member constant names -> declaration line."""
    sf = ctx.file_in_package("faults.py")
    if sf is None or sf.tree is None:
        return {}
    for node in sf.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "SITES"
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            return {
                e.id: e.lineno
                for e in node.value.elts
                if isinstance(e, ast.Name)
            }
    return {}


@rule("site-detector")
def check_site_detectors(ctx: LintContext) -> Iterable[Finding]:
    sites = _sites_decl(ctx)
    if not sites:
        return []
    faults_sf = ctx.file_in_package("faults.py")
    assert faults_sf is not None  # _sites_decl parsed it
    aux = ctx.aux_trees.get(_INTEGRITY_AUX)
    if aux is None or aux.tree is None:
        return [
            Finding(
                "site-detector",
                faults_sf.path,
                min(sites.values()),
                f"faults.SITES declares {len(sites)} fault sites but no"
                f" {_INTEGRITY_AUX} detector inventory was found next to"
                " the package",
            )
        ]
    detectors: Dict[str, int] = {}
    for node in ast.walk(aux.tree):
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "_SITE_DETECTORS"
            and isinstance(node.value, ast.Dict)
        ):
            continue
        for key in node.value.keys:
            if (
                isinstance(key, ast.Attribute)
                and isinstance(key.value, ast.Name)
                and key.value.id == "faults"
            ):
                detectors[key.attr] = key.lineno
    out: List[Finding] = []
    for name, lineno in sorted(sites.items()):
        if name not in detectors:
            out.append(
                Finding(
                    "site-detector",
                    faults_sf.path,
                    lineno,
                    f"fault site faults.{name} has no _SITE_DETECTORS entry"
                    f" in {_INTEGRITY_AUX}; every site needs a detector"
                    " proving its fault is observable",
                )
            )
    for name, lineno in sorted(detectors.items()):
        if name not in sites:
            out.append(
                Finding(
                    "site-detector",
                    aux.path,
                    lineno,
                    f"_SITE_DETECTORS key faults.{name} is not a member of"
                    " faults.SITES -- a detector probing an undeclared"
                    " site",
                )
            )
    return out


def _readme_metric_tokens(readme: str) -> Set[str]:
    """Every backticked README token, with ``{...}`` read both as a
    label suffix (stripped) and as a brace expansion (each member)."""
    out: Set[str] = set()
    for tok in _BACKTICK.findall(readme):
        tok = tok.strip()
        out.add(tok)
        m = _EXPANSION.match(tok)
        if m is None:
            continue
        head, members, tail = m.groups()
        out.add((head + tail).rstrip("."))
        out.add(head.rstrip(".{") + tail)
        for member in members.split(","):
            out.add(f"{head}{member.strip()}{tail}")
    return out


@rule("metric-doc")
def check_metric_docs(ctx: LintContext) -> Iterable[Finding]:
    from sketches_tpu.analysis.rules.telemetry_names import _declared_metrics

    declared = _declared_metrics(ctx)
    if not declared:
        return []
    telemetry_sf = ctx.file_in_package("telemetry.py")
    assert telemetry_sf is not None  # _declared_metrics parsed it
    if ctx.readme is None:
        return [
            Finding(
                "metric-doc",
                telemetry_sf.path,
                min(declared.values()),
                f"telemetry.py declares {len(declared)} metrics but no"
                " README.md was found to document them",
            )
        ]
    documented = _readme_metric_tokens(ctx.readme)
    out: List[Finding] = []
    for name, lineno in sorted(declared.items()):
        if name not in documented:
            out.append(
                Finding(
                    "metric-doc",
                    telemetry_sf.path,
                    lineno,
                    f"declared metric {name!r} has no README row; an"
                    " operator cannot discover what the process measures",
                )
            )
    return out


def _campaign_choices(ctx: LintContext) -> Dict[str, int]:
    """``--campaign`` argparse choices in ``chaos.py`` -> line, plus the
    default under the pseudo-key ``__default__:<name>``."""
    sf = ctx.file_in_package("chaos.py")
    if sf is None or sf.tree is None:
        return {}
    out: Dict[str, int] = {}
    for node in ast.walk(sf.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value == "--campaign"
        ):
            continue
        default: Optional[str] = None
        for kw in node.keywords:
            if kw.arg == "choices" and isinstance(
                kw.value, (ast.Tuple, ast.List)
            ):
                for e in kw.value.elts:
                    if isinstance(e, ast.Constant) and isinstance(
                        e.value, str
                    ):
                        out[e.value] = e.lineno
            if kw.arg == "default" and isinstance(kw.value, ast.Constant):
                default = kw.value.value
        if isinstance(default, str):
            out.setdefault(default, node.lineno)
            out["__default__:" + default] = node.lineno
    return out


@rule("campaign-ci")
def check_campaign_ci(ctx: LintContext) -> Iterable[Finding]:
    choices = _campaign_choices(ctx)
    default = next(
        (k.split(":", 1)[1] for k in choices if k.startswith("__default__:")),
        None,
    )
    names = {
        k: v
        for k, v in choices.items()
        if k and not k.startswith("__default__:")
    }
    if not names:
        return []
    chaos_sf = ctx.file_in_package("chaos.py")
    assert chaos_sf is not None  # _campaign_choices parsed it
    if not ctx.aux_texts:
        return [
            Finding(
                "campaign-ci",
                chaos_sf.path,
                min(names.values()),
                f"chaos declares {len(names)} campaigns but no CI workflow"
                " files were found next to the package",
            )
        ]
    ci_blob = "\n".join(ctx.aux_texts.values())
    # A default-campaign run is a chaos invocation with NO explicit
    # --campaign on the same line.
    bare_chaos = any(
        re.search(r"-m\s+sketches_tpu\.chaos\b", line)
        and "--campaign" not in line
        for line in ci_blob.splitlines()
    )
    out: List[Finding] = []
    for name, lineno in sorted(names.items()):
        explicit = re.search(
            rf"--campaign[=\s]+{re.escape(name)}\b", ci_blob
        )
        if explicit is None and not (name == default and bare_chaos):
            out.append(
                Finding(
                    "campaign-ci",
                    chaos_sf.path,
                    lineno,
                    f"chaos campaign {name!r} is never run by a CI"
                    " workflow (no '--campaign" f" {name}' in"
                    " .github/workflows); an unexercised campaign is"
                    " dead coverage",
                )
            )
    return out
