"""Rule ``taxonomy-raise``: every raise goes through the SketchError taxonomy.

PR 2 rooted the library's own failures in ``resilience.SketchError`` so
``except SketchError`` catches everything the package raises on its own
behalf, and so legacy ``except ValueError`` call sites keep working via
the taxonomy's dual bases.  A fresh ``raise ValueError(...)`` or
``raise RuntimeError(...)`` silently re-opens the hole: the failure
escapes the taxonomy, the health ledger, and the documented contract.

``resilience.py`` itself (the taxonomy's home) is exempt; so is the
analyzer subsystem (which sits below the package and may not import it).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from sketches_tpu.analysis.lint import Finding, LintContext, rule

#: The bare builtins the taxonomy replaces.  TypeError /
#: NotImplementedError stay allowed: they mark caller-side type bugs and
#: abstract methods, not library failure modes.
_BARE = ("ValueError", "RuntimeError")

_EXEMPT = ("resilience.py",)


@rule("taxonomy-raise")
def check(ctx: LintContext) -> Iterable[Finding]:
    out: List[Finding] = []
    for sf in ctx.iter_files(exclude_in_pkg=_EXEMPT):
        if sf.tree is None or ctx.rel_in_package(sf.path).startswith("analysis/"):
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            target = node.exc
            if isinstance(target, ast.Call):
                target = target.func
            name = None
            if isinstance(target, ast.Name):
                name = target.id
            elif isinstance(target, ast.Attribute):
                name = target.attr
            if name in _BARE:
                out.append(
                    Finding(
                        "taxonomy-raise",
                        sf.path,
                        node.lineno,
                        f"bare `raise {name}` bypasses the SketchError"
                        " taxonomy; raise a resilience.* subclass"
                        " (SpecError/SketchValueError keep ValueError"
                        " compatibility)",
                    )
                )
    return out
