"""sketchlint: the repo-specific static analyzer (docs/DESIGN.md section 9).

Two layers, one CLI (``python -m sketches_tpu.analysis``, non-zero exit
on violations):

* **Layer 1 -- AST lint** (:mod:`~sketches_tpu.analysis.lint` +
  ``analysis/rules/``): a small rule engine over ``ast`` encoding the
  invariants the test suite can only sample -- the ``SketchError``
  taxonomy, the kill-switch registry, the engine fallback ladder, the
  f32-only device tier, deterministic hot paths, failure-mode
  docstrings.
* **Layer 2 -- jaxpr/lowering audit**
  (:mod:`~sketches_tpu.analysis.jaxpr_audit`): trace every engine entry
  point and verify what actually lowers -- no f64 ops, no host
  callbacks, no weak-type scalar leaks, and a VMEM-budget check on the
  overlap engine's DMA ring.

This package also hosts the **kill-switch registry**
(:mod:`~sketches_tpu.analysis.registry`): the single declared inventory
of ``SKETCHES_TPU_*`` environment variables, which the production
modules read at import time.  ``registry`` is therefore imported
eagerly (it is stdlib-only and cycle-free); the analyzer layers load
lazily so importing ``sketches_tpu`` never pays for them.

Module-level failure story: the registry refuses undeclared variable
names with ``KeyError``; the analyzer layers never raise on findings --
violations are *returned* (and exit-coded by the CLI), and even a
syntax error in a scanned file becomes a finding rather than an
exception.
"""

from sketches_tpu.analysis import registry

__all__ = ["registry", "lint", "jaxpr_audit"]


def __getattr__(name):
    # Lazy layer loading: `analysis.lint` / `analysis.jaxpr_audit` import
    # on first attribute access, so `import sketches_tpu` (which pulls
    # this package for the registry) stays free of analyzer weight.
    if name in ("lint", "jaxpr_audit"):
        import importlib

        return importlib.import_module(f"sketches_tpu.analysis.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
