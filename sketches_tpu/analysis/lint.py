"""sketchlint layer 1: the AST rule engine.

A small, repo-specific static analyzer: rules (``analysis/rules/``)
encode the conventions PR 1/2 introduced -- the ``SketchError``
taxonomy, the kill-switch registry, the engine fallback ladder, f32
device paths, deterministic hot paths, failure-mode docstrings -- and
this module gives them a shared scanning context, inline suppression,
and a baseline file so pre-existing findings can be grandfathered while
new ones fail CI.

Vocabulary:

* **Finding** -- one violation: rule id, file, line, message.  Its
  ``fingerprint`` is content-addressed (rule + path + message, not line
  numbers), so baselines survive unrelated edits.
* **Inline suppression** -- ``# sketchlint: ignore[rule-id]`` (or a bare
  ``# sketchlint: ignore``) on the flagged line or the line above.
  Use it for individually-justified exceptions; the comment doubles as
  the justification's anchor.
* **Baseline** -- a JSON file of fingerprints (plus required
  ``reason`` strings) that are reported but do not fail the run.  The
  intended steady state is an EMPTY baseline: fix findings instead of
  baselining them, and treat a non-empty baseline as debt.

The engine is pure stdlib (``ast``) and never imports the code under
analysis, so it runs identically with or without jax installed and can
scan fixture trees in tests.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Callable, Dict, Iterable, List, Optional, Sequence

__all__ = [
    "Finding",
    "SourceFile",
    "LintContext",
    "rule",
    "all_rules",
    "run_lint",
    "load_baseline",
    "apply_baseline",
    "write_baseline",
]

# The directive may sit anywhere inside a comment ("# why...  sketchlint:
# ignore[rule]"), so the justification and the suppression share a line.
_IGNORE_RE = re.compile(r"#.*\bsketchlint:\s*ignore(?:\[([a-z0-9_,\- ]+)\])?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str  # posix-style path relative to the scanned root's parent
    line: int
    message: str
    layer: str = "lint"  # "lint" (AST) or "jaxpr" (lowering audit)

    @property
    def fingerprint(self) -> str:
        """Content-addressed id: stable across line-number drift."""
        digest = hashlib.sha256(
            f"{self.rule}|{self.path}|{self.message}".encode()
        ).hexdigest()
        return digest[:16]

    def to_dict(self) -> dict:
        out = dataclasses.asdict(self)
        out["fingerprint"] = self.fingerprint
        return out

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """One parsed module: path, source text, AST, and per-line access."""

    def __init__(self, rel_path: str, text: str):
        self.path = rel_path
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(text, filename=rel_path)
        except SyntaxError as e:
            self.parse_error = e

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def is_suppressed(self, lineno: int, rule_id: str) -> bool:
        """``# sketchlint: ignore[...]`` on the line or the line above."""
        for ln in (lineno, lineno - 1):
            m = _IGNORE_RE.search(self.line_at(ln))
            if m:
                listed = m.group(1)
                if listed is None:
                    return True
                if rule_id in {s.strip() for s in listed.split(",")}:
                    return True
        return False


class LintContext:
    """Everything a rule may inspect: the parsed tree plus repo documents.

    ``root`` is the *package* directory under analysis (``sketches_tpu/``
    in the live tree; a synthetic mini-package in fixture tests).  File
    paths in findings are relative to the root's parent so they read as
    repo-relative (``sketches_tpu/native.py``).
    """

    #: Directory/file basenames never scanned.
    EXCLUDE_NAMES = frozenset({"__pycache__", "ddsketch_pb2.py"})

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.package = os.path.basename(self.root)
        base = os.path.dirname(self.root)
        self.files: Dict[str, SourceFile] = {}
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = sorted(
                d for d in dirnames if d not in self.EXCLUDE_NAMES
            )
            for fn in sorted(filenames):
                if not fn.endswith(".py") or fn in self.EXCLUDE_NAMES:
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, base).replace(os.sep, "/")
                with open(full, "r", encoding="utf-8") as f:
                    self.files[rel] = SourceFile(rel, f.read())
        self.readme: Optional[str] = None
        for cand in (
            os.path.join(base, "README.md"),
            os.path.join(self.root, "README.md"),
        ):
            if os.path.exists(cand):
                with open(cand, "r", encoding="utf-8") as f:
                    self.readme = f.read()
                break
        # Aux inventories OUTSIDE the package: the closure rules prove
        # test-time and CI-time inventories against the package, so the
        # context carries them when the surrounding repo checkout has
        # them (absent in installed-package scans -- rules then report
        # the missing inventory rather than silently passing).
        self.aux_trees: Dict[str, SourceFile] = {}
        self.aux_texts: Dict[str, str] = {}
        aux_py = os.path.join(base, "tests", "test_integrity.py")
        if os.path.exists(aux_py):
            rel = os.path.relpath(aux_py, base).replace(os.sep, "/")
            with open(aux_py, "r", encoding="utf-8") as f:
                self.aux_trees[rel] = SourceFile(rel, f.read())
        wf_dir = os.path.join(base, ".github", "workflows")
        if os.path.isdir(wf_dir):
            for fn in sorted(os.listdir(wf_dir)):
                if not fn.endswith((".yml", ".yaml")):
                    continue
                rel = f".github/workflows/{fn}"
                with open(os.path.join(wf_dir, fn), "r", encoding="utf-8") as f:
                    self.aux_texts[rel] = f.read()

    # -- path helpers -------------------------------------------------------
    def rel_in_package(self, rel_path: str) -> str:
        """Path relative to the package root (``native.py``,
        ``analysis/registry.py``)."""
        prefix = self.package + "/"
        return rel_path[len(prefix):] if rel_path.startswith(prefix) else rel_path

    def file_in_package(self, in_pkg: str) -> Optional[SourceFile]:
        return self.files.get(f"{self.package}/{in_pkg}")

    def iter_files(
        self, exclude_in_pkg: Sequence[str] = ()
    ) -> Iterable[SourceFile]:
        for rel, sf in self.files.items():
            if self.rel_in_package(rel) in exclude_in_pkg:
                continue
            yield sf

    # -- registry declarations (parsed, never imported) ---------------------
    def declared_env_vars(self) -> Dict[str, int]:
        """``SKETCHES_TPU_*`` names declared in ``analysis/registry.py``
        -> line number, by parsing its ``EnvVar(name=...)`` calls."""
        sf = self.file_in_package("analysis/registry.py")
        out: Dict[str, int] = {}
        if sf is None or sf.tree is None:
            return out
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call) and _call_name(node) == "EnvVar"):
                continue
            name = None
            if node.args and isinstance(node.args[0], ast.Constant):
                name = node.args[0].value
            for kw in node.keywords:
                if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    name = kw.value.value
            if isinstance(name, str):
                out[name] = node.lineno
        return out


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


# ---------------------------------------------------------------------------
# Rule registration
# ---------------------------------------------------------------------------

RuleFn = Callable[[LintContext], Iterable[Finding]]
_RULES: Dict[str, RuleFn] = {}


def rule(rule_id: str) -> Callable[[RuleFn], RuleFn]:
    """Register a rule function under ``rule_id`` (its inline-ignore tag)."""

    def deco(fn: RuleFn) -> RuleFn:
        if rule_id in _RULES:
            raise KeyError(f"duplicate rule id {rule_id!r}")
        _RULES[rule_id] = fn
        fn.rule_id = rule_id  # type: ignore[attr-defined]
        return fn

    return deco


def all_rules() -> Dict[str, RuleFn]:
    """Every registered rule, importing the rule modules on first use."""
    from sketches_tpu.analysis import rules as _rules_pkg  # noqa: F401

    return dict(_RULES)


def run_lint(
    root: str, only: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Run every (or ``only`` the named) rule over the package at ``root``.

    Returns findings sorted by (path, line, rule), inline suppressions
    already removed.  Unparseable files surface as ``syntax`` findings
    rather than crashing the run.
    """
    ctx = LintContext(root)
    findings: List[Finding] = []
    for sf in ctx.files.values():
        if sf.parse_error is not None:
            findings.append(
                Finding(
                    "syntax",
                    sf.path,
                    sf.parse_error.lineno or 1,
                    f"file does not parse: {sf.parse_error.msg}",
                )
            )
    for rule_id, fn in sorted(all_rules().items()):
        if only is not None and rule_id not in only:
            continue
        for f in fn(ctx):
            sf = ctx.files.get(f.path)
            if sf is not None and sf.is_suppressed(f.line, f.rule):
                continue
            findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


# ---------------------------------------------------------------------------
# Baseline (suppression) file
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> Dict[str, str]:
    """``{fingerprint: reason}`` from a baseline JSON file ('' if absent)."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    out: Dict[str, str] = {}
    for entry in data.get("suppressions", []):
        out[entry["fingerprint"]] = entry.get("reason", "")
    return out


def apply_baseline(
    findings: Sequence[Finding], baseline: Dict[str, str]
) -> List[Finding]:
    """Findings NOT covered by the baseline (the ones that fail the run)."""
    return [f for f in findings if f.fingerprint not in baseline]


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    """Write every current finding as a suppression (``--update-baseline``).

    Each entry gets a placeholder reason naming the finding; a human is
    expected to either fix the finding or replace the placeholder with a
    real justification in review.
    """
    seen: Dict[str, dict] = {}
    for f in findings:
        seen.setdefault(
            f.fingerprint, {"fingerprint": f.fingerprint, "reason": str(f)}
        )
    payload = {"version": 1, "suppressions": sorted(
        seen.values(), key=lambda e: e["fingerprint"]
    )}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
