"""CLI: ``python -m sketches_tpu.analysis`` -- run sketchlint, exit
non-zero on violations.

Default run (no arguments): AST lint + jaxpr audit over the installed
``sketches_tpu`` package, findings filtered through the checked-in
baseline (``analysis/baseline.json``), human-readable findings on
stdout, exit 1 if anything non-baselined remains.  This is exactly what
the CI ``static-analysis`` job runs on every push.

Useful flags::

    --no-jaxpr            AST layer only (fast; no jax import)
    --json PATH           write the machine-readable report
    --root PATH           lint a different package tree (fixture tests)
    --rules a,b           run only the named rules
    --baseline PATH       override the suppression file
    --update-baseline     rewrite the baseline to suppress every current
                          finding (then justify or fix each entry!)
    --stats               per-rule finding counts + files scanned
    --budgets PATH        override the static-cost budgets file
    --update-budgets      re-measure and rewrite the budgets file (then
                          justify the new ceilings in review!)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from sketches_tpu.analysis import lint as lint_mod
from sketches_tpu.analysis.lint import Finding


def _default_root() -> str:
    """The installed sketches_tpu package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: List[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sketches_tpu.analysis",
        description="sketchlint: AST invariant lint + jaxpr/lowering audit",
    )
    parser.add_argument(
        "--root",
        default=None,
        help="package directory to lint (default: the installed"
        " sketches_tpu); the jaxpr audit only runs on the default root",
    )
    parser.add_argument(
        "--rules",
        default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--baseline",
        default=None,
        help="suppression file (default: <root>/analysis/baseline.json)",
    )
    parser.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline to suppress every current finding",
    )
    parser.add_argument(
        "--no-jaxpr",
        action="store_true",
        help="skip the jaxpr/lowering audit (no jax import)",
    )
    parser.add_argument(
        "--json", dest="json_path", default=None,
        help="write the machine-readable JSON report here",
    )
    parser.add_argument(
        "--stats",
        action="store_true",
        help="print per-rule finding counts and the files-scanned total",
    )
    parser.add_argument(
        "--budgets",
        default=None,
        help="static-cost budgets file (default:"
        " <root>/analysis/budgets.json; jaxpr layer only)",
    )
    parser.add_argument(
        "--update-budgets",
        action="store_true",
        help="re-measure every static cost and rewrite the budgets file",
    )
    args = parser.parse_args(argv)

    root = os.path.abspath(args.root or _default_root())
    only = args.rules.split(",") if args.rules else None
    baseline_path = args.baseline or os.path.join(
        root, "analysis", "baseline.json"
    )

    findings = lint_mod.run_lint(root, only=only)

    report = {
        "root": root,
        "layers": {"lint": True, "jaxpr": False},
        "findings": [],
        "jaxpr": None,
    }
    # The jaxpr audit traces the *imported* package, so it only means
    # something when the linted root IS that package.
    run_jaxpr = not args.no_jaxpr and root == _default_root()
    budgets_path = args.budgets or os.path.join(
        root, "analysis", "budgets.json"
    )
    if args.update_budgets:
        if not run_jaxpr:
            print(
                "error: --update-budgets needs the jaxpr layer (default"
                " root, no --no-jaxpr)",
                file=sys.stderr,
            )
            return 2
        from sketches_tpu.analysis import jaxpr_audit

        doc = jaxpr_audit.measure_budgets()
        jaxpr_audit.write_budgets(budgets_path, doc)
        print(
            f"budgets: wrote {len(doc['entries'])} entry pin(s),"
            f" {len(doc['ingest_elem_ops_per_value'])} ingest-variant"
            f" pin(s) to {budgets_path}"
        )
        return 0
    if run_jaxpr:
        from sketches_tpu.analysis import jaxpr_audit

        jaxpr_findings, jaxpr_report = jaxpr_audit.audit(
            budgets_path=budgets_path
        )
        findings.extend(jaxpr_findings)
        report["layers"]["jaxpr"] = True
        report["jaxpr"] = jaxpr_report

    if args.update_baseline:
        lint_mod.write_baseline(baseline_path, findings)
        print(
            f"baseline: wrote {len(findings)} suppression(s) to"
            f" {baseline_path}"
        )
        return 0

    baseline = lint_mod.load_baseline(baseline_path)
    active = lint_mod.apply_baseline(findings, baseline)
    suppressed = len(findings) - len(active)
    stale = sorted(
        set(baseline) - {f.fingerprint for f in findings}
    )

    report["findings"] = [f.to_dict() for f in findings]
    report["baseline"] = {
        "path": baseline_path,
        "suppressed": suppressed,
        "stale_fingerprints": stale,
    }
    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")

    for f in active:
        print(f)
    if args.stats:
        ctx_files = len(lint_mod.LintContext(root).files)
        print(f"stats: {ctx_files} file(s) scanned")
        counts: dict = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        for rule_id in sorted(counts):
            print(f"stats: {rule_id}: {counts[rule_id]}")
        if not counts:
            print("stats: no findings")
    if stale:
        print(
            f"warning: {len(stale)} stale baseline entr"
            f"{'y' if len(stale) == 1 else 'ies'} (fixed findings --"
            " remove them): " + ", ".join(stale),
            file=sys.stderr,
        )
    n_rules_note = f" ({suppressed} baselined)" if suppressed else ""
    if active:
        first = active[0]
        print(
            f"sketchlint: {len(active)} violation(s){n_rules_note};"
            f" first offender: [{first.rule}] at {first.path}:{first.line}",
            file=sys.stderr,
        )
        return 1
    print(f"sketchlint: clean{n_rules_note}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
