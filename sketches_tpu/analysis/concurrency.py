"""sketchlint lock-discipline pass: per-class data-race detection.

The serving tier (``serve.SketchServer``, ``fabric.ServeFabric``) keeps
its mutable state consistent with one instance ``threading.RLock``; the
convention is structural, not advisory: *every* access to an attribute
that is ever touched under ``with self._lock`` must itself hold the
lock, otherwise a reader can observe a torn multi-attribute update (the
exact bug class the chaos campaigns probe dynamically).  This pass
proves the convention at lint time, per class:

1. **Lock detection** -- an attribute assigned ``threading.Lock()`` /
   ``threading.RLock()`` anywhere in the class is a lock attribute; a
   class with none is skipped (single-threaded facades such as
   ``WindowedSketch`` are out of scope by construction).
2. **Locked-context closure** -- a statement is *locked* when it sits
   syntactically inside ``with self._lock:``, when its method's name
   ends in ``_locked`` (the caller-must-hold convention), or when
   *every* in-class call site of its method is itself locked (computed
   as a greatest fixpoint over the in-class call graph, so helper
   chains like ``flush -> _dispatch_group -> _fused_quantile`` are
   recognized without annotations).  ``__init__`` counts as a locked
   caller: construction happens-before publication.
3. **Guarded set** -- the attributes read or written at locked sites
   (lock attributes themselves excluded).  Attributes only ever touched
   outside the lock are deliberately unguarded (nothing to protect).
4. **Findings** -- ``lock-discipline``: a read/write of a guarded
   attribute at an unlocked site, or a call of a ``*_locked`` method
   from an unlocked site.  ``lock-escape``: a guarded attribute's
   *object* leaks out of the lock region -- ``return self._cache`` or
   storing ``self._cache`` onto a foreign object -- so the caller holds
   a reference the lock no longer covers; hand out a copy, a snapshot,
   or a facade instead.

Failure modes the pass accepts (documented, not bugs): accesses inside
``__init__`` never flag (pre-publication); nested functions inherit the
lock depth of their definition site (a closure stashed and called later
defeats this -- none exist in the tree, and one that appears should be
rewritten, not accommodated); attribute accesses through ``self``
only -- state reached via a second object is that object's contract.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from sketches_tpu.analysis.lint import Finding, LintContext, SourceFile, rule

__all__ = ["analyze_class", "ClassReport"]

_LOCK_FACTORIES = ("Lock", "RLock")
_LOCKED_SUFFIX = "_locked"


def _is_lock_ctor(node: ast.AST) -> bool:
    """``threading.Lock()`` / ``threading.RLock()`` / bare ``RLock()``."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr in _LOCK_FACTORIES
    if isinstance(fn, ast.Name):
        return fn.id in _LOCK_FACTORIES
    return False


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``"X"`` (else None)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


@dataclasses.dataclass
class _Access:
    attr: str
    lineno: int
    locked: bool
    store: bool
    method: str


@dataclasses.dataclass
class _CallSite:
    callee: str
    lineno: int
    locked: bool
    caller: str


@dataclasses.dataclass
class _Escape:
    attr: str
    lineno: int
    how: str  # "returned" | "stored"
    method: str


class _MethodVisitor(ast.NodeVisitor):
    """Walk one method body tracking syntactic ``with self.<lock>:`` depth."""

    def __init__(self, method: str, lock_attrs: Set[str], attr_universe: Set[str]):
        self.method = method
        self.lock_attrs = lock_attrs
        self.attr_universe = attr_universe
        self.depth = 0
        self.accesses: List[_Access] = []
        self.calls: List[_CallSite] = []
        self.escapes: List[_Escape] = []

    # -- lock regions -------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        holds = any(
            _self_attr(item.context_expr) in self.lock_attrs
            for item in node.items
        )
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        if holds:
            self.depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if holds:
            self.depth -= 1

    # -- accesses and calls -------------------------------------------------
    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None and attr in self.attr_universe:
            self.accesses.append(
                _Access(
                    attr,
                    node.lineno,
                    self.depth > 0,
                    isinstance(node.ctx, (ast.Store, ast.Del)),
                    self.method,
                )
            )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        callee = _self_attr(node.func)
        if callee is not None:
            self.calls.append(
                _CallSite(callee, node.lineno, self.depth > 0, self.method)
            )
        self.generic_visit(node)

    # -- escapes ------------------------------------------------------------
    def visit_Return(self, node: ast.Return) -> None:
        attr = _self_attr(node.value) if node.value is not None else None
        if attr is not None and attr in self.attr_universe:
            self.escapes.append(
                _Escape(attr, node.lineno, "returned", self.method)
            )
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        attr = _self_attr(node.value)
        if attr is not None and attr in self.attr_universe:
            for tgt in node.targets:
                # Storing the guarded object onto anything that is not a
                # plain local (an attribute/subscript of another object)
                # hands out an uncovered reference.
                base = tgt
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    base = base.value
                if isinstance(tgt, (ast.Attribute, ast.Subscript)) and not (
                    isinstance(base, ast.Name) and base.id == "self"
                ):
                    self.escapes.append(
                        _Escape(attr, node.lineno, "stored", self.method)
                    )
        self.generic_visit(node)


@dataclasses.dataclass
class ClassReport:
    """What the pass inferred for one lock-owning class (test surface)."""

    name: str
    lock_attrs: Set[str]
    guarded: Set[str]
    always_locked: Set[str]
    findings: List[Finding]


def _class_methods(
    cls: ast.ClassDef,
) -> List[Tuple[str, ast.AST]]:
    out: List[Tuple[str, ast.AST]] = []
    for node in cls.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        deco = {
            d.id if isinstance(d, ast.Name) else getattr(d, "attr", "")
            for d in node.decorator_list
        }
        if "staticmethod" in deco or "classmethod" in deco:
            continue
        if not node.args.args or node.args.args[0].arg != "self":
            continue
        out.append((node.name, node))
    return out


def analyze_class(
    sf: SourceFile, cls: ast.ClassDef
) -> Optional[ClassReport]:
    """Run the lock-discipline analysis on one class; None if lock-free."""
    methods = _class_methods(cls)

    # Pass 0: lock attributes and the stored-attribute universe.  Only
    # attributes *assigned* somewhere on self participate -- properties
    # and bound methods are computed names, not shared state.
    lock_attrs: Set[str] = set()
    attr_universe: Set[str] = set()
    for _, fn in methods:
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is not None:
                        lock_attrs.add(attr)
            tgt_attr = None
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for tgt in targets:
                    tgt_attr = _self_attr(tgt)
                    if tgt_attr is not None:
                        attr_universe.add(tgt_attr)
    if not lock_attrs:
        return None
    attr_universe -= lock_attrs

    # Pass 1: per-method access/call/escape records with syntactic depth.
    visitors: Dict[str, _MethodVisitor] = {}
    for name, fn in methods:
        v = _MethodVisitor(name, lock_attrs, attr_universe)
        for stmt in fn.body:
            v.visit(stmt)
        visitors[name] = v

    # Pass 2: greatest-fixpoint always-locked set over the in-class call
    # graph.  Start optimistic (every convention-named or called method),
    # then evict any method with an unlocked call site.
    call_sites: Dict[str, List[_CallSite]] = {name: [] for name in visitors}
    for v in visitors.values():
        for c in v.calls:
            if c.callee in call_sites:
                call_sites[c.callee].append(c)
    always_locked: Set[str] = {
        name
        for name in visitors
        if name.endswith(_LOCKED_SUFFIX) or call_sites[name]
    }
    changed = True
    while changed:
        changed = False
        for name in sorted(always_locked):
            if name.endswith(_LOCKED_SUFFIX):
                continue
            ok = bool(call_sites[name]) and all(
                c.locked
                or c.caller == "__init__"
                or c.caller in always_locked
                for c in call_sites[name]
            )
            if not ok:
                always_locked.discard(name)
                changed = True

    def _site_locked(method: str, syntactic: bool) -> bool:
        return syntactic or method == "__init__" or method in always_locked

    # Pass 3: guarded set = attrs accessed at any locked site outside
    # __init__ (construction writes don't make an attribute shared).
    guarded: Set[str] = set()
    for v in visitors.values():
        if v.method == "__init__":
            continue
        for a in v.accesses:
            if _site_locked(a.method, a.locked):
                guarded.add(a.attr)

    # Pass 4: findings.
    findings: List[Finding] = []
    for v in visitors.values():
        if v.method == "__init__":
            continue
        for a in v.accesses:
            if a.attr in guarded and not _site_locked(a.method, a.locked):
                verb = "written" if a.store else "read"
                findings.append(
                    Finding(
                        "lock-discipline",
                        sf.path,
                        a.lineno,
                        f"{cls.name}.{a.method}: self.{a.attr} is lock-"
                        f"guarded (accessed under the instance lock"
                        f" elsewhere) but {verb} here without holding"
                        " it -- a torn read/write race",
                    )
                )
        for c in v.calls:
            if c.callee.endswith(_LOCKED_SUFFIX) and not _site_locked(
                c.caller, c.locked
            ):
                findings.append(
                    Finding(
                        "lock-discipline",
                        sf.path,
                        c.lineno,
                        f"{cls.name}.{c.caller}: calls {c.callee}() without"
                        " holding the instance lock its _locked suffix"
                        " requires",
                    )
                )
        for e in v.escapes:
            if e.attr in guarded:
                findings.append(
                    Finding(
                        "lock-escape",
                        sf.path,
                        e.lineno,
                        f"{cls.name}.{e.method}: guarded attribute"
                        f" self.{e.attr} {e.how} raw -- the reference"
                        " outlives the lock region; hand out a copy,"
                        " snapshot, or facade instead",
                    )
                )
    return ClassReport(cls.name, lock_attrs, guarded, always_locked, findings)


@rule("lock-discipline")
def check_lock_discipline(ctx: LintContext) -> Iterable[Finding]:
    out: List[Finding] = []
    for sf in ctx.iter_files():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                report = analyze_class(sf, node)
                if report is not None:
                    out.extend(
                        f for f in report.findings
                        if f.rule == "lock-discipline"
                    )
    return out


@rule("lock-escape")
def check_lock_escape(ctx: LintContext) -> Iterable[Finding]:
    out: List[Finding] = []
    for sf in ctx.iter_files():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                report = analyze_class(sf, node)
                if report is not None:
                    out.extend(
                        f for f in report.findings if f.rule == "lock-escape"
                    )
    return out
