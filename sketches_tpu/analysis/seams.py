"""sketchlint atomic-commit pass: torn-write seams commit by reference
swap.

Every method that passes a torn site (``faults.ATOMIC_SITES``:
``checkpoint.write``, ``reshard.torn``, ``window.rotate_torn``,
``window.stack_torn``, ``mesh.partition_heal``) promises the
**atomic-commit contract** the chaos campaigns probe dynamically: build
the new state functionally in locals, inject the fault *between* plan
and commit, then publish with a single reference swap -- so an
exception at the seam leaves the old state fully intact.  This pass
proves the contract structurally:

* ``seam-premutation`` -- a ``self`` mutation *before* the inject call:
  an attribute assign/augment/delete, a subscript store, or a mutator
  method (``append``/``update``/``pop``/...) on a ``self`` attribute,
  including through simple local aliases (``host = self._hosts[h]``
  followed by ``host.partitioned = True``).  Any of these means a fault
  at the seam tears the state.
* ``seam-commit`` -- the *first* ``self`` mutation after the inject is
  an in-place mutator call rather than a plain store: in-place
  publication mutates the observable object before the update is
  complete, so a concurrent reader (or a second fault) sees a torn
  commit.  Plain attribute or subscript stores are accepted -- each is
  one atomic slot write.
* ``seam-sites`` -- the declared inventory stays closed: every
  ``ATOMIC_SITES`` member is also in ``faults.SITES``, and every
  ``faults.inject(faults.X)`` call whose constant name contains
  ``TORN`` is declared atomic (an undeclared torn seam is exactly the
  unproven-contract bug).

Scope and accepted failure modes: only *methods* (first arg ``self``)
are analyzed -- module-level functions (``checkpoint.save_state``)
mutate locals and commit via ``os.replace`` by construction; alias
tracking follows pure attribute/subscript chains rooted at ``self``
(``x = self._hosts[h]``) but not call results (``meta = self._meta(n)``
is a fresh-object boundary the callee owns); mutations via a second
``self``-taking helper called pre-site are that helper's contract (it
either injects the site itself or holds no seam).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from sketches_tpu.analysis.lint import Finding, LintContext, SourceFile, rule

__all__ = ["atomic_site_names", "analyze_method"]

_FAULTS_FILE = "faults.py"

#: In-place mutator method names that tear shared containers.
_MUTATORS = frozenset(
    """
    append extend insert remove pop popitem clear update setdefault
    add discard sort reverse
    """.split()
)


def _parse_faults(
    ctx: LintContext,
) -> Tuple[Dict[str, str], Set[str], Set[str]]:
    """Parse ``faults.py`` (never import): ``{const_name: site_string}``,
    the ``SITES`` member names, and the ``ATOMIC_SITES`` member names."""
    consts: Dict[str, str] = {}
    sites: Set[str] = set()
    atomic: Set[str] = set()
    sf = ctx.file_in_package(_FAULTS_FILE)
    if sf is None or sf.tree is None:
        return consts, sites, atomic
    for node in sf.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        if isinstance(node.value, ast.Constant) and isinstance(
            node.value.value, str
        ):
            consts[tgt.id] = node.value.value
        elif isinstance(node.value, (ast.Tuple, ast.List)):
            names = {
                e.id for e in node.value.elts if isinstance(e, ast.Name)
            }
            if tgt.id == "SITES":
                sites = names
            elif tgt.id == "ATOMIC_SITES":
                atomic = names
    return consts, sites, atomic


def atomic_site_names(ctx: LintContext) -> Set[str]:
    """The ``faults.<CONST>`` names declared torn-atomic (may be empty
    in fixture trees without a faults module)."""
    return _parse_faults(ctx)[2]


def _inject_site_const(node: ast.Call) -> Optional[str]:
    """``faults.inject(faults.X, ...)`` -> ``"X"`` (else None)."""
    fn = node.func
    if not (
        isinstance(fn, ast.Attribute)
        and fn.attr == "inject"
        and isinstance(fn.value, ast.Name)
        and fn.value.id == "faults"
    ):
        return None
    if not node.args:
        return None
    site = node.args[0]
    if (
        isinstance(site, ast.Attribute)
        and isinstance(site.value, ast.Name)
        and site.value.id == "faults"
    ):
        return site.attr
    return None


def _alias_root(node: ast.AST) -> Optional[str]:
    """For a pure Attribute/Subscript chain, the base name (``self`` or a
    local); any Call or other node in the chain -> None (fresh object)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


class _Mutation:
    __slots__ = ("lineno", "kind", "desc")

    def __init__(self, lineno: int, kind: str, desc: str):
        self.lineno = lineno
        self.kind = kind  # "store" (atomic slot write) | "mutate" (in-place)
        self.desc = desc


def _collect_mutations(fn: ast.AST, self_name: str = "self") -> List[_Mutation]:
    """Every self-state mutation in the method, aliases included."""
    aliases: Set[str] = {self_name}
    out: List[_Mutation] = []

    def is_self_rooted(node: ast.AST) -> bool:
        root = _alias_root(node)
        return root is not None and root in aliases

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            # Alias creation: local = pure chain rooted at self/alias.
            if (
                len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, (ast.Attribute, ast.Subscript))
                and is_self_rooted(node.value)
            ):
                aliases.add(node.targets[0].id)
                continue
            for tgt in node.targets:
                if isinstance(
                    tgt, (ast.Attribute, ast.Subscript)
                ) and is_self_rooted(tgt):
                    out.append(
                        _Mutation(
                            node.lineno, "store", ast.unparse(tgt)
                        )
                    )
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            tgt = node.target
            if isinstance(
                tgt, (ast.Attribute, ast.Subscript)
            ) and is_self_rooted(tgt):
                kind = "store" if isinstance(node, ast.AnnAssign) else "aug"
                out.append(_Mutation(node.lineno, kind, ast.unparse(tgt)))
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(
                    tgt, (ast.Attribute, ast.Subscript)
                ) and is_self_rooted(tgt):
                    out.append(
                        _Mutation(node.lineno, "mutate", ast.unparse(tgt))
                    )
        elif isinstance(node, ast.Call):
            fn_node = node.func
            if (
                isinstance(fn_node, ast.Attribute)
                and fn_node.attr in _MUTATORS
                and is_self_rooted(fn_node.value)
            ):
                out.append(
                    _Mutation(
                        node.lineno,
                        "mutate",
                        f"{ast.unparse(fn_node.value)}.{fn_node.attr}(...)",
                    )
                )
    return sorted(out, key=lambda m: m.lineno)


def analyze_method(
    sf: SourceFile,
    fn: ast.AST,
    qualname: str,
    atomic_consts: Set[str],
) -> List[Finding]:
    """Check one method against the atomic-commit contract."""
    inject_lines = [
        node.lineno
        for node in ast.walk(fn)
        if isinstance(node, ast.Call)
        and _inject_site_const(node) in atomic_consts
    ]
    if not inject_lines:
        return []
    seam = min(inject_lines)
    findings: List[Finding] = []
    mutations = _collect_mutations(fn)
    for m in mutations:
        if m.lineno < seam:
            findings.append(
                Finding(
                    "seam-premutation",
                    sf.path,
                    m.lineno,
                    f"{qualname}: mutates {m.desc} before the torn-site"
                    f" inject at line {seam}; the atomic-commit contract"
                    " requires a purely functional plan (locals only)"
                    " before the seam",
                )
            )
    post = [m for m in mutations if m.lineno > seam]
    if post and post[0].kind == "mutate":
        findings.append(
            Finding(
                "seam-commit",
                sf.path,
                post[0].lineno,
                f"{qualname}: first post-seam commit is an in-place"
                f" mutation of {post[0].desc}; commit with a single"
                " reference swap (plain store) so a reader never sees"
                " a half-applied update",
            )
        )
    return findings


def _iter_methods(tree: ast.AST) -> Iterable[Tuple[str, ast.AST]]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for item in node.body:
            if (
                isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                and item.args.args
                and item.args.args[0].arg == "self"
            ):
                yield f"{node.name}.{item.name}", item


@rule("seam-premutation")
def check_premutation(ctx: LintContext) -> Iterable[Finding]:
    atomic = atomic_site_names(ctx)
    if not atomic:
        return []
    out: List[Finding] = []
    for sf in ctx.iter_files():
        if sf.tree is None:
            continue
        for qualname, fn in _iter_methods(sf.tree):
            out.extend(
                f
                for f in analyze_method(sf, fn, qualname, atomic)
                if f.rule == "seam-premutation"
            )
    return out


@rule("seam-commit")
def check_commit(ctx: LintContext) -> Iterable[Finding]:
    atomic = atomic_site_names(ctx)
    if not atomic:
        return []
    out: List[Finding] = []
    for sf in ctx.iter_files():
        if sf.tree is None:
            continue
        for qualname, fn in _iter_methods(sf.tree):
            out.extend(
                f
                for f in analyze_method(sf, fn, qualname, atomic)
                if f.rule == "seam-commit"
            )
    return out


@rule("seam-sites")
def check_sites(ctx: LintContext) -> Iterable[Finding]:
    consts, sites, atomic = _parse_faults(ctx)
    sf = ctx.file_in_package(_FAULTS_FILE)
    if sf is None or not consts:
        return []
    out: List[Finding] = []
    for name in sorted(atomic - sites):
        out.append(
            Finding(
                "seam-sites",
                sf.path,
                1,
                f"ATOMIC_SITES member {name} is not in faults.SITES --"
                " an atomic seam the fault harness cannot arm",
            )
        )
    # Every *_TORN inject anywhere in the tree must be declared atomic.
    for src in ctx.iter_files():
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            const = _inject_site_const(node)
            if const is None or const in atomic:
                continue
            if "TORN" in const:
                out.append(
                    Finding(
                        "seam-sites",
                        src.path,
                        node.lineno,
                        f"faults.{const} is injected as a torn seam but is"
                        " not declared in faults.ATOMIC_SITES; declare it"
                        " so the atomic-commit contract is proven here",
                    )
                )
    return out
