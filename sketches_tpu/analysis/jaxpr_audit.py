"""sketchlint layer 2: trace the jitted/Pallas entry points and audit
what actually lowers.

The AST layer checks what the source *says*; this layer checks what the
tracer *builds*.  Each registered entry point is traced with
``jax.make_jaxpr`` under small abstract-shaped inputs (128 streams,
256 bins -- tracing needs no TPU: Pallas calls abstract-eval on any
backend), and the closed jaxpr is walked recursively for:

* **f64 ops** (``jaxpr-f64``): any equation aval with a float64 /
  complex128 dtype.  With x64 off these can't appear (jax demotes), but
  the audit also runs in x64 contexts (multihost drivers), where an f64
  leak silently de-optimizes the TPU path.
* **host callbacks** (``jaxpr-callback``): ``pure_callback`` /
  ``io_callback`` / debug-callback primitives inside a hot path -- each
  execution would sync device->host.
* **weak-type leaks** (``jaxpr-weak-type``): weak-typed *top-level*
  inputs or outputs.  A weak input means a Python scalar reached the
  traced boundary: the same call site recompiles when the scalar's
  concrete type changes, and a weak output re-poisons the next stage's
  cache key.
* **trace failures** (``jaxpr-trace``): an entry point that no longer
  traces under its documented signature is drift by definition.

Separately, :func:`vmem_report` re-derives the overlap engine's VMEM
ring footprint from the constants in ``kernels.py`` (ring depth x
stream block x 128 lanes x 4 bytes, plus the rank slab and packed
operands at the eligibility caps) and checks it against the declared
:data:`VMEM_BUDGET_BYTES` -- the "kernels fit VMEM" convention,
machine-checked (``vmem-budget``).

The **budgets gate** (``jaxpr-budget``) pins the same census in a
checked-in file, ``analysis/budgets.json``: per-entry element-ops per
output value, collective-primitive counts, and the VMEM total.  A
lowering change that exceeds a pin by more than
:data:`BUDGET_TOLERANCE_PCT` percent (element ops; collectives and VMEM
are exact ceilings) fails the run, as does an unpinned or stale entry
-- regressions must be consciously re-pinned (``--update-budgets``),
never silently absorbed.  See :func:`measure_budgets` /
:func:`check_budgets`.

Everything returns :class:`~sketches_tpu.analysis.lint.Finding` objects
(layer ``"jaxpr"``) so the CLI, baseline, and JSON report treat both
layers uniformly.  jax imports stay inside functions: importing this
module is free, and the AST layer keeps working where jax is absent.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from sketches_tpu.analysis.lint import Finding

__all__ = [
    "VMEM_BUDGET_BYTES",
    "ELEMENTWISE_PRIMS",
    "COLLECTIVE_PRIMS",
    "audit",
    "audit_callable",
    "check_budgets",
    "default_entry_points",
    "elem_ops_per_value",
    "load_budgets",
    "measure_budgets",
    "vmem_report",
    "write_budgets",
]

#: Per-core VMEM on the targeted TPU generations (v4/v5e: 16 MiB).  The
#: audit requires the overlap ring + slab + operand blocks to fit WELL
#: inside this -- Mosaic needs headroom for double-buffered operand
#: blocks the automatic pipeline allocates.
VMEM_BUDGET_BYTES = 16 * 1024 * 1024

_BAD_DTYPES = ("float64", "complex128")
_CALLBACK_MARKERS = ("callback", "outside_call")

#: The primitives :func:`elem_ops_per_value` counts as one VPU lane-op
#: per output element: elementwise arithmetic/compare/select/convert --
#: the construction-width currency of DESIGN.md §2-r5/§2-r17.  Excluded
#: on purpose: ``dot_general`` (MXU, measured ~8% of the kernel),
#: ``iota``/``broadcast_in_dim``/layout ops (no arithmetic), and the
#: ``reduce_*`` family (bookkeeping reductions, not construction rows).
ELEMENTWISE_PRIMS = frozenset(
    """
    add sub mul div neg sign abs floor ceil round rem pow integer_pow
    max min eq ne lt le gt ge and or not xor nand nor
    shift_left shift_right_logical shift_right_arithmetic
    select_n convert_element_type clamp is_finite
    exp exp2 log log1p expm1 sqrt rsqrt cbrt logistic tanh erf
    population_count clz bitcast_convert_type
    """.split()
)

#: Cross-device communication primitives counted by the budget census.
#: Every audited entry point is single-device today, so the checked-in
#: budgets pin these at zero -- a refactor that sneaks a collective into
#: a serving path fails the static-analysis job, not a TPU bench.
COLLECTIVE_PRIMS = frozenset(
    """
    psum pmax pmin pmean ppermute pshuffle all_gather all_to_all
    reduce_scatter
    """.split()
)


def _iter_jaxprs(jaxpr) -> Iterable:
    """The jaxpr and every sub-jaxpr reachable through eqn params."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for val in eqn.params.values():
            for sub in _extract_jaxprs(val):
                yield from _iter_jaxprs(sub)


def _extract_jaxprs(val) -> Iterable:
    import jax.core

    if isinstance(val, jax.core.ClosedJaxpr):
        yield val.jaxpr
    elif isinstance(val, jax.core.Jaxpr):
        yield val
    elif isinstance(val, (tuple, list)):
        for item in val:
            yield from _extract_jaxprs(item)


def _aval_issues(aval) -> Optional[str]:
    dtype = getattr(aval, "dtype", None)
    if dtype is not None and str(dtype) in _BAD_DTYPES:
        return str(dtype)
    return None


def audit_callable(
    name: str, fn: Callable, args: Sequence, check_weak: bool = True
) -> List[Finding]:
    """Trace ``fn(*args)`` and audit the closed jaxpr.  Returns findings
    (empty = clean); a trace failure is itself a finding, never a crash."""
    import jax

    path = f"<jaxpr:{name}>"
    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # noqa: BLE001 - any trace failure is the finding
        return [
            Finding(
                "jaxpr-trace",
                path,
                0,
                f"entry point {name} failed to trace: {type(e).__name__}:"
                f" {str(e)[:300]}",
                layer="jaxpr",
            )
        ]
    findings: List[Finding] = []
    jaxpr = closed.jaxpr
    if check_weak:
        for kind, vs in (("input", jaxpr.invars), ("output", jaxpr.outvars)):
            for i, v in enumerate(vs):
                aval = getattr(v, "aval", None)
                if aval is not None and getattr(aval, "weak_type", False):
                    findings.append(
                        Finding(
                            "jaxpr-weak-type",
                            path,
                            0,
                            f"{name}: weak-typed {kind} #{i} ({aval}); a"
                            " Python scalar reached the traced boundary and"
                            " will recompile per concrete type",
                            layer="jaxpr",
                        )
                    )
    for sub in _iter_jaxprs(jaxpr):
        for eqn in sub.eqns:
            prim = eqn.primitive.name
            if any(marker in prim for marker in _CALLBACK_MARKERS):
                findings.append(
                    Finding(
                        "jaxpr-callback",
                        path,
                        0,
                        f"{name}: host callback primitive {prim!r} in the"
                        " traced path (device->host sync every execution)",
                        layer="jaxpr",
                    )
                )
            for v in list(eqn.invars) + list(eqn.outvars):
                bad = _aval_issues(getattr(v, "aval", None))
                if bad is not None:
                    findings.append(
                        Finding(
                            "jaxpr-f64",
                            path,
                            0,
                            f"{name}: {bad} aval in primitive {prim!r};"
                            " the device tier is f32-only",
                            layer="jaxpr",
                        )
                    )
                    break
    # One finding per (rule, entry) is enough signal; dedup repeats.
    seen: set = set()
    unique: List[Finding] = []
    for f in findings:
        if f.fingerprint not in seen:
            seen.add(f.fingerprint)
            unique.append(f)
    return unique


def elem_ops_per_value(
    variant: str = "stock",
    weighted: bool = False,
    n_streams: int = 128,
    n_bins: int = 256,
    batch: int = 128,
) -> float:
    """Static construction-width audit: elementwise VPU lane-ops per
    ingested value, derived from the traced ingest jaxpr (ISSUE 12
    satellite 2).

    Traces ``kernels.ingest_histogram`` for the given construction rung
    and walks every sub-jaxpr (the Pallas kernel body included -- pallas
    abstract-eval needs no TPU), summing output elements over
    :data:`ELEMENTWISE_PRIMS` equations and dividing by the ingested
    value count.  Hardware-independent by construction: the number
    moves only when the traced formulation's arithmetic width moves, so
    a test pin on it fails CI on a construction-width regression
    without waiting for the next TPU bench run.  (The §2-r5 stock
    budget in these units: (LO + 2·HI) rows × compare+mask+cast ≈ 272+
    lane-ops/value at 512 bins, keys/masks/bookkeeping included.)
    """
    import functools

    import jax
    import jax.numpy as jnp

    from sketches_tpu import batched, kernels

    spec = batched.SketchSpec(n_bins=n_bins)
    state = batched.init(spec, n_streams)
    values = jnp.zeros((n_streams, batch), jnp.float32)
    weights = jnp.ones((n_streams, batch), jnp.float32)
    fn = functools.partial(
        kernels.ingest_histogram, spec, weighted=weighted, variant=variant
    )
    closed = jax.make_jaxpr(fn)(values, weights, state.key_offset)
    total = 0
    for sub in _iter_jaxprs(closed.jaxpr):
        for eqn in sub.eqns:
            if eqn.primitive.name not in ELEMENTWISE_PRIMS:
                continue
            size = 0
            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                shape = getattr(aval, "shape", None)
                if shape is not None:
                    n = 1
                    for d in shape:
                        n *= int(d)
                    size = max(size, n)
            total += size
    # The kernel body traces ONCE at block shapes while the grid replays
    # it per (stream-block, value-chunk) cell; the default shapes pick
    # exactly one grid cell (128 streams x 128 values), so the traced
    # element count IS the executed count and the per-value ratio is
    # exact.  Cell-invariant hoisted work (identity row, unpack
    # matrices) is charged to the single cell -- conservative for the
    # variants, which amortize it across the real grid.
    return total / float(n_streams * batch)


def default_entry_points() -> List[Tuple[str, Callable, Sequence]]:
    """The audited surface: every engine a facade can dispatch to.

    Shapes are the smallest eligible configuration (128 streams, 256
    bins = 2 tiles, 4 quantiles) -- eligibility gates, not performance,
    decide what traces.
    """
    import functools

    import jax.numpy as jnp

    from sketches_tpu import batched, kernels

    spec = batched.SketchSpec(n_bins=256)
    state = batched.init(spec, 128)
    values = jnp.zeros((128, 128), jnp.float32)
    weights = jnp.ones((128, 128), jnp.float32)
    qs = jnp.asarray([0.5, 0.9, 0.99, 0.999], jnp.float32)
    lo = jnp.asarray(0, jnp.int32)

    return [
        ("batched.add", functools.partial(batched.add, spec), (state, values)),
        (
            "batched.quantile",
            functools.partial(batched.quantile, spec),
            (state, qs),
        ),
        (
            "batched.merge",
            functools.partial(batched.merge, spec),
            (state, batched.init(spec, 128)),
        ),
        (
            "kernels.ingest_histogram",
            functools.partial(kernels.ingest_histogram, spec),
            (values, weights, state.key_offset),
        ),
        # The construction-variant rungs (unit-weight; see
        # kernels.INGEST_VARIANTS) -- each a distinct audited entry so
        # profiling's roofline join can name the rung that served.
        *[
            (
                f"kernels.ingest_histogram:{v}",
                functools.partial(
                    kernels.ingest_histogram, spec,
                    weighted=False, variant=v,
                ),
                (values, weights, state.key_offset),
            )
            for v in kernels.INGEST_VARIANTS[1:]
        ],
        (
            "kernels.fused_quantile",
            functools.partial(kernels.fused_quantile, spec),
            (state, qs),
        ),
        (
            "kernels.fused_quantile_windowed",
            functools.partial(
                kernels.fused_quantile_windowed, spec, n_wblocks=2, w_tiles=1
            ),
            (state, qs, lo),
        ),
        (
            "kernels.fused_quantile_tiles",
            functools.partial(kernels.fused_quantile_tiles, spec, k_tiles=2),
            (state, qs),
        ),
        (
            "kernels.fused_quantile_tiles_overlap",
            functools.partial(
                kernels.fused_quantile_tiles_overlap, spec, k_tiles=2
            ),
            (state, qs),
        ),
        (
            "kernels.quantile_windowed_xla",
            functools.partial(
                kernels.quantile_windowed_xla, spec, n_tiles_window=2
            ),
            (state, qs, lo),
        ),
    ]


def vmem_report() -> Dict:
    """The overlap engine's worst-case VMEM footprint, from first
    principles and the constants in ``kernels.py``.

    Worst case by construction: the widest stream block
    (``kernels._stream_block``'s largest candidate), the deepest ring
    (``_overlap_depth`` caps at 8), and the most quantiles the tile
    family admits (``tile_query_eligible`` caps Q at 8).
    """
    from sketches_tpu import kernels

    bn = max((1024, 512, 256, 128))  # _stream_block's candidate set
    # Derive instead of trusting the literal above if the source evolved:
    try:
        bn = max(bn, kernels._stream_block(1 << 20))
    except Exception:  # noqa: BLE001 - constants-only fallback
        pass
    q_max = 8  # tile_query_eligible: "q_total <= 8" keeps the slab bounded
    depth_max = kernels._overlap_depth(2 * q_max * 8, 8)  # cap is 8
    lane = kernels.LO
    f32 = 4

    ring = depth_max * bn * lane * f32
    slab = q_max * bn * lane * f32
    packed = bn * ((4 * q_max + 5 + 7) // 8 * 8) * f32
    out = bn * q_max * f32
    total = ring + slab + packed + out
    return {
        "budget_bytes": VMEM_BUDGET_BYTES,
        "stream_block": bn,
        "ring_depth": depth_max,
        "q_max": q_max,
        "ring_bytes": ring,
        "slab_bytes": slab,
        "packed_bytes": packed,
        "out_bytes": out,
        "total_bytes": total,
        "ok": total <= VMEM_BUDGET_BYTES,
    }


# ---------------------------------------------------------------------------
# CI-pinned static cost budgets (analysis/budgets.json)
# ---------------------------------------------------------------------------

#: Upward drift allowed on elementwise lane-op counts before the gate
#: fails.  The census is deterministic for a fixed jax version, so the
#: slack only absorbs tracer-formulation churn across jax upgrades --
#: a real construction-width regression (the §2-r17 ladder kind) moves
#: by whole rows, far past 2%.
BUDGET_TOLERANCE_PCT = 2.0

_BUDGET_PATH = "<budgets:analysis/budgets.json>"


def _entry_census(fn: Callable, args: Sequence) -> Optional[Dict]:
    """Trace ``fn(*args)`` -> {"elem_ops": N, "collectives": {prim: n}}
    (None when the entry fails to trace -- ``audit_callable`` already
    reports that as ``jaxpr-trace``)."""
    import jax

    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception:  # noqa: BLE001 - jaxpr-trace owns the report
        return None
    elem_ops = 0
    collectives: Dict[str, int] = {}
    for sub in _iter_jaxprs(closed.jaxpr):
        for eqn in sub.eqns:
            prim = eqn.primitive.name
            if prim in COLLECTIVE_PRIMS:
                collectives[prim] = collectives.get(prim, 0) + 1
            if prim not in ELEMENTWISE_PRIMS:
                continue
            size = 0
            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                shape = getattr(aval, "shape", None)
                if shape is not None:
                    n = 1
                    for d in shape:
                        n *= int(d)
                    size = max(size, n)
            elem_ops += size
    return {"elem_ops": elem_ops, "collectives": collectives}


def measure_budgets(
    entries: Optional[List[Tuple[str, Callable, Sequence]]] = None,
    ingest_variants: Optional[Sequence[str]] = None,
) -> Dict:
    """Measure the full static-cost surface -> a budgets document.

    Three cost families, all derived from traces (no TPU): per-entry
    elementwise lane-op totals and collective census, the per-variant
    ingest construction width (:func:`elem_ops_per_value`), and the
    overlap engine's worst-case VMEM footprint.  ``entries`` and
    ``ingest_variants`` default to the full audited surface; tests pass
    small synthetic sets.
    """
    from sketches_tpu import kernels

    if entries is None:
        entries = default_entry_points()
    if ingest_variants is None:
        ingest_variants = kernels.INGEST_VARIANTS
    doc: Dict = {
        "version": 1,
        "tolerance_pct": BUDGET_TOLERANCE_PCT,
        "entries": {},
        "ingest_elem_ops_per_value": {},
        "vmem_total_bytes": vmem_report()["total_bytes"],
    }
    for name, fn, args in entries:
        census = _entry_census(fn, args)
        if census is not None:
            doc["entries"][name] = census
    for variant in ingest_variants:
        doc["ingest_elem_ops_per_value"][variant] = round(
            elem_ops_per_value(variant), 4
        )
    return doc


def load_budgets(path: str) -> Optional[Dict]:
    """The checked-in budgets document (None when absent)."""
    import json
    import os

    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def write_budgets(path: str, doc: Dict) -> None:
    """Write a budgets document (``--update-budgets``)."""
    import json

    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")


def check_budgets(budgets: Optional[Dict], measured: Dict) -> List[Finding]:
    """Gate the measured costs against the checked-in budgets.

    Budgets are *ceilings*: an entry may get cheaper silently, but
    costing more than budget (beyond ``tolerance_pct`` for lane-op
    counts; exactly for collectives and VMEM), introducing an
    unbudgeted entry point, or keeping a stale budget row all fail --
    each failure names ``--update-budgets`` as the (reviewed) way out.
    """
    findings: List[Finding] = []
    if budgets is None:
        return [
            Finding(
                "jaxpr-budget",
                _BUDGET_PATH,
                0,
                "no budgets file is checked in; run `python -m"
                " sketches_tpu.analysis --update-budgets` and commit"
                " analysis/budgets.json",
                layer="jaxpr",
            )
        ]
    tol = 1.0 + float(
        budgets.get("tolerance_pct", BUDGET_TOLERANCE_PCT)
    ) / 100.0
    b_entries = budgets.get("entries", {})
    m_entries = measured.get("entries", {})
    for name in sorted(set(m_entries) - set(b_entries)):
        findings.append(
            Finding(
                "jaxpr-budget",
                _BUDGET_PATH,
                0,
                f"entry point {name} has no budget row; every audited"
                " entry point is cost-pinned (--update-budgets)",
                layer="jaxpr",
            )
        )
    for name in sorted(set(b_entries) - set(m_entries)):
        findings.append(
            Finding(
                "jaxpr-budget",
                _BUDGET_PATH,
                0,
                f"budget row {name} matches no audited entry point --"
                " stale pin (--update-budgets)",
                layer="jaxpr",
            )
        )
    for name in sorted(set(b_entries) & set(m_entries)):
        b, m = b_entries[name], m_entries[name]
        if m["elem_ops"] > b.get("elem_ops", 0) * tol:
            findings.append(
                Finding(
                    "jaxpr-budget",
                    _BUDGET_PATH,
                    0,
                    f"{name}: {m['elem_ops']} elementwise lane-ops exceeds"
                    f" the budgeted {b.get('elem_ops', 0)} -- a static"
                    " cost regression; fix the width or justify it via"
                    " --update-budgets in review",
                    layer="jaxpr",
                )
            )
        b_coll = b.get("collectives", {})
        for prim, count in sorted(m.get("collectives", {}).items()):
            if count > b_coll.get(prim, 0):
                findings.append(
                    Finding(
                        "jaxpr-budget",
                        _BUDGET_PATH,
                        0,
                        f"{name}: collective {prim!r} appears {count}x"
                        f" against a budget of {b_coll.get(prim, 0)} --"
                        " a new cross-device sync in a serving path",
                        layer="jaxpr",
                    )
                )
    b_ingest = budgets.get("ingest_elem_ops_per_value", {})
    for variant, value in sorted(
        measured.get("ingest_elem_ops_per_value", {}).items()
    ):
        if variant not in b_ingest:
            findings.append(
                Finding(
                    "jaxpr-budget",
                    _BUDGET_PATH,
                    0,
                    f"ingest variant {variant!r} has no construction-width"
                    " budget (--update-budgets)",
                    layer="jaxpr",
                )
            )
        elif value > b_ingest[variant] * tol:
            findings.append(
                Finding(
                    "jaxpr-budget",
                    _BUDGET_PATH,
                    0,
                    f"ingest variant {variant!r}: {value:g} lane-ops/value"
                    f" exceeds the budgeted {b_ingest[variant]:g} -- the"
                    " §2-r17 construction-width regression class",
                    layer="jaxpr",
                )
            )
    vmem_budget = budgets.get("vmem_total_bytes")
    vmem_measured = measured.get("vmem_total_bytes", 0)
    if vmem_budget is not None and vmem_measured > vmem_budget:
        findings.append(
            Finding(
                "jaxpr-budget",
                _BUDGET_PATH,
                0,
                f"overlap-ring VMEM footprint grew to {vmem_measured}"
                f" bytes against a budgeted {vmem_budget} -- the ring no"
                " longer fits its pinned envelope",
                layer="jaxpr",
            )
        )
    return findings


def audit(
    entries: Optional[List[Tuple[str, Callable, Sequence]]] = None,
    budgets_path: Optional[str] = None,
) -> Tuple[List[Finding], Dict]:
    """Run the full layer-2 audit -> (findings, machine-readable report).

    ``entries`` defaults to :func:`default_entry_points`; tests pass
    synthetic callables to prove each check fires.  With
    ``budgets_path`` the static-cost census runs too and is gated
    against the checked-in budgets document (``jaxpr-budget``).
    """
    if entries is None:
        entries = default_entry_points()
    findings: List[Finding] = []
    report: Dict = {"entries": {}, "vmem": None, "budgets": None}
    for name, fn, args in entries:
        entry_findings = audit_callable(name, fn, args)
        findings.extend(entry_findings)
        report["entries"][name] = {
            "findings": [f.to_dict() for f in entry_findings],
            "ok": not entry_findings,
        }
    vmem = vmem_report()
    report["vmem"] = vmem
    if not vmem["ok"]:
        findings.append(
            Finding(
                "vmem-budget",
                "<vmem:overlap-ring>",
                0,
                f"overlap engine worst case needs {vmem['total_bytes']}"
                f" bytes of VMEM against a {vmem['budget_bytes']}-byte"
                " budget; shrink the ring depth, stream block, or Q cap",
                layer="jaxpr",
            )
        )
    if budgets_path is not None:
        budgets = load_budgets(budgets_path)
        measured = measure_budgets(entries)
        budget_findings = check_budgets(budgets, measured)
        findings.extend(budget_findings)
        report["budgets"] = {
            "path": budgets_path,
            "checked": budgets is not None,
            "measured": measured,
            "findings": [f.to_dict() for f in budget_findings],
            "ok": not budget_findings,
        }
    return findings, report
