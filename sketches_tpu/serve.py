"""Overload-hardened multi-tenant serving tier: the paper's production story.

DDSketch exists to serve p50/p99 under production traffic (PAPER.md),
and production traffic is bursty, repetitive, and adversarial.  This
module is the serving facade over the device tier: N tenants each own
an isolated :class:`~sketches_tpu.batched.BatchedDDSketch` (per-tenant
``SketchSpec``), concurrent quantile requests are admitted into a
bounded queue and flushed as **fused device dispatches** -- requests
for one tenant fold into a single fused multi-quantile call (the union
of their quantiles), and tenants sharing a spec stack their states and
answer in ONE device dispatch -- wrapped in a full robustness envelope:

* **Admission control** -- a bounded queue with a declared shed order:
  a request is refused at admission (``ServeOverload``, structured
  ``reason``) when its tenant is over quota (``tenant_quota`` -- one
  hot tenant cannot starve the rest) or the global queue is at depth
  (``queue_depth``); admitted requests are NEVER evicted, and shedding
  is counted (``serve.shed`` metric + health ledger), never silent.
* **Deadline budgets** -- every request carries a deadline; a request
  whose remaining budget falls under ``floor_margin_s`` at flush time
  skips straight to the ``xla`` floor tier (already compiled, no plan
  fetch) instead of risking a timeout on a faster-but-colder rung; a
  budget spent before flush answers ``DeadlineExceeded``; late answers
  are still returned but counted (``serve.deadline_misses``).
* **Hedged retries** -- a primary dispatch that fails (the armed
  ``serve.straggler`` site is the adversary) or straggles past
  ``hedge_after_s`` is hedged with a floor-tier dispatch; queries are
  pure, so the hedge is idempotent by construction and the loser's
  result is discarded bit-identically (test-asserted).
* **Circuit breaker per engine tier** -- repeated failures/stragglers
  on a non-floor ladder rung (threshold ``breaker_threshold``) open
  that tier's breaker: subsequent dispatches skip the rung (via the
  facade's caller-scoped tier exclusion, folding into the existing
  ``overlap -> tiles -> windowed -> wxla -> xla`` ladder) for
  ``breaker_cooldown`` dispatches, then a half-open probe either
  closes it or re-opens it.  The ``xla`` floor never opens (it is the
  answer of last resort, exactly like the resilience ladder's floor).
* **Fingerprint-keyed result cache with poison detection** -- results
  are memoized under ``(tenant, content fingerprint, quantiles)`` using
  the integrity layer's merge-additive fingerprints
  (:func:`sketches_tpu.integrity.fingerprint`), so a write naturally
  invalidates (the fingerprint moves) and identical reads are served
  from memory bit-identical to a cold recompute.  Every hit is
  re-verified: the entry's stored fingerprint must equal the live one
  and its payload checksum must match (the armed
  ``serve.cache_poison`` site corrupts entries to prove it); a
  mismatch quarantines the entry (``serve.cache.poisoned``), and the
  request silently recomputes -- a poisoned cache degrades to a cache
  miss, never to a wrong answer.

Tracing (r13): with the flight recorder armed
(``sketches_tpu.tracing``, always-on when telemetry is), every request
roots a :class:`~sketches_tpu.tracing.TraceContext` at admission
(``ticket.trace``); cache hit/miss/poison, shed, deadline, hedge, and
breaker decisions become recorder events on that trace; each fused
dispatch binds a child context so the resolved engine-tier span (and
the fold/serde spans under it) link causally; the per-request latency
observation carries the trace as a histogram exemplar -- so "the p99
bin" answers with trace ids.  Cache poison and unexpected admission
errors auto-dump forensic bundles (``tracing.dump_forensics``).

Determinism: the serving clock is injectable (``clock=`` -- defaults to
``telemetry.clock``), so deadline/hedge/breaker behavior replays
exactly under a virtual clock; no code here sleeps or reads wall time
directly.  Kill switches (declared in ``analysis/registry.py``):
``SKETCHES_TPU_SERVE_CACHE=0`` disables the cache (no fingerprint
fetch, one bool test per query), ``SKETCHES_TPU_SERVE_HEDGE=0``
disables hedging (a straggler's failure surfaces as its structured
error instead).

Failure modes: shed requests raise :class:`ServeOverload` (reason
``queue_depth`` / ``tenant_quota`` / ``injected``), spent budgets raise
:class:`DeadlineExceeded`, unknown tenants raise ``SpecError``; an
engine-floor failure re-raises after the hedge path is exhausted -- a
request is always answered, refused, or failed loudly, never hung.
"""

from __future__ import annotations

import binascii
import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sketches_tpu import faults, integrity, resilience, telemetry, tracing
from sketches_tpu.analysis import registry
from sketches_tpu.resilience import (
    QUERY_LADDER,
    DeadlineExceeded,
    ServeOverload,
    SketchError,
    SpecError,
    SketchValueError,
)

__all__ = [
    "ServeConfig",
    "Ticket",
    "ServeResult",
    "SketchServer",
    "ServeOverload",
    "DeadlineExceeded",
]

#: Non-floor ladder rungs a circuit breaker may open; the ``xla`` floor
#: is the answer of last resort and never opens.
_BREAKABLE_TIERS = QUERY_LADDER[:-1]
_FLOOR_TIER = QUERY_LADDER[-1]


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving-envelope knobs (all bounded, all declared).

    ``max_queue_depth`` / ``tenant_quota`` bound the admission queue
    (overflow sheds with ``ServeOverload``; admitted requests are never
    evicted).  ``default_deadline_s`` is the per-request budget when the
    caller passes none; a request with less than ``floor_margin_s``
    remaining at flush skips to the floor tier.  ``hedge_after_s`` is
    the straggler threshold for hedged retries.  ``breaker_threshold``
    consecutive failures open a tier's breaker for ``breaker_cooldown``
    dispatches before the half-open probe.  ``cache_capacity`` bounds
    the result cache (LRU past capacity; 0 disables it outright).
    Invalid (non-positive) bounds raise ``SpecError``.
    """

    max_queue_depth: int = 256
    tenant_quota: int = 64
    default_deadline_s: float = 0.25
    floor_margin_s: float = 0.02
    hedge_after_s: float = 0.05
    breaker_threshold: int = 3
    breaker_cooldown: int = 8
    cache_capacity: int = 4096

    def __post_init__(self):
        if self.max_queue_depth <= 0 or self.tenant_quota <= 0:
            raise SpecError("queue depth and tenant quota must be positive")
        if self.default_deadline_s <= 0:
            raise SpecError("default_deadline_s must be positive")
        if self.breaker_threshold <= 0 or self.breaker_cooldown <= 0:
            raise SpecError("breaker threshold/cooldown must be positive")
        if self.cache_capacity < 0:
            raise SpecError("cache_capacity must be non-negative")


@dataclasses.dataclass
class Ticket:
    """One admitted (or cache-answered) quantile request.

    ``deadline`` is absolute serving-clock seconds; ``result`` is
    filled by the admission cache hit or the next :meth:`flush` --
    ``None`` until then.  A shed request never gets a ticket (admission
    raises instead).  ``trace`` is the request's root
    :class:`~sketches_tpu.tracing.TraceContext` (None while the flight
    recorder is disarmed): the id that links this request to its span
    events, histogram exemplars, and forensic bundles.
    """

    id: int
    tenant: str
    qs: Tuple[float, ...]
    deadline: float
    submitted_at: float
    result: Optional["ServeResult"] = None
    trace: Optional[Any] = None


@dataclasses.dataclass
class ServeResult:
    """One answered request: per-stream values for the requested
    quantiles (``[n_streams, Q]``, NaN for empty streams), the engine
    ``tier`` that answered (``cache`` for hits), and the robustness
    accounting -- ``hedged`` (a hedge dispatch was issued),
    ``deadline_missed`` (answered after the budget; the answer is still
    exact, the miss is counted)."""

    values: np.ndarray
    tier: str
    hedged: bool = False
    deadline_missed: bool = False

    @property
    def cached(self) -> bool:
        return self.tier == "cache"


class _Breaker:
    """One engine tier's circuit breaker (request-count cooldown -- no
    wall clock, so a failing sequence replays exactly).

    closed --(``threshold`` consecutive failures)--> open
    open --(``cooldown`` skipped dispatches)--> half_open
    half_open --(probe success)--> closed; --(probe failure)--> open
    """

    __slots__ = ("threshold", "cooldown", "failures", "state", "cooldown_left")

    def __init__(self, threshold: int, cooldown: int):
        self.threshold = threshold
        self.cooldown = cooldown
        self.failures = 0
        self.state = "closed"
        self.cooldown_left = 0

    def blocks(self) -> bool:
        """Whether this dispatch must skip the tier (advances cooldown)."""
        if self.state == "open":
            self.cooldown_left -= 1
            if self.cooldown_left <= 0:
                self.state = "half_open"
            return True
        return False  # closed and half_open both allow (probe) traffic

    def record_success(self) -> None:
        self.failures = 0
        self.state = "closed"

    def record_failure(self) -> bool:
        """Count a failure -> True iff the breaker (re-)opened."""
        self.failures += 1
        if self.state == "half_open" or self.failures >= self.threshold:
            self.state = "open"
            self.cooldown_left = self.cooldown
            self.failures = 0
            return True
        return False


class _CacheEntry:
    __slots__ = ("fp", "values", "tier", "checksum")

    def __init__(self, fp: np.ndarray, values: np.ndarray, tier: str):
        self.fp = fp
        self.values = values
        self.tier = tier
        self.checksum = _payload_checksum(fp, values)


def _payload_checksum(fp: np.ndarray, values: np.ndarray) -> int:
    """Content checksum binding a cached payload to its fingerprint
    (crc32 over both byte images; any single-bit rot in either fails
    re-verification).  Never raises on well-formed arrays."""
    crc = binascii.crc32(np.ascontiguousarray(fp).tobytes())
    return binascii.crc32(np.ascontiguousarray(values).tobytes(), crc)


class _Tenant:
    __slots__ = ("name", "facade", "version", "fp_cache")

    def __init__(self, name: str, facade):
        self.name = name
        self.facade = facade
        self.version = 0  # bumped on every server-mediated write
        self.fp_cache: Optional[Tuple[int, np.ndarray, bytes]] = None


class SketchServer:
    """The multi-tenant serving facade (module docstring for the full
    envelope: admission/shedding, deadlines, hedging, breakers, cache).

    Writes MUST go through :meth:`ingest`/:meth:`merge` (or be followed
    by :meth:`invalidate`): the result cache keys on content
    fingerprints that the server memoizes per tenant write-version, so
    a write behind the server's back would serve stale (but
    detectable: the live-fingerprint re-verification quarantines such
    entries on the next hit).  Unknown tenants raise ``SpecError``;
    shed requests raise ``ServeOverload``; spent deadline budgets raise
    ``DeadlineExceeded``.  Thread-safe for submit/flush under one lock
    (dispatches serialize -- the device is one resource).
    """

    def __init__(
        self,
        config: Optional[ServeConfig] = None,
        *,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.config = config or ServeConfig()
        self._clock = clock if clock is not None else telemetry.clock
        self._tenants: Dict[str, _Tenant] = {}
        self._queue: List[Ticket] = []
        self._pending_per_tenant: Dict[str, int] = {}
        self._next_id = 0
        self._lock = threading.RLock()
        # Kill switches: read once (registry discipline); disarmed cost
        # is one bool test per query / dispatch.
        self._cache_enabled = (
            registry.enabled(registry.SERVE_CACHE)
            and self.config.cache_capacity > 0
        )
        self._hedge_enabled = registry.enabled(registry.SERVE_HEDGE)
        self._cache: "Dict[Tuple[str, bytes, Tuple[float, ...]], _CacheEntry]" = {}
        self._cache_order: List[Tuple[str, bytes, Tuple[float, ...]]] = []
        self._breakers: Dict[str, _Breaker] = {}
        self._fused_jits: Dict[Any, Any] = {}
        self._stats: Dict[str, float] = {
            "requests": 0, "shed": 0, "deadline_misses": 0, "hedges": 0,
            "cache_hits": 0, "cache_misses": 0, "cache_poisoned": 0,
            "dispatches": 0, "fused_dispatches": 0, "breaker_trips": 0,
        }

    # -- tenancy ----------------------------------------------------------

    def add_tenant(
        self, name: str, n_streams: int, *, mesh=None, value_axis=None,
        stream_axis=None, window=None, **kwargs,
    ):
        """Register tenant ``name`` with its own isolated facade (and
        therefore its own ``SketchSpec``) -> the facade.

        ``kwargs`` pass through to ``BatchedDDSketch`` (``spec=``,
        ``relative_accuracy=``, ``n_bins=``, ...).  Passing any of
        ``mesh``/``value_axis``/``stream_axis`` instead builds a
        mesh-sharded ``DistributedDDSketch`` tenant -- the elastic
        fleet behind the serving tier; its read path (fingerprints,
        fused dispatch, the breaker/deadline tier exclusions) is
        API-identical, and :meth:`reshard_tenant` can later resize its
        mesh live without poisoning the cache.  Passing ``window=``
        (``True`` for the default 5s -> 1m -> 1h ladder, or a
        ``sketches_tpu.windows.WindowConfig``) backs the tenant with a
        :class:`~sketches_tpu.windows.WindowedSketch` on the serving
        clock: time-scoped reads then go through :meth:`quantile` with
        ``window=...`` (the queued :meth:`submit` path refuses windowed
        tenants loudly), writes ride :meth:`ingest` unchanged, and
        ``SKETCHES_TPU_WINDOWED=0`` refuses at registration.
        Re-registering an existing name raises ``SpecError`` -- tenant
        state is never silently replaced.
        """
        with self._lock:
            if name in self._tenants:
                raise SpecError(f"tenant {name!r} already registered")
            if window is not None:
                from sketches_tpu.windows import WindowConfig, WindowedSketch

                config = None if window is True else window
                if config is not None and not isinstance(
                    config, WindowConfig
                ):
                    raise SpecError(
                        "window= takes True (default ladder) or a"
                        f" WindowConfig; got {type(window).__name__}"
                    )
                facade = WindowedSketch(
                    n_streams, config=config, clock=self._clock,
                    mesh=mesh, value_axis=value_axis,
                    stream_axis=stream_axis, **kwargs,
                )
            elif mesh is not None or value_axis is not None \
                    or stream_axis is not None:
                from sketches_tpu.parallel import (
                    DistributedDDSketch,
                    SketchMesh,
                )

                if isinstance(mesh, SketchMesh):
                    # The layout already names its axes; honor them
                    # unless the caller overrode explicitly.
                    if value_axis is None and stream_axis is None:
                        value_axis = mesh.value_axis
                        stream_axis = mesh.stream_axis
                elif value_axis is None and stream_axis is None:
                    value_axis = "values"
                facade = DistributedDDSketch(
                    n_streams, mesh=mesh, value_axis=value_axis,
                    stream_axis=stream_axis, **kwargs,
                )
            else:
                # Per-tenant accuracy/memory contract: the spec's
                # backend picks the facade class (dense BatchedDDSketch,
                # uniform_collapse AdaptiveDDSketch, or moment
                # MomentDDSketch) -- mixed-backend fleets serve
                # correctly because cache keys are fingerprint-derived
                # and fused groups key on the (backend-carrying) spec.
                _backend = getattr(
                    kwargs.get("spec"), "backend",
                    kwargs.get("backend", "dense"),
                )
                if _backend != "dense":
                    from sketches_tpu.backends import facade_for

                    facade = facade_for(n_streams, **kwargs)
                else:
                    from sketches_tpu.batched import BatchedDDSketch

                    kwargs.pop("backend", None)
                    facade = BatchedDDSketch(n_streams, **kwargs)
            self._tenants[name] = _Tenant(name, facade)
            return facade

    def reshard_tenant(
        self, name: str, mesh=None, n_devices: Optional[int] = None,
        *, live_mask=None,
    ):
        """Resize a distributed tenant's mesh LIVE -- the tenant
        survives the reshard -> its ``ReshardReport``.

        Wraps :meth:`DistributedDDSketch.reshard` under the serving
        lock (no request observes a half-resharded tenant).  Because
        content fingerprints are topology-free, a clean reshard (no
        dead shards) leaves every cached ``(tenant, fingerprint, q)``
        entry VALID -- the cache survives, no recompute storm; a
        reshard that dropped mass (dead shards/hosts) changed content,
        so the tenant's write version bumps and the stale fingerprint
        is released (old entries then miss naturally).  Raises
        ``SpecError`` for a batched (non-distributed) tenant, an
        unknown tenant, or when ``SKETCHES_TPU_ELASTIC=0``; a failed
        reshard (torn, all shards dead) raises and leaves the tenant
        untouched on its old mesh.
        """
        from sketches_tpu.parallel import DistributedDDSketch

        with self._lock:
            t = self._tenant(name)
            if not isinstance(t.facade, DistributedDDSketch):
                raise SpecError(
                    f"tenant {name!r} is not mesh-sharded; only"
                    " DistributedDDSketch tenants reshard"
                )
            new_facade, report = t.facade.reshard(
                mesh=mesh, n_devices=n_devices, live_mask=live_mask
            )
            t.facade = new_facade
            if report.n_dead:
                # Dead shards dropped mass: the content (and so the
                # fingerprint) changed -- stale cache entries must miss.
                t.version += 1
                t.fp_cache = None
            if tracing._ACTIVE:
                tracing.record_event(
                    "serve.reshard", tenant=name,
                    from_devices=report.from_devices,
                    to_devices=report.to_devices,
                    n_dead=report.n_dead, exact=report.exact,
                )
            return report

    def tenant(self, name: str):
        """The named tenant's facade (raises ``SpecError`` if unknown)."""
        with self._lock:
            return self._tenant(name).facade

    def _tenant(self, name: str) -> _Tenant:
        t = self._tenants.get(name)
        if t is None:
            raise SpecError(f"unknown tenant {name!r}")
        return t

    # -- write path -------------------------------------------------------

    def ingest(self, name: str, values, weights=None) -> None:
        """Ingest a batch into tenant ``name`` (write path).

        Bumps the tenant's write version, so cached fingerprints (and
        therefore cached results) invalidate naturally -- the next read
        recomputes.  Ingest failures degrade/raise exactly as the
        facade's engine ladder does.
        """
        with self._lock:
            t = self._tenant(name)
            t.facade.add(values, weights)
            t.version += 1
            t.fp_cache = None
            if tracing._ACTIVE:
                tracing.record_event("serve.write", tenant=name, op="ingest")

    def merge(self, name: str, other) -> None:
        """Fold another ``BatchedDDSketch`` into tenant ``name`` (write
        path; same invalidation discipline as :meth:`ingest`).  Unequal
        specs raise ``UnequalSketchParametersError``."""
        with self._lock:
            t = self._tenant(name)
            t.facade.merge(other)
            t.version += 1
            t.fp_cache = None
            if tracing._ACTIVE:
                tracing.record_event("serve.write", tenant=name, op="merge")

    def invalidate(self, name: str) -> None:
        """Drop tenant ``name``'s memoized fingerprint after an
        out-of-band write to its facade (raises ``SpecError`` when the
        tenant is unknown).  Without this, stale entries are still
        caught -- the hit-time live-fingerprint re-verification
        quarantines them -- but at hit-time cost."""
        with self._lock:
            t = self._tenant(name)
            t.version += 1
            t.fp_cache = None

    # -- fingerprints & cache --------------------------------------------

    def _fingerprint(self, t: _Tenant) -> Tuple[np.ndarray, bytes]:
        """Tenant content fingerprint (memoized per write version)."""
        cached = t.fp_cache
        if cached is not None and cached[0] == t.version:
            return cached[1], cached[2]
        fp = integrity.fingerprint(t.facade.spec, t.facade.state)
        digest = np.ascontiguousarray(fp).tobytes()
        t.fp_cache = (t.version, fp, digest)
        return fp, digest

    def _cache_get(
        self, t: _Tenant, qs: Tuple[float, ...], ctx=None
    ) -> Optional[np.ndarray]:
        """Cache lookup with poison detection -> values (a defensive
        copy) or None.  A hit is re-verified (live fingerprint + payload
        checksum); a mismatch quarantines the entry, counts it, dumps a
        forensic bundle naming the poisoned entry (recorder armed), and
        reads as a miss -- the request recomputes."""
        fp, digest = self._fingerprint(t)
        key = (t.name, digest, qs)
        entry = self._cache.get(key)
        if entry is None:
            return None
        if faults._ACTIVE:
            flip = faults.cache_poison_flip(entry.values.nbytes)
            if flip is not None:
                # The armed adversary: silent rot in the cached payload.
                buf = np.ascontiguousarray(entry.values).copy()
                view = buf.view(np.uint8).reshape(-1)
                view[flip[0]] ^= np.uint8(1 << flip[1])
                entry.values = buf
        live_ok = entry.fp.shape == fp.shape and bool(
            np.array_equal(entry.fp, fp)
        )
        sum_ok = entry.checksum == _payload_checksum(entry.fp, entry.values)
        if not (live_ok and sum_ok):
            self._quarantine(key, ctx=ctx)
            return None
        # LRU touch.
        try:
            self._cache_order.remove(key)
        except ValueError:  # pragma: no cover - defensive
            pass
        self._cache_order.append(key)
        return entry.values.copy()

    def _quarantine(self, key, ctx=None) -> None:
        self._cache.pop(key, None)
        try:
            self._cache_order.remove(key)
        except ValueError:
            pass
        self._stats["cache_poisoned"] += 1
        resilience.bump("serve.cache_poisoned")
        if telemetry._ACTIVE:
            telemetry.counter_inc("serve.cache.poisoned")
        if tracing._ACTIVE:
            # A poisoned cache entry is silent-corruption evidence: name
            # the entry and dump the forensic picture around it.
            entry_name = {
                "tenant": key[0],
                "quantiles": ",".join(f"{q:g}" for q in key[2]),
                "fingerprint": key[1].hex()[:16],
            }
            tracing.record_event(
                "serve.cache.poisoned", ctx=ctx, **entry_name
            )
            tracing.dump_forensics(
                "serve.cache_poison", trace=ctx, detail=entry_name
            )

    def _cache_put(
        self, t: _Tenant, qs: Tuple[float, ...], fp: np.ndarray,
        digest: bytes, values: np.ndarray, tier: str,
    ) -> None:
        key = (t.name, digest, qs)
        if key not in self._cache:
            self._cache_order.append(key)
        self._cache[key] = _CacheEntry(fp, values, tier)
        while len(self._cache_order) > self.config.cache_capacity:
            old = self._cache_order.pop(0)
            self._cache.pop(old, None)

    # -- admission --------------------------------------------------------

    def _shed(self, tenant: str, reason: str, ctx=None) -> None:
        self._stats["shed"] += 1
        resilience.bump("serve.shed")
        if telemetry._ACTIVE:
            telemetry.counter_inc("serve.shed", reason=reason)
        if tracing._ACTIVE:
            tracing.record_event(
                "serve.shed", ctx=ctx, tenant=tenant, reason=reason
            )
        raise ServeOverload(
            f"request for tenant {tenant!r} shed at admission ({reason})",
            reason=reason, tenant=tenant,
        )

    def submit(
        self,
        name: str,
        quantiles: Sequence[float],
        deadline_s: Optional[float] = None,
    ) -> Ticket:
        """Admit one quantile request -> a :class:`Ticket`.

        A cache hit answers immediately (``ticket.result`` set, no
        queue slot consumed).  Otherwise admission applies the declared
        shed order -- injected-overflow fault, then tenant quota, then
        global depth -- and a refused request raises
        :class:`ServeOverload` (structured ``reason``); a deadline
        budget that is already non-positive raises
        :class:`DeadlineExceeded`.  Admitted requests are never
        evicted; :meth:`flush` answers them.  With the flight recorder
        armed every request roots a trace context (``ticket.trace``)
        and its admission decisions become recorder events; a
        ``SketchError`` escaping admission that is NOT one of the two
        structured refusals auto-dumps a forensic bundle before
        re-raising.
        """
        qs = tuple(sorted(float(q) for q in quantiles))
        if not qs:
            raise SketchValueError("a request needs at least one quantile")
        try:
            return self._submit_admitted(name, qs, deadline_s)
        except (ServeOverload, DeadlineExceeded):
            raise  # the structured refusals: handled, not forensic
        except SketchError as e:
            if tracing._ACTIVE:
                tracing.dump_forensics(
                    "serve.submit",
                    detail={"tenant": name, "error": repr(e)},
                )
            raise

    def _submit_admitted(
        self,
        name: str,
        qs: Tuple[float, ...],
        deadline_s: Optional[float],
    ) -> Ticket:
        """:meth:`submit` body (admission under the lock); split out so
        the caller can wrap it in the forensic-dump net.  Raises exactly
        as :meth:`submit` documents."""
        with self._lock:
            t = self._tenant(name)
            if self._is_windowed(t):
                raise SpecError(
                    f"tenant {name!r} is time-windowed: query it with"
                    " quantile(tenant, qs, window=...) -- the queued"
                    " submit/flush path has no window semantics"
                )
            self._stats["requests"] += 1
            now = self._clock()
            _trc = tracing.new_trace() if tracing._ACTIVE else None
            if telemetry._ACTIVE:
                telemetry.counter_inc("serve.requests")
            budget = (
                self.config.default_deadline_s
                if deadline_s is None else float(deadline_s)
            )
            if _trc is not None:
                tracing.record_event(
                    "serve.submit", ctx=_trc, tenant=name,
                    qs=",".join(f"{q:g}" for q in qs), budget_s=budget,
                )
            if budget <= 0:
                self._stats["deadline_misses"] += 1
                resilience.bump("serve.deadline_misses")
                if telemetry._ACTIVE:
                    telemetry.counter_inc("serve.deadline_misses")
                if _trc is not None:
                    tracing.record_event(
                        "serve.deadline_spent", ctx=_trc, tenant=name,
                        budget_s=budget,
                    )
                raise DeadlineExceeded(
                    f"request for tenant {name!r} arrived with a spent"
                    f" deadline budget ({budget:g}s)"
                )
            ticket = Ticket(
                id=self._next_id, tenant=name, qs=qs,
                deadline=now + budget, submitted_at=now, trace=_trc,
            )
            self._next_id += 1
            if self._cache_enabled:
                values = self._cache_get(t, qs, ctx=_trc)
                if values is not None:
                    self._stats["cache_hits"] += 1
                    if _trc is not None:
                        tracing.record_event(
                            "serve.cache.hit", ctx=_trc, tenant=name
                        )
                    if telemetry._ACTIVE:
                        telemetry.counter_inc("serve.cache.hits")
                        telemetry.observe(
                            "serve.request_s", self._clock() - now,
                            trace=_trc, source="cache",
                        )
                    ticket.result = ServeResult(values=values, tier="cache")
                    return ticket
                self._stats["cache_misses"] += 1
                if _trc is not None:
                    tracing.record_event(
                        "serve.cache.miss", ctx=_trc, tenant=name
                    )
                if telemetry._ACTIVE:
                    telemetry.counter_inc("serve.cache.misses")
            if faults._ACTIVE:
                try:
                    faults.inject(faults.SERVE_QUEUE_OVERFLOW)
                except SketchError:
                    self._shed(name, "injected", ctx=_trc)
            if self._pending_per_tenant.get(name, 0) >= self.config.tenant_quota:
                self._shed(name, "tenant_quota", ctx=_trc)
            if len(self._queue) >= self.config.max_queue_depth:
                self._shed(name, "queue_depth", ctx=_trc)
            self._queue.append(ticket)
            self._pending_per_tenant[name] = (
                self._pending_per_tenant.get(name, 0) + 1
            )
            if telemetry._ACTIVE:
                telemetry.gauge_set("serve.queue_depth", len(self._queue))
            return ticket

    # -- dispatch ---------------------------------------------------------

    def _breaker(self, tier: str) -> _Breaker:
        b = self._breakers.get(tier)
        if b is None:
            b = self._breakers[tier] = _Breaker(
                self.config.breaker_threshold, self.config.breaker_cooldown
            )
        return b

    def breaker_state(self, tier: str) -> str:
        """The named tier's breaker state (``closed`` when it has never
        failed; unknown tiers raise ``SpecError``)."""
        if tier not in QUERY_LADDER:
            raise SpecError(f"unknown engine tier {tier!r}")
        with self._lock:
            b = self._breakers.get(tier)
            return b.state if b is not None else "closed"

    def _breaker_failure(self, tier: str) -> None:
        if tier not in _BREAKABLE_TIERS:
            return
        if self._breaker(tier).record_failure():
            self._stats["breaker_trips"] += 1
            resilience.record_downgrade(
                "serve.breaker", tier, "open", "circuit breaker tripped"
            )
            if telemetry._ACTIVE:
                telemetry.counter_inc("serve.breaker.trips", tier=tier)
            if tracing._ACTIVE:
                tracing.record_event(
                    "serve.breaker", tier=tier, state="open"
                )

    def _blocked_tiers(self) -> frozenset:
        blocked = set()
        for tier, b in self._breakers.items():
            if b.blocks():
                blocked.add(tier)
        return frozenset(blocked)

    def _hedge(self, t: _Tenant, qs: Tuple[float, ...]) -> np.ndarray:
        """The hedge dispatch: the already-compiled ``xla`` floor --
        pure, so idempotent with the primary by construction.  A floor
        failure re-raises (nothing cheaper exists)."""
        self._stats["hedges"] += 1
        resilience.bump("serve.hedges")
        if telemetry._ACTIVE:
            telemetry.counter_inc("serve.hedges", tier=_FLOOR_TIER)
        if tracing._ACTIVE:
            tracing.record_event("serve.hedge", tier=_FLOOR_TIER)
        _, values = t.facade.get_quantile_values_resolved(
            qs, disabled_tiers=_BREAKABLE_TIERS
        )
        return np.asarray(values)

    def _dispatch_tenant(
        self, t: _Tenant, qs: Tuple[float, ...], force_floor: bool
    ) -> Tuple[str, np.ndarray, bool]:
        """One tenant's fused dispatch through the robustness envelope
        -> ``(tier, values, hedged)``.  Stragglers (injected or slower
        than ``hedge_after_s``) are hedged on the floor tier when
        hedging is enabled; with hedging disabled a straggler's failure
        re-raises to the caller."""
        disabled = self._blocked_tiers()
        if force_floor:
            disabled = disabled | frozenset(_BREAKABLE_TIERS)
        # Resolve the tier first (plan fetch, memoized) so the armed
        # straggler site can target one rung, then dispatch on it.
        tier = t.facade._query_choice(qs, disabled)[0]
        t0 = self._clock()
        try:
            if faults._ACTIVE:
                faults.inject(faults.SERVE_STRAGGLER, tier=tier)
            tier, values = t.facade.get_quantile_values_resolved(
                qs, disabled_tiers=disabled
            )
        except SketchError as e:
            self._breaker_failure(tier)
            if not self._hedge_enabled:
                raise
            resilience.record_downgrade(
                "serve.dispatch", tier, _FLOOR_TIER, f"hedged: {e!r}"
            )
            return _FLOOR_TIER, self._hedge(t, qs), True
        elapsed = self._clock() - t0
        values = np.asarray(values)
        if (
            self._hedge_enabled
            and tier != _FLOOR_TIER
            and elapsed > self.config.hedge_after_s
        ):
            # The primary straggled but completed: issue the hedge it
            # would have raced and discard the loser.  Query purity
            # makes both answers bit-identical, so discarding is safe
            # by construction (asserted, not assumed).
            self._breaker_failure(tier)
            hedged_values = self._hedge(t, qs)
            if not np.array_equal(
                hedged_values, values, equal_nan=True
            ):  # pragma: no cover - purity violation
                raise SketchError(
                    "hedge dispatch disagreed with its primary: query"
                    " purity violated"
                )
            return tier, values, True
        self._breaker_success(tier)
        return tier, values, False

    def _breaker_success(self, tier: str) -> None:
        b = self._breakers.get(tier)
        if b is not None:
            b.record_success()

    def _fused_quantile(self, spec):
        fn = self._fused_jits.get(spec)
        if fn is None:
            import functools

            import jax

            backend = getattr(spec, "backend", "dense")
            if backend == "uniform_collapse":
                from sketches_tpu.backends.uniform import quantile as _aq

                fn = jax.jit(functools.partial(_aq, spec))
            elif backend == "moment":
                from sketches_tpu.backends.moment import quantile as _mq

                # Host maxent solve: a plain callable, not a jit -- the
                # fused stacking still answers every same-spec tenant
                # in one call.
                fn = functools.partial(_mq, spec)
            else:
                from sketches_tpu.batched import quantile

                fn = jax.jit(functools.partial(quantile, spec))
            self._fused_jits[spec] = fn
        return fn

    def _dispatch_group(
        self, tenants: List[_Tenant], qs: Tuple[float, ...]
    ) -> Tuple[str, List[np.ndarray], bool]:
        """Cross-tenant fused dispatch: stack the group's states and
        answer every tenant in ONE device call (the ``xla``-tier pure
        quantile -- the floor, so no breaker applies) ->
        ``(tier, per-tenant values, hedged)``.  Injected stragglers
        hedge by re-running the same pure dispatch."""
        import jax
        import jax.numpy as jnp

        states = [t.facade.state for t in tenants]
        stacked = jax.tree.map(
            lambda *xs: jnp.concatenate(xs, axis=0), *states
        )
        fn = self._fused_quantile(tenants[0].facade.spec)
        qs_arr = jnp.asarray(qs)
        hedged = False
        try:
            if faults._ACTIVE:
                faults.inject(faults.SERVE_STRAGGLER, tier=_FLOOR_TIER)
            out = np.asarray(fn(stacked, qs_arr))
        except SketchError:
            if not self._hedge_enabled:
                raise
            self._stats["hedges"] += 1
            resilience.bump("serve.hedges")
            if telemetry._ACTIVE:
                telemetry.counter_inc("serve.hedges", tier=_FLOOR_TIER)
            if tracing._ACTIVE:
                tracing.record_event("serve.hedge", tier=_FLOOR_TIER)
            out = np.asarray(fn(stacked, qs_arr))
            hedged = True
        rows: List[np.ndarray] = []
        lo = 0
        for t in tenants:
            hi = lo + t.facade.n_streams
            rows.append(out[lo:hi])
            lo = hi
        return _FLOOR_TIER, rows, hedged

    # -- flush ------------------------------------------------------------

    def flush(self) -> Dict[int, ServeResult]:
        """Drain the admission queue and answer every admitted request
        -> ``{ticket id: result}`` (tickets' ``result`` fields are
        filled too).

        Requests fold per tenant into one fused multi-quantile dispatch
        (the union of their quantiles); tenants sharing a spec fold
        further into one stacked cross-tenant device call.  Requests
        within ``floor_margin_s`` of their deadline force the floor
        tier; answers landing past a deadline are returned but counted
        (``serve.deadline_misses``).  An empty queue returns ``{}``.
        Dispatch failures below the hedge/ladder floor re-raise.
        """
        with self._lock:
            batch, self._queue = self._queue, []
            self._pending_per_tenant = {}
            if telemetry._ACTIVE:
                telemetry.gauge_set("serve.queue_depth", 0)
            if not batch:
                return {}
            # Fold requests per tenant: one fused dispatch each.
            per_tenant: Dict[str, List[Ticket]] = {}
            for tk in batch:
                per_tenant.setdefault(tk.tenant, []).append(tk)
            plans: List[Tuple[_Tenant, Tuple[float, ...], List[Ticket], bool]] = []
            now = self._clock()
            for name, tickets in per_tenant.items():
                t = self._tenant(name)
                union = tuple(sorted({q for tk in tickets for q in tk.qs}))
                near = any(
                    tk.deadline - now < self.config.floor_margin_s
                    for tk in tickets
                )
                plans.append((t, union, tickets, near))
            # Tenants sharing (spec, quantile union, no floor forcing
            # needed -- the fused path IS the floor) stack into one
            # cross-tenant device dispatch.
            groups: Dict[Any, List[int]] = {}
            for i, (t, union, _tks, _near) in enumerate(plans):
                groups.setdefault((t.facade.spec, union), []).append(i)
            out: Dict[int, ServeResult] = {}
            for key, idxs in groups.items():
                _spec, union = key
                t0 = self._clock()
                # Dispatch under a child of the first traced ticket's
                # context, so the engine-tier span (and the psum fold /
                # wire spans under it) link into the request's trace.
                _dctx = None
                if tracing._ACTIVE:
                    _primary = next(
                        (tk.trace for i in idxs for tk in plans[i][2]
                         if tk.trace is not None),
                        None,
                    )
                    if _primary is not None:
                        _dctx = tracing.child_span(_primary)
                _tok = tracing.bind(_dctx) if _dctx is not None else None
                try:
                    if len(idxs) > 1:
                        tenants = [plans[i][0] for i in idxs]
                        tier, rows, hedged = self._dispatch_group(
                            tenants, union
                        )
                        self._stats["fused_dispatches"] += 1
                        results = list(zip(idxs, rows))
                    else:
                        i = idxs[0]
                        t, union, _tks, near = plans[i]
                        tier, values, hedged = self._dispatch_tenant(
                            t, union, force_floor=near
                        )
                        results = [(i, values)]
                finally:
                    if _tok is not None:
                        tracing.unbind(_tok)
                self._stats["dispatches"] += 1
                if telemetry._ACTIVE:
                    telemetry.observe(
                        "serve.batch_s", self._clock() - t0, trace=_dctx,
                        tier=tier,
                    )
                for i, values in results:
                    t, _union, tickets, _near = plans[i]
                    if self._cache_enabled:
                        fp, digest = self._fingerprint(t)
                        self._cache_put(t, union, fp, digest, values, tier)
                    done = self._clock()
                    cols = {q: j for j, q in enumerate(union)}
                    for tk in tickets:
                        sel = [cols[q] for q in tk.qs]
                        missed = done > tk.deadline
                        if missed:
                            self._stats["deadline_misses"] += 1
                            resilience.bump("serve.deadline_misses")
                            if telemetry._ACTIVE:
                                telemetry.counter_inc("serve.deadline_misses")
                        tk.result = ServeResult(
                            values=values[:, sel], tier=tier, hedged=hedged,
                            deadline_missed=missed,
                        )
                        out[tk.id] = tk.result
                        if tk.trace is not None and tracing._ACTIVE:
                            tracing.record_event(
                                "serve.dispatch", ctx=tk.trace,
                                tenant=tk.tenant, tier=tier, hedged=hedged,
                                fused=len(idxs) > 1,
                                dispatch_span=(
                                    _dctx.span_hex if _dctx is not None
                                    else None
                                ),
                            )
                            if missed:
                                tracing.record_event(
                                    "serve.deadline_miss", ctx=tk.trace,
                                    tenant=tk.tenant,
                                )
                        if telemetry._ACTIVE:
                            telemetry.observe(
                                "serve.request_s", done - tk.submitted_at,
                                trace=tk.trace, source="dispatch",
                            )
            return out

    @staticmethod
    def _is_windowed(t: _Tenant) -> bool:
        # Cheap structural probe (no import unless windows is loaded):
        # WindowedSketch is the only facade carrying a window_plan.
        return hasattr(t.facade, "window_plan")

    def quantile(
        self,
        name: str,
        quantiles: Sequence[float],
        window: Optional[float] = None,
        deadline_s: Optional[float] = None,
    ) -> ServeResult:
        """``quantile(tenant, qs, window=W)``: the time-scoped read --
        "p99 over the last W seconds" -> a :class:`ServeResult`.

        For a windowed tenant the answer is ONE fused stacked-merge
        dispatch over the buckets covering ``[now - W, now)``
        (``window=None`` covers the whole retained horizon), cached
        under ``(tenant, covered-bucket fingerprint-set digest, qs)``:
        a rotation or an ingest changes the covered set's fingerprints,
        so stale entries MISS -- they can never serve a stale-wrong
        window (hits are re-verified against the live fingerprint +
        payload checksum and poisoned entries quarantine exactly like
        the unwindowed cache).  For a plain tenant ``window`` must be
        None (``SpecError``) and the call is :meth:`query`.  Spent
        deadline budgets raise :class:`DeadlineExceeded`; late answers
        are returned but counted; unknown tenants raise ``SpecError``.
        """
        qs = tuple(sorted(float(q) for q in quantiles))
        with self._lock:
            t = self._tenant(name)
            if not self._is_windowed(t):
                if window is not None:
                    raise SpecError(
                        f"tenant {name!r} is not time-windowed: register it"
                        " with add_tenant(..., window=...) to serve"
                        " window-scoped quantiles"
                    )
                return self.query(name, quantiles, deadline_s)
            if not qs:
                raise SketchValueError(
                    "a request needs at least one quantile"
                )
            self._stats["requests"] += 1
            now = self._clock()
            _trc = tracing.new_trace() if tracing._ACTIVE else None
            if telemetry._ACTIVE:
                telemetry.counter_inc("serve.requests")
            budget = (
                self.config.default_deadline_s
                if deadline_s is None else float(deadline_s)
            )
            if budget <= 0:
                self._stats["deadline_misses"] += 1
                resilience.bump("serve.deadline_misses")
                if telemetry._ACTIVE:
                    telemetry.counter_inc("serve.deadline_misses")
                raise DeadlineExceeded(
                    f"window query for tenant {name!r} arrived with a"
                    f" spent deadline budget ({budget:g}s)"
                )
            plan = t.facade.window_plan(window)
            fp = plan.fingerprint
            digest = plan.digest
            key = (t.name, digest, qs)
            if self._cache_enabled:
                entry = self._cache.get(key)
                if entry is not None:
                    if faults._ACTIVE:
                        flip = faults.cache_poison_flip(entry.values.nbytes)
                        if flip is not None:
                            buf = np.ascontiguousarray(entry.values).copy()
                            view = buf.view(np.uint8).reshape(-1)
                            view[flip[0]] ^= np.uint8(1 << flip[1])
                            entry.values = buf
                    live_ok = entry.fp.shape == fp.shape and bool(
                        np.array_equal(entry.fp, fp)
                    )
                    sum_ok = entry.checksum == _payload_checksum(
                        entry.fp, entry.values
                    )
                    if live_ok and sum_ok:
                        self._stats["cache_hits"] += 1
                        if _trc is not None:
                            tracing.record_event(
                                "serve.cache.hit", ctx=_trc, tenant=name
                            )
                        if telemetry._ACTIVE:
                            telemetry.counter_inc("serve.cache.hits")
                            telemetry.observe(
                                "serve.request_s", self._clock() - now,
                                trace=_trc, source="cache",
                            )
                        return ServeResult(
                            values=entry.values.copy(), tier="cache"
                        )
                    self._quarantine(key, ctx=_trc)
                self._stats["cache_misses"] += 1
                if telemetry._ACTIVE:
                    telemetry.counter_inc("serve.cache.misses")
            values = np.asarray(t.facade.query_plan(plan, qs))
            self._stats["dispatches"] += 1
            if self._cache_enabled:
                if key not in self._cache:
                    self._cache_order.append(key)
                self._cache[key] = _CacheEntry(fp, values, "window")
                while len(self._cache_order) > self.config.cache_capacity:
                    old = self._cache_order.pop(0)
                    self._cache.pop(old, None)
            done = self._clock()
            missed = done > now + budget
            if missed:
                self._stats["deadline_misses"] += 1
                resilience.bump("serve.deadline_misses")
                if telemetry._ACTIVE:
                    telemetry.counter_inc("serve.deadline_misses")
            if _trc is not None:
                tracing.record_event(
                    "serve.dispatch", ctx=_trc, tenant=name,
                    tier="window", hedged=False,
                    covered=plan.n_covered,
                )
            if telemetry._ACTIVE:
                telemetry.observe(
                    "serve.request_s", done - now, trace=_trc,
                    source="dispatch",
                )
            return ServeResult(
                values=values, tier="window", deadline_missed=missed
            )

    def quantile_many(
        self,
        names: Sequence[str],
        quantiles: Sequence[float],
        window: Optional[float] = None,
        deadline_s: Optional[float] = None,
    ) -> Dict[str, "ServeResult"]:
        """The windowed twin of the flush path's same-spec stacking:
        answer ``quantile(qs, window=W)`` for MANY windowed tenants,
        folding each tenant's maintained window components to one state
        and stacking same-spec tenants into ONE fused quantile dispatch
        -> ``{tenant: ServeResult}``.

        Every tenant keeps its own cache entry under the existing
        ``(tenant, covered-bucket digest, qs)`` key -- hits are
        re-verified (poisoned entries quarantine) and misses fill the
        cache, so a later single-tenant :meth:`quantile` hits the entry
        this call wrote (the answers are bit-identical: the per-tenant
        fold is the same maintained component chain, pinned by test).
        Non-windowed tenants raise ``SpecError``; empty ``names``
        answers ``{}``; spent deadline budgets raise
        :class:`DeadlineExceeded`; late answers are returned but
        counted once per tenant.
        """
        qs = tuple(sorted(float(q) for q in quantiles))
        if not qs:
            raise SketchValueError("a request needs at least one quantile")
        names = list(names)
        if not names:
            return {}
        import jax
        import jax.numpy as jnp

        from sketches_tpu.windows import _fold_mode, _fold_state_for

        with self._lock:
            tenants = [self._tenant(n) for n in names]
            for t in tenants:
                if not self._is_windowed(t):
                    raise SpecError(
                        f"tenant {t.name!r} is not time-windowed:"
                        " quantile_many serves windowed tenants only"
                    )
            now = self._clock()
            self._stats["requests"] += len(names)
            if telemetry._ACTIVE:
                telemetry.counter_inc("serve.requests", float(len(names)))
            budget = (
                self.config.default_deadline_s
                if deadline_s is None else float(deadline_s)
            )
            if budget <= 0:
                self._stats["deadline_misses"] += len(names)
                resilience.bump("serve.deadline_misses", len(names))
                if telemetry._ACTIVE:
                    telemetry.counter_inc(
                        "serve.deadline_misses", float(len(names))
                    )
                raise DeadlineExceeded(
                    "window query batch arrived with a spent deadline"
                    f" budget ({budget:g}s)"
                )
            out: Dict[str, ServeResult] = {}
            misses: List[Tuple[Any, Any, Tuple, np.ndarray]] = []
            for t in tenants:
                plan = t.facade.window_plan(window)
                fp = plan.fingerprint
                key = (t.name, plan.digest, qs)
                if self._cache_enabled:
                    entry = self._cache.get(key)
                    if entry is not None:
                        if faults._ACTIVE:
                            flip = faults.cache_poison_flip(
                                entry.values.nbytes
                            )
                            if flip is not None:
                                buf = np.ascontiguousarray(
                                    entry.values
                                ).copy()
                                view = buf.view(np.uint8).reshape(-1)
                                view[flip[0]] ^= np.uint8(1 << flip[1])
                                entry.values = buf
                        live_ok = entry.fp.shape == fp.shape and bool(
                            np.array_equal(entry.fp, fp)
                        )
                        sum_ok = entry.checksum == _payload_checksum(
                            entry.fp, entry.values
                        )
                        if live_ok and sum_ok:
                            self._stats["cache_hits"] += 1
                            if telemetry._ACTIVE:
                                telemetry.counter_inc("serve.cache.hits")
                            out[t.name] = ServeResult(
                                values=entry.values.copy(), tier="cache"
                            )
                            continue
                        self._quarantine(key, ctx=None)
                    self._stats["cache_misses"] += 1
                    if telemetry._ACTIVE:
                        telemetry.counter_inc("serve.cache.misses")
                misses.append((t, plan, key, fp))
            # Same-spec miss groups: fold each tenant's maintained
            # components to ONE state, stack along the stream axis, and
            # decode every tenant in one fused quantile dispatch.
            groups: Dict[Any, List[int]] = {}
            for i, (t, plan, _key, _fp) in enumerate(misses):
                if not plan.states:
                    dtype = np.dtype(jnp.dtype(t.facade.spec.dtype).name)
                    self._fill_window_result(
                        t, plan, _key, _fp, qs,
                        np.full(
                            (t.facade.n_streams, len(qs)), np.nan, dtype
                        ),
                        out,
                    )
                    continue
                groups.setdefault(t.facade.spec, []).append(i)
            for spec, idxs in groups.items():
                if len(idxs) == 1:
                    t, plan, key, fp = misses[idxs[0]]
                    values = np.asarray(t.facade.query_plan(plan, qs))
                    self._stats["dispatches"] += 1
                    self._fill_window_result(
                        t, plan, key, fp, qs, values, out
                    )
                    continue
                folded = []
                for i in idxs:
                    t, plan, _key, _fp = misses[i]
                    if plan.components is not None:
                        # Share the ring's per-digest folded-window
                        # cache: a repeat stacking on unchanged plans
                        # contributes zero merges to the fused dispatch.
                        folded.append(t.facade._agg_fold(plan))
                        continue
                    comps = plan.states
                    if len(comps) == 1:
                        folded.append(comps[0])
                    else:
                        mode = _fold_mode(spec, comps)
                        folded.append(_fold_state_for(spec)[mode](comps))
                stacked = jax.tree.map(
                    lambda *xs: jnp.concatenate(xs, axis=0), *folded
                )
                fn = self._fused_quantile(spec)
                qs_arr = jnp.asarray(qs, spec.dtype)
                try:
                    if faults._ACTIVE:
                        faults.inject(
                            faults.SERVE_STRAGGLER, tier=_FLOOR_TIER
                        )
                    rows = np.asarray(fn(stacked, qs_arr))
                except SketchError:
                    if not self._hedge_enabled:
                        raise
                    self._stats["hedges"] += 1
                    resilience.bump("serve.hedges")
                    if telemetry._ACTIVE:
                        telemetry.counter_inc(
                            "serve.hedges", tier=_FLOOR_TIER
                        )
                    rows = np.asarray(fn(stacked, qs_arr))
                self._stats["dispatches"] += 1
                self._stats["fused_dispatches"] += 1
                lo = 0
                for i in idxs:
                    t, plan, key, fp = misses[i]
                    hi = lo + t.facade.n_streams
                    self._fill_window_result(
                        t, plan, key, fp, qs, rows[lo:hi].copy(), out
                    )
                    lo = hi
            done = self._clock()
            missed = done > now + budget
            if missed:
                self._stats["deadline_misses"] += len(names)
                resilience.bump("serve.deadline_misses", len(names))
                if telemetry._ACTIVE:
                    telemetry.counter_inc(
                        "serve.deadline_misses", float(len(names))
                    )
                for r in out.values():
                    if r.tier != "cache":
                        r.deadline_missed = True
            if telemetry._ACTIVE:
                for t in tenants:
                    telemetry.observe(
                        "serve.request_s", done - now,
                        source=(
                            "cache" if out[t.name].tier == "cache"
                            else "dispatch"
                        ),
                    )
            return out

    def _fill_window_result(
        self, t, plan, key, fp, qs, values, out
    ) -> None:
        """Cache-fill + result-build shared by the quantile_many paths
        (single-tenant fallback, empty coverage, fused rows)."""
        if self._cache_enabled:
            if key not in self._cache:
                self._cache_order.append(key)
            self._cache[key] = _CacheEntry(fp, values, "window")
            while len(self._cache_order) > self.config.cache_capacity:
                old = self._cache_order.pop(0)
                self._cache.pop(old, None)
        out[t.name] = ServeResult(values=values, tier="window")

    def query(
        self,
        name: str,
        quantiles: Sequence[float],
        deadline_s: Optional[float] = None,
    ) -> ServeResult:
        """Submit-and-flush convenience for the synchronous caller ->
        the request's :class:`ServeResult`.

        Shed requests raise :class:`ServeOverload`; spent budgets raise
        :class:`DeadlineExceeded`; everything the batch path counts
        (hedges, deadline misses, cache hits) is counted here too.
        Concurrent callers' queued requests flush in the same pass --
        batching is cooperative, not per-caller.
        """
        ticket = self.submit(name, quantiles, deadline_s)
        if ticket.result is not None:  # cache hit at admission
            return ticket.result
        self.flush()
        assert ticket.result is not None  # flush answers every admitted ticket
        return ticket.result

    # -- introspection ----------------------------------------------------

    def stats(self) -> Dict[str, float]:
        """Always-on serving counters (requests, shed, hedges, cache
        hits/misses/poisoned, deadline misses, dispatches, breaker
        trips) -- a copy; zeros mean nothing failed yet.  The armed
        telemetry layer mirrors these under the declared ``serve.*``
        metric names."""
        with self._lock:
            out = dict(self._stats)
            out["queue_depth"] = len(self._queue)
            out["tenants"] = len(self._tenants)
            out["cache_entries"] = len(self._cache)
            return out
